// Native runtime kernels for oceanbase_tpu's host control plane.
//
// Reference analog: the reference implements its checksums and block codecs
// in C++ with SIMD (crc64 with hardware acceleration in
// deps/oblib/src/lib/checksum, cs_encoding integer codecs in
// src/storage/blocksstable/cs_encoding).  The TPU build keeps the device
// compute in XLA/Pallas; these host-side hot loops (log integrity, segment
// wire codecs) are native for the same reason the reference's are: they sit
// on the WAL fsync path and the segment persistence path.
//
// Exposed via a C ABI consumed through ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC-64 (ECMA-182 polynomial, as used by XZ): slicing-by-8 table version.
// ---------------------------------------------------------------------------

static uint64_t crc64_table[8][256];
static bool crc64_init_done = false;

static void crc64_init() {
    const uint64_t poly = 0xC96C5795D7870F42ULL;  // reflected ECMA-182
    for (int i = 0; i < 256; i++) {
        uint64_t crc = (uint64_t)i;
        for (int j = 0; j < 8; j++) {
            crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
        }
        crc64_table[0][i] = crc;
    }
    for (int t = 1; t < 8; t++) {
        for (int i = 0; i < 256; i++) {
            uint64_t crc = crc64_table[t - 1][i];
            crc64_table[t][i] = (crc >> 8) ^ crc64_table[0][crc & 0xFF];
        }
    }
    crc64_init_done = true;
}

uint64_t obtpu_crc64(const uint8_t* data, uint64_t len, uint64_t seed) {
    if (!crc64_init_done) crc64_init();
    uint64_t crc = ~seed;
    // 8-byte strides through the slicing tables
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, data, 8);
        crc ^= word;
        crc = crc64_table[7][crc & 0xFF] ^
              crc64_table[6][(crc >> 8) & 0xFF] ^
              crc64_table[5][(crc >> 16) & 0xFF] ^
              crc64_table[4][(crc >> 24) & 0xFF] ^
              crc64_table[3][(crc >> 32) & 0xFF] ^
              crc64_table[2][(crc >> 40) & 0xFF] ^
              crc64_table[1][(crc >> 48) & 0xFF] ^
              crc64_table[0][(crc >> 56) & 0xFF];
        data += 8;
        len -= 8;
    }
    while (len--) {
        crc = crc64_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    }
    return ~crc;
}

// ---------------------------------------------------------------------------
// Integer block codec: delta + zigzag + varint (LEB128).
// encode: int64[n] -> bytes; returns encoded length (or 0 on overflow).
// decode: bytes -> int64[n]; returns consumed length (0 on error).
// Worst case 10 bytes per value; callers size out_cap accordingly.
// ---------------------------------------------------------------------------

static inline uint64_t zigzag(int64_t v) {
    return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
}

static inline int64_t unzigzag(uint64_t u) {
    return (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
}

uint64_t obtpu_delta_varint_encode(const int64_t* in, uint64_t n,
                                   uint8_t* out, uint64_t out_cap) {
    uint64_t pos = 0;
    int64_t prev = 0;
    for (uint64_t i = 0; i < n; i++) {
        // delta in wrapping (unsigned) arithmetic: signed int64 overflow
        // is UB, and deltas like MAX-MIN exceed the signed range anyway
        uint64_t delta = (uint64_t)in[i] - (uint64_t)prev;
        uint64_t u = zigzag((int64_t)delta);
        prev = in[i];
        do {
            if (pos >= out_cap) return 0;
            uint8_t byte = u & 0x7F;
            u >>= 7;
            out[pos++] = byte | (u ? 0x80 : 0);
        } while (u);
    }
    return pos;
}

uint64_t obtpu_delta_varint_decode(const uint8_t* in, uint64_t in_len,
                                   int64_t* out, uint64_t n) {
    uint64_t pos = 0;
    int64_t prev = 0;
    for (uint64_t i = 0; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        while (true) {
            if (pos >= in_len || shift > 63) return 0;
            uint8_t byte = in[pos++];
            u |= (uint64_t)(byte & 0x7F) << shift;
            if (!(byte & 0x80)) break;
            shift += 7;
        }
        prev = (int64_t)((uint64_t)prev + (uint64_t)unzigzag(u));
        out[i] = prev;
    }
    return pos;
}

// ---------------------------------------------------------------------------
// Run-length scan: fills starts[] with run-start indices; returns run count
// (used by the RLE encoder to avoid a python-level pass).
// ---------------------------------------------------------------------------

uint64_t obtpu_rle_runs_i64(const int64_t* in, uint64_t n,
                            uint64_t* starts, uint64_t cap) {
    if (n == 0) return 0;
    uint64_t count = 0;
    if (count < cap) starts[count] = 0;
    count++;
    for (uint64_t i = 1; i < n; i++) {
        if (in[i] != in[i - 1]) {
            if (count < cap) starts[count] = i;
            count++;
        }
    }
    return count;
}

// ---------------------------------------------------------------------------
// CSV tokenizer (direct-load path): scans a whole buffer into row-major
// (offset, length) pairs per field.  Handles RFC-4180-style double-quoted
// fields with "" escapes, \n and \r\n terminators.  Returns the number of
// rows tokenized, 0 on structural error (ragged row), with *err_row set.
// The (offset,length) of a quoted field excludes the quotes; embedded ""
// stays doubled (caller unescapes the rare fields that contain quotes —
// flagged via the high bit of the length).
// ---------------------------------------------------------------------------

uint64_t obtpu_csv_tokenize(const uint8_t* buf, uint64_t len, uint8_t delim,
                            uint64_t n_cols, uint64_t* offsets,
                            uint32_t* lengths, uint64_t max_rows,
                            uint64_t* err_row) {
    uint64_t pos = 0, row = 0;
    *err_row = 0;
    while (pos < len && row < max_rows) {
        uint64_t col = 0;
        bool row_done = false;
        while (!row_done) {
            if (col >= n_cols) { *err_row = row + 1; return 0; }
            uint64_t field_start, field_len;
            bool quoted_escape = false;
            if (pos < len && buf[pos] == '"') {
                pos++;
                field_start = pos;
                while (pos < len) {
                    if (buf[pos] == '"') {
                        if (pos + 1 < len && buf[pos + 1] == '"') {
                            quoted_escape = true;
                            pos += 2;
                            continue;
                        }
                        break;
                    }
                    pos++;
                }
                field_len = pos - field_start;
                if (pos < len) pos++;  // closing quote
            } else {
                field_start = pos;
                while (pos < len && buf[pos] != delim && buf[pos] != '\n' &&
                       buf[pos] != '\r') {
                    pos++;
                }
                field_len = pos - field_start;
            }
            uint64_t idx = row * n_cols + col;
            offsets[idx] = field_start;
            lengths[idx] = (uint32_t)field_len |
                           (quoted_escape ? 0x80000000u : 0);
            col++;
            if (pos >= len) { row_done = true; }
            else if (buf[pos] == (uint8_t)delim) { pos++; }
            else if (buf[pos] == '\r') {
                pos++;
                if (pos < len && buf[pos] == '\n') pos++;
                row_done = true;
            } else if (buf[pos] == '\n') { pos++; row_done = true; }
        }
        if (col != n_cols) { *err_row = row + 1; return 0; }
        row++;
        // skip trailing blank line
        if (pos >= len) break;
    }
    if (pos < len && row >= max_rows) {
        // allocation too small (caller's row estimate missed the line
        // terminator style): error rather than silently truncate
        *err_row = row;
        return 0;
    }
    return row;
}

// Batch int64 parse over tokenized fields: empty/invalid -> null.
// Returns count of successfully parsed values.
uint64_t obtpu_parse_int64_fields(const uint8_t* buf, const uint64_t* offs,
                                  const uint32_t* lens, uint64_t n,
                                  int64_t scale_pow10, int64_t* out,
                                  uint8_t* valid) {
    uint64_t ok = 0;
    for (uint64_t i = 0; i < n; i++) {
        uint32_t ln = lens[i] & 0x7FFFFFFF;
        const uint8_t* p = buf + offs[i];
        if (ln == 0) { valid[i] = 0; out[i] = 0; continue; }
        uint64_t j = 0;
        bool neg = false;
        if (p[0] == '-' || p[0] == '+') { neg = (p[0] == '-'); j = 1; }
        const int64_t IP_LIMIT = (0x7FFFFFFFFFFFFFFFLL - 9) / 10;
        int64_t ip = 0, fp = 0, fdigits = 1;
        int first_dropped = -1;
        bool in_frac = false, any = false, bad = false;
        for (; j < ln; j++) {
            uint8_t c = p[j];
            if (c == '.') {
                if (in_frac || scale_pow10 == 1) { bad = true; break; }
                in_frac = true;
            } else if (c >= '0' && c <= '9') {
                any = true;
                if (in_frac) {
                    if (fdigits < scale_pow10) {
                        fp = fp * 10 + (c - '0');
                        fdigits *= 10;
                    } else if (first_dropped < 0) {
                        first_dropped = c - '0';
                    }
                } else {
                    if (ip > IP_LIMIT) { bad = true; break; }  // overflow
                    ip = ip * 10 + (c - '0');
                }
            } else { bad = true; break; }
        }
        if (bad || !any) { valid[i] = 0; out[i] = 0; continue; }
        while (fdigits < scale_pow10) {
            fp *= 10; fdigits *= 10;
        }
        if (first_dropped >= 5) {
            // round half away from zero (matches the python oracle)
            fp += 1;
            if (fp >= scale_pow10) { fp = 0; ip += 1; }
        }
        if (ip > (0x7FFFFFFFFFFFFFFFLL - fp) / scale_pow10) {
            valid[i] = 0; out[i] = 0; continue;  // scaled overflow
        }
        int64_t v = ip * scale_pow10 + fp;
        out[i] = neg ? -v : v;
        valid[i] = 1;
        ok++;
    }
    return ok;
}

}  // extern "C"
