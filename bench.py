"""Driver benchmark: TPC-H Q1 scan-aggregate throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
North star (BASELINE.md): rows/sec/chip on Q1 scan-agg; vs_baseline is the
speedup over a vectorized numpy implementation of the same query on the
host CPU (the stand-in for the reference's SIMD CPU executor,
src/sql/engine/aggregate/ob_hash_groupby_vec_op.cpp path).

Env: BENCH_SF (default 1.0), BENCH_ITERS (default 5), BENCH_QUERY (q1|q6),
BENCH_MODE (whole|stream|pallas; stream = granule pipeline for
HBM-exceeding tables, pallas = fused Q6 kernel).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def numpy_q1(li, cutoff):
    sel = li["l_shipdate"] <= cutoff
    rf = li["l_returnflag"][sel]
    ls = li["l_linestatus"][sel]
    qty = li["l_quantity"][sel]
    price = li["l_extendedprice"][sel]
    disc = li["l_discount"][sel]
    tax = li["l_tax"][sel]
    # dictionary-encode group keys then aggregate with bincount segments
    key = rf.astype("U1")
    ukeys, codes = np.unique(np.char.add(key, ls.astype("U1")), return_inverse=True)
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {}
    out["sum_qty"] = np.bincount(codes, qty)
    out["sum_base_price"] = np.bincount(codes, price)
    out["sum_disc_price"] = np.bincount(codes, disc_price)
    out["sum_charge"] = np.bincount(codes, charge)
    out["count"] = np.bincount(codes)
    out["avg_qty"] = out["sum_qty"] / out["count"]
    out["avg_price"] = out["sum_base_price"] / out["count"]
    out["avg_disc"] = np.bincount(codes, disc) / out["count"]
    return ukeys, out


def numpy_q6(li, d0, d1):
    sel = (
        (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
        & (li["l_discount"] >= 5) & (li["l_discount"] <= 7)
        & (li["l_quantity"] < 2400)
    )
    return (li["l_extendedprice"][sel] * li["l_discount"][sel]).sum()


def _relay_floor_s(jax):
    """Round-trip latency of a trivial dispatch + scalar readback.

    Under the axon loopback relay a single dispatch costs ~30-70ms of RPC
    latency and ``block_until_ready`` returns at dispatch, not completion —
    so device timing must (a) force a host readback to synchronize and
    (b) amortize many iterations inside ONE compiled program, subtracting
    this floor."""
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.int32(0)
    float(f(x))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _perturbed(tables, delta):
    """Add a runtime scalar (0 in practice) to every numeric column so a
    fori_loop over ``delta*i`` cannot be hoisted by XLA."""
    import jax.numpy as jnp

    out = {}
    for tname, r in tables.items():
        cols = {}
        for cname, col in r.columns.items():
            if jnp.issubdtype(col.data.dtype, jnp.bool_):
                cols[cname] = col
            else:
                cols[cname] = col.with_data(
                    col.data + delta.astype(col.data.dtype))
        out[tname] = type(r)(columns=cols, mask=r.mask)
    return out


def _checksum(rel):
    import jax.numpy as jnp

    acc = jnp.float32(0)
    for col in rel.columns.values():
        acc = acc + jnp.sum(col.data.astype(jnp.float32))
    if rel.mask is not None:
        acc = acc + jnp.sum(rel.mask.astype(jnp.float32))
    return acc


def _timed_device_loop(jax, make_loop, min_total_s=1.0):
    """make_loop(k) -> compiled fn(salt) returning a scalar for k
    in-program iterations.  ``salt`` MUST be a traced argument (0 at
    runtime): a closed-over jnp constant would let XLA fold the
    perturbation away and hoist the loop body into a single computation.

    Returns (per_iter_s, k_used, floor_s). Two compiles: a pilot k=8 run
    estimates per-iter cost, then one right-sized run produces the number."""
    import jax.numpy as jnp

    floor = _relay_floor_s(jax)
    salt = jnp.int32(0)
    pilot_k = 8
    f = make_loop(pilot_k)
    t0 = time.perf_counter()
    float(f(salt))  # compile + warm
    print(f"# pilot k={pilot_k} compile+run: {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    float(f(salt))
    total = time.perf_counter() - t0
    per = max(total - floor, 1e-7) / pilot_k
    k = int(min(4096, max(pilot_k, min_total_s / per)))
    if k > pilot_k * 2:
        f = make_loop(k)
        t0 = time.perf_counter()
        float(f(salt))
        print(f"# sized k={k} compile+run: {time.perf_counter()-t0:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        float(f(salt))
        total = time.perf_counter() - t0
    else:
        k = pilot_k
    best = max(total - floor, 1e-7) / k
    for _ in range(2):
        t0 = time.perf_counter()
        float(f(salt))
        total = time.perf_counter() - t0
        best = min(best, max(total - floor, 1e-7) / k)
    return best, k, floor


def _ensure_backend():
    """The axon TPU tunnel can be unavailable; rather than hang or crash,
    re-exec on CPU (the JSON line carries `platform` so the fallback is
    transparent to the reader)."""
    import subprocess

    budget = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "600"))
    if os.environ.get("OBTPU_BENCH_FALLBACK") != "1" and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        # only the axon tunnel can hang; plain CPU/TPU setups skip the probe
        # probe in a CHILD process: a stuck tunnel blocks inside native
        # code where no Python signal can interrupt, so the only safe
        # timeout is process-level
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=budget, capture_output=True)
            ok = probe.returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        if not ok:
            print("# TPU backend unavailable (probe failed/timed out); "
                  "falling back to CPU", file=sys.stderr)
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["OBTPU_BENCH_FALLBACK"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)

    import jax

    return jax


def main():
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    which = os.environ.get("BENCH_QUERY", "q1")

    jax = _ensure_backend()

    from oceanbase_tpu.bench.queries import q1_plan, q6_plan
    from oceanbase_tpu.bench.tpch import gen_tpch
    from oceanbase_tpu.datatypes import SqlType, date_to_days
    from oceanbase_tpu.exec.plan import _lower
    from oceanbase_tpu.vector import from_numpy, to_numpy

    t0 = time.time()
    tables, types = gen_tpch(sf=sf)
    li = tables["lineitem"]
    n_rows = len(li["l_orderkey"])
    print(f"# generated SF{sf} lineitem: {n_rows} rows in {time.time()-t0:.1f}s",
          file=sys.stderr)

    mode = os.environ.get("BENCH_MODE", "whole")
    plan = q1_plan() if which == "q1" else q6_plan()
    needed = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
              "l_discount", "l_tax", "l_shipdate"]
    arrays = {k: li[k] for k in needed}
    btypes = {k: v for k, v in types.items() if k in needed}

    if mode == "pallas":
        if which != "q6":
            raise SystemExit("BENCH_MODE=pallas supports BENCH_QUERY=q6 only")
        from oceanbase_tpu.datatypes import date_to_days
        from oceanbase_tpu.ops import q6_filter_sum

        interp = jax.devices()[0].platform == "cpu"
        args = dict(
            ship_lo=date_to_days("1994-01-01"),
            ship_hi=date_to_days("1995-01-01"),
            disc_lo=5, disc_hi=7, qty_hi=2400, interpret=interp)
        import jax.numpy as jnp

        ship = jnp.asarray(li["l_shipdate"].astype(np.int32))
        disc = jnp.asarray(li["l_discount"].astype(np.int32))
        qty = jnp.asarray(li["l_quantity"].astype(np.int32))
        price = jnp.asarray(li["l_extendedprice"].astype(np.int32))
        live = jnp.ones(n_rows, dtype=jnp.int32)

        out_v = q6_filter_sum(ship, disc, qty, price, live, **args)
        oracle = numpy_q6(li, date_to_days("1994-01-01"),
                          date_to_days("1995-01-01"))
        assert int(out_v) == int(oracle), "pallas Q6 mismatch"

        def make_loop(k):
            def loop(salt):
                def body(i, acc):
                    d = salt * i
                    return acc + q6_filter_sum(
                        ship + d, disc + d, qty + d, price + d, live,
                        **args).astype(jnp.float32)
                return jax.lax.fori_loop(0, k, body, jnp.float32(0))
            return jax.jit(loop)

        dev_time, k_used, floor = _timed_device_loop(jax, make_loop)
        which = "q6_pallas"
        out = None
    elif mode == "stream":
        from oceanbase_tpu.exec.granule import (
            execute_streamed,
            numpy_chunk_provider,
        )

        chunk = int(os.environ.get("BENCH_CHUNK_ROWS", 1 << 21))
        provider = numpy_chunk_provider(arrays)
        cache = {}

        def run_stream():
            r = execute_streamed(
                plan, provider, chunk_rows=chunk, types=btypes, cache=cache)
            float(_checksum(r))  # true sync: scalar readback
            return r

        t0 = time.time()
        out = run_stream()
        print(f"# stream compile+dict-pass+first-run: {time.time()-t0:.1f}s",
              file=sys.stderr)
        times = []
        for _ in range(iters):
            t0 = time.time()
            out = run_stream()
            times.append(time.time() - t0)
        # streaming is inherently multi-dispatch (host chunk feed); report
        # end-to-end including per-chunk dispatch latency, minus one floor
        dev_time = max(min(times) - _relay_floor_s(jax), 1e-7)
        which = which + "_stream"
    else:
        import jax.numpy as jnp

        rel = from_numpy(arrays, types=btypes)
        dev_tables = {"lineitem": rel}

        run = jax.jit(lambda t: _lower(plan, t))
        t0 = time.time()
        out = run(dev_tables)
        float(_checksum(out))  # sync
        compile_s = time.time() - t0
        print(f"# compile+first-run: {compile_s:.1f}s", file=sys.stderr)

        def make_loop(k):
            def loop_t(tabs, salt):
                def body(i, acc):
                    t2 = _perturbed(tabs, salt * i)
                    return acc + _checksum(_lower(plan, t2))
                return jax.lax.fori_loop(0, k, body, jnp.float32(0))
            jf = jax.jit(loop_t)
            return lambda salt: jf(dev_tables, salt)


        dev_time, k_used, floor = _timed_device_loop(jax, make_loop)

    # host numpy baseline
    cutoff = date_to_days("1998-09-02")
    t0 = time.time()
    if which == "q1":
        numpy_q1(li, cutoff)
    else:
        numpy_q6(li, date_to_days("1994-01-01"), date_to_days("1995-01-01"))
    cpu_time = time.time() - t0

    # sanity: compare engine vs numpy result
    if out is not None and which.startswith("q1"):
        res = to_numpy(out)
        _, oracle = numpy_q1(li, cutoff)
        assert np.array_equal(np.sort(res["sum_qty"]),
                              np.sort(oracle["sum_qty"])), "Q1 mismatch"

    rows_per_sec = n_rows / dev_time
    platform = jax.devices()[0].platform
    # resolved-backend provenance: a CPU-fallback run (TPU relay dead)
    # is visible in the artifact itself, not just the stderr log
    from oceanbase_tpu.server.backend_info import (
        last_tpu_probe,
        resolve_backend,
    )

    rec = {
        "metric": f"tpch_{which}_sf{sf:g}_rows_per_sec_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu_time / dev_time, 3),
        "device_time_s": round(dev_time, 6),
        "numpy_cpu_time_s": round(cpu_time, 4),
        "rows": n_rows,
        "platform": platform,
        "backend": {**resolve_backend(), "tpu_probe": last_tpu_probe()},
        # baseline fairness: the numpy oracle is single-threaded; on this
        # host that IS the CPU's best (report cores so a skeptic can see)
        "host_nproc": os.cpu_count(),
    }
    try:
        rec["loop_iters"] = k_used
        rec["relay_floor_ms"] = round(floor * 1e3, 2)
    except NameError:
        pass  # stream mode times end-to-end, no in-program loop
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
