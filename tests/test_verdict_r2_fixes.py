"""Regression tests for round-1 VERDICT correctness traps.

- multi-key (hash-combined) joins: left-join phantom NULL rows,
  semi/anti collision verification
- WITH RECURSIVE must be rejected loudly
- broadcast decision is bytes-based
"""

import numpy as np
import pytest

from oceanbase_tpu.exec import ops
from oceanbase_tpu.expr import ir
from oceanbase_tpu.sql.parser import ParseError, Parser
from oceanbase_tpu.vector import from_numpy


def _rels(lrows, rrows):
    left = from_numpy({"a": np.array([r[0] for r in lrows]),
                       "b": np.array([r[1] for r in lrows])})
    right = from_numpy({"x": np.array([r[0] for r in rrows]),
                        "y": np.array([r[1] for r in rrows]),
                        "v": np.array([r[2] for r in rrows])})
    keys_l = [ir.ColumnRef("a"), ir.ColumnRef("b")]
    keys_r = [ir.ColumnRef("x"), ir.ColumnRef("y")]
    return left, right, keys_l, keys_r


def _result_rows(rel, cols):
    import jax.numpy as jnp

    mask = np.asarray(rel.mask_or_true())
    out = []
    for i in np.nonzero(mask)[0]:
        row = []
        for c in cols:
            col = rel.columns[c]
            valid = col.valid is None or bool(np.asarray(col.valid)[i])
            row.append(np.asarray(col.data)[i].item() if valid else None)
        out.append(tuple(row))
    return sorted(out)


def test_multikey_left_join_no_phantom_rows():
    # 2-key join goes through the hash-combined (inexact) path
    left, right, kl, kr = _rels(
        [(1, 1), (2, 2), (3, 3)],
        [(1, 1, 10), (1, 1, 11), (9, 9, 99)])
    out = ops.join(left, right, kl, kr, how="left", out_capacity=16)
    rows = _result_rows(out, ["a", "v"])
    # (1,1) matches twice; (2,2),(3,3) get exactly ONE null-extended row
    assert rows == [(1, 10), (1, 11), (2, None), (3, None)]


def test_multikey_semi_anti_verified():
    left, right, kl, kr = _rels(
        [(1, 1), (2, 2)],
        [(1, 1, 10), (5, 5, 50)])
    semi = ops.join(left, right, kl, kr, how="semi")
    assert _result_rows(semi, ["a"]) == [(1,)]
    anti = ops.join(left, right, kl, kr, how="anti")
    assert _result_rows(anti, ["a"]) == [(2,)]


def test_multikey_left_join_engineered_collision():
    """Force a false-positive candidate range: many build rows, probe row
    whose keys match none. The output must contain exactly one
    NULL-extended row for it, not one per candidate."""
    n = 64
    left, right, kl, kr = _rels(
        [(999, 999)],
        [(i, i, i) for i in range(n)])
    out = ops.join(left, right, kl, kr, how="left", out_capacity=128)
    rows = _result_rows(out, ["a", "v"])
    assert rows == [(999, None)]


def test_with_recursive_rejected():
    with pytest.raises(ParseError, match="RECURSIVE"):
        Parser("with recursive r as (select 1) select * from r").parse()
    # plain WITH still works
    Parser("with r as (select 1 as x) select x from r").parse()


def test_broadcast_threshold_is_bytes():
    from oceanbase_tpu.px import planner

    wide = from_numpy({f"c{i}": np.zeros(1 << 12, dtype=np.int64)
                       for i in range(200)})
    narrow = from_numpy({"c": np.zeros(1 << 12, dtype=np.int64)})
    assert narrow.capacity * planner._row_bytes(narrow) \
        <= planner.BROADCAST_THRESHOLD_BYTES
    assert wide.capacity * planner._row_bytes(wide) \
        > planner.BROADCAST_THRESHOLD_BYTES
