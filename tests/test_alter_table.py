"""ALTER TABLE ADD/DROP COLUMN: instant schema change over old segments."""

import pytest

from oceanbase_tpu.server import Database


def test_add_column_over_existing_segments(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20)")
    db.checkpoint()  # old rows live in a segment WITHOUT the new column
    s.execute("alter table t add column note varchar(20)")
    s.execute("insert into t values (3, 30, 'hello')")
    rows = s.execute("select k, v, note from t order by k").rows()
    assert rows == [(1, 10, None), (2, 20, None), (3, 30, "hello")]
    s.execute("update t set note = 'old' where k = 1")
    assert s.execute("select note from t where k = 1").rows() == [("old",)]
    # survives restart (slog/manifest)
    db.checkpoint()
    db.close()
    db2 = Database(root)
    rows = db2.session().execute("select k, note from t order by k").rows()
    assert rows == [(1, "old"), (2, None), (3, "hello")]
    db2.close()


def test_drop_column(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, a int, b int)")
    s.execute("insert into t values (1, 10, 100)")
    s.execute("alter table t drop column b")
    assert s.execute("select * from t").names == ["k", "a"]
    with pytest.raises(Exception):
        s.execute("select b from t")
    with pytest.raises(ValueError):
        s.execute("alter table t drop column k")  # PK protected
    # re-add with the same name: old values must NOT resurface
    s.execute("alter table t add column b int")
    assert s.execute("select b from t").rows() == [(None,)]
    db.close()
