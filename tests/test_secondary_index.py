"""Secondary indexes: DDL, transactional maintenance, uniqueness,
recovery, and the pruned point-lookup path.

Reference behaviors mirrored: index tables keyed by (index cols + pk)
maintained in the same transaction as the base row (src/storage DML
index-write path), MySQL unique-index NULL semantics, index survival
across restart (schema + backfilled segments persisted)."""

import numpy as np
import pytest

from oceanbase_tpu.server.database import Database
from oceanbase_tpu.tx.errors import DuplicateKey


def _mk(tmp_path, name="db"):
    return Database(str(tmp_path / name))


def test_create_index_backfill_and_lookup(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int, w int)")
    for i in range(50):
        s.execute(f"insert into t values ({i}, {i % 7}, {i * 10})")
    s.execute("create index iv on t (v)")
    store = db.engine.tables["__idx__t__iv"]
    assert store.tablet.key_cols == ["v", "k"]
    # backfilled entries match the base table
    rows = s.execute("select k from t where v = 3 order by k").rows()
    assert [r[0] for r in rows] == [3, 10, 17, 24, 31, 38, 45]
    assert store.tablet.row_count_estimate() == 50
    db.close()


def test_index_maintained_by_dml(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create index iv on t (v)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 10)")
    s.execute("update t set v = 99 where k = 2")
    s.execute("delete from t where k = 3")
    snap = db.tenant().tx.gts.current()
    store = db.engine.tables["__idx__t__iv"].tablet
    arrays, _ = store.snapshot_arrays(snap)
    live = sorted(zip(arrays["v"].tolist(), arrays["k"].tolist()))
    assert live == [(10, 1), (99, 2)]
    db.close()


def test_unique_index_rejects_duplicates(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, email varchar(64))")
    s.execute("insert into t values (1, 'a@x'), (2, 'b@x')")
    s.execute("create unique index ue on t (email)")
    with pytest.raises(DuplicateKey):
        s.execute("insert into t values (3, 'a@x')")
    # NULLs never conflict (MySQL semantics)
    s.execute("insert into t values (4, null)")
    s.execute("insert into t values (5, null)")
    # updating into a conflict also rejected
    with pytest.raises(DuplicateKey):
        s.execute("update t set email = 'b@x' where k = 1")
    # the failed statements left no partial state
    assert s.execute("select count(*) from t").rows()[0][0] == 4
    db.close()


def test_create_unique_index_on_duplicate_data_fails(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 5), (2, 5)")
    with pytest.raises(DuplicateKey):
        s.execute("create unique index uv on t (v)")
    # failed creation leaves no index behind
    assert db.engine.tables["t"].tdef.indexes == [] or \
        all(ix.name != "uv" for ix in db.engine.tables["t"].tdef.indexes)
    db.close()


def test_index_survives_restart(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create index iv on t (v)")
    s.execute("insert into t values (1, 10), (2, 20)")
    db.checkpoint()
    s.execute("insert into t values (3, 30)")  # WAL-only at crash
    db.close()
    db2 = Database(root)
    td = db2.engine.tables["t"].tdef
    assert [ix.name for ix in td.indexes] == ["iv"]
    s2 = db2.session()
    s2.execute("insert into t values (4, 20)")
    snap = db2.tenant().tx.gts.current()
    store = db2.engine.tables["__idx__t__iv"].tablet
    arrays, _ = store.snapshot_arrays(snap)
    live = sorted(zip(arrays["v"].tolist(), arrays["k"].tolist()))
    assert live == [(10, 1), (20, 2), (20, 4), (30, 3)]
    db2.close()


def test_drop_index_and_guards(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create index iv on t (v)")
    with pytest.raises(ValueError):
        s.execute("alter table t drop column v")
    s.execute("drop index iv on t")
    assert "__idx__t__iv" not in db.engine.tables
    s.execute("alter table t drop column v")  # now allowed
    s.execute("drop index if exists iv on t")  # no error
    db.close()


def test_inline_index_specs_and_show_create(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int, e varchar(10), "
              "index iv (v), unique key ue (e))")
    td = db.engine.tables["t"].tdef
    assert sorted(ix.name for ix in td.indexes) == ["iv", "ue"]
    text = s.execute("show create table t").rows()[0][1]
    assert "KEY iv (v)" in text and "UNIQUE KEY ue (e)" in text
    # SHOW TABLES hides index storage tables (virtual views do list)
    names = [r[0] for r in s.execute("show tables").rows()
             if r[0] not in db.virtual_tables.names()]
    assert names == ["t"]
    with pytest.raises(DuplicateKey):
        s.execute("insert into t values (1, 1, 'x'), (2, 2, 'x')")
    db.close()


def test_truncate_clears_indexes(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create unique index uv on t (v)")
    s.execute("insert into t values (1, 10)")
    s.execute("truncate table t")
    # the old entry must not block re-insertion of the same value
    s.execute("insert into t values (2, 10)")
    snap = db.tenant().tx.gts.current()
    store = db.engine.tables["__idx__t__uv"].tablet
    arrays, _ = store.snapshot_arrays(snap)
    assert sorted(zip(arrays["v"].tolist(), arrays["k"].tolist())) == \
        [(10, 2)]
    db.close()


def test_bulk_load_maintains_index(tmp_path):
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create index iv on t (v)")
    db.engine.bulk_load("t", {"k": np.arange(100, dtype=np.int64),
                              "v": np.arange(100, dtype=np.int64) % 5},
                        version=db.tenant().tx.gts.current())
    db.tenant().catalog.invalidate("t")
    rows = s.execute("select count(*) from t where v = 2").rows()
    assert rows[0][0] == 20
    store = db.engine.tables["__idx__t__iv"].tablet
    assert store.row_count_estimate() == 100
    db.close()


def test_point_lookup_prunes_chunks(tmp_path):
    """Key-sorted segments + zone maps: a point get decodes only the
    chunks that can hold the key, not the whole segment."""
    from oceanbase_tpu.kv import KvTable
    from oceanbase_tpu.storage import segment as seg_mod

    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    n = 50_000
    db.engine.bulk_load("t", {"k": np.arange(n, dtype=np.int64),
                              "v": np.arange(n, dtype=np.int64)},
                        version=db.tenant().tx.gts.current())
    # shrink chunks so one segment has many (bulk_load above used the
    # default 64k chunk; rebuild with small chunks to exercise pruning)
    tab = db.engine.tables["t"].tablet
    old = tab.segments[-1]
    a, v = old.decode()
    small = seg_mod.Segment.build(
        old.segment_id, old.level, a, old.types,
        {k: x for k, x in v.items() if x is not None},
        min_version=old.min_version, max_version=old.max_version,
        chunk_rows=4096)
    tab.segments[-1] = small
    calls = {"n": 0}
    orig = seg_mod.decode_column

    def counting(ec, out_dtype=None):
        calls["n"] += 1
        return orig(ec, out_dtype)

    seg_mod.decode_column = counting
    try:
        kv = KvTable(db.tenant(), "t")
        row = kv.get((12345,))
    finally:
        seg_mod.decode_column = orig
    assert row["v"] == 12345
    # one chunk x (2 cols + bookkeeping) decodes, not ~13 chunks' worth
    n_chunks = small.n_chunks
    assert n_chunks >= 12
    assert calls["n"] <= 6, f"decoded {calls['n']} chunks-worth"
    db.close()


def test_create_index_waits_for_inflight_tx(tmp_path):
    """Review finding: writes of a transaction live at CREATE INDEX time
    predate maintenance; the build must drain it before backfilling."""
    import threading
    import time as _t

    db = _mk(tmp_path)
    s1 = db.session()
    s2 = db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("begin")
    s1.execute("insert into t values (1, 10)")

    done = {}

    def build():
        done["t0"] = _t.time()
        s2.execute("create index iv on t (v)")
        done["t1"] = _t.time()

    th = threading.Thread(target=build)
    th.start()
    _t.sleep(0.3)
    assert "t1" not in done  # still draining
    s1.execute("commit")
    th.join(timeout=10)
    assert "t1" in done
    # the drained transaction's row made it into the index
    rows = s1.execute("select k from t where v = 10").rows()
    assert rows == [(1,)]
    snap = db.tenant().tx.gts.current()
    store = db.engine.tables["__idx__t__iv"].tablet
    arrays, _ = store.snapshot_arrays(snap)
    assert sorted(zip(arrays["v"].tolist(), arrays["k"].tolist())) == \
        [(10, 1)]
    db.close()


def test_bulk_load_unique_checks_existing_rows(tmp_path):
    """Review finding: LOAD DATA must enforce unique indexes against
    already-committed rows, not only batch-locally."""
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create unique index uv on t (v)")
    s.execute("insert into t values (1, 5)")
    with pytest.raises(Exception):
        db.engine.bulk_load(
            "t", {"k": np.array([2], dtype=np.int64),
                  "v": np.array([5], dtype=np.int64)},
            version=db.tenant().tx.gts.current())
    # re-loading the SAME row (same pk) is fine
    db.engine.bulk_load(
        "t", {"k": np.array([1], dtype=np.int64),
              "v": np.array([5], dtype=np.int64)},
        version=db.tenant().tx.gts.current())
    db.close()


def test_inline_index_catalog_only_session_fails_cleanly():
    """Review finding: inline KEY in a catalog-only session must fail
    BEFORE creating the table."""
    from oceanbase_tpu.sql.session import Session

    s = Session()
    with pytest.raises(NotImplementedError):
        s.execute("create table t (a int, index ia (a))")
    assert not s.catalog.has_table("t")
    s.execute("create table t (a int)")  # now works
