"""XA transactions: externally-coordinated 2PC across sessions
(≙ src/storage/tx/ob_xa_service.h).
"""

import pytest

from oceanbase_tpu.server import Database


def test_xa_prepare_commit_across_sessions(tmp_path):
    db = Database(str(tmp_path / "db"))
    s1 = db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("xa start 'x1'")
    s1.execute("insert into t values (1, 10)")
    s1.execute("xa end 'x1'")
    s1.execute("xa prepare 'x1'")
    # invisible until commit; visible in XA RECOVER
    s2 = db.session()
    assert s2.execute("select count(*) from t").rows()[0][0] == 0
    assert s2.execute("xa recover").rows() == [("x1",)]
    # ANOTHER session drives the commit (the XA point)
    s2.execute("xa commit 'x1'")
    assert s2.execute("select k, v from t").rows() == [(1, 10)]
    assert s2.execute("xa recover").rows() == []
    db.close()


def test_xa_rollback_prepared(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("xa start 'r1'")
    s.execute("insert into t values (1, 1)")
    s.execute("xa end 'r1'")
    s.execute("xa prepare 'r1'")
    s.execute("xa rollback 'r1'")
    assert s.execute("select count(*) from t").rows()[0][0] == 0
    # the xid is free again
    s.execute("xa start 'r1'")
    s.execute("insert into t values (2, 2)")
    s.execute("xa end 'r1'")
    s.execute("xa commit 'r1'")  # one-phase (never prepared)
    assert s.execute("select k from t").rows() == [(2,)]
    db.close()


def test_xa_prepared_redo_is_durable_in_wal(tmp_path):
    """The prepare phase ships redo+prepare to the replicated log: a
    commit record after it must replay the writes at recovery."""
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("xa start 'd1'")
    s.execute("insert into t values (7, 70)")
    s.execute("xa end 'd1'")
    s.execute("xa prepare 'd1'")
    s.execute("xa commit 'd1'")
    db.close()
    db2 = Database(str(tmp_path / "db"))
    assert db2.session().execute(
        "select k, v from t").rows() == [(7, 70)]
    db2.close()


def test_xa_errors(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    with pytest.raises(KeyError):
        s.execute("xa commit 'nope'")
    s.execute("xa start 'a'")
    with pytest.raises(RuntimeError):
        s.execute("xa start 'b'")
    s.execute("xa end 'a'")
    s.execute("xa rollback 'a'")
    db.close()


def test_xa_guards(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    # plain COMMIT inside an XA branch is rejected (XAER_RMFAIL analog)
    s.execute("xa start 'g1'")
    s.execute("insert into t values (1)")
    with pytest.raises(RuntimeError):
        s.execute("commit")
    # the session is NOT wedged after XA PREPARE (tx detaches)
    s.execute("xa end 'g1'")
    s.execute("xa prepare 'g1'")
    s.execute("insert into t values (99)")  # autocommit works again
    s.execute("xa commit 'g1'")
    rows = s.execute("select k from t order by k").rows()
    assert rows == [(1,), (99,)]
    # ONE PHASE syntax parses
    s.execute("xa start 'g2'")
    s.execute("insert into t values (2)")
    s.execute("xa end 'g2'")
    s.execute("xa commit 'g2' one phase")
    assert s.execute("select count(*) from t").rows()[0][0] == 3
    db.close()
