"""Regression tests for the partitioning/auto-increment review findings."""

import pytest

from oceanbase_tpu.server import Database


def test_partition_moving_update(tmp_path):
    # finding 1: UPDATE moving the partition key must not duplicate the row
    db = Database(str(tmp_path / "db"))
    s = db.session()
    # the partition column must be part of the PK (MySQL/OceanBase rule,
    # enforced since r2) — a composite PK still exercises the move
    s.execute("create table t (k int, v int, primary key (k, v)) "
              "partition by range (v) ("
              "partition p0 values less than (100), "
              "partition p1 values less than maxvalue)")
    s.execute("insert into t values (1, 50)")
    s.execute("update t set v = 150 where k = 1")
    rows = s.execute("select k, v from t").rows()
    assert rows == [(1, 150)]
    tablet = db.engine.tables["t"].tablet
    assert len(tablet.partitions[1].active) >= 1
    db.close()


def test_partial_minor_compact_keeps_other_partitions(tmp_path):
    # finding 2: slog must not record still-live segments as removed
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int) "
              "partition by range (k) ("
              "partition p0 values less than (100), "
              "partition p1 values less than maxvalue)")
    # two flushes for partition 0, one for partition 1
    s.execute("insert into t values (1, 1), (200, 2)")
    db.checkpoint()
    s.execute("insert into t values (2, 3)")
    db.checkpoint()
    db.engine.minor_compact("t")  # only partition 0 has >= 2 L0s
    # crash WITHOUT a manifest checkpoint: slog replay must keep p1's data
    db.close()
    db2 = Database(root)
    r = db2.session().execute("select k from t order by k").rows()
    assert r == [(1,), (2,), (200,)]
    db2.close()


def test_auto_increment_survives_restart(tmp_path):
    # finding 3: the auto-increment property persists
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (id int primary key auto_increment, "
              "name varchar(10))")
    s.execute("insert into t (name) values ('a'), ('b')")
    db.checkpoint()
    db.close()
    db2 = Database(root)
    s2 = db2.session()
    s2.execute("insert into t (name) values ('c')")
    rows = s2.execute("select id, name from t order by id").rows()
    ids = [r[0] for r in rows]
    assert None not in ids and len(set(ids)) == 3
    db2.close()


def test_auto_increment_advances_past_explicit(tmp_path):
    # finding 4: explicit inserts bump the counter
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key auto_increment, "
              "name varchar(10))")
    s.execute("insert into t values (3, 'x')")
    s.execute("insert into t (name) values ('a'), ('b'), ('c')")
    rows = s.execute("select id from t order by id").rows()
    ids = [r[0] for r in rows]
    assert len(ids) == 4 and len(set(ids)) == 4
    assert 3 in ids
    db.close()


def test_partition_spec_validation(tmp_path):
    from oceanbase_tpu.sql.parser import ParseError

    db = Database(str(tmp_path / "db"))
    s = db.session()
    with pytest.raises(ParseError):
        s.execute("create table b1 (k int) partition by range (k) ("
                  "partition p0 values less than maxvalue, "
                  "partition p1 values less than (10))")
    with pytest.raises(ParseError):
        s.execute("create table b2 (k int) partition by range (k) ("
                  "partition p0 values less than (20), "
                  "partition p1 values less than (10))")
    db.close()
