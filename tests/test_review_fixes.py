"""Regression tests for the round-1 code-review findings."""

import numpy as np
import pytest

from oceanbase_tpu.datatypes import SqlType, date_to_days
from oceanbase_tpu.exec import AggSpec, hash_groupby, join, sort_rows
from oceanbase_tpu.exec.diag import CapacityOverflow
from oceanbase_tpu.exec.plan import HashJoin, TableScan, execute_plan
from oceanbase_tpu.expr import ir
from oceanbase_tpu.expr.compile import US_PER_DAY, eval_expr, eval_predicate
from oceanbase_tpu.vector import from_numpy, to_numpy


def test_join_overflow_raises():
    left = from_numpy({"k": np.array([1, 1, 1, 1])})
    right = from_numpy({"rk": np.array([1, 1, 1, 1])})
    plan = HashJoin(TableScan("l"), TableScan("r"),
                    [ir.col("k")], [ir.col("rk")], how="inner",
                    out_capacity=4)  # true output is 16
    with pytest.raises(CapacityOverflow):
        execute_plan(plan, {"l": left, "r": right})
    # sufficient capacity succeeds
    plan2 = HashJoin(TableScan("l"), TableScan("r"),
                     [ir.col("k")], [ir.col("rk")], how="inner",
                     out_capacity=16)
    out = execute_plan(plan2, {"l": left, "r": right})
    assert int(out.count()) == 16


def test_datetime_literal_compare_microseconds():
    us = np.array([0, US_PER_DAY, date_to_days("1994-06-01") * US_PER_DAY])
    rel = from_numpy({"ts": us}, types={"ts": SqlType.datetime()})
    p = eval_predicate(ir.col("ts") >= ir.lit("1994-01-01"), rel)
    np.testing.assert_array_equal(np.asarray(p), [False, False, True])
    # with a time component
    p = eval_predicate(ir.col("ts") < ir.lit("1970-01-01 00:00:01"), rel)
    np.testing.assert_array_equal(np.asarray(p), [True, False, False])


def test_count_distinct_null_key_group():
    rel = from_numpy(
        {"k": np.array([0, 0, 5, 5]), "v": np.array([10, 20, 30, 30])},
        valids={"k": np.array([True, False, True, True])},
    )
    out = hash_groupby(rel, {"k": ir.col("k")},
                       [AggSpec("cd", "count_distinct", ir.col("v"))])
    res = to_numpy(out)
    # groups: k=0 -> {10}, k=NULL -> {20}, k=5 -> {30}
    assert sorted(res["cd"].tolist()) == [1, 1, 1]
    assert len(res["cd"]) == 3


def test_inlist_decimal_scale_down():
    rel = from_numpy({"d": np.array([5, 7, 50])},  # 0.05, 0.07, 0.50
                     types={"d": SqlType.decimal(15, 2)})
    p = eval_predicate(
        ir.col("d").isin([ir.lit("0.050", SqlType.decimal()),
                          ir.lit("0.071", SqlType.decimal()),
                          ir.lit("0.5", SqlType.decimal())]), rel)
    np.testing.assert_array_equal(np.asarray(p), [True, False, True])


def test_sort_nulls_mysql_order():
    rel = from_numpy({"x": np.array([3, 0, 2])},
                     valids={"x": np.array([True, False, True])})
    out = sort_rows(rel, [ir.col("x")], [True])
    got = np.asarray(out.columns["x"].valid)
    assert not got[0] and got[1] and got[2]  # NULL first under ASC
    np.testing.assert_array_equal(np.asarray(out.columns["x"].data)[1:], [2, 3])
    out = sort_rows(rel, [ir.col("x")], [False])
    got = np.asarray(out.columns["x"].valid)
    assert got[0] and got[1] and not got[2]  # NULL last under DESC
    np.testing.assert_array_equal(np.asarray(out.columns["x"].data)[:2], [3, 2])


def test_arith_reversed_date_and_datetime():
    days = np.array([date_to_days("1994-01-01")])
    rel = from_numpy({"d": days, "ts": days.astype(np.int64) * US_PER_DAY},
                     types={"d": SqlType.date(), "ts": SqlType.datetime()})
    c = eval_expr(ir.Arith("+", ir.lit(5), ir.col("d")), rel)
    assert int(c.data[0]) == date_to_days("1994-01-06")
    c = eval_expr(ir.col("ts") + ir.lit(1), rel)
    assert int(c.data[0]) == date_to_days("1994-01-02") * US_PER_DAY
    c = eval_expr(ir.col("d") - ir.lit("1993-12-31", SqlType.date()), rel)
    assert int(c.data[0]) == 1
    with pytest.raises(TypeError):
        eval_expr(ir.Arith("-", ir.lit(5), ir.col("d")), rel)


def test_case_string_branches():
    rel = from_numpy({"s": np.array(["a", "b", "c"])})
    e = ir.Case(whens=[(ir.col("s").eq(ir.lit("a")), ir.lit("hit"))],
                else_=ir.lit("miss"))
    c = eval_expr(e, rel)
    assert c.sdict is not None
    vals = c.sdict.values[np.asarray(c.data)]
    np.testing.assert_array_equal(vals, ["hit", "miss", "miss"])
    # coalesce over strings keeps a dictionary too
    e2 = ir.FuncCall("coalesce", [ir.col("s"), ir.lit("x")])
    c2 = eval_expr(e2, rel)
    assert c2.sdict is not None


def test_dist_exchange_overflow_raises(rng):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from oceanbase_tpu.px.dist_ops import dist_groupby
    from oceanbase_tpu.px.exchange import default_mesh

    mesh = default_mesh(8)
    n = 4096
    # many distinct keys with a tiny per-stage capacity must raise instead
    # of silently dropping groups
    g = rng.integers(0, 4096, n)
    rel = from_numpy({"g": g, "v": rng.integers(0, 10, n)})
    with pytest.raises(CapacityOverflow):
        dist_groupby(rel, {"g": ir.col("g")},
                     [AggSpec("s", "sum", ir.col("v"))],
                     mesh, local_cap=8, out_cap=4096)
