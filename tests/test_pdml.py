"""Parallel DML: the write phase of big statements fans out over tenant
workers under ONE transaction (VERDICT r3 item #6).

≙ src/sql/engine/pdml (partition-aware parallel insert/update/delete
DFOs) + ob_sub_trans_ctrl.h (sub-tasks under one tx).
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database

N = 30_000


def _mk(tmp_path, threshold=1000, dop=4):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute(f"alter system set pdml_min_rows = {threshold}")
    s.execute(f"alter system set pdml_dop = {dop}")
    return db, s


def test_pdml_insert_select_with_index_and_wal(tmp_path):
    db, s = _mk(tmp_path)
    s.execute("create table src (k int primary key, v int, g int)")
    rows = ", ".join(f"({i}, {i * 3 % 997}, {i % 50})" for i in range(N))
    s.execute(f"insert into src values {rows}")
    s.execute("create table dst (k int primary key, v int, g int)")
    s.execute("create index iv on dst (v)")
    # the PDML path: INSERT ... SELECT over the threshold
    r = s.execute("insert into dst select k, v, g from src")
    assert r.rowcount == N
    r = s.execute("select count(*), sum(v) from dst")
    cnt, sv = r.rows()[0]
    assert cnt == N and sv == sum(i * 3 % 997 for i in range(N))
    # secondary index maintained by the parallel writers
    r = s.execute("select count(*) from dst where v = 3")
    exp = sum(1 for i in range(N) if i * 3 % 997 == 3)
    assert r.rows()[0][0] == exp
    # WAL intact: recovery rebuilds the same table
    db.close()
    db2 = Database(str(tmp_path / "db"))
    s2 = db2.session()
    r = s2.execute("select count(*), sum(v) from dst")
    assert tuple(r.rows()[0]) == (cnt, sv)
    db2.close()


def test_pdml_insert_into_partitioned_table(tmp_path):
    db, s = _mk(tmp_path)
    s.execute("create table src (k int primary key, v int)")
    rows = ", ".join(f"({i}, {i % 1000})" for i in range(N))
    s.execute(f"insert into src values {rows}")
    s.execute("create table pt (k int primary key, v int) "
              "partition by range (k) ("
              "partition p0 values less than (10000), "
              "partition p1 values less than (20000), "
              "partition p2 values less than maxvalue)")
    s.execute("insert into pt select k, v from src")
    r = s.execute("select count(*), sum(v) from pt")
    assert tuple(r.rows()[0]) == (N, sum(i % 1000 for i in range(N)))
    # per-partition routing kept rows where they belong
    r = s.execute("select count(*) from pt where k < 10000")
    assert r.rows()[0][0] == 10000
    db.close()


def test_pdml_bulk_update_and_delete(tmp_path):
    db, s = _mk(tmp_path)
    s.execute("create table t (k int primary key, v int, g int)")
    rows = ", ".join(f"({i}, {i % 100}, {i % 7})" for i in range(N))
    s.execute(f"insert into t values {rows}")
    r = s.execute("update t set v = v + 1000 where g < 5")
    n_upd = sum(1 for i in range(N) if i % 7 < 5)
    assert r.rowcount == n_upd
    r = s.execute("select sum(v) from t")
    exp = sum((i % 100) + (1000 if i % 7 < 5 else 0) for i in range(N))
    assert r.rows()[0][0] == exp
    r = s.execute("delete from t where g = 6")
    n_del = sum(1 for i in range(N) if i % 7 == 6)
    assert r.rowcount == n_del
    r = s.execute("select count(*) from t")
    assert r.rows()[0][0] == N - n_del
    db.close()


def test_pdml_atomicity_on_failure(tmp_path):
    db, s = _mk(tmp_path)
    s.execute("create table src (k int primary key, v int)")
    # duplicate target PKs WITHIN the payload -> serial path handles;
    # here: dup against EXISTING rows must roll the whole statement back
    rows = ", ".join(f"({i}, {i})" for i in range(5000))
    s.execute(f"insert into src values {rows}")
    s.execute("create table dst (k int primary key, v int)")
    s.execute("insert into dst values (4999, -1)")
    with pytest.raises(Exception):
        s.execute("insert into dst select k, v from src")
    r = s.execute("select count(*), sum(v) from dst")
    # statement rolled back atomically: only the pre-existing row remains
    assert tuple(r.rows()[0]) == (1, -1)
    db.close()
