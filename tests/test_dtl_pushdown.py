"""DTL cross-node compute pushdown: partial plans ship to data nodes,
only exchange rows come back (px/dtl.py; ≙ PX DFOs executing on the
servers that own the data, src/sql/dtl).

Covers: wire-codec roundtrip + qualification (unit), and over a real
3-process cluster: result parity pushdown vs serial, bytes-on-wire
< 5% of the das.scan snapshot-pull baseline, gv$px_exchange counters,
group-by pushdown, and node-down fallback.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from test_multinode import Cluster

# ---------------------------------------------------------------------------
# unit: qualification + wire codec
# ---------------------------------------------------------------------------


def _bind(sql, cat):
    from oceanbase_tpu.sql.binder import Binder
    from oceanbase_tpu.sql.parser import parse_sql

    plan, outs, _ = Binder(cat).bind_select(parse_sql(sql))
    return plan


@pytest.fixture()
def catalog():
    from oceanbase_tpu.catalog import Catalog

    cat = Catalog()
    rng = np.random.default_rng(7)
    n = 4096
    cat.load_numpy("t", {
        "k": np.arange(n),
        "v": rng.integers(0, 100, n),
        "d": rng.integers(0, 1000, n),
    }, primary_key=["k"])
    return cat


def test_plan_codec_roundtrip(catalog):
    from oceanbase_tpu.px import dtl

    for sql in (
        "select sum(v), count(*), min(d), max(d), avg(v) from t "
        "where d < 500 and v > 3",
        "select d, sum(v) from t where d in (1, 2, 3) group by d",
        "select k, v from t where d < 5 or d > 990",
    ):
        push = dtl.split_pushdown(_bind(sql, catalog))
        assert push is not None, sql
        dec = dtl.decode_plan(push.encoded)
        assert dec.fingerprint() == push.remote.fingerprint()


def test_split_pushdown_qualification(catalog):
    from oceanbase_tpu.px import dtl

    # joins / multi-scan plans stay serial
    assert dtl.split_pushdown(
        _bind("select a.k from t a, t b where a.k = b.k", catalog)) is None
    # an unfiltered un-aggregated scan would ship the whole table
    assert dtl.split_pushdown(_bind("select k from t", catalog)) is None
    # count(distinct) does not decompose into partial/final
    assert dtl.split_pushdown(
        _bind("select count(distinct v) from t where d < 9",
              catalog)) is None
    # aggregates above the scan chain qualify, Sort/Limit stay local
    push = dtl.split_pushdown(
        _bind("select d, sum(v) as s from t where d < 100 "
              "group by d order by s desc limit 3", catalog))
    assert push is not None and push.has_agg
    assert push.table == "t"


def test_slice_masks_partition_and_cover():
    from oceanbase_tpu.px import dtl

    arrays = {"a": np.arange(10000), "b": np.arange(10000) % 97}
    masks = [dtl.slice_mask(arrays, ["a", "b"], p, 3) for p in range(3)]
    total = np.zeros(10000, dtype=np.int64)
    for m in masks:
        total += m.astype(np.int64)
    assert (total == 1).all()  # disjoint and complete
    # deterministic across calls (replicas must agree)
    again = dtl.slice_mask(arrays, ["a", "b"], 1, 3)
    assert (again == masks[1]).all()


# ---------------------------------------------------------------------------
# cluster: pushdown vs pull over 3 real node processes
# ---------------------------------------------------------------------------

N_ROWS = 3000


def _load(c, n=N_ROWS, batch=750):
    c.execute(1, "create table q6 (k int primary key, v int, d int)")
    rng = np.random.default_rng(11)
    v = rng.integers(0, 100, n)
    d = rng.integers(0, 1000, n)
    for s in range(0, n, batch):
        vals = ", ".join(f"({i}, {v[i]}, {d[i]})"
                         for i in range(s, min(s + batch, n)))
        c.execute(1, f"insert into q6 values {vals}")
    return v, d


def _wait_converged(c, n, nodes=(2, 3), timeout=40):
    deadline = time.time() + timeout
    for i in nodes:
        while time.time() < deadline:
            try:
                res = c.execute(i, "select count(*) from q6",
                                consistency="weak")
                if res["node"] == i and c.rows(res)[0][0] == n:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            raise AssertionError(f"node {i} never converged")


def _pull_bytes(c, node=1, table="q6"):
    """Wire cost of the legacy snapshot pull: node 1 pages the whole
    table from node 2 over das.scan (the path pushdown replaces)."""
    r = c.clients[node].call("das.pull", table=table, node_id=2)
    assert r["rows"] == N_ROWS
    return r["bytes"]


def test_dtl_pushdown_parity_bytes_and_groupby(tmp_path):
    c = Cluster(tmp_path, n=3)
    try:
        v, d = _load(c)
        _wait_converged(c, N_ROWS)
        c.execute(1, "alter system set dtl_min_rows = 1")

        q = "select sum(v), count(*) from q6 where d < 500"
        res = c.execute(1, q)
        sel = d < 500
        assert c.rows(res) == [(int(v[sel].sum()), int(sel.sum()))]

        # the exchange recorded a pushdown hit with tiny wire cost
        ex = c.execute(
            1, "select mode, pushdown_hit, bytes_shipped, rows_shipped,"
               " parts, fallback_parts from gv$px_exchange"
               " order by ts desc limit 1")
        (mode, hit, nbytes, rows, parts, fallbacks), = c.rows(ex)
        assert mode == "pushdown" and hit == 1
        assert parts == 3 and fallbacks == 0
        assert rows <= 4  # two partial-agg rows, not 3000 table rows
        baseline = _pull_bytes(c)
        assert nbytes < 0.05 * baseline, (nbytes, baseline)
        # the pull recorded its own gv$px_exchange row for comparison
        pl = c.execute(
            1, "select bytes_shipped from gv$px_exchange where"
               " mode = 'pull' order by ts desc limit 1")
        assert c.rows(pl)[0][0] == baseline
        # v$palf works on a cluster node (NetPalf single-replica view)
        pf = c.execute(1, "select role, replica_id from v$palf")
        assert c.rows(pf) == [("leader", 1)]

        # group-by pushdown: parity against the serial path
        gq = ("select d, sum(v), count(*), avg(v) from q6 "
              "where d < 200 group by d order by d")
        push_rows = c.rows(c.execute(1, gq))
        c.execute(1, "alter system set enable_dtl_pushdown = false")
        serial_rows = c.rows(c.execute(1, gq))
        assert push_rows == serial_rows
        serial_scalar = c.rows(c.execute(1, q))
        assert serial_scalar == [(int(v[sel].sum()), int(sel.sum()))]
    finally:
        c.close()


def test_dtl_node_down_falls_back(tmp_path):
    c = Cluster(tmp_path, n=3)
    try:
        v, d = _load(c, n=1500)
        _wait_converged(c, 1500)
        c.execute(1, "alter system set dtl_min_rows = 1")
        c.kill(3)
        q = "select sum(v), count(*) from q6 where d >= 500"
        res = c.execute(1, q)
        sel = d >= 500
        assert c.rows(res) == [(int(v[sel].sum()), int(sel.sum()))]
        ex = c.execute(
            1, "select pushdown_hit, fallback_parts from gv$px_exchange"
               " order by ts desc limit 1")
        (hit, fallbacks), = c.rows(ex)
        assert hit == 1
        assert fallbacks >= 1  # the dead node's slice ran locally
    finally:
        c.close()
