"""Regression tests for the satellites/streaming code-review findings."""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import WriteConflict


def test_streamed_lsm_after_update_delete(tmp_path):
    # finding 1: streamed scans must apply newest-wins + tombstones
    from oceanbase_tpu.exec.granule import (
        execute_streamed,
        segment_chunk_provider,
    )
    from oceanbase_tpu.exec.ops import AggSpec
    from oceanbase_tpu.exec.plan import ScalarAgg, TableScan
    from oceanbase_tpu.expr import ir
    from oceanbase_tpu.vector import to_numpy

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 5), (2, 9), (3, 100)")
    s.execute("update t set v = 7 where k = 1")
    s.execute("delete from t where k = 3")
    db.checkpoint()  # multi-version L0 with tombstone
    s.execute("update t set v = 10 where k = 2")  # newer, memtable only

    plan = ScalarAgg(TableScan("t", rename={"k": "k", "v": "v"}),
                     [AggSpec("s", "sum", ir.col("v")),
                      AggSpec("c", "count_star")])
    tablet = db.engine.tables["t"].tablet
    out = to_numpy(execute_streamed(
        plan, segment_chunk_provider(tablet, db.tx.gts.current()),
        chunk_rows=2))
    assert out["c"][0] == 2          # k=3 deleted
    assert out["s"][0] == 7 + 10     # newest versions only
    db.close()


def test_lock_tables_blocks_dml(tmp_path):
    # finding 3: LOCK TABLES WRITE must block other sessions' DML
    db = Database(str(tmp_path / "db"))
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key)")
    s1.execute("alter system set lock_wait_timeout_s = 0.3")
    s1.execute("lock tables t write")
    with pytest.raises(WriteConflict):
        # DML takes an implicit IX lock that conflicts with the X lock
        s2.execute("insert into t values (1)")
    s1.execute("unlock tables")
    s2.execute("insert into t values (1)")
    # finding 2: autocommit DML after UNLOCK actually commits
    s2_tx = s2._tx
    assert s2_tx is None
    assert db.session().execute("select count(*) from t").rows() == [(1,)]
    db.close()


def test_kv_put_after_checkpoint_is_update(tmp_path):
    # finding 5: upsert of a flushed key must log/CDC as update
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    kv = db.tenant().kv("t")
    pump = db.tenant().cdc()
    kv.put({"k": 1, "v": 1})
    db.checkpoint()
    pump.poll()
    kv.put({"k": 1, "v": 2})
    events = pump.poll()
    assert [(e.op, e.key) for e in events] == [("update", (1,))]
    db.close()


def test_explain_does_not_burn_sequence(tmp_path):
    # finding 6
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create sequence sq start 5")
    s.execute("explain select nextval('sq')")
    assert s.execute("select nextval('sq') as v").rows() == [(5,)]
    from oceanbase_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        s.execute("select nextval()")
    db.close()


def test_descending_sequence(tmp_path):
    # finding 7: negative increments use the cache properly
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create sequence down start 0 increment -2 cache 100")
    vals = [s.execute("select nextval('down') as v").rows()[0][0]
            for _ in range(4)]
    assert vals == [0, -2, -4, -6]
    # only one range allocation persisted (cache actually caches)
    hwm = db.engine.meta["sequences"]["down"]["hwm"]
    assert hwm == 0 - 2 * 100
    db.close()
