"""Storage engine unit tests (≙ unittest/storage tiers)."""

import numpy as np
import pytest

from oceanbase_tpu.catalog import ColumnDef, TableDef
from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.storage.encoding import decode_column, encode_column
from oceanbase_tpu.storage.engine import StorageCatalog, StorageEngine
from oceanbase_tpu.storage.segment import Segment, merge_segments
from oceanbase_tpu.storage.tablet import Tablet


def test_encodings_roundtrip(rng):
    cases = {
        "rand": rng.integers(0, 1_000_000, 10000),
        "runs": np.repeat(rng.integers(0, 5, 100), 100),
        "lowcard": rng.integers(0, 10, 10000),
        "monotonic": np.cumsum(rng.integers(1, 5, 10000)),
        "floats": rng.random(1000),
    }
    encs = {}
    for name, arr in cases.items():
        ec = encode_column(np.asarray(arr), None)
        encs[name] = ec.encoding
        np.testing.assert_array_equal(decode_column(ec), arr)
    assert encs["runs"] == "rle"
    assert encs["lowcard"] in ("dict", "delta", "varint")  # all ~1B/row
    assert encs["monotonic"] in ("delta", "varint")


def test_zone_map_pruning(rng):
    arr = np.arange(200000)
    seg = Segment.build(1, 2, {"a": arr}, {"a": SqlType.int_()})
    assert seg.n_chunks == 4  # 65536-row chunks
    mask = seg.prune_chunks("a", 100_000, 120_000)
    assert mask.tolist() == [False, True, False, False]
    arrays, _ = seg.decode(chunk_mask=mask)
    assert arrays["a"].min() == 65536 and arrays["a"].max() == 131071


def test_segment_persistence(tmp_path, rng):
    arr = {"k": np.arange(1000),
           "s": rng.choice(np.array(["aa", "bb", "cc"]), 1000),
           "v": rng.integers(0, 100, 1000)}
    valids = {"v": rng.random(1000) > 0.1}
    seg = Segment.build(7, 1, arr, {"k": SqlType.int_(),
                                    "s": SqlType.string(),
                                    "v": SqlType.int_()}, valids)
    p = str(tmp_path / "seg.npz")
    seg.save(p)
    seg2 = Segment.load(p)
    a2, v2 = seg2.decode()
    np.testing.assert_array_equal(a2["k"], arr["k"])
    np.testing.assert_array_equal(a2["s"].astype(str), arr["s"].astype(str))
    np.testing.assert_array_equal(v2["v"], valids["v"])
    assert seg2.level == 1 and seg2.segment_id == 7


def test_tablet_mvcc_and_compaction():
    types = {"k": SqlType.int_(), "v": SqlType.int_()}
    t = Tablet(1, ["k", "v"], types, ["k"])
    # tx 1 inserts two rows, commits at version 10
    t.write((1,), "insert", {"k": 1, "v": 100}, tx_id=1)
    t.write((2,), "insert", {"k": 2, "v": 200}, tx_id=1)
    t.commit(1, 10, [(1,), (2,)])
    # tx 2 updates row 1 at v20, deletes row 2 at v20
    t.write((1,), "update", {"k": 1, "v": 111}, tx_id=2)
    t.write((2,), "delete", {"k": 2, "v": 200}, tx_id=2)
    t.commit(2, 20, [(1,), (2,)])

    a, _ = t.snapshot_arrays(snapshot=15)
    assert sorted(zip(a["k"], a["v"])) == [(1, 100), (2, 200)]
    a, _ = t.snapshot_arrays(snapshot=25)
    assert sorted(zip(a["k"], a["v"])) == [(1, 111)]

    # freeze + mini compact, then read again
    t.freeze()
    seg = t.mini_compact(snapshot=30)
    assert seg is not None and seg.level == 0
    a, _ = t.snapshot_arrays(snapshot=25)
    assert sorted(zip(a["k"], a["v"])) == [(1, 111)]

    # more writes -> second L0 -> minor compact -> major
    t.write((3,), "insert", {"k": 3, "v": 300}, tx_id=3)
    t.commit(3, 40, [(3,)])
    t.freeze()
    t.mini_compact(snapshot=50)
    assert len([s for s in t.segments if s.level == 0]) == 2
    t.minor_compact()
    assert len(t.segments) == 1 and t.segments[0].level == 1
    merged = t.major_compact()
    assert merged.level == 2
    a, _ = t.snapshot_arrays(snapshot=50)
    assert sorted(zip(a["k"], a["v"])) == [(1, 111), (3, 300)]


def test_uncommitted_visibility():
    types = {"k": SqlType.int_(), "v": SqlType.int_()}
    t = Tablet(1, ["k", "v"], types, ["k"])
    t.write((1,), "insert", {"k": 1, "v": 1}, tx_id=5)
    # other snapshots don't see it; tx 5 does
    a, _ = t.snapshot_arrays(snapshot=100)
    assert len(a["k"]) == 0
    a, _ = t.snapshot_arrays(snapshot=100, tx_id=5)
    assert list(a["k"]) == [1]
    # write-write conflict
    from oceanbase_tpu.tx.errors import WriteConflict

    with pytest.raises(WriteConflict):
        t.write((1,), "update", {"k": 1, "v": 2}, tx_id=6)
    t.abort(5, [(1,)])
    a, _ = t.snapshot_arrays(snapshot=100, tx_id=5)
    assert len(a["k"]) == 0


def test_engine_persistence_and_recovery(tmp_path):
    root = str(tmp_path / "db")
    eng = StorageEngine(root)
    tdef = TableDef("t", [ColumnDef("k", SqlType.int_()),
                          ColumnDef("v", SqlType.int_())],
                    primary_key=["k"])
    eng.create_table(tdef)
    eng.bulk_load("t", {"k": np.arange(100), "v": np.arange(100) * 2})
    # memtable write + flush
    ts = eng.tables["t"]
    ts.tablet.write((200,), "insert", {"k": 200, "v": 400}, tx_id=1)
    ts.tablet.commit(1, 5, [(200,)])
    eng.freeze_and_flush("t", snapshot=10)
    eng.checkpoint()

    # reopen
    eng2 = StorageEngine(root)
    assert "t" in eng2.tables
    a, _ = eng2.tables["t"].tablet.snapshot_arrays(snapshot=10)
    assert len(a["k"]) == 101
    assert 200 in set(a["k"])

    # compaction after recovery + slog replay path
    eng2.major_compact("t")
    eng3 = StorageEngine(root)
    a, _ = eng3.tables["t"].tablet.snapshot_arrays(snapshot=10)
    assert len(a["k"]) == 101


def test_storage_catalog_executor_integration(tmp_path):
    from oceanbase_tpu.exec.ops import AggSpec
    from oceanbase_tpu.exec.plan import ScalarAgg, TableScan, execute_plan
    from oceanbase_tpu.expr import ir

    eng = StorageEngine(None)
    cat = StorageCatalog(eng)
    cat.load_numpy("t", {"k": np.arange(50), "v": np.arange(50) * 3},
                   primary_key=["k"])
    rel = cat.table_data("t")
    plan = ScalarAgg(TableScan("t"), [AggSpec("s", "sum", ir.col("v"))])
    out = execute_plan(plan, {"t": rel})
    from oceanbase_tpu.vector import to_numpy

    assert to_numpy(out)["s"][0] == sum(range(50)) * 3
    # DML through the tablet invalidates the snapshot cache by version
    ts = eng.tables["t"]
    ts.tablet.write((100,), "insert", {"k": 100, "v": 1000}, tx_id=9)
    ts.tablet.commit(9, 99, [(100,)])
    rel2 = cat.table_data("t")
    # capacity is bucket-padded (static-shape policy); the LIVE count
    # reflects the new row
    assert int(np.asarray(rel2.mask_or_true()).sum()) == 51
    assert rel2.capacity >= 51
