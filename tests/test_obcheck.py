"""obcheck static-analysis suite tests.

Each checker must (a) catch its seeded violation fixture, (b) stay quiet
on the clean twin, (c) honor ``# obcheck: ok(<rule>)`` pragmas, and
(d) report only NEW findings against a baseline.  The final test is the
tier-1 CI gate: the shipped tree diffed against the shipped baseline
must be clean — introducing a host sync, mask drop, or lock inversion
anywhere in the package fails the suite here.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from oceanbase_tpu.analysis import (
    Analyzer,
    diff_findings,
    load_baseline,
    load_package_files,
    run_all,
    write_baseline,
)
from oceanbase_tpu.analysis.cancel_rules import check_cancel_rules
from oceanbase_tpu.analysis.io_rules import check_io_rules
from oceanbase_tpu.analysis.lock_order import check_lock_order
from oceanbase_tpu.analysis.mask_discipline import check_mask_discipline
from oceanbase_tpu.analysis.rpc_rules import check_rpc_rules
from oceanbase_tpu.analysis.trace_safety import check_trace_safety

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

TRACED_BAD = '''
import jax
import jax.numpy as jnp

@jax.jit
def body(x):
    s = jnp.sum(x)
    n = int(s)
    if s > 0:
        return s
    return s * n
'''

HOST_BAD = '''
import jax
import jax.numpy as jnp

def factory():
    def f(x):
        return jnp.sum(x), jnp.max(x)
    return jax.jit(f)

def driver(x):
    run = factory()
    out, ovf = run(x)
    if int(ovf) > 0:
        raise RuntimeError("overflow")
    return out
'''

TRACED_CLEAN = '''
import jax
import jax.numpy as jnp

@jax.jit
def body(x):
    s = jnp.sum(x)
    k = int(x.shape[0])  # static metadata: fine
    if k > 4:            # python int: fine
        return s * 2
    return s
'''

CACHE_BAD = '''
import functools

class Holder:
    def __init__(self, plan):
        self.plan = plan

@functools.lru_cache(maxsize=8)
def compile_plan(holder):
    return holder

def lookup(plan):
    return compile_plan(Holder(plan))
'''

CACHE_CLEAN = '''
import functools

class Holder:
    def __init__(self, plan, key):
        self.plan = plan
        self.key = key

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return isinstance(other, Holder) and other.key == self.key

@functools.lru_cache(maxsize=8)
def compile_plan(holder):
    return holder

def lookup(plan):
    return compile_plan(Holder(plan, repr(plan)))
'''


def test_trace_safety_catches_traced_host_sync():
    fs = {"oceanbase_tpu/exec/bad.py": TRACED_BAD}
    found = run_all(fs, [check_trace_safety])
    assert "trace.host-sync" in _rules(found)
    assert "trace.tracer-branch" in _rules(found)


def test_trace_safety_catches_post_jit_sync():
    fs = {"oceanbase_tpu/px/bad.py": HOST_BAD}
    found = run_all(fs, [check_trace_safety])
    syncs = [f for f in found if f.rule == "trace.host-sync"]
    assert syncs and "int(ovf)" in syncs[0].message


def test_trace_safety_clean_fixture_passes():
    fs = {"oceanbase_tpu/exec/good.py": TRACED_CLEAN}
    assert run_all(fs, [check_trace_safety]) == []


def test_cache_key_identity_hash():
    fs = {"oceanbase_tpu/exec/cache.py": CACHE_BAD}
    found = run_all(fs, [check_trace_safety])
    assert "trace.cache-key" in _rules(found)
    fs = {"oceanbase_tpu/exec/cache.py": CACHE_CLEAN}
    assert run_all(fs, [check_trace_safety]) == []


def test_trace_pragma_suppresses():
    src = TRACED_BAD.replace(
        "    n = int(s)",
        "    n = int(s)  # obcheck: ok(trace.host-sync)").replace(
        "    if s > 0:",
        "    # obcheck: ok(trace)\n    if s > 0:")
    fs = {"oceanbase_tpu/exec/bad.py": src}
    assert run_all(fs, [check_trace_safety]) == []


# ---------------------------------------------------------------------------
# mask discipline
# ---------------------------------------------------------------------------

MASK_BAD = '''
import jax.numpy as jnp

def leaky_total(rel):
    total = jnp.zeros(())
    for c in rel.columns.values():
        total = total + jnp.sum(c.data)
    return total
'''

MASK_CLEAN = '''
import jax.numpy as jnp

def masked_total(rel):
    m = rel.mask_or_true()
    total = jnp.zeros(())
    for c in rel.columns.values():
        total = total + jnp.sum(jnp.where(m, c.data, 0))
    return total
'''


def test_mask_discipline_catches_drop():
    fs = {"oceanbase_tpu/px/leaky.py": MASK_BAD}
    found = run_all(fs, [check_mask_discipline])
    assert _rules(found) == ["mask.drop"]
    # same code outside the operator surface: not under contract
    fs = {"oceanbase_tpu/share/leaky.py": MASK_BAD}
    assert run_all(fs, [check_mask_discipline]) == []


def test_mask_discipline_clean_and_pragma():
    fs = {"oceanbase_tpu/px/ok.py": MASK_CLEAN}
    assert run_all(fs, [check_mask_discipline]) == []
    sup = MASK_BAD.replace(
        "def leaky_total(rel):",
        "def leaky_total(rel):  # obcheck: ok(mask.drop)")
    fs = {"oceanbase_tpu/px/leaky.py": sup}
    assert run_all(fs, [check_mask_discipline]) == []


def test_mask_registry_hygiene():
    from oceanbase_tpu.analysis import mask_discipline as md

    # a stale exemption (function handles mask itself) is itself flagged
    fs = {"oceanbase_tpu/px/ok.py": MASK_CLEAN}
    old = md.CONTRACTS.get("oceanbase_tpu/px/ok.py")
    md.CONTRACTS["oceanbase_tpu/px/ok.py"] = {
        "masked_total": "bogus", "ghost_fn": "gone"}
    try:
        found = run_all(fs, [check_mask_discipline])
    finally:
        if old is None:
            del md.CONTRACTS["oceanbase_tpu/px/ok.py"]
        else:
            md.CONTRACTS["oceanbase_tpu/px/ok.py"] = old
    assert _rules(found) == ["mask.stale-exempt", "mask.unknown-exempt"]


# ---------------------------------------------------------------------------
# lock order
# ---------------------------------------------------------------------------

LOCK_INVERSION = '''
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = B()

    def one(self):
        with self._lock:
            self.peer.two()

class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.owner = A()

    def two(self):
        with self._lock:
            return 1

    def back(self):
        with self._lock:
            self.owner.one()
'''

LOCK_CLEAN = '''
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = B()

    def one(self):
        with self._lock:
            pass
        self.peer.two()   # lock released before calling out

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def two(self):
        with self._lock:
            return 1
'''

UNLOCKED_MUT = '''
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}

    def put(self, k, v):
        self.items[k] = v

    def get(self, k):
        with self._lock:
            return self.items.get(k)
'''


def test_lock_inversion_detected():
    fs = {"oceanbase_tpu/tx/fixture.py": LOCK_INVERSION}
    found = run_all(fs, [check_lock_order])
    inv = [f for f in found if f.rule == "lock.inversion"]
    assert inv and "A._lock" in inv[0].message and \
        "B._lock" in inv[0].message


def test_lock_clean_passes():
    fs = {"oceanbase_tpu/tx/fixture.py": LOCK_CLEAN}
    found = run_all(fs, [check_lock_order])
    assert [f for f in found if f.rule == "lock.inversion"] == []


def test_unlocked_mutation_detected_and_pragma():
    fs = {"oceanbase_tpu/tx/fixture.py": UNLOCKED_MUT}
    found = run_all(fs, [check_lock_order])
    assert _rules(found) == ["lock.unlocked-mut"]
    sup = UNLOCKED_MUT.replace(
        "        self.items[k] = v",
        "        # obcheck: ok(lock.unlocked-mut)\n"
        "        self.items[k] = v")
    fs = {"oceanbase_tpu/tx/fixture.py": sup}
    assert run_all(fs, [check_lock_order]) == []


# ---------------------------------------------------------------------------
# io discipline (durable binary writes carry checksums)
# ---------------------------------------------------------------------------

IO_BAD = '''
import os

def save_blob(path, payload):
    with open(path + ".tmp", "wb") as f:
        f.write(payload)
    os.replace(path + ".tmp", path)
'''

IO_CLEAN = '''
import os

from oceanbase_tpu.native import crc64

def save_blob(path, payload):
    digest = crc64(payload)
    with open(path + ".tmp", "wb") as f:
        f.write(payload + digest.to_bytes(8, "little"))
    os.replace(path + ".tmp", path)
'''

IO_CLEAN_TRANSITIVE = '''
import os

from oceanbase_tpu.native import crc64

def _stamp(payload):
    return payload + crc64(payload).to_bytes(8, "little")

def save_blob(path, payload):
    with open(path + ".tmp", "wb") as f:
        f.write(_stamp(payload))
    os.replace(path + ".tmp", path)
'''


def test_io_catches_unverified_write():
    fs = {"oceanbase_tpu/storage/blob.py": IO_BAD}
    found = run_all(fs, [check_io_rules])
    assert _rules(found) == ["io.unverified-write"]
    # same code outside the durable surface: not under contract
    fs = {"oceanbase_tpu/exec/blob.py": IO_BAD}
    assert run_all(fs, [check_io_rules]) == []


def test_io_clean_direct_and_transitive():
    for src in (IO_CLEAN, IO_CLEAN_TRANSITIVE):
        fs = {"oceanbase_tpu/storage/blob.py": src}
        assert run_all(fs, [check_io_rules]) == []


def test_io_pragma_and_registry():
    sup = IO_BAD.replace(
        '    with open(path + ".tmp", "wb") as f:',
        '    with open(path + ".tmp", "wb") as f:'
        '  # obcheck: ok(io.unverified-write)')
    fs = {"oceanbase_tpu/storage/blob.py": sup}
    assert run_all(fs, [check_io_rules]) == []
    # a registered exemption silences the write without a pragma
    exempt = {"oceanbase_tpu/storage/blob.py": {"save_blob": "transient"}}
    fs = {"oceanbase_tpu/storage/blob.py": IO_BAD}
    assert run_all(fs, [lambda az: check_io_rules(az, exempt)]) == []


def test_io_registry_hygiene():
    """Unknown and stale IO_EXEMPT entries are themselves findings —
    the registry must not rot into a suppression dump."""
    exempt = {"oceanbase_tpu/storage/blob.py": {
        "save_blob": "stale: now digest-protected",
        "ghost_fn": "gone"}}
    fs = {"oceanbase_tpu/storage/blob.py": IO_CLEAN}
    found = run_all(fs, [lambda az: check_io_rules(az, exempt)])
    assert _rules(found) == ["io.unregistered-exemption"]
    assert len(found) == 2  # one stale, one unknown


# ---------------------------------------------------------------------------
# io.inplace-durable-write (stage-then-publish discipline)
# ---------------------------------------------------------------------------

INPLACE_BAD = '''
from oceanbase_tpu.native import crc64

def save_meta(path, payload):
    with open(path, "w") as f:
        f.write(payload + str(crc64(payload.encode())))
'''

INPLACE_STAGED = '''
import os

from oceanbase_tpu.native import crc64

def save_meta(path, payload):
    with open(path + ".tmp", "w") as f:
        f.write(payload + str(crc64(payload.encode())))
    os.replace(path + ".tmp", path)
'''

INPLACE_APPEND = '''
from oceanbase_tpu.native import crc64

def append_meta(path, payload):
    with open(path, "a") as f:
        f.write(payload + str(crc64(payload.encode())))
'''


def test_inplace_catches_direct_write():
    """A create-mode open on the final path (even digest-protected,
    even text mode) is a torn-artifact risk; staging via *.tmp +
    os.replace or appending is the discipline."""
    fs = {"oceanbase_tpu/storage/meta.py": INPLACE_BAD}
    found = run_all(fs, [check_io_rules])
    assert _rules(found) == ["io.inplace-durable-write"]
    # outside the durable surface: not under contract
    fs = {"oceanbase_tpu/exec/meta.py": INPLACE_BAD}
    assert run_all(fs, [check_io_rules]) == []


def test_inplace_clean_staged_and_append():
    for src in (INPLACE_STAGED, INPLACE_APPEND):
        fs = {"oceanbase_tpu/storage/meta.py": src}
        assert run_all(fs, [check_io_rules]) == []


def test_inplace_pragma_and_registry():
    sup = INPLACE_BAD.replace(
        '    with open(path, "w") as f:',
        '    with open(path, "w") as f:'
        '  # obcheck: ok(io.inplace-durable-write)')
    fs = {"oceanbase_tpu/storage/meta.py": sup}
    assert run_all(fs, [check_io_rules]) == []
    ie = {"oceanbase_tpu/storage/meta.py": {"save_meta": "verified"}}
    fs = {"oceanbase_tpu/storage/meta.py": INPLACE_BAD}
    assert run_all(
        fs, [lambda az: check_io_rules(az, inplace_exempt=ie)]) == []


def test_inplace_registry_hygiene():
    """Unknown and stale INPLACE_EXEMPT entries are findings too."""
    ie = {"oceanbase_tpu/storage/meta.py": {
        "save_meta": "stale: now staged",
        "ghost_fn": "gone"}}
    fs = {"oceanbase_tpu/storage/meta.py": INPLACE_STAGED}
    found = run_all(
        fs, [lambda az: check_io_rules(az, inplace_exempt=ie)])
    assert _rules(found) == ["io.unregistered-exemption"]
    assert len(found) == 2  # one stale, one unknown


def test_inplace_real_repo_baseline_empty():
    """The real repo carries no in-place durable writes: every site is
    staged, appended, or audited in INPLACE_EXEMPT."""
    import subprocess

    script = os.path.join(REPO, "scripts", "obcheck.py")
    r = subprocess.run(
        [sys.executable, script, "--json", "--family", "io"],
        capture_output=True, text=True, cwd=REPO)
    summary = json.loads(r.stdout.splitlines()[0])
    assert summary["by_rule"].get("io.inplace-durable-write", 0) == 0
    assert summary["by_rule"].get("io.unregistered-exemption", 0) == 0


# ---------------------------------------------------------------------------
# cancel discipline (blocking loops observe checkpoints)
# ---------------------------------------------------------------------------

CANCEL_BAD = '''
def drain(cli, items):
    out = []
    for it in items:
        out.append(cli.call("das.scan", item=it))
    return out
'''

CANCEL_CLEAN = '''
from oceanbase_tpu.server import admission as qadmission

def drain(cli, items):
    out = []
    for it in items:
        qadmission.checkpoint()
        out.append(cli.call("das.scan", item=it))
    return out
'''

CANCEL_NAMESAKE = '''
def drain(cli, items, tenant):
    out = []
    for it in items:
        tenant.checkpoint()  # the STORAGE checkpoint, not admission
        out.append(cli.call("das.scan", item=it))
    return out
'''


def test_cancel_catches_unchecked_loop():
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_BAD}
    found = run_all(fs, [check_cancel_rules])
    assert _rules(found) == ["cancel.loop-no-checkpoint"]
    # same loop outside the contract surface: quiet
    fs = {"oceanbase_tpu/share/pump.py": CANCEL_BAD}
    assert run_all(fs, [check_cancel_rules]) == []
    # pure-CPU loop (no rpc/sleep/copy): quiet
    fs = {"oceanbase_tpu/exec/pump.py":
          "def f(items):\n    return [i * 2 for i in items]\n"}
    assert run_all(fs, [check_cancel_rules]) == []


def test_cancel_clean_and_namesake_not_satisfying():
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_CLEAN}
    assert run_all(fs, [check_cancel_rules]) == []
    # a storage-plane `.checkpoint()` namesake must NOT satisfy the rule
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_NAMESAKE}
    found = run_all(fs, [check_cancel_rules])
    assert _rules(found) == ["cancel.loop-no-checkpoint"]


def test_cancel_pragma_and_registry():
    sup = CANCEL_BAD.replace(
        "    for it in items:",
        "    for it in items:  # obcheck: ok(cancel)")
    fs = {"oceanbase_tpu/exec/pump.py": sup}
    assert run_all(fs, [check_cancel_rules]) == []
    exempt = {"oceanbase_tpu/exec/pump.py": {"drain": "unwind path"}}
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_BAD}
    assert run_all(fs, [lambda az: check_cancel_rules(az, exempt)]) == []
    # hygiene: entries naming clean or missing functions are flagged
    exempt = {"oceanbase_tpu/exec/pump.py": {"drain": "stale",
                                             "ghost_fn": "gone"}}
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_CLEAN}
    found = run_all(fs, [lambda az: check_cancel_rules(az, exempt)])
    assert _rules(found) == ["cancel.stale-exempt", "cancel.unknown-exempt"]


def test_cancel_fanout_needs_propagation():
    fanout = '''
import threading

def scatter(clients, frag):
    def run_one(cli):
        return cli.call("dtl.execute", frag=frag)
    ts = [threading.Thread(target=run_one) for cli in clients]
    for t in ts:
        t.start()
'''
    fs = {"oceanbase_tpu/px/scatter.py": fanout}
    found = run_all(fs, [check_cancel_rules])
    assert "cancel.fanout-no-propagation" in _rules(found)
    # a cancel-verb path in the spawning function satisfies it
    fixed = fanout.replace(
        "    for t in ts:\n        t.start()",
        "    for t in ts:\n        t.start()\n"
        "    for cli in clients:  # obcheck: ok(cancel.loop-no-checkpoint)\n"
        '        cli.call("dtl.cancel")')
    fs = {"oceanbase_tpu/px/scatter.py": fixed}
    found = run_all(fs, [check_cancel_rules])
    assert "cancel.fanout-no-propagation" not in _rules(found)


# ---------------------------------------------------------------------------
# rpc verb/policy coherence
# ---------------------------------------------------------------------------

RPC_POLICY_SRC = '''
POLICIES: dict = {
    "das.scan":    VerbPolicy(30.0, True, 3),
    "sql.execute": VerbPolicy(600.0, False),
}
'''

RPC_HANDLERS_SRC = '''
class S:
    def handlers(self):
        return {
            "das.scan": self._h_scan,
            "node.rogue": self._h_rogue,
        }
'''

RPC_RESEND_SRC = '''
def forward(cli, sql):
    for _ in range(3):
        try:
            return cli.call("sql.execute", sql=sql)
        except OSError:
            pass
'''


def test_rpc_missing_policy():
    fs = {"oceanbase_tpu/net/rpc.py": RPC_POLICY_SRC,
          "oceanbase_tpu/net/extra.py": RPC_HANDLERS_SRC}
    found = run_all(fs, [check_rpc_rules])
    missing = [f for f in found if f.rule == "rpc.missing-policy"]
    assert len(missing) == 1 and "node.rogue" in missing[0].message
    assert missing[0].path == "oceanbase_tpu/net/extra.py"


def test_rpc_nonidempotent_resend():
    fs = {"oceanbase_tpu/net/rpc.py": RPC_POLICY_SRC,
          "oceanbase_tpu/net/fwd.py": RPC_RESEND_SRC}
    found = run_all(fs, [check_rpc_rules])
    assert "rpc.nonidempotent-resend" in _rules(found)
    # an idempotent verb in the same shape is fine
    fs["oceanbase_tpu/net/fwd.py"] = RPC_RESEND_SRC.replace(
        "sql.execute", "das.scan")
    assert run_all(fs, [check_rpc_rules]) == []
    # pragma round-trip
    fs["oceanbase_tpu/net/fwd.py"] = RPC_RESEND_SRC.replace(
        'return cli.call("sql.execute", sql=sql)',
        'return cli.call(  # obcheck: ok(rpc.nonidempotent-resend)\n'
        '                "sql.execute", sql=sql)')
    assert run_all(fs, [check_rpc_rules]) == []


def test_rpc_bulk_reply_needs_digest():
    handler = '''
def h_scan(table):
    return {"arrays": {}, "total": 0}
'''
    fs = {"oceanbase_tpu/net/extra.py": handler}
    found = run_all(fs, [check_rpc_rules])
    assert _rules(found) == ["rpc.bulk-no-digest"]
    fixed = handler.replace('"total": 0', '"total": 0, "crc": 0')
    fs = {"oceanbase_tpu/net/extra.py": fixed}
    assert run_all(fs, [check_rpc_rules]) == []


def test_new_families_baseline_round_trip(tmp_path):
    """cancel/io findings baseline like every other family: the seeded
    violation lands green once baselined, a second one is new."""
    fs = {"oceanbase_tpu/exec/pump.py": CANCEL_BAD,
          "oceanbase_tpu/storage/blob.py": IO_BAD}
    first = run_all(fs, [check_cancel_rules, check_io_rules])
    assert _rules(first) == ["cancel.loop-no-checkpoint",
                             "io.unverified-write"]
    bp = str(tmp_path / "base.json")
    write_baseline(first, bp)
    base = load_baseline(bp)
    assert diff_findings(first, base) == []
    fs["oceanbase_tpu/storage/blob.py"] = IO_BAD + (
        '\ndef save_other(path, b):\n'
        '    with open(path + ".tmp", "wb") as f:\n'
        '        f.write(b)\n'
        '    os.replace(path + ".tmp", path)\n')
    second = run_all(fs, [check_cancel_rules, check_io_rules])
    new = diff_findings(second, base)
    assert len(new) == 1 and new[0].func == "save_other"


# ---------------------------------------------------------------------------
# baseline diffing
# ---------------------------------------------------------------------------


def test_baseline_only_reports_new(tmp_path):
    fs = {"oceanbase_tpu/tx/fixture.py": UNLOCKED_MUT}
    first = run_all(fs, [check_lock_order])
    assert first
    bp = str(tmp_path / "base.json")
    write_baseline(first, bp)
    base = load_baseline(bp)
    assert diff_findings(first, base) == []
    # a SECOND violation in another method is new, the first stays quiet
    src = UNLOCKED_MUT + (
        "\n    def drop(self, k):\n        self.items.pop(k, None)\n")
    fs = {"oceanbase_tpu/tx/fixture.py": src}
    second = run_all(fs, [check_lock_order])
    new = diff_findings(second, base)
    assert len(new) == 1 and "pop" in new[0].message


def test_parse_error_is_a_finding():
    fs = {"oceanbase_tpu/exec/broken.py": "def f(:\n"}
    found = run_all(fs, [check_trace_safety])
    assert [f.rule for f in found] == ["trace.parse-error"]


# ---------------------------------------------------------------------------
# pragma mechanics
# ---------------------------------------------------------------------------


def test_pragma_family_prefix_and_exact():
    az = Analyzer({"x.py": "a = 1  # obcheck: ok(trace)\n"
                          "b = 2  # obcheck: ok(mask.drop, lock.inversion)\n"
                          "c = 3\n"})
    assert az.suppressed("x.py", 1, "trace.host-sync")
    assert az.suppressed("x.py", 2, "mask.drop")
    assert az.suppressed("x.py", 2, "lock.inversion")
    assert not az.suppressed("x.py", 2, "mask.stale-exempt")
    # a pragma covers its own line and the one below (decorator/def
    # idiom), never two lines down
    assert az.suppressed("x.py", 2, "trace.host-sync")
    assert az.suppressed("x.py", 3, "mask.drop")
    assert not az.suppressed("x.py", 3, "trace.host-sync")


# ---------------------------------------------------------------------------
# the CI gate: shipped tree vs shipped baseline
# ---------------------------------------------------------------------------


def test_repo_is_clean_vs_baseline():
    """Tier-1 gate: any new trace/mask/lock violation in the package
    fails here with the finding's file:line and message."""
    files = load_package_files(REPO)
    findings = run_all(files)
    new = diff_findings(findings, load_baseline())
    assert not new, "NEW obcheck findings:\n" + "\n".join(
        f.render() for f in new)


def test_cli_ci_gate_end_to_end(tmp_path):
    """scripts/obcheck.py --ci: green on a clean tree, red once a seeded
    violation lands, green again after --write-baseline."""
    root = tmp_path / "mini"
    pkg = root / "oceanbase_tpu" / "px"
    pkg.mkdir(parents=True)
    (pkg / "ok.py").write_text(MASK_CLEAN)
    bp = str(tmp_path / "base.json")
    script = os.path.join(REPO, "scripts", "obcheck.py")

    def run(*extra):
        return subprocess.run(
            [sys.executable, script, "--root", str(root),
             "--baseline", bp, *extra],
            capture_output=True, text=True)

    r = run("--write-baseline")
    assert r.returncode == 0, r.stderr
    r = run("--ci", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.splitlines()[0])
    assert summary["metric"] == "obcheck" and summary["new"] == 0

    # seed all three violation families; each must trip the gate
    (pkg / "leaky.py").write_text(MASK_BAD)
    (root / "oceanbase_tpu" / "exec").mkdir()
    (root / "oceanbase_tpu" / "exec" / "sync.py").write_text(TRACED_BAD)
    (root / "oceanbase_tpu" / "tx").mkdir()
    (root / "oceanbase_tpu" / "tx" / "inv.py").write_text(LOCK_INVERSION)
    r = run("--ci", "--json")
    assert r.returncode == 1
    summary = json.loads(r.stdout.splitlines()[0])
    assert summary["new"] >= 3
    assert "mask.drop" in r.stderr
    assert "trace.host-sync" in r.stderr
    assert "lock.inversion" in r.stderr

    r = run("--write-baseline")
    assert r.returncode == 0
    r = run("--ci")
    assert r.returncode == 0


def test_cli_family_filter(tmp_path):
    """--family narrows both the checkers run and the reported rules,
    and the --json summary carries per-family timings."""
    root = tmp_path / "mini"
    pkg = root / "oceanbase_tpu" / "storage"
    pkg.mkdir(parents=True)
    (pkg / "blob.py").write_text(IO_BAD)
    (root / "oceanbase_tpu" / "exec").mkdir()
    (root / "oceanbase_tpu" / "exec" / "pump.py").write_text(CANCEL_BAD)
    script = os.path.join(REPO, "scripts", "obcheck.py")

    def run(*extra):
        return subprocess.run(
            [sys.executable, script, "--root", str(root),
             "--baseline", str(tmp_path / "none.json"), *extra],
            capture_output=True, text=True)

    r = run("--json", "--family", "io")
    summary = json.loads(r.stdout.splitlines()[0])
    assert set(summary["by_rule"]) == {"io.unverified-write"}
    assert set(summary["family_s"]) == {"io"}
    # a full-rule prefix also selects its family
    r = run("--json", "--family", "cancel.loop-no-checkpoint")
    summary = json.loads(r.stdout.splitlines()[0])
    assert set(summary["by_rule"]) == {"cancel.loop-no-checkpoint"}
    # two prefixes compose
    r = run("--json", "--family", "io", "--family", "cancel")
    summary = json.loads(r.stdout.splitlines()[0])
    assert set(summary["by_rule"]) == {"io.unverified-write",
                                       "cancel.loop-no-checkpoint"}
    # --write-baseline refuses a partial run
    r = run("--write-baseline", "--family", "io")
    assert r.returncode == 2
