"""Parser unit tests (≙ unittest/sql/parser)."""

import pytest

from oceanbase_tpu.bench.tpch_queries import QUERIES
from oceanbase_tpu.expr import ir
from oceanbase_tpu.sql import ast
from oceanbase_tpu.sql.parser import parse_sql


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_parse_all_tpch(qnum):
    stmt = parse_sql(QUERIES[qnum])
    assert isinstance(stmt, ast.SelectStmt)
    assert stmt.items


def test_basic_select():
    s = parse_sql("select a, b + 1 as c from t where a > 5 and b in (1,2,3) "
                  "group by a order by c desc limit 10 offset 2")
    assert len(s.items) == 2
    assert s.items[1][1] == "c"
    assert isinstance(s.where, ir.Logic)
    assert s.limit == 10 and s.offset == 2
    assert not s.order_by[0].ascending


def test_joins_and_aliases():
    s = parse_sql("select * from a join b on a.x = b.y "
                  "left join c as cc on b.z = cc.w, d")
    assert len(s.from_) == 2
    j = s.from_[0]
    assert isinstance(j, ast.JoinRef) and j.kind == "left"
    assert isinstance(j.left, ast.JoinRef) and j.left.kind == "inner"


def test_subqueries():
    s = parse_sql("select a from t where exists (select 1 from u where u.x = t.a) "
                  "and a in (select b from v) "
                  "and a > (select avg(b) from w)")
    conj = s.where
    assert isinstance(conj, ir.Logic)


def test_case_cast_extract():
    s = parse_sql("select case when a > 0 then 'p' else 'n' end, "
                  "cast(a as decimal(10,2)), extract(year from d) from t")
    assert isinstance(s.items[0][0], ir.Case)
    assert isinstance(s.items[1][0], ir.Cast)
    assert isinstance(s.items[2][0], ir.FuncCall)


def test_ddl_dml():
    c = parse_sql("create table t (a int primary key, b varchar(10) not null, "
                  "c decimal(15,2), d date)")
    assert isinstance(c, ast.CreateTableStmt)
    assert c.primary_key == ["a"]
    assert len(c.columns) == 4

    i = parse_sql("insert into t (a, b) values (1, 'x'), (2, 'y')")
    assert isinstance(i, ast.InsertStmt) and len(i.rows) == 2

    u = parse_sql("update t set b = 'z', c = c + 1 where a = 1")
    assert isinstance(u, ast.UpdateStmt) and len(u.assignments) == 2

    d = parse_sql("delete from t where a < 5")
    assert isinstance(d, ast.DeleteStmt)

    x = parse_sql("drop table if exists t")
    assert isinstance(x, ast.DropTableStmt) and x.if_exists


def test_setops_and_ctes():
    s = parse_sql("with x as (select a from t) "
                  "select a from x union all select b from u order by 1")
    assert len(s.ctes) == 1
    assert len(s.setops) == 1 and s.setops[0][0] == "union" and s.setops[0][1]


def test_interval_folding():
    s = parse_sql("select 1 from t where d < date '1994-01-01' + interval '1' year")
    cmp = s.where
    assert isinstance(cmp.right, ir.FuncCall) and cmp.right.name == "date_add"


def test_params():
    from oceanbase_tpu.sql.parser import Parser

    p = Parser("select a from t where b = ? and c > ?")
    p.parse()
    assert p.n_params == 2
