"""Shape-bucketed relation capacities (the static-shape policy).

Covers the three contracts the bucket ladder rests on:

1. padded-lane semantics — every operator treats pad lanes as dead, so a
   relation at exact vs bucket-padded capacity yields identical results
   (aggregates, group-by, joins, sorts, top-N, NULL lanes, empty tables);
2. compile amortization — a table grown through several increments
   inside one bucket compiles its plan exactly once, and exactly twice
   across a bucket boundary (exec.plan trace counters / gv$plan_cache);
3. the session plan cache evicts LRU (move-to-front on hit, oldest out)
   honoring plan_cache_mem_limit.
"""

import numpy as np
import pytest

from oceanbase_tpu.exec import ops
from oceanbase_tpu.exec.ops import AggSpec
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector import Relation, bucket_capacity, from_numpy, to_numpy


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------


def test_bucket_ladder():
    assert bucket_capacity(0) == 64
    assert bucket_capacity(1) == 64
    assert bucket_capacity(64) == 64
    assert bucket_capacity(65) == 128
    assert bucket_capacity(1000) == 1024
    assert bucket_capacity(1 << 20) == 1 << 20
    # custom floor/growth
    assert bucket_capacity(5, floor=8, growth=2.0) == 8
    assert bucket_capacity(100, floor=10, growth=3.0) == 270
    # a degenerate growth factor still terminates and rounds up
    assert bucket_capacity(100, floor=4, growth=1.0) >= 100


def test_pad_to_always_materializes_mask():
    rel = from_numpy({"a": np.arange(8)})
    assert rel.mask is None
    same = rel.pad_to(8)
    assert same.mask is not None and bool(np.asarray(same.mask).all())
    padded = rel.pad_to(16)
    assert padded.capacity == 16
    assert int(np.asarray(padded.mask).sum()) == 8
    with pytest.raises(ValueError):
        rel.pad_to(4)


def test_string_dict_content_equality():
    from oceanbase_tpu.vector.column import StringDict

    a = StringDict(np.array(["a", "b", "c"], dtype=object))
    b = StringDict(np.array(["a", "b", "c"], dtype=object))
    c = StringDict(np.array(["a", "b", "d"], dtype=object))
    assert a == b and hash(a) == hash(b)
    assert a != c


# ---------------------------------------------------------------------------
# padded-lane semantics: exact vs bucket-padded capacity
# ---------------------------------------------------------------------------


def _sample_rel():
    return from_numpy(
        {
            "k": np.array([1, 2, 1, 3, 2, 1, 4], dtype=np.int64),
            "v": np.array([10, 20, 30, 40, 50, 60, 70], dtype=np.int64),
            "f": np.array([1.5, 2.5, 3.5, 4.5, 5.5, 6.5, 7.5]),
            "s": np.array(["x", "y", "x", "z", "y", "x", "w"],
                          dtype=object),
        },
        valids={"v": np.array([1, 1, 0, 1, 1, 1, 0], dtype=bool)},
    )


def _rows(rel, names=None):
    out = to_numpy(rel)
    names = names or sorted(k for k in out if not k.startswith("__valid__"))
    rows = []
    n = len(out[names[0]]) if names else 0
    for i in range(n):
        row = []
        for nm in names:
            v = out.get("__valid__" + nm)
            row.append(None if v is not None and not v[i]
                       else out[nm][i])
        rows.append(tuple(row))
    return rows


CASES = [
    ("count_star", lambda r: ops.scalar_agg(
        r, [AggSpec("c", "count_star", None)])),
    ("sum", lambda r: ops.scalar_agg(r, [AggSpec("s", "sum", ir.col("v"))])),
    ("avg", lambda r: ops.scalar_agg(r, [AggSpec("a", "avg", ir.col("f"))])),
    ("count_col", lambda r: ops.scalar_agg(
        r, [AggSpec("c", "count", ir.col("v"))])),
    ("min_max", lambda r: ops.scalar_agg(
        r, [AggSpec("lo", "min", ir.col("v")),
            AggSpec("hi", "max", ir.col("v"))])),
    ("group_by", lambda r: ops.hash_groupby(
        r, {"k": ir.col("k")},
        [AggSpec("s", "sum", ir.col("v")),
         AggSpec("c", "count_star", None)], out_capacity=16)),
    ("group_by_str", lambda r: ops.hash_groupby(
        r, {"s": ir.col("s")},
        [AggSpec("c", "count_star", None)], out_capacity=16)),
    ("order_by", lambda r: ops.sort_rows(
        r, [ir.col("k"), ir.col("v")], [True, False])),
    ("top_n", lambda r: ops.top_n(r, ir.col("f"), False, 3)),
    ("filter", lambda r: ops.filter_rows(
        r, ir.Cmp(">", ir.col("k"), ir.Literal(1)))),
]


@pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
def test_padded_lanes_invisible(name, fn):
    rel = _sample_rel()
    padded = rel.pad_to(bucket_capacity(rel.capacity))
    assert padded.capacity == 64
    a = _rows(fn(rel))
    b = _rows(fn(padded))
    if name in ("group_by", "group_by_str"):
        a, b = sorted(a), sorted(b)
    assert a == b


@pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
def test_poisoned_padded_lanes_invisible(name, fn, poison):
    """The poison-lane verifier over the parity suite: adversarial
    garbage in pad lanes (NaN payloads, sentinel ints, validity flipped
    true) must leave every operator's result bit-identical."""
    from oceanbase_tpu.vector import to_numpy as _to_np

    padded = _sample_rel().pad_to(64)
    clean = _to_np(fn(padded))
    poisoned = _to_np(fn(poison.poison_pad_lanes(padded)))
    ok, why = poison.results_identical(clean, poisoned)
    assert ok, f"{name}: {why}"


def test_poisoned_join_matches_clean(poison):
    left = _sample_rel().pad_to(64)
    right = from_numpy({
        "k2": np.array([1, 2, 5], dtype=np.int64),
        "w": np.array([100, 200, 500], dtype=np.int64),
    }).pad_to(64)
    for how in ("inner", "left", "semi", "anti"):
        clean = ops.join(left, right, [ir.col("k")], [ir.col("k2")],
                         how=how, out_capacity=64)
        pois = ops.join(poison.poison_pad_lanes(left),
                        poison.poison_pad_lanes(right),
                        [ir.col("k")], [ir.col("k2")],
                        how=how, out_capacity=64)
        assert sorted(_rows(clean), key=repr) == \
            sorted(_rows(pois), key=repr), how


def test_padded_join_matches_exact():
    left = _sample_rel()
    right = from_numpy({
        "k2": np.array([1, 2, 5], dtype=np.int64),
        "w": np.array([100, 200, 500], dtype=np.int64),
    })
    exact = ops.join(left, right, [ir.col("k")], [ir.col("k2")],
                     how="inner", out_capacity=64)
    padded = ops.join(left.pad_to(64), right.pad_to(64),
                      [ir.col("k")], [ir.col("k2")],
                      how="inner", out_capacity=64)
    assert sorted(_rows(exact)) == sorted(_rows(padded))
    # outer join: pad lanes must not emit NULL-extended ghost rows
    exact_l = ops.join(left, right, [ir.col("k")], [ir.col("k2")],
                       how="left", out_capacity=64)
    padded_l = ops.join(left.pad_to(64), right.pad_to(64),
                        [ir.col("k")], [ir.col("k2")],
                        how="left", out_capacity=64)
    assert sorted(_rows(exact_l), key=repr) == \
        sorted(_rows(padded_l), key=repr)


def test_empty_table_bucketed(tmp_path):
    """_empty_rel pads to the floor bucket, all lanes dead, and queries
    over it behave as over an empty table."""
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table e (k int primary key, v int)")
    rel = db.tenant("sys").catalog.table_data("e")
    assert rel.capacity == 64  # floor bucket
    assert int(np.asarray(rel.mask).sum()) == 0
    r = s.execute("select count(*), sum(v) from e")
    assert r.rows() == [(0, None)]
    db.close()


# ---------------------------------------------------------------------------
# compile amortization: trace counters
# ---------------------------------------------------------------------------


def test_trace_count_within_and_across_buckets(tmp_path):
    """10 growth increments inside one bucket -> exactly one XLA trace;
    crossing the bucket boundary -> exactly one more."""
    from oceanbase_tpu.exec import plan as ep
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key, v int)")
    q = "select sum(v), count(*) from t"
    nid = 0

    def grow(n):
        nonlocal nid
        vals = ", ".join(f"({nid + i}, {(nid + i) % 7})" for i in range(n))
        nid += n
        s.execute(f"insert into t values {vals}")

    ep.reset_plan_cache_stats()
    expect = []
    for _ in range(10):
        grow(5)  # 10 increments, 50 rows total: all inside bucket 64
        expect.append(s.execute(q).rows())
    stats = ep.plan_cache_stats()
    assert sum(e.xla_traces for e in stats) == 1
    assert sum(e.executions for e in stats) == 10
    assert max(e.last_compile_s for e in stats) > 0

    grow(30)  # 80 rows: bucket 64 -> 128
    r = s.execute(q)
    stats = ep.plan_cache_stats()
    assert sum(e.xla_traces for e in stats) == 2
    assert r.rows()[0][1] == 80

    # gv$plan_cache serves the same counters (snapshot taken before the
    # gv$ query itself executes)
    before = sum(e.xla_traces for e in ep.plan_cache_stats())
    r = s.execute("select xla_trace_count, executions, hit_count "
                  "from gv$plan_cache")
    assert sum(int(x[0]) for x in r.rows()) == before
    db.close()


def test_disable_shape_buckets_retraces(tmp_path):
    """With the knob off, every cardinality change retraces (the old
    behavior stays reachable)."""
    from oceanbase_tpu.exec import plan as ep
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("alter system set enable_shape_buckets = false")
    s.execute("create table t (id int primary key, v int)")
    q = "select sum(v) from t"
    nid = 0
    ep.reset_plan_cache_stats()
    for _ in range(3):
        vals = ", ".join(f"({nid + i}, 1)" for i in range(5))
        nid += 5
        s.execute(f"insert into t values {vals}")
        s.execute(q)
    stats = ep.plan_cache_stats()
    assert sum(e.xla_traces for e in stats) == 3
    rel = db.tenant("sys").catalog.table_data("t")
    assert rel.capacity == 15  # exact, no padding
    db.close()


def test_row_count_is_live_not_padded(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key, v int)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i})" for i in range(10)))
    s.execute("select * from t")  # materializes (padded to 64)
    td = db.tenant("sys").catalog.table_def("t")
    assert td.row_count == 10  # live rows, not the bucket capacity
    db.close()


def test_ann_runtime_handles_bucket_padded_suffix():
    """Bucket padding adds a dead SUFFIX; the ANN runtime slices it off
    instead of disabling the index access path."""
    from oceanbase_tpu.sql import Session

    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(100, 8)).astype(np.float32)
    s = Session()
    s.catalog.load_numpy("emb", {"id": np.arange(100), "v": vecs},
                         primary_key=["id"])
    rel = s.catalog.table_data("emb").pad_to(bucket_capacity(100))
    idx = s._ann_runtime("emb", "v", "l2", rel)
    assert idx is not None and np.asarray(idx).shape == (100, 8)
    # interior dead rows still bail (would need an id remap)
    holed = rel.with_mask(rel.mask_or_true().at[3].set(False))
    s.catalog._ann_cache.clear()
    assert s._ann_runtime("emb", "v", "l2", holed) is None


# ---------------------------------------------------------------------------
# session plan cache: real LRU honoring plan_cache_mem_limit
# ---------------------------------------------------------------------------


def test_plan_cache_lru_eviction(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    # measure one entry, then budget for two-and-a-half of them
    s.execute("select v from t where id = 0")
    per_entry = s._plan_cache_total
    assert per_entry > 0
    limit = int(2.5 * per_entry)
    s.execute(f"alter system set plan_cache_mem_limit = {limit}")
    s.plan_cache.clear()
    s._plan_cache_bytes.clear()
    s._plan_cache_total = 0
    s.execute("select v from t where id = 1")
    s.execute("select v from t where id = 2")
    assert len(s.plan_cache) == 2
    keys = list(s.plan_cache)
    s.execute("select v from t where id = 1")  # LRU touch: 1 to front
    assert list(s.plan_cache)[-1] == keys[0]
    s.execute("select v from t where id = 3")  # evicts the oldest (id=2)
    assert keys[1] not in s.plan_cache
    assert keys[0] in s.plan_cache
    assert s._plan_cache_total <= limit
    db.close()
