"""Full-link query tracing (server/trace.py): span-tree shape for serial
and 3-node DTL queries, sampling knobs, slow-query retention, the
audit<->trace join, ASH/trace integration, and the poison-lane guarantee
that tracing never changes results."""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from test_multinode import Cluster

Q_AGG = ("select v, sum(k) as s from t where k < 90 "
         "group by v order by v")


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    s = d.session()
    s.execute("create table t (k int primary key, v int)")
    vals = ", ".join(f"({i}, {i % 7})" for i in range(100))
    s.execute(f"insert into t values {vals}")
    yield d, s
    d.close()


def _trace_rows(sess, trace_id):
    r = sess.execute(
        "select trace_id, span_id, parent_span_id, node, span_name,"
        " elapsed_s, tags from gv$trace")
    return [row for row in r.rows() if row[0] == trace_id]


def _audit_trace_id(sess, sql_prefix):
    r = sess.execute("select sql, trace_id from gv$sql_audit")
    hits = [t for q, t in r.rows() if q.startswith(sql_prefix)]
    assert hits, f"no audit row for {sql_prefix!r}"
    return hits[-1]


# ---------------------------------------------------------------------------
# serial span tree
# ---------------------------------------------------------------------------


def test_serial_span_tree_shape(db):
    _d, s = db
    s.execute(Q_AGG)
    tid = _audit_trace_id(s, "select v, sum(k)")
    assert tid, "statement did not keep a trace at sample_rate=1.0"
    spans = _trace_rows(s, tid)
    names = [r[4] for r in spans]
    assert "statement" in names and "compile" in names \
        and "execute" in names and "plan.execute" in names
    # exactly one root, and every parent edge lands on a known span
    ids = {r[1] for r in spans}
    roots = [r for r in spans if r[2] == 0]
    assert len(roots) == 1 and roots[0][4] == "statement"
    for row in spans:
        assert row[2] == 0 or row[2] in ids, f"orphan span {row}"
    # compile/execute are children of the statement root
    root_id = roots[0][1]
    by_name = {r[4]: r for r in spans}
    assert by_name["compile"][2] == root_id
    assert by_name["execute"][2] == root_id
    assert by_name["plan.execute"][2] == by_name["execute"][1]
    # plan-monitor operator breakdown rides under plan.execute
    ops = [r for r in spans if r[4].startswith("op.")]
    assert ops and all(r[2] == by_name["plan.execute"][1] for r in ops)
    # first execution of this fingerprint traced XLA
    assert "xla.compile" in names


def test_show_trace_renders_last_statement(db):
    _d, s = db
    s.execute(Q_AGG)
    r = s.execute("show trace")
    assert r.rowcount > 0
    rows = r.rows()
    assert rows[0][0] == "statement"
    # children render indented under the root
    assert any(op.startswith("  ") for op, *_ in rows[1:])
    # SHOW TRACE must not clobber the trace it displays
    again = s.execute("show trace")
    assert [x[0] for x in again.rows()] == [x[0] for x in rows]


def test_audit_join_and_compile_s(db):
    _d, s = db
    s.execute(Q_AGG)
    r = s.execute(
        "select a.sql, t.span_name from gv$sql_audit a, gv$trace t"
        " where a.trace_id = t.trace_id and t.parent_span_id = 0")
    joined = [q for q, n in r.rows() if q.startswith("select v, sum")]
    assert joined, "audit row did not join its gv$trace tree"


# ---------------------------------------------------------------------------
# sampling / retention knobs
# ---------------------------------------------------------------------------


def test_sample_rate_zero_drops_fast_queries(db):
    d, s = db
    s.execute("alter system set trace_sample_rate = 0.0")
    s.execute("alter system set trace_slow_threshold_s = 100.0")
    try:
        dropped_before = d.trace_registry.traces_dropped
        s.execute("select k from t where k = 1")
        assert _audit_trace_id(s, "select k from t where k = 1") == ""
        assert d.trace_registry.traces_dropped > dropped_before
    finally:
        s.execute("alter system set trace_sample_rate = 1.0")
        s.execute("alter system set trace_slow_threshold_s = 1.0")


def test_show_trace_empty_when_sampled_away(db):
    _d, s = db
    s.execute(Q_AGG)  # kept at rate 1.0
    assert s.execute("show trace").rowcount > 0
    s.execute("alter system set trace_sample_rate = 0.0")
    s.execute("alter system set trace_slow_threshold_s = 100.0")
    try:
        s.execute("select k from t where k = 2")  # dropped
        # SHOW TRACE must NOT fall back to the older kept tree
        assert s.execute("show trace").rowcount == 0
    finally:
        s.execute("alter system set trace_sample_rate = 1.0")
        s.execute("alter system set trace_slow_threshold_s = 1.0")


def test_slow_query_always_traced(db):
    d, s = db
    s.execute("alter system set trace_sample_rate = 0.0")
    s.execute("alter system set trace_slow_threshold_s = 0.0")  # all "slow"
    try:
        s.execute("select count(*) from t")
        tid = _audit_trace_id(s, "select count(*) from t")
        assert tid and _trace_rows(s, tid), \
            "slow statement lost its trace to the sample draw"
    finally:
        s.execute("alter system set trace_sample_rate = 1.0")
        s.execute("alter system set trace_slow_threshold_s = 1.0")


def test_trace_disabled_is_silent(db):
    d, s = db
    s.execute("alter system set enable_query_trace = false")
    try:
        kept = d.trace_registry.traces_kept
        res = s.execute(Q_AGG)
        assert res.rowcount > 0
        assert d.trace_registry.traces_kept == kept
        assert _audit_trace_id(s, "select v, sum(k)") == ""
    finally:
        s.execute("alter system set enable_query_trace = true")


# ---------------------------------------------------------------------------
# ASH / system events
# ---------------------------------------------------------------------------


def test_ash_samples_carry_trace_id(db):
    d, s = db
    # the session's ASH slot carries the live trace_id during execution;
    # sample the registered slot directly (the sampler thread races a
    # short statement, so drive sample_once by hand)
    s._ash_state.update(active=True, sql="select 1", state="executing",
                        trace_id="cafebabe")
    d.ash.sample_once()
    s._ash_state.update(active=False, trace_id="")
    r = s.execute("select session_id, trace_id from"
                  " gv$active_session_history")
    assert (s.session_id, "cafebabe") in r.rows()


def test_ash_state_tracks_statement_trace(db):
    d, s = db
    seen = {}
    orig = s._materialize_virtuals

    def spy(stmt):
        seen["trace_id"] = s._ash_state.get("trace_id")
        return orig(stmt)

    s._materialize_virtuals = spy
    s.execute("select count(*) from t")
    assert seen["trace_id"], "no live trace_id in the ASH slot"
    assert s._ash_state["trace_id"] == ""  # cleared at statement end


def test_system_event_view(db):
    d, s = db
    d.wait_events.add("unit test wait", 0.25)
    r = s.execute("select event, total_waits, time_waited_s"
                  " from gv$system_event")
    rows = {e: (w, t) for e, w, t in r.rows()}
    assert rows["unit test wait"][0] == 1
    assert rows["unit test wait"][1] == pytest.approx(0.25)


def test_ring_recent_slices_tail():
    from oceanbase_tpu.server.monitor import AuditRecord, SqlAudit

    a = SqlAudit(capacity=100)
    for i in range(150):
        a.record(AuditRecord(sql=f"q{i}", session_id=i, tenant="sys",
                             start_ts=0.0, elapsed_s=0.0, rows=0))
    tail = a.recent(10)
    assert [r.sql for r in tail] == [f"q{i}" for i in range(140, 150)]
    assert len(a.recent(1000)) == 100


# ---------------------------------------------------------------------------
# tracing must never change results (poison-lane case)
# ---------------------------------------------------------------------------


def test_tracing_never_changes_results_poisoned(poison):
    from oceanbase_tpu.catalog import Catalog
    from oceanbase_tpu.exec.plan import execute_plan, referenced_tables
    from oceanbase_tpu.server import trace as qtrace
    from oceanbase_tpu.sql.binder import Binder
    from oceanbase_tpu.sql.parser import parse_sql
    from oceanbase_tpu.vector import to_numpy

    cat = Catalog()
    rng = np.random.default_rng(3)
    n = 100
    cat.load_numpy("t", {
        "k": np.arange(n), "v": rng.integers(0, 9, n),
    }, primary_key=["k"])
    plan, _outs, _est = Binder(cat).bind_select(parse_sql(
        "select v, sum(k) as s, count(*) as c from t where k < 77"
        " group by v order by v"))
    tables = {t: cat.table_data(t).pad_to(256)
              for t in referenced_tables(plan)}
    poisoned = {t: poison.poison_pad_lanes(rel)
                for t, rel in tables.items()}
    clean = to_numpy(execute_plan(plan, tables))
    ctx = qtrace.TraceCtx("poisontest", node=0)
    with qtrace.activate(ctx):
        traced = to_numpy(execute_plan(plan, poisoned))
    ok, why = poison.results_identical(clean, traced)
    assert ok, f"tracing + poisoned pad lanes changed results: {why}"
    assert ctx.spans, "no spans collected under the activated context"


# ---------------------------------------------------------------------------
# 3-node cluster: remote halves of the tree
# ---------------------------------------------------------------------------


def test_dtl_remote_spans_parented(tmp_path):
    cl = Cluster(tmp_path, n=3)
    try:
        cl.execute(1, "create table t (k int primary key, v int)")
        vals = ", ".join(f"({i}, {i % 5})" for i in range(600))
        cl.execute(1, f"insert into t values {vals}")
        # wait for followers to apply so pushdown slices run remotely
        deadline = time.time() + 60
        while time.time() < deadline:
            counts = []
            for i in (2, 3):
                try:
                    r = cl.execute(i, "select count(*) from t",
                                   consistency="weak")
                    counts.append(int(r["arrays"][r["names"][0]][0]))
                except Exception:
                    counts.append(-1)
            if counts == [600, 600]:
                break
            time.sleep(0.3)
        cl.execute(1, "alter system set dtl_min_rows = 1")
        q = "select v, sum(k) as s from t where k < 500 group by v"
        res = cl.execute(1, q)
        assert res["node"] == 1

        audit = cl.execute(1, "select sql, trace_id from gv$sql_audit")
        tid = [t for s_, t in cl.rows(audit)
               if s_.startswith("select v, sum(k)") and t][-1]
        tr = cl.execute(
            1, "select trace_id, span_id, parent_span_id, node,"
            " span_name, tags from gv$trace")
        spans = [r for r in cl.rows(tr) if r[0] == tid]
        assert spans, "no gv$trace tree for the pushdown statement"
        ids = {r[1] for r in spans}
        by_id = {r[1]: r for r in spans}
        # remote halves present, and every remote span's parent chain
        # reaches the coordinator's tree (no orphans)
        remote = [r for r in spans if r[3] in (2, 3)]
        assert remote, "no remote spans shipped back with the replies"
        for r in remote:
            assert r[2] in ids, f"orphan remote span {r}"
        # the remote verb span hangs under the coordinator's rpc span
        rpc = {r[1]: r for r in spans if r[4] == "rpc.dtl.execute"}
        verb = [r for r in remote if r[4] == "dtl.execute"]
        assert verb and all(r[2] in rpc for r in verb)
        # and its peer tag names the node that executed it
        for r in verb:
            peer = json.loads(rpc[r[2]][5])["peer"]
            assert peer == r[3]
        # remote fragment execution appears under the verb span
        frags = [r for r in remote if r[4] == "dtl.fragment"]
        assert frags, "remote dtl.fragment span missing"
        # exchange structure on the coordinator
        names = {r[4] for r in spans if r[3] == 1}
        assert {"statement", "execute", "dtl.exchange", "dtl.slice",
                "dtl.merge"} <= names
    finally:
        cl.close()
