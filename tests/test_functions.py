"""Scalar function library tests vs SQLite/numpy oracles."""

import math
import sqlite3

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(3)
    n = 200
    a = rng.integers(-50, 50, n)
    f = rng.uniform(-5, 5, n)
    words = rng.choice(np.array(["  hello ", "World", "abcdef", "x"]), n)
    s = Session()
    s.catalog.load_numpy("t", {"a": a, "f": f, "w": words})
    conn = sqlite3.connect(":memory:")
    conn.create_function("ln", 1, math.log)
    # sign()/mod() are native only from sqlite 3.35; UDFs keep old oracles
    conn.create_function(
        "sign", 1,
        lambda x: None if x is None else (x > 0) - (x < 0))
    conn.create_function(
        "mod", 2,
        lambda x, y: None if x is None or y is None else math.fmod(x, y))
    conn.execute("create table t (a, f, w)")
    conn.executemany("insert into t values (?,?,?)",
                     list(zip(a.tolist(), f.tolist(), words.tolist())))
    return s, conn


def _both(env, sql, rel=1e-9):
    s, conn = env
    got = sorted(s.execute(sql).rows())
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert len(got) == len(want), sql
    for g, w in zip(got, want):
        for x, y in zip(g, w):
            if isinstance(x, float) or isinstance(y, float):
                assert x == pytest.approx(y, rel=rel), sql
            else:
                assert x == y, sql


def test_math_functions(env):
    _both(env, "select a, abs(a), sign(a) from t")
    _both(env, "select f, round(f, 2) from t", rel=1e-6)
    _both(env, "select a, mod(a, 7) from t")
    _both(env, "select ln(abs(a) + 1) from t")
    s, _ = env
    r = s.execute("select ceil(2.3) as c, floor(2.7) as fl, "
                  "power(2, 10) as p, sqrt(16.0) as q").rows()
    assert r == [(3, 2, 1024.0, 4.0)]


def test_string_functions(env):
    _both(env, "select w, length(w), trim(w), ltrim(w), rtrim(w), "
               "replace(w, 'l', 'L') from t")
    s, _ = env
    r = s.execute("select upper(trim(w)) as u from t where w = 'x'").rows()
    assert all(x == ("X",) for x in r)
    r = s.execute("select concat(trim(w), '!') as c from t limit 1").rows()
    assert r[0][0].endswith("!")
    r = s.execute("select left(w, 2) as l, right(w, 2) as r, "
                  "reverse(w) as v from t where w = 'World'").rows()
    assert r[0] == ("Wo", "ld", "dlroW")


def test_null_functions(env):
    s, _ = env
    s.catalog.load_numpy("nn", {"x": np.array([1, 2, 3])},
                         valids={"x": np.array([True, False, True])})
    r = s.execute("select ifnull(x, -1) as v from nn order by v").rows()
    assert r == [(-1,), (1,), (3,)]
    r = s.execute("select nullif(x, 1) as v from nn order by x").rows()
    assert r == [(None,), (None,), (3,)]
    r = s.execute("select greatest(x, 2) as g, least(x, 2) as l "
                  "from nn where x = 3").rows()
    assert r == [(3, 2)]


def test_date_functions():
    s = Session()
    from oceanbase_tpu.datatypes import SqlType, date_to_days

    days = np.array([date_to_days(x) for x in
                     ["1994-03-15", "1996-12-31", "2000-02-29"]])
    s.catalog.load_numpy("d", {"dt": days}, types={"dt": SqlType.date()})
    r = s.execute("select quarter(dt) as q, dayofyear(dt) as dy, "
                  "dayofweek(dt) as dw from d order by dt").rows()
    assert r[0] == (1, 74, 3)     # 1994-03-15 was a Tuesday (dow=3)
    assert r[1][0] == 4 and r[1][1] == 366  # 1996 is a leap year
    r = s.execute("select datediff(dt, date '1994-01-01') as dd "
                  "from d order by dt limit 1").rows()
    assert r == [(73,)]
    # add_months through non-literal date arithmetic (device path)
    r = s.execute("select add_months(dt, 12) as nx from d order by dt"
                  ).rows()
    assert r[0][0] == "1995-03-15"
    assert r[2][0] == "2001-02-28"  # leap-day clamp


def test_extended_function_batch():
    """Round-4 function-surface widening (≙ src/sql/engine/expr breadth:
    string pad/search, math, conditional, date-name functions)."""
    import numpy as np

    from oceanbase_tpu.sql import Session

    s = Session()
    s.catalog.load_numpy(
        "fx", {"k": np.arange(3),
               "s": np.array(["abc", "hello world", ""], dtype=object),
               "d": np.array([19723, 19754, 19783], dtype=np.int64)},
        primary_key=["k"])
    cases = [
        ("select lpad(s, 5, '*') from fx order by k",
         ["**abc", "hello", "*****"]),
        ("select repeat(s, 2) from fx order by k",
         ["abcabc", "hello worldhello world", ""]),
        ("select instr(s, 'l') from fx order by k", [0, 3, 0]),
        ("select substring_index(s, ' ', 1) from fx order by k",
         ["abc", "hello", ""]),
        ("select if(k = 1, upper(s), s) from fx order by k",
         ["abc", "HELLO WORLD", ""]),
        ("select isnull(s) from fx order by k", [0, 0, 0]),
        ("select sign(k - 1) from fx order by k", [-1, 0, 1]),
    ]
    for sql, exp in cases:
        got = [r[0] for r in s.execute(sql).rows()]
        assert got == exp, (sql, got, exp)
    # float math
    got = s.execute("select degrees(pi()), log(2, 8.0), "
                    "round(atan2(1.0, 1.0), 4) from fx limit 1").rows()[0]
    assert abs(got[0] - 180.0) < 1e-9 and abs(got[1] - 3.0) < 1e-9
    # date names: day 19723 = 2024-01-01, a Monday
    got = s.execute("select dayname(d), monthname(d) from fx "
                    "order by k limit 1").rows()[0]
    assert got == ("Monday", "January")
    # md5 is the real digest
    import hashlib

    got = s.execute("select md5(s) from fx order by k limit 1").rows()[0][0]
    assert got == hashlib.md5(b"abc").hexdigest()


def test_concat_ws_skips_nulls():
    """MySQL CONCAT_WS semantics: NULL values are skipped with their
    separator (unlike CONCAT's null propagation)."""
    import numpy as np

    from oceanbase_tpu.sql import Session

    s = Session()
    s.catalog.load_numpy(
        "cw", {"k": np.arange(3),
               "a": np.array(["x", "y", "z"], dtype=object),
               "b": np.array(["1", "", "3"], dtype=object)},
        valids={"b": np.array([True, False, True])},
        primary_key=["k"])
    r = s.execute("select concat_ws('-', a, b) from cw order by k")
    assert [x[0] for x in r.rows()] == ["x-1", "y", "z-3"]
