"""Scalar function library tests vs SQLite/numpy oracles."""

import math
import sqlite3

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(3)
    n = 200
    a = rng.integers(-50, 50, n)
    f = rng.uniform(-5, 5, n)
    words = rng.choice(np.array(["  hello ", "World", "abcdef", "x"]), n)
    s = Session()
    s.catalog.load_numpy("t", {"a": a, "f": f, "w": words})
    conn = sqlite3.connect(":memory:")
    conn.create_function("ln", 1, math.log)
    conn.execute("create table t (a, f, w)")
    conn.executemany("insert into t values (?,?,?)",
                     list(zip(a.tolist(), f.tolist(), words.tolist())))
    return s, conn


def _both(env, sql, rel=1e-9):
    s, conn = env
    got = sorted(s.execute(sql).rows())
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert len(got) == len(want), sql
    for g, w in zip(got, want):
        for x, y in zip(g, w):
            if isinstance(x, float) or isinstance(y, float):
                assert x == pytest.approx(y, rel=rel), sql
            else:
                assert x == y, sql


def test_math_functions(env):
    _both(env, "select a, abs(a), sign(a) from t")
    _both(env, "select f, round(f, 2) from t", rel=1e-6)
    _both(env, "select a, mod(a, 7) from t")
    _both(env, "select ln(abs(a) + 1) from t")
    s, _ = env
    r = s.execute("select ceil(2.3) as c, floor(2.7) as fl, "
                  "power(2, 10) as p, sqrt(16.0) as q").rows()
    assert r == [(3, 2, 1024.0, 4.0)]


def test_string_functions(env):
    _both(env, "select w, length(w), trim(w), ltrim(w), rtrim(w), "
               "replace(w, 'l', 'L') from t")
    s, _ = env
    r = s.execute("select upper(trim(w)) as u from t where w = 'x'").rows()
    assert all(x == ("X",) for x in r)
    r = s.execute("select concat(trim(w), '!') as c from t limit 1").rows()
    assert r[0][0].endswith("!")
    r = s.execute("select left(w, 2) as l, right(w, 2) as r, "
                  "reverse(w) as v from t where w = 'World'").rows()
    assert r[0] == ("Wo", "ld", "dlroW")


def test_null_functions(env):
    s, _ = env
    s.catalog.load_numpy("nn", {"x": np.array([1, 2, 3])},
                         valids={"x": np.array([True, False, True])})
    r = s.execute("select ifnull(x, -1) as v from nn order by v").rows()
    assert r == [(-1,), (1,), (3,)]
    r = s.execute("select nullif(x, 1) as v from nn order by x").rows()
    assert r == [(None,), (None,), (3,)]
    r = s.execute("select greatest(x, 2) as g, least(x, 2) as l "
                  "from nn where x = 3").rows()
    assert r == [(3, 2)]


def test_date_functions():
    s = Session()
    from oceanbase_tpu.datatypes import SqlType, date_to_days

    days = np.array([date_to_days(x) for x in
                     ["1994-03-15", "1996-12-31", "2000-02-29"]])
    s.catalog.load_numpy("d", {"dt": days}, types={"dt": SqlType.date()})
    r = s.execute("select quarter(dt) as q, dayofyear(dt) as dy, "
                  "dayofweek(dt) as dw from d order by dt").rows()
    assert r[0] == (1, 74, 3)     # 1994-03-15 was a Tuesday (dow=3)
    assert r[1][0] == 4 and r[1][1] == 366  # 1996 is a leap year
    r = s.execute("select datediff(dt, date '1994-01-01') as dd "
                  "from d order by dt limit 1").rows()
    assert r == [(73,)]
    # add_months through non-literal date arithmetic (device path)
    r = s.execute("select add_months(dt, 12) as nx from d order by dt"
                  ).rows()
    assert r[0][0] == "1995-03-15"
    assert r[2][0] == "2001-02-28"  # leap-day clamp
