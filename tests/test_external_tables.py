"""External (lake) tables: CSV + Parquet scanned at query time, and
Arrow interop (VERDICT r3 missing #12).

≙ src/share/external_table + src/sql/engine/connector +
src/sql/engine/basic/ob_arrow_basic.h.
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.sql import Session


def _write_csv(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(",".join(str(x) for x in r) + "\n")


def test_external_csv_table(tmp_path):
    p = tmp_path / "sales.csv"
    _write_csv(p, [(1, "north", "2024-01-05", "10.50"),
                   (2, "south", "2024-02-11", "3.25"),
                   (3, "north", "2024-03-02", "7.00")])
    s = Session()
    s.execute(f"create external table sales ("
              f"id int, region varchar(16), d date, amt decimal(10,2)) "
              f"location '{p}'")
    r = s.execute("select region, sum(amt), count(*) from sales "
                  "group by region order by region")
    assert r.rows() == [("north", 17.5, 2), ("south", 3.25, 1)]
    r = s.execute("select id from sales where d >= date '2024-02-01' "
                  "order by id")
    assert [x[0] for x in r.rows()] == [2, 3]
    # joins against regular tables work
    s.catalog.load_numpy("dim", {"region": np.array(
        ["north", "south"], dtype=object),
        "mgr": np.array(["ann", "bob"], dtype=object)})
    r = s.execute("select mgr, count(*) from sales join dim using "
                  "(region) group by mgr order by mgr")
    assert r.rows() == [("ann", 2), ("bob", 1)]
    # DROP removes it
    s.execute("drop table sales")
    assert not s.catalog.has_table("sales")


def test_external_csv_reflects_file_changes(tmp_path):
    p = tmp_path / "t.csv"
    _write_csv(p, [(1, 10)])
    s = Session()
    s.execute(f"create external table t (k int, v int) location '{p}'")
    assert s.execute("select count(*) from t").rows()[0][0] == 1
    import os
    import time

    _write_csv(p, [(1, 10), (2, 20), (3, 30)])
    os.utime(p, (time.time() + 5, time.time() + 5))
    assert s.execute("select count(*) from t").rows()[0][0] == 3


def test_external_parquet_table(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    p = str(tmp_path / "d.parquet")
    table = pa.table({
        "k": pa.array([1, 2, 3]),
        "name": pa.array(["a", "b", None]),
        "score": pa.array([1.5, 2.5, 3.5])})
    pq.write_table(table, p)
    s = Session()
    s.execute(f"create external table d ("
              f"k int, name varchar(8), score double) location '{p}'")
    r = s.execute("select k, name, score from d order by k")
    assert r.rows() == [(1, "a", 1.5), (2, "b", 2.5), (3, None, 3.5)]
    # external tables work in a Database (engine catalog) too
    db = Database(str(tmp_path / "db"))
    sdb = db.session()
    sdb.execute(f"create external table d2 (k int, name varchar(8), "
                f"score double) location '{p}'")
    assert sdb.execute("select sum(score) from d2").rows()[0][0] == 7.5
    db.close()


def test_arrow_interop_roundtrip(tmp_path):
    pa = pytest.importorskip("pyarrow")
    from oceanbase_tpu.share.external import (
        arrow_to_arrays, result_to_arrow)

    s = Session()
    t = pa.table({"k": pa.array([1, 2]),
                  "s": pa.array(["x", "y"])})
    arrays, valids, types = arrow_to_arrays(t)
    s.catalog.load_numpy("a", arrays, types=types,
                         valids=valids or None)
    res = s.execute("select k, upper(s) as u from a order by k")
    out = result_to_arrow(res)
    assert out.column("k").to_pylist() == [1, 2]
    assert out.column("u").to_pylist() == ["X", "Y"]


def test_external_table_persists_with_database(tmp_path):
    p = tmp_path / "e.csv"
    _write_csv(p, [(1, 5), (2, 6)])
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute(f"create external table e (k int, v int) location '{p}'")
    assert s.execute("select sum(v) from e").rows()[0][0] == 11
    # shadowing a base table is rejected
    s.execute("create table base (k int primary key)")
    with pytest.raises(ValueError):
        s.execute(f"create external table base (k int) location '{p}'")
    db.close()
    db2 = Database(str(tmp_path / "db"))
    s2 = db2.session()
    assert s2.execute("select count(*) from e").rows()[0][0] == 2
    s2.execute("drop table e")
    db2.close()
    db3 = Database(str(tmp_path / "db"))
    assert not db3.session().catalog.has_table("e")
    db3.close()
