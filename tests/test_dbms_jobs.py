"""DBMS job scheduler: auto stats gather, auto compaction, custom SQL
jobs, v$dbms_jobs (≙ src/observer/dbms_job + dbms_scheduler).
"""

import time

import numpy as np

from oceanbase_tpu.server import Database


def test_stats_auto_gather(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i % 7})" for i in range(500)))
    db.jobs.tick_s = 0.05
    db.jobs.schedule_fn("stats_gather", 0.1, db.jobs._stats_gather)
    db.jobs.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        td = s.catalog.table_def("t")
        if td.ndv.get("v") == 7:  # exact NDV only comes from ANALYZE
            break
        time.sleep(0.1)
    else:
        raise AssertionError("stats job never gathered exact NDV")
    r = s.execute("select job_name, runs from v$dbms_jobs "
                  "where job_name = 'stats_gather'")
    assert r.rows()[0][1] >= 1
    db.close()


def test_custom_sql_job(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table log (k int primary key auto_increment, "
              "v int)")
    db.jobs.tick_s = 0.05
    db.jobs.schedule("writer", 0.1, "insert into log (v) values (1)")
    db.jobs.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        if s.execute("select count(*) from log").rows()[0][0] >= 2:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("custom job never ran twice")
    db.jobs.cancel("writer")
    db.close()


def test_job_failure_recorded(tmp_path):
    db = Database(str(tmp_path / "db"))
    db.jobs.tick_s = 0.05
    db.jobs.schedule("bad", 0.1, "select * from missing_table")
    db.jobs.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        j = db.jobs.jobs.get("bad")
        if j and j["failures"] >= 1:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("failure never recorded")
    assert any(h["job"] == "bad" and not h["ok"]
               for h in db.jobs.history)
    db.close()
