"""Vector-search and FTS SQL surface (VERDICT r3 missing #8).

≙ src/share/vector_index (ANN access path: ORDER BY distance LIMIT k)
and src/storage/fts (MATCH ... AGAINST) — TPU-first: exact search is one
MXU matmul + top_k; IVF-Flat above 100k rows; FTS scores evaluate in the
string-dictionary domain (host LUT + device gather).
"""

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


def _vec_env(n=2000, d=16, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    s = Session()
    s.catalog.load_numpy(
        "emb", {"id": np.arange(n), "v": vecs, "tag": np.arange(n) % 5},
        primary_key=["id"])
    return s, vecs


def test_vector_type_and_distance_functions():
    s, vecs = _vec_env()
    q = vecs[7]
    qtxt = "[" + ", ".join(f"{x:.6f}" for x in q) + "]"
    r = s.execute(f"select id, l2_distance(v, '{qtxt}') as d from emb "
                  "order by d limit 3")
    rows = r.rows()
    assert rows[0][0] == 7 and rows[0][1] < 1e-3
    # verify against numpy
    dist = np.linalg.norm(vecs - q, axis=1)
    exp = np.argsort(dist, kind="stable")[:3].tolist()
    assert [r0[0] for r0 in rows] == exp


def test_vector_index_topk_exact_parity():
    s, vecs = _vec_env()
    s.execute("create vector index iv on emb (v) with (metric = 'l2')")
    q = vecs[123] + 0.01
    qtxt = "[" + ", ".join(f"{x:.6f}" for x in q) + "]"
    sql = (f"select id from emb order by l2_distance(v, '{qtxt}') "
           "limit 5")
    got = [r[0] for r in s.execute(sql).rows()]
    dist = np.linalg.norm(vecs - q, axis=1)
    exp = np.argsort(dist, kind="stable")[:5].tolist()
    assert got == exp
    # the ANN access path actually engaged (runtime cache populated)
    assert any(k[0] == "emb" for k in s.catalog._ann_cache)


def test_vector_cosine_index():
    s, vecs = _vec_env()
    s.execute("create vector index ic on emb (v) "
              "with (metric = 'cosine')")
    q = vecs[55]
    qtxt = "[" + ", ".join(f"{x:.6f}" for x in q) + "]"
    got = [r[0] for r in s.execute(
        f"select id from emb order by cosine_distance(v, '{qtxt}') "
        "limit 1").rows()]
    assert got == [55]


def test_vector_insert_through_engine(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table items (id int primary key, e vector(3))")
    s.execute("insert into items values (1, '[1, 0, 0]'), "
              "(2, '[0, 1, 0]'), (3, '[0.9, 0.1, 0]')")
    r = s.execute("select id from items "
                  "order by l2_distance(e, '[1, 0, 0]') limit 2")
    assert [x[0] for x in r.rows()] == [1, 3]
    db.close()


def test_fulltext_match_against():
    s = Session()
    docs = np.array([
        "the quick brown fox", "jumped over the lazy dog",
        "quick quick slow", "a dog and a fox", "nothing relevant here",
    ], dtype=object)
    s.catalog.load_numpy("docs", {"id": np.arange(5), "body": docs},
                         primary_key=["id"])
    s.execute("create fulltext index ft on docs (body)")
    r = s.execute("select id from docs "
                  "where match(body) against('fox') order by id")
    assert [x[0] for x in r.rows()] == [0, 3]
    # multi-term scoring ranks docs containing more terms higher
    r = s.execute("select id, match(body) against('quick fox') as s "
                  "from docs where match(body) against('quick fox') "
                  "order by s desc, id")
    rows = r.rows()
    assert rows[0][0] == 0 and rows[0][1] == 2.0
    assert {x[0] for x in rows} == {0, 2, 3}
    # boolean-mode syntax parses
    r = s.execute("select count(*) from docs where "
                  "match(body) against('dog' in boolean mode)")
    assert r.rows()[0][0] == 2


def test_vector_index_persists_across_restart(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table items (id int primary key, e vector(3))")
    s.execute("insert into items values (1, '[1, 0, 0]'), "
              "(2, '[0, 1, 0]')")
    s.execute("create vector index iv on items (e) with (metric = 'l2')")
    db.checkpoint()
    db.close()
    db2 = Database(str(tmp_path / "db"))
    s2 = db2.session()
    td = s2.catalog.table_def("items")
    assert "iv" in td.aux_indexes
    # a second identical CREATE errors (it survived the restart)
    import pytest as _pt

    with _pt.raises(ValueError):
        s2.execute("create vector index iv on items (e)")
    s2.execute("drop index iv on items")
    assert "iv" not in s2.catalog.table_def("items").aux_indexes
    db2.close()
    # the drop also persisted
    db3 = Database(str(tmp_path / "db"))
    assert "iv" not in db3.session().catalog.table_def(
        "items").aux_indexes
    db3.close()


def test_empty_vector_table_create():
    s = Session()
    import numpy as np

    # a VECTOR column on a table created without data must not crash
    s.catalog.load_numpy(
        "ev", {"id": np.zeros(1, np.int64),
               "v": np.zeros(1, np.float32)},
        types={"v": __import__("oceanbase_tpu.datatypes",
                               fromlist=["SqlType"]).SqlType.vector(3)})
    assert s.catalog.table_def("ev").column("v").dtype.precision == 3


def test_vector_index_approximate_opt_in():
    """IVF recall only engages when the index opts in WITH
    (approximate = true); a plain vector index keeps exact answers."""
    import numpy as np

    s, vecs = _vec_env(n=5000, d=8, seed=4)
    s.execute("create vector index ia on emb (v) "
              "with (metric = 'l2', approximate = true)")
    q = vecs[42]
    qtxt = "[" + ", ".join(f"{x:.6f}" for x in q) + "]"
    got = [r[0] for r in s.execute(
        f"select id from emb order by l2_distance(v, '{qtxt}') "
        "limit 5").rows()]
    # the true nearest (the query vector itself) must be found even by
    # IVF (it lands in the probed centroid's bucket)
    assert got[0] == 42
    from oceanbase_tpu.share.vector_index import IvfFlatIndex

    hit = next(v for k, v in s.catalog._ann_cache.items()
               if k[0] == "emb")
    assert isinstance(hit[1], IvfFlatIndex)
