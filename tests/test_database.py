"""Full-stack database tests: SQL -> tx -> memtable -> WAL -> recovery.

≙ mittest/simple_server (real SQL against a booted instance) at
single-node scale.
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database


def test_sql_through_storage_engine(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int, name varchar(10))")
    s.execute("insert into t values (1, 10, 'a'), (2, 20, 'b'), (3, 30, 'c')")
    r = s.execute("select sum(v) from t")
    assert r.rows() == [(60,)]
    s.execute("update t set v = v * 10 where k >= 2")
    s.execute("delete from t where k = 1")
    r = s.execute("select k, v from t order by k")
    assert r.rows() == [(2, 200), (3, 300)]
    db.close()


def test_explicit_transactions(tmp_path):
    db = Database(str(tmp_path / "db"))
    s1 = db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("insert into t values (1, 100)")

    s1.execute("begin")
    s1.execute("update t set v = 999 where k = 1")
    # own write visible inside the tx
    assert s1.execute("select v from t").rows() == [(999,)]
    # a second session still sees the committed value
    s2 = db.session()
    assert s2.execute("select v from t").rows() == [(100,)]
    s1.execute("rollback")
    assert s1.execute("select v from t").rows() == [(100,)]

    s1.execute("begin")
    s1.execute("update t set v = 555 where k = 1")
    s1.execute("commit")
    assert s2.execute("select v from t").rows() == [(555,)]
    db.close()


def test_write_conflict_between_sessions(tmp_path):
    from oceanbase_tpu.tx.errors import WriteConflict

    db = Database(str(tmp_path / "db"))
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("insert into t values (1, 1)")
    s1.execute("begin")
    s1.execute("update t set v = 2 where k = 1")
    with pytest.raises(WriteConflict):
        s2.execute("update t set v = 3 where k = 1")
    s1.execute("commit")
    s2.execute("update t set v = 3 where k = 1")
    assert s1.execute("select v from t").rows() == [(3,)]
    db.close()


def test_crash_recovery_from_wal(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20)")
    s.execute("update t set v = 99 where k = 2")
    # no checkpoint: simulate crash (WAL is the only persistence)
    db.close()

    db2 = Database(root)
    s2 = db2.session()
    r = s2.execute("select k, v from t order by k")
    assert r.rows() == [(1, 10), (2, 99)]

    # checkpoint, more writes, crash again: mixed segment+wal recovery
    db2.checkpoint()
    s2.execute("insert into t values (3, 30)")
    db2.close()
    db3 = Database(root)
    r = db3.session().execute("select k, v from t order by k")
    assert r.rows() == [(1, 10), (2, 99), (3, 30)]
    db3.close()


def test_keyless_table_dml(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table h (a int, b int)")
    s.execute("insert into h values (1, 1), (1, 2), (2, 3)")
    s.execute("delete from h where b = 2")
    r = s.execute("select a, b from h order by b")
    assert r.rows() == [(1, 1), (2, 3)]
    s.execute("update h set b = b + 10 where a = 1")
    r = s.execute("select a, b from h order by b")
    assert r.rows() == [(2, 3), (1, 11)]
    db.close()


def test_freeze_flush_compact_visibility(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    db.checkpoint()  # flush to L0
    s.execute("update t set v = 20 where k = 2")
    db.engine.freeze_and_flush("t", snapshot=db.tx.gts.current())
    db.engine.minor_compact("t")
    db.engine.major_compact("t")
    r = s.execute("select k, v from t order by k")
    assert r.rows() == [(1, 1), (2, 20)]
    db.close()
