"""TRUNCATE, REPLACE INTO, SHOW CREATE TABLE."""

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import DuplicateKey


def test_truncate_and_recovery(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    db.checkpoint()
    s.execute("insert into t values (3, 3)")
    s.execute("truncate table t")
    assert s.execute("select count(*) from t").rows() == [(0,)]
    s.execute("insert into t values (9, 9)")
    assert s.execute("select k from t").rows() == [(9,)]
    # crash: WAL replay must respect the truncate barrier
    db.close()
    db2 = Database(root)
    assert db2.session().execute("select k from t").rows() == [(9,)]
    db2.close()


def test_replace_into(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10)")
    with pytest.raises(DuplicateKey):
        s.execute("insert into t values (1, 20)")
    s.execute("replace into t values (1, 20), (2, 22)")
    assert s.execute("select k, v from t order by k").rows() == \
        [(1, 20), (2, 22)]
    # replace over a flushed row
    db.checkpoint()
    s.execute("replace into t values (1, 30)")
    assert s.execute("select v from t where k = 1").rows() == [(30,)]
    db.close()


def test_replace_sees_own_statement_and_tx_writes(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    # duplicate key WITHIN one REPLACE statement: last row wins
    s.execute("replace into t values (1, 1), (1, 2)")
    assert s.execute("select v from t where k = 1").rows() == [(2,)]
    # insert-then-replace inside one explicit transaction
    s.execute("begin")
    s.execute("insert into t values (7, 70)")
    s.execute("replace into t values (7, 71)")
    s.execute("commit")
    assert s.execute("select v from t where k = 7").rows() == [(71,)]
    db.close()


def test_truncate_with_open_tx_crash_safe(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("begin")
    s.execute("insert into t values (5, 5)")
    s.execute("truncate table t")  # implicit commit, then truncate
    assert s.execute("select count(*) from t").rows() == [(0,)]
    db.close()
    # crash recovery must agree with the live system
    db2 = Database(root)
    assert db2.session().execute("select count(*) from t").rows() == [(0,)]
    db2.close()


def test_truncate_resets_auto_increment(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key auto_increment, "
              "v int)")
    s.execute("insert into t (v) values (1), (2), (3)")
    s.execute("truncate table t")
    s.execute("insert into t (v) values (9)")
    assert s.execute("select id from t").rows() == [(1,)]
    db.close()


def test_create_table_as_select(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table src (k int primary key, v decimal(10,2), "
              "name varchar(20))")
    s.execute("insert into src values (1, 1.50, 'a'), (2, 2.25, 'b'), "
              "(3, 3.00, null)")
    r = s.execute("create table dst as select k, v * 2 as v2, name "
                  "from src where k >= 2")
    assert r.rowcount == 2
    rows = s.execute("select k, v2, name from dst order by k").rows()
    assert rows == [(2, 4.5, "b"), (3, 6.0, None)]
    # CTAS over aggregates
    s.execute("create table agg as select name, count(*) as n from src "
              "group by name")
    assert s.execute("select sum(n) from agg").rows() == [(3,)]
    db.close()


def test_show_create_table(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (id int primary key auto_increment, "
              "v decimal(10,2) not null, name varchar(20)) "
              "partition by range (id) ("
              "partition p0 values less than (100), "
              "partition p1 values less than maxvalue)")
    r = s.execute("show create table t")
    text = r.rows()[0][1]
    assert "AUTO_INCREMENT" in text
    assert "PRIMARY KEY (id)" in text
    assert "NOT NULL" in text
    assert "PARTITION BY RANGE (id)" in text and "MAXVALUE" in text
    db.close()
