"""Range-partitioned tables: routing, scans, compaction, recovery.

≙ partitioned tables over multiple tablets (tablet/LS partitioning).
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.storage.partition import PartitionedTablet


@pytest.fixture()
def pdb(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute(
        "create table t (k int primary key, v int) partition by range (k) ("
        "partition p0 values less than (100), "
        "partition p1 values less than (200), "
        "partition p2 values less than maxvalue)")
    yield db, s
    db.close()


def test_partition_routing_and_scan(pdb):
    db, s = pdb
    tablet = db.engine.tables["t"].tablet
    assert isinstance(tablet, PartitionedTablet)
    assert len(tablet.partitions) == 3
    s.execute("insert into t values (50, 1), (150, 2), (250, 3), (99, 4)")
    # rows landed in the right partitions
    counts = [len(p.active) for p in tablet.partitions]
    assert counts == [2, 1, 1]
    # scans see all partitions
    r = s.execute("select k, v from t order by k")
    assert r.rows() == [(50, 1), (99, 4), (150, 2), (250, 3)]
    # DML routes correctly
    s.execute("update t set v = 20 where k = 150")
    s.execute("delete from t where k = 50")
    r = s.execute("select k, v from t order by k")
    assert r.rows() == [(99, 4), (150, 20), (250, 3)]


def test_partitioned_flush_compact_recovery(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute(
        "create table t (k int primary key, v int) partition by range (k) ("
        "partition p0 values less than (10), "
        "partition p1 values less than maxvalue)")
    rows = ", ".join(f"({i}, {i})" for i in range(20))
    s.execute(f"insert into t values {rows}")
    db.checkpoint()  # flushes both partitions
    tablet = db.engine.tables["t"].tablet
    assert all(p.segments for p in tablet.partitions)
    s.execute("insert into t values (100, 100)")
    db.checkpoint()
    db.engine.major_compact("t")
    r = s.execute("select count(*), sum(v) from t").rows()
    assert r == [(21, sum(range(20)) + 100)]
    db.close()

    # restart: partition layout + segments reload per partition
    db2 = Database(root)
    t2 = db2.engine.tables["t"].tablet
    assert isinstance(t2, PartitionedTablet)
    assert [len(p.segments) for p in t2.partitions].count(0) == 0
    r = db2.session().execute("select count(*), sum(v) from t").rows()
    assert r == [(21, sum(range(20)) + 100)]
    db2.close()


def test_kv_and_streaming_over_partitions(pdb):
    db, s = pdb
    s.execute("insert into t values (50, 1), (150, 2), (250, 3)")
    kv = db.tenant().kv("t")
    # point lookups must see memtables of EVERY partition
    assert kv.get(150) == {"k": 150, "v": 2}
    assert kv.get(250) == {"k": 250, "v": 3}
    assert kv.get(999) is None
    # streamed scan covers all partitions' memtables + segments
    db.checkpoint()
    s.execute("insert into t values (160, 4)")  # memtable, partition 1
    from oceanbase_tpu.exec.granule import (
        execute_streamed,
        segment_chunk_provider,
    )
    from oceanbase_tpu.exec.ops import AggSpec
    from oceanbase_tpu.exec.plan import ScalarAgg, TableScan
    from oceanbase_tpu.expr import ir
    from oceanbase_tpu.vector import to_numpy

    plan = ScalarAgg(TableScan("t", rename={"k": "k", "v": "v"}),
                     [AggSpec("s", "sum", ir.col("v")),
                      AggSpec("c", "count_star")])
    tablet = db.engine.tables["t"].tablet
    out = to_numpy(execute_streamed(
        plan, segment_chunk_provider(tablet, db.tx.gts.current()),
        chunk_rows=2))
    assert out["c"][0] == 4 and out["s"][0] == 10


def test_partitioned_bulk_load(pdb):
    db, s = pdb
    db.catalog.load_numpy("u", {"k": np.arange(300),
                                "v": np.arange(300) * 2},
                          primary_key=["k"])
    # non-partitioned load path untouched
    assert db.session().execute("select count(*) from u").rows() == [(300,)]
    # partitioned direct load routes by range
    eng = db.engine
    eng.bulk_load("t", {"k": np.arange(0, 300, 10),
                        "v": np.arange(30)})
    tablet = eng.tables["t"].tablet
    per_part = [sum(sg.n_rows for sg in p.segments)
                for p in tablet.partitions]
    assert per_part == [10, 10, 10]
    assert db.session().execute("select count(*) from t").rows() == [(30,)]
