"""Top-N fusion + plan cache tests."""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.sql import Session


def test_topn_matches_numpy_oracle(rng):
    n = 20000
    a = rng.integers(-1000, 1000, n)
    f = rng.random(n)
    sv = rng.choice(np.array(["aa", "bb", "cc", "dd"]), n)
    s = Session()
    s.catalog.load_numpy("t", {"a": a, "f": f, "s": sv})
    got = [r[0] for r in s.execute(
        "select a from t order by a limit 7").rows()]
    assert got == sorted(a.tolist())[:7]
    got = [r[0] for r in s.execute(
        "select a from t order by a desc limit 7").rows()]
    assert got == sorted(a.tolist(), reverse=True)[:7]
    got = [r[0] for r in s.execute(
        "select f from t order by f desc limit 5").rows()]
    np.testing.assert_allclose(got, np.sort(f)[::-1][:5])
    got = [r[0] for r in s.execute(
        "select s from t order by s limit 4").rows()]
    assert got == sorted(sv.tolist())[:4]
    # filtered top-N: dead rows must never crowd out live ones
    got = s.execute("select a from t where a > 900 order by a desc limit 10"
                    ).rows()
    want = sorted([x for x in a.tolist() if x > 900], reverse=True)[:10]
    assert [r[0] for r in got] == want


def test_topn_null_desc_with_filter():
    # live NULLs under DESC must outrank dead (filtered) rows
    s = Session()
    s.catalog.load_numpy(
        "t", {"x": np.array([10, 500, 0, 0]),
              "flt": np.array([1, 0, 1, 1])},
        valids={"x": np.array([True, True, False, False])})
    r = s.execute("select x from t where flt = 1 order by x desc limit 3"
                  ).rows()
    assert r == [(10,), (None,), (None,)]


def test_topn_with_nulls():
    s = Session()
    s.catalog.load_numpy("t", {"x": np.array([5, 1, 9, 3])},
                         valids={"x": np.array([True, False, True, True])})
    r = s.execute("select x from t order by x limit 2").rows()
    assert r == [(None,), (3,)]  # nulls first under ASC
    r = s.execute("select x from t order by x desc limit 2").rows()
    assert r == [(9,), (5,)]


def test_plan_cache_hit_and_invalidation(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20)")
    q = "select sum(v) from t where k >= ?"
    assert s.execute(q, params=[1]).rows() == [(30,)]
    n_entries = len(s.plan_cache)
    assert n_entries >= 1
    # same text+params hits the cache (no growth)
    assert s.execute(q, params=[1]).rows() == [(30,)]
    assert len(s.plan_cache) == n_entries
    # data changes flow through a cached plan
    s.execute("insert into t values (3, 5)")
    assert s.execute(q, params=[1]).rows() == [(35,)]
    # schema change invalidates (new key -> rebind)
    s.execute("create table u (z int)")
    assert s.execute(q, params=[1]).rows() == [(35,)]
    db.close()
