"""Roofline calibration & device-time profiling plane (the PR's
coverage satellite): calibration determinism, predicted_s monotonicity,
knob on/off behavior, poisoned-lane parity for every probe kernel, and
gv$cost_units / gv$device_profile row shapes + the persistence
(checksum) contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oceanbase_tpu.server import calibrate
from oceanbase_tpu.storage.integrity import CorruptionError


@pytest.fixture()
def db(tmp_path):
    from oceanbase_tpu.server import Database

    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


# ---------------------------------------------------------------------------
# calibration probe
# ---------------------------------------------------------------------------


def test_probe_produces_constants():
    u = calibrate.run_probe("boot")
    assert u.backend == jax.default_backend()
    assert u.device_count >= 1
    assert u.peak_flops_s > 0.0
    assert u.peak_bytes_s > 0.0
    assert u.launch_overhead_s > 0.0
    assert u.calibrated_ts > 0.0
    ok = [m for m in u.measurements if "error" not in m]
    kernels = {m["kernel"] for m in ok}
    assert kernels == {"stream_copy", "masked_reduce",
                       "segment_groupby", "searchsorted",
                       "small_matmul"}
    for m in ok:
        assert m["device_s"] > 0.0
        assert m["flops"] >= 0.0 and m["bytes"] >= 0.0


def test_probe_determinism_two_runs_agree():
    """Two probe runs on the same backend must agree on the machine
    constants within a noise tolerance (min-of-repeats on a shared CI
    host: a generous factor, but a REAL bound — a broken measurement is
    off by orders of magnitude, not by 4x)."""
    a = calibrate.run_probe("boot")
    b = calibrate.run_probe("boot")
    for attr in ("peak_flops_s", "peak_bytes_s"):
        x, y = getattr(a, attr), getattr(b, attr)
        ratio = max(x, y) / max(min(x, y), 1e-30)
        assert ratio < 4.0, f"{attr}: {x} vs {y} (ratio {ratio:.1f})"


def test_predicted_s_monotone_in_rows():
    """The roofline prediction must grow (weakly) with input size —
    the property the CBO's cost comparisons rest on."""
    u = calibrate.run_probe("boot")
    preds = []
    for n in (1_000, 10_000, 100_000, 1_000_000, 10_000_000):
        flops = 2.0 * n
        nbytes = 8.0 * n
        preds.append(calibrate.predict_seconds(u, flops, nbytes))
    assert all(b >= a for a, b in zip(preds, preds[1:])), preds
    # and monotone in launch count
    p1 = calibrate.predict_seconds(u, 1e6, 1e6, calls=1)
    p4 = calibrate.predict_seconds(u, 1e6, 1e6, calls=4)
    assert p4 >= p1


def test_time_q_error():
    assert calibrate.time_q_error(0.0, 1.0) == 0.0
    assert calibrate.time_q_error(1.0, 0.0) == 0.0
    assert calibrate.time_q_error(2.0, 1.0) == pytest.approx(2.0)
    assert calibrate.time_q_error(1.0, 2.0) == pytest.approx(2.0)
    assert calibrate.time_q_error(3.0, 3.0) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# poisoned-lane parity for every probe kernel (Static-shape policy)
# ---------------------------------------------------------------------------


def _poison_floats(x, mask):
    return jnp.where(mask, x, jnp.nan)


def _poison_ints(x, mask):
    from oceanbase_tpu.analysis.poison import INT_POISON

    return jnp.where(mask, x, jnp.asarray(INT_POISON, x.dtype))


def _bit_identical(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.shape == b.shape
    assert a.tobytes() == b.tobytes()


@pytest.mark.parametrize("case_ix", range(5))
def test_probe_kernels_poison_parity(case_ix):
    """Every calibration kernel must treat masked-dead lanes as if they
    did not exist: NaN/sentinel garbage in the dead lanes may not move
    a single output bit."""
    cases = calibrate.probe_cases("boot")
    name, _rows, build, _f, _b = cases[case_ix]
    fn, args = build()
    mask = args[-1]
    clean = jax.jit(fn)(*args)
    poisoned_args = []
    for a in args[:-1]:
        if jnp.issubdtype(a.dtype, jnp.floating):
            if a.ndim == 2:  # matmul lhs: poison dead rows
                poisoned_args.append(
                    jnp.where(mask[:, None], a, jnp.nan))
            else:
                poisoned_args.append(_poison_floats(a, mask))
        elif name == "searchsorted" and a is args[0]:
            # the sorted KEY column is not masked input — leave it
            poisoned_args.append(a)
        else:
            poisoned_args.append(_poison_ints(a, mask))
    out = jax.jit(fn)(*poisoned_args, mask)
    _bit_identical(clean, out)


# ---------------------------------------------------------------------------
# persistence: checksummed on disk (PR 9 contract)
# ---------------------------------------------------------------------------


def test_units_roundtrip_and_corruption(tmp_path):
    root = str(tmp_path)
    u = calibrate.run_probe("boot")
    calibrate.save_units(root, u)
    loaded = calibrate.load_units(root)
    assert loaded is not None
    assert loaded.peak_flops_s == pytest.approx(u.peak_flops_s)
    assert loaded.backend == u.backend
    # flip bytes: load must raise CorruptionError, never serve garbage
    path = calibrate._units_path(root)
    body = open(path).read().replace(
        '"peak_flops_s"', '"peak_flops_sX"', 1)
    with open(path, "w") as fh:
        fh.write(body)
    with pytest.raises(CorruptionError):
        calibrate.load_units(root)
    # the boot path quarantines + re-probes instead of failing
    units = calibrate.ensure_units(root, force=True)
    assert units.peak_flops_s > 0
    assert calibrate.load_units(root).backend == units.backend


def test_missing_units_file_is_none(tmp_path):
    assert calibrate.load_units(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# the live plane: knobs, gv$ row shapes, PROFILE
# ---------------------------------------------------------------------------


def _load(sess, n=300):
    sess.execute("create table pt (id int primary key, v int)")
    sess.execute("insert into pt values "
                 + ",".join(f"({i},{i % 5})" for i in range(n)))


def test_device_split_recorded(db):
    s = db.session()
    _load(s)
    for _ in range(2):
        s.execute("select v, count(*) from pt group by v")
    rows = s.execute(
        "select executions, device_executions, achieved_gflops,"
        " achieved_gbps, device_s_total from gv$plan_cache"
        " order by executions desc limit 1").rows()
    execs, dev_execs, gflops, gbps, dev_s = rows[0]
    assert execs >= 2 and dev_execs >= 2
    assert dev_s > 0.0
    assert gflops > 0.0, "achieved_gflops must be nonzero on CPU"
    assert gbps > 0.0
    # gv$sql_audit carries the split
    au = s.execute(
        "select host_s, device_s from gv$sql_audit"
        " where sql like 'select v%' order by start_ts desc limit 1"
    ).rows()
    assert au[0][0] > 0.0 and au[0][1] > 0.0


def test_enable_profiling_off_stops_split(db):
    s = db.session()
    _load(s)
    s.execute("alter system set enable_profiling = false")
    try:
        s.execute("select count(*) from pt")
        au = s.execute(
            "select host_s, device_s from gv$sql_audit"
            " where sql like 'select count%' order by start_ts desc"
            " limit 1").rows()
        assert au[0][1] == 0.0  # no device half without the knob
        assert au[0][0] > 0.0   # host half still measured
    finally:
        s.execute("alter system set enable_profiling = true")


def test_monitor_carries_time_qerror(db):
    s = db.session()
    _load(s)
    assert db.cost_units is not None  # boot calibration ran
    s.execute("select v, count(*) from pt group by v")
    pm = s.execute(
        "select device_s, pred_s, time_q_error from"
        " gv$sql_plan_monitor order by ts desc limit 1").rows()
    dev, pred, tq = pm[0]
    assert dev > 0.0 and pred > 0.0 and tq >= 1.0
    # aggregated per-operator-type calibration table
    tc = s.execute(
        "select operator, executions, correction, time_q_p50"
        " from gv$time_calibration").rows()
    assert len(tc) >= 1
    for _op, n, corr, p50 in tc:
        assert n >= 1 and corr > 0.0 and p50 >= 1.0


def test_explain_analyze_roofline_line(db):
    s = db.session()
    _load(s)
    r = s.execute("explain analyze select v, count(*) from pt group by v")
    text = r.plan_text
    assert "roofline: [pred=" in text
    assert "dev=" in text and "tq=" in text


def test_cost_units_rows(db):
    s = db.session()
    rows = s.execute(
        "select kind, name, value, unit from gv$cost_units").rows()
    kinds = {r[0] for r in rows}
    assert kinds == {"constant", "probe"}
    consts = {r[1]: r[2] for r in rows if r[0] == "constant"}
    assert set(consts) == {"peak_flops_s", "peak_bytes_s",
                           "eff_bytes_s", "launch_overhead_s",
                           "rpc_s_per_byte"}
    assert consts["peak_flops_s"] > 0
    assert 0 < consts["eff_bytes_s"] <= consts["peak_bytes_s"]
    probes = {r[1] for r in rows if r[0] == "probe"}
    assert "stream_copy" in probes and "small_matmul" in probes


def test_alter_system_calibrate(db):
    s = db.session()
    before = db.cost_units.calibrated_ts
    r = s.execute("alter system calibrate")
    got = dict(r.rows())
    assert got["backend"] == jax.default_backend()
    assert float(got["peak_gflops"]) > 0
    assert db.cost_units.calibrated_ts >= before
    assert db.cost_units.preset == "full"
    # calibrate with the knob off is a typed error
    s.execute("alter system set enable_calibration = false")
    try:
        with pytest.raises(ValueError):
            s.execute("alter system calibrate")
    finally:
        s.execute("alter system set enable_calibration = true")


def test_profile_statement_and_device_profile_rows(db):
    s = db.session()
    _load(s)
    s.execute("select sum(v) from pt")  # warm (compile outside trace)
    r = s.execute("profile select sum(v) from pt")
    assert r.rows() == [(600,)]
    # joined by trace_id to the audit row of the PROFILE statement
    tid = s.execute(
        "select trace_id from gv$sql_audit where sql like 'profile%'"
        " order by start_ts desc limit 1").rows()[0][0]
    assert tid
    dp = s.execute(
        f"select kernel, kind, occurrences, total_s from"
        f" gv$device_profile where trace_id = '{tid}'").rows()
    assert len(dp) >= 1, "PROFILE must yield >=1 gv$device_profile row"
    for _k, kind, occ, total in dp:
        assert kind in ("kernel", "runtime")
        assert occ >= 1 and total >= 0.0
    # SHOW PROFILE renders the same capture
    sp = s.execute("show profile").rows()
    assert len(sp) >= 1


def test_show_profile_without_capture(db):
    s = db.session()
    rows = s.execute("show profile").rows()
    assert len(rows) == 1
    assert "no PROFILE captured" in rows[0][1]


def test_profile_knob_off_runs_plain(db):
    s = db.session()
    _load(s)
    s.execute("alter system set enable_profiling = false")
    try:
        r = s.execute("profile select count(*) from pt")
        assert r.rows() == [(300,)]
        assert s.execute(
            "select count(*) from gv$device_profile").rows() == [(0,)]
    finally:
        s.execute("alter system set enable_profiling = true")


def test_profile_propagates_statement_errors(db):
    s = db.session()
    with pytest.raises(Exception):
        s.execute("profile select * from no_such_table_xyz")


def test_gv_backend_row(db):
    s = db.session()
    rows = s.execute(
        "select platform, device_count, cpu_fallback,"
        " calibration_age_s from gv$backend").rows()
    assert len(rows) == 1
    platform, count, _fb, age = rows[0]
    assert platform == jax.default_backend()
    assert count >= 1
    assert age >= 0.0  # boot calibration ran in this process


def test_calibration_disabled_boot(tmp_path):
    """enable_calibration=false at boot: no units adopted, predictions
    degrade to zeros, everything still runs."""
    from oceanbase_tpu.server import Database

    root = str(tmp_path / "nocal")
    import json
    import os

    os.makedirs(root)
    with open(os.path.join(root, "config.json"), "w") as fh:
        json.dump({"enable_calibration": False}, fh)
    d = Database(root)
    try:
        assert d.cost_units is None
        s = d.session()
        _load(s, n=50)
        assert s.execute("select count(*) from pt").rows() == [(50,)]
        assert not os.path.exists(os.path.join(root, "cost_units.json"))
    finally:
        d.close()


def test_calibration_disabled_predicts_nothing(tmp_path):
    """A database booted with enable_calibration=false must emit ZERO
    predictions even when ANOTHER database already calibrated the
    process cache — per-Database units, not the global cache."""
    import json
    import os

    from oceanbase_tpu.server import Database

    calibrate.ensure_units(None)  # process cache deliberately warm
    root = str(tmp_path / "nocal2")
    os.makedirs(root)
    with open(os.path.join(root, "config.json"), "w") as fh:
        json.dump({"enable_calibration": False}, fh)
    d = Database(root)
    try:
        s = d.session()
        _load(s, n=100)
        s.execute("select v, count(*) from pt group by v")
        pm = s.execute(
            "select pred_s, time_q_error from gv$sql_plan_monitor"
            " order by ts desc limit 1").rows()
        assert pm[0] == (0.0, 0.0)
        assert s.execute("select count(*) from gv$time_calibration"
                         ).rows() == [(0,)]
    finally:
        d.close()


def test_profile_with_tracing_off_still_joinable(db):
    s = db.session()
    _load(s, n=100)
    s.execute("select sum(v) from pt")  # warm
    s.execute("alter system set enable_query_trace = false")
    try:
        r = s.execute("profile select sum(v) from pt")
        assert r.rowcount == 1
        sp = s.execute("show profile").rows()
        # a successful capture, not the 'no PROFILE captured' note
        assert sp and sp[0][2] != "note"
        tids = set(s.execute(
            "select trace_id from gv$device_profile").rows())
        assert len(tids) >= 1 and ("",) not in tids
    finally:
        s.execute("alter system set enable_query_trace = true")


def test_units_persisted_at_boot(db):
    import os

    assert os.path.exists(os.path.join(db.root, "cost_units.json"))
    loaded = calibrate.load_units(db.root)
    assert loaded is not None and loaded.peak_flops_s > 0


def test_exec_times_accumulator():
    from oceanbase_tpu.exec import plan as qplan

    qplan.reset_exec_times()
    qplan.add_exec_times(host_s=0.5, device_s=0.25, flops=10.0,
                         bytes=20.0, calls=2)
    t = qplan.exec_times()
    assert (t.host_s, t.device_s, t.flops, t.bytes, t.calls) == \
        (0.5, 0.25, 10.0, 20.0, 2)
    qplan.reset_exec_times()
    t = qplan.exec_times()
    assert t.calls == 0 and t.device_s == 0.0


def test_trace_parse_dir_empty(tmp_path):
    from oceanbase_tpu.server import profiler

    assert profiler.parse_trace_dir(str(tmp_path)) == []
