"""SQL-level spill tier: over-budget queries complete through the disk
tier with spill counters visible (VERDICT r2 item #2).

≙ src/sql/engine/ob_tenant_sql_memory_manager.h (spill decision) +
ob_sort_vec_op.h / ob_hash_join_vec_op.h:413 (spilling operators).
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database

N = 40_000  # rows; budget drops to 4096 so these are ~10x over budget


def _mk(tmp_path, budget=4096):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute(f"alter system set sql_work_area_rows = {budget}")
    return db, s


def _load_big(s, name="t", n=N, seed=1):
    rng = np.random.default_rng(seed)
    k = np.arange(n)
    v = rng.integers(0, 1_000_000, n)
    g = rng.integers(0, n // 2, n)  # high NDV for group-by
    s.execute(f"create table {name} "
              f"(k int primary key, v int, g int)")
    rows = ", ".join(f"({k[i]}, {v[i]}, {g[i]})" for i in range(n))
    s.execute(f"insert into {name} values {rows}")
    return k, v, g


def test_order_by_over_budget_spills_and_completes(tmp_path):
    db, s = _mk(tmp_path)
    _k, v, _g = _load_big(s)
    r = s.execute("select k, v from t order by v, k limit 20")
    got = r.rows()
    order = np.lexsort((np.arange(N), v))
    exp = [(int(order[i]), int(v[order[i]])) for i in range(20)]
    assert got == exp
    st = s._last_spill
    assert st is not None and st.kind.startswith("sort")
    assert st.runs > 0 and st.bytes > 0 and st.spilled_rows > 0
    db.close()


def test_group_by_over_budget_spills_with_parity(tmp_path):
    db, s = _mk(tmp_path)
    _k, v, g = _load_big(s)
    r = s.execute("select g, count(*) as c, sum(v) as sv, min(v) as mn "
                  "from t group by g order by g")
    got = r.rows()
    exp = {}
    for gi, vi in zip(g.tolist(), v.tolist()):
        c, sv, mn = exp.get(gi, (0, 0, None))
        exp[gi] = (c + 1, sv + vi, vi if mn is None else min(mn, vi))
    assert len(got) == len(exp)
    for gi, c, sv, mn in got:
        ec, esv, emn = exp[gi]
        assert (c, sv, mn) == (ec, esv, emn)
    assert s._last_spill is not None
    assert "groupby" in s._last_spill.kind
    db.close()


def test_scalar_agg_over_budget_streams(tmp_path):
    db, s = _mk(tmp_path)
    _k, v, _g = _load_big(s)
    r = s.execute("select count(*), sum(v), avg(v), max(v) from t")
    cnt, sv, av, mx = r.rows()[0]
    assert cnt == N and sv == int(v.sum()) and mx == int(v.max())
    assert abs(av - v.mean()) < 1.0
    assert s._last_spill is not None and "scalar" in s._last_spill.kind
    db.close()


def test_join_big_probe_small_build_spills(tmp_path):
    db, s = _mk(tmp_path)
    _k, v, g = _load_big(s)
    s.execute("create table d (g int primary key, name varchar(16))")
    rows = ", ".join(f"({i}, 'n{i % 7}')" for i in range(0, N // 2, 16))
    s.execute(f"insert into d values {rows}")
    r = s.execute("select d.name as name, count(*) as c, sum(t.v) as sv "
                  "from t join d on t.g = d.g "
                  "group by d.name order by name")
    got = r.rows()
    dset = {i: f"n{i % 7}" for i in range(0, N // 2, 16)}
    exp = {}
    for gi, vi in zip(g.tolist(), v.tolist()):
        nm = dset.get(gi)
        if nm is None:
            continue
        c, sv = exp.get(nm, (0, 0))
        exp[nm] = (c + 1, sv + vi)
    assert got == [(nm, exp[nm][0], exp[nm][1]) for nm in sorted(exp)]
    st = s._last_spill
    assert st is not None and "join" in st.kind
    db.close()


def test_join_both_sides_over_budget_copartitions(tmp_path):
    db, s = _mk(tmp_path)
    n = 20_000
    rng = np.random.default_rng(5)
    a_v = rng.integers(0, 100, n)
    s.execute("create table a (k int primary key, j int, v int)")
    s.execute("insert into a values " + ", ".join(
        f"({i}, {i % (n // 4)}, {a_v[i]})" for i in range(n)))
    s.execute("create table b (k int primary key, j int, w int)")
    s.execute("insert into b values " + ", ".join(
        f"({i}, {i % (n // 4)}, {i % 13})" for i in range(n)))
    r = s.execute("select count(*) as c, sum(a.v + b.w) as sv "
                  "from a join b on a.j = b.j")
    cnt, sv = r.rows()[0]
    # each j value appears 4x in each table -> 16 pairs per j
    assert cnt == 16 * (n // 4)
    exp = 0
    for i in range(n):
        for m in range(i % (n // 4), n, n // 4):
            exp += int(a_v[i]) + (m % 13)
    assert sv == exp
    st = s._last_spill
    assert st is not None and st.spilled_rows > 0
    db.close()


def test_spill_counters_in_vsql_workarea_and_explain(tmp_path):
    db, s = _mk(tmp_path)
    _load_big(s)
    s.execute("select k from t order by v limit 5")
    r = s.execute("select operation, spill_runs, spill_bytes "
                  "from v$sql_workarea")
    rows = r.rows()
    assert rows and any(op.startswith("sort") and runs > 0 and b > 0
                        for op, runs, b in rows)
    r = s.execute("explain analyze select k from t order by v limit 5")
    assert "spill:" in r.plan_text
    db.close()


def test_under_budget_queries_do_not_spill(tmp_path):
    db, s = _mk(tmp_path, budget=1 << 22)
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i * 3})" for i in range(500)))
    r = s.execute("select k from t order by v desc limit 3")
    assert r.rows() == [(499,), (498,), (497,)]
    assert s._last_spill is None
    db.close()


def test_spill_disabled_falls_back(tmp_path):
    db, s = _mk(tmp_path, budget=4096)
    s.execute("alter system set enable_sql_spill = false")
    _load_big(s, n=8192)
    # in-memory path must still answer (8k rows fit on device fine)
    r = s.execute("select count(*) from t")
    assert r.rows()[0][0] == 8192
    assert s._last_spill is None
    db.close()


def test_distinct_over_budget_spills(tmp_path):
    """SELECT DISTINCT streams through the spill group-by; COUNT(DISTINCT)
    (non-splittable) falls back to the in-memory engine instead of
    leaking NotImplementedError (VERDICT r3 #7 tail)."""
    db, s = _mk(tmp_path)
    _k, _v, g = _load_big(s)
    r = s.execute("select distinct g from t order by g")
    assert len(r.rows()) == len(set(g.tolist()))
    assert s._last_spill is not None and "groupby" in s._last_spill.kind
    r = s.execute("select count(distinct g) from t")
    assert r.rows()[0][0] == len(set(g.tolist()))
    db.close()
