"""Bounded KV cache: LRU eviction, byte budget, stats, v$kvcache
(≙ src/share/cache/ob_kv_storecache.h ObKVGlobalCache)."""

import numpy as np

from oceanbase_tpu.server import Database
from oceanbase_tpu.share.kvcache import KvCache


def test_lru_eviction_and_stats():
    c = KvCache(limit_bytes=100, name="t")
    c.put("a", "va", nbytes=40)
    c.put("b", "vb", nbytes=40)
    assert c.get("a") == "va"          # touch a -> b is LRU
    c.put("c", "vc", nbytes=40)        # evicts b
    assert c.get("b") is None
    assert c.get("a") == "va" and c.get("c") == "vc"
    st = c.stats()
    assert st["evictions"] == 1 and st["entries"] == 2
    assert st["bytes"] <= 100
    # oversized values are refused, not cached
    c.put("huge", "x", nbytes=1000)
    assert c.get("huge") is None
    c.resize(40)
    assert c.stats()["entries"] == 1


def test_catalog_cache_behind_kvcache(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i})" for i in range(1000)))
    s.execute("select sum(v) from t")
    s.execute("select sum(v) from t")   # second read hits the cache
    r = s.execute("select cache_name, hits, bytes from v$kvcache "
                  "where tenant = 'sys'")
    rows = r.rows()
    assert rows and rows[0][1] >= 1 and rows[0][2] > 0
    # resizing to nothing evicts (ALTER SYSTEM hot-reload path)
    s.execute("alter system set kv_cache_limit_bytes = 1")
    assert db.tenant("sys").catalog._cache.stats()["entries"] == 0
    db.close()
