"""End-to-end TPC-H slice tests (Q6/Q1/Q14 at tiny SF) vs numpy oracles.

≙ the reference's mysqltest result-diff tier (SURVEY §4 tier 4) at unit
scale: run the whole plan through the engine and diff numbers computed by
an independent numpy implementation.
"""

import numpy as np
import pytest

from oceanbase_tpu.bench.queries import q1_plan, q14_plan, q6_plan
from oceanbase_tpu.bench.tpch import TPCH_PRIMARY_KEYS, gen_tpch
from oceanbase_tpu.catalog import Catalog
from oceanbase_tpu.datatypes import date_to_days
from oceanbase_tpu.exec.plan import execute_plan
from oceanbase_tpu.vector import to_numpy


@pytest.fixture(scope="module")
def db():
    tables, types = gen_tpch(sf=0.01)
    cat = Catalog()
    for name, arrays in tables.items():
        cat.load_numpy(name, arrays,
                       types={k: v for k, v in types.items() if k in arrays},
                       primary_key=TPCH_PRIMARY_KEYS[name])
    return cat, tables


def _table_data(cat):
    return {t: cat.table_data(t) for t in cat.tables()}


def test_q6(db):
    cat, tables = db
    li = tables["lineitem"]
    d0, d1 = date_to_days("1994-01-01"), date_to_days("1995-01-01")
    sel = (
        (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
        & (li["l_discount"] >= 5) & (li["l_discount"] <= 7)
        & (li["l_quantity"] < 2400)
    )
    oracle = (li["l_extendedprice"][sel] * li["l_discount"][sel]).sum()

    out = execute_plan(q6_plan(), _table_data(cat))
    res = to_numpy(out)
    assert res["revenue"][0] == oracle  # exact fixed-point (scale 4)


def test_q1(db):
    cat, tables = db
    li = tables["lineitem"]
    cutoff = date_to_days("1998-09-02")
    sel = li["l_shipdate"] <= cutoff
    out = execute_plan(q1_plan(), _table_data(cat))
    res = to_numpy(out)

    import collections
    groups = collections.defaultdict(list)
    for i in np.nonzero(sel)[0]:
        groups[(li["l_returnflag"][i], li["l_linestatus"][i])].append(i)
    keys = sorted(groups)
    assert [tuple(x) for x in zip(res["l_returnflag"], res["l_linestatus"])] == keys
    for row, k in enumerate(keys):
        idx = np.array(groups[k])
        assert res["sum_qty"][row] == li["l_quantity"][idx].sum()
        assert res["sum_base_price"][row] == li["l_extendedprice"][idx].sum()
        disc = li["l_extendedprice"][idx] * (100 - li["l_discount"][idx])
        assert res["sum_disc_price"][row] == disc.sum()
        charge = disc * (100 + li["l_tax"][idx])
        assert res["sum_charge"][row] == charge.sum()
        assert res["count_order"][row] == len(idx)
        np.testing.assert_allclose(
            res["avg_qty"][row], li["l_quantity"][idx].mean() / 100, rtol=1e-12
        )


def test_q14(db):
    cat, tables = db
    li, part = tables["lineitem"], tables["part"]
    d0, d1 = date_to_days("1995-09-01"), date_to_days("1995-10-01")
    sel = (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
    ptype = part["p_type"][li["l_partkey"][sel] - 1].astype(str)
    disc = li["l_extendedprice"][sel] * (100 - li["l_discount"][sel])
    promo = disc[np.char.startswith(ptype, "PROMO")].sum()
    oracle = 100.0 * promo / disc.sum()

    out = execute_plan(q14_plan(len(li["l_orderkey"])), _table_data(cat))
    res = to_numpy(out)
    np.testing.assert_allclose(res["promo_revenue"][0], oracle, rtol=1e-9)


# ---------------------------------------------------------------------------
# poison-lane verifier (obcheck dynamic half): pad every input to the
# next bucket, fill the dead lanes with NaN/sentinel garbage, and demand
# bit-identical results — the Static-shape policy as an executable check
# ---------------------------------------------------------------------------


def _padded_tables(cat):
    from oceanbase_tpu.vector import bucket_capacity

    out = {}
    for t in cat.tables():
        rel = cat.table_data(t)
        # +1 guarantees at least one masked-dead pad lane per table
        out[t] = rel.pad_to(bucket_capacity(rel.capacity + 1))
    return out


@pytest.mark.parametrize("qname", ["q6", "q1", "q14"])
def test_poison_lanes_tpch(db, poison, qname):
    cat, tables = db
    n = len(tables["lineitem"]["l_orderkey"])
    plan = {"q6": q6_plan, "q1": q1_plan,
            "q14": lambda: q14_plan(n)}[qname]()
    poison.assert_poison_invariant(
        lambda tabs: execute_plan(plan, tabs), _padded_tables(cat))
