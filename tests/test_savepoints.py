"""SAVEPOINT / ROLLBACK TO / RELEASE inside explicit transactions.

≙ savepoint rollback over statement-scoped undo
(src/storage/tx savepoint handling).
"""

import pytest

from oceanbase_tpu.server import Database


def test_savepoint_rollback_to(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("begin")
    s.execute("insert into t values (1, 10)")
    s.execute("savepoint sp1")
    s.execute("insert into t values (2, 20)")
    s.execute("update t set v = 99 where k = 1")
    assert s.execute("select sum(v) from t").rows()[0][0] == 119
    s.execute("rollback to savepoint sp1")
    # writes after sp1 are gone, the one before it remains
    assert s.execute("select k, v from t order by k").rows() == [(1, 10)]
    s.execute("insert into t values (3, 30)")
    s.execute("commit")
    assert s.execute("select k, v from t order by k").rows() == \
        [(1, 10), (3, 30)]
    db.close()


def test_savepoint_release_and_nesting(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("begin")
    s.execute("insert into t values (1, 1)")
    s.execute("savepoint a")
    s.execute("insert into t values (2, 2)")
    s.execute("savepoint b")
    s.execute("insert into t values (3, 3)")
    s.execute("rollback to a")
    # b was created after a -> destroyed
    with pytest.raises(Exception):
        s.execute("rollback to b")
    s.execute("commit")
    assert s.execute("select count(*) from t").rows()[0][0] == 1
    # release removes the name
    s.execute("begin")
    s.execute("savepoint x")
    s.execute("release savepoint x")
    with pytest.raises(Exception):
        s.execute("rollback to x")
    s.execute("rollback")
    db.close()


def test_savepoint_with_secondary_index(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("create unique index iv on t (v)")
    s.execute("begin")
    s.execute("insert into t values (1, 100)")
    s.execute("savepoint sp")
    s.execute("insert into t values (2, 200)")
    s.execute("rollback to sp")
    # the rolled-back unique value is free again
    s.execute("insert into t values (3, 200)")
    s.execute("commit")
    assert s.execute("select k from t where v = 200").rows() == [(3,)]
    db.close()
