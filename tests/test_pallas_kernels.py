"""Pallas kernel tests (interpret mode on CPU) vs numpy oracle."""

import numpy as np
import pytest

from oceanbase_tpu.datatypes import date_to_days
from oceanbase_tpu.ops import q6_filter_sum


def test_q6_kernel_exact(rng):
    n = 100_000
    ship = rng.integers(date_to_days("1992-01-01"),
                        date_to_days("1998-12-01"), n).astype(np.int32)
    disc = rng.integers(0, 11, n).astype(np.int32)
    qty = (rng.integers(1, 51, n) * 100).astype(np.int32)
    price = rng.integers(90_000, 10_000_000, n).astype(np.int32)
    live = np.ones(n, dtype=np.int32)
    live[::17] = 0  # some dead lanes

    d0, d1 = date_to_days("1994-01-01"), date_to_days("1995-01-01")
    got = int(q6_filter_sum(ship, disc, qty, price, live,
                            ship_lo=d0, ship_hi=d1, disc_lo=5, disc_hi=7,
                            qty_hi=2400, interpret=True))
    sel = ((ship >= d0) & (ship < d1) & (disc >= 5) & (disc <= 7)
           & (qty < 2400) & (live != 0))
    want = int((price[sel].astype(np.int64) * disc[sel]).sum())
    assert got == want


def test_q6_kernel_ragged_and_empty(rng):
    # non-multiple-of-block sizes and all-filtered input
    for n in (1, 100, 8192, 8193):
        ship = np.full(n, date_to_days("1994-06-01"), dtype=np.int32)
        disc = np.full(n, 6, dtype=np.int32)
        qty = np.full(n, 100, dtype=np.int32)
        price = np.full(n, 1_000_000, dtype=np.int32)
        live = np.ones(n, dtype=np.int32)
        got = int(q6_filter_sum(
            ship, disc, qty, price, live,
            ship_lo=date_to_days("1994-01-01"),
            ship_hi=date_to_days("1995-01-01"),
            disc_lo=5, disc_hi=7, qty_hi=2400, interpret=True))
        assert got == n * 6_000_000
    # nothing matches
    got = int(q6_filter_sum(
        ship, disc, qty, price, live,
        ship_lo=0, ship_hi=1, disc_lo=5, disc_hi=7, qty_hi=2400,
        interpret=True))
    assert got == 0
