"""Regression tests for the round-1 advisor findings (ADVICE.md r2).

1. WAL truncate barrier vs slog-restored direct-load segments
2. GTS seeding below bulk_load segment versions after restart
3. partition column must be part of the primary key
4. checkpoint racing a concurrent commit (lost on crash)
5. keyless KvTable.put losing hidden rowids
"""

import pytest

from oceanbase_tpu.server import Database


def test_truncate_then_load_data_survives_restart(tmp_path):
    """ADVICE #1: TRUNCATE writes a WAL barrier; LOAD DATA writes only
    slog (add_segment). On recovery the WAL truncate replay must not drop
    the slog-restored post-truncate segments."""
    csv = tmp_path / "rows.csv"
    csv.write_text("5,50\n6,60\n")
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    s.execute("truncate table t")
    s.execute(f"load data infile '{csv}' into table t "
              f"fields terminated by ','")
    assert sorted(s.execute("select k from t").rows()) == [(5,), (6,)]
    db.close()
    db2 = Database(root)
    assert sorted(db2.session().execute("select k from t").rows()) == \
        [(5,), (6,)]
    db2.close()


def test_truncate_replay_still_drops_pre_truncate_rows(tmp_path):
    """The fence must not resurrect pre-truncate WAL rows either."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    s.execute("truncate table t")
    s.execute("insert into t values (9, 9)")
    db.close()
    db2 = Database(root)
    assert sorted(db2.session().execute("select k from t").rows()) == [(9,)]
    db2.close()


def test_ctas_visible_after_restart(tmp_path):
    """ADVICE #2: CTAS stamps segments with GTS values that reach neither
    the WAL nor pre-checkpoint meta; boot must seed GTS past them."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table src (k int primary key, v int)")
    s.execute("insert into src values (1, 10), (2, 20)")
    s.execute("create table dst as select * from src")
    db.close()
    db2 = Database(root)
    s2 = db2.session()
    assert sorted(s2.execute("select k, v from dst").rows()) == \
        [(1, 10), (2, 20)]
    # and repeatedly (the relation cache must not pin an empty view)
    assert sorted(s2.execute("select k, v from dst").rows()) == \
        [(1, 10), (2, 20)]
    db2.close()


def test_partition_column_must_be_in_primary_key(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    with pytest.raises(Exception, match="[Pp]artition"):
        s.execute(
            "create table p (k int primary key, v int) "
            "partition by range (v) ("
            "partition p0 values less than (100), "
            "partition p1 values less than maxvalue)")
    # keyless tables carry no uniqueness constraint: any column is fine
    s.execute(
        "create table q (a int, b int) partition by range (b) ("
        "partition p0 values less than (100), "
        "partition p1 values less than maxvalue)")
    s.execute("insert into q values (1, 10), (1, 200)")
    assert sorted(s.execute("select a, b from q").rows()) == \
        [(1, 10), (1, 200)]
    db.close()


def test_checkpoint_concurrent_commit_not_lost(tmp_path):
    """ADVICE #4: a commit landing between the flush snapshot and the
    recorded WAL replay point must survive a crash. The fix records the
    replay point BEFORE the snapshot; inject a commit mid-checkpoint."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1)")
    tenant = db.tenant()
    orig = tenant.engine.freeze_and_flush
    injected = {"done": False}

    def inject_then_flush(name, snapshot):
        if not injected["done"]:
            injected["done"] = True
            db.session().execute("insert into t values (2, 2)")
        return orig(name, snapshot=snapshot)

    tenant.engine.freeze_and_flush = inject_then_flush
    try:
        db.checkpoint()
    finally:
        tenant.engine.freeze_and_flush = orig
    db.close()
    db2 = Database(root)
    assert sorted(db2.session().execute("select k from t").rows()) == \
        [(1,), (2,)]
    db2.close()


def test_kv_keyless_puts_persist_rowids(tmp_path):
    """ADVICE #5: puts on a __rowid__ table must persist distinct rowids
    (newest-wins dedup collapsed them all into one row)."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table logs (msg varchar(32), n int)")  # keyless
    kv = db.tenant().kv("logs")
    kv.put({"msg": "a", "n": 1})
    kv.put({"msg": "b", "n": 2})
    rows = kv.scan()
    assert sorted((r["msg"], r["n"]) for r in rows) == [("a", 1), ("b", 2)]
    assert sorted(s.execute("select msg, n from logs").rows()) == \
        [("a", 1), ("b", 2)]
    # rowids survive flush + restart
    db.checkpoint()
    db.close()
    db2 = Database(root)
    assert sorted(db2.session().execute("select msg, n from logs")
                  .rows()) == [("a", 1), ("b", 2)]
    db2.close()
