"""MySQL wire protocol tests with a minimal raw-socket client.

≙ mysqltest driving the real wire protocol (SURVEY §4 tier 4).
"""

import socket
import struct

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.mysql_protocol import MySQLServer


class MiniClient:
    """Just enough of the 4.1 text protocol to drive the server."""

    def __init__(self, host, port, user="root", password=""):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.seq = 0
        self.user = user
        self.password = password
        self._handshake()

    def _read_packet(self):
        hdr = self._read_n(4)
        (ln,) = struct.unpack("<I", hdr[:3] + b"\x00")
        self.seq = hdr[3] + 1
        return self._read_n(ln)

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("closed")
            buf += part
        return buf

    def _send(self, payload):
        self.sock.sendall(struct.pack("<I", len(payload))[:3] +
                          bytes([self.seq & 0xFF]) + payload)
        self.seq += 1

    def _handshake(self):
        import hashlib

        greeting = self._read_packet()
        assert greeting[0] == 0x0A
        ver = greeting[1:greeting.index(b"\x00", 1)]
        assert b"oceanbase-tpu" in ver
        # salt: 8 bytes after ver+thread id, 12 more later
        p = greeting.index(b"\x00", 1) + 1 + 4
        salt = greeting[p:p + 8]
        rest = greeting[p + 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10:]
        salt += rest[:rest.index(b"\x00")]
        if self.password:
            sha_pw = hashlib.sha1(self.password.encode()).digest()
            stage2 = hashlib.sha1(sha_pw).digest()
            mask = hashlib.sha1(salt[:20] + stage2).digest()
            token = bytes(a ^ b for a, b in zip(sha_pw, mask))
        else:
            token = b""
        caps = 0x0200 | 0x8000  # PROTOCOL_41 | SECURE_CONNECTION
        resp = (struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23 +
                self.user.encode() + b"\x00" +
                bytes([len(token)]) + token)
        self._send(resp)
        ok = self._read_packet()
        if ok[0] == 0xFF:
            code = struct.unpack_from("<H", ok, 1)[0]
            raise PermissionError(f"auth failed: {code}")
        assert ok[0] == 0x00, ok

    @staticmethod
    def _lenenc(buf, pos):
        c = buf[pos]
        if c < 251:
            return c, pos + 1
        if c == 0xFC:
            return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
        if c == 0xFD:
            return struct.unpack("<I", buf[pos + 1:pos + 4] + b"\x00")[0], \
                pos + 4
        return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9

    def query(self, sql):
        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._read_packet()
        if first[0] == 0x00:
            affected, _ = self._lenenc(first, 1)
            return {"ok": True, "affected": affected}
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            raise RuntimeError(f"server error {code}: "
                               f"{first[9:].decode(errors='replace')}")
        ncols, _ = self._lenenc(first, 0)
        for _ in range(ncols):
            self._read_packet()  # column definitions
        assert self._read_packet()[0] == 0xFE  # EOF after columns
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            pos, row = 0, []
            while pos < len(pkt):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    ln, pos = self._lenenc(pkt, pos)
                    row.append(pkt[pos:pos + ln].decode())
                    pos += ln
            rows.append(tuple(row))
        return {"ok": True, "rows": rows}

    def ping(self):
        self.seq = 0
        self._send(b"\x0e")
        return self._read_packet()[0] == 0x00

    def close(self):
        try:
            self.seq = 0
            self._send(b"\x01")
        except Exception:
            pass
        self.sock.close()


@pytest.fixture()
def server(tmp_path):
    db = Database(str(tmp_path / "db"))
    srv = MySQLServer(db).start()
    yield srv
    srv.stop()
    db.close()


def test_wire_protocol_end_to_end(server):
    c = MiniClient(server.host, server.port)
    assert c.ping()
    r = c.query("create table t (k int primary key, v decimal(10,2), "
                "name varchar(20))")
    assert r["ok"]
    r = c.query("insert into t values (1, 10.50, 'ann'), (2, 20.25, null)")
    assert r["affected"] == 2
    r = c.query("select k, v, name from t order by k")
    assert r["rows"] == [("1", "10.5", "ann"), ("2", "20.25", None)]
    r = c.query("select sum(v) as total, count(*) as n from t")
    assert r["rows"] == [("30.75", "2")]
    # errors come back as ERR packets, connection stays usable
    with pytest.raises(RuntimeError, match="server error"):
        c.query("select nope from t")
    assert c.ping()
    c.close()


def test_prepared_statements_binary_protocol(server):
    c = MiniClient(server.host, server.port)
    c.query("create table p (k int primary key, v decimal(10,2))")
    c.query("insert into p values (1, 1.50), (2, 2.25), (3, 3.75)")

    # COM_STMT_PREPARE
    c.seq = 0
    c._send(b"\x16" + b"select k, v from p where k >= ? order by k")
    ok = c._read_packet()
    assert ok[0] == 0x00
    stmt_id, ncols, nparams = struct.unpack_from("<IHH", ok, 1)
    assert nparams == 1
    for _ in range(nparams):
        c._read_packet()       # param definition
    if nparams:
        assert c._read_packet()[0] == 0xFE

    # COM_STMT_EXECUTE with one LONGLONG param = 2
    c.seq = 0
    payload = (b"\x17" + struct.pack("<IBI", stmt_id, 0, 1) +
               b"\x00" +                    # null bitmap
               b"\x01" +                    # new params bound
               struct.pack("<H", 8) +       # type LONGLONG
               struct.pack("<q", 2))
    c._send(payload)
    first = c._read_packet()
    ncols, _ = c._lenenc(first, 0)
    assert ncols == 2
    for _ in range(ncols):
        c._read_packet()
    assert c._read_packet()[0] == 0xFE
    rows = []
    while True:
        pkt = c._read_packet()
        if pkt[0] == 0xFE and len(pkt) < 9:
            break
        assert pkt[0] == 0x00  # binary row header
        pos = 1 + (ncols + 2 + 7) // 8
        k = struct.unpack_from("<q", pkt, pos)[0]
        pos += 8
        ln, pos = c._lenenc(pkt, pos)
        v = pkt[pos:pos + ln].decode()
        rows.append((k, v))
    assert rows == [(2, "2.25"), (3, "3.75")]

    # COM_STMT_CLOSE then re-execute -> clean error
    c.seq = 0
    c._send(b"\x19" + struct.pack("<I", stmt_id))
    c.seq = 0
    c._send(b"\x17" + struct.pack("<IBI", stmt_id, 0, 1) + b"\x00\x01" +
            struct.pack("<H", 8) + struct.pack("<q", 1))
    err = c._read_packet()
    assert err[0] == 0xFF
    assert c.ping()
    c.close()


def test_wire_two_concurrent_sessions(server):
    c1 = MiniClient(server.host, server.port)
    c2 = MiniClient(server.host, server.port)
    c1.query("create table s (k int primary key, v int)")
    c1.query("insert into s values (1, 1)")
    c1.query("begin")
    c1.query("update s set v = 2 where k = 1")
    # c2 sees the committed value until c1 commits
    assert c2.query("select v from s")["rows"] == [("1",)]
    c1.query("commit")
    assert c2.query("select v from s")["rows"] == [("2",)]
    c1.close()
    c2.close()


def test_auth_rejects_bad_password(server):
    c = MiniClient(server.host, server.port)
    assert c.query("create user alice identified by 'secret'")["ok"]
    c.close()
    # correct password authenticates
    c2 = MiniClient(server.host, server.port, user="alice",
                    password="secret")
    assert c2.ping()
    c2.close()
    # wrong password rejected with 1045
    with pytest.raises(PermissionError):
        MiniClient(server.host, server.port, user="alice",
                   password="wrong")
    # unknown user rejected
    with pytest.raises(PermissionError):
        MiniClient(server.host, server.port, user="mallory",
                   password="x")
    # root with a bogus password (it expects empty) rejected
    with pytest.raises(PermissionError):
        MiniClient(server.host, server.port, user="root",
                   password="nope")


def test_auth_persists_across_restart(server, tmp_path):
    c = MiniClient(server.host, server.port)
    c.query("create user bob identified by 'pw1'")
    c.close()
    db2 = Database(server.database.root)
    assert "bob" in db2.users
    db2.close()


def test_set_password(server):
    c = MiniClient(server.host, server.port)
    c.query("create user carol identified by 'old'")
    c.query("set password for carol = 'new'")
    c.close()
    with pytest.raises(PermissionError):
        MiniClient(server.host, server.port, user="carol", password="old")
    c2 = MiniClient(server.host, server.port, user="carol",
                    password="new")
    assert c2.ping()
    c2.close()


def test_tls_upgrade(server):
    """SSLRequest upgrade: TLS handshake mid-protocol, then normal auth
    and queries over the encrypted channel (≙ ussl-hook TLS upgrade)."""
    import ssl

    c = MiniClient.__new__(MiniClient)
    c.sock = socket.create_connection((server.host, server.port),
                                      timeout=10)
    c.seq = 0
    c.user, c.password = "root", ""
    greeting = c._read_packet()
    assert greeting[0] == 0x0A
    # capability flags advertise SSL
    p = greeting.index(b"\x00", 1) + 1 + 4 + 8 + 1
    caps_lo = struct.unpack_from("<H", greeting, p)[0]
    assert caps_lo & 0x800, "server must advertise CLIENT_SSL"
    # send SSLRequest (caps with CLIENT_SSL, no username)
    caps = 0x0200 | 0x8000 | 0x800
    c._send(struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    c.sock = ctx.wrap_socket(c.sock)
    assert c.sock.version() is not None  # TLS established
    # now the real login over TLS
    c._send(struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23 +
            b"root\x00" + b"\x00")
    ok = c._read_packet()
    assert ok[0] == 0x00, ok
    c.query("create table tt (k int primary key)")
    c.query("insert into tt values (1), (2)")
    assert c.query("select count(*) from tt")["rows"] == [("2",)]
    c.close()


def test_tls_key_file_mode(tmp_path):
    """The generated private key must be owner-only (0o600) — a
    world-readable key silently voids the TLS upgrade."""
    import os
    import stat

    from oceanbase_tpu.server.tls import ensure_server_credentials

    cert_p, key_p = ensure_server_credentials(str(tmp_path))
    assert os.path.exists(cert_p)
    assert stat.S_IMODE(os.stat(key_p).st_mode) == 0o600


def test_tls_key_file_mode_openssl_fallback(tmp_path):
    """Same 0o600 guarantee on the openssl-CLI fallback path."""
    import os
    import shutil
    import stat

    from oceanbase_tpu.server.tls import _openssl_credentials

    if shutil.which("openssl") is None:
        pytest.skip("no openssl binary on this host")
    tdir = str(tmp_path / "tls")
    os.makedirs(tdir)
    cert_p = os.path.join(tdir, "server-cert.pem")
    key_p = os.path.join(tdir, "server-key.pem")
    _openssl_credentials(tdir, cert_p, key_p)
    assert stat.S_IMODE(os.stat(key_p).st_mode) == 0o600
