"""Regression tests for the round-3 advisor findings (ADVICE.md r3) and
the VERDICT r3 #7 spill-tier reachability holes.

≙ the reference's regression suite discipline: every review finding gets
a pinned test (SURVEY §4).
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database

N = 40_000


def _mk(tmp_path, budget=4096):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute(f"alter system set sql_work_area_rows = {budget}")
    return db, s


def _load_big(s, name="t", n=N, seed=1):
    rng = np.random.default_rng(seed)
    v = rng.integers(0, 1_000_000, n)
    g = rng.integers(0, n // 2, n)
    s.execute(f"create table {name} "
              f"(k int primary key, v int, g int)")
    rows = ", ".join(f"({i}, {v[i]}, {g[i]})" for i in range(n))
    s.execute(f"insert into {name} values {rows}")
    return v, g


# ---------------------------------------------------------------------------
# ADVICE r3 medium: _stream_join per-batch capacity must scale with the
# batch, not the planner's whole-query estimate
# ---------------------------------------------------------------------------

def test_stream_join_batch_capacity_ignores_plan_estimate(tmp_path):
    from oceanbase_tpu.exec import spill_exec

    db, s = _mk(tmp_path)
    _load_big(s)
    s.execute("create table d (g int primary key, name varchar(16))")
    s.execute("insert into d values " + ", ".join(
        f"({i}, 'n{i % 7}')" for i in range(0, N // 2, 16)))

    caps = []
    orig = spill_exec.ops.join

    def spy(left, right, lk, rk, **kw):
        caps.append(kw.get("out_capacity"))
        return orig(left, right, lk, rk, **kw)

    spill_exec.ops.join, _saved = spy, orig
    try:
        r = s.execute("select count(*) from t join d on t.g = d.g")
        assert r.rows()[0][0] > 0
    finally:
        spill_exec.ops.join = _saved
    assert s._last_spill is not None and "join" in s._last_spill.kind
    assert caps, "streamed join never reached ops.join"
    # chunk size is spill_exec.DEFAULT_CHUNK_ROWS; first-attempt caps must
    # be O(batch), nowhere near the whole-join estimate (~N rows)
    bound = 4 * spill_exec.DEFAULT_CHUNK_ROWS
    assert all(c is None or c <= bound for c in caps), caps
    db.close()


# ---------------------------------------------------------------------------
# ADVICE r3 low: _materialize_host must surface a dropped output column
# ---------------------------------------------------------------------------

def test_materialize_host_raises_on_missing_column(tmp_path):
    db, s = _mk(tmp_path)
    with pytest.raises(KeyError):
        s._materialize_host(
            {"c1": np.arange(4)}, {}, {}, [("c1", "a"), ("c2", "b")])
    db.close()


# ---------------------------------------------------------------------------
# ADVICE r3 low: selective indexed queries keep the in-memory fast path
# even when the raw table is over budget
# ---------------------------------------------------------------------------

def test_selective_pk_query_skips_spill(tmp_path):
    db, s = _mk(tmp_path)
    _load_big(s)
    s._last_spill = None
    r = s.execute("select v from t where k = 17")
    assert len(r.rows()) == 1
    assert s._last_spill is None, \
        "point lookup on an over-budget table must not stream the table"
    # whole-table scan still spills
    r = s.execute("select k from t order by v limit 3")
    assert s._last_spill is not None
    db.close()


# ---------------------------------------------------------------------------
# ADVICE r3 low: one read point across big streams and small device tables
# ---------------------------------------------------------------------------

def test_spilled_join_reads_small_table_at_one_snapshot(tmp_path):
    db, s = _mk(tmp_path)
    _load_big(s)
    s.execute("create table d (g int primary key, name varchar(16))")
    s.execute("insert into d values " + ", ".join(
        f"({i}, 'n{i % 7}')" for i in range(0, N // 2, 16)))

    snaps = []
    orig = s.catalog.table_data_at

    def spy(name, snapshot, tx_id=0):
        snaps.append((name, snapshot))
        return orig(name, snapshot, tx_id)

    s.catalog.table_data_at = spy
    try:
        s.execute("select count(*) from t join d on t.g = d.g")
    finally:
        s.catalog.table_data_at = orig
    assert s._last_spill is not None
    small_reads = [sn for nm, sn in snaps if nm == "d"]
    assert small_reads, "small side must be read via the snapshot API"
    db.close()


# ---------------------------------------------------------------------------
# VERDICT r3 #7: spill inside explicit transactions
# ---------------------------------------------------------------------------

def test_spill_works_inside_transaction_for_clean_tables(tmp_path):
    db, s = _mk(tmp_path)
    v, _g = _load_big(s)
    s.execute("begin")
    r = s.execute("select count(*), sum(v) from t")
    cnt, sv = r.rows()[0]
    assert cnt == N and sv == int(v.sum())
    assert s._last_spill is not None, \
        "over-budget query inside a tx must still reach the disk tier"
    s.execute("commit")
    db.close()


def test_spill_skipped_for_tables_written_by_the_tx(tmp_path):
    db, s = _mk(tmp_path)
    v, _g = _load_big(s)
    s.execute("begin")
    s.execute("insert into t values (999999, 1, 1)")
    s._last_spill = None
    r = s.execute("select count(*) from t")
    # own write must be visible -> in-memory own-writes path, no spill
    assert r.rows()[0][0] == N + 1
    assert s._last_spill is None
    s.execute("rollback")
    db.close()


def test_tx_snapshot_isolation_through_spill_tier(tmp_path):
    db, s = _mk(tmp_path)
    v, _g = _load_big(s)
    s.execute("begin")
    r = s.execute("select count(*) from t")
    assert r.rows()[0][0] == N
    # a concurrent session commits new rows mid-transaction
    s2 = db.session()
    s2.execute("insert into t values (888888, 5, 5)")
    # the tx's spilled reads stay at its begin snapshot
    r = s.execute("select count(*) from t")
    assert r.rows()[0][0] == N
    s.execute("commit")
    r = s.execute("select count(*) from t")
    assert r.rows()[0][0] == N + 1
    db.close()


def test_nested_scalar_subquery_filter_not_dropped():
    """TPC-H Q20 shape: a correlated scalar comparison nested inside an
    IN-subquery must filter the SAME rows the sibling IN predicate
    filters — the decorrelation used to drop the comparison entirely
    (SF1 parity Q20 off-by-one)."""
    import numpy as np

    from oceanbase_tpu.sql import Session

    s = Session()
    s.catalog.load_numpy("supplier", {
        "s_suppkey": np.array([1, 2]),
        "s_name": np.array(["sup1", "sup2"], dtype=object)},
        primary_key=["s_suppkey"])
    s.catalog.load_numpy("partsupp", {
        "ps_partkey": np.array([10, 20, 30]),
        "ps_suppkey": np.array([1, 1, 2]),
        "ps_availqty": np.array([1, 1000, 1000])}, primary_key=[])
    s.catalog.load_numpy("part", {
        "p_partkey": np.array([10, 30]),
        "p_name": np.array(["forest a", "forest b"], dtype=object)},
        primary_key=["p_partkey"])
    s.catalog.load_numpy("lineitem", {
        "l_partkey": np.array([10, 20, 30]),
        "l_suppkey": np.array([1, 1, 2]),
        "l_quantity": np.array([100.0, 1.0, 4.0])}, primary_key=[])
    r = s.execute(
        "select s_name from supplier where s_suppkey in ("
        " select ps_suppkey from partsupp"
        " where ps_partkey in (select p_partkey from part"
        "                      where p_name like 'forest%')"
        "   and ps_availqty > (select 0.5 * sum(l_quantity)"
        "                      from lineitem"
        "                      where l_partkey = ps_partkey"
        "                        and l_suppkey = ps_suppkey)"
        ") order by s_name")
    assert r.rows() == [("sup2",)]
