"""Disk spill tier: temp-file store, external merge sort, and the
spilled partitioned join (≙ src/storage/tmp_file + the sort operator's
dump/merge path ob_sort_vec_op.h + recursive hash-join partition dump
ob_hash_join_vec_op.h:413).

Budgets are set far below the input size so the paths genuinely spill
(asserted via the store's byte counters)."""

import numpy as np
import pytest

from oceanbase_tpu.exec.external_sort import external_sort
from oceanbase_tpu.exec.spill import partitioned_join_spilled
from oceanbase_tpu.storage.tmpfile import TempFileStore


def _chunks(arrays, valids=None, chunk=1000):
    n = len(next(iter(arrays.values())))
    for s in range(0, n, chunk):
        a = {k: v[s:s + chunk] for k, v in arrays.items()}
        v = {k: (vv[s:s + chunk] if vv is not None else None)
             for k, vv in (valids or {}).items()}
        yield a, v


def _drain(gen, cols):
    parts = []
    for arrays, _valids in gen:
        parts.append(arrays)
    if not parts:
        return {c: np.zeros(0) for c in cols}
    return {c: np.concatenate(
        [p[c].astype(object) if p[c].dtype == object else p[c]
         for p in parts]) for c in cols}


def test_tmpfile_roundtrip(tmp_path):
    with TempFileStore(str(tmp_path / "spill")) as store:
        rid = store.new_run()
        a1 = {"x": np.arange(10, dtype=np.int64),
              "s": np.array([f"v{i}" for i in range(10)], dtype=object)}
        v1 = {"x": np.arange(10) % 2 == 0}
        store.append_chunk(rid, a1, v1)
        store.append_chunk(rid, a1)
        chunks = list(store.read_chunks(rid))
        assert len(chunks) == 2
        ra, rv = chunks[0]
        np.testing.assert_array_equal(ra["x"], a1["x"])
        assert ra["s"].tolist() == a1["s"].tolist()
        np.testing.assert_array_equal(rv["x"], v1["x"])
        assert store.run(rid).n_rows == 20
        assert store.total_bytes() > 0
        store.close_run(rid)
        assert store.total_bytes() == 0


def test_external_sort_beyond_budget(tmp_path):
    rng = np.random.default_rng(2)
    n = 120_000
    arrays = {"a": rng.integers(-10_000, 10_000, n).astype(np.int64),
              "b": rng.integers(0, 3, n).astype(np.int64)}
    with TempFileStore(str(tmp_path / "spill")) as store:
        got = _drain(external_sort(
            _chunks(arrays, chunk=7_000), ["a", "b"], [True, False],
            store, budget_rows=10_000, out_chunk=8_192), ["a", "b"])
        assert store.bytes_written > 0  # it really spilled
    order = np.lexsort((-arrays["b"], arrays["a"]))
    np.testing.assert_array_equal(got["a"], arrays["a"][order])
    np.testing.assert_array_equal(got["b"], arrays["b"][order])


def test_external_sort_strings_desc_and_nulls(tmp_path):
    rng = np.random.default_rng(3)
    n = 30_000
    names = np.array([f"w{int(i):04d}" for i in
                      rng.integers(0, 500, n)], dtype=object)
    valid = rng.random(n) > 0.1
    arrays = {"s": names, "k": np.arange(n, dtype=np.int64)}
    valids = {"s": valid, "k": None}
    with TempFileStore(str(tmp_path / "spill")) as store:
        got = external_sort(_chunks(arrays, valids, chunk=4_000),
                            ["s"], [False], store, budget_rows=5_000)
        svals = []
        for a, v in got:
            vv = v.get("s", np.ones(len(a["s"]), bool))
            svals.extend([x if ok else None
                          for x, ok in zip(a["s"].tolist(), vv)])
    # oracle: DESC with NULLs last (MySQL: NULL smallest)
    nonnull = sorted([x for x in svals if x is not None], reverse=True)
    n_null = sum(1 for x in svals if x is None)
    want = [x if ok else None for x, ok in zip(names.tolist(), valid)]
    want_nonnull = sorted([x for x in want if x is not None],
                          reverse=True)
    assert svals[:len(nonnull)] == want_nonnull
    assert svals[len(nonnull):] == [None] * n_null


def test_spilled_join_matches_in_memory(tmp_path):
    rng = np.random.default_rng(4)
    nl, nr = 80_000, 20_000
    left = {"lk": rng.integers(0, 30_000, nl).astype(np.int64),
            "lv": rng.integers(0, 100, nl).astype(np.int64)}
    right = {"rk": np.arange(nr, dtype=np.int64),
             "rv": rng.integers(0, 9, nr).astype(np.int64)}
    with TempFileStore(str(tmp_path / "spill")) as store:
        got = _drain(partitioned_join_spilled(
            _chunks(left, chunk=9_000), _chunks(right, chunk=9_000),
            ["lk"], ["rk"], store, how="inner", n_partitions=8,
            budget_rows=1 << 22), ["lk", "lv", "rk", "rv"])
        assert store.bytes_written > 0
    # numpy oracle
    sel = left["lk"] < nr
    import collections

    rmap = {int(k): int(v) for k, v in zip(right["rk"], right["rv"])}
    want = sorted((int(k), int(v), int(k), rmap[int(k)])
                  for k, v in zip(left["lk"][sel], left["lv"][sel]))
    got_rows = sorted(zip(got["lk"].tolist(), got["lv"].tolist(),
                          got["rk"].tolist(), got["rv"].tolist()))
    assert got_rows == want


def test_spilled_join_recursive_repartition(tmp_path):
    """A pathological key distribution (every key equal) forces one
    partition to exceed budget_rows and recurse."""
    n = 40_000
    left = {"lk": np.zeros(n, dtype=np.int64),
            "lv": np.arange(n, dtype=np.int64)}
    right = {"rk": np.array([0], dtype=np.int64),
             "rv": np.array([5], dtype=np.int64)}
    with TempFileStore(str(tmp_path / "spill")) as store:
        got = _drain(partitioned_join_spilled(
            _chunks(left, chunk=8_000), _chunks(right, chunk=8_000),
            ["lk"], ["rk"], store, how="inner", n_partitions=4,
            budget_rows=10_000), ["lk", "lv", "rk", "rv"])
    assert len(got["lk"]) == n
    assert set(got["rv"].tolist()) == {5}


def test_spilled_left_join_null_extension(tmp_path):
    left = {"lk": np.arange(100, dtype=np.int64),
            "lv": np.arange(100, dtype=np.int64) * 2}
    right = {"rk": np.arange(0, 50, dtype=np.int64),
             "rv": np.arange(0, 50, dtype=np.int64) + 1000}
    with TempFileStore(str(tmp_path / "spill")) as store:
        parts = list(partitioned_join_spilled(
            _chunks(left, chunk=30), _chunks(right, chunk=30),
            ["lk"], ["rk"], store, how="left", n_partitions=4))
    total = 0
    matched = 0
    for arrays, valids in parts:
        total += len(arrays["lk"])
        vv = valids.get("rv")
        if vv is None:
            matched += len(arrays["lk"])
        else:
            matched += int(np.sum(vv))
    assert total == 100 and matched == 50


def test_execute_sorted_streamed_with_limit(tmp_path):
    """End-to-end: plan-level ORDER BY + LIMIT over granules with an
    external sort spill, early-exiting the merge."""
    from oceanbase_tpu.exec.granule import (
        execute_sorted_streamed,
        numpy_chunk_provider,
    )
    from oceanbase_tpu.exec.plan import Limit, Sort, TableScan
    from oceanbase_tpu.expr import ir

    rng = np.random.default_rng(6)
    n = 200_000
    arrays = {"a": rng.integers(0, 1 << 30, n).astype(np.int64),
              "b": np.arange(n, dtype=np.int64)}
    provider = numpy_chunk_provider(arrays)
    plan = Limit(Sort(TableScan("t"), [ir.col("a")], [True]), 10)
    got_a, _ = execute_sorted_streamed(
        plan, provider, str(tmp_path / "spill"), chunk_rows=32_768,
        budget_rows=20_000)
    want = np.sort(arrays["a"])[:10]
    np.testing.assert_array_equal(got_a["a"], want)
