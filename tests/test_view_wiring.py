"""View DDL wired through the SQL surface + catalog DDL race hardening.

Satellites of the DTL pushdown PR: CREATE/DROP VIEW dispatch in
sql/session.py, views in SHOW TABLES / DESCRIBE / SHOW CREATE, the loud
WITH RECURSIVE rejection, and the catalog's locked collision checks.
"""

from __future__ import annotations

import numpy as np
import pytest

from oceanbase_tpu.catalog import Catalog, ColumnDef, TableDef
from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.sql.session import Session


@pytest.fixture()
def session():
    s = Session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    return s


def test_create_select_show_drop_view_end_to_end(session):
    s = session
    s.execute("create view big (kk, vv) as select k, v from t "
              "where v >= 20")
    assert s.execute("select kk, vv from big order by kk").rows() == \
        [(2, 20), (3, 30)]
    # views show up in metadata
    names = [r[0] for r in s.execute("show tables").rows()
             if not r[0].startswith("gv$")]
    assert names == ["big", "t"]
    desc = s.execute("describe big").rows()
    assert [(f, t) for f, t, _n, _k in desc] == \
        [("kk", "INT"), ("vv", "INT")]
    create = s.execute("show create table big").rows()[0][1]
    assert create.startswith("CREATE VIEW big (kk, vv) AS")
    # OR REPLACE swaps the body; plain re-create errors
    with pytest.raises(ValueError, match="exists"):
        s.execute("create view big as select k from t")
    s.execute("create or replace view big as select k from t where k = 1")
    assert s.execute("select * from big").rows() == [(1,)]
    # drop removes it from metadata and binding
    s.execute("drop view big")
    assert [r[0] for r in s.execute("show tables").rows()
            if not r[0].startswith("gv$")] == ["t"]
    with pytest.raises(KeyError):
        s.execute("drop view big")
    s.execute("drop view if exists big")  # no error
    with pytest.raises(KeyError):
        s.execute("select * from big")


def test_view_name_collisions(session):
    s = session
    s.execute("create view v1 as select k from t")
    # a table must not shadow a view, in either creation order
    with pytest.raises(ValueError, match="view v1"):
        s.execute("create table v1 (x int)")
    with pytest.raises(ValueError, match="already exists"):
        s.execute("create view t as select 1")


def test_self_referencing_cte_message(session):
    # a plain CTE referencing itself gets a direct, non-contradicting
    # error instead of pretending a materializer exists
    with pytest.raises(Exception, match="WITH RECURSIVE is not"):
        session.execute(
            "with r (x) as (select x from r) select * from r")


def test_view_over_virtual_table_refreshes(tmp_path):
    """A view body referencing a gv$ table must re-materialize the
    virtual relation per statement, not serve the snapshot captured by
    whichever query touched it first."""
    from oceanbase_tpu.server.database import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create view audit_v as select sql from gv$sql_audit")
    r1 = s.execute("select count(*) from audit_v").rows()[0][0]
    r2 = s.execute("select count(*) from audit_v").rows()[0][0]
    assert r2 > r1  # the audit ring grew between statements


def _tdef(name):
    return TableDef(name, [ColumnDef("x", SqlType.int_())])


def test_catalog_collision_checks_are_locked():
    cat = Catalog()
    cat.create_view("v", "select 1")
    # create_table checks views inside the locked section
    with pytest.raises(ValueError, match="view v"):
        cat.create_table(_tdef("v"))
    # register_external refuses views and base tables atomically
    with pytest.raises(ValueError, match="view v"):
        cat.register_external(_tdef("v"), "/nowhere.csv")
    cat.create_table(_tdef("t"))
    with pytest.raises(ValueError, match="already exists"):
        cat.register_external(_tdef("t"), "/nowhere.csv")
    # register_transient refuses to shadow a view ...
    with pytest.raises(ValueError, match="view v"):
        cat.register_transient("v", {"x": np.arange(3)})
    # ... but re-registering an existing transient (per-statement gv$
    # refresh) stays allowed
    cat.register_transient("gv$x", {"x": np.arange(3)})
    cat.register_transient("gv$x", {"x": np.arange(4)})


def test_concurrent_view_vs_table_create_never_coexist():
    """Race a CREATE VIEW against a CREATE TABLE of the same name: with
    the check inside the lock, exactly one side wins."""
    import threading

    for trial in range(20):
        cat = Catalog()
        errs = []
        barrier = threading.Barrier(2)

        def mk_table():
            barrier.wait()
            try:
                cat.create_table(_tdef("x"))
            except ValueError as e:
                errs.append(e)

        def mk_view():
            barrier.wait()
            try:
                cat.create_view("x", "select 1")
            except ValueError as e:
                errs.append(e)

        ts = [threading.Thread(target=mk_table),
              threading.Thread(target=mk_view)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        is_table = cat.has_table("x")
        is_view = cat.view_def("x") is not None
        assert is_table != is_view, (trial, is_table, is_view)
        assert len(errs) == 1
