"""Vector index tests: exact parity + IVF recall (≙ vector-index tests)."""

import numpy as np
import pytest

from oceanbase_tpu.share.vector_index import IvfFlatIndex, exact_search


def test_exact_search_matches_numpy(rng):
    n, d, q, k = 2000, 64, 10, 5
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    queries = rng.normal(size=(q, d)).astype(np.float32)
    _, idx = exact_search(queries, vecs, k, metric="l2")
    idx = np.asarray(idx)
    d2 = ((queries[:, None, :] - vecs[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d2, axis=1)[:, :k]
    # same top-k sets (tie order may differ)
    for i in range(q):
        assert set(idx[i]) == set(want[i])


def test_exact_cosine_and_ip(rng):
    n, d = 500, 32
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    qs = rng.normal(size=(3, d)).astype(np.float32)
    _, ip_idx = exact_search(qs, vecs, 3, metric="ip")
    want = np.argsort(-(qs @ vecs.T), axis=1)[:, :3]
    assert set(np.asarray(ip_idx)[0]) == set(want[0])
    _, cos_idx = exact_search(qs, vecs, 3, metric="cosine")
    vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
    want = np.argsort(-(qn @ vn.T), axis=1)[:, :3]
    assert set(np.asarray(cos_idx)[0]) == set(want[0])


def test_ivf_recall(rng):
    # clustered data: IVF with a few probes should have high recall
    n_clusters, per, d = 20, 200, 32
    centers = rng.normal(size=(n_clusters, d)) * 10
    vecs = np.concatenate([
        c + rng.normal(size=(per, d)) for c in centers
    ]).astype(np.float32)
    queries = (centers[:5] + rng.normal(size=(5, d)) * 0.5).astype(np.float32)

    idx = IvfFlatIndex(vecs, n_clusters=32, metric="l2", seed=1)
    _, approx = idx.search(queries, k=10, nprobe=8)
    _, exact = exact_search(queries, vecs, 10, metric="l2")
    approx, exact = np.asarray(approx), np.asarray(exact)
    recall = np.mean([
        len(set(approx[i]) & set(exact[i])) / 10 for i in range(len(queries))
    ])
    assert recall >= 0.9, recall


def test_ivf_small_inputs(rng):
    vecs = rng.normal(size=(5, 8)).astype(np.float32)
    idx = IvfFlatIndex(vecs, n_clusters=2)
    _, got = idx.search(vecs[:2], k=3, nprobe=2)
    assert np.asarray(got).shape == (2, 3)
    # query for its own vector finds itself first
    assert np.asarray(got)[0, 0] == 0


def test_ivf_padding_reports_minus_one(rng):
    # k exceeding the probed candidates must yield -1, not vector 0
    vecs = np.concatenate([
        np.zeros((3, 4)), np.full((50, 4), 100.0)
    ]).astype(np.float32)
    idx = IvfFlatIndex(vecs, n_clusters=2, seed=3)
    scores, got = idx.search(np.zeros((1, 4), np.float32), k=10, nprobe=1)
    got = np.asarray(got)[0]
    scores = np.asarray(scores)[0]
    pad = np.isneginf(scores)
    assert pad.any()
    assert (got[pad] == -1).all()
    assert set(got[~pad]) == {0, 1, 2}
