"""Full-stack failure scenarios: WAL leader loss mid-workload, errsim
fault storms (≙ mittest errsim failover suites, SURVEY §5.3), and —
over a real 3-process cluster — failure-detector-driven re-election and
suspect-node slice avoidance (net/health.py + net/faults.py).
"""

import time

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.errsim import ERRSIM


def test_wal_leader_failover_mid_workload(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")

    old_leader = db.wal.leader_id
    db.wal.kill(old_leader)
    # next write re-elects automatically and succeeds
    s.execute("insert into t values (3, 3)")
    assert db.wal.leader_id != old_leader
    s.execute("update t set v = 30 where k = 3")
    r = s.execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]

    # the dead replica revives and catches up
    db.wal.revive(old_leader)
    db.wal.tick()
    lsns = {r.last_lsn() for r in db.wal.replicas.values()}
    assert len(lsns) == 1

    # crash + recover with the post-failover log
    db.close()
    db2 = Database(root)
    r = db2.session().execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]
    db2.close()


def test_errsim_storm_keeps_consistency(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    applied = 0
    ERRSIM.arm("palf.append", error=IOError("disk gremlin"), count=3,
               prob=0.5)
    try:
        for i in range(30):
            try:
                s.execute(f"insert into t values ({i}, {i})")
                applied += 1
            except Exception:
                pass
    finally:
        ERRSIM.reset()
    got = s.execute("select count(*) from t").rows()[0][0]
    assert got == applied
    # every surviving row intact
    r = s.execute("select sum(v) from t").rows()[0][0]
    ks = [row[0] for row in s.execute("select k from t").rows()]
    assert r == sum(ks)
    db.close()


# ---------------------------------------------------------------------------
# cluster scenarios: failure detector + fault plane over real processes
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_health_triggered_reelection_bounded(tmp_path):
    """Kill the leader and issue NO statements: the failure detector on
    the survivors must notice (heartbeat interval × down threshold) and
    campaign AUTONOMOUSLY — the old code only re-elected when a write
    arrived to pay the lease out."""
    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table t (k int primary key, v int)")
        c.execute(1, "insert into t values (1, 1), (2, 2)")
        t_kill = time.monotonic()
        c.kill(1)
        # detection ≈ health_ping_interval_s (0.5) × down threshold (4)
        # with the ping policy's internal retries compressing rounds,
        # plus one randomized-backoff election round — generously bound
        bound_s = 15.0
        new_leader = None
        while time.monotonic() - t_kill < bound_s:
            for i in (2, 3):
                try:
                    st = c.clients[i].call("palf.state",
                                           _deadline_s=1.0)
                    if st["role"] == "leader":
                        new_leader = i
                        break
                except OSError:
                    pass
            if new_leader is not None:
                break
            time.sleep(0.2)
        elapsed = time.monotonic() - t_kill
        assert new_leader in (2, 3), \
            f"no autonomous re-election within {bound_s}s"
        # the cluster serves writes promptly — concurrent campaigns may
        # still be settling, so retry the statement like any client
        # (the documented NotLeader routing contract)
        from oceanbase_tpu.net.rpc import RpcError

        res = None
        for _ in range(20):
            try:
                res = c.execute(new_leader,
                                "insert into t values (3, 3)")
                break
            except (RpcError, OSError):
                time.sleep(0.25)
        assert res is not None, "write never succeeded after failover"
        res = c.execute(5 - new_leader, "select count(*) from t")
        assert c.rows(res)[0][0] == 3
        # and the survivors' detectors agree the old leader is down
        h = c.clients[new_leader].call("cluster.health")
        st = {r["peer"]: r for r in h["peers"]}
        assert st[1]["state"] == "down"
        assert st[1]["consecutive_failures"] >= 1
        assert elapsed < bound_s
    finally:
        c.close()


@pytest.mark.slow
def test_suspect_node_slice_avoidance_parity(tmp_path):
    """One-way traffic loss leader→node3: the detector turns node 3
    down, the DTL exchange routes its slice locally FROM THE START
    (avoided_parts, not fallback_parts), and results stay bit-identical
    with the serial path."""
    import numpy as np

    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table q (k int primary key, v int, d int)")
        rng = np.random.default_rng(3)
        n = 1500
        v = rng.integers(0, 100, n)
        d = rng.integers(0, 1000, n)
        for s in range(0, n, 500):
            vals = ", ".join(f"({i}, {v[i]}, {d[i]})"
                             for i in range(s, min(s + 500, n)))
            c.execute(1, f"insert into q values {vals}")
        c.execute(1, "alter system set dtl_min_rows = 1")

        # the admin verb is config-gated
        from oceanbase_tpu.net.rpc import RpcError

        with pytest.raises(RpcError) as ei:
            c.clients[1].call("fault.inject", where="send",
                              action="drop", peer=3)
        assert ei.value.kind == "PermissionError"
        c.execute(1, "alter system set enable_fault_injection = true")

        # cut every frame node 1 SENDS to node 3 (its replies to node
        # 3's requests still flow, so node 3 never suspects the leader
        # and no takeover muddies the scenario)
        c.clients[1].call("fault.inject", where="send", action="drop",
                          peer=3)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            h = c.clients[1].call("cluster.health")
            st = {r["peer"]: r for r in h["peers"]}
            if st[3]["state"] != "up":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("detector never suspected node 3")

        q = "select sum(v), count(*) from q where d < 500"
        res = c.execute(1, q)
        sel = d < 500
        expect = [(int(v[sel].sum()), int(sel.sum()))]
        assert c.rows(res) == expect
        ex = c.execute(
            1, "select pushdown_hit, fallback_parts, avoided_parts"
               " from gv$px_exchange where mode = 'pushdown'"
               " order by ts desc limit 1")
        (hit, fallbacks, avoided), = c.rows(ex)
        assert hit == 1
        assert avoided >= 1      # pre-emptive local routing
        assert fallbacks == 0    # no deadline was paid first
        # parity vs the serial path
        c.execute(1, "alter system set enable_dtl_pushdown = false")
        assert c.rows(c.execute(1, q)) == expect
        # gv$cluster_health through SQL mirrors the wire snapshot
        hv = c.execute(
            1, "select peer, state, failures from gv$cluster_health"
               " order by peer")
        rows = c.rows(hv)
        assert [r[0] for r in rows] == [2, 3]
        assert rows[1][1] in ("suspect", "down")
        assert rows[1][2] >= 1
        # clearing the rules heals the link; the breaker half-opens
        c.clients[1].call("fault.clear")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            h = c.clients[1].call("cluster.health")
            st = {r["peer"]: r for r in h["peers"]}
            if st[3]["state"] == "up":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("breaker never recovered")
    finally:
        c.close()
