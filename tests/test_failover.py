"""Full-stack failure scenarios: WAL leader loss mid-workload, errsim
fault storms (≙ mittest errsim failover suites, SURVEY §5.3).
"""

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.errsim import ERRSIM


def test_wal_leader_failover_mid_workload(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")

    old_leader = db.wal.leader_id
    db.wal.kill(old_leader)
    # next write re-elects automatically and succeeds
    s.execute("insert into t values (3, 3)")
    assert db.wal.leader_id != old_leader
    s.execute("update t set v = 30 where k = 3")
    r = s.execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]

    # the dead replica revives and catches up
    db.wal.revive(old_leader)
    db.wal.tick()
    lsns = {r.last_lsn() for r in db.wal.replicas.values()}
    assert len(lsns) == 1

    # crash + recover with the post-failover log
    db.close()
    db2 = Database(root)
    r = db2.session().execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]
    db2.close()


def test_errsim_storm_keeps_consistency(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    applied = 0
    ERRSIM.arm("palf.append", error=IOError("disk gremlin"), count=3,
               prob=0.5)
    try:
        for i in range(30):
            try:
                s.execute(f"insert into t values ({i}, {i})")
                applied += 1
            except Exception:
                pass
    finally:
        ERRSIM.reset()
    got = s.execute("select count(*) from t").rows()[0][0]
    assert got == applied
    # every surviving row intact
    r = s.execute("select sum(v) from t").rows()[0][0]
    ks = [row[0] for row in s.execute("select k from t").rows()]
    assert r == sum(ks)
    db.close()
