"""Full-stack failure scenarios: WAL leader loss mid-workload, errsim
fault storms (≙ mittest errsim failover suites, SURVEY §5.3), and —
over a real 3-process cluster — failure-detector-driven re-election,
suspect-node slice avoidance (net/health.py + net/faults.py), and the
crash-recovery plane: kill→restart→rejoin, wipe→rebuild, and durable XA
across leader failover (net/rebuild.py, tx/service.py recovery).
"""

import shutil
import time

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.errsim import ERRSIM


def test_wal_leader_failover_mid_workload(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")

    old_leader = db.wal.leader_id
    db.wal.kill(old_leader)
    # next write re-elects automatically and succeeds
    s.execute("insert into t values (3, 3)")
    assert db.wal.leader_id != old_leader
    s.execute("update t set v = 30 where k = 3")
    r = s.execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]

    # the dead replica revives and catches up
    db.wal.revive(old_leader)
    db.wal.tick()
    lsns = {r.last_lsn() for r in db.wal.replicas.values()}
    assert len(lsns) == 1

    # crash + recover with the post-failover log
    db.close()
    db2 = Database(root)
    r = db2.session().execute("select k, v from t order by k").rows()
    assert r == [(1, 1), (2, 2), (3, 30)]
    db2.close()


def test_errsim_storm_keeps_consistency(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    applied = 0
    ERRSIM.arm("palf.append", error=IOError("disk gremlin"), count=3,
               prob=0.5)
    try:
        for i in range(30):
            try:
                s.execute(f"insert into t values ({i}, {i})")
                applied += 1
            except Exception:
                pass
    finally:
        ERRSIM.reset()
    got = s.execute("select count(*) from t").rows()[0][0]
    assert got == applied
    # every surviving row intact
    r = s.execute("select sum(v) from t").rows()[0][0]
    ks = [row[0] for row in s.execute("select k from t").rows()]
    assert r == sum(ks)
    db.close()


# ---------------------------------------------------------------------------
# cluster scenarios: failure detector + fault plane over real processes
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_health_triggered_reelection_bounded(tmp_path):
    """Kill the leader and issue NO statements: the failure detector on
    the survivors must notice (heartbeat interval × down threshold) and
    campaign AUTONOMOUSLY — the old code only re-elected when a write
    arrived to pay the lease out."""
    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table t (k int primary key, v int)")
        c.execute(1, "insert into t values (1, 1), (2, 2)")
        t_kill = time.monotonic()
        c.kill(1)
        # detection ≈ health_ping_interval_s (0.5) × down threshold (4)
        # with the ping policy's internal retries compressing rounds,
        # plus one randomized-backoff election round — generously bound
        bound_s = 15.0
        new_leader = None
        while time.monotonic() - t_kill < bound_s:
            for i in (2, 3):
                try:
                    st = c.clients[i].call("palf.state",
                                           _deadline_s=1.0)
                    if st["role"] == "leader":
                        new_leader = i
                        break
                except OSError:
                    pass
            if new_leader is not None:
                break
            time.sleep(0.2)
        elapsed = time.monotonic() - t_kill
        assert new_leader in (2, 3), \
            f"no autonomous re-election within {bound_s}s"
        # the cluster serves writes promptly — concurrent campaigns may
        # still be settling, so retry the statement like any client
        # (the documented NotLeader routing contract)
        from oceanbase_tpu.net.rpc import RpcError

        res = None
        for _ in range(20):
            try:
                res = c.execute(new_leader,
                                "insert into t values (3, 3)")
                break
            except (RpcError, OSError):
                time.sleep(0.25)
        assert res is not None, "write never succeeded after failover"
        res = c.execute(5 - new_leader, "select count(*) from t")
        assert c.rows(res)[0][0] == 3
        # and the survivors' detectors agree the old leader is down
        h = c.clients[new_leader].call("cluster.health")
        st = {r["peer"]: r for r in h["peers"]}
        assert st[1]["state"] == "down"
        assert st[1]["consecutive_failures"] >= 1
        assert elapsed < bound_s
    finally:
        c.close()


@pytest.mark.slow
def test_suspect_node_slice_avoidance_parity(tmp_path):
    """One-way traffic loss leader→node3: the detector turns node 3
    down, the DTL exchange routes its slice locally FROM THE START
    (avoided_parts, not fallback_parts), and results stay bit-identical
    with the serial path."""
    import numpy as np

    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table q (k int primary key, v int, d int)")
        rng = np.random.default_rng(3)
        n = 1500
        v = rng.integers(0, 100, n)
        d = rng.integers(0, 1000, n)
        for s in range(0, n, 500):
            vals = ", ".join(f"({i}, {v[i]}, {d[i]})"
                             for i in range(s, min(s + 500, n)))
            c.execute(1, f"insert into q values {vals}")
        c.execute(1, "alter system set dtl_min_rows = 1")

        # the admin verb is config-gated
        from oceanbase_tpu.net.rpc import RpcError

        with pytest.raises(RpcError) as ei:
            c.clients[1].call("fault.inject", where="send",
                              action="drop", peer=3)
        assert ei.value.kind == "PermissionError"
        c.execute(1, "alter system set enable_fault_injection = true")

        # cut every frame node 1 SENDS to node 3 (its replies to node
        # 3's requests still flow, so node 3 never suspects the leader
        # and no takeover muddies the scenario)
        c.clients[1].call("fault.inject", where="send", action="drop",
                          peer=3)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            h = c.clients[1].call("cluster.health")
            st = {r["peer"]: r for r in h["peers"]}
            if st[3]["state"] != "up":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("detector never suspected node 3")

        q = "select sum(v), count(*) from q where d < 500"
        res = c.execute(1, q)
        sel = d < 500
        expect = [(int(v[sel].sum()), int(sel.sum()))]
        assert c.rows(res) == expect
        ex = c.execute(
            1, "select pushdown_hit, fallback_parts, avoided_parts"
               " from gv$px_exchange where mode = 'pushdown'"
               " order by ts desc limit 1")
        (hit, fallbacks, avoided), = c.rows(ex)
        assert hit == 1
        assert avoided >= 1      # pre-emptive local routing
        assert fallbacks == 0    # no deadline was paid first
        # parity vs the serial path
        c.execute(1, "alter system set enable_dtl_pushdown = false")
        assert c.rows(c.execute(1, q)) == expect
        # gv$cluster_health through SQL mirrors the wire snapshot
        hv = c.execute(
            1, "select peer, state, failures from gv$cluster_health"
               " order by peer")
        rows = c.rows(hv)
        assert [r[0] for r in rows] == [2, 3]
        assert rows[1][1] in ("suspect", "down")
        assert rows[1][2] >= 1
        # clearing the rules heals the link; the breaker half-opens
        c.clients[1].call("fault.clear")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            h = c.clients[1].call("cluster.health")
            st = {r["peer"]: r for r in h["peers"]}
            if st[3]["state"] == "up":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("breaker never recovered")
    finally:
        c.close()

# ---------------------------------------------------------------------------
# crash recovery & rejoin: restart replay, wiped-replica rebuild, durable XA
# ---------------------------------------------------------------------------


def _wait(fn, timeout=60, period=0.25, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(period)
    raise AssertionError(f"timed out waiting for {msg}")


def _weak_count(c, i, table):
    r = c.execute(i, f"select count(*) from {table}",
                  consistency="weak")
    return (int(c.rows(r)[0][0]), r["node"])


@pytest.mark.slow
def test_nodekill_restart_rejoin(tmp_path):
    """SIGKILL a follower, restart the process: it replays its WAL,
    rejoins the palf group without disturbing the term, catches up via
    the leader's push protocol, the detector flips down→up within a
    heartbeat, and DTL routing sends slices back to it (avoided_parts
    returns to 0).  A row committed immediately before the kill must be
    readable FROM the restarted node."""
    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table t (k int primary key, v int)")
        for s in range(0, 1000, 250):
            vals = ", ".join(f"({i}, {i * 2})"
                             for i in range(s, s + 250))
            c.execute(1, f"insert into t values {vals}")
        c.execute(1, "alter system set dtl_min_rows = 1")
        # the row committed right before the kill
        c.execute(1, "insert into t values (99999, 7)")
        _wait(lambda: _weak_count(c, 3, "t") == (1001, 3),
              msg="node 3 pre-kill convergence")
        st_before = c.clients[1].call("palf.state")

        c.kill(3)
        # writes continue while node 3 is dead
        c.execute(1, "insert into t values (99998, 8)")
        c.start_node(3)
        c.wait_ready()
        _wait(lambda: _weak_count(c, 3, "t") == (1002, 3),
              msg="restarted node catch-up")
        # pre-kill marker served BY the restarted node
        r = c.execute(3, "select v from t where k = 99999",
                      consistency="weak")
        assert r["node"] == 3 and c.rows(r) == [(7,)]
        # the rejoin did not disturb the term (no takeover election)
        st_after = c.clients[1].call("palf.state")
        assert st_after["role"] == "leader"
        assert st_after["term"] == st_before["term"]
        # detector returns to up...
        def _up():
            h = c.clients[1].call("cluster.health")
            return {x["peer"]: x["state"]
                    for x in h["peers"]}[3] == "up"
        _wait(_up, timeout=20, msg="detector down→up")
        # ...and a fresh pushdown query routes slices to node 3 again
        q = "select sum(v), count(*) from t where k < 500"
        c.execute(1, q)
        ex = c.execute(
            1, "select avoided_parts, fallback_parts from gv$px_exchange"
               " where mode = 'pushdown' order by ts desc limit 1")
        avoided, fallbacks = c.rows(ex)[0]
        assert (avoided, fallbacks) == (0, 0)
        # the restarted node's gv$recovery names its boot
        rec = c.clients[3].call("recovery.state")
        phases = [e["phase"] for e in rec["events"]]
        assert "boot_replay" in phases
        assert rec["applied_lsn"] == rec["committed_lsn"] > 0
    finally:
        c.close()


@pytest.mark.slow
def test_wipe_rebuild_reaches_parity(tmp_path):
    """Empty a node's data dir entirely: it bootstraps from a peer's
    checkpoint + segments + WAL over the chunked rebuild verbs, then
    catches up from the leader — zero local recovery sources needed."""
    import numpy as np

    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table q (k int primary key, v int)")
        rng = np.random.default_rng(11)
        v = rng.integers(0, 1000, 1500)
        for s in range(0, 1500, 500):
            vals = ", ".join(f"({i}, {v[i]})"
                             for i in range(s, s + 500))
            c.execute(1, f"insert into q values {vals}")
        _wait(lambda: _weak_count(c, 3, "q") == (1500, 3),
              msg="node 3 pre-wipe convergence")

        c.kill(3)
        shutil.rmtree(tmp_path / "node3", ignore_errors=True)
        c.execute(1, "insert into q values (50000, 1)")
        c.start_node(3)
        c.wait_ready(timeout=90)
        _wait(lambda: _weak_count(c, 3, "q") == (1501, 3),
              timeout=90, msg="wiped node parity")
        # bit-identical content, served by the rebuilt node
        r = c.execute(3, "select sum(v) from q", consistency="weak")
        assert r["node"] == 3
        assert c.rows(r)[0][0] == int(v.sum()) + 1
        # the rebuild is byte-accounted and names its source peer
        rec = c.clients[3].call("recovery.state")
        ev = {e["phase"]: e for e in rec["events"]}
        assert "rebuild" in ev
        assert ev["rebuild"]["bytes"] > 0
        assert ev["rebuild"]["peer"] in (1, 2)
        # gv$recovery through SQL mirrors the wire snapshot
        rows = c.rows(c.execute(
            3, "select phase, bytes from gv$recovery"
               " where phase = 'rebuild'", consistency="weak"))
        assert rows and rows[0][1] == ev["rebuild"]["bytes"]
    finally:
        c.close()


@pytest.mark.slow
def test_xa_prepared_survives_leader_failover(tmp_path):
    """Durable XA across node death: a branch prepared on the leader is
    recoverable on the SURVIVORS (they replayed its redo+prepare
    records), commits there after failover, and the restarted old
    leader converges to the committed result."""
    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table t (k int primary key, v int)")
        c.execute(1, "insert into t values (1, 10)")
        c.execute(1, "xa start 'fx1'")
        c.execute(1, "insert into t values (2, 20)")
        c.execute(1, "xa end 'fx1'")
        c.execute(1, "xa prepare 'fx1'")
        _wait(lambda: "fx1" in c.clients[3].call(
            "recovery.state")["prepared_xids"],
            timeout=20, msg="follower registers prepared branch")

        c.kill(1)
        # a survivor takes over...
        def _new_leader():
            for i in (2, 3):
                st = c.clients[i].call("palf.state", _deadline_s=1.0)
                if st["role"] == "leader":
                    return i
            return None
        _wait(lambda: _new_leader() is not None, timeout=30,
              msg="re-election")
        leader = _new_leader()
        # ...reports the branch recoverable and commits it
        assert "fx1" in c.clients[leader].call(
            "recovery.state")["prepared_xids"]
        def _commit():
            c.execute(leader, "xa commit 'fx1'")
            return True
        _wait(_commit, timeout=30, msg="xa commit after failover")
        r = c.execute(leader, "select k, v from t order by k")
        assert c.rows(r) == [(1, 10), (2, 20)]

        # the old leader restarts, replays its own prepare records,
        # then retires the branch when catch-up ships the commit
        c.start_node(1)
        c.wait_ready()
        _wait(lambda: _weak_count(c, 1, "t") == (2, 1),
              msg="old leader catch-up")
        r = c.execute(1, "select k, v from t order by k",
                      consistency="weak")
        assert c.rows(r) == [(1, 10), (2, 20)]
        _wait(lambda: c.clients[1].call(
            "recovery.state")["prepared_xids"] == [],
            timeout=20, msg="old leader retires the branch")
    finally:
        c.close()
