"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's tiering (SURVEY §4): unit tests construct operators
with synthetic inputs (≙ unittest/sql/engine fake table scan), multi-device
tests use the forced host platform mesh (≙ mittest in-process cluster).
"""

import os

# must be set before jax initializes any backend; force-override — the
# environment pins JAX_PLATFORMS to the real TPU tunnel, which unit tests
# must never touch
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize registers the real-TPU PJRT plugin in every
# interpreter and pins platform selection to it; creating that client from
# a test process would hang on / contend for the single tunnel.  Drop the
# factory before any backend is instantiated.
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
