"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's tiering (SURVEY §4): unit tests construct operators
with synthetic inputs (≙ unittest/sql/engine fake table scan), multi-device
tests use the forced host platform mesh (≙ mittest in-process cluster).
"""

import os

# must be set before jax initializes any backend; force-override — the
# environment pins JAX_PLATFORMS to the real TPU tunnel, which unit tests
# must never touch
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize registers the real-TPU PJRT plugin in every
# interpreter and pins platform selection to it; creating that client from
# a test process would hang on / contend for the single tunnel.  Drop the
# factory before any backend is instantiated.
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy multi-process cluster scenarios excluded from the "
        "tier-1 (-m 'not slow') gate")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def rewrite_outer_join_for_old_sqlite(sql: str, left: str, right: str,
                                      left_cols, right_cols) -> str:
    """RIGHT/FULL OUTER JOIN oracle queries for pre-3.39 sqlite: right
    join becomes the swapped left join; full outer becomes a left join
    UNION ALL the unmatched build rows (detected via a rowid probe).
    WHERE/GROUP BY/ORDER BY tails stay outside the rewritten join, which
    preserves their post-join semantics.  No-op on sqlite >= 3.39."""
    import re
    import sqlite3

    if sqlite3.sqlite_version_info >= (3, 39):
        return sql
    m = re.search(
        rf"from {left} (full outer|right outer|right) join {right} on "
        rf"(.+?)(?= where| order by| group by|$)", sql)
    if m is None:
        return sql
    kind, cond = m.group(1), m.group(2).strip()
    if kind in ("right", "right outer"):
        repl = f"from {right} left join {left} on {cond}"
    else:
        exposed = ", ".join(
            [f"{left}.{c} as {c}" for c in left_cols]
            + [f"{right}.{c} as {c}" for c in right_cols])
        plain = ", ".join(
            [f"{left}.{c}" for c in left_cols]
            + [f"{right}.{c}" for c in right_cols])
        repl = (f"from (select {exposed} from {left} left join {right} "
                f"on {cond} union all select {plain} from {right} left "
                f"join {left} on {cond} where {left}.rowid is null)")
    return sql.replace(m.group(0), repl)


@pytest.fixture()
def poison():
    """Poison-lane verifier (oceanbase_tpu.analysis.poison): fills
    masked-dead pad lanes with NaN/sentinel garbage so a query result
    that changes proves an operator read a dead lane."""
    from oceanbase_tpu.analysis import poison as _p

    return _p
