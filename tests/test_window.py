"""Window function tests vs the SQLite oracle (≙ window-function op tests)."""

import sqlite3

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


@pytest.fixture(scope="module")
def env(rng=np.random.default_rng(7)):
    n = 500
    dept = rng.integers(0, 5, n)
    sal = rng.integers(1000, 9000, n)
    emp = np.arange(n)
    sess = Session()
    sess.catalog.load_numpy("emp", {"eid": emp, "dept": dept, "sal": sal})
    conn = sqlite3.connect(":memory:")
    conn.execute("create table emp (eid, dept, sal)")
    conn.executemany("insert into emp values (?,?,?)",
                     list(zip(emp.tolist(), dept.tolist(), sal.tolist())))
    return sess, conn


def _both(env, sql):
    sess, conn = env
    got = sorted(sess.execute(sql).rows())
    want = sorted(tuple(r) for r in conn.execute(sql).fetchall())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9)
            else:
                assert a == b


def test_row_number(env):
    _both(env, "select eid, row_number() over "
               "(partition by dept order by sal desc, eid) as rn from emp")


def test_rank_dense_rank(env):
    _both(env, "select eid, rank() over (partition by dept order by sal) as r, "
               "dense_rank() over (partition by dept order by sal) as dr "
               "from emp")


def test_partition_aggregates(env):
    _both(env, "select eid, sum(sal) over (partition by dept) as total, "
               "count(*) over (partition by dept) as cnt, "
               "max(sal) over (partition by dept) as mx from emp")


def test_running_aggregates(env):
    _both(env, "select eid, sum(sal) over "
               "(partition by dept order by eid) as running from emp")
    # RANGE-frame peers: ties on the order key share values
    _both(env, "select eid, sum(sal) over "
               "(partition by dept order by sal) as running, "
               "min(sal) over (partition by dept order by eid) as rmin "
               "from emp")


def test_window_no_partition(env):
    _both(env, "select eid, avg(sal) over () as a, "
               "row_number() over (order by eid) as rn from emp")


def test_window_over_groupby(env):
    _both(env, "select dept, sum(sal) as s, "
               "rank() over (order by sum(sal) desc) as r "
               "from emp group by dept")
