"""Runtime & aux subsystem tests: config, tenants, observability, errsim.

≙ unittest/share config tests + omt tenant tests + virtual-table queries.
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.config import Config
from oceanbase_tpu.server.errsim import ERRSIM


def test_config_registry(tmp_path):
    p = str(tmp_path / "cfg.json")
    c = Config(persist_path=p)
    assert c["minor_compact_trigger"] == 4
    c.set("minor_compact_trigger", "8")   # string coercion
    assert c["minor_compact_trigger"] == 8
    with pytest.raises(ValueError):
        c.set("minor_compact_trigger", 0)  # validator
    with pytest.raises(KeyError):
        c.set("no_such_param", 1)
    c.set("tenant_memory_limit", "2g")     # capacity units
    assert c["tenant_memory_limit"] == 2 << 30
    # persisted + reloaded
    c2 = Config(persist_path=p)
    assert c2["minor_compact_trigger"] == 8
    # overlay falls back to parent
    t = Config(parent=c2)
    assert t["minor_compact_trigger"] == 8
    t.set("minor_compact_trigger", 16)
    assert t["minor_compact_trigger"] == 16 and c2["minor_compact_trigger"] == 8


def test_multi_tenant_isolation(tmp_path):
    db = Database(str(tmp_path / "db"))
    s_sys = db.session()
    s_sys.execute("create tenant t1")
    s1 = db.session(tenant="t1")
    s1.execute("create table x (a int)")
    s1.execute("insert into x values (1)")
    # sys tenant does not see t1's table
    with pytest.raises(Exception):
        s_sys.execute("select * from x")
    assert s1.execute("select count(*) from x").rows() == [(1,)]
    # tenant survives restart
    db.close()
    db2 = Database(str(tmp_path / "db"))
    assert "t1" in db2.tenants
    assert db2.session(tenant="t1").execute(
        "select count(*) from x").rows() == [(1,)]
    db2.close()


def test_set_and_alter_system(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("set @@x = 1") if False else None
    s.execute("set autocommit = 0")
    assert s.variables["autocommit"] == 0
    s.execute("alter system set minor_compact_trigger = 6")
    assert db.config["minor_compact_trigger"] == 6
    r = s.execute("show parameters")
    assert r.rowcount > 20
    r = s.execute("show variables")
    assert r.rowcount >= 2
    # major freeze compacts all tables
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1)")
    s.execute("alter system major freeze")
    assert db.engine.tables["t"].tablet.segments[-1].level == 2
    assert s.execute("select v from t").rows() == [(1,)]
    db.close()


def test_virtual_tables_via_sql(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("insert into t values (1), (2)")
    s.execute("select count(*) from t")
    # audit has the statements above
    r = s.execute("select sql, rows_returned from gv$sql_audit")
    assert r.rowcount >= 3
    # tables inventory
    r = s.execute("select table_name, row_count from v$tables "
                  "where tenant = 'sys' order by table_name")
    assert ("t", 2) in r.rows()
    # palf replica states
    r = s.execute("select role, count(*) as n from v$palf group by role "
                  "order by role")
    rows = dict(r.rows())
    assert rows.get("leader") == 1 and rows.get("follower") == 2
    # parameters
    r = s.execute("select value from v$parameters "
                  "where name = 'wal_replica_count'")
    assert r.rowcount == 1
    db.close()


def test_analyze_updates_stats(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, g int)")
    s.execute("insert into t values (1, 1), (2, 1), (3, 2)")
    s.execute("analyze table t")
    td = db.catalog.table_def("t")
    assert td.row_count == 3
    assert td.ndv["g"] == 2 and td.ndv["k"] == 3
    db.close()


def test_errsim_injection(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    ERRSIM.arm("tx.commit", error=RuntimeError("injected"), count=1)
    try:
        with pytest.raises(RuntimeError, match="injected"):
            s.execute("insert into t values (1)")
        # budget consumed: next statement passes, the failed one rolled back
        s.execute("insert into t values (1)")
        assert s.execute("select count(*) from t").rows() == [(1,)]
        r = s.execute("select tracepoint, fired from v$errsim")
        assert ("tx.commit", 1) in r.rows()
    finally:
        ERRSIM.reset()
    db.close()


def test_ash_sampling(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int)")
    s._ash_state.update(active=True, sql="select 1", state="executing")
    db.ash.sample_once()
    s._ash_state.update(active=False)
    r = s.execute("select sql, state from v$session_history")
    assert r.rowcount >= 1
    db.close()
