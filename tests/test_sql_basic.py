"""SQL end-to-end basics: DDL, DML, simple queries (≙ mysqltest smoke)."""

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


@pytest.fixture()
def sess():
    return Session()


def test_create_insert_select(sess):
    sess.execute("create table t (a int primary key, b varchar(20), "
                 "c decimal(10,2), d date)")
    sess.execute("insert into t values (1, 'x', 1.50, '2020-01-05'), "
                 "(2, 'y', 2.25, '2021-06-01'), (3, null, 0.75, '2020-01-05')")
    r = sess.execute("select a, b, c from t where c > 1.00 order by a")
    assert r.rows() == [(1, "x", 1.5), (2, "y", 2.25)]

    r = sess.execute("select count(*), sum(c) from t")
    assert r.rows() == [(3, 4.5)]

    r = sess.execute("select b, count(*) as n from t group by b order by n desc, b")
    rows = r.rows()
    assert len(rows) == 3  # 'x', 'y', NULL are distinct groups

    r = sess.execute("select a from t where b is null")
    assert r.rows() == [(3,)]


def test_update_delete(sess):
    sess.execute("create table u (k int, v int)")
    sess.execute("insert into u values (1, 10), (2, 20), (3, 30)")
    r = sess.execute("update u set v = v + 5 where k >= 2")
    assert r.rowcount == 2
    r = sess.execute("select sum(v) from u")
    assert r.rows() == [(70,)]
    r = sess.execute("delete from u where k = 1")
    assert r.rowcount == 1
    assert sess.execute("select count(*) from u").rows() == [(2,)]


def test_joins_sql(sess):
    sess.execute("create table dept (id int primary key, dname varchar(10))")
    sess.execute("create table emp (eid int, did int, sal int)")
    sess.execute("insert into dept values (1, 'eng'), (2, 'ops')")
    sess.execute("insert into emp values (1, 1, 100), (2, 1, 200), (3, 2, 50), (4, 9, 10)")
    r = sess.execute("select dname, sum(sal) as total from emp, dept "
                     "where did = id group by dname order by total desc")
    assert r.rows() == [("eng", 300), ("ops", 50)]
    # left join keeps unmatched emp
    r = sess.execute("select eid, dname from emp left join dept on did = id "
                     "order by eid")
    rows = r.rows()
    assert rows[3] == (4, None)


def test_subqueries_sql(sess):
    sess.execute("create table t1 (a int, b int)")
    sess.execute("insert into t1 values (1, 10), (2, 20), (3, 30)")
    sess.execute("create table t2 (x int)")
    sess.execute("insert into t2 values (2), (3), (5)")
    r = sess.execute("select a from t1 where a in (select x from t2) order by a")
    assert r.rows() == [(2,), (3,)]
    r = sess.execute("select a from t1 where not exists "
                     "(select * from t2 where x = a) order by a")
    assert r.rows() == [(1,)]
    r = sess.execute("select a from t1 where b > (select avg(b) from t1) order by a")
    assert r.rows() == [(3,)]


def test_setops_sql(sess):
    sess.execute("create table s1 (v int)")
    sess.execute("insert into s1 values (1), (2), (2), (3)")
    sess.execute("create table s2 (v int)")
    sess.execute("insert into s2 values (2), (4)")
    r = sess.execute("select v from s1 union select v from s2 order by v")
    assert r.rows() == [(1,), (2,), (3,), (4,)]
    r = sess.execute("select v from s1 union all select v from s2 order by v")
    assert len(r.rows()) == 6
    r = sess.execute("select v from s1 intersect select v from s2")
    assert r.rows() == [(2,)]
    r = sess.execute("select v from s1 except select v from s2 order by v")
    assert r.rows() == [(1,), (3,)]


def test_explain_show_describe(sess):
    sess.execute("create table e (a int, b varchar(5))")
    r = sess.execute("explain select a from e where b = 'x'")
    assert "TableScan" in r.plan_text
    assert "Filter" in r.plan_text
    names = sess.execute("show tables").arrays["table_name"]
    assert "e" in list(names)
    d = sess.execute("describe e")
    assert d.rowcount == 2


def test_params(sess):
    sess.execute("create table p (a int, b int)")
    sess.execute("insert into p values (1, 2), (3, 4)")
    r = sess.execute("select b from p where a = ?", params=[3])
    assert r.rows() == [(4,)]


def test_distinct_and_case(sess):
    sess.execute("create table dc (g varchar(2), v int)")
    sess.execute("insert into dc values ('a', 1), ('a', 2), ('b', 3)")
    r = sess.execute("select distinct g from dc order by g")
    assert r.rows() == [("a",), ("b",)]
    r = sess.execute(
        "select g, sum(case when v > 1 then v else 0 end) as s "
        "from dc group by g order by g")
    assert r.rows() == [("a", 2), ("b", 3)]
