"""Fault-injection plane, per-verb deadline/retry policies, failure
detector (net/faults.py, net/rpc.py, net/health.py).

≙ the errsim net-error mittest suites: deterministic (seeded) message
loss / corruption / delay against the rpc frame, plus the breaker state
machine the routing layers consult.  Everything here is in-process —
real sockets, no subprocesses — so it runs as the fast chaos smoke of
the tier-1 suite.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import pytest

from oceanbase_tpu.net.faults import FaultDrop, FaultPlane
from oceanbase_tpu.net.health import HealthMonitor
from oceanbase_tpu.net.rpc import (
    DeadlineExceeded,
    POLICIES,
    RpcClient,
    RpcError,
    RpcServer,
    verb_policy,
)

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


class EchoServer:
    """RpcServer with counting handlers, on an ephemeral port."""

    def __init__(self, faults=None):
        self.calls: dict[str, int] = {}

        def make(name):
            def h(**kw):
                self.calls[name] = self.calls.get(name, 0) + 1
                return kw.get("x", "pong" if name == "ping" else None)
            return h

        handlers = {n: make(n) for n in
                    ("ping", "das.scan", "sql.execute", "node.state")}
        self.server = RpcServer("127.0.0.1", 0, handlers, faults=faults)
        self.server.start()
        self.port = self.server.port

    def stop(self):
        self.server.stop()


@pytest.fixture()
def echo():
    s = EchoServer()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# FaultPlane unit
# ---------------------------------------------------------------------------


def _fire_pattern(seed, n=300):
    fp = FaultPlane(seed=seed)
    fp.inject("send", "drop", verb="v", prob=0.3)
    out = []
    for _ in range(n):
        try:
            fp.act("send", "v", None)
            out.append(0)
        except FaultDrop:
            out.append(1)
    return out


def test_fault_plane_seed_determinism():
    a, b = _fire_pattern(7), _fire_pattern(7)
    assert a == b  # same seed -> frame-for-frame identical schedule
    assert sum(a) > 0
    assert _fire_pattern(8) != a  # and the seed actually matters


def test_fault_rule_nth_count_and_clear():
    fp = FaultPlane(seed=0)
    rid = fp.inject("send", "drop", verb="v", nth=3)
    fp.act("send", "v")
    fp.act("send", "v")
    with pytest.raises(FaultDrop):
        fp.act("send", "v")
    fp.act("send", "v")  # nth fires exactly once
    assert fp.clear(rid) == 1

    fp.inject("send", "drop", count=2)
    for _ in range(2):
        with pytest.raises(FaultDrop):
            fp.act("send", "anything")
    fp.act("send", "anything")  # budget exhausted
    # peer matching: rules scoped to another peer never fire
    fp.clear()
    fp.inject("send", "drop", peer=2)
    fp.act("send", "v", 3)
    with pytest.raises(FaultDrop):
        fp.act("send", "v", 2)
    fp.clear()


def test_garble_recv_rejected():
    # the server consults the plane after decode — recv-garble would be
    # a silently armed no-op, so the plane refuses it outright
    fp = FaultPlane(seed=0)
    with pytest.raises(ValueError):
        fp.inject("recv", "garble")


def test_injected_delay_burns_deadline(echo):
    """A send-side delay models network latency: it must count against
    the verb deadline, not stall the caller and then run anyway."""
    fp = FaultPlane(seed=0)
    cli = RpcClient("127.0.0.1", echo.port, faults=fp, peer_id=9)
    fp.delay(600.0, verb="ping", where="send")
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        cli.call("ping", _deadline_s=0.5)
    assert time.monotonic() - t0 < 2.0  # no post-delay dial-and-run
    cli.close()


def test_fault_plane_garble_and_delay():
    fp = FaultPlane(seed=0)
    fp.garble_frame(verb="v", where="reply")
    body = b"x" * 64
    garbled = fp.act("reply", "v", None, body)
    assert garbled != body and len(garbled) == len(body)
    fp.clear()
    fp.delay(30.0, verb="v", where="send")
    t0 = time.monotonic()
    fp.act("send", "v")
    assert time.monotonic() - t0 >= 0.025


# ---------------------------------------------------------------------------
# policy table
# ---------------------------------------------------------------------------


def test_policy_table_shape():
    # reads / state probes / the term-checked palf protocol may resend;
    # anything carrying DML must never be resent once on the wire
    for verb in ("ping", "das.scan", "das.pull", "dtl.execute",
                 "palf.state", "node.state"):
        pol = verb_policy(verb)
        assert pol.idempotent and pol.max_retries >= 1, verb
    assert not verb_policy("sql.execute").idempotent
    assert not verb_policy("unknown.verb").idempotent
    for verb, pol in POLICIES.items():
        assert pol.deadline_s > 0, verb
        if not pol.idempotent:
            assert pol.max_retries == 0, verb


# ---------------------------------------------------------------------------
# rpc client: pool, deadlines, resync, resend discipline
# ---------------------------------------------------------------------------


def test_pool_no_head_of_line_blocking(echo):
    """A slow bulk call must not queue control-plane pings behind it
    (the old single-connection client serialized the full round-trip)."""
    ev = threading.Event()

    def slow(**kw):
        ev.wait(2.0)
        return "done"

    echo.server.register("das.pull", slow)
    cli = RpcClient("127.0.0.1", echo.port)
    th = threading.Thread(target=lambda: cli.call("das.pull"))
    th.start()
    time.sleep(0.05)  # the slow call owns its pooled connection now
    t0 = time.monotonic()
    assert cli.ping()
    assert time.monotonic() - t0 < 0.5
    ev.set()
    th.join()
    cli.close()


def test_oversized_frame_closes_connection(echo):
    """A bogus length prefix desynchronizes the stream; the server must
    drop the connection (not read garbage as the next frame) and keep
    serving fresh connections."""
    raw = socket.create_connection(("127.0.0.1", echo.port), timeout=5)
    raw.sendall(struct.pack("<I", (1 << 30) + 1) + b"junk")
    raw.settimeout(5)
    assert raw.recv(1) == b""  # server closed on the protocol error
    raw.close()
    cli = RpcClient("127.0.0.1", echo.port)
    assert cli.ping()  # and the server is still healthy
    cli.close()


def test_garbled_reply_resyncs_and_retries(echo):
    """Corrupted reply frame -> the client closes the desynchronized
    connection; an idempotent verb transparently retries clean."""
    fp = FaultPlane(seed=0)
    echo.server.faults = fp
    cli = RpcClient("127.0.0.1", echo.port, faults=fp, peer_id=9)
    fp.garble_frame(verb="das.scan", where="reply", nth=1)
    assert cli.call("das.scan", x=11) == 11
    assert echo.calls["das.scan"] == 2  # executed, garbled, re-executed
    # a non-idempotent verb surfaces the protocol failure instead
    fp.clear()
    fp.garble_frame(verb="sql.execute", where="reply", nth=1)
    with pytest.raises(RpcError) as ei:
        cli.call("sql.execute", x=1)
    assert ei.value.kind == "Protocol"
    assert echo.calls["sql.execute"] == 1  # never re-executed
    cli.close()


def test_non_idempotent_reply_loss_never_double_executes(echo):
    """The lost-reply case: the handler RAN; a non-idempotent verb must
    surface the error — never resend (≙ the no-retry rule for DML)."""
    fp = FaultPlane(seed=0)
    echo.server.faults = fp
    cli = RpcClient("127.0.0.1", echo.port, faults=fp, peer_id=9)
    fp.inject("reply", "reset", verb="sql.execute", nth=1)
    with pytest.raises((ConnectionError, OSError)):
        cli.call("sql.execute", x=1)
    assert echo.calls["sql.execute"] == 1, "resent non-idempotent work"
    # the same loss on an idempotent verb is retried to success
    fp.clear()
    fp.inject("reply", "reset", verb="das.scan", nth=1)
    assert cli.call("das.scan", x=5) == 5
    assert echo.calls["das.scan"] == 2
    cli.close()


def test_send_drop_retry_budget(echo):
    fp = FaultPlane(seed=0)
    cli = RpcClient("127.0.0.1", echo.port, faults=fp, peer_id=9)
    pol = verb_policy("das.scan")
    fp.inject("send", "drop", verb="das.scan", count=pol.max_retries)
    assert cli.call("das.scan", x=3) == 3  # absorbed by the budget
    fp.clear()
    fp.inject("send", "drop", verb="das.scan",
              count=pol.max_retries + 1)
    with pytest.raises(ConnectionError):
        cli.call("das.scan", x=3)  # one past the budget
    cli.close()


def test_deadline_fail_fast_on_silent_loss(echo):
    """A request swallowed in the network (server recv-drop): the caller
    cannot know, so it must ride its DEADLINE — not a 10 s socket
    default — and fail with DeadlineExceeded."""
    fp = FaultPlane(seed=0)
    echo.server.faults = fp
    cli = RpcClient("127.0.0.1", echo.port, faults=fp, peer_id=9)
    fp.inject("recv", "drop", verb="sql.execute")
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        cli.call("sql.execute", x=1, _deadline_s=0.3)
    assert time.monotonic() - t0 < 1.5
    assert isinstance(DeadlineExceeded("x"), OSError)  # old except paths
    cli.close()


# ---------------------------------------------------------------------------
# failure detector
# ---------------------------------------------------------------------------


def test_breaker_state_transitions():
    mon = HealthMonitor(1, {}, suspect_after=2, down_after=4)
    mon.observer(2)  # registers the peer
    assert mon.state(2) == "up"
    mon.record_failure(2)
    assert mon.state(2) == "up"
    mon.record_failure(2)
    assert mon.state(2) == "suspect"
    mon.record_failure(2)
    mon.record_failure(2)
    assert mon.state(2) == "down"
    row = mon.snapshot()[0]
    assert row["consecutive_failures"] == 4
    assert row["breaker_opens"] == 1  # one departure from "up"
    mon.record_success(2, 0.001)
    assert mon.state(2) == "up"
    assert mon.snapshot()[0]["consecutive_failures"] == 0
    # rtt ewma moves with samples
    mon.record_success(2, 0.010)
    assert 0 < mon.snapshot()[0]["rtt_ewma_ms"] < 10.0


def test_on_down_fires_once_per_episode():
    fired = []
    mon = HealthMonitor(1, {}, suspect_after=1, down_after=2,
                        on_down=fired.append)
    mon.observer(3)
    for _ in range(6):
        mon.record_failure(3)
    assert fired == [3]  # not re-fired while already down
    mon.record_success(3, 0.001)
    for _ in range(2):
        mon.record_failure(3)
    assert fired == [3, 3]  # a fresh episode fires again


def test_observer_counters_feed_breaker(echo):
    mon = HealthMonitor(1, {}, suspect_after=2, down_after=3)
    cli = RpcClient("127.0.0.1", echo.port, observer=mon.observer(2))
    assert cli.ping()
    assert mon.state(2) == "up"
    assert mon.snapshot()[0]["successes"] == 1
    # now point at a dead port: failures accumulate through the breaker
    dead = RpcClient("127.0.0.1", 1, timeout_s=0.2,
                     observer=mon.observer(5))
    assert not dead.ping(_deadline_s=0.3)
    st = {r["peer"]: r for r in mon.snapshot()}
    assert st[5]["failures"] >= 1
    assert st[5]["retries"] >= 1  # ping's policy retried inside ping()
    cli.close()


def test_heartbeat_detects_death_and_recovery():
    srv = EchoServer()
    port = srv.port
    mon = HealthMonitor(1, {2: RpcClient("127.0.0.1", port,
                                         timeout_s=0.2)},
                        interval_s=0.05, suspect_after=2, down_after=4)
    mon.start()
    try:
        deadline = time.monotonic() + 3
        while mon.state(2) != "up" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.state(2) == "up"
        srv.stop()
        # detection latency ~ interval * down_threshold (+ rpc retries)
        deadline = time.monotonic() + 4
        while mon.state(2) != "down" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.state(2) == "down"
        # the breaker half-opens via the heartbeat: recovery -> up
        srv2 = RpcServer("127.0.0.1", port,
                         {"ping": lambda: "pong"})
        srv2.start()
        try:
            deadline = time.monotonic() + 4
            while mon.state(2) != "up" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert mon.state(2) == "up"
        finally:
            srv2.stop()
    finally:
        mon.stop()


# ---------------------------------------------------------------------------
# deterministic chaos smoke (tier-1; seeded, in-process, < 10 s)
# ---------------------------------------------------------------------------


def test_chaos_smoke_deterministic_seed():
    """Nemesis cocktail on an idempotent verb — drops, delays, garbled
    replies, connection resets — with a FIXED seed: every call still
    returns the right answer inside its deadline, and the schedule
    replays identically."""

    def run(seed):
        fp = FaultPlane(seed=seed)
        srv = EchoServer(faults=fp)
        cli = RpcClient("127.0.0.1", srv.port, faults=fp, peer_id=2)
        fp.inject("send", "drop", verb="das.scan", prob=0.15)
        fp.inject("reply", "garble", verb="das.scan", prob=0.10)
        fp.inject("reply", "reset", verb="das.scan", prob=0.05)
        fp.delay(1.0, verb="das.scan", prob=0.3, where="recv")
        t0 = time.monotonic()
        oks = 0
        for i in range(40):
            if cli.call("das.scan", x=i, _deadline_s=5.0) == i:
                oks += 1
        elapsed = time.monotonic() - t0
        fired = tuple(r["fired"] for r in fp.rules())
        cli.close()
        srv.stop()
        return oks, fired, elapsed

    # seed 7: a schedule where every failure streak stays inside the
    # das.scan retry budget (other seeds legitimately exhaust it — the
    # budget is finite by design; the point here is determinism)
    oks, fired, elapsed = run(7)
    assert oks == 40          # parity: every answer correct
    assert sum(fired) > 0     # the nemesis actually fired
    assert elapsed < 8.0      # bounded: nobody rode a 10 s socket stall
    oks2, fired2, _ = run(7)
    assert (oks2, fired2) == (oks, fired)  # frame-for-frame replay
