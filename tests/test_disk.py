"""Disk-pressure plane: write-error fault injection, crash-safe
unwind, per-surface budgets, and read-only degradation
(net/faults.py disk errno rules, server/diskmgr.py, the durable
writers in palf/log.py / storage/engine.py / storage/tmpfile.py /
server/backup.py).

≙ the reference's errsim disk-error suites (ENOSPC/EIO injection in
the log engine and sstable writers) plus the log-disk guard tests:
``log_disk_utilization_threshold`` crossing → checkpoint + recycle
reclaim → tenant read-only → auto-exit.  Every fault is seeded and
one-shot; every faulted surface is followed by a restart/reopen that
must land on the unfaulted oracle state (no torn artifacts).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from oceanbase_tpu.catalog import ColumnDef, TableDef
from oceanbase_tpu.net.faults import FaultPlane
from oceanbase_tpu.palf.log import PalfReplica
from oceanbase_tpu.server import Database
from oceanbase_tpu.server.diskmgr import (
    DiskFull,
    DiskIOError,
    DiskManager,
    SpillBudgetExceeded,
    TenantReadOnly,
)
from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.storage.engine import StorageEngine
from oceanbase_tpu.storage.tmpfile import TempFileStore

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _tdef(name="t"):
    return TableDef(name, [ColumnDef("k", SqlType.int_()),
                           ColumnDef("v", SqlType.int_())],
                    primary_key=["k"])


def _du(paths):
    total = 0
    for root in paths:
        if os.path.isfile(root):
            total += os.path.getsize(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    pass
    return total


def _leader(tmp_path, n_entries=0):
    r = PalfReplica(0, log_dir=str(tmp_path / "wal"))
    r.role = "leader"
    r.current_term = 1
    if n_entries:
        r.leader_append([f"e{i}".encode() for i in range(n_entries)])
    return r


def _cfg(**kw):
    cfg = {"log_disk_limit_bytes": 0, "data_disk_limit_bytes": 0,
           "spill_disk_limit_bytes": 0,
           "log_disk_utilization_threshold": 80}
    cfg.update(kw)
    return cfg


# ---------------------------------------------------------------------------
# FaultPlane: the disk errno family
# ---------------------------------------------------------------------------


def test_disk_errno_rules_validate_and_scope():
    fp = FaultPlane(seed=0)
    # errno actions live on the disk plane only
    with pytest.raises(ValueError):
        fp.inject("send", "enospc")
    with pytest.raises(ValueError):
        fp.disk("enospc", kind="nonsense")
    # kind scoping: a wal rule never fires for segment writes
    fp.disk("enospc", kind="wal")
    assert fp.check_write("segment", "/x") is None
    with pytest.raises(OSError) as ei:
        fp.check_write("wal", "/x")
    import errno as _errno

    assert ei.value.errno == _errno.ENOSPC
    # one-shot by default: the budget is spent
    assert fp.check_write("wal", "/x") is None


def test_disk_partial_rule_is_seeded_and_bounded():
    fp = FaultPlane(seed=7)
    fp.disk("partial", kind="wal", seed=7)
    cut = None
    with pytest.raises(OSError):
        # the writer persists cut bytes then raises; without nbytes the
        # plane degrades to a plain ENOSPC raise
        fp.check_write("wal", "/x")
    fp2 = FaultPlane(seed=7)
    fp2.disk("partial", kind="wal", seed=7)
    cut = fp2.check_write("wal", "/x", nbytes=1000)
    assert cut is not None and 1 <= cut < 1000
    fp3 = FaultPlane(seed=7)
    fp3.disk("partial", kind="wal", seed=7)
    assert fp3.check_write("wal", "/x", nbytes=1000) == cut  # seeded


# ---------------------------------------------------------------------------
# WAL (palf/log.py::_persist): typed errors + crash-safe unwind
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("action,exc_type", [
    ("enospc", DiskFull), ("eio", DiskIOError)])
def test_wal_errno_fault_typed_and_unwound(tmp_path, action, exc_type):
    r = _leader(tmp_path, n_entries=4)
    pre_size = os.path.getsize(r._log_path())
    pre_last = r.last_lsn()
    fp = FaultPlane(seed=1)
    fp.disk(action, kind="wal")
    r.faults = fp
    with pytest.raises(exc_type):
        r.leader_append([b"doomed"])
    # memory did not run ahead of the failed durable append
    assert r.last_lsn() == pre_last
    assert os.path.getsize(r._log_path()) == pre_size
    # the one-shot budget is spent: the next append goes through
    r.leader_append([b"after"])
    r.close()
    r2 = PalfReplica(0, log_dir=str(tmp_path / "wal"))
    assert r2.last_lsn() == pre_last + 1
    assert r2.entries[-1].payload == b"after"
    r2.close()


def test_wal_partial_write_truncates_back_no_torn_entry(tmp_path):
    r = _leader(tmp_path, n_entries=3)
    pre_size = os.path.getsize(r._log_path())
    oracle = [(e.term, e.lsn, e.payload) for e in r.entries]
    fp = FaultPlane(seed=5)
    fp.disk("partial", kind="wal", seed=5)
    r.faults = fp
    with pytest.raises(DiskFull):
        r.leader_append([b"x" * 512, b"y" * 512])
    # the torn half-batch was physically truncated back
    assert os.path.getsize(r._log_path()) == pre_size
    assert r.last_lsn() == 3
    r.close()
    # restart lands on the unfaulted oracle, and keeps working
    r2 = PalfReplica(0, log_dir=str(tmp_path / "wal"))
    assert [(e.term, e.lsn, e.payload) for e in r2.entries] == oracle
    r2.role, r2.current_term = "leader", 1
    r2.leader_append([b"clean"])
    assert r2.last_lsn() == 4
    r2.close()


# ---------------------------------------------------------------------------
# slog / manifest / segment (storage/engine.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("action,exc_type", [
    ("enospc", DiskFull), ("eio", DiskIOError)])
def test_slog_fault_typed_and_restart_clean(tmp_path, action, exc_type):
    root = str(tmp_path / "db")
    eng = StorageEngine(root)
    eng.create_table(_tdef("t1"))
    fp = FaultPlane(seed=2)
    fp.disk(action, kind="slog")
    eng.faults = fp
    with pytest.raises(exc_type):
        eng.create_table(_tdef("t2"))
    # the slog carries no torn record: reopen replays cleanly and sees
    # only the durable table
    eng2 = StorageEngine(root)
    assert "t1" in eng2.tables and "t2" not in eng2.tables
    eng2.create_table(_tdef("t2"))
    eng3 = StorageEngine(root)
    assert set(eng3.tables) >= {"t1", "t2"}


def test_manifest_fault_keeps_previous_generation(tmp_path):
    root = str(tmp_path / "db")
    eng = StorageEngine(root)
    eng.create_table(_tdef())
    eng.bulk_load("t", {"k": np.arange(50), "v": np.arange(50) * 2})
    eng.checkpoint()  # generation 1
    eng.create_table(_tdef("u"))
    fp = FaultPlane(seed=3)
    fp.disk("enospc", kind="manifest")
    eng.faults = fp
    with pytest.raises(DiskFull):
        eng.checkpoint()
    # no torn tmp left behind; the previous generation is intact and
    # the slog (NOT truncated by the failed checkpoint) still carries u
    assert not os.path.exists(eng._manifest_path() + ".tmp")
    eng2 = StorageEngine(root)
    assert set(eng2.tables) >= {"t", "u"}
    a, _ = eng2.tables["t"].tablet.snapshot_arrays(snapshot=10)
    assert len(a["k"]) == 50
    # the budget is spent: the retry checkpoint publishes atomically
    eng.checkpoint()
    eng3 = StorageEngine(root)
    assert set(eng3.tables) >= {"t", "u"}


@pytest.mark.parametrize("action,exc_type", [
    ("enospc", DiskFull), ("eio", DiskIOError)])
def test_segment_fault_no_torn_file(tmp_path, action, exc_type):
    root = str(tmp_path / "db")
    eng = StorageEngine(root)
    eng.create_table(_tdef())
    eng.bulk_load("t", {"k": np.arange(100), "v": np.arange(100)})
    eng.checkpoint()
    ts = eng.tables["t"]
    ts.tablet.write((500,), "insert", {"k": 500, "v": 1}, tx_id=1)
    ts.tablet.commit(1, 5, [(500,)])
    fp = FaultPlane(seed=4)
    fp.disk(action, kind="segment")
    eng.faults = fp
    with pytest.raises(exc_type):
        eng.freeze_and_flush("t", snapshot=10)
    segdir = os.path.join(root, "segments")
    assert not [f for f in os.listdir(segdir) if f.endswith(".tmp")]
    # the durable prefix reopens oracle-identical
    eng2 = StorageEngine(root)
    a, _ = eng2.tables["t"].tablet.snapshot_arrays(snapshot=10)
    assert len(a["k"]) == 100


def test_segment_fault_pending_retry_persists(tmp_path):
    """A failed segment save parks the seg (memory keeps serving it)
    and the NEXT flush/checkpoint re-persists — the manifest never
    references a file that does not exist."""
    root = str(tmp_path / "db")
    eng = StorageEngine(root)
    eng.create_table(_tdef())
    ts = eng.tables["t"]
    ts.tablet.write((1,), "insert", {"k": 1, "v": 10}, tx_id=1)
    ts.tablet.commit(1, 5, [(1,)])
    fp = FaultPlane(seed=11)
    fp.disk("enospc", kind="segment")
    eng.faults = fp
    with pytest.raises(DiskFull):
        eng.freeze_and_flush("t", snapshot=10)
    assert eng._pending_segs  # parked, not lost
    # the live engine still serves the row (memory is authoritative)
    a, _ = ts.tablet.snapshot_arrays(snapshot=10)
    assert list(a["k"]) == [1]
    # checkpoint drains the pending persist first, then publishes a
    # manifest that references only on-disk files
    eng.checkpoint()
    assert not eng._pending_segs
    eng2 = StorageEngine(root)
    a, _ = eng2.tables["t"].tablet.snapshot_arrays(snapshot=10)
    assert list(a["k"]) == [1]


# ---------------------------------------------------------------------------
# spill (storage/tmpfile.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("action,exc_type", [
    ("enospc", DiskFull), ("eio", DiskIOError)])
def test_spill_fault_typed_no_residue(tmp_path, action, exc_type):
    fp = FaultPlane(seed=6)
    fp.disk(action, kind="spill")
    with TempFileStore(str(tmp_path / "spill"), faults=fp) as store:
        rid = store.new_run()
        arrays = {"x": np.arange(64, dtype=np.int64)}
        with pytest.raises(exc_type):
            store.append_chunk(rid, arrays)
        # no chunk (or tmp) published for the failed append
        assert store.run(rid).n_chunks == 0
        assert not os.listdir(store._chunk_dir(rid))
        # budget spent: spilling continues
        store.append_chunk(rid, arrays)
        (got, _), = list(store.read_chunks(rid))
        np.testing.assert_array_equal(got["x"], arrays["x"])


def test_spill_budget_kills_statement_only(tmp_path):
    dm = DiskManager(_cfg(spill_disk_limit_bytes=1), paths={},
                     poll_interval_s=0.0)
    big = {"x": np.random.default_rng(0).integers(0, 1 << 30, 4096)}
    with TempFileStore(str(tmp_path / "spill"), budget=dm,
                       label="stmt-1") as store:
        rid = store.new_run()
        with pytest.raises(SpillBudgetExceeded):
            store.append_chunk(rid, big)
        # the rejected chunk left no file AND no phantom accounting
        assert not os.listdir(store._chunk_dir(rid))
        assert dm.usage("spill") == 0
        assert dm.spill_rejections == 1
    # the durable surface was never involved
    assert not dm.read_only
    dm.admit_write()  # writes still admitted


def test_spill_accounting_admit_release_and_stats(tmp_path):
    dm = DiskManager(_cfg(spill_disk_limit_bytes=1 << 20), paths={})
    arrays = {"x": np.arange(256, dtype=np.int64)}
    with TempFileStore(str(tmp_path / "s"), budget=dm,
                       label="select heavy") as store:
        rid = store.new_run()
        store.append_chunk(rid, arrays)
        used = dm.usage("spill")
        assert used > 0
        rows = dm.stats(tenant="sys")
        stmt = [r for r in rows if r["surface"] == "spill_stmt"]
        assert stmt and stmt[0]["detail"] == "select heavy"
        assert stmt[0]["used_bytes"] == used
        store.close_run(rid)
        assert dm.usage("spill") == 0


# ---------------------------------------------------------------------------
# backup (server/backup.py)
# ---------------------------------------------------------------------------


def test_backup_enospc_typed_and_retry_restores(tmp_path):
    from oceanbase_tpu.server.backup import full_backup, restore_chain

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i * 3})" for i in range(500)))
    fp = FaultPlane(seed=8)
    fp.disk("enospc", kind="backup")
    db.faults = fp
    dest = str(tmp_path / "b0")
    with pytest.raises(DiskFull):
        full_backup(db, dest)
    assert not os.path.exists(dest)  # no half backup left behind
    full = full_backup(db, dest)  # budget spent: retry succeeds
    db.close()
    target = str(tmp_path / "restored")
    restore_chain(full, target)
    db2 = Database(target)
    got = db2.session().execute("select count(*), sum(v) from t").rows()
    assert got[0] == (500, sum(i * 3 for i in range(500)))
    db2.close()


def test_wal_archive_eio_typed(tmp_path):
    from oceanbase_tpu.server.backup import archive_wal

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("insert into t values (1), (2)")
    fp = FaultPlane(seed=9)
    fp.disk("eio", kind="backup")
    db.faults = fp
    with pytest.raises(DiskIOError):
        archive_wal(db, str(tmp_path / "arch"))
    archive_wal(db, str(tmp_path / "arch"))  # budget spent
    db.close()


# ---------------------------------------------------------------------------
# DiskManager: budgets, reclaim, read-only enter/auto-exit
# ---------------------------------------------------------------------------


def test_diskmgr_readonly_enter_and_autoexit(tmp_path):
    d = str(tmp_path / "log")
    os.makedirs(d)
    f = os.path.join(d, "wal.log")
    with open(f, "wb") as fh:
        fh.write(b"x" * 1000)
    events = []
    cfg = _cfg(log_disk_limit_bytes=500)
    dm = DiskManager(cfg, paths={"log": [d]},
                     reclaim_cb=lambda: events.append("reclaim"),
                     on_readonly=lambda s: events.append(f"ro:{s}"),
                     on_exit_readonly=lambda: events.append("exit"),
                     poll_interval_s=0.0, reclaim_backoff_s=0.0)
    dm.poll(force=True)
    # reclaim was tried first; it freed nothing, so read-only followed
    assert events[:2] == ["reclaim", "ro:log"]
    assert dm.read_only and dm.state("log") == "readonly"
    with pytest.raises(TenantReadOnly):
        dm.admit_write()
    assert dm.write_rejections == 1
    # space frees up -> the next poll auto-exits
    with open(f, "wb") as fh:
        fh.write(b"x" * 100)
    dm.poll(force=True)
    assert not dm.read_only and "exit" in events
    dm.admit_write()


def test_diskmgr_reclaim_avoids_readonly(tmp_path):
    d = str(tmp_path / "log")
    os.makedirs(d)
    f = os.path.join(d, "wal.log")
    with open(f, "wb") as fh:
        fh.write(b"x" * 900)

    def reclaim():  # the aggressive checkpoint + WAL recycle analog
        with open(f, "wb") as fh:
            fh.write(b"x" * 100)

    dm = DiskManager(_cfg(log_disk_limit_bytes=1000),
                     paths={"log": [d]}, reclaim_cb=reclaim,
                     poll_interval_s=0.0, reclaim_backoff_s=0.0)
    dm.poll(force=True)
    assert dm.reclaims == 1
    assert not dm.read_only
    dm.admit_write()


def test_diskmgr_data_surface_readonly(tmp_path):
    d = str(tmp_path / "data")
    os.makedirs(d)
    with open(os.path.join(d, "seg.npz"), "wb") as fh:
        fh.write(b"x" * 400)
    cfg = _cfg(data_disk_limit_bytes=300)
    dm = DiskManager(cfg, paths={"data": [d]}, poll_interval_s=0.0)
    dm.poll(force=True)
    assert dm.read_only and dm.readonly_surface == "data"
    cfg["data_disk_limit_bytes"] = 10_000
    dm.poll(force=True)
    assert not dm.read_only


# ---------------------------------------------------------------------------
# tenant-level degradation (server/tenant.py wiring + gv$disk)
# ---------------------------------------------------------------------------


def test_tenant_log_budget_readonly_reads_serve_then_autoexit(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i})" for i in range(200)))
    dm = db.tenant("sys").diskmgr
    s.execute("alter system set log_disk_limit_bytes = 10")
    dm.poll(force=True)
    # reclaim (checkpoint + recycle) ran first but 10 bytes is
    # unreachable -> read-only
    assert dm.reclaims >= 1 and dm.read_only
    with pytest.raises(TenantReadOnly):
        s.execute("insert into t values (9001, 1)")
    # reads keep serving in read-only (writes shed, not the tenant)
    assert s.execute("select count(*) from t").rows()[0][0] == 200
    rows = s.execute(
        "select surface, state from gv$disk"
        " where surface = 'log'").rows()
    assert rows == [("log", "readonly")]
    # the reclaim actually shrank the wal (recycle dropped the prefix)
    assert _du(dm.paths["log"]) < 10_000
    s.execute("alter system set log_disk_limit_bytes = 0")
    dm.poll(force=True)
    assert not dm.read_only and dm.readonly_exits >= 1
    s.execute("insert into t values (9001, 1)")
    assert s.execute("select count(*) from t").rows()[0][0] == 201
    db.close()
    # restart after the whole episode is oracle-identical
    db2 = Database(str(tmp_path / "db"))
    assert db2.session().execute(
        "select count(*) from t").rows()[0][0] == 201
    db2.close()


def test_gv_disk_matches_du_within_5pct(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i})" for i in range(500)))
    db.checkpoint()
    s.execute("alter system set log_disk_limit_bytes = 1073741824")
    s.execute("alter system set data_disk_limit_bytes = 1073741824")
    dm = db.tenant("sys").diskmgr
    rows = s.execute(
        "select surface, used_bytes, limit_bytes, state from gv$disk"
        " order by surface").rows()
    by_surface = {r[0]: r for r in rows}
    for surface in ("log", "data"):
        du = _du(dm.paths[surface])
        used = by_surface[surface][1]
        assert abs(used - du) <= max(1, du) * 0.05, (surface, used, du)
        assert by_surface[surface][3] == "ok"
    assert by_surface["log"][2] == 1 << 30
    db.close()


def test_statement_spill_budget_via_sql(tmp_path):
    """Spill exhaustion kills ONLY the statement: the session keeps
    working and the durable surface never degrades."""
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {(i * 7919) % 100000})" for i in range(3000)))
    s.execute("alter system set sql_work_area_rows = 100")
    s.execute("alter system set spill_disk_limit_bytes = 1")
    with pytest.raises(SpillBudgetExceeded):
        s.execute("select k, v from t order by v, k")
    dm = db.tenant("sys").diskmgr
    assert not dm.read_only
    assert dm.usage("spill") == 0  # failed statement left no residue
    # the session and durable surface keep working
    s.execute("insert into t values (9001, 1)")
    assert s.execute("select count(*) from t").rows()[0][0] == 3001
    # with a sane budget the same statement completes spilled
    s.execute("alter system set spill_disk_limit_bytes = 1073741824")
    got = s.execute("select k, v from t order by v, k").rows()
    assert len(got) == 3001
    assert got == sorted(got, key=lambda r: (r[1], r[0]))
    db.close()


# ---------------------------------------------------------------------------
# WAL recycle + restart identity (reclaim correctness)
# ---------------------------------------------------------------------------


def test_wal_recycle_restart_identical_and_smaller(tmp_path):
    r = _leader(tmp_path)
    r.leader_append([f"p{i}".encode() for i in range(40)])
    r.advance_commit(40)
    assert r.applied_lsn == 40
    before = os.path.getsize(r._log_path())
    freed = r.recycle(25)
    assert freed > 0
    after = os.path.getsize(r._log_path())
    assert after < before
    assert r.base_lsn == 25 and r.last_lsn() == 40
    oracle = [(e.term, e.lsn, e.payload) for e in r.entries]
    r.close()
    r2 = PalfReplica(0, log_dir=str(tmp_path / "wal"))
    assert (r2.base_lsn, r2.base_term) == (25, 1)
    assert r2.committed_lsn == 25 and r2.applied_lsn == 25
    assert [(e.term, e.lsn, e.payload) for e in r2.entries] == oracle
    # recycled history is unservable (rebuild plane); the suffix serves
    assert r2.entries_from(10) is None
    got = r2.entries_from(25)
    assert [e.lsn for e in got] == list(range(26, 41))
    r2.close()
