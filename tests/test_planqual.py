"""Plan-quality observability: the estimate-vs-actual cardinality
ledger (gv$sql_plan_monitor), cardinality feedback (gv$plan_feedback),
the plan-regression watchdog (gv$plan_history), EXPLAIN ANALYZE's
ledger format, and DTL slice-skew attribution.

Cluster scenarios ride the ``slow`` marker (the tier-1 gate is nearly
full); everything else is tier-1 cheap.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from oceanbase_tpu.server import Database


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def _seed_join_tables(s, n=100):
    """Two 100%-duplicate-key tables: the binder estimates the join at
    ~max(l, r) * 1.5 rows while the true output is l * r — the seeded
    underestimate every feedback test rides."""
    s.execute("create table a (id int primary key, k int)")
    s.execute("create table b (id int primary key, k int)")
    s.execute("insert into a values "
              + ",".join(f"({i},1)" for i in range(n)))
    s.execute("insert into b values "
              + ",".join(f"({i},1)" for i in range(n)))


# ---------------------------------------------------------------------------
# ledger: serial path
# ---------------------------------------------------------------------------


def test_qerror_ledger_serial(db):
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1,1),(2,2),(3,3),(4,4)")
    s.execute("select sum(v) from t where k >= 2")
    rec = db.plan_monitor.recent(1)[-1]
    assert rec.path == "serial" and rec.logical_hash
    by_op = {r["op"]: r for r in rec.op_stats}
    assert by_op["TableScan"]["est"] == 4
    assert by_op["TableScan"]["rows"] == 4
    assert by_op["TableScan"]["q_error"] == 1.0
    # every operator row carries an estimate to q-error against
    assert all(r["est"] is not None and r["q_error"] >= 1.0
               for r in rec.op_stats)
    # surfaced through SQL with the new columns
    r = s.execute(
        "select operator, est_rows, output_rows, q_error,"
        " capacity_retries, spill_bytes, path from gv$sql_plan_monitor"
        " where operator = 'TableScan' order by ts desc limit 1")
    assert r.rows() == [("TableScan", 4, 4, 1.0, 0, 0, "serial")]


def test_qerror_ledger_spill_path(db):
    s = db.session()
    s.execute("create table big (k int primary key, v int)")
    s.execute("insert into big values "
              + ",".join(f"({i},{i % 7})" for i in range(600)))
    # force the disk tier: the table estimate exceeds the work area
    s.execute("alter system set sql_work_area_rows = 100")
    # an external sort writes temp-file runs (a streamed scalar agg
    # would legitimately spill zero bytes)
    r = s.execute("select k, v from big order by v, k limit 5")
    assert len(r.rows()) == 5
    rec = db.plan_monitor.recent(1)[-1]
    assert rec.path == "spill"
    assert rec.spill_bytes > 0
    root = rec.op_stats[-1]
    assert root["est"] is not None and root["q_error"] >= 1.0
    r = s.execute("select path, spill_bytes, q_error from"
                  " gv$sql_plan_monitor where path = 'spill'"
                  " order by ts desc limit 1")
    path, sbytes, q = r.rows()[0]
    assert path == "spill" and sbytes > 0 and q >= 1.0


# ---------------------------------------------------------------------------
# cardinality feedback
# ---------------------------------------------------------------------------


def test_feedback_avoids_second_overflow(db):
    from oceanbase_tpu.server import metrics as qm

    def retries():
        return int(qm.sysstat_dict().get("plan.capacity_retries", 0))

    s = db.session()
    _seed_join_tables(s)
    q = "select count(*) from a, b where a.k = b.k"
    r0 = retries()
    assert s.execute(q).rows() == [(10000,)]
    first = retries() - r0
    # the overflow report (lane capacity + dropped rows) jumps straight
    # to a clearing budget: exactly ONE retry, not a blind 4x ladder
    assert first == 1, first
    # a FRESH session (cold plan cache) consults gv$plan_feedback at
    # bind time and starts at the observed bucket: zero further retries
    s2 = db.session()
    r1 = retries()
    assert s2.execute(q).rows() == [(10000,)]
    assert retries() - r1 == 0
    fb = s2.execute(
        "select operator, observed_rows from gv$plan_feedback"
        " where kind = 'card' and operator = 'HashJoin'"
        " order by observed_rows desc limit 1")
    assert fb.rows()[0] == ("HashJoin", 10000)


def test_feedback_off_rides_blind_ladder(db):
    from oceanbase_tpu.server import metrics as qm

    s = db.session()
    s.execute("alter system set enable_plan_feedback = false")
    _seed_join_tables(s)
    r0 = int(qm.sysstat_dict().get("plan.capacity_retries", 0))
    assert s.execute(
        "select count(*) from a, b where a.k = b.k").rows() == [(10000,)]
    burned = int(qm.sysstat_dict().get("plan.capacity_retries", 0)) - r0
    assert burned >= 2, burned  # 4x, 16x, 64x


def test_overflow_jump_factor_unit():
    from oceanbase_tpu.sql.optimizer import overflow_jump_factor

    # no report -> the plain ladder step
    assert overflow_jump_factor([]) == 4
    assert overflow_jump_factor([("join_overflow", None, 10)]) == 4
    # capacity 256, 9744 dropped -> needs ~57x -> 64
    assert overflow_jump_factor([("join_overflow", 256, 9744)]) == 64
    # the worst lane wins
    assert overflow_jump_factor(
        [("a", 256, 100), ("b", 256, 9744)]) == 64


def test_logical_hash_colid_vs_table_names():
    from oceanbase_tpu.exec import plan as pp
    from oceanbase_tpu.exec.plan import logical_hash

    # capacity scaling must NOT open a fresh feedback/history key ...
    a = pp.Compact(pp.TableScan("events"), 128)
    b = pp.Compact(pp.TableScan("events"), 512)
    assert logical_hash(a) == logical_hash(b)
    # ... but distinct tables with digit suffixes must not share one
    # (the colid normalization strips ``_<digits>``; table identifiers
    # are hex-protected from it)
    y24 = pp.TableScan("events_2024")
    y25 = pp.TableScan("events_2025")
    assert logical_hash(y24) != logical_hash(y25)


def test_feedback_store_is_bounded():
    from oceanbase_tpu.server.monitor import PlanFeedback

    fb = PlanFeedback(capacity=4)
    for i in range(10):
        fb.observe(f"hash{i}", [{"op": "HashJoin", "pos": 0, "est": 1,
                                 "rows": 100, "q_error": 100.0}])
    assert len(fb) == 4
    assert fb.corrections("hash0") == {}          # evicted
    assert fb.corrections("hash9") == {0: ("HashJoin", 100)}
    # max-observed semantics: a smaller later run never shrinks the
    # correction
    fb.observe("hash9", [{"op": "HashJoin", "pos": 0, "est": 1,
                          "rows": 5, "q_error": 5.0}])
    assert fb.corrections("hash9") == {0: ("HashJoin", 100)}


# ---------------------------------------------------------------------------
# plan-regression watchdog
# ---------------------------------------------------------------------------


def test_watchdog_flags_slowed_plan(db):
    ph = db.plan_history
    thr = float(db.config["plan_regress_threshold"])
    # warmup at ~1ms: the baseline freezes
    for _ in range(ph.WARMUP):
        assert ph.record("lh1", 0.001, thr) is False
    # deliberately slowed plan: 50x the baseline trips the flag (the
    # record() return marks the TRANSITION exactly once)
    transitions = [ph.record("lh1", 0.05, thr) for _ in range(4)]
    assert transitions.count(True) == 1
    (row,) = [r for r in ph.rows() if r["logical_hash"] == "lh1"]
    assert row["regressed"] is True and row["regress_count"] == 1
    assert row["baseline_s"] > 0
    # recovery clears the flag without erasing the count
    for _ in range(20):
        ph.record("lh1", 0.001, thr)
    (row,) = [r for r in ph.rows() if r["logical_hash"] == "lh1"]
    assert row["regressed"] is False and row["regress_count"] == 1
    # surfaced through SQL
    s = db.session()
    r = s.execute("select logical_hash, regressed, regress_count from"
                  " gv$plan_history where logical_hash = 'lh1'")
    assert r.rows() == [("lh1", False, 1)]


def test_watchdog_records_real_executions(db):
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1,1),(2,2)")
    # the first execution pays the XLA compile and is excluded from the
    # latency baseline (one-time plan work, not steady-state latency)
    for _ in range(4):
        s.execute("select sum(v) from t")
    rows = db.plan_history.rows()
    assert any(r["executions"] >= 3 for r in rows)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------


def test_explain_analyze_forces_collection_when_knob_off(db):
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1,1),(2,2),(3,3)")
    s.execute("alter system set enable_sql_plan_monitor = false")
    n0 = len(db.plan_monitor.recent(1000))
    # ordinary statements stay un-monitored with the knob off ...
    s.execute("select count(*) from t")
    assert len(db.plan_monitor.recent(1000)) == n0
    # ... but an explicit ANALYZE request forces collection for its own
    # statement AND records the ledger
    r = s.execute("explain analyze select sum(v) from t where k >= 2")
    assert "[est=" in r.plan_text and "act=" in r.plan_text
    recent = db.plan_monitor.recent(1000)
    assert len(recent) == n0 + 1
    assert any(x["op"] == "Filter" and x["rows"] == 2
               for x in recent[-1].op_stats)


def test_explain_analyze_flags_worst_misestimate(db):
    s = db.session()
    _seed_join_tables(s, n=30)
    r = s.execute(
        "explain analyze select count(*) from a, b where a.k = b.k")
    assert "worst misestimate: HashJoin" in r.plan_text


# ---------------------------------------------------------------------------
# ANALYZE MCV lists (string-equality selectivity)
# ---------------------------------------------------------------------------


def test_mcv_string_selectivity(db):
    s = db.session()
    s.execute("create table t (k int primary key, c varchar(8))")
    vals = ["hot"] * 90 + ["cold"] * 10
    s.execute("insert into t values "
              + ",".join(f"({i},'{v}')" for i, v in enumerate(vals)))
    s.execute("analyze table t")
    td = s.catalog.table_def("t")
    mvals, mfreqs = td.mcv["c"]
    assert mvals[0] == "hot" and abs(mfreqs[0] - 0.9) < 1e-9
    # the binder's equality estimate reads the measured frequency, not
    # the 0.1 guess: est(c='hot') ~ 90, est(c='cold') ~ 10
    r = s.execute("explain analyze select k from t where c = 'hot'")
    assert "[est=90 act=90" in r.plan_text
    r = s.execute("explain analyze select k from t where c = 'cold'")
    assert "[est=10 act=10" in r.plan_text
    # joinable surface: the MCV rides gv$plan_feedback as kind='mcv'
    r = s.execute("select operator, est_rows, observed_rows, detail"
                  " from gv$plan_feedback where kind = 'mcv'")
    (op, ndv, nvals, detail), = r.rows()
    assert op == "t.c" and ndv == 2 and nvals == 2 and "hot" in detail


def test_mcv_uncommon_value_uses_residual_mass():
    from oceanbase_tpu.sql.binder import _mcv_selectivity

    mcv = {"c": (["a", "b"], [0.5, 0.3])}
    ndv = {"c": 12}
    f_common = _mcv_selectivity("c", "a", "=", mcv, ndv)
    f_rare = _mcv_selectivity("c", "zzz", "=", mcv, ndv)
    assert f_common == 0.5
    # residual 0.2 spread over the 10 uncovered distinct values
    assert abs(f_rare - 0.02) < 1e-9
    # != inverts; non-string and unknown columns decline
    assert _mcv_selectivity("c", "a", "!=", mcv, ndv) == 0.5
    assert _mcv_selectivity("c", 5, "=", mcv, ndv) is None
    assert _mcv_selectivity("x", "a", "=", mcv, ndv) is None


# ---------------------------------------------------------------------------
# poison-lane parity: monitoring must never read dead lanes
# ---------------------------------------------------------------------------


def test_poison_parity_with_monitoring_on(poison):
    from oceanbase_tpu.exec import plan as pp
    from oceanbase_tpu.expr import ir
    from oceanbase_tpu.vector import from_numpy, to_numpy

    rel = from_numpy({
        "k": np.array([1, 2, 2, 3, 3], dtype=np.int64),
        "v": np.array([10, 20, 30, 40, 50], dtype=np.int64),
    }).pad_to(64)
    plan = pp.GroupBy(
        pp.Filter(pp.TableScan("t", est_rows=5),
                  ir.Cmp(">=", ir.col("k"), ir.Literal(2)),
                  est_rows=3),
        {"k": ir.col("k")},
        [__import__("oceanbase_tpu.exec.ops", fromlist=["AggSpec"])
         .AggSpec("s", "sum", ir.col("v"))],
        out_capacity=16, est_rows=2)
    mon_clean: list = []
    mon_pois: list = []
    clean = to_numpy(pp.execute_plan(plan, {"t": rel},
                                     monitor_out=mon_clean))
    pois = to_numpy(pp.execute_plan(
        plan, {"t": poison.poison_pad_lanes(rel)},
        monitor_out=mon_pois))
    ok, why = poison.results_identical(clean, pois)
    assert ok, why
    # the ledger itself is poison-immune: identical per-op actuals
    assert [r["rows"] for r in mon_clean] == \
        [r["rows"] for r in mon_pois]
    assert all(r["q_error"] >= 1.0 for r in mon_clean)


# ---------------------------------------------------------------------------
# cluster: DTL path ledger + slice skew (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_dtl_qerror_and_slice_attribution(tmp_path):
    from test_multinode import Cluster

    c = Cluster(tmp_path, n=3)
    try:
        c.execute(1, "create table q (k int primary key, v int)")
        rng = np.random.default_rng(7)
        v = rng.integers(0, 100, 3000)
        for s0 in range(0, 3000, 750):
            vals = ", ".join(f"({i}, {v[i]})"
                             for i in range(s0, s0 + 750))
            c.execute(1, f"insert into q values {vals}")
        deadline = time.time() + 40
        while time.time() < deadline:
            try:
                res = c.execute(2, "select count(*) from q",
                                consistency="weak")
                if c.rows(res)[0][0] == 3000:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        c.execute(1, "alter system set dtl_min_rows = 1")
        q = "select sum(v), count(*) from q where v < 50"
        res = c.execute(1, q)
        sel = v < 50
        assert c.rows(res) == [(int(v[sel].sum()), int(sel.sum()))]
        # the DTL path's ledger: remote partial ops merged back with
        # estimates, the exchange summary row, path = 'dtl'
        r = c.execute(
            1, "select operator, est_rows, output_rows, q_error from"
               " gv$sql_plan_monitor where path = 'dtl'"
               " and operator like 'DtlPartial:%'")
        rows = c.rows(r)
        assert rows, "remote per-op ledger rows missing"
        scan = [x for x in rows if x[0] == "DtlPartial:TableScan"]
        assert scan and scan[-1][1] == 3000 and scan[-1][2] == 3000
        assert all(x[3] >= 1.0 for x in rows if x[1] != -1)
        # per-slice attribution in gv$px_exchange
        r = c.execute(
            1, "select parts, max_slice_rows, mean_slice_rows,"
               " slice_skew from gv$px_exchange where mode = 'pushdown'"
               " order by ts desc limit 1")
        parts, mx, mean, skew = c.rows(r)[0]
        assert parts == 3 and mx >= 1 and mean > 0
        # pk-hash slicing of a uniform filter: balanced slices
        assert 0.0 < skew < 1.5, skew
    finally:
        c.close()
