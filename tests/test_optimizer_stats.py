"""Optimizer depth: DP join enumeration + histogram selectivity
(VERDICT r3 item #8).

≙ src/sql/optimizer/ob_join_order_enum_idp.cpp (enumeration) and
src/share/stat/ob_opt_column_stat.h (equi-height histograms).
"""

import numpy as np

from oceanbase_tpu.sql import Session
from oceanbase_tpu.sql.binder import Binder
from oceanbase_tpu.sql.parser import Parser


def _est(sess, sql):
    b = Binder(sess.catalog)
    _plan, _outs, est = b.bind_select(Parser(sql).parse())
    return est


def test_histogram_improves_range_estimates():
    rng = np.random.default_rng(0)
    n = 20_000
    v = np.where(rng.random(n) < 0.99, rng.integers(0, 100, n),
                 rng.integers(100, 10_000, n))
    s = Session()
    s.catalog.load_numpy("t", {"k": np.arange(n), "v": v},
                         primary_key=["k"])
    before = _est(s, "select k from t where v >= 5000")
    s.execute("analyze table t")
    after = _est(s, "select k from t where v >= 5000")
    true = int((v >= 5000).sum())
    assert abs(after - true) < abs(before - true)
    # the low-range estimate moves the other way
    lo = _est(s, "select k from t where v < 100")
    assert lo > n // 2


def test_dp_join_order_avoids_low_ndv_edge_first():
    """Q5-shaped trap: joining the low-NDV nationkey edge before the PK
    orders edge explodes the intermediate; DP must order orders before
    customer."""
    from oceanbase_tpu.exec import plan as pp

    rng = np.random.default_rng(1)
    n_li, n_ord, n_cust = 50_000, 12_000, 1500
    s = Session()
    s.catalog.load_numpy("li", {
        "l_ok": rng.integers(0, n_ord, n_li),
        "l_sk": rng.integers(0, 100, n_li)}, primary_key=[])
    s.catalog.load_numpy("ord", {
        "o_ok": np.arange(n_ord),
        "o_ck": rng.integers(0, n_cust, n_ord)}, primary_key=["o_ok"])
    s.catalog.load_numpy("cust", {
        "c_ck": np.arange(n_cust),
        "c_nk": rng.integers(0, 25, n_cust)}, primary_key=["c_ck"])
    s.catalog.load_numpy("supp", {
        "s_sk": np.arange(100),
        "s_nk": rng.integers(0, 25, 100)}, primary_key=["s_sk"])
    sql = ("select count(*) from li, ord, cust, supp "
           "where l_ok = o_ok and o_ck = c_ck and l_sk = s_sk "
           "and c_nk = s_nk")
    b = Binder(s.catalog)
    plan, _outs, est = b.bind_select(Parser(sql).parse())

    # walk the join tree: the nationkey-only join (cust joined with only
    # the c_nk = s_nk edge available) must not appear — every join of
    # cust must include the o_ck = c_ck PK edge
    def joins(node):
        if isinstance(node, pp.HashJoin):
            yield node
            yield from joins(node.left)
            yield from joins(node.right)
        else:
            for f in ("child", "left", "right"):
                k = getattr(node, f, None)
                if k is not None:
                    yield from joins(k)

    for j in joins(plan):
        keys = {k.name for k in j.right_keys
                if hasattr(k, "name")}
        if "c_ck" in keys or "c_nk" in keys:
            assert "c_ck" in keys, (
                "customer joined by nationkey only — the DP order "
                f"regressed (keys={keys})")
    # the overall estimate stays near |li|, not the nationkey blowup
    assert est < n_li * 4


def test_dp_plans_are_correct_vs_greedy():
    rng = np.random.default_rng(2)
    s = Session()
    n = 3000
    s.catalog.load_numpy("a", {"ak": np.arange(n),
                               "aj": rng.integers(0, 50, n)},
                         primary_key=["ak"])
    s.catalog.load_numpy("b", {"bk": np.arange(50),
                               "bv": rng.integers(0, 10, 50)},
                         primary_key=["bk"])
    s.catalog.load_numpy("c", {"ck": np.arange(10),
                               "cv": rng.integers(0, 5, 10)},
                         primary_key=["ck"])
    sql = ("select count(*), sum(cv) from a, b, c "
           "where aj = bk and bv = ck")
    got = s.execute(sql).rows()[0]
    import sqlite3

    conn = sqlite3.connect(":memory:")
    for nm in ("a", "b", "c"):
        rel = s.catalog.table_data(nm)
        cols = list(rel.columns)
        conn.execute(f"create table {nm} ({', '.join(cols)})")
        arrs = [np.asarray(rel.columns[c].data).tolist() for c in cols]
        conn.executemany(
            f"insert into {nm} values ({','.join('?' * len(cols))})",
            list(zip(*arrs)))
    want = conn.execute(sql).fetchone()
    assert tuple(got) == tuple(want)
