"""Regression tests for the round-2 advisor findings (ADVICE.md r3).

1. (high) unique-index check vs entries committed after the checker's
   snapshot — must see the LATEST committed state
2. (medium) execute_sorted_streamed must apply Projects above the Sort
3. (low) NaN float sort keys through the external merge sort
4. (low) CREATE INDEX drain fence captures the live-tx set after the
   IndexDef install
5. (low) unique-check dirty probe is a lock-table hit, not an
   O(memtable) scan
"""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import DuplicateKey, WriteConflict


def _mk(tmp_path, name="db"):
    return Database(str(tmp_path / name))


def test_unique_check_sees_commits_after_snapshot(tmp_path):
    """ADVICE high: T1 BEGIN (snapshot taken); T2 inserts v and commits;
    T1 inserting v must fail — the base rows have different pks, so only
    a latest-state (not snapshot) check catches it."""
    db = _mk(tmp_path)
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, email varchar(64))")
    s1.execute("create unique index ue on t (email)")
    s1.execute("begin")
    s1.execute("insert into t values (1, 'seed@x')")  # pin the snapshot
    s2.execute("insert into t values (2, 'dup@x')")   # autocommit
    with pytest.raises((DuplicateKey, WriteConflict)):
        s1.execute("insert into t values (3, 'dup@x')")
    s1.execute("rollback")
    db.close()


def test_unique_concurrent_uncommitted_insert_conflicts(tmp_path):
    """ADVICE low #5: the rival's UNCOMMITTED same-value insert now
    conflicts via the index rowkey lock table (fail fast)."""
    db = _mk(tmp_path)
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, email varchar(64))")
    s1.execute("create unique index ue on t (email)")
    s1.execute("begin")
    s1.execute("insert into t values (1, 'v@x')")
    with pytest.raises(WriteConflict):
        s2.execute("insert into t values (2, 'v@x')")
    s1.execute("commit")
    # lock released at commit; a later duplicate now hits DuplicateKey
    with pytest.raises(DuplicateKey):
        s2.execute("insert into t values (3, 'v@x')")
    # and after the holder rolls back, the value is free
    s2.execute("begin")
    s2.execute("insert into t values (4, 'w@x')")
    s2.execute("rollback")
    s1.execute("insert into t values (5, 'w@x')")
    db.close()


def test_unique_lock_released_on_failed_statement_tx_end(tmp_path):
    """A DuplicateKey-failed statement must not wedge the value forever:
    the lock releases with its transaction."""
    db = _mk(tmp_path)
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("create unique index uv on t (v)")
    s1.execute("insert into t values (1, 7)")
    s2.execute("begin")
    with pytest.raises(DuplicateKey):
        s2.execute("insert into t values (2, 7)")
    s2.execute("rollback")
    s1.execute("delete from t where k = 1")
    s1.execute("insert into t values (3, 7)")  # value free again
    db.close()


def test_streamed_sort_applies_top_project(tmp_path):
    """ADVICE medium: [Project Limit Sort scan] must return the projected
    columns, not the raw droot output."""
    from oceanbase_tpu.exec import plan as pp
    from oceanbase_tpu.exec.granule import execute_sorted_streamed
    from oceanbase_tpu.expr import ir

    rng = np.random.default_rng(7)
    n = 5000
    ks = rng.permutation(n).astype(np.int64)
    vs = (ks * 3).astype(np.int64)

    def provider(table, chunk_rows, bounds=None):
        for s in range(0, n, chunk_rows):
            yield {"k": ks[s:s + chunk_rows],
                   "v": vs[s:s + chunk_rows]}, {}

    scan = pp.TableScan("t", ["k", "v"])
    sort = pp.Sort(scan, [ir.col("k")], [True])
    lim = pp.Limit(sort, 10, 0)
    proj = pp.Project(lim, {"kk": ir.col("k"),
                            "twice": ir.Arith("*", ir.col("v"),
                                              ir.lit(2))})
    arrays, valids = execute_sorted_streamed(
        proj, provider, str(tmp_path / "spill"), chunk_rows=512,
        budget_rows=1024)
    assert set(arrays) == {"kk", "twice"}
    np.testing.assert_array_equal(arrays["kk"], np.arange(10))
    np.testing.assert_array_equal(arrays["twice"], np.arange(10) * 6)


def test_external_sort_nan_keys_terminate_and_order(tmp_path):
    """ADVICE low #3: NaN primary keys must not stall the merge emit
    condition; NaN sorts with +inf (ASC) / -inf (DESC) like the
    range-distribution comparator."""
    from oceanbase_tpu.exec.external_sort import external_sort
    from oceanbase_tpu.storage.tmpfile import TempFileStore

    rng = np.random.default_rng(3)
    n = 4000
    x = rng.normal(size=n)
    x[rng.random(n) < 0.3] = np.nan  # plenty of NaN, incl. run tails

    def chunks():
        for s in range(0, n, 256):
            yield {"x": x[s:s + 256].copy()}, {}

    for asc in (True, False):
        with TempFileStore(str(tmp_path / f"sp{asc}")) as store:
            got = np.concatenate([
                a["x"] for a, _v in external_sort(
                    chunks(), ["x"], [asc], store, budget_rows=500)])
        assert len(got) == n
        # NaN sorts strictly last in both directions (lexsort semantics)
        n_nan = int(np.isnan(x).sum())
        assert np.isnan(got[-n_nan:]).all()
        finite = got[:-n_nan]
        assert not np.isnan(finite).any()
        ref = np.sort(x[~np.isnan(x)])
        np.testing.assert_allclose(
            finite, ref if asc else ref[::-1])


def test_external_sort_nan_vs_inf_boundary(tmp_path):
    """NaN must land AFTER real +inf under ASC even across merge-buffer
    boundaries (NaN and inf are distinct ranks, not a tie)."""
    from oceanbase_tpu.exec.external_sort import external_sort
    from oceanbase_tpu.storage.tmpfile import TempFileStore

    rng = np.random.default_rng(11)
    n = 2000
    x = rng.normal(size=n)
    x[rng.random(n) < 0.25] = np.inf
    x[rng.random(n) < 0.25] = np.nan

    def chunks():
        for s in range(0, n, 128):
            yield {"x": x[s:s + 128].copy()}, {}

    with TempFileStore(str(tmp_path / "sp")) as store:
        got = np.concatenate([
            a["x"] for a, _v in external_sort(
                chunks(), ["x"], [True], store, budget_rows=300)])
    n_nan = int(np.isnan(x).sum())
    n_inf = int(np.isinf(x[~np.isnan(x)]).sum())
    assert np.isnan(got[-n_nan:]).all()
    assert np.isinf(got[-n_nan - n_inf:-n_nan]).all()


def test_unique_lock_released_by_statement_rollback(tmp_path):
    """A failed INSERT inside an explicit tx releases its index rowkey
    lock with the statement rollback — the value must not stay wedged
    until the tx ends."""
    db = _mk(tmp_path)
    s1, s2, s3 = db.session(), db.session(), db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("create unique index uv on t (v)")
    s1.execute("insert into t values (1, 7)")
    s1.execute("begin")
    with pytest.raises(DuplicateKey):
        s1.execute("insert into t values (2, 7)")  # stmt rolls back
    # T1 still open; T2 frees the value, T3 takes it — no WriteConflict
    # pointing at T1's dead statement
    s2.execute("delete from t where k = 1")
    s3.execute("insert into t values (3, 7)")
    s1.execute("rollback")
    db.close()


def test_create_index_drain_fence_after_install(tmp_path):
    """ADVICE low #4: the drain fence must capture the live-transaction
    set AFTER the IndexDef installs, so a tx starting inside the old
    window is either maintained or drained.  Simulate the window by
    beginning a tx from a hook between fence construction and install."""
    db = _mk(tmp_path)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10)")

    # direct engine-level reproduction: build the fence (old code captured
    # live set here), then begin+write+commit a tx, then create the index
    sess2 = db.session()
    fence = s._tx_drain_fence()
    sess2.execute("insert into t values (2, 20)")  # commits before drain
    db.engine.create_index("t", "iv", ["v"], drain=fence)
    s.catalog.invalidate("t")
    s.catalog.schema_version += 1
    # row (2,20) must be findable through the index
    istore = db.engine.tables[db.engine.index_storage_name("t", "iv")]
    arrays, _ = istore.tablet.snapshot_arrays(2**62)
    assert 20 in set(np.asarray(arrays["v"]).tolist())
    db.close()
