"""Cost-based optimizer plane: index-probe access paths, magic-set
seeded decorrelation, strict Compact overflow, the gv$plan_choice
ledger, general partition-wise matching — plus the PR's admission/dtl
satellites (tenant timeout overlay, memstore running total, cancel
pinning, RUNNING-path lane counters).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from oceanbase_tpu.exec.plan import (
    Compact,
    HashJoin,
    IndexProbe,
    TableScan,
    execute_plan,
    prepare_index_probes,
    referenced_tables,
)
from oceanbase_tpu.expr import ir
from oceanbase_tpu.server.database import Database
from oceanbase_tpu.sql import Session
from oceanbase_tpu.sql.parser import parse_sql


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def _walk(plan):
    stack = [plan]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(n.children())


def _mk_indexed(seed=3, n_big=4000, n_small=60):
    """big (indexed on k, ~8 rows/key) joined by a tiny filtered side:
    the shape where the index probe beats sorting big for a hash join."""
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 500, n_big).astype(np.int64)
    v = rng.integers(0, 1000, n_big).astype(np.int64)
    tag = rng.integers(0, 100, 500).astype(np.int64)
    s = Session()
    s.catalog.load_numpy("big", {
        "id": np.arange(n_big, dtype=np.int64), "k": k, "v": v})
    s.catalog.load_numpy("small", {
        "sk": np.arange(500, dtype=np.int64), "tag": tag})
    s.execute("analyze table big")
    s.execute("analyze table small")
    s.execute("create index idx_big_k on big (k)")
    q = ("select sum(big.v) as sv from big, small "
         "where big.k = small.sk and small.tag < 10")
    return s, q, k, v, tag


def _oracle_sum(k, v, tag):
    keep = set(np.nonzero(tag < 10)[0].tolist())
    return int(sum(int(vv) for kk, vv in zip(k, v) if int(kk) in keep))


# ---------------------------------------------------------------------------
# index-probe access path
# ---------------------------------------------------------------------------


def test_index_probe_chosen_and_correct():
    """The CBO picks the index probe for a small-probe/big-base join,
    and the answer matches both a host oracle and the no-index plan."""
    s, q, k, v, tag = _mk_indexed()
    want = _oracle_sum(k, v, tag)
    txt = "\n".join(str(r) for r in s.execute("explain " + q).rows())
    assert "IndexProbe" in txt, txt
    assert s.execute(q).rows() == [(want,)]
    # drop the index: the hash plan must agree (schema bump re-binds)
    s.execute("drop index idx_big_k on big")
    txt2 = "\n".join(str(r) for r in s.execute("explain " + q).rows())
    assert "IndexProbe" not in txt2
    assert s.execute(q).rows() == [(want,)]


def test_index_probe_poison_parity(poison):
    """IndexProbe is a data-reading operator: masked-dead lanes in the
    base, the probe side, or the sidecar must not influence results."""
    s, q, _k, _v, _tag = _mk_indexed()
    plan, _outs, _est = s._plan_select(parse_sql(q), None)
    assert any(isinstance(n, IndexProbe) for n in _walk(plan))
    tables = {t: s.catalog.table_data(t)
              for t in referenced_tables(plan)
              if s.catalog.has_table(t)}
    prepare_index_probes(s.catalog, plan, tables)
    poison.assert_poison_invariant(
        lambda t: execute_plan(plan, t), tables)


def test_index_probe_survives_dml_between_executions():
    """The sidecar cache keys on snapshot identity: rows inserted after
    the first execution must be visible to the second."""
    s = Session()
    s.catalog.load_numpy("t", {
        "a": np.arange(100, dtype=np.int64),
        "k": (np.arange(100, dtype=np.int64) % 10)})
    s.catalog.load_numpy("d", {"dk": np.arange(10, dtype=np.int64)})
    s.execute("analyze table t")
    s.execute("analyze table d")
    s.execute("create index idx_t_k on t (k)")
    q = ("select count(*) from t, d where t.k = d.dk and d.dk < 3")
    first = s.execute(q).rows()
    assert first == [(30,)]


# ---------------------------------------------------------------------------
# magic-set seeded decorrelation (q17 shape)
# ---------------------------------------------------------------------------


def _q17_session(seed=7, n_part=2000, n_li=12000):
    rng = np.random.default_rng(seed)
    part = {"p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
            "p_brand": rng.integers(0, 25, n_part).astype(np.int64)}
    li = {"l_partkey": rng.integers(1, n_part + 1, n_li).astype(np.int64),
          "l_quantity": rng.integers(1, 51, n_li).astype(np.int64),
          "l_extendedprice":
              rng.integers(100, 100000, n_li).astype(np.int64)}
    s = Session()
    s.catalog.load_numpy("part", part, primary_key=["p_partkey"])
    s.catalog.load_numpy("lineitem", li)
    s.execute("analyze table part")
    s.execute("analyze table lineitem")
    s.execute("create index idx_l_pk on lineitem (l_partkey)")
    return s, part, li


_Q17 = ("select sum(l_extendedprice) as s from lineitem, part "
        "where p_partkey = l_partkey and p_brand = 7 "
        "and l_quantity < (select 0.2 * avg(l_quantity) from lineitem l2 "
        "where l2.l_partkey = p_partkey)")


def _q17_oracle(part, li):
    sums: dict = {}
    cnts: dict = {}
    for pk, qy in zip(li["l_partkey"], li["l_quantity"]):
        sums[pk] = sums.get(pk, 0) + int(qy)
        cnts[pk] = cnts.get(pk, 0) + 1
    brand7 = set(part["p_partkey"][part["p_brand"] == 7].tolist())
    tot = 0
    for pk, qy, ep in zip(li["l_partkey"], li["l_quantity"],
                          li["l_extendedprice"]):
        if pk in brand7 and qy < 0.2 * sums[pk] / cnts[pk]:
            tot += int(ep)
    return tot


def test_magic_set_seeds_decorrelated_aggregate():
    """The decorrelated AVG-per-key aggregate is seeded by a semi join
    against the filtered outer keys (magic set) and guarded by a STRICT
    Compact, and the result matches the host oracle."""
    s, part, li = _q17_session()
    plan, _outs, _est = s._plan_select(parse_sql(_Q17), None)
    semis = [n for n in _walk(plan)
             if isinstance(n, HashJoin) and n.how == "semi"]
    stricts = [n for n in _walk(plan)
               if isinstance(n, Compact) and n.strict]
    assert semis, "magic-set semi join missing from the q17 plan"
    assert stricts, "magic-set Compact is not strict"
    assert s.execute(_Q17).rows() == [(_q17_oracle(part, li),)]


def test_magic_set_plan_poison_parity(poison):
    s, _part, _li = _q17_session(n_part=500, n_li=3000)
    plan, _outs, _est = s._plan_select(parse_sql(_Q17), None)
    tables = {t: s.catalog.table_data(t)
              for t in referenced_tables(plan)
              if s.catalog.has_table(t)}
    prepare_index_probes(s.catalog, plan, tables)
    poison.assert_poison_invariant(
        lambda t: execute_plan(plan, t), tables)


# ---------------------------------------------------------------------------
# strict Compact: overflow surfaces instead of truncating
# ---------------------------------------------------------------------------


def test_strict_compact_overflow_raises_and_rescales():
    from oceanbase_tpu.exec.diag import CapacityOverflow
    from oceanbase_tpu.sql.optimizer import scale_capacities

    s = Session()
    s.catalog.load_numpy("t", {"a": np.arange(1000, dtype=np.int64)})
    rel = s.catalog.table_data("t")
    plan = Compact(TableScan("t"), capacity=64, strict=True)
    with pytest.raises(CapacityOverflow):
        execute_plan(plan, {"t": rel})
    # the retry ladder scales the strict capacity out of the overflow
    scaled = scale_capacities(plan, 32)
    out = execute_plan(scaled, {"t": rel})
    assert int(np.asarray(out.mask_or_true()).sum()) == 1000
    # non-strict Compact with no cap never overflows
    out2 = execute_plan(Compact(TableScan("t")), {"t": rel})
    assert int(np.asarray(out2.mask_or_true()).sum()) == 1000


# ---------------------------------------------------------------------------
# gv$plan_choice ledger
# ---------------------------------------------------------------------------


def test_plan_choice_ledger_records_and_observes(db):
    s = db.session()
    s.execute("create table pa (id int primary key, k int, v int)")
    s.execute("create table pb (id int primary key, k int)")
    s.execute("insert into pa values "
              + ",".join(f"({i},{i % 20},{i})" for i in range(400)))
    s.execute("insert into pb values "
              + ",".join(f"({i},{i % 20})" for i in range(100)))
    s.execute("analyze table pa")
    s.execute("analyze table pb")
    s.execute("select count(*) from pa, pb where pa.k = pb.k")
    rows = db.plan_choice.rows()
    assert rows, "join bind did not reach the plan-choice ledger"
    rec = rows[-1]
    assert rec["enumerated"] >= 1 and rec["n_rels"] == 2
    assert rec["executions"] >= 1
    assert rec["pred_s"] > 0.0
    # the virtual table surfaces the same rows through SQL
    got = s.execute("select method, executions from gv$plan_choice")
    assert len(got.rows()) == len(rows)


# ---------------------------------------------------------------------------
# general partition-wise matching (choose_affinity)
# ---------------------------------------------------------------------------


def test_choose_affinity_claims_multiple_joins():
    """A bushy plan with two independent scan-to-scan joins co-shards
    BOTH (the old planner stopped after the first match)."""
    from oceanbase_tpu.px.planner import choose_affinity
    from oceanbase_tpu.vector import from_numpy

    n = 4000
    rng = np.random.default_rng(11)
    tabs = {}
    for name, key in (("fa", "ak"), ("fb", "bk"),
                      ("fc", "ck"), ("fd", "dk")):
        tabs[name] = from_numpy({
            key: rng.integers(0, 500, n).astype(np.int64),
            name + "_v": rng.integers(0, 9, n).astype(np.int64)})
    j1 = HashJoin(TableScan("fa"), TableScan("fb"),
                  [ir.col("ak")], [ir.col("bk")], how="inner",
                  out_capacity=1 << 16)
    j2 = HashJoin(TableScan("fc"), TableScan("fd"),
                  [ir.col("ck")], [ir.col("dk")], how="inner",
                  out_capacity=1 << 16)
    top = HashJoin(j1, j2, [ir.col("ak")], [ir.col("ck")],
                   how="inner", out_capacity=1 << 18)
    aff, elide = choose_affinity(top, tabs)
    assert set(aff) == {"fa", "fb", "fc", "fd"}
    assert len(elide) == 2
    assert id(j1) in elide and id(j2) in elide


# ---------------------------------------------------------------------------
# satellites: timeout overlay, memstore total, cancel pinning, lane kills
# ---------------------------------------------------------------------------


def test_set_global_timeout_reaches_statement_deadline(db):
    """SET GLOBAL writes the tenant config overlay; the session must
    read the overlay (not db.config) when no session variable is set."""
    s = db.session()
    assert s._stmt_timeout_s() == float(db.config["query_timeout_s"])
    s.execute("set global query_timeout_s = 120")
    assert s._stmt_timeout_s() == 120.0
    # a fresh session of the same tenant sees the overlay too
    s2 = db.session()
    assert s2._stmt_timeout_s() == 120.0
    # the session variable wins over the overlay
    s.execute("set query_timeout_s = 7")
    assert s._stmt_timeout_s() == 7.0
    # cluster default untouched
    assert int(db.config["query_timeout_s"]) != 120


def test_memstore_throttle_running_total_stays_consistent():
    """used_bytes() is O(1) now — it must track the per-table ledger
    exactly across writes, partial flushes, and table drops."""
    from oceanbase_tpu.server.admission import MemstoreThrottle
    from oceanbase_tpu.server.config import Config

    cfg = Config()
    cfg.set("enable_rate_limit", True)
    cfg.set("memstore_limit_bytes", 1 << 22)
    thr = MemstoreThrottle(cfg)

    def ledger_total():
        with thr._lock:
            return sum(e["bytes"] for e in thr._tables.values())

    for i in range(50):
        thr.admit_write("t1", {"a": i})
        thr.admit_write("t2", {"a": i, "b": "x" * 20})
    assert thr.used_bytes() == ledger_total() > 0
    thr.on_flush("t1", remaining_rows=10)
    assert thr.used_bytes() == ledger_total()
    thr.on_flush("t2", remaining_rows=0)
    assert thr.used_bytes() == ledger_total()
    thr.drop_table("t1")
    assert thr.used_bytes() == ledger_total()
    thr.drop_table("t2")
    assert thr.used_bytes() == ledger_total() == 0


def test_cancel_registry_pins_inflight_entries():
    """An Event pinned by an executing fragment must survive LRU
    pressure from >MAX_ENTRIES other tokens; unpinned tombstones stay
    bounded."""
    from oceanbase_tpu.px.dtl import CancelRegistry

    reg = CancelRegistry()
    ev = reg.pin("inflight")
    for i in range(CancelRegistry.MAX_ENTRIES + 50):
        reg.entry(f"t{i}")
    # identity check: entry() would re-create a fresh Event if the
    # pinned one had been evicted, silently orphaning the cancel
    assert reg.entry("inflight") is ev
    assert reg.cancel("inflight") is False  # first set: wasn't flagged
    assert ev.is_set()
    assert reg.cancel("inflight") is True  # idempotent re-apply
    reg.unpin("inflight")
    for i in range(CancelRegistry.MAX_ENTRIES + 50):
        reg.entry(f"u{i}")
    assert len(reg._entries) <= CancelRegistry.MAX_ENTRIES


def test_running_kill_and_timeout_bump_lane_counters():
    """KILL/timeout observed at a RUNNING checkpoint must count in the
    per-tenant gv$tenant_resource lane, not only the global counter."""
    from oceanbase_tpu.server.admission import (
        AdmissionController,
        QueryKilled,
        QueryTimeout,
        StmtCtx,
        activate,
        checkpoint,
    )
    from oceanbase_tpu.server.config import Config

    adm = AdmissionController(Config())
    ctx = StmtCtx(session_id=51, tenant="lt", controller=adm)
    adm.acquire(ctx)
    ctx.kill("test")
    with activate(ctx):
        with pytest.raises(QueryKilled):
            checkpoint()
    adm.release(ctx)
    rows = {r["tenant"]: r for r in adm.stats()}
    assert rows["lt"]["kills"] == 1

    tctx = StmtCtx(session_id=52, tenant="lt", controller=adm,
                   timeout_s=0.01)
    adm.acquire(tctx)
    time.sleep(0.03)
    with activate(tctx):
        with pytest.raises(QueryTimeout):
            checkpoint()
    adm.release(tctx)
    rows = {r["tenant"]: r for r in adm.stats()}
    assert rows["lt"]["timeouts"] == 1


# ---------------------------------------------------------------------------
# catalog-only CREATE INDEX metadata
# ---------------------------------------------------------------------------


def test_catalog_only_create_and_drop_index():
    s = Session()
    s.catalog.load_numpy("t", {"a": np.arange(10, dtype=np.int64),
                               "k": np.arange(10, dtype=np.int64)})
    s.execute("create index ix on t (k)")
    td = s.catalog.table_def("t")
    assert any(i.name == "ix" for i in td.indexes)
    with pytest.raises(Exception):
        s.execute("create index ix on t (k)")  # duplicate name
    with pytest.raises(Exception):
        s.execute("create index ix2 on t (missing)")  # unknown column
    s.execute("drop index ix on t")
    assert not any(i.name == "ix"
                   for i in s.catalog.table_def("t").indexes)
    s.execute("drop index if exists ix on t")  # idempotent
