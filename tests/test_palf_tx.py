"""PALF replicated log + transaction service tests.

≙ mittest/palf_cluster (replication/failover) and mittest/mtlenv tx tests.
"""

import json

import numpy as np
import pytest

from oceanbase_tpu.catalog import ColumnDef, TableDef
from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.palf.cluster import NoQuorum, PalfCluster
from oceanbase_tpu.storage.engine import StorageEngine
from oceanbase_tpu.tx.errors import WriteConflict
from oceanbase_tpu.tx.service import TransService, TxState


def test_palf_replication_and_commit():
    c = PalfCluster(3)
    c.elect()
    lsn = c.append([b"a", b"b", b"c"])
    assert lsn >= 3
    for r in c.replicas.values():
        assert r.committed_lsn == c.replicas[c.leader_id].committed_lsn
        assert [e.payload for e in r.entries[-3:]] == [b"a", b"b", b"c"]


def test_palf_leader_failover():
    applied = {i: [] for i in (1, 2, 3)}

    def cb_factory(i):
        return lambda e: applied[i].append(e.payload)

    c = PalfCluster(3, apply_cb_factory=cb_factory)
    c.elect()
    c.append([b"x1"])
    old = c.leader_id
    c.kill(old)
    new = c.elect()
    assert new != old
    c.append([b"x2"])
    # committed entries survive failover on the new leader
    ldr = c.replicas[new]
    payloads = [e.payload for e in ldr.entries]
    assert b"x1" in payloads and b"x2" in payloads
    # revive old leader: catches up on tick
    c.revive(old)
    c.tick()
    assert [e.payload for e in c.replicas[old].entries] == payloads


def test_palf_no_quorum():
    c = PalfCluster(3)
    c.elect()
    c.kill(2)
    c.kill(3)
    with pytest.raises(NoQuorum):
        c.append([b"y"])


def test_palf_disk_recovery(tmp_path):
    root = str(tmp_path)
    c = PalfCluster(3, log_root=root)
    c.elect()
    c.append([b"p1", b"p2"])
    c.close()
    # recover each replica from disk
    c2 = PalfCluster(3, log_root=root)
    assert all(r.last_lsn() >= 2 for r in c2.replicas.values())
    c2.elect()
    c2.append([b"p3"])
    ldr = c2.replicas[c2.leader_id]
    assert [e.payload for e in ldr.entries if e.payload.startswith(b"p")] == \
        [b"p1", b"p2", b"p3"]


def _mk_engine():
    eng = StorageEngine(None)
    for name in ("t1", "t2"):
        eng.create_table(TableDef(name, [ColumnDef("k", SqlType.int_()),
                                         ColumnDef("v", SqlType.int_())],
                                  primary_key=["k"]))
    return eng


def test_tx_single_and_2pc():
    eng = _mk_engine()
    svc = TransService()
    t1 = eng.tables["t1"].tablet
    t2 = eng.tables["t2"].tablet

    tx = svc.begin()
    svc.write(tx, "t1", t1, (1,), "insert", {"k": 1, "v": 10})
    v1 = svc.commit(tx)
    assert v1 > 0

    # 2PC across two participants
    tx = svc.begin()
    svc.write(tx, "t1", t1, (2,), "insert", {"k": 2, "v": 20})
    svc.write(tx, "t2", t2, (2,), "insert", {"k": 2, "v": 200})
    v2 = svc.commit(tx)
    assert v2 > v1
    a, _ = t1.snapshot_arrays(snapshot=v2)
    assert sorted(a["k"]) == [1, 2]
    a, _ = t2.snapshot_arrays(snapshot=v2)
    assert sorted(a["k"]) == [2]
    # atomic visibility: both participants commit at the SAME version
    a, _ = t2.snapshot_arrays(snapshot=v2 - 1)
    assert len(a["k"]) == 0


def test_tx_conflict_and_rollback():
    eng = _mk_engine()
    svc = TransService()
    t1 = eng.tables["t1"].tablet
    txa = svc.begin()
    svc.write(txa, "t1", t1, (1,), "insert", {"k": 1, "v": 1})
    txb = svc.begin()
    with pytest.raises(WriteConflict):
        svc.write(txb, "t1", t1, (1,), "insert", {"k": 1, "v": 2})
    svc.rollback(txa)
    assert txa.state == TxState.ABORT
    # now txb can write
    svc.write(txb, "t1", t1, (1,), "insert", {"k": 1, "v": 2})
    v = svc.commit(txb)
    a, _ = t1.snapshot_arrays(snapshot=v)
    assert list(a["v"]) == [2]


def test_tx_wal_replay_recovery():
    wal = PalfCluster(3)
    wal.elect()
    eng = _mk_engine()
    svc = TransService(wal=wal)
    t1 = eng.tables["t1"].tablet
    tx = svc.begin()
    svc.write(tx, "t1", t1, (1,), "insert", {"k": 1, "v": 42})
    svc.commit(tx)
    tx2 = svc.begin()
    svc.write(tx2, "t1", t1, (2,), "insert", {"k": 2, "v": 43})
    svc.rollback(tx2)  # aborted: must NOT reappear on replay

    # crash: fresh engine, replay committed WAL
    eng2 = _mk_engine()
    ldr = wal.replicas[wal.leader_id]
    max_ts = TransService.replay(ldr.entries[: ldr.committed_lsn], eng2)
    a, _ = eng2.tables["t1"].tablet.snapshot_arrays(snapshot=max_ts)
    assert sorted(zip(a["k"], a["v"])) == [(1, 42)]
