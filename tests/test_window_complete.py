"""Window-function completeness: lead/lag/ntile/first_value/last_value
and ROWS frames, diffed against the SQLite oracle (VERDICT r3 item #9).

≙ src/sql/engine/window_function/ob_window_function_vec_op.h coverage.
"""

import numpy as np
import pytest

from oceanbase_tpu.bench.oracle import load_sqlite, rows_match, run_oracle
from oceanbase_tpu.sql import Session


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(7)
    n = 500
    tables = {
        "t": {
            "k": np.arange(n),
            "g": rng.integers(0, 7, n),
            "v": rng.integers(-50, 100, n),
        }
    }
    # some NULLs in v via a second nullable column
    sess = Session()
    sess.catalog.load_numpy("t", tables["t"], primary_key=["k"])
    conn = load_sqlite(tables, {})
    return sess, conn


QUERIES = [
    # lead/lag with offsets and defaults
    "select k, lag(v) over (partition by g order by k) from t order by k",
    "select k, lead(v) over (partition by g order by k) from t order by k",
    "select k, lead(v, 3) over (partition by g order by k) from t "
    "order by k",
    "select k, lag(v, 2, -1) over (partition by g order by k) from t "
    "order by k",
    # ntile
    "select k, ntile(4) over (order by k) from t order by k",
    "select k, ntile(3) over (partition by g order by k) from t "
    "order by k",
    # first/last value (default frame)
    "select k, first_value(v) over (partition by g order by k) from t "
    "order by k",
    "select k, last_value(v) over (partition by g order by k) from t "
    "order by k",
    # ROWS frames: running and sliding aggregates
    "select k, sum(v) over (partition by g order by k "
    "rows between unbounded preceding and current row) from t order by k",
    "select k, sum(v) over (partition by g order by k "
    "rows between 3 preceding and current row) from t order by k",
    "select k, sum(v) over (partition by g order by k "
    "rows between 2 preceding and 2 following) from t order by k",
    "select k, count(v) over (partition by g order by k "
    "rows between 1 preceding and 1 following) from t order by k",
    "select k, min(v) over (partition by g order by k "
    "rows between 5 preceding and current row) from t order by k",
    "select k, max(v) over (partition by g order by k "
    "rows between 2 preceding and 4 following) from t order by k",
    "select k, avg(v) over (partition by g order by k "
    "rows between 3 preceding and 1 following) from t order by k",
    # frame + navigation combined
    "select k, first_value(v) over (partition by g order by k "
    "rows between 2 preceding and current row) from t order by k",
    "select k, last_value(v) over (partition by g order by k "
    "rows between current row and 2 following) from t order by k",
    # unbounded following side
    "select k, sum(v) over (partition by g order by k "
    "rows between current row and unbounded following) from t "
    "order by k",
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_window_oracle_parity(env, qi):
    sess, conn = env
    sql = QUERIES[qi]
    want = run_oracle(conn, sql)
    got = sess.execute(sql).rows()
    ok, why = rows_match(got, want, ordered=True)
    assert ok, f"{sql}\n{why}\n got={got[:5]}\nwant={want[:5]}"


def test_window_null_handling():
    sess = Session()
    n = 60
    v = np.arange(n, dtype=np.int64)
    valid = (np.arange(n) % 5) != 0
    sess.catalog.load_numpy(
        "tn", {"k": np.arange(n), "g": np.arange(n) % 3, "v": v},
        primary_key=["k"], valids={"v": valid})
    tables = {"tn": {"k": np.arange(n), "g": np.arange(n) % 3,
                     "v": np.where(valid, v, None)}}
    import sqlite3

    conn = sqlite3.connect(":memory:")
    conn.execute("create table tn (k, g, v)")
    conn.executemany("insert into tn values (?,?,?)",
                     list(zip(*[c.tolist()
                                for c in tables["tn"].values()])))
    for sql in (
        "select k, lag(v) over (partition by g order by k) from tn "
        "order by k",
        "select k, sum(v) over (partition by g order by k "
        "rows between 2 preceding and current row) from tn order by k",
        "select k, min(v) over (partition by g order by k "
        "rows between 1 preceding and 1 following) from tn order by k",
    ):
        want = [tuple(r) for r in conn.execute(sql).fetchall()]
        got = sess.execute(sql).rows()
        ok, why = rows_match(got, want, ordered=True)
        assert ok, f"{sql}\n{why}\n got={got[:8]}\nwant={want[:8]}"
