"""Regression tests for the storage/tx code-review findings."""

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import WriteConflict


def test_checkpoint_during_active_tx_preserves_writes(tmp_path):
    # finding 1: checkpoint while a tx is open must not lose its writes
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10)")
    s.execute("begin")
    s.execute("update t set v = 77 where k = 1")
    db.checkpoint()  # freezes + flushes mid-transaction
    s.execute("commit")
    assert s.execute("select v from t").rows() == [(77,)]
    # and it survives a restart (WAL replay past the checkpoint)
    db.close()
    db2 = Database(str(tmp_path / "db"))
    assert db2.session().execute("select v from t").rows() == [(77,)]
    db2.close()


def test_minor_compact_keeps_tombstones(tmp_path):
    # finding 2: deleting a row whose base lives in L2, then minor-merging
    # the L0s, must not resurrect the row
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    db.checkpoint()                      # L0 with both rows
    db.engine.major_compact("t")         # -> L2 baseline
    s.execute("delete from t where k = 1")
    db.checkpoint()                      # L0 tombstone
    s.execute("insert into t values (3, 3)")
    db.checkpoint()                      # second L0
    db.engine.minor_compact("t")         # merges only the L0s
    r = s.execute("select k from t order by k")
    assert r.rows() == [(2,), (3,)]      # k=1 must stay deleted
    db.close()


def test_major_compact_applies_tombstones_from_bulk_base(tmp_path):
    # finding 3: bulk-loaded L2 lacks __deleted__; major merge must still
    # honor tombstones from newer L0s
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    db.catalog.load_numpy("t2_bulk", {"k": np.arange(3), "v": np.arange(3)},
                          primary_key=["k"])
    s2 = db.session()
    r = s2.execute("select count(*) from t2_bulk")
    assert r.rows() == [(3,)]
    s2.execute("delete from t2_bulk where k = 1")
    db.checkpoint()
    db.engine.major_compact("t2_bulk")
    r = s2.execute("select k from t2_bulk order by k")
    assert r.rows() == [(0,), (2,)]
    db.close()


def test_update_primary_key(tmp_path):
    # finding 4: UPDATE that changes the PK must move the row, not clone it
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 100)")
    s.execute("update t set k = 2 where k = 1")
    r = s.execute("select k, v from t order by k")
    assert r.rows() == [(2, 100)]
    db.close()


def test_keyless_rowid_after_wal_recovery(tmp_path):
    # finding 5: rowid allocation must not collide with WAL-replayed rows
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table h (a int)")
    s.execute("insert into h values (10), (20)")
    db.close()  # crash: rows only in WAL
    db2 = Database(root)
    s2 = db2.session()
    s2.execute("insert into h values (30)")
    r = s2.execute("select a from h order by a")
    assert r.rows() == [(10,), (20,), (30,)]
    db2.close()


def test_snapshot_isolation_across_flush(tmp_path):
    # finding 6: a flush must not leak newer-committed rows into an older
    # snapshot read
    db = Database(str(tmp_path / "db"))
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("insert into t values (1, 100)")
    s1.execute("begin")
    assert s1.execute("select v from t").rows() == [(100,)]
    s2.execute("update t set v = 200 where k = 1")  # newer commit
    db.checkpoint()  # flush the v=200 version into a segment
    # s1's snapshot must still see 100
    assert s1.execute("select v from t").rows() == [(100,)]
    s1.execute("commit")
    assert s1.execute("select v from t").rows() == [(200,)]
    db.close()


def test_statement_rollback_in_explicit_tx(tmp_path):
    # finding 7: a failed statement must not leave partial writes in the tx
    db = Database(str(tmp_path / "db"))
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key, v int)")
    s1.execute("insert into t values (5, 50)")
    # s2 locks key 5
    s2.execute("begin")
    s2.execute("update t set v = 51 where k = 5")
    # s1: multi-row insert hits the lock on (5,) after writing (4,)
    s1.execute("begin")
    with pytest.raises(WriteConflict):
        s1.execute("insert into t values (4, 40), (5, 55)")
    s2.execute("rollback")
    s1.execute("commit")
    r = s1.execute("select k, v from t order by k")
    assert r.rows() == [(5, 50)]  # neither 4 nor 55 applied
    db.close()
