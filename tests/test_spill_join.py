"""Spill-partitioned join == single-shot join (≙ recursive partition dump)."""

import numpy as np
import pytest

from oceanbase_tpu.exec.ops import join
from oceanbase_tpu.exec.spill import partitioned_join
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector import from_numpy, to_numpy


def test_partitioned_inner_join_matches(rng):
    nl, nr = 20000, 3000
    left = {"fk": rng.integers(0, nr, nl), "lv": rng.integers(0, 99, nl)}
    right = {"pk": np.arange(nr), "rv": rng.integers(0, 99, nr)}
    got, _ = partitioned_join(left, right, ["fk"], ["pk"], n_partitions=7)
    whole = to_numpy(join(from_numpy(left), from_numpy(right),
                          [ir.col("fk")], [ir.col("pk")], how="inner",
                          out_capacity=nl))
    key = lambda d: sorted(zip(d["fk"].tolist(), d["lv"].tolist(),
                               d["rv"].tolist()))
    assert key(got) == key(whole)


def test_partitioned_left_and_semi(rng):
    left = {"k": np.array([1, 2, 3, 4, 5]), "lv": np.arange(5)}
    right = {"rk": np.array([2, 2, 5]), "rv": np.array([7, 8, 9])}
    got, valids = partitioned_join(left, right, ["k"], ["rk"], how="left",
                                   n_partitions=3)
    assert sorted(got["k"].tolist()) == [1, 2, 2, 3, 4, 5]
    # unmatched left rows carry NULL right columns (validity reported)
    order = np.argsort(got["k"])
    rv_valid = valids["rv"][order]
    assert rv_valid.tolist() == [False, True, True, False, False, True]
    got, _ = partitioned_join(left, right, ["k"], ["rk"], how="semi",
                              n_partitions=3)
    assert sorted(got["k"].tolist()) == [2, 5]
    got, _ = partitioned_join(left, right, ["k"], ["rk"], how="anti",
                              n_partitions=3)
    assert sorted(got["k"].tolist()) == [1, 3, 4]


def test_partitioned_multikey_and_strings(rng):
    n = 5000
    left = {"a": rng.integers(0, 20, n),
            "b": rng.choice(np.array(["x", "y", "z"]), n),
            "lv": np.arange(n)}
    right = {"c": np.repeat(np.arange(20), 3),
             "d": np.tile(np.array(["x", "y", "z"], dtype=object), 20),
             "rv": np.arange(60)}
    got, _ = partitioned_join(left, right, ["a", "b"], ["c", "d"],
                              n_partitions=5)
    whole = to_numpy(join(from_numpy(left), from_numpy(right),
                          [ir.col("a"), ir.col("b")],
                          [ir.col("c"), ir.col("d")], how="inner",
                          out_capacity=2 * n))
    assert sorted(got["lv"].tolist()) == sorted(whole["lv"].tolist())
    assert len(got["lv"]) == n


def test_partitioned_join_fanout_overflow_retry(rng):
    # every left row matches 4 right rows: default cap (2x) must grow
    # instead of silently truncating
    nl = 600
    left = {"fk": rng.integers(0, 10, nl), "lv": np.arange(nl)}
    right = {"pk": np.repeat(np.arange(10), 4), "rv": np.arange(40)}
    got, _ = partitioned_join(left, right, ["fk"], ["pk"], n_partitions=3)
    assert len(got["fk"]) == nl * 4
