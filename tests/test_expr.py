"""Expression engine unit tests vs numpy oracles.

≙ reference expr unit tests under unittest/sql/engine (expr eval on
synthetic vectors)."""

import numpy as np
import pytest

from oceanbase_tpu.datatypes import SqlType, date_to_days
from oceanbase_tpu.expr import ir
from oceanbase_tpu.expr.compile import eval_expr, eval_predicate
from oceanbase_tpu.vector import from_numpy


def make_rel(rng, n=1000):
    return from_numpy(
        {
            "a": rng.integers(-100, 100, n),
            "b": rng.integers(0, 10, n),
            "f": rng.random(n),
            "s": rng.choice(np.array(["apple", "banana", "cherry", "date"]), n),
        }
    )


def test_arith_and_cmp(rng):
    rel = make_rel(rng)
    a = np.asarray(rel.columns["a"].data)
    b = np.asarray(rel.columns["b"].data)

    c = eval_expr(ir.col("a") + ir.col("b") * 3, rel)
    np.testing.assert_array_equal(np.asarray(c.data), a + b * 3)

    p = eval_predicate((ir.col("a") > 10).and_(ir.col("b").ne(3)), rel)
    np.testing.assert_array_equal(np.asarray(p), (a > 10) & (b != 3))


def test_decimal_fixed_point(rng):
    rel = from_numpy(
        {"price": np.array([10050, 99999, 123])},  # cents: 100.50, 999.99, 1.23
        types={"price": SqlType.decimal(15, 2)},
    )
    # price * (1 - 0.06) -> scale 2 + scale 2 = 4
    e = ir.col("price") * (ir.lit("1.00", SqlType.decimal()) - ir.lit("0.06", SqlType.decimal()))
    c = eval_expr(e, rel)
    assert c.dtype.scale == 4
    np.testing.assert_array_equal(
        np.asarray(c.data), np.array([10050, 99999, 123]) * 94
    )


def test_string_predicates(rng):
    rel = make_rel(rng)
    sdict = rel.columns["s"].sdict
    svals = sdict.values[np.asarray(rel.columns["s"].data)]

    p = eval_predicate(ir.col("s").eq(ir.lit("banana")), rel)
    np.testing.assert_array_equal(np.asarray(p), svals == "banana")

    p = eval_predicate(ir.col("s") < ir.lit("cherry"), rel)
    np.testing.assert_array_equal(np.asarray(p), svals < "cherry")

    p = eval_predicate(ir.col("s").like("%an%"), rel)
    np.testing.assert_array_equal(np.asarray(p), np.char.find(svals.astype(str), "an") >= 0)

    p = eval_predicate(ir.col("s").isin(["apple", "date", "zzz"]), rel)
    np.testing.assert_array_equal(np.asarray(p), np.isin(svals, ["apple", "date"]))


def test_date_extract():
    days = np.array([date_to_days(s) for s in
                     ["1992-01-01", "1994-06-15", "1998-12-31", "1970-01-01", "2000-02-29"]])
    rel = from_numpy({"d": days}, types={"d": SqlType.date()})
    y = eval_expr(ir.FuncCall("extract_year", [ir.col("d")]), rel)
    m = eval_expr(ir.FuncCall("extract_month", [ir.col("d")]), rel)
    dd = eval_expr(ir.FuncCall("extract_day", [ir.col("d")]), rel)
    np.testing.assert_array_equal(np.asarray(y.data), [1992, 1994, 1998, 1970, 2000])
    np.testing.assert_array_equal(np.asarray(m.data), [1, 6, 12, 1, 2])
    np.testing.assert_array_equal(np.asarray(dd.data), [1, 15, 31, 1, 29])


def test_three_valued_logic():
    rel = from_numpy(
        {"x": np.array([1, 2, 3, 4])},
        valids={"x": np.array([True, False, True, False])},
    )
    # (x > 2) AND true: null lanes must stay null -> filtered by predicate
    p = eval_predicate((ir.col("x") > 2).and_(ir.lit(True)), rel)
    np.testing.assert_array_equal(np.asarray(p), [False, False, True, False])
    # (x > 2) OR true == true even for null lanes
    p = eval_predicate((ir.col("x") > 2).or_(ir.lit(True)), rel)
    np.testing.assert_array_equal(np.asarray(p), [True, True, True, True])
    # IS NULL / IS NOT NULL
    p = eval_predicate(ir.col("x").is_null(), rel)
    np.testing.assert_array_equal(np.asarray(p), [False, True, False, True])


def test_case_when(rng):
    rel = make_rel(rng, 100)
    a = np.asarray(rel.columns["a"].data)
    e = ir.Case(
        whens=[(ir.col("a") > 50, ir.lit(1)), (ir.col("a") > 0, ir.lit(2))],
        else_=ir.lit(3),
    )
    c = eval_expr(e, rel)
    expect = np.where(a > 50, 1, np.where(a > 0, 2, 3))
    np.testing.assert_array_equal(np.asarray(c.data), expect)


def test_substring_dict():
    rel = from_numpy({"phone": np.array(["13-555", "28-999", "13-111"])})
    c = eval_expr(ir.FuncCall("substring", [ir.col("phone"), ir.lit(1), ir.lit(2)]), rel)
    codes = np.asarray(c.data)
    vals = c.sdict.values[codes]
    np.testing.assert_array_equal(vals, ["13", "28", "13"])
