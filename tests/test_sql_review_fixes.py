"""Regression tests for SQL-frontend code-review findings."""

import pytest

from oceanbase_tpu.sql import Session
from oceanbase_tpu.sql.binder import BindError


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table a (x int, v int)")
    s.execute("insert into a values (1, 10), (2, 20)")
    s.execute("create table b (x int, z int)")
    s.execute("insert into b values (1, 100), (1, 101), (3, 300)")
    s.execute("create table c (x int, w int)")
    s.execute("insert into c values (2, 7)")
    return s


def test_left_join_with_inner_join_side(sess):
    # the a-b inner join predicate must apply (not degrade to cross join),
    # and the LEFT join must keep unmatched rows
    r = sess.execute(
        "select a.x, b.z, c.w from a join b on a.x = b.x "
        "left join c on a.x = c.x order by b.z")
    assert r.rows() == [(1, 100, None), (1, 101, None)]


def test_left_join_same_column_names(sess):
    # 'x' exists on both sides: ownership must track colids, not names
    r = sess.execute(
        "select a.x, c.w from a left join c on a.x = c.x order by a.x")
    assert r.rows() == [(1, None), (2, 7)]


def test_paren_union_limit(sess):
    r = sess.execute("(select x from a order by x limit 1) "
                     "union all select x from b order by x")
    # limit applies to the left branch only: 1 + 3 rows
    assert [t[0] for t in r.rows()] == [1, 1, 1, 3]


def test_union_trailing_limit(sess):
    r = sess.execute("select x from a union all select x from b "
                     "order by x limit 2")
    assert len(r.rows()) == 2


def test_order_by_aggregate_expr(sess):
    r = sess.execute("select x from b group by x order by count(*) desc, x")
    assert [t[0] for t in r.rows()] == [1, 3]
    r = sess.execute("select x, sum(z) as s from b group by x "
                     "order by sum(z) desc")
    assert r.rows() == [(3, 300), (1, 201)]


def test_order_by_base_column_not_selected(sess):
    r = sess.execute("select v from a order by x desc")
    assert r.rows() == [(20,), (10,)]


def test_order_by_ordinal_bounds(sess):
    with pytest.raises(BindError):
        sess.execute("select x from a order by 3")
    with pytest.raises(BindError):
        sess.execute("select x from a order by 0")


def test_storage_package_imports():
    import oceanbase_tpu.storage  # noqa: F401
