"""UDFs (trace-time JIT) + LOAD DATA INFILE tests."""

import numpy as np
import pytest

from oceanbase_tpu.expr.compile import register_udf, unregister_udf
from oceanbase_tpu.server import Database
from oceanbase_tpu.sql import Session


def test_udf_traced_into_plan(rng):
    import jax.numpy as jnp

    register_udf("sigmoid_cents",
                 lambda x: 1.0 / (1.0 + jnp.exp(-x.astype(jnp.float64) / 100)))
    try:
        s = Session()
        s.catalog.load_numpy("t", {"v": np.array([0, 100, -100])})
        r = s.execute("select v, sigmoid_cents(v) as p from t order by v")
        rows = r.rows()
        assert rows[1][1] == pytest.approx(0.5)
        assert rows[2][1] == pytest.approx(1 / (1 + np.exp(-1)))
        # strict NULL semantics
        s.catalog.load_numpy("n", {"v": np.array([5, 7])},
                             valids={"v": np.array([True, False])})
        r = s.execute("select sigmoid_cents(v) as p from n order by v")
        assert r.rows()[1][0] is None or r.rows()[0][0] is None
    finally:
        unregister_udf("sigmoid_cents")
    # unregistered again -> clean error
    with pytest.raises(Exception):
        s.execute("select sigmoid_cents(1)")


def test_load_data_infile(tmp_path):
    csv_path = tmp_path / "in.csv"
    csv_path.write_text(
        "k,v,name,d\n"
        "1,10.50,ann,2020-01-01\n"
        "2,20.25,bob,2021-06-15\n"
        "3,,carol,2022-12-31\n"
    )
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v decimal(10,2), "
              "name varchar(20), d date)")
    r = s.execute(f"load data infile '{csv_path}' into table t "
                  f"fields terminated by ',' ignore 1 lines")
    assert r.rowcount == 3
    rows = s.execute("select k, v, name, d from t order by k").rows()
    assert rows[0] == (1, 10.5, "ann", "2020-01-01")
    assert rows[2][1] is None  # empty field -> NULL
    # direct load produced a baseline segment, not memtable rows
    assert db.engine.tables["t"].tablet.segments
    db.close()
