"""Zone-map chunk pruning in streamed scans (blockscan skip analog)."""

import numpy as np
import pytest

from oceanbase_tpu.exec.granule import (
    execute_streamed,
    extract_column_bounds,
    segment_chunk_provider,
)
from oceanbase_tpu.exec.ops import AggSpec
from oceanbase_tpu.exec.plan import Filter, ScalarAgg, TableScan
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector import to_numpy


def _plan(lo, hi):
    scan = TableScan("t", rename={"k": "k", "v": "v"})
    pred = (ir.col("k") >= ir.lit(lo)).and_(ir.col("k") < ir.lit(hi))
    return ScalarAgg(Filter(scan, pred),
                     [AggSpec("s", "sum", ir.col("v")),
                      AggSpec("c", "count_star")])


def test_bounds_extraction():
    plan = _plan(100, 200)
    b = extract_column_bounds(plan.child)
    assert b == {"k": (100, 200)}
    # decimal literals must NOT produce bounds (scale mismatch hazard)
    scan = TableScan("t", rename={"v": "v"})
    from oceanbase_tpu.datatypes import SqlType

    p2 = Filter(scan, ir.col("v") > ir.lit("1.5", SqlType.decimal()))
    assert extract_column_bounds(p2) == {}


def test_streamed_zone_map_pruning(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    # sorted keys -> perfectly prunable chunks
    rows = ", ".join(f"({i}, {i % 10})" for i in range(2000))
    s.execute(f"insert into t values {rows}")
    db.checkpoint()
    tablet = db.engine.tables["t"].tablet
    snap = db.tx.gts.current()

    # count chunks the provider yields with vs without pruning
    plan = _plan(100, 200)
    out = to_numpy(execute_streamed(
        plan, segment_chunk_provider(tablet, snap), chunk_rows=64))
    want_c = 100
    want_s = sum(i % 10 for i in range(100, 200))
    assert out["c"][0] == want_c and out["s"][0] == want_s

    # fully-pruned range: correct empty aggregate (count 0, sum NULL)
    plan2 = _plan(10_000, 20_000)
    out2 = execute_streamed(plan2, segment_chunk_provider(tablet, snap),
                            chunk_rows=64)
    res = to_numpy(out2)
    assert res["c"][0] == 0
    db.close()


def test_pruning_skips_host_work(tmp_path):
    # multi-chunk segment built directly with a small chunk size so zone
    # maps have real granularity
    from oceanbase_tpu.catalog import ColumnDef, TableDef
    from oceanbase_tpu.datatypes import SqlType
    from oceanbase_tpu.storage.engine import StorageEngine
    from oceanbase_tpu.storage.segment import Segment

    eng = StorageEngine(None)
    eng.create_table(TableDef("t", [ColumnDef("k", SqlType.int_()),
                                    ColumnDef("v", SqlType.int_())],
                              primary_key=["k"]))
    tablet = eng.tables["t"].tablet
    seg = Segment.build(1, 2, {"k": np.arange(5000),
                               "v": np.ones(5000, dtype=np.int64)},
                        tablet.types, chunk_rows=512, max_version=1)
    tablet.segments.append(seg)
    assert seg.n_chunks == 10
    provider = segment_chunk_provider(tablet, snapshot=10)
    total_all = sum(len(next(iter(a.values())))
                    for a, _v in provider("t", 512, None))
    total_pruned = sum(len(next(iter(a.values())))
                       for a, _v in provider("t", 512, {"k": (0, 100)}))
    assert total_all == 5000
    assert total_pruned == 512  # one matching chunk survives
