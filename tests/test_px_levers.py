"""PX perf levers: runtime bloom join filter, partition-wise (affinity)
co-sharding with exchange elision, and RANGE-repartition distributed
sort (≙ ob_px_bloom_filter.h, ob_pwj_comparer.h, ob_dh_range_dist_wf.h).

Runs on the 8-virtual-device CPU mesh from conftest."""

import numpy as np
import pytest

from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.exec import ops
from oceanbase_tpu.exec.plan import (
    Filter, HashJoin, Limit, Project, Sort, TableScan,
)
from oceanbase_tpu.expr import ir
from oceanbase_tpu.px import planner as px_planner
from oceanbase_tpu.px.exchange import default_mesh
from oceanbase_tpu.px.planner import choose_affinity, execute_plan_distributed
from oceanbase_tpu.vector import from_numpy, to_numpy


def _rel(arrays, types=None):
    return from_numpy(arrays, types=types)


def _fact_dim(n_fact=20_000, n_dim=2_000, seed=3):
    rng = np.random.default_rng(seed)
    fact = {
        "f_key": rng.integers(0, n_dim * 4, n_fact).astype(np.int64),
        "f_val": rng.integers(0, 100, n_fact).astype(np.int64),
    }
    dim = {
        "d_key": np.arange(n_dim, dtype=np.int64),
        "d_tag": rng.integers(0, 10, n_dim).astype(np.int64),
    }
    return fact, dim


def _join_plan(how="inner"):
    scan_f = TableScan("fact", rename={"f_key": "fk", "f_val": "fv"})
    scan_d = TableScan("dim", rename={"d_key": "dk", "d_tag": "dt"})
    return HashJoin(scan_f, scan_d, [ir.col("fk")], [ir.col("dk")],
                    how=how, out_capacity=1 << 16)


def _serial_join(tables, plan):
    from oceanbase_tpu.exec.plan import execute_plan

    return execute_plan(plan, tables)


_NULL = -(10 ** 15)  # sentinel: NULL payloads are arbitrary raw values


def _sorted_rows(rel, cols):
    mask = np.asarray(rel.mask_or_true())
    idx = np.nonzero(mask)[0]
    lanes = []
    for c in cols:
        col = rel.columns[c]
        vals = np.asarray(col.data)[idx].tolist()
        if col.valid is not None:
            vv = np.asarray(col.valid)[idx]
            vals = [v if ok else _NULL for v, ok in zip(vals, vv)]
        lanes.append(vals)
    return sorted(zip(*lanes))


def test_affinity_chosen_and_join_correct():
    fact, dim = _fact_dim()
    tables = {"fact": _rel(fact), "dim": _rel(dim)}
    plan = _join_plan()
    aff, elide = choose_affinity(plan, tables)
    assert aff == {"fact": ["f_key"], "dim": ["d_key"]}
    assert len(elide) == 1

    got = execute_plan_distributed(plan, tables, dop=8)
    want = _serial_join(tables, plan)
    cols = ["fk", "fv", "dk", "dt"]
    assert _sorted_rows(got, cols) == _sorted_rows(want, cols)


def test_affinity_skipped_for_string_keys_and_self_join():
    fact, dim = _fact_dim(2_000, 500)
    sfact = dict(fact, f_name=np.array(
        [f"s{i % 7}" for i in range(2_000)], dtype=object))
    sdim = dict(dim, d_name=np.array(
        [f"s{i % 7}" for i in range(500)], dtype=object))
    tables = {"fact": _rel(sfact), "dim": _rel(sdim)}
    scan_f = TableScan("fact", rename={"f_name": "fn", "f_val": "fv"})
    scan_d = TableScan("dim", rename={"d_name": "dn", "d_tag": "dt"})
    plan = HashJoin(scan_f, scan_d, [ir.col("fn")], [ir.col("dn")],
                    how="inner", out_capacity=1 << 16)
    aff, elide = choose_affinity(plan, tables)
    assert aff == {} and not elide  # string keys -> no affinity

    scan_a = TableScan("dim", rename={"d_key": "ak", "d_tag": "at"})
    scan_b = TableScan("dim", rename={"d_key": "bk", "d_tag": "bt"})
    self_plan = HashJoin(scan_a, scan_b, [ir.col("ak")], [ir.col("bk")],
                         how="inner", out_capacity=1 << 14)
    aff2, elide2 = choose_affinity(self_plan, {"dim": _rel(sdim)})
    assert aff2 == {} and not elide2  # table scanned twice -> no affinity


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_bloom_hash_join_parity(how):
    """Force the HASH-HASH + bloom path (build side above the broadcast
    threshold) and check parity with the serial join for every how."""
    fact, dim = _fact_dim(6_000, 3_000, seed=11)
    tables = {"fact": _rel(fact), "dim": _rel(dim)}
    scan_f = TableScan("fact", rename={"f_key": "fk", "f_val": "fv"})
    scan_d = TableScan("dim", rename={"d_key": "dk", "d_tag": "dt"})
    # a Project breaks the scan-chain shape -> no affinity elision, and
    # we shrink the broadcast threshold to force the hash-hash path
    proj = Project(scan_d, {"dk": ir.col("dk"), "dt": ir.col("dt")})
    plan = HashJoin(scan_f, proj, [ir.col("fk")], [ir.col("dk")],
                    how=how, out_capacity=1 << 16)
    old = px_planner.BROADCAST_THRESHOLD_BYTES
    px_planner.BROADCAST_THRESHOLD_BYTES = 1
    try:
        got = execute_plan_distributed(plan, tables, dop=8)
    finally:
        px_planner.BROADCAST_THRESHOLD_BYTES = old
    want = _serial_join(tables, plan)
    cols = (["fk", "fv"] if how in ("semi", "anti")
            else ["fk", "fv", "dk", "dt"])
    assert _sorted_rows(got, cols) == _sorted_rows(want, cols)


def test_distributed_sort_global_order():
    rng = np.random.default_rng(5)
    n = 50_000
    arrays = {"a": rng.integers(-1000, 1000, n).astype(np.int64),
              "b": rng.integers(0, 5, n).astype(np.int64)}
    tables = {"t": _rel(arrays)}
    scan = TableScan("t", rename={"a": "a", "b": "b"})
    plan = Sort(scan, [ir.col("a"), ir.col("b")], [True, False])
    got = to_numpy(execute_plan_distributed(plan, tables, dop=8))
    rows = list(zip(got["a"].tolist(), got["b"].tolist()))
    assert rows == sorted(rows, key=lambda r: (r[0], -r[1]))
    assert len(rows) == n


def test_distributed_sort_desc_with_nulls_and_limit():
    n = 9_000
    vals = np.arange(n, dtype=np.int64) % 97
    valid = (np.arange(n) % 11) != 0  # ~9% NULLs
    rel = from_numpy({"v": vals}, valids={"v": ~np.zeros(n, bool) & valid})
    tables = {"t": rel}
    scan = TableScan("t", rename={"v": "v"})
    plan = Limit(Sort(scan, [ir.col("v")], [False]), 50)
    got = to_numpy(execute_plan_distributed(plan, tables, dop=8))

    # serial oracle
    want = to_numpy(ops.limit(
        ops.sort_rows(rel.select(["v"]), [ir.col("v")], [False]), 50))
    assert got["v"].tolist() == want["v"].tolist()


def test_distributed_sort_skew_overflow_retries(tmp_path):
    """All-equal sort keys land on ONE shard: the first attempt's range
    exchange overflows and the session retry loop must still produce the
    right answer end-to-end."""
    from oceanbase_tpu.server.database import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("set px_dop = 8")
    s.execute("create table t (k int primary key, v int)")
    n = 4_000
    db.engine.bulk_load("t", {"k": np.arange(n, dtype=np.int64),
                              "v": np.zeros(n, dtype=np.int64)},
                        version=db.tenant().tx.gts.current())
    db.tenant().catalog.invalidate("t")
    rows = s.execute("select k from t order by v, k limit 5").rows()
    assert [r[0] for r in rows] == [0, 1, 2, 3, 4]
    db.close()


def test_distributed_sort_float_nan_asc():
    """Review finding: NaN range-dest must match the local comparator
    (lexsort orders NaN last) for ASC too."""
    rng = np.random.default_rng(9)
    n = 8_192
    vals = rng.normal(size=n)
    vals[::97] = np.nan
    rel = from_numpy({"x": vals})
    scan = TableScan("t", rename={"x": "x"})
    plan = Sort(scan, [ir.col("x")], [True])
    got = to_numpy(execute_plan_distributed(plan, {"t": rel}, dop=8))
    want = to_numpy(ops.sort_rows(rel, [ir.col("x")], [True]))
    np.testing.assert_array_equal(got["x"], want["x"])


def test_affinity_rejects_mismatched_decimal_scales():
    """Review finding: raw-value hashing cannot reconcile mixed DECIMAL
    scales; such joins must not elide exchanges."""
    fact = {"f_key": np.array([500, 1500], dtype=np.int64),
            "f_val": np.array([1, 2], dtype=np.int64)}
    dim = {"d_key": np.array([50, 150], dtype=np.int64),
           "d_tag": np.array([7, 8], dtype=np.int64)}
    tf = _rel(fact, types={"f_key": SqlType.decimal(10, 2),
                           "f_val": SqlType.int_()})
    td = _rel(dim, types={"d_key": SqlType.decimal(10, 1),
                          "d_tag": SqlType.int_()})
    tables = {"fact": tf, "dim": td}
    plan = _join_plan()
    aff, elide = choose_affinity(plan, tables)
    assert aff == {} and not elide


def test_hash_partitionable_guard():
    from oceanbase_tpu.px.planner import _keys_hash_partitionable

    sl = _rel({"a": np.array(["x", "y"], dtype=object)})
    sr = _rel({"b": np.array(["x", "z"], dtype=object)})
    assert not _keys_hash_partitionable(sl, sr, [ir.col("a")],
                                        [ir.col("b")])
    il = _rel({"a": np.array([1, 2], dtype=np.int64)})
    ir_ = _rel({"b": np.array([1, 3], dtype=np.int64)})
    assert _keys_hash_partitionable(il, ir_, [ir.col("a")],
                                    [ir.col("b")])
    dl = _rel({"a": np.array([10], dtype=np.int64)},
              types={"a": SqlType.decimal(10, 1)})
    dr = _rel({"b": np.array([100], dtype=np.int64)},
              types={"b": SqlType.decimal(10, 2)})
    assert not _keys_hash_partitionable(dl, dr, [ir.col("a")],
                                        [ir.col("b")])
