"""Low-cardinality (direct dictionary code) GROUP BY fast path."""

import numpy as np
import pytest

from oceanbase_tpu.exec import ops
from oceanbase_tpu.exec.ops import AggSpec, hash_groupby
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector import from_numpy, to_numpy


def _run(rel, keys, aggs, force_sort=False, cap=None):
    if force_sort:
        old = ops.LOWCARD_GROUP_LIMIT
        ops.LOWCARD_GROUP_LIMIT = 0
        try:
            return to_numpy(hash_groupby(rel, keys, aggs, out_capacity=cap))
        finally:
            ops.LOWCARD_GROUP_LIMIT = old
    return to_numpy(hash_groupby(rel, keys, aggs, out_capacity=cap))


def _norm(res, cols):
    rows = sorted(zip(*[list(res[c]) for c in cols]))
    return rows


def test_lowcard_matches_sort_path(rng):
    n = 5000
    flag = rng.choice(np.array(["A", "N", "R"]), n)
    status = rng.choice(np.array(["F", "O"]), n)
    nulls = rng.random(n) < 0.1
    v = rng.integers(-100, 100, n)
    rel = from_numpy({"f": flag, "s": status, "v": v},
                     valids={"s": ~nulls})
    keys = {"f": ir.col("f"), "s": ir.col("s")}
    aggs = [AggSpec("sum", "sum", ir.col("v")),
            AggSpec("cnt", "count_star"),
            AggSpec("mn", "min", ir.col("v")),
            AggSpec("av", "avg", ir.col("v"))]
    fast = _run(rel, keys, aggs)
    slow = _run(rel, keys, aggs, force_sort=True)
    cols = ["f", "s", "sum", "cnt", "mn"]
    assert _norm(fast, cols) == _norm(slow, cols)
    np.testing.assert_allclose(sorted(fast["av"]), sorted(slow["av"]))


def test_lowcard_bool_keys(rng):
    n = 1000
    b = rng.integers(0, 2, n).astype(bool)
    v = rng.integers(0, 10, n)
    rel = from_numpy({"b": b, "v": v})
    out = to_numpy(hash_groupby(rel, {"b": ir.col("b")},
                                [AggSpec("s", "sum", ir.col("v"))]))
    got = dict(zip(out["b"], out["s"]))
    assert got[False] == v[~b].sum() and got[True] == v[b].sum()


def test_lowcard_respects_capacity_fallback(rng):
    # out_capacity below the code space must fall back (still correct)
    n = 500
    s = rng.choice(np.array([f"k{i}" for i in range(50)]), n)
    rel = from_numpy({"s": s})
    out = to_numpy(hash_groupby(rel, {"s": ir.col("s")},
                                [AggSpec("c", "count_star")],
                                out_capacity=8))
    # truncated sort-path output of 8 groups (overflow handled upstream)
    assert len(out["s"]) <= 8
