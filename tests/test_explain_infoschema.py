"""EXPLAIN ANALYZE + information_schema tests."""

import pytest

from oceanbase_tpu.server import Database


def test_explain_analyze_row_counts(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2), (3, 3), (4, 4)")
    r = s.execute("explain analyze select sum(v) from t where k >= 3")
    text = r.plan_text
    assert "TableScan" in text and "act=4" in text
    assert "Filter" in text and "act=2" in text
    assert "ScalarAgg" in text and "act=1" in text
    # the estimate-vs-actual ledger rides every annotation
    assert "[est=" in text and "q=" in text
    assert "worst misestimate:" in text
    # plain EXPLAIN has no ledger annotations and does not execute
    r = s.execute("explain select sum(v) from t")
    assert "[est=" not in r.plan_text and "act=" not in r.plan_text
    db.close()


def test_information_schema(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v decimal(10,2))")
    s.execute("insert into t values (1, 1.5)")
    r = s.execute("select table_name, table_rows from information_schema.tables "
                  "where table_schema = 'sys'")
    assert ("t", 1) in r.rows()
    r = s.execute("select column_name, data_type, column_key "
                  "from information_schema.columns "
                  "where table_name = 't' order by ordinal_position")
    rows = r.rows()
    assert rows[0] == ("k", "INT", "PRI")
    assert rows[1][0] == "v" and "DECIMAL" in rows[1][1]
    db.close()


def test_show_index_and_processlist(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int, "
              "e vector(3))")
    s.execute("create unique index iv on t (v)")
    s.execute("create vector index ie on t (e)")
    rows = s.execute("show index from t").rows()
    by_name = {r[0]: r for r in rows}
    assert by_name["PRIMARY"][3] == "primary"
    assert by_name["iv"][2] == 1 and by_name["iv"][3] == "unique"
    assert by_name["ie"][3] == "vector"
    rows = s.execute("show processlist").rows()
    assert any("show processlist" in (r[2] or "") for r in rows)
    db.close()
