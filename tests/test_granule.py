"""Granule streaming tests: chunked execution == whole-table execution.

≙ granule iterator rescans (ob_granule_pump) producing identical results.
"""

import numpy as np
import pytest

from oceanbase_tpu.bench.queries import q1_plan, q6_plan
from oceanbase_tpu.bench.tpch import gen_tpch
from oceanbase_tpu.exec.granule import (
    execute_streamed,
    numpy_chunk_provider,
    segment_chunk_provider,
)
from oceanbase_tpu.exec.plan import execute_plan
from oceanbase_tpu.vector import from_numpy, to_numpy


@pytest.fixture(scope="module")
def li():
    tables, types = gen_tpch(sf=0.02)
    needed = ["l_returnflag", "l_linestatus", "l_quantity",
              "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]
    arrays = {k: tables["lineitem"][k] for k in needed}
    t = {k: v for k, v in types.items() if k in needed}
    return arrays, t


def test_streamed_q6_matches(li):
    arrays, types = li
    whole = execute_plan(q6_plan(), {"lineitem": from_numpy(arrays, types)})
    streamed = execute_streamed(
        q6_plan(), numpy_chunk_provider(arrays), chunk_rows=10_000,
        types=types)
    assert to_numpy(whole)["revenue"][0] == to_numpy(streamed)["revenue"][0]


def test_streamed_q1_matches(li):
    arrays, types = li
    whole = to_numpy(execute_plan(
        q1_plan(), {"lineitem": from_numpy(arrays, types)}))
    streamed = to_numpy(execute_streamed(
        q1_plan(), numpy_chunk_provider(arrays), chunk_rows=16_384,
        types=types))
    # group keys are dict-decoded strings; compare aligned rows
    np.testing.assert_array_equal(whole["l_returnflag"],
                                  streamed["l_returnflag"])
    np.testing.assert_array_equal(whole["l_linestatus"],
                                  streamed["l_linestatus"])
    for col in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                "count_order"):
        np.testing.assert_array_equal(whole[col], streamed[col])
    for col in ("avg_qty", "avg_price", "avg_disc"):
        np.testing.assert_allclose(whole[col], streamed[col], rtol=1e-12)


def test_streamed_from_lsm_segments(tmp_path):
    from oceanbase_tpu.server import Database

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    rows = ", ".join(f"({i}, {i % 7})" for i in range(500))
    s.execute(f"insert into t values {rows}")
    db.checkpoint()
    s.execute(f"insert into t values (1000, 3), (1001, 4)")
    db.checkpoint()

    from oceanbase_tpu.exec.ops import AggSpec
    from oceanbase_tpu.exec.plan import ScalarAgg, TableScan
    from oceanbase_tpu.expr import ir

    plan = ScalarAgg(TableScan("t", rename={"k": "k", "v": "v"}),
                     [AggSpec("s", "sum", ir.col("v")),
                      AggSpec("c", "count_star")])
    tablet = db.engine.tables["t"].tablet
    out = execute_streamed(
        plan, segment_chunk_provider(tablet, db.tx.gts.current()),
        chunk_rows=128)
    res = to_numpy(out)
    want = sum(i % 7 for i in range(500)) + 3 + 4
    assert res["s"][0] == want and res["c"][0] == 502
    db.close()


def test_prefetch_iter_semantics():
    """prefetch_iter: order preserved, exceptions surface, abandoning
    the consumer closes the wrapped generator (no leaked producers)."""
    import threading
    import time

    from oceanbase_tpu.exec.granule import prefetch_iter

    assert list(prefetch_iter(iter(range(20)))) == list(range(20))

    def boom():
        yield 1
        raise ValueError("producer failed")

    it = prefetch_iter(boom())
    assert next(it) == 1
    try:
        next(it)
        raise AssertionError("exception not propagated")
    except ValueError:
        pass

    closed = threading.Event()

    def src():
        try:
            for i in range(1000):
                yield i
        finally:
            closed.set()

    g = prefetch_iter(src())
    assert next(g) == 0
    g.close()  # abandon early (LIMIT mid-stream)
    assert closed.wait(5), "wrapped generator was never closed"
