"""RIGHT/FULL OUTER join parity vs the SQLite oracle (VERDICT r3 #5).

≙ src/sql/engine/join/hash_join/ob_hash_join_vec_op.h:342 (unmatched-
build FILL_RIGHT emission) — here the full-outer lowering appends one
lane per build row after the probe expansion.
"""

import numpy as np
import pytest

from oceanbase_tpu.bench.oracle import rows_match
from oceanbase_tpu.sql import Session


@pytest.fixture(scope="module")
def env():
    import sqlite3

    rng = np.random.default_rng(3)
    na, nb = 300, 200
    a = {"ak": np.arange(na), "aj": rng.integers(0, 80, na),
         "av": rng.integers(0, 1000, na)}
    b = {"bk": np.arange(nb), "bj": rng.integers(40, 120, nb),
         "bv": rng.integers(0, 1000, nb)}
    sess = Session()
    sess.catalog.load_numpy("a", a, primary_key=["ak"])
    sess.catalog.load_numpy("b", b, primary_key=["bk"])
    conn = sqlite3.connect(":memory:")
    for nm, cols in (("a", a), ("b", b)):
        conn.execute(f"create table {nm} ({', '.join(cols)})")
        conn.executemany(
            f"insert into {nm} values ({','.join('?' * len(cols))})",
            list(zip(*[c.tolist() for c in cols.values()])))
    return sess, conn


# sqlite grew RIGHT/FULL OUTER JOIN in 3.39; older oracles get the
# rewritten equivalent from conftest
from conftest import rewrite_outer_join_for_old_sqlite


def _oracle_sql(sql: str) -> str:
    return rewrite_outer_join_for_old_sqlite(
        sql, "a", "b", ("ak", "aj", "av"), ("bk", "bj", "bv"))


QUERIES = [
    "select ak, aj, bk, bj from a full outer join b on aj = bj "
    "order by ak, bk",
    "select count(*), sum(av), sum(bv) from a full outer join b "
    "on aj = bj",
    "select ak, bk from a right outer join b on aj = bj order by bk, ak",
    "select count(*) from a right join b on aj = bj",
    # full outer + aggregation over the null-extended side
    "select bj, count(ak) from a full outer join b on aj = bj "
    "group by bj order by bj",
    # full outer with no matches at all on one side
    "select count(*) from a full outer join b on av = bk + 5000",
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_outer_join_parity(env, qi):
    sess, conn = env
    sql = QUERIES[qi]
    want = [tuple(r) for r in conn.execute(_oracle_sql(sql)).fetchall()]
    got = sess.execute(sql).rows()
    ok, why = rows_match(got, want, ordered="order by" in sql)
    assert ok, f"{sql}\n{why}\n got={got[:5]}\nwant={want[:5]}"


def test_full_outer_distributes_on_px(env):
    sess, _conn = env
    sql = ("select count(*), sum(av), sum(bv) from a full outer join b "
           "on aj = bj")
    serial = sess.execute(sql).rows()
    sess.variables["px_dop"] = 8
    try:
        dist = sess.execute(sql).rows()
        assert sess._last_px, "full outer should distribute via HASH-HASH"
    finally:
        sess.variables["px_dop"] = 0
    assert serial == dist
