"""Distributed (mesh) execution tests on the virtual 8-device CPU mesh.

≙ mittest tier (SURVEY §4 tier 3): real multi-worker wiring in one process.
"""

import jax
import numpy as np
import pytest

from oceanbase_tpu.exec.ops import AggSpec
from oceanbase_tpu.expr import ir
from oceanbase_tpu.px.dist_ops import dist_groupby, dist_join_shard
from oceanbase_tpu.px.exchange import (
    default_mesh,
    shard_map_compat,
    shard_relation,
    unshard_relation,
)
from oceanbase_tpu.vector import from_numpy, to_numpy


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    return default_mesh(8)


def test_dist_groupby_matches_local(rng, mesh):
    n = 4096
    g = rng.integers(0, 37, n)
    v = rng.integers(-100, 100, n)
    rel = from_numpy({"g": g, "v": v})
    out = dist_groupby(
        rel, {"g": ir.col("g")},
        [AggSpec("s", "sum", ir.col("v")),
         AggSpec("c", "count_star"),
         AggSpec("mx", "max", ir.col("v")),
         AggSpec("av", "avg", ir.col("v"))],
        mesh, local_cap=64, out_cap=64,
    )
    res = to_numpy(out)
    order = np.argsort(res["g"])
    keys = np.unique(g)
    np.testing.assert_array_equal(res["g"][order], keys)
    np.testing.assert_array_equal(res["s"][order], [v[g == k].sum() for k in keys])
    np.testing.assert_array_equal(res["c"][order], [(g == k).sum() for k in keys])
    np.testing.assert_array_equal(res["mx"][order], [v[g == k].max() for k in keys])
    np.testing.assert_allclose(res["av"][order], [v[g == k].mean() for k in keys])


def test_dist_join_matches_local(rng, mesh):
    from functools import partial

    from jax.sharding import PartitionSpec as P

    nl, nr = 2048, 256
    fk = rng.integers(0, nr, nl)
    left = from_numpy({"fk": fk, "lv": np.arange(nl)})
    right = from_numpy({"pk": np.arange(nr), "rv": rng.integers(0, 1000, nr)})

    ls = shard_relation(left, mesh)
    rs = shard_relation(right, mesh)

    def fn(l, r):
        out, local_ovf = dist_join_shard(
            l, r, left_keys=[ir.col("fk")], right_keys=[ir.col("pk")],
            ndev=8, cap_per_dest=nl // 4, out_capacity=nl, how="inner")
        return out, jax.lax.psum(local_ovf, "px")

    run = jax.jit(shard_map_compat(
        fn, mesh=mesh, in_specs=(P("px"), P("px")), out_specs=(P("px"), P()),
    ))
    shard_out, overflow = run(ls, rs)
    assert int(overflow) == 0
    out = unshard_relation(shard_out)
    res = to_numpy(out)
    assert len(res["fk"]) == nl
    np.testing.assert_array_equal(res["fk"], res["pk"])
    rv = np.asarray(right.columns["rv"].data)
    np.testing.assert_array_equal(res["rv"], rv[res["fk"]])
