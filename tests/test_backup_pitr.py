"""Incremental backup, WAL archiving, point-in-time restore
(VERDICT r3 missing #10).

≙ src/storage/backup (data backup), src/logservice/archiveservice
(log archive), src/storage/restore (PITR).
"""

import os

from oceanbase_tpu.server import Database
from oceanbase_tpu.server.backup import (
    archive_wal,
    full_backup,
    incremental_backup,
    overlay_archive,
    pitr_cut,
    restore_chain,
)


def test_incremental_backup_restore(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i * 2})" for i in range(3000)))
    full = full_backup(db, str(tmp_path / "b0"))
    # more data after the full backup
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i * 2})" for i in range(3000, 5000)))
    s.execute("create table u (k int primary key, s varchar(8))")
    s.execute("insert into u values (1, 'x'), (2, 'y')")
    inc = incremental_backup(db, str(tmp_path / "b1"), full)
    db.close()

    # the incremental skipped unchanged segment files
    import json

    with open(os.path.join(inc, "BACKUP_MANIFEST.json")) as fh:
        m = json.load(fh)
    assert m["kind"] == "incremental" and m["skipped"] > 0

    target = str(tmp_path / "restored")
    restore_chain(inc, target)
    db2 = Database(target)
    s2 = db2.session()
    assert s2.execute("select count(*), sum(v) from t").rows()[0] == \
        (5000, sum(i * 2 for i in range(5000)))
    assert s2.execute("select count(*) from u").rows()[0][0] == 2
    db2.close()


def test_wal_archive_and_pitr(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20)")
    full = full_backup(db, str(tmp_path / "base"))
    # capture the PITR target point AFTER the next commit
    s.execute("insert into t values (3, 30)")
    cut_version = db.tx.gts.current()
    # later commits that PITR must NOT restore
    s.execute("insert into t values (4, 40)")
    s.execute("update t set v = 999 where k = 1")
    archive = archive_wal(db, str(tmp_path / "arch"))
    db.close()

    target = str(tmp_path / "pitr")
    restore_chain(full, target)
    overlay_archive(archive, target)
    pitr_cut(target, cut_version)
    db2 = Database(target)
    s2 = db2.session()
    rows = s2.execute("select k, v from t order by k").rows()
    assert rows == [(1, 10), (2, 20), (3, 30)], rows
    # the restored instance keeps working (new writes replicate fine)
    s2.execute("insert into t values (5, 50)")
    assert s2.execute("select count(*) from t").rows()[0][0] == 4
    db2.close()


def test_archive_is_incremental(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("insert into t values (1)")
    arch = str(tmp_path / "arch")
    archive_wal(db, arch)
    import json

    with open(os.path.join(arch, "ARCHIVE_STATE.json")) as fh:
        st1 = json.load(fh)
    s.execute("insert into t values (2)")
    archive_wal(db, arch)
    with open(os.path.join(arch, "ARCHIVE_STATE.json")) as fh:
        st2 = json.load(fh)
    # progress points advanced (suffix-only copy)
    assert any(st2[k] > st1.get(k, 0) for k in st2)
    db.close()
