"""Differential fuzzer: random queries diffed against SQLite.

≙ the reference's mysqltest result-diff philosophy, randomized: generate
projection/filter/join/aggregate/order-by combinations over typed tables
and require row-for-row agreement with SQLite.  Seeds are fixed so
failures reproduce.
"""

import sqlite3

import numpy as np
import pytest

from oceanbase_tpu.sql import Session

N_QUERIES = 60


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(11)
    n1, n2 = 400, 120
    t1 = {
        "a": rng.integers(-20, 20, n1),
        "b": rng.integers(0, 8, n1),
        "f": np.round(rng.uniform(-10, 10, n1), 3),
        "s": rng.choice(np.array(["red", "green", "blue", "teal"]), n1),
    }
    nulls = rng.random(n1) < 0.15
    t2 = {
        "x": rng.integers(0, 8, n2),
        "y": rng.integers(-5, 5, n2),
        "w": rng.choice(np.array(["red", "blue", "pink"]), n2),
    }
    s = Session()
    s.catalog.load_numpy("t1", t1, valids={"b": ~nulls})
    s.catalog.load_numpy("t2", t2)
    conn = sqlite3.connect(":memory:")
    conn.execute("create table t1 (a, b, f, s)")
    conn.executemany(
        "insert into t1 values (?,?,?,?)",
        [(int(t1["a"][i]), None if nulls[i] else int(t1["b"][i]),
          float(t1["f"][i]), str(t1["s"][i])) for i in range(n1)])
    conn.execute("create table t2 (x, y, w)")
    conn.executemany("insert into t2 values (?,?,?)",
                     list(zip(t2["x"].tolist(), t2["y"].tolist(),
                              t2["w"].tolist())))
    # MySQL functions SQLite lacks: give the oracle reference impls
    conn.create_function("repeat", 2, lambda s_, n: None if s_ is None
                         else str(s_) * max(int(n), 0))
    conn.create_function(
        "lpad", 3, lambda s_, n, p: None if s_ is None else
        (str(s_)[:n] if len(str(s_)) >= n
         else (str(p) * n)[: n - len(str(s_))] + str(s_)))
    conn.create_function(
        "concat_ws", -1,
        lambda sep, *xs: sep.join(str(x) for x in xs if x is not None))
    conn.create_function("isnull", 1, lambda x: 1 if x is None else 0)
    conn.create_function("if", 3, lambda c, a, b: a if c else b)
    conn.create_function(
        "substring_index", 3, lambda s_, d, k: None if s_ is None else
        (d.join(str(s_).split(d)[:k]) if k >= 0
         else d.join(str(s_).split(d)[k:])))
    return s, conn


from conftest import rewrite_outer_join_for_old_sqlite


def _oracle_sql(sql: str) -> str:
    return rewrite_outer_join_for_old_sqlite(
        sql, "t1", "t2", ("a", "b", "f", "s"), ("x", "y", "w"))


def _gen_query(rng) -> str:
    preds = [
        "a > 0", "a between -5 and 10", "b = 3", "b is null",
        "b is not null", "s = 'red'", "s in ('red', 'blue')",
        "s like '%e%'", "f < 2.5", "a % 3 = 0", "abs(a) > 10",
        "not (a > 0)", "a > 0 or b = 2", "length(s) = 4",
    ]
    aggs = ["count(*)", "sum(a)", "min(f)", "max(a)", "avg(a)", "count(b)"]
    shape = rng.integers(0, 10)
    where = ""
    if rng.random() < 0.8:
        k = int(rng.integers(1, 3))
        chosen = list(rng.choice(preds, k, replace=False))
        where = " where " + " and ".join(chosen)
    if shape == 0:      # projection + filter + order
        return f"select a, b, s from t1{where} order by a, b, s, f"
    if shape == 1:      # scalar aggregates
        k = int(rng.integers(1, 4))
        cols = ", ".join(f"{a} as c{i}"
                         for i, a in enumerate(rng.choice(aggs, k,
                                                          replace=False)))
        return f"select {cols} from t1{where}"
    if shape == 2:      # group by
        agg = rng.choice(aggs)
        return (f"select b, {agg} as agg1 from t1{where} "
                f"group by b order by b")
    if shape == 3:      # window functions
        wf = rng.choice([
            "row_number() over (partition by s order by a, f)",
            "rank() over (partition by b order by a)",
            "sum(a) over (partition by s)",
            "count(*) over (partition by b order by a, f)",
        ])
        return f"select a, s, {wf} as w from t1{where} order by a, f, s"
    if shape == 4:      # CTE + derived table
        return (f"with base as (select a, b, s from t1{where}) "
                f"select s, count(*) as n from base group by s order by s")
    if shape == 5:      # set operation
        op = rng.choice(["union", "union all", "except", "intersect"])
        return (f"select b from t1{where} {op} "
                f"select x from t2 order by 1")
    if shape == 6:      # join + aggregate
        return (f"select s, count(*) as n, sum(y) as sy from t1, t2 "
                f"where b = x{' and ' + rng.choice(preds) if rng.random() < 0.5 else ''} "
                f"group by s order by s")
    if shape == 7:      # outer joins (round 4: RIGHT/FULL)
        kind = rng.choice(["left", "right", "full outer"])
        return (f"select a, b, x, y from t1 {kind} join t2 on b = x"
                f"{where} order by a, b, x, y")
    if shape == 8:      # round-4 window functions + ROWS frames
        wf = rng.choice([
            "lag(a) over (partition by b order by a, f)",
            "lead(a, 2) over (partition by s order by a, f)",
            "ntile(3) over (order by a, f)",
            "first_value(a) over (partition by s order by a, f)",
            "sum(a) over (partition by s order by a, f "
            "rows between 2 preceding and current row)",
            "min(f) over (partition by b order by a, f "
            "rows between 1 preceding and 1 following)",
        ])
        return f"select a, s, {wf} as w from t1{where} order by a, f, s"
    # round-4 string/conditional functions
    fn = rng.choice([
        "concat_ws('-', s, s)", "if(a > 0, s, 'neg')",
        "instr(s, 'e')", "substring_index(s, 'e', 1)",
        "lpad(s, 6, '*')", "repeat(s, 2)",
    ])
    return f"select a, {fn} as r from t1{where} order by a, s, f"


def _normalize(rows):
    out = []
    for r in rows:
        row = []
        for x in r:
            if isinstance(x, float):
                row.append(round(x, 6))
            else:
                row.append(x)
        out.append(tuple(row))
    return sorted(out, key=lambda t: tuple((v is None, str(type(v)), v)
                                           for v in t))


def test_fuzz_vs_sqlite(env):
    s, conn = env
    rng = np.random.default_rng(99)
    failures = []
    for qi in range(N_QUERIES):
        sql = _gen_query(rng)
        try:
            got = _normalize(s.execute(sql).rows())
            want = _normalize(
                [tuple(r) for r in conn.execute(_oracle_sql(sql))])
        except Exception as e:  # noqa: BLE001
            failures.append((sql, f"exception {type(e).__name__}: {e}"))
            continue
        if len(got) != len(want):
            failures.append((sql, f"rowcount {len(got)} != {len(want)}"))
            continue
        for g, w in zip(got, want):
            ok = len(g) == len(w) and all(
                (a == pytest.approx(b, rel=1e-6)
                 if isinstance(a, float) or isinstance(b, float)
                 else a == b)
                for a, b in zip(g, w)
                if not (a is None and b is None))
            if not ok:
                failures.append((sql, f"row diff: {g} != {w}"))
                break
    assert not failures, "\n".join(f"{q}\n  -> {why}"
                                   for q, why in failures[:5])
