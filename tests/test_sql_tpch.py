"""TPC-H 22-query result parity vs the SQLite oracle (SURVEY §4 tier 4).

Scale factor via TPCH_SF (default 0.01 for the CI-speed suite; the
round evidence runs TPCH_SF=1 — see SF1_PARITY artifacts)."""

import os

import numpy as np
import pytest

from oceanbase_tpu.bench.oracle import load_sqlite, rows_match, run_oracle
from oceanbase_tpu.bench.tpch import TPCH_PRIMARY_KEYS, gen_tpch
from oceanbase_tpu.bench.tpch_queries import QUERIES
from oceanbase_tpu.sql import Session

SF = float(os.environ.get("TPCH_SF", "0.01"))


@pytest.fixture(scope="module")
def env():
    tables, types = gen_tpch(sf=SF)
    sess = Session()
    for name, arrays in tables.items():
        sess.catalog.load_numpy(
            name, arrays,
            types={k: v for k, v in types.items() if k in arrays},
            primary_key=TPCH_PRIMARY_KEYS[name],
        )
    conn = load_sqlite(tables, types)
    return sess, conn


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query(env, qnum):
    sess, conn = env
    sql = QUERIES[qnum]
    want = run_oracle(conn, sql)
    got = sess.execute(sql).rows()
    ordered = "order by" in sql.lower() and qnum not in (2, 18, 21)
    ok, why = rows_match(got, want, ordered=ordered)
    assert ok, f"Q{qnum}: {why}\n got[:3]={got[:3]}\nwant[:3]={want[:3]}"
