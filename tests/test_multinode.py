"""Multi-node cluster tests: 3 OS processes, networked PALF, statement
routing, DAS remote scan, leader kill + re-election.

≙ mittest/simple_server (ob_simple_server.h:21) booting real observer
processes and driving them over the wire; failover scenarios ≙ the
palf_cluster mittest.  These tests spawn `python -m
oceanbase_tpu.net.node` subprocesses — real sockets, real fsync, real
process kill.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from oceanbase_tpu.net.rpc import RpcClient, RpcError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class Cluster:
    def __init__(self, tmp_path, n=3, lease_ms=1500):
        self.n = n
        self.ports = _free_ports(n)
        self.procs: dict[int, subprocess.Popen] = {}
        self.tmp = tmp_path
        self.lease_ms = lease_ms
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        self.env = env
        for i in range(1, n + 1):
            self.start_node(i, bootstrap=(i == 1))
        self.clients = {i: RpcClient("127.0.0.1", self.ports[i - 1],
                                     timeout_s=30.0)
                        for i in range(1, n + 1)}
        self.wait_ready()

    def start_node(self, i, bootstrap=False):
        peers = ",".join(f"{j}=127.0.0.1:{self.ports[j - 1]}"
                         for j in range(1, self.n + 1) if j != i)
        cmd = [sys.executable, "-m", "oceanbase_tpu.net.node",
               "--node-id", str(i), "--port", str(self.ports[i - 1]),
               "--peers", peers, "--root",
               str(self.tmp / f"node{i}"),
               "--lease-ms", str(self.lease_ms)]
        if bootstrap:
            cmd.append("--bootstrap")
        self.procs[i] = subprocess.Popen(
            cmd, env=self.env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def wait_ready(self, timeout=60):
        deadline = time.time() + timeout
        for i, cli in self.clients.items():
            while time.time() < deadline:
                if self.procs[i].poll() is not None:
                    out = self.procs[i].stdout.read()
                    raise RuntimeError(f"node {i} died:\n{out[-3000:]}")
                if cli.ping():
                    break
                time.sleep(0.2)
            else:
                raise TimeoutError(f"node {i} not ready")

    def kill(self, i, sig=signal.SIGKILL):
        self.procs[i].send_signal(sig)
        self.procs[i].wait(timeout=10)

    def execute(self, i, sql, **kw):
        return self.clients[i].call("sql.execute", sql=sql, **kw)

    def rows(self, res):
        names = res["names"]
        n = res["rowcount"] if not names else len(
            next(iter(res["arrays"].values())))
        out = []
        for r in range(n):
            row = []
            for nm in names:
                v = res.get("valids", {}).get(nm)
                if v is not None and not v[r]:
                    row.append(None)
                else:
                    x = res["arrays"][nm][r]
                    row.append(x.item() if hasattr(x, "item") else x)
            out.append(tuple(row))
        return out

    def close(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass


@pytest.fixture()
def cluster(tmp_path):
    c = Cluster(tmp_path, n=3)
    yield c
    c.close()


def test_replication_and_routing(cluster):
    c = cluster
    # DDL + DML against the bootstrap leader (node 1)
    c.execute(1, "create table t (k int primary key, v int)")
    c.execute(1, "insert into t values (1, 10), (2, 20), (3, 30)")
    # write via a FOLLOWER: statement routes to the leader
    res = c.execute(2, "insert into t values (4, 40)")
    assert res["node"] == 1
    # strong read via a follower routes to the leader
    res = c.execute(3, "select k, v from t order by k")
    assert res["node"] == 1
    assert c.rows(res) == [(1, 10), (2, 20), (3, 30), (4, 40)]
    # replication: followers converge (weak local read)
    deadline = time.time() + 20
    while time.time() < deadline:
        res = c.execute(2, "select count(*) from t",
                        consistency="weak")
        if res["node"] == 2 and c.rows(res)[0][0] == 4:
            break
        time.sleep(0.3)
    else:
        raise AssertionError("follower never converged")


def test_das_remote_scan_endpoint(cluster):
    c = cluster
    c.execute(1, "create table r (k int primary key, s varchar(16))")
    c.execute(1, "insert into r values (1, 'aa'), (2, 'bb')")
    # scan the leader's snapshot directly (the DAS wire surface)
    got = c.clients[1].call("das.scan", table="r")
    assert got["total"] == 2
    assert sorted(got["arrays"]["k"].tolist()) == [1, 2]
    assert sorted(got["arrays"]["s"].tolist()) == ["aa", "bb"]
    # location: every node agrees on the home (the leader)
    st = c.clients[2].call("node.state")
    assert st["leader_hint"] == 1


def test_leader_kill_reelection_no_committed_loss(cluster):
    c = cluster
    c.execute(1, "create table t (k int primary key, v int)")
    c.execute(1, "insert into t values " + ", ".join(
        f"({i}, {i * 7})" for i in range(50)))
    # committed on a majority; kill the leader process outright
    c.kill(1)
    # a write via a surviving node forces re-election (2/3 quorum)
    deadline = time.time() + 40
    last = None
    while time.time() < deadline:
        try:
            res = c.execute(2, "insert into t values (1000, 1)")
            break
        except (RpcError, OSError, ConnectionError) as e:
            last = e
            time.sleep(0.5)
    else:
        raise AssertionError(f"no re-election: {last}")
    assert res["node"] in (2, 3)
    # committed data survived the failover
    res = c.execute(2, "select count(*), sum(v) from t where k < 1000")
    assert c.rows(res)[0] == (50, sum(i * 7 for i in range(50)))
    # and the new cluster keeps serving both nodes
    res = c.execute(3, "select count(*) from t")
    assert c.rows(res)[0][0] == 51


def test_killed_node_rejoins_and_catches_up(cluster):
    """A crashed node restarts from its WAL and catches up on writes it
    missed (≙ rebootstrap + fetch-log catch-up)."""
    c = cluster
    c.execute(1, "create table t (k int primary key, v int)")
    c.execute(1, "insert into t values (1, 1), (2, 2)")
    # take node 3 down; cluster keeps committing on 1+2
    c.kill(3)
    c.execute(1, "insert into t values (3, 3), (4, 4)")
    # restart node 3 from its data dir
    c.start_node(3)
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if c.clients[3].ping():
                res = c.execute(3, "select count(*) from t",
                                consistency="weak")
                if res["node"] == 3 and c.rows(res)[0][0] == 4:
                    break
        except Exception:
            pass
        time.sleep(0.5)
    else:
        raise AssertionError("rejoined node never caught up")
    # and it serves strong reads through the leader as before
    res = c.execute(3, "select sum(v) from t")
    assert c.rows(res)[0][0] == 10
