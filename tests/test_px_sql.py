"""Distributed SQL execution: PX planner over the 8-device CPU mesh.

≙ PX integration tests — the same SQL must produce identical results
serial and distributed (SURVEY §2.3 parity).
"""

import jax
import numpy as np
import pytest

from oceanbase_tpu.bench.tpch import TPCH_PRIMARY_KEYS, gen_tpch
from oceanbase_tpu.bench.tpch_queries import QUERIES
from oceanbase_tpu.sql import Session

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 devices")


@pytest.fixture(scope="module")
def sess():
    tables, types = gen_tpch(sf=0.01)
    s = Session()
    for name, arrays in tables.items():
        s.catalog.load_numpy(
            name, arrays,
            types={k: v for k, v in types.items() if k in arrays},
            primary_key=TPCH_PRIMARY_KEYS[name])
    return s


def _compare_serial_px(sess, sql, qname):
    sess.variables["px_dop"] = 0
    serial = sess.execute(sql).rows()
    sess.variables["px_dop"] = 8
    dist = sess.execute(sql).rows()
    sess.variables["px_dop"] = 0
    key = lambda r: tuple(
        (x is None, round(x, 6) if isinstance(x, float) else x) for x in r)
    a, b = sorted(dist, key=key), sorted(serial, key=key)
    assert len(a) == len(b), qname
    for ra, rb in zip(a, b):
        for xa, xb in zip(ra, rb):
            if isinstance(xa, float) or isinstance(xb, float):
                # float reduction order differs across shards
                assert xa == pytest.approx(xb, rel=1e-9), qname
            else:
                assert xa == xb, qname


def test_px_q6_scalar_agg(sess):
    _compare_serial_px(sess, QUERIES[6], "q6")


def test_px_q1_groupby(sess):
    _compare_serial_px(sess, QUERIES[1], "q1")


def test_px_q14_join(sess):
    _compare_serial_px(sess, QUERIES[14], "q14")


def test_px_q3_multi_join_groupby(sess):
    _compare_serial_px(sess, QUERIES[3], "q3")


def test_px_q5_six_way_join(sess):
    _compare_serial_px(sess, QUERIES[5], "q5")


def test_px_q12_semi(sess):
    _compare_serial_px(sess, QUERIES[12], "q12")


def test_px_fallback_on_unsupported(sess):
    # Q16 has count(distinct ...): distribution unsupported -> silent
    # serial fallback with identical results
    _compare_serial_px(sess, QUERIES[16], "q16")
