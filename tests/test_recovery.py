"""Crash recovery: restart replay, durable XA reconstruction, torn-tail
truncation, and checkpoint-bounded replay work.

≙ the reference's restart/HA suites: slog+checkpoint boot
(ob_server_checkpoint_slog_handler), XA crash recovery into prepared
state (src/storage/tx/ob_xa_service.h), and the palf log tail scan.
All deterministic and in-process (tier-1); the cluster-level
kill→restart→rejoin and wipe→rebuild scenarios live in
tests/test_failover.py -m slow.
"""

import os

import pytest

from oceanbase_tpu.palf.log import LogEntry, PalfReplica
from oceanbase_tpu.server import Database


def _crash(db):
    """Simulate a crash: abandon the process state WITHOUT checkpoint or
    graceful close (the WAL and slog are all recovery gets)."""
    db.ash.stop()
    db.jobs.stop()


# ---------------------------------------------------------------------------
# restart replay
# ---------------------------------------------------------------------------


def test_restart_replays_committed_writes(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    s.execute("update t set v = 99 where k = 2")
    s.execute("delete from t where k = 3")
    _crash(db)

    db2 = Database(root)
    rows = db2.session().execute("select k, v from t order by k").rows()
    assert rows == [(1, 10), (2, 99)]
    # a second generation of writes + crash replays on top
    db2.session().execute("insert into t values (4, 40)")
    _crash(db2)
    db3 = Database(root)
    rows = db3.session().execute("select k, v from t order by k").rows()
    assert rows == [(1, 10), (2, 99), (4, 40)]
    db3.close()


def test_checkpoint_bounds_replay_work(tmp_path):
    """After a checkpoint, restart replay covers only the WAL tail —
    O(tail), not O(history)."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    for i in range(40):
        s.execute(f"insert into t values ({i}, {i})")
    db.checkpoint()
    for i in range(40, 45):
        s.execute(f"insert into t values ({i}, {i})")
    _crash(db)

    db2 = Database(root)
    s2 = db2.session()
    assert s2.execute("select count(*) from t").rows()[0][0] == 45
    ev = db2.tenant("sys").recovery.last("boot_replay")
    assert ev is not None
    # the replay point moved past the pre-checkpoint history: the tail
    # (5 inserts = 5 redo + 5 commit records) is all that replays
    assert ev["wal_start_lsn"] > 0
    assert 0 < ev["entries"] <= 12
    rows = s2.execute(
        "select phase, wal_start_lsn, entries from gv$recovery"
        " where phase = 'boot_replay'").rows()
    assert rows and rows[-1][1] == ev["wal_start_lsn"]
    db2.close()


def test_restart_tx_ids_do_not_collide(tmp_path):
    """Replayed transaction ids seed the allocator: a new tx must not
    reuse a replayed id (a reconstructed prepared branch keys its
    uncommitted state by tx id)."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key)")
    for i in range(5):
        s.execute(f"insert into t values ({i})")
    s.execute("xa start 'c1'")
    s.execute("insert into t values (100)")
    s.execute("xa end 'c1'")
    s.execute("xa prepare 'c1'")
    _crash(db)

    db2 = Database(root)
    svc = db2.tenant("sys").tx
    branch = svc.xa_transactions["c1"]
    tx = svc.begin()
    assert tx.tx_id > branch.tx_id
    svc.rollback(tx)
    db2.close()


# ---------------------------------------------------------------------------
# durable XA
# ---------------------------------------------------------------------------


def test_prepared_xa_branch_survives_crash_and_commits(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10)")
    s.execute("xa start 'x1'")
    s.execute("insert into t values (2, 20)")
    s.execute("update t set v = 11 where k = 1")
    s.execute("xa end 'x1'")
    s.execute("xa prepare 'x1'")
    # a branch that COMMITTED before the crash must not resurface
    s.execute("xa start 'x2'")
    s.execute("insert into t values (3, 30)")
    s.execute("xa end 'x2'")
    s.execute("xa prepare 'x2'")
    s.execute("xa commit 'x2'")
    _crash(db)

    db2 = Database(root)
    s2 = db2.session()
    # prepared-but-uncommitted writes stay invisible...
    assert s2.execute("select k, v from t order by k").rows() == \
        [(1, 10), (3, 30)]
    # ...but the branch is RECOVERABLE, not rolled back
    assert s2.execute("xa recover").rows() == [("x1",)]
    rows = s2.execute("select xids from gv$recovery"
                      " where phase = 'restore_prepared'").rows()
    assert rows == [("x1",)]
    s2.execute("xa commit 'x1'")
    assert s2.execute("select k, v from t order by k").rows() == \
        [(1, 11), (2, 20), (3, 30)]
    assert s2.execute("xa recover").rows() == []
    _crash(db2)
    # the recovered commit is itself durable
    db3 = Database(root)
    s3 = db3.session()
    assert s3.execute("select k, v from t order by k").rows() == \
        [(1, 11), (2, 20), (3, 30)]
    assert s3.execute("xa recover").rows() == []
    db3.close()


def test_prepared_xa_branch_recovered_rollback(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("xa start 'r1'")
    s.execute("insert into t values (7)")
    s.execute("xa end 'r1'")
    s.execute("xa prepare 'r1'")
    _crash(db)

    db2 = Database(root)
    s2 = db2.session()
    assert s2.execute("xa recover").rows() == [("r1",)]
    s2.execute("xa rollback 'r1'")
    assert s2.execute("select count(*) from t").rows()[0][0] == 0
    # the xid frees up and the rollback is durable
    _crash(db2)
    db3 = Database(root)
    s3 = db3.session()
    assert s3.execute("xa recover").rows() == []
    assert s3.execute("select count(*) from t").rows()[0][0] == 0
    s3.execute("xa start 'r1'")
    s3.execute("insert into t values (8)")
    s3.execute("xa end 'r1'")
    s3.execute("xa commit 'r1' one phase")
    assert s3.execute("select k from t").rows() == [(8,)]
    db3.close()


def test_prepared_xa_survives_checkpoint_then_crash(tmp_path):
    """The checkpoint replay point clamps at a pending prepared branch:
    its redo lives ONLY in the WAL, so advancing past the prepare batch
    would lose the branch at the next restart."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("xa start 'k1'")
    s.execute("insert into t values (5, 50)")
    s.execute("xa end 'k1'")
    s.execute("xa prepare 'k1'")
    # unrelated committed traffic + a checkpoint AFTER the prepare
    s.execute("insert into t values (6, 60)")
    db.checkpoint()
    svc = db.tenant("sys").tx
    assert svc.min_prepared_lsn() is not None
    assert db.engine.meta["wal_lsn"] <= svc.min_prepared_lsn()
    _crash(db)

    db2 = Database(root)
    s2 = db2.session()
    assert s2.execute("xa recover").rows() == [("k1",)]
    s2.execute("xa commit 'k1'")
    assert s2.execute("select k, v from t order by k").rows() == \
        [(5, 50), (6, 60)]
    # committing released the clamp: the next checkpoint advances
    db2.checkpoint()
    assert db2.tenant("sys").tx.min_prepared_lsn() is None
    _crash(db2)
    db3 = Database(root)
    assert db3.session().execute(
        "select k, v from t order by k").rows() == [(5, 50), (6, 60)]
    db3.close()


# ---------------------------------------------------------------------------
# palf torn-tail truncation
# ---------------------------------------------------------------------------


def test_torn_tail_truncate_roundtrip(tmp_path):
    """Appends after a torn tail must survive the NEXT recovery: the
    file physically truncates to the last valid entry before append
    mode reopens (the old behavior wrote new entries after the garbage,
    where the next recovery's scan never reached them)."""
    d = str(tmp_path)
    r = PalfReplica(1, d)
    r.role = "leader"
    r.leader_append([b"a", b"b"])
    r.close()
    path = os.path.join(d, "replica_1.log")
    size_clean = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x07torn-partial-entry")

    r2 = PalfReplica(1, d)
    assert [e.payload for e in r2.entries] == [b"a", b"b"]
    assert os.path.getsize(path) == size_clean  # garbage truncated
    r2.role = "leader"
    r2.current_term = r2.entries[-1].term
    r2.leader_append([b"c"])
    r2.close()

    r3 = PalfReplica(1, d)
    assert [e.payload for e in r3.entries] == [b"a", b"b", b"c"]
    r3.close()


def test_torn_tail_corrupt_crc(tmp_path):
    """A bit-flipped tail entry truncates; earlier entries survive."""
    d = str(tmp_path)
    r = PalfReplica(1, d)
    r.role = "leader"
    entries = r.leader_append([b"aaaa", b"bbbb"])
    r.close()
    path = os.path.join(d, "replica_1.log")
    # flip a payload byte of the LAST entry (crc now mismatches)
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        f.write(b"\xff")
    r2 = PalfReplica(1, d)
    assert [e.payload for e in r2.entries] == [b"aaaa"]
    assert entries[0].lsn == 1
    r2.role = "leader"
    r2.leader_append([b"cccc"])
    r2.close()
    r3 = PalfReplica(1, d)
    assert [e.payload for e in r3.entries] == [b"aaaa", b"cccc"]
    r3.close()


def test_unreadable_log_quarantined(tmp_path):
    """A log with a foreign magic is moved aside (uniquely named,
    surfaced to gv$recovery, retention-capped), never appended after."""
    from oceanbase_tpu.storage.recovery import RecoveryState

    d = str(tmp_path)
    path = os.path.join(d, "replica_1.log")
    with open(path, "wb") as f:
        f.write(b"NOTMAGIC" + b"\x00" * 64)
    rec = RecoveryState(1)
    r = PalfReplica(1, d, recovery=rec)
    assert r.entries == []
    r.role = "leader"
    r.leader_append([b"x"])
    r.close()
    corrupt = [n for n in os.listdir(d) if ".corrupt" in n]
    assert len(corrupt) == 1
    ev = rec.last("quarantine")
    assert ev is not None and ev["bytes"] == 72
    r2 = PalfReplica(1, d)
    assert [e.payload for e in r2.entries] == [b"x"]
    r2.close()


def test_quarantine_retention_capped(tmp_path):
    """Repeated quarantines never grow the log dir unbounded."""
    from oceanbase_tpu.palf.log import QUARANTINE_KEEP

    d = str(tmp_path)
    path = os.path.join(d, "replica_1.log")
    for _ in range(QUARANTINE_KEEP + 3):
        with open(path, "wb") as f:
            f.write(b"NOTMAGIC" + b"\x00" * 16)
        PalfReplica(1, d).close()
    corrupt = [n for n in os.listdir(d) if ".corrupt" in n]
    assert 1 <= len(corrupt) <= QUARANTINE_KEEP


def test_follower_accept_after_torn_tail(tmp_path):
    """The follower path persists through the truncated tail too."""
    d = str(tmp_path)
    r = PalfReplica(2, d)
    r.accept(0, 0, [LogEntry(1, 1, b"p1"), LogEntry(1, 2, b"p2")])
    r.close()
    path = os.path.join(d, "replica_2.log")
    with open(path, "ab") as f:
        f.write(b"junk")
    r2 = PalfReplica(2, d)
    assert r2.last_lsn() == 2
    assert r2.accept(2, 1, [LogEntry(1, 3, b"p3")])
    r2.close()
    r3 = PalfReplica(2, d)
    assert [e.payload for e in r3.entries] == [b"p1", b"p2", b"p3"]
    r3.close()


# ---------------------------------------------------------------------------
# failure detector satellite: transition timestamps + prompt down→up
# ---------------------------------------------------------------------------


def test_health_transition_ts_and_prompt_recovery():
    from oceanbase_tpu.net.health import DOWN, UP, HealthMonitor

    mon = HealthMonitor(1, {}, suspect_after=2, down_after=4)
    mon.observer(9)
    row = mon.snapshot()[0]
    assert row["last_transition_ts"] == 0.0
    for _ in range(4):
        mon.record_failure(9)
    row = mon.snapshot()[0]
    assert row["state"] == DOWN
    t_down = row["last_transition_ts"]
    assert t_down > 0
    # ONE success flips the breaker straight back to up
    mon.record_success(9, 0.001)
    row = mon.snapshot()[0]
    assert row["state"] == UP
    assert row["last_transition_ts"] >= t_down
    assert row["consecutive_failures"] == 0


def test_rebuild_policies_registered():
    """Standing contract: every rebuild/recovery verb has a POLICIES
    entry; the chunked fetches are idempotent with a retry budget."""
    from oceanbase_tpu.net.rpc import POLICIES

    for verb in ("rebuild.fetch_meta", "rebuild.fetch_segments",
                 "recovery.state"):
        assert verb in POLICIES, verb
        assert POLICIES[verb].idempotent
        assert POLICIES[verb].max_retries >= 1


def test_needs_rebuild_detection(tmp_path):
    from oceanbase_tpu.net.rebuild import needs_rebuild

    root = str(tmp_path)
    assert needs_rebuild(root, 3)  # nothing at all
    # a non-trivial WAL is a local recovery source: no rebuild
    os.makedirs(os.path.join(root, "wal"))
    with open(os.path.join(root, "wal", "replica_3.log"), "wb") as f:
        f.write(b"OBTPULG1" + b"\x01" * 32)
    assert not needs_rebuild(root, 3)
    os.remove(os.path.join(root, "wal", "replica_3.log"))
    assert needs_rebuild(root, 3)
    # a manifest alone is a recovery source too
    os.makedirs(os.path.join(root, "data"))
    with open(os.path.join(root, "data", "manifest.json"), "w") as f:
        f.write("{}")
    assert not needs_rebuild(root, 3)


def test_gv_recovery_catchup_row_absent_single_node(tmp_path):
    """The live catchup row is cluster-only; the single-node surface
    still serves the table (schema intact, events present)."""
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    db.checkpoint()
    rows = s.execute(
        "select phase from gv$recovery order by ts").rows()
    phases = [r[0] for r in rows]
    assert "checkpoint" in phases
    assert "catchup" not in phases
    db.close()


def test_recovered_branch_blocks_conflicting_writes(tmp_path):
    """A reconstructed prepared branch keeps its lock-like presence: a
    concurrent write to its keys conflicts (as it would have before the
    crash) instead of silently racing the pending XA COMMIT."""
    from oceanbase_tpu.tx.errors import WriteConflict

    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 10)")
    s.execute("xa start 'b1'")
    s.execute("update t set v = 11 where k = 1")
    s.execute("xa end 'b1'")
    s.execute("xa prepare 'b1'")
    _crash(db)

    db2 = Database(root)
    s2 = db2.session()
    assert s2.execute("xa recover").rows() == [("b1",)]
    with pytest.raises(WriteConflict):
        s2.execute("update t set v = 99 where k = 1")
    # an unrelated key is untouched by the branch's presence
    s2.execute("insert into t values (2, 20)")
    s2.execute("xa commit 'b1'")
    assert s2.execute("select k, v from t order by k").rows() == \
        [(1, 11), (2, 20)]
    # after the commit the key writes normally again
    s2.execute("update t set v = 12 where k = 1")
    assert s2.execute("select v from t where k = 1").rows() == [(12,)]
    db2.close()


def test_rebuild_resolve_refuses_traversal(tmp_path):
    from oceanbase_tpu.net.rebuild import RebuildServer

    class _N:
        root = str(tmp_path)
        node_id = 3

    srv = RebuildServer(_N())
    for bad in ("data/../config.json", "/etc/passwd",
                "data/../../x", "wal/replica_1.log", "config.json"):
        with pytest.raises(PermissionError):
            srv._resolve(bad)
    ok = srv._resolve("data/segments/t_1.npz")
    assert ok.endswith(os.path.join("data", "segments", "t_1.npz"))


def test_xa_branch_without_prepare_still_rolls_back(tmp_path):
    """An ACTIVE (never prepared) XA branch dies with the crash — only
    PREPARED branches recover (the XA contract)."""
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("xa start 'a1'")
    s.execute("insert into t values (1)")
    s.execute("xa end 'a1'")
    _crash(db)  # no prepare: redo never reached the WAL

    db2 = Database(root)
    s2 = db2.session()
    assert s2.execute("xa recover").rows() == []
    assert s2.execute("select count(*) from t").rows()[0][0] == 0
    db2.close()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
