"""Overload-robustness plane: statement admission + fair queuing,
deadlines & KILL, write backpressure, dtl.cancel, bounded rpc pool
(server/admission.py, net/rpc.py, px/dtl.py).

≙ the resource-manager / large-query-queue / writing-throttling mittest
suites.  Everything here is in-process and fast (tier-1); the 3-node
overload_shed storm lives in scripts/chaos_bench.py and the offered-load
gate in scripts/overload_bench.py.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from oceanbase_tpu.server.admission import (
    AdmissionController,
    MemstoreFull,
    MemstoreThrottle,
    QueryKilled,
    QueryTimeout,
    RemoteCtx,
    ServerBusy,
    StmtCtx,
    activate,
    checkpoint,
)
from oceanbase_tpu.server.config import Config
from oceanbase_tpu.server.database import Database


# ---------------------------------------------------------------------------
# controller unit tests (no database)
# ---------------------------------------------------------------------------


def _cfg(**over):
    c = Config()
    for k, v in over.items():
        c.set(k, v)
    return c


def _ctx(sid=1, tenant="sys", timeout_s=None, controller=None):
    return StmtCtx(session_id=sid, tenant=tenant, timeout_s=timeout_s,
                   controller=controller)


def test_slot_checkout_release_and_stats():
    adm = AdmissionController(_cfg(admission_slots=2,
                                   admission_tenant_slots=2))
    a, b = _ctx(1), _ctx(2)
    adm.acquire(a)
    adm.acquire(b)
    assert adm.active_slots() == 2
    adm.release(a)
    adm.release(b)
    assert adm.active_slots() == 0
    row = adm.stats()[0]
    assert row["tenant"] == "sys" and row["admitted"] == 2


def test_full_queue_rejects_serverbusy_fast():
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   admission_queue_limit=0))
    adm.acquire(_ctx(1))
    t0 = time.monotonic()
    with pytest.raises(ServerBusy):
        adm.acquire(_ctx(2))
    assert time.monotonic() - t0 < 1.0  # rejected fast, no wait
    assert adm.stats()[0]["rejected"] == 1


def test_queue_wait_budget_rejects_typed():
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   admission_queue_limit=4,
                                   admission_queue_timeout_s=0.15))
    adm.acquire(_ctx(1))
    t0 = time.monotonic()
    with pytest.raises(ServerBusy):
        adm.acquire(_ctx(2))
    dt = time.monotonic() - t0
    assert 0.1 <= dt < 2.0  # waited the budget, then failed typed


def test_queued_statement_grants_on_release():
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1))
    a = _ctx(1)
    adm.acquire(a)
    got = []

    def waiter():
        c = _ctx(2)
        adm.acquire(c)
        got.append(c)
        adm.release(c)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.1)
    assert not got  # still queued
    adm.release(a)
    th.join(5)
    assert got and got[0].queue_s > 0


def test_wrr_fairness_across_tenants():
    """One slot, a loud tenant with 8 waiters vs a quiet one with 2:
    round-robin interleaves grants — the quiet tenant's statements do
    not sit behind the loud tenant's whole backlog."""
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   admission_queue_limit=16,
                                   admission_queue_timeout_s=30.0))
    hold = _ctx(0)
    adm.acquire(hold)
    order: list[str] = []
    lock = threading.Lock()
    threads = []

    def waiter(sid, tenant):
        c = StmtCtx(session_id=sid, tenant=tenant)
        adm.acquire(c)
        with lock:
            order.append(tenant)
        time.sleep(0.01)
        adm.release(c)

    for i in range(8):
        threads.append(threading.Thread(target=waiter,
                                        args=(10 + i, "loud")))
    for i in range(2):
        threads.append(threading.Thread(target=waiter,
                                        args=(50 + i, "quiet")))
    for t in threads:
        t.start()
    time.sleep(0.2)  # everyone queued behind `hold`
    adm.release(hold)
    for t in threads:
        t.join(20)
    assert len(order) == 10
    # both quiet statements admitted within the first half of grants:
    # WRR alternates tenants instead of draining `loud` first
    assert all(t in order[:6] for t in ["quiet"]) and \
        order[:6].count("quiet") == 2, order


def test_wrr_weight_biases_grants():
    cfg = _cfg(admission_slots=1, admission_tenant_slots=1,
               admission_queue_limit=16,
               admission_queue_timeout_s=30.0)
    weights = {"heavy": 2, "light": 1}
    adm = AdmissionController(cfg, weight_of=lambda t: weights.get(t, 1))
    hold = _ctx(0)
    adm.acquire(hold)
    order = []
    lock = threading.Lock()

    def waiter(sid, tenant):
        c = StmtCtx(session_id=sid, tenant=tenant)
        adm.acquire(c)
        with lock:
            order.append(tenant)
        adm.release(c)

    threads = [threading.Thread(target=waiter, args=(10 + i, "heavy"))
               for i in range(4)]
    threads += [threading.Thread(target=waiter, args=(50 + i, "light"))
                for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    adm.release(hold)
    for t in threads:
        t.join(20)
    # weight 2:1 -> heavy gets ~2 grants per light one in the prefix
    assert order.count("heavy") == 4 and order.count("light") == 4
    assert order[:3].count("heavy") >= 2


def test_kill_while_queued_raises_querykilled():
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   admission_queue_timeout_s=30.0))
    adm.acquire(_ctx(1))
    victim = _ctx(2)
    err = []

    def waiter():
        try:
            adm.acquire(victim)
        except BaseException as e:  # noqa: BLE001 — captured for assert
            err.append(e)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.1)
    victim.kill()
    th.join(5)
    assert err and isinstance(err[0], QueryKilled)


def test_checkpoint_timeout_and_kill():
    ctx = _ctx(timeout_s=0.05)
    with activate(ctx):
        checkpoint()  # inside the deadline: fine
        time.sleep(0.08)
        with pytest.raises(QueryTimeout):
            checkpoint()
    ctx2 = _ctx()
    with activate(ctx2):
        ctx2.kill()
        with pytest.raises(QueryKilled):
            checkpoint()
    checkpoint()  # no active ctx: no-op


def test_large_query_demotion_frees_slot():
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   large_query_threshold_s=0.01,
                                   admission_large_slots=2,
                                   admission_queue_timeout_s=30.0))
    big = _ctx(1, controller=adm)
    adm.acquire(big)
    got = []

    def pointq():
        c = _ctx(2)
        adm.acquire(c)
        got.append(c)
        adm.release(c)

    th = threading.Thread(target=pointq)
    th.start()
    time.sleep(0.1)
    assert not got  # the scan holds the only slot
    with activate(big):
        checkpoint()  # past the threshold: demotes to the large lane
    th.join(5)
    assert got, "demotion must free the normal slot for the point query"
    assert big.lane == "large" and big.demoted
    adm.release(big)
    assert adm.active_slots() == 0


def test_release_after_rejection_does_not_over_admit():
    """A rejected acquire holds nothing: the session's finally still
    calls release(ctx), which must NOT decrement someone else's slot
    (over-admitting by one per rejection under load)."""
    adm = AdmissionController(_cfg(admission_slots=1,
                                   admission_tenant_slots=1,
                                   admission_queue_limit=0))
    holder = _ctx(1)
    adm.acquire(holder)
    loser = _ctx(2)
    with pytest.raises(ServerBusy):
        adm.acquire(loser)
    adm.release(loser)  # what Session.execute's finally does
    assert adm.active_slots() == 1  # the holder's slot is intact
    # and the pool is still saturated: a third statement rejects too
    with pytest.raises(ServerBusy):
        adm.acquire(_ctx(3))
    adm.release(holder)
    assert adm.active_slots() == 0


def test_release_survives_knob_toggle_mid_statement():
    """ctx.slot records what was taken; flipping enable_admission (or
    slots to 0) mid-flight must neither leak nor double-free."""
    cfg = _cfg(admission_slots=2, admission_tenant_slots=2)
    adm = AdmissionController(cfg)
    a = _ctx(1)
    adm.acquire(a)
    cfg.set("enable_admission", False)
    adm.release(a)  # took a slot while enabled: must free it
    cfg.set("enable_admission", True)
    assert adm.active_slots() == 0
    # and the other direction: admitted while DISABLED holds nothing
    cfg.set("enable_admission", False)
    b = _ctx(2)
    adm.acquire(b)
    cfg.set("enable_admission", True)
    adm.release(b)
    assert adm.active_slots() == 0


def test_kill_reaches_queued_statement(db):
    """KILL <id> of a statement still waiting in the admission FIFO
    (state QUEUED) must cancel it — not silently no-op."""
    db.config.set("admission_slots", 1)
    db.config.set("admission_tenant_slots", 1)
    db.config.set("admission_queue_timeout_s", 30.0)
    hold = StmtCtx(session_id=998, tenant="sys")
    db.admission.acquire(hold)
    s, killer = db.session(), db.session()
    res: dict = {}

    def victim():
        try:
            res["r"] = s.execute("select 1")
        except BaseException as e:  # noqa: BLE001 — captured
            res["e"] = e

    th = threading.Thread(target=victim)
    th.start()
    time.sleep(0.15)  # victim is parked in the FIFO now
    assert killer.execute(f"kill {s.session_id}").rowcount == 1
    th.join(10)
    assert isinstance(res.get("e"), QueryKilled)
    db.admission.release(hold)
    assert db.admission.active_slots() == 0
    db.config.set("admission_slots", 32)
    db.config.set("admission_tenant_slots", 16)


def test_demotion_denied_then_killed_frees_exactly_once():
    """Kill while parked on a saturated large lane: the normal slot
    was already yielded at demote time, so release() must not free a
    second one."""
    adm = AdmissionController(_cfg(admission_slots=2,
                                   admission_tenant_slots=2,
                                   admission_large_slots=1,
                                   large_query_threshold_s=0.01))
    occupier = _ctx(1, controller=adm)
    adm.acquire(occupier)
    with activate(occupier):
        time.sleep(0.02)
        checkpoint()  # takes the only large slot
    assert occupier.lane == "large"
    victim = _ctx(2, controller=adm)
    adm.acquire(victim)
    err = []

    def run():
        with activate(victim):
            try:
                time.sleep(0.02)
                checkpoint()  # demotes; large lane full -> parks
            except BaseException as e:  # noqa: BLE001 — captured
                err.append(e)

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.15)
    victim.kill()
    th.join(5)
    assert err and isinstance(err[0], QueryKilled)
    adm.release(victim)  # held NOTHING: must not free a second slot
    assert adm.active_slots() == 1  # only the occupier's large slot
    adm.release(occupier)
    assert adm.active_slots() == 0


def test_memstore_accepts_exactly_to_the_limit():
    """An accepted write is never re-judged against its own bytes: a
    write that fits exactly must succeed, and a rejected write must
    not inflate the accounting."""
    cfg = _cfg(enable_rate_limit=True, memstore_limit_bytes=1000,
               writing_throttle_trigger_pct=99,
               writing_throttle_max_sleep_s=0.001)
    thr = MemstoreThrottle(cfg)
    row = {"a": 1}  # 72 bytes under the estimate
    nb = thr.row_bytes(row)
    fits = 1000 // nb
    for _ in range(fits):
        thr.admit_write("t", row)  # every one fits: no spurious wall
    used = thr.used_bytes()
    assert used == fits * nb <= 1000
    with pytest.raises(MemstoreFull):
        thr.admit_write("t", row)
    # the rejected row left no trace in the accounting
    assert thr.used_bytes() == used


def test_remote_ctx_observes_cancel_event():
    ev = threading.Event()
    with activate(RemoteCtx(ev, token="tok")):
        checkpoint()
        ev.set()
        with pytest.raises(QueryKilled):
            checkpoint()


# ---------------------------------------------------------------------------
# memstore throttle (unit)
# ---------------------------------------------------------------------------


def test_memstore_hard_limit_typed_and_recovers():
    cfg = _cfg(enable_rate_limit=True, memstore_limit_bytes=4096,
               writing_throttle_trigger_pct=50,
               writing_throttle_max_sleep_s=0.001)
    flushed = []
    thr = MemstoreThrottle(cfg, flush_cb=flushed.append)
    row = {"a": 1, "b": "x" * 100}
    with pytest.raises(MemstoreFull):
        for _ in range(1000):
            thr.admit_write("t", row)
    st = thr.stats()
    assert st["memstore_bytes"] <= 4096  # the limit held
    # rejected writes never account, so used sits just UNDER the
    # limit while the wall is up — deep in the throttle band
    assert st["throttle_state"] in ("throttle", "full")
    assert thr.throttle_sleeps > 0  # the ramp fired before the wall
    assert flushed and flushed[0] == "t"  # pressure kicked a flush
    # flush catches up: accounting re-bases, writes admit again
    thr.on_flush("t", remaining_rows=0)
    thr.admit_write("t", row)
    assert thr.stats()["throttle_state"] in ("ok", "throttle")


def test_memstore_accounting_rebase_keeps_avg():
    cfg = _cfg(enable_rate_limit=True, memstore_limit_bytes=1 << 20)
    thr = MemstoreThrottle(cfg)
    for _ in range(10):
        thr.admit_write("t", {"a": 1})
    before = thr.used_bytes()
    thr.on_flush("t", remaining_rows=5)
    assert 0 < thr.used_bytes() < before


# ---------------------------------------------------------------------------
# SQL-level integration (Database + sessions)
# ---------------------------------------------------------------------------


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def _load_big(s, n=20000):
    s.execute("create table big (a int primary key, b int)")
    vals = ", ".join(f"({i}, {i % 97})" for i in range(n))
    s.execute(f"insert into big values {vals}")


def test_query_timeout_typed_sql(db):
    s = db.session()
    _load_big(s)
    db.config.set("sql_work_area_rows", 512)  # spill: many checkpoints
    s.execute("set query_timeout_s = 0.05")
    with pytest.raises(QueryTimeout):
        s.execute("select sum(b), count(*) from big where b < 90")
    # the deadline is per statement, not sticky damage: raise it back
    s.execute("set query_timeout_s = 3600")
    r = s.execute("select count(*) from big")
    assert r.rows() == [(20000,)]


def test_kill_query_mid_statement_and_hygiene(db):
    """KILL unwinds a long (spilling) scan at a chunk checkpoint; no
    dangling spill files, no leaked admission slots, no locked session,
    and gv$sql_audit records the typed error."""
    s = db.session()
    _load_big(s)
    db.config.set("sql_work_area_rows", 512)
    killer = db.session()
    res: dict = {}

    def victim():
        try:
            res["r"] = s.execute(
                "select sum(b), count(*) from big where b < 70")
        except BaseException as e:  # noqa: BLE001 — captured
            res["e"] = e

    th = threading.Thread(target=victim)
    th.start()
    time.sleep(0.15)
    killer.execute(f"kill query {s.session_id}")
    th.join(15)
    assert not th.is_alive(), "killed statement hung"
    assert isinstance(res.get("e"), QueryKilled)
    # hygiene: spill temp dirs removed, slots back to baseline,
    # session usable, audit shows the typed error
    tmpdir = os.path.join(db.root, "tmpfile")
    leftovers = os.listdir(tmpdir) if os.path.isdir(tmpdir) else []
    assert leftovers == []
    assert db.admission.active_slots() == 0
    assert s.execute("select 1").rows() == [(1,)]
    errs = [r.error for r in db.audit.recent(None) if r.error]
    assert any("QueryKilled" in e for e in errs)


def test_kill_unknown_session_and_idle_session(db):
    s = db.session()
    with pytest.raises(KeyError):
        s.execute("kill query 987654")
    with pytest.raises(KeyError):
        s.execute("kill 987654")  # plain KILL checks existence too
    # KILL QUERY on an idle session: nothing in flight, 0 rows, the
    # session stays usable
    s2 = db.session()
    assert s.execute(f"kill query {s2.session_id}").rowcount == 0
    assert s2.execute("select 1").rows() == [(1,)]
    # plain KILL EVICTS the session: later statements fail typed
    assert s.execute(f"kill {s2.session_id}").rowcount == 1
    with pytest.raises(QueryKilled):
        s2.execute("select 1")
    s2.close()
    s3 = db.session()  # fresh session (reconnect): works
    assert s3.execute("select 1").rows() == [(1,)]


def test_memstore_flush_token_survives_unflushable_kick():
    """A kick that cannot flush (oversized first write: nothing
    accounted yet) must not wedge the one-shot token and disable
    pressure flushes forever."""
    cfg = _cfg(enable_rate_limit=True, memstore_limit_bytes=2048,
               writing_throttle_trigger_pct=50,
               writing_throttle_max_sleep_s=0.001)
    flushed = []
    thr = MemstoreThrottle(cfg, flush_cb=flushed.append)
    with pytest.raises(MemstoreFull):
        thr.admit_write("t", {"a": "x" * 4096})  # bigger than limit
    assert not thr._flush_inflight
    small = {"a": "y" * 400}
    with pytest.raises(MemstoreFull):
        for _ in range(100):
            thr.admit_write("t", small)
    assert flushed, "pressure flush never kicked after the bad first kick"


def test_gv_tenant_resource_large_lane_is_per_tenant():
    adm = AdmissionController(_cfg(admission_slots=4,
                                   admission_tenant_slots=4,
                                   admission_large_slots=2,
                                   large_query_threshold_s=0.01))
    a = StmtCtx(session_id=1, tenant="t1", controller=adm)
    adm.acquire(a)
    with activate(a):
        time.sleep(0.02)
        checkpoint()  # demotes into the large lane
    rows = {r["tenant"]: r for r in adm.stats()}
    assert rows["t1"]["large_in_use"] == 1
    b = StmtCtx(session_id=2, tenant="t2", controller=adm)
    adm.acquire(b)
    rows = {r["tenant"]: r for r in adm.stats()}
    assert rows["t2"]["large_in_use"] == 0  # not t1's demoted scan
    adm.release(a)
    adm.release(b)
    rows = {r["tenant"]: r for r in adm.stats()}
    assert rows["t1"]["large_in_use"] == 0


def test_serverbusy_typed_under_saturation(db):
    """admission_slots=1 + zero queue: a second concurrent statement
    rejects typed while the first runs."""
    db.config.set("admission_slots", 1)
    db.config.set("admission_tenant_slots", 1)
    db.config.set("admission_queue_limit", 0)
    s1, s2 = db.session(), db.session()
    _load_big(s1, n=4000)
    db.config.set("sql_work_area_rows", 256)
    errs: list = []
    started = threading.Event()

    def long_q():
        started.set()
        s1.execute("select sum(b), count(*) from big where b < 90")

    def busy_q():
        started.wait(5)
        time.sleep(0.05)
        try:
            s2.execute("select count(*) from big")
        except ServerBusy as e:
            errs.append(e)

    t1 = threading.Thread(target=long_q)
    t2 = threading.Thread(target=busy_q)
    t1.start()
    t2.start()
    t1.join(30)
    t2.join(30)
    assert errs, "second statement should have been rejected typed"
    # restore generous knobs for the fixture teardown's own statements
    db.config.set("admission_queue_limit", 64)
    db.config.set("admission_slots", 32)


def test_queue_s_in_audit_and_admission_wait_span(db):
    db.config.set("admission_slots", 1)
    db.config.set("admission_tenant_slots", 1)
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (a int primary key)")
    s1.execute("insert into t values (1)")
    # hold the only slot directly through the controller
    hold_ctx = StmtCtx(session_id=999, tenant="sys")
    db.admission.acquire(hold_ctx)
    res: dict = {}

    def waiter():
        res["r"] = s2.execute("select count(*) from t")

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.12)
    db.admission.release(hold_ctx)
    th.join(10)
    assert res["r"].rows() == [(1,)]
    recs = [r for r in db.audit.recent(None)
            if r.session_id == s2.session_id and "count" in r.sql]
    assert recs and recs[-1].queue_s > 0.05
    rows = s1.execute(
        "select span_name from gv$trace").arrays.get("span_name", [])
    assert "admission.wait" in set(rows)


def test_px_downgrade_counted_and_visible(db, monkeypatch):
    """Drained px_admission: the downgrade is counted, span-tagged and
    shown by EXPLAIN ANALYZE instead of silently running serial."""
    from oceanbase_tpu.server import metrics as qmetrics

    s = db.session()
    _load_big(s, n=2000)
    s.execute("set px_dop = 2")
    t = db.tenant("sys")
    # drain the quota (non-blocking: grab everything grantable)
    grabbed = 0
    while t.px_admission.acquire(blocking=False):
        grabbed += 1
    try:
        before = qmetrics.counter_value("admission.px_downgrades")
        r = s.execute("select sum(b) from big")
        assert r.rowcount == 1
        after = qmetrics.counter_value("admission.px_downgrades")
        assert after > before
        txt = s.execute(
            "explain analyze select sum(b) from big").plan_text
        assert "admission denied" in txt
    finally:
        for _ in range(grabbed):
            t.px_admission.release()


def test_gv_tenant_resource_rows(db):
    s = db.session()
    s.execute("create table t (a int primary key)")
    s.execute("insert into t values (1)")
    r = s.execute("select tenant, slots_total, queue_limit, "
                  "memstore_limit_bytes, throttle_state "
                  "from gv$tenant_resource")
    rows = r.rows()
    assert len(rows) == 1 and rows[0][0] == "sys"
    assert rows[0][1] > 0 and rows[0][3] > 0
    assert rows[0][4] in ("ok", "throttle", "full", "off")


def test_show_processlist_states(db):
    s = db.session()
    s.execute("create table t (a int primary key)")
    r = s.execute("show processlist")
    i = r.names.index("state")
    states = {row[i] for row in r.rows()}
    assert states <= {"RUNNING", "QUEUED", "KILLED", "IDLE"}
    assert "RUNNING" in states  # this statement itself


def test_memstore_backpressure_sql(db):
    """A write flood against a tiny memstore budget while an old open
    transaction pins the flush horizon (flushes cannot drain): bytes
    stay under the hard limit, writes fail typed MemstoreFull, and the
    flood is survivable once the pin commits and the flush catches
    up."""
    s = db.session()
    s.execute("create table w (a int primary key, b string)")
    # the pin: an ACTIVE transaction with an old snapshot clamps the
    # flush horizon, so pressure flushes retain the flood's versions
    pin = db.session()
    pin.execute("begin")
    pin.execute("insert into w values (-1, 'pin')")
    db.config.set("memstore_limit_bytes", 40000)
    db.config.set("writing_throttle_trigger_pct", 50)
    db.config.set("writing_throttle_max_sleep_s", 0.001)
    thr = db.tenant("sys").throttle
    payload = "y" * 200
    full = 0
    for i in range(300):
        try:
            s.execute(f"insert into w values ({i}, '{payload}')")
        except MemstoreFull:
            full += 1
    assert full > 0, "the hard limit never engaged under a pinned flush"
    assert thr.peak_bytes <= 40000, "memstore exceeded its hard limit"
    assert thr.throttle_sleeps > 0  # the ramp fired before the wall
    # the flood is survivable: pin commits, the flush catches up,
    # writes admit again (retry loop = the MemstoreFull contract)
    pin.execute("commit")
    for _ in range(20):
        try:
            s.execute("insert into w values (100000, 'ok')")
            break
        except MemstoreFull:
            time.sleep(0.02)
    else:
        raise AssertionError("writes never recovered after the flush")
    r = s.execute("select b from w where a = 100000")
    assert r.rows() == [("ok",)]


# ---------------------------------------------------------------------------
# POLICIES completeness is now machine-enforced by obcheck's rpc.*
# family (oceanbase_tpu/analysis/rpc_rules.py, run by scripts/ci.sh):
# the old AST-scraping completeness tests here are retired in its
# favor.  What stays is a seeded-violation proof that the enforcing
# rule actually fires when a handler ships without a policy entry.
# ---------------------------------------------------------------------------


def test_obcheck_catches_handler_without_policy():
    """A verb registered in a handler map with no POLICIES entry must
    surface as rpc.missing-policy — the rule that replaced the coarse
    completeness assertions."""
    from oceanbase_tpu.analysis.rpc_rules import check_rpc_rules
    from oceanbase_tpu.analysis.core import run_all

    policy_src = (
        "POLICIES: dict = {\n"
        '    "node.state": VerbPolicy(2.0, True),\n'
        "}\n")
    handler_src = (
        "class S:\n"
        "    def handlers(self):\n"
        "        return {\n"
        '            "node.state": self._h_state,\n'
        '            "node.rogue": self._h_rogue,\n'
        "        }\n")
    findings = run_all({"oceanbase_tpu/net/rpc.py": policy_src,
                        "oceanbase_tpu/net/extra.py": handler_src},
                       [check_rpc_rules])
    rules = {(f.rule, f.path) for f in findings}
    assert ("rpc.missing-policy", "oceanbase_tpu/net/extra.py") in rules
    # the covered verb must NOT fire
    assert not any("node.state" in f.message for f in findings)


# ---------------------------------------------------------------------------
# dtl.cancel registry + bounded rpc pool (satellites)
# ---------------------------------------------------------------------------


def test_cancel_registry_idempotent_tombstones():
    from oceanbase_tpu.px.dtl import CancelRegistry

    reg = CancelRegistry()
    assert reg.cancel("tok") is False     # unknown: plants a tombstone
    assert reg.cancel("tok") is True      # idempotent re-apply
    assert reg.entry("tok").is_set()      # late fragment sees the flag
    # bounded: never grows past MAX_ENTRIES
    for i in range(CancelRegistry.MAX_ENTRIES + 10):
        reg.entry(f"t{i}")
    assert len(reg._entries) <= CancelRegistry.MAX_ENTRIES


def test_rpc_pool_bounded_typed_error_and_lru_close():
    from oceanbase_tpu.net.rpc import (
        ConnPoolExhausted,
        RpcClient,
        RpcServer,
    )

    gate = threading.Event()

    def slow(**kw):
        gate.wait(5)
        return "done"

    srv = RpcServer("127.0.0.1", 0,
                    {"ping": lambda: "pong", "das.pull": slow})
    srv.start()
    try:
        cli = RpcClient("127.0.0.1", srv.port, pool_size=1, max_conns=1)
        th = threading.Thread(
            target=lambda: cli.call("das.pull", _deadline_s=10.0))
        th.start()
        time.sleep(0.1)  # the slow call owns the only connection
        t0 = time.monotonic()
        with pytest.raises(ConnPoolExhausted):
            cli.call("ping", _deadline_s=0.3)
        assert time.monotonic() - t0 < 2.0  # typed fail at the deadline
        gate.set()
        th.join(5)
        # after checkin the connection frees: calls work again
        assert cli.ping()
        # LRU close on checkin: idle never exceeds pool_size and the
        # live count never exceeds max_conns
        assert len(cli._pool) <= 1 and cli._conns <= 1
        cli.close()
        assert cli._conns == 0
    finally:
        gate.set()
        srv.stop()


def test_rpc_pool_waits_for_free_conn_inside_deadline():
    from oceanbase_tpu.net.rpc import RpcClient, RpcServer

    srv = RpcServer("127.0.0.1", 0, {"ping": lambda: "pong"})
    srv.start()
    try:
        cli = RpcClient("127.0.0.1", srv.port, pool_size=2, max_conns=2)
        # fan out more concurrent calls than max_conns: all succeed by
        # waiting for checkins instead of dialing without bound
        errs = []

        def call():
            try:
                cli.call("ping", _deadline_s=5.0)
            except Exception as e:  # noqa: BLE001 — captured
                errs.append(e)

        threads = [threading.Thread(target=call) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert not errs
        assert cli._conns <= 2
        cli.close()
    finally:
        srv.stop()
