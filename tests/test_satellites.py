"""Satellite components: sequences, table locks, KV API, CDC, backup,
memstore auto-freeze.

≙ reference satellites (src/share/sequence, src/storage/tablelock,
src/libtable, src/logservice/libobcdc, src/storage/backup).
"""

import threading

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import WriteConflict
from oceanbase_tpu.tx.tablelock import DeadlockDetected, LockTable


def test_sequences(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    s.execute("create sequence sq start 100 increment 2 cache 10")
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (nextval('sq'), 1), (nextval('sq'), 2)")
    assert s.execute("select k from t order by k").rows() == [(100,), (102,)]
    r = s.execute("select nextval('sq') as v")
    assert r.rows() == [(104,)]
    # persisted high-water survives restart without duplicates
    db.checkpoint()
    db.close()
    db2 = Database(root)
    v = db2.session().execute("select nextval('sq') as v").rows()[0][0]
    assert v >= 110  # resumed past the cached range
    db2.close()


def test_table_locks_and_deadlock():
    lt = LockTable()
    lt.acquire("a", "X", tx_id=1)
    lt.acquire("b", "X", tx_id=2)
    # 2 waits for a (held by 1); then 1 requesting b would deadlock
    results = {}

    def t2():
        try:
            lt.acquire("a", "X", tx_id=2, timeout=5)
            results["t2"] = "ok"
        except Exception as e:
            results["t2"] = type(e).__name__

    th = threading.Thread(target=t2)
    th.start()
    import time

    time.sleep(0.1)
    with pytest.raises(DeadlockDetected):
        lt.acquire("b", "X", tx_id=1)
    lt.release_all(1)  # victim releases; t2 proceeds
    th.join(timeout=5)
    assert results["t2"] == "ok"
    # shared locks coexist
    lt2 = LockTable()
    lt2.acquire("t", "S", 10)
    lt2.acquire("t", "S", 11)
    with pytest.raises(WriteConflict):
        lt2.acquire("t", "X", 12, timeout=0.2)


def test_lock_tables_sql(tmp_path):
    db = Database(str(tmp_path / "db"))
    s1, s2 = db.session(), db.session()
    s1.execute("create table t (k int primary key)")
    s1.execute("lock tables t write")
    with pytest.raises((WriteConflict, DeadlockDetected)):
        s2.execute("lock tables t write")  # blocked; times out
    s1.execute("commit")  # releases the implicit lock tx
    s2.execute("lock tables t write")
    s2.execute("unlock tables")
    db.close()


def test_kv_api(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table kvt (k int primary key, v varchar(20), n int)")
    kv = db.tenant().kv("kvt")
    kv.put({"k": 1, "v": "one", "n": 10})
    kv.put({"k": 2, "v": "two", "n": 20})
    assert kv.get(1) == {"k": 1, "v": "one", "n": 10}
    kv.put({"k": 1, "v": "uno", "n": 11})   # upsert
    assert kv.get(1)["v"] == "uno"
    # survives flush to segments
    db.checkpoint()
    assert kv.get(2)["n"] == 20
    assert kv.delete(2)
    assert kv.get(2) is None
    assert not kv.delete(2)
    rows = kv.scan()
    assert len(rows) == 1 and rows[0]["k"] == 1
    # SQL sees KV writes
    assert s.execute("select v from kvt").rows() == [("uno",)]
    db.close()


def test_cdc_pump(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    pump = db.tenant().cdc()
    s.execute("insert into t values (1, 10), (2, 20)")
    s.execute("update t set v = 11 where k = 1")
    s.execute("begin")
    s.execute("delete from t where k = 2")
    s.execute("rollback")  # must NOT surface
    events = pump.poll()
    kinds = [(e.op, e.key) for e in events]
    assert ("insert", (1,)) in kinds and ("insert", (2,)) in kinds
    assert ("update", (1,)) in kinds
    assert all(e.op != "delete" for e in events)
    # commit order preserved and versions monotone
    vers = [e.commit_version for e in events]
    assert vers == sorted(vers)
    # incremental: nothing new
    assert pump.poll() == []
    s.execute("delete from t where k = 1")
    ev2 = pump.poll()
    assert [(e.op, e.key) for e in ev2] == [("delete", (1,))]
    db.close()


def test_backup_restore(tmp_path):
    src = str(tmp_path / "src")
    db = Database(src)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2)")
    dest = str(tmp_path / "bak")
    db.backup(dest)
    s.execute("insert into t values (3, 3)")  # after backup
    db.close()
    restored = Database(dest)
    r = restored.session().execute("select k from t order by k").rows()
    assert r == [(1,), (2,)]
    restored.close()


def test_memstore_auto_freeze(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("alter system set memstore_limit_rows = 50")
    s.execute("create table t (k int primary key)")
    rows = ", ".join(f"({i})" for i in range(120))
    s.execute(f"insert into t values {rows}")
    tablet = db.engine.tables["t"].tablet
    assert tablet.segments, "memstore pressure should have flushed L0s"
    assert s.execute("select count(*) from t").rows() == [(120,)]
    db.close()
