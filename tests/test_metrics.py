"""Metrics-plane suite (server/metrics.py + its surfaces).

Covers the ISSUE-7 acceptance checklist at tier-1 speed:

- registry semantics under threads (lock-free shards must not lose
  increments; dead-thread shards fold instead of leaking);
- log-bucket histogram percentile math against numpy percentiles
  (error bounded by the bucket growth factor), exact min/max;
- cross-node scrape merge parity (wire round-trip + merge_wire sums);
- gv$plan_cache cost columns populated after one compile
  (XLA cost_analysis / memory_analysis attribution);
- gv$memory pad-waste ratio reacting to ``shape_bucket_growth``;
- SHOW METRICS / gv$sysstat / gv$sysstat_histogram SQL faces;
- the obcheck ``metric.*`` family (seeded violations + clean tree);
- WaitEvents' histogram upgrade staying wire-compatible.
"""

import threading

import numpy as np
import pytest

from oceanbase_tpu.server import metrics as qmetrics


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Each test sees an empty registry and an enabled plane."""
    qmetrics.reset()
    qmetrics.set_enabled(True)
    yield
    qmetrics.reset()
    qmetrics.set_enabled(True)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_undeclared_name_raises():
    with pytest.raises(KeyError):
        qmetrics.inc("test.never_declared_xyz")
    qmetrics.declare("test.h1", "histogram", "t")
    with pytest.raises(TypeError):
        qmetrics.inc("test.h1")  # wrong kind


def test_declare_idempotent_but_kind_stable():
    qmetrics.declare("test.c1", "counter", "t")
    qmetrics.declare("test.c1", "counter", "t")  # fine
    with pytest.raises(ValueError):
        qmetrics.declare("test.c1", "gauge", "t")


def test_counters_under_threads_lose_nothing():
    qmetrics.declare("test.thr", "counter", "t")
    n_threads, per = 8, 5000

    def worker(i):
        for _ in range(per):
            qmetrics.inc("test.thr", worker=i % 2)

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # dead threads: their shards must FOLD into the retired pool, and
    # the total must be exact (each shard is single-writer)
    assert qmetrics.counter_value("test.thr") == n_threads * per
    assert qmetrics.counter_value("test.thr", worker=0) == \
        (n_threads // 2) * per


def test_disabled_plane_is_a_noop():
    qmetrics.declare("test.off", "counter", "t")
    qmetrics.set_enabled(False)
    qmetrics.inc("test.off", 100)
    qmetrics.set_enabled(True)
    assert qmetrics.counter_value("test.off") == 0


def test_gauge_last_write_wins():
    qmetrics.declare("test.g", "gauge", "t")
    qmetrics.set_gauge("test.g", 1.5)
    qmetrics.set_gauge("test.g", 2.5)
    snap = qmetrics.snapshot()
    assert snap["gauges"][("test.g", ())] == 2.5


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------


def test_histogram_percentiles_vs_numpy():
    rng = np.random.default_rng(7)
    vals = rng.lognormal(mean=-6.0, sigma=1.5, size=20000)
    h = qmetrics.Histogram()
    for v in vals:
        h.observe(float(v))
    assert h.count == len(vals)
    assert h.min == pytest.approx(vals.min())
    assert h.max == pytest.approx(vals.max())
    assert h.sum == pytest.approx(vals.sum(), rel=1e-9)
    # a log-bucket estimate is off by at most one bucket width:
    # relative error bounded by the growth factor (plus interpolation
    # slack on the tail bucket)
    tol = qmetrics.HIST_GROWTH - 1.0 + 0.05
    for q in (50.0, 95.0, 99.0):
        want = float(np.percentile(vals, q))
        got = h.percentile(q)
        assert abs(got - want) <= tol * want, (q, got, want)


def test_histogram_bucket_bounds_are_consistent():
    for v in (1e-7, 1e-6, 2e-6, 1e-3, 0.5, 1.0, 37.0, 1e9):
        i = qmetrics.bucket_index(v)
        assert v <= qmetrics.bucket_bound(i)
        if i > 0:
            assert v > qmetrics.bucket_bound(i - 1)


def test_histogram_wire_roundtrip_and_merge():
    a, b = qmetrics.Histogram(), qmetrics.Histogram()
    for v in (0.001, 0.002, 0.1):
        a.observe(v)
    for v in (0.5, 0.004):
        b.observe(v)
    back = qmetrics.Histogram.from_wire(a.to_wire())
    assert back.count == a.count and back.sum == a.sum
    assert back.buckets == a.buckets
    m = a.copy()
    m.merge(b)
    assert m.count == 5
    assert m.min == 0.001 and m.max == 0.5
    assert sum(m.buckets.values()) == 5


# ---------------------------------------------------------------------------
# scrape wire + cross-node merge parity
# ---------------------------------------------------------------------------


def test_scrape_merge_parity():
    """Merging two nodes' wire bodies must equal the per-series sums —
    the property the cluster gv$sysstat aggregation rides on."""
    qmetrics.declare("test.m", "counter", "t")
    qmetrics.declare("test.ms", "histogram", "t")
    qmetrics.inc("test.m", 3, verb="a")
    qmetrics.observe("test.ms", 0.01)
    wire_a = qmetrics.wire_snapshot()
    qmetrics.reset()
    qmetrics.inc("test.m", 4, verb="a")
    qmetrics.inc("test.m", 5, verb="b")
    qmetrics.observe("test.ms", 0.02)
    qmetrics.observe("test.ms", 0.04)
    wire_b = qmetrics.wire_snapshot()

    merged = qmetrics.merge_wire(wire_a, wire_b)
    flat = qmetrics.wire_to_flat(merged)
    assert flat["test.m{verb=a}"] == 7
    assert flat["test.m{verb=b}"] == 5
    hists = {qmetrics.series_id(n, lbl): hw
             for n, lbl, hw in merged["hists"]}
    h = qmetrics.Histogram.from_wire(hists["test.ms"])
    assert h.count == 3
    assert h.sum == pytest.approx(0.07)
    assert h.min == pytest.approx(0.01) and h.max == pytest.approx(0.04)
    # merge is associative with the empty body (scrape of a fresh node)
    again = qmetrics.merge_wire(merged, {})
    assert qmetrics.wire_to_flat(again) == flat


def test_prom_text_exposition_shape():
    qmetrics.declare("test.p", "counter", "t")
    qmetrics.declare("test.ps", "histogram", "t")
    qmetrics.inc("test.p", 2, verb="x")
    qmetrics.observe("test.ps", 0.003)
    # land one observation in the overflow bucket: the exposition must
    # still emit exactly ONE +Inf line per series (a duplicate sample
    # makes the whole scrape unparseable to Prometheus)
    qmetrics.observe("test.ps", 1e12)
    text = qmetrics.prom_text()
    assert '# TYPE ob_test_p counter' in text
    assert 'ob_test_p{verb="x"} 2' in text
    assert '# TYPE ob_test_ps histogram' in text
    assert 'ob_test_ps_count 2' in text
    # cumulative buckets end at +Inf with the total count, exactly once
    assert text.count('ob_test_ps_bucket{le="+Inf"}') == 1
    assert 'ob_test_ps_bucket{le="+Inf"} 2' in text


# ---------------------------------------------------------------------------
# WaitEvents histogram upgrade (gv$system_event columns)
# ---------------------------------------------------------------------------


def test_wait_events_wire_compatible_and_extended():
    from oceanbase_tpu.server.monitor import WaitEvents

    we = WaitEvents()
    for s in (0.001, 0.002, 0.004, 0.100):
        we.add("dtl exchange", s)
    legacy = we.snapshot()
    assert legacy["dtl exchange"][0] == 4
    assert legacy["dtl exchange"][1] == pytest.approx(0.107)
    st = we.stats()["dtl exchange"]
    assert st["min"] == pytest.approx(0.001)
    assert st["max"] == pytest.approx(0.100)
    assert st["count"] == 4
    assert 0.001 <= st["p50"] <= 0.004 < st["p99"] <= 0.100


# ---------------------------------------------------------------------------
# SQL surfaces: gv$plan_cache cost columns, gv$memory, gv$sysstat
# ---------------------------------------------------------------------------


@pytest.fixture
def db(tmp_path):
    from oceanbase_tpu.server import Database

    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def test_plan_cache_cost_columns_after_one_compile(db):
    s = db.session()
    s.execute("create table t (a int primary key, b int)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i * 3})" for i in range(200)))
    s.execute("select sum(b) from t where a < 100")
    r = s.execute(
        "select executions, xla_trace_count, flops, bytes_accessed,"
        " peak_memory, last_compile_s from gv$plan_cache"
        " where executions > 0 order by executions desc")
    rows = r.rows()
    assert rows, "no plan-cache entries after a query"
    # at least one executed plan carries nonzero XLA attribution
    attributed = [row for row in rows
                  if row[2] > 0 and row[3] > 0 and row[4] > 0]
    assert attributed, f"no cost attribution in {rows[:5]}"
    ex, traces, _f, _b, _m, compile_s = attributed[0]
    assert ex >= 1 and traces >= 1 and compile_s > 0


def test_plan_metrics_counters_flow(db):
    s = db.session()
    s.execute("create table t (a int primary key)")
    s.execute("insert into t values (1), (2), (3)")
    s.execute("select count(*) from t")
    assert qmetrics.counter_value("plan.compiles") >= 1
    assert qmetrics.counter_value("plan.executions") >= 1
    assert qmetrics.counter_value("plan.flops_executed") > 0
    assert qmetrics.counter_value("sql.statements", tenant="sys") >= 3


def test_pad_waste_ratio_reacts_to_bucket_growth(db):
    s = db.session()
    s.execute("create table t (a int primary key, b int)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i})" for i in range(100)))
    r = s.execute("select live_rows, buffer_capacity, pad_waste_ratio,"
                  " buffer_bytes, live_bytes from gv$memory"
                  " where table_name = 't'").rows()
    assert len(r) == 1
    live, cap, waste, buf_b, live_b = r[0]
    assert live == 100
    assert cap == 128  # floor 64, growth 2.0 ladder
    assert waste == pytest.approx(1.0 - 100 / 128)
    assert buf_b > live_b > 0

    s.execute("alter system set shape_bucket_growth = 4.0")
    r2 = s.execute("select buffer_capacity, pad_waste_ratio from"
                   " gv$memory where table_name = 't'").rows()
    cap2, waste2 = r2[0]
    assert cap2 == 256  # 64 * 4
    assert waste2 == pytest.approx(1.0 - 100 / 256)
    assert waste2 != waste


def test_sysstat_sql_face_and_show_metrics(db):
    s = db.session()
    s.execute("create table t (a int primary key)")
    s.execute("insert into t values (1)")
    s.execute("select * from t")
    rows = s.execute(
        "select stat_name, value from gv$sysstat"
        " where name = 'sql.statements'").rows()
    assert rows and all(v >= 1 for _n, v in rows)
    hrows = s.execute(
        "select stat_name, count, p50_s, p95_s, p99_s, max_s from"
        " gv$sysstat_histogram where name = 'sql.statement_s'").rows()
    assert hrows
    _n, cnt, p50, p95, p99, mx = hrows[0]
    assert cnt >= 3 and 0 < p50 <= p95 <= p99 <= mx
    lines = s.execute("show metrics").rows()
    text = "\n".join(r[0] for r in lines)
    assert "# TYPE ob_sql_statements counter" in text
    assert "ob_sql_statement_s_bucket" in text


def test_enable_metrics_knob(db):
    s = db.session()
    s.execute("create table t (a int primary key)")
    s.execute("alter system set enable_metrics = false")
    base = qmetrics.counter_value("sql.statements")
    s.execute("insert into t values (1)")
    assert qmetrics.counter_value("sql.statements") == base
    # the re-enabling ALTER counts itself: the knob flips mid-statement,
    # before the statement boundary where sql.statements increments
    s.execute("alter system set enable_metrics = true")
    s.execute("insert into t values (2)")
    assert qmetrics.counter_value("sql.statements") == base + 2


# ---------------------------------------------------------------------------
# obcheck metric.* family
# ---------------------------------------------------------------------------

METRIC_BAD = '''
import jax
from oceanbase_tpu.server import metrics as qmetrics

qmetrics.declare("good.counter", "counter", "d")
GOOD = qmetrics.declare("good.const", "counter", "d")

def traced(x):
    qmetrics.inc("good.counter")
    return x + 1

jax.jit(traced)

def host(name):
    qmetrics.inc("good.counter")
    qmetrics.inc(GOOD)
    qmetrics.inc("never.declared")
    qmetrics.observe(f"dyn.{name}", 1.0)
'''

METRIC_CLEAN = '''
from oceanbase_tpu.server import metrics as qmetrics

qmetrics.declare("good.counter", "counter", "d")

def host():
    qmetrics.inc("good.counter", verb="x")
'''


def test_obcheck_metric_family_catches_violations():
    from oceanbase_tpu.analysis import Analyzer, check_metric_rules

    az = Analyzer({"pkg/mod.py": METRIC_BAD})
    rules = sorted({f.rule for f in check_metric_rules(az)})
    assert rules == ["metric.dynamic-name", "metric.jit-reachable",
                     "metric.undeclared"]


def test_obcheck_metric_family_quiet_on_clean_and_pragma():
    from oceanbase_tpu.analysis import Analyzer, check_metric_rules

    az = Analyzer({"pkg/mod.py": METRIC_CLEAN})
    assert check_metric_rules(az) == []
    suppressed = METRIC_BAD.replace(
        'qmetrics.inc("never.declared")',
        'qmetrics.inc("never.declared")  # obcheck: ok(metric)')
    az = Analyzer({"pkg/mod.py": suppressed})
    findings = az.filter(check_metric_rules(az))
    assert "metric.undeclared" not in {f.rule for f in findings}


def test_repo_metric_family_clean():
    """The shipped tree must carry ZERO new metric.* findings — the
    family's baseline stays empty (same CI gate as trace/mask/lock)."""
    import os

    from oceanbase_tpu.analysis import (
        diff_findings,
        load_baseline,
        load_package_files,
        run_all,
    )
    from oceanbase_tpu.analysis import check_metric_rules

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = load_package_files(repo)
    findings = run_all(files, (check_metric_rules,))
    new = diff_findings(findings, load_baseline())
    assert not new, "\n".join(f.render() for f in new)
