"""USING join clause + AUTO_INCREMENT tests."""

import pytest

from oceanbase_tpu.server import Database


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def test_join_using(db):
    s = db.session()
    s.execute("create table a (id int primary key, av int)")
    s.execute("create table b (id int, bv int)")
    s.execute("insert into a values (1, 10), (2, 20), (3, 30)")
    s.execute("insert into b values (1, 100), (1, 101), (3, 300)")
    r = s.execute("select a.id, av, bv from a join b using (id) "
                  "order by a.id, bv")
    assert r.rows() == [(1, 10, 100), (1, 10, 101), (3, 30, 300)]
    # left join using
    r = s.execute("select a.id, bv from a left join b using (id) "
                  "order by a.id, bv")
    rows = r.rows()
    assert (2, None) in rows and len(rows) == 4


def test_auto_increment(db):
    s = db.session()
    s.execute("create table t (id int primary key auto_increment, "
              "name varchar(10))")
    s.execute("insert into t (name) values ('a'), ('b')")
    s.execute("insert into t values (100, 'x')")   # explicit id wins
    s.execute("insert into t (name) values ('c')")
    rows = s.execute("select id, name from t order by id").rows()
    ids = [r[0] for r in rows]
    assert ids[:2] == [1, 2] and 100 in ids
    assert len(set(ids)) == 4
