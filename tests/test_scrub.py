"""Silent-corruption defense: checksums at every persistence boundary,
cross-replica scrub, automatic repair.

≙ the reference's macro/micro-block checksum verification + replica
checksum comparison at major freeze (src/storage/ob_sstable_struct.h)
and the bad-block inspection tooling.  The bit-flip matrix is the
contract: for EVERY persisted artifact kind, one flipped bit must be
detected and never served — either a typed CorruptionError or a
repaired, oracle-identical result.
"""

from __future__ import annotations

import glob
import os
import socket
import struct
import time

import numpy as np
import pytest

from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.net.faults import FaultPlane, bitflip_file
from oceanbase_tpu.server import Database
from oceanbase_tpu.storage.engine import StorageEngine, read_slog
from oceanbase_tpu.storage.integrity import (
    CorruptionError,
    arrays_crc,
    chunk_crc,
    table_digest,
)
from oceanbase_tpu.storage.segment import Segment


def _mk_segment(n=1000, chunk_rows=256):
    rng = np.random.default_rng(0)
    arrays = {"k": np.arange(n, dtype=np.int64),
              "v": rng.integers(0, 100, n),
              "s": np.array([f"row{i % 17}" for i in range(n)],
                            dtype=object)}
    types = {"k": SqlType.int_(), "v": SqlType.int_(),
             "s": SqlType.string()}
    valids = {"v": rng.random(n) > 0.1}
    return Segment.build(1, 2, arrays, types, valids,
                         chunk_rows=chunk_rows), arrays


# ---------------------------------------------------------------------------
# digests + chunk crcs (unit)
# ---------------------------------------------------------------------------


def test_segment_roundtrip_verifies(tmp_path):
    seg, arrays = _mk_segment()
    p = str(tmp_path / "t_1.npz")
    seg.save(p)
    out = Segment.load(p)  # verify=True is the default read path
    a, _v = out.decode()
    assert (a["k"] == arrays["k"]).all()
    assert (a["s"] == arrays["s"]).all()


def test_chunk_crc_detects_value_change():
    seg, _ = _mk_segment(n=64)
    ec = seg.columns["v"][0]
    base = chunk_crc(ec.payload, ec.valid, ec.encoding, ec.n)
    tampered = {k: np.array(v, copy=True) for k, v in ec.payload.items()}
    key = next(iter(tampered))
    flat = tampered[key].reshape(-1)
    if flat.dtype == object:
        flat[0] = str(flat[0]) + "x"
    else:
        flat[0] ^= np.asarray(1, dtype=flat.dtype)
    assert chunk_crc(tampered, ec.valid, ec.encoding, ec.n) != base
    # validity flips matter too: NULL-ness is data
    if ec.valid is not None:
        v2 = ec.valid.copy()
        v2[0] = ~v2[0]
        assert chunk_crc(ec.payload, v2, ec.encoding, ec.n) != base


def test_table_digest_order_and_layout_independent():
    _seg, arrays = _mk_segment()
    valids = {"v": np.ones(len(arrays["k"]), dtype=bool)}
    d1 = table_digest(arrays, valids)
    perm = np.random.default_rng(3).permutation(len(arrays["k"]))
    d2 = table_digest({k: v[perm] for k, v in arrays.items()},
                      {"v": valids["v"][perm]})
    assert d1 == d2
    # a single changed value changes the digest
    mod = {k: v.copy() for k, v in arrays.items()}
    mod["v"][7] += 1
    assert table_digest(mod, valids) != d1
    # NULL-ness is part of the content
    v3 = {"v": valids["v"].copy()}
    v3["v"][5] = False
    assert table_digest(arrays, v3) != d1


def test_dtl_reply_digest_detects_tamper():
    from oceanbase_tpu.px import dtl

    arrays = {"a": np.arange(10, dtype=np.int64)}
    valids = {"a": np.ones(10, dtype=bool)}
    reply = {"arrays": arrays, "valids": valids,
             "crc": arrays_crc(arrays, valids)}
    dtl.verify_reply(reply, part=1, peer=2)  # clean passes
    reply["arrays"]["a"][3] = 999
    with pytest.raises(CorruptionError):
        dtl.verify_reply(reply, part=1, peer=2)
    # a pre-integrity peer (no crc) is accepted, not rejected
    dtl.verify_reply({"arrays": arrays, "valids": valids}, 1, 2)


# ---------------------------------------------------------------------------
# the bit-flip matrix: every persisted artifact kind, one seeded flip,
# detected and never served
# ---------------------------------------------------------------------------


def _sysdir(root):
    return os.path.join(root, "tenants", "sys")


def _flip_segment(path, seeds=range(1, 64)):
    """Flip one seeded bit that actually lands in covered bytes (a zip
    container aligns members with don't-care padding a flip could hit;
    such a flip corrupts nothing and rightly goes undetected)."""
    import shutil as _sh
    import tempfile as _tf

    for seed in seeds:
        with _tf.NamedTemporaryFile(delete=False) as tf:
            probe = tf.name
        _sh.copyfile(path, probe)
        bitflip_file(probe, seed=seed)
        try:
            Segment.load(probe)
        except CorruptionError:
            os.remove(probe)
            bitflip_file(path, seed=seed)
            return seed
        finally:
            if os.path.exists(probe):
                os.remove(probe)
    raise AssertionError("no seed produced a detectable flip")


def _seed_db(root):
    db = Database(root)
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i * 3})" for i in range(200)))
    db.checkpoint()
    return db, s


def test_bitflip_segment_detected(tmp_path):
    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    db.ash.stop(), db.jobs.stop()
    seg = glob.glob(os.path.join(_sysdir(root), "data",
                                 "segments", "t_*.npz"))[0]
    _flip_segment(seg)
    with pytest.raises(CorruptionError):
        Database(root)


def test_bitflip_manifest_detected(tmp_path):
    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    db.ash.stop(), db.jobs.stop()
    bitflip_file(os.path.join(_sysdir(root), "data",
                              "manifest.json"), seed=5)
    with pytest.raises(CorruptionError):
        Database(root)


def test_bitflip_slog_detected(tmp_path):
    root = str(tmp_path / "db")
    db, s = _seed_db(root)
    # post-checkpoint DDL leaves a slog tail to corrupt
    s.execute("create table u (k int primary key)")
    db.ash.stop(), db.jobs.stop()
    slog = os.path.join(_sysdir(root), "data", "slog.jsonl")
    assert os.path.getsize(slog) > 0
    # flip a payload byte of the FIRST record (never its newline — a
    # final-newline flip is indistinguishable from a torn append, which
    # the line format legitimately truncates)
    with open(slog, "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 0x04]))
    with pytest.raises(CorruptionError):
        Database(root)


def test_bitflip_wal_entry_never_served(tmp_path):
    """A flipped bit in a WAL entry fails its crc64: replay stops at
    the last valid prefix and the poisoned suffix is truncated — the
    entry is never applied (≙ the log tail checksum scan)."""
    from oceanbase_tpu.palf.log import _HDR, _MAGIC, PalfReplica

    d = str(tmp_path)
    r = PalfReplica(1, d)
    r.role = "leader"
    r.current_term = 1
    r.leader_append([f"p{i}".encode() for i in range(8)])
    r.close()
    path = os.path.join(d, "replica_1.log")
    with open(path, "rb") as f:
        buf = f.read()
    # flip one payload bit of the LAST entry (offset: walk the headers)
    off = len(_MAGIC)
    last_payload = None
    while off + _HDR.size <= len(buf):
        _t, _l, plen, _c = _HDR.unpack_from(buf, off)
        last_payload = off + _HDR.size
        off = off + _HDR.size + plen
    with open(path, "r+b") as f:
        f.seek(last_payload)
        b = f.read(1)
        f.seek(last_payload)
        f.write(bytes([b[0] ^ 0x01]))
    r2 = PalfReplica(1, d)
    assert [e.payload for e in r2.entries] == \
        [f"p{i}".encode() for i in range(7)]  # poisoned entry dropped
    assert os.path.getsize(path) < len(buf)  # physically truncated
    r2.close()


def test_slog_torn_tail_tolerated_bad_crc_raises(tmp_path):
    root = str(tmp_path / "e")
    os.makedirs(root, exist_ok=True)
    eng = StorageEngine(root)
    eng._log_meta({"op": "create_view", "name": "v1", "sql": "select 1"})
    slog = eng._slog_path()
    # torn final line (no newline): tolerated, scan just ends
    with open(slog, "a") as f:
        f.write('{"crc": 1, "rec": "')
    ops = list(read_slog(slog))
    assert [o["op"] for o in ops] == ["create_view"]
    # a WELL-FORMED record with a wrong crc is corruption
    with open(slog, "w") as f:
        f.write('{"crc": 12345, "rec": "{\\"op\\": \\"drop_view\\"}"}\n')
    with pytest.raises(CorruptionError):
        list(read_slog(slog))


def test_boot_quarantine_policy(tmp_path):
    """corrupt_policy='quarantine' (cluster nodes): boot moves the
    rotten segment aside and records it instead of failing — the scrub
    plane repairs from a peer afterward."""
    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    db.ash.stop(), db.jobs.stop()
    seg = glob.glob(os.path.join(_sysdir(root), "data",
                                 "segments", "t_*.npz"))[0]
    bitflip_file(seg, seed=11)
    eng = StorageEngine(os.path.join(_sysdir(root), "data"),
                        corrupt_policy="quarantine")
    assert [q["table"] for q in eng.quarantined] == ["t"]
    assert not os.path.exists(seg)
    assert glob.glob(seg + ".corrupt.*")


def test_boot_quarantine_covers_slog_replayed_segments(tmp_path):
    """A segment persisted AFTER the last checkpoint reaches boot via
    the slog's add_segment record, not the manifest — the quarantine
    policy must cover that load path too."""
    from oceanbase_tpu.catalog import ColumnDef, TableDef

    root = str(tmp_path / "e")
    eng = StorageEngine(root)
    eng.create_table(TableDef("t", [ColumnDef("k", SqlType.int_())],
                              primary_key=["k"]))
    eng.bulk_load("t", {"k": np.arange(400, dtype=np.int64)})
    seg = glob.glob(os.path.join(root, "segments", "t_*.npz"))[0]
    _flip_segment(seg)
    with pytest.raises(CorruptionError):
        StorageEngine(root)  # default policy: loud
    eng2 = StorageEngine(root, corrupt_policy="quarantine")
    assert [q["table"] for q in eng2.quarantined] == ["t"]


# ---------------------------------------------------------------------------
# disk-fault plane (net/faults.py where="disk")
# ---------------------------------------------------------------------------


def test_disk_fault_rules_validate():
    fp = FaultPlane(seed=1)
    with pytest.raises(ValueError):
        fp.inject("send", "bitflip")       # disk actions need disk
    with pytest.raises(ValueError):
        fp.inject("disk", "drop")          # rpc actions can't target disk
    with pytest.raises(ValueError):
        fp.disk("bitflip", kind="nope")    # unknown artifact kind
    rid = fp.disk("bitflip", kind="segment")
    assert fp.rules()[0]["where"] == "disk"
    fp.clear(rid)


def test_disk_fault_corrupts_next_segment_write(tmp_path):
    root = str(tmp_path / "e")
    eng = StorageEngine(root)
    fp = FaultPlane(seed=7)
    fp.disk("bitflip", kind="segment", count=1)
    eng.faults = fp
    from oceanbase_tpu.catalog import ColumnDef, TableDef

    eng.create_table(TableDef("t", [ColumnDef("k", SqlType.int_())],
                              primary_key=["k"]))
    eng.bulk_load("t", {"k": np.arange(500, dtype=np.int64)})
    path = glob.glob(os.path.join(root, "segments", "t_*.npz"))[0]
    with pytest.raises(CorruptionError):
        Segment.load(path)
    assert fp.rules()[0]["fired"] == 1
    # scrub's local pass detects + quarantines it
    r = eng.scrub_verify_table("t")
    assert r["corrupt"] and eng.quarantined
    # single-node repair: the resident copy is healthy — rewrite it
    assert eng.rewrite_segment_from_memory("t", r["corrupt"][0])
    path2 = eng._segment_file("t", r["corrupt"][0])
    Segment.load(path2)  # verifies clean now
    assert not eng.quarantined


def test_disk_fault_deterministic_offset(tmp_path):
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    for p in (p1, p2):
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 16)
    assert bitflip_file(p1, seed=99) == bitflip_file(p2, seed=99)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read()


# ---------------------------------------------------------------------------
# rebuild transfer verification (net/rebuild.py)
# ---------------------------------------------------------------------------


class _FakeChunkClient:
    """rebuild.fetch_segments stub: serves `blob` but corrupts the
    first `bad` replies for one offset."""

    def __init__(self, blob: bytes, bad: int = 0):
        self.blob, self.bad, self.calls = blob, bad, 0

    def call(self, verb, name=None, offset=0, limit=0, **kw):
        from oceanbase_tpu.native import crc64

        assert verb == "rebuild.fetch_segments"
        self.calls += 1
        data = self.blob[offset:offset + limit]
        crc = crc64(data)
        if self.bad > 0:
            self.bad -= 1
            data = b"\x00" + data[1:] if data else data
        return {"data": data, "size": len(self.blob), "crc": crc,
                "eof": offset + len(data) >= len(self.blob)}


def test_rebuild_chunk_crc_retry_then_ok(tmp_path):
    from oceanbase_tpu.native import crc64
    from oceanbase_tpu.net.rebuild import fetch_file

    blob = os.urandom(10000)
    cli = _FakeChunkClient(blob, bad=2)
    dst = str(tmp_path / "f")
    n = fetch_file(cli, "data/x", dst, chunk_bytes=4096,
                   expect_crc=crc64(blob))
    assert n == len(blob)
    with open(dst, "rb") as f:
        assert f.read() == blob


def test_rebuild_chunk_crc_exhausted_raises(tmp_path):
    from oceanbase_tpu.net.rebuild import CHUNK_CRC_RETRIES, fetch_file

    blob = os.urandom(5000)
    cli = _FakeChunkClient(blob, bad=CHUNK_CRC_RETRIES + 5)
    with pytest.raises(CorruptionError):
        fetch_file(cli, "data/x", str(tmp_path / "f"), chunk_bytes=4096)


def test_corrupt_baseline_quarantined_preboot(tmp_path):
    from oceanbase_tpu.net.rebuild import quarantine_corrupt_baseline

    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    db.ash.stop(), db.jobs.stop()
    manifest = os.path.join(_sysdir(root), "data", "manifest.json")
    bitflip_file(manifest, seed=5)
    assert quarantine_corrupt_baseline(_sysdir(root)) is True
    assert not os.path.exists(manifest)
    assert glob.glob(manifest + ".corrupt.*")
    # idempotent: nothing left to quarantine
    assert quarantine_corrupt_baseline(_sysdir(root)) is False


# ---------------------------------------------------------------------------
# backup refuses corrupt bytes (satellite)
# ---------------------------------------------------------------------------


def _flip_first_wal_payload(root):
    from oceanbase_tpu.palf.log import _HDR, _MAGIC

    path = sorted(glob.glob(os.path.join(
        root, "tenants", "sys", "wal", "replica_*.log")))[0]
    with open(path, "r+b") as f:
        buf = f.read()
        assert buf.startswith(_MAGIC)
        off = len(_MAGIC) + _HDR.size  # first entry's payload
        f.seek(off)
        b = buf[off]
        f.seek(off)
        f.write(bytes([b ^ 0x02]))
    return path


def test_backup_fails_loudly_on_corrupt_wal(tmp_path):
    from oceanbase_tpu.server import backup

    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    _flip_first_wal_payload(root)
    dest = str(tmp_path / "bk")
    with pytest.raises(CorruptionError):
        backup.full_backup(db, dest)
    assert not os.path.exists(dest)  # no half-made poison archive
    db.close()


def test_pitr_cut_verifies_entry_crc(tmp_path):
    from oceanbase_tpu.server import backup

    root = str(tmp_path / "db")
    db, _s = _seed_db(root)
    dest = str(tmp_path / "bk")
    backup.full_backup(db, dest)
    db.close()
    _flip_first_wal_payload(dest)
    with pytest.raises(CorruptionError):
        backup.pitr_cut(dest, until_version=2**62)


# ---------------------------------------------------------------------------
# policy + surface registration
# ---------------------------------------------------------------------------


def test_scrub_policies_registered():
    from oceanbase_tpu.net.rpc import POLICIES

    for verb in ("scrub.checksum", "scrub.run"):
        assert verb in POLICIES
        assert POLICIES[verb].idempotent
        assert POLICIES[verb].max_retries >= 1


def test_gv_scrub_empty_single_node(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    assert s.execute("select count(*) from gv$scrub").rows() == [(0,)]
    db.close()


def test_scrub_metrics_declared():
    import oceanbase_tpu.storage.scrub  # noqa: F401 — declares on import
    from oceanbase_tpu.server import metrics as qmetrics

    for name in ("scrub.runs", "scrub.segments_verified",
                 "scrub.bytes_verified", "scrub.corruptions",
                 "scrub.digest_mismatches", "scrub.repairs",
                 "scrub.repair_bytes", "scrub.verify_s"):
        assert name in qmetrics.declared()


# ---------------------------------------------------------------------------
# 3-node scrub → repair round trip (in-process NodeServers, real TCP)
# ---------------------------------------------------------------------------


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture()
def trio(tmp_path):
    from oceanbase_tpu.net.node import NodeServer

    ports = _free_ports(3)
    nodes = {}
    for i in range(1, 4):
        peers = {j: ("127.0.0.1", ports[j - 1])
                 for j in range(1, 4) if j != i}
        nodes[i] = NodeServer(i, "127.0.0.1", ports[i - 1], peers,
                              root=str(tmp_path / f"n{i}"),
                              bootstrap=(i == 1), lease_ms=1500)
    for n in nodes.values():
        n.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            nodes[1].execute("select 1")
            break
        except Exception:
            time.sleep(0.3)
    yield nodes
    for n in nodes.values():
        n.stop()


def _rows(res):
    name = res["names"][0]
    return [v.item() if hasattr(v, "item") else v
            for v in res["arrays"][name]]


def _sql(nodes, text, node=1, deadline_s=30.0):
    """Statement with retry over election churn (cluster tests boot
    concurrently with the first DDL)."""
    last = None
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            return nodes[node].execute(text)
        except Exception as e:  # noqa: BLE001 — retried
            last = e
            time.sleep(0.3)
    raise TimeoutError(f"statement never succeeded: {last}")


def _wait_converged(nodes, count, timeout=60):
    deadline = time.time() + timeout
    for i in (2, 3):
        while time.time() < deadline:
            try:
                r = nodes[i].execute("select count(*) from t",
                                     consistency="weak")
                if _rows(r)[0] == count:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        else:
            raise TimeoutError(f"node {i} never converged")


def test_cluster_scrub_detect_quarantine_repair_parity(trio):
    """The whole tentpole loop on a live 3-node cluster: seeded disk
    rot on one replica is detected by its scrub round, quarantined,
    refetched from a healthy peer over the chunked crc-verified
    rebuild verbs, and re-verified to cross-replica digest parity —
    zero corrupt rows served, results bit-identical to the oracle."""
    nodes = trio
    _sql(nodes, "create table t (k int primary key, v int)")
    vals = ", ".join(f"({i}, {(i * 7) % 23})" for i in range(800))
    _sql(nodes, f"insert into t values {vals}")
    oracle = sum((i * 7) % 23 for i in range(800))
    _wait_converged(nodes, 800)
    for n in nodes.values():
        n.tenant.checkpoint()

    # clean round first: nothing to repair, digests agree
    s = nodes[3].scrubber.run_once()
    assert s["corrupt"] == [] and s["mismatch"] == [] \
        and s["repaired"] == []

    # rot node 3's segment file on disk (resident copy keeps serving)
    seg = glob.glob(os.path.join(nodes[3].root, "data", "segments",
                                 "t_*.npz"))[0]
    _flip_segment(seg)
    s = nodes[3].scrubber.run_once()
    assert s["corrupt"] and s["repaired"] == ["t"] and not s["failed"]
    phases = [r["phase"] for r in nodes[3].scrubber.state.rows()]
    for phase in ("quarantine", "repair", "parity", "verify"):
        assert phase in phases
    # the repaired file verifies clean and the served rows match oracle
    for p in glob.glob(os.path.join(nodes[3].root, "data", "segments",
                                    "t_*.npz")):
        Segment.load(p)
    r = nodes[3].execute("select sum(v) from t", consistency="weak")
    assert _rows(r)[0] == oracle
    # gv$scrub surfaces the story over SQL
    r = nodes[3].execute(
        "select count(*) from gv$scrub where phase = 'repair'",
        consistency="weak")
    assert _rows(r)[0] >= 1

    # ---- digest-minority repair: resident (memory) corruption -------
    ts = nodes[3].engine.tables["t"]
    seg0 = ts.tablet.segments[0]
    a, v = seg0.decode()
    a["v"] = a["v"].copy()
    a["v"][0] += 1  # silent in-memory rot: checksums on disk still pass
    bad = Segment.build(seg0.segment_id, seg0.level, a, seg0.types,
                        {k: x for k, x in v.items() if x is not None},
                        min_version=seg0.min_version,
                        max_version=seg0.max_version)
    with ts.tablet._lock:
        ts.tablet.segments[0] = bad
        ts.tablet.data_version += 1
    nodes[3].catalog.invalidate("t")
    r = nodes[3].execute("select sum(v) from t", consistency="weak")
    assert _rows(r)[0] == oracle + 1  # the rot IS visible pre-scrub
    s = nodes[3].scrubber.run_once()
    assert "t" in s["mismatch"] and "t" in s["repaired"]
    r = nodes[3].execute("select sum(v) from t", consistency="weak")
    assert _rows(r)[0] == oracle  # majority won; rot gone

    # scrub.checksum over the wire agrees across all replicas now
    d1 = nodes[1].scrubber.checksum_handler()
    d3 = nodes[3].scrubber.checksum_handler(
        snapshot=d1["snapshot"])
    assert d1["tables"]["t"] == d3["tables"]["t"]


def test_scrub_checksum_lagging_guard(trio):
    from oceanbase_tpu.storage.scrub import ScrubLagging

    nodes = trio
    with pytest.raises(ScrubLagging):
        nodes[2].scrubber.checksum_handler(
            applied_lsn=nodes[2].palf.replica.applied_lsn + 100)


def test_boot_quarantine_then_scrub_repairs(trio):
    """Rot found at BOOT (node restart over a rotten segment file):
    the engine quarantines instead of failing, then the first scrub
    round refetches the table from a peer."""
    nodes = trio
    _sql(nodes, "create table t (k int primary key, v int)")
    vals = ", ".join(f"({i}, {i})" for i in range(300))
    _sql(nodes, f"insert into t values {vals}")
    _wait_converged(nodes, 300)
    for n in nodes.values():
        n.tenant.checkpoint()
    seg = glob.glob(os.path.join(nodes[3].root, "data", "segments",
                                 "t_*.npz"))[0]
    _flip_segment(seg)
    # simulate the restart half: a fresh engine over the same root
    eng = StorageEngine(os.path.join(nodes[3].root, "data"),
                        corrupt_policy="quarantine")
    assert [q["table"] for q in eng.quarantined] == ["t"]
    # the live node's scrubber sees the same quarantine list shape —
    # run the repair against the LIVE node (its engine still resident)
    nodes[3].engine.quarantined.append(
        {"table": "t", "segment_id": 1, "part": None, "path": ""})
    s = nodes[3].scrubber.run_once()
    assert "t" in s["repaired"]
    r = nodes[3].execute("select count(*) from t", consistency="weak")
    assert _rows(r)[0] == 300
