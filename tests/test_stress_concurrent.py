"""Concurrency stress: many sessions hammering one table with conflicts,
checkpoints, and compactions in the middle (≙ mittest concurrency tier).
"""

import threading

import numpy as np
import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.tx.errors import TxAborted, WriteConflict


def test_concurrent_increments_with_checkpoints(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table acct (k int primary key, bal int)")
    s.execute("insert into acct values (1, 0), (2, 0), (3, 0), (4, 0)")

    n_threads, n_ops = 6, 25
    applied = [0] * n_threads
    errors = []

    def worker(wid):
        rng = np.random.default_rng(wid)
        sess = db.session()
        for i in range(n_ops):
            k = int(rng.integers(1, 5))
            try:
                sess.execute(f"update acct set bal = bal + 1 where k = {k}")
                applied[wid] += 1
            except (WriteConflict, TxAborted):
                pass  # lost the race; fine
            except Exception as e:  # pragma: no cover
                errors.append(e)
        sess.close()

    def chaos():
        for _ in range(6):
            try:
                db.checkpoint()
                db.engine.minor_compact("acct")
            except Exception as e:  # pragma: no cover
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)] + [threading.Thread(target=chaos)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors

    total = sum(applied)
    got = db.session().execute("select sum(bal) from acct").rows()[0][0]
    assert got == total, (got, total)
    # recovery agrees after a crash
    db.close()
    db2 = Database(str(tmp_path / "db"))
    got2 = db2.session().execute("select sum(bal) from acct").rows()[0][0]
    assert got2 == total
    db2.close()


def test_sysvar_probe_like_mysql_client(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    r = s.execute("select @@version_comment as c, @@max_allowed_packet as m")
    assert r.rows()[0][0] == "oceanbase-tpu"
    s.execute("set @@autocommit = 0")
    assert s.execute("select @@autocommit as a").rows() == [(0,)]
    s.execute("set autocommit = 1")
    from oceanbase_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        s.execute("select @@no_such_var")
    db.close()
