"""Stored procedures: CREATE/DROP PROCEDURE, CALL, DECLARE/SET/IF/WHILE
(≙ src/pl — here an interpreted statement list over the shared
expression engine; traced UDFs remain the JIT analog).
"""

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.sql import Session


def test_procedure_control_flow(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("""
create procedure fill(in n int)
begin
  declare i int default 0;
  while i < n do
    insert into t values (i, i * i);
    set i = i + 1;
  end while;
end""")
    s.execute("call fill(5)")
    assert s.execute("select count(*), sum(v) from t").rows()[0] == \
        (5, 0 + 1 + 4 + 9 + 16)
    # IF / ELSEIF / ELSE
    s.execute("""
create procedure judge(in x int)
begin
  if x > 10 then
    select 'big';
  elseif x > 5 then
    select 'mid';
  else
    select 'small';
  end if;
end""")
    assert s.execute("call judge(20)").rows() == [("big",)]
    assert s.execute("call judge(7)").rows() == [("mid",)]
    assert s.execute("call judge(1)").rows() == [("small",)]
    db.close()


def test_procedure_params_in_queries(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table acc (id int primary key, bal int)")
    s.execute("insert into acc values (1, 100), (2, 50)")
    s.execute("""
create procedure transfer(in src int, in dst int, in amt int)
begin
  update acc set bal = bal - amt where id = src;
  update acc set bal = bal + amt where id = dst;
  select bal from acc where id = dst;
end""")
    r = s.execute("call transfer(1, 2, 30)")
    assert r.rows() == [(80,)]
    assert s.execute("select bal from acc order by id").rows() == \
        [(70,), (80,)]
    db.close()


def test_procedure_persists_across_restart(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key)")
    s.execute("create procedure p1(in k int) begin "
              "insert into t values (k); end")
    db.close()
    db2 = Database(str(tmp_path / "db"))
    s2 = db2.session()
    s2.execute("call p1(7)")
    assert s2.execute("select k from t").rows() == [(7,)]
    s2.execute("drop procedure p1")
    with pytest.raises(KeyError):
        s2.execute("call p1(8)")
    db2.close()


def test_procedure_in_memory_session():
    s = Session()
    import numpy as np

    s.catalog.load_numpy("t", {"k": np.arange(4),
                               "v": np.array([1, 2, 3, 4])},
                         primary_key=["k"])
    s.execute("create procedure q(in lo int) begin "
              "select sum(v) from t where k >= lo; end")
    assert s.execute("call q(2)").rows() == [(7,)]
