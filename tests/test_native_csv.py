"""Native CSV tokenizer/parser parity with the python path."""

import numpy as np
import pytest

from oceanbase_tpu import native
from oceanbase_tpu.server import Database


def test_tokenizer_quoting_and_escapes():
    data = b'1,"hello, world",2.5\n2,"say ""hi""",3.5\n3,,4.5\n'
    tok = native.csv_tokenize(data, 3)
    assert tok is not None
    buf, offs, lens, n = tok
    assert n == 3
    strs = native.field_strings(buf, np.ascontiguousarray(offs[1::3]),
                                np.ascontiguousarray(lens[1::3]))
    assert list(strs) == ["hello, world", 'say "hi"', ""]
    ints, valid = native.parse_int64_fields(
        buf, np.ascontiguousarray(offs[0::3]),
        np.ascontiguousarray(lens[0::3]), 0)
    np.testing.assert_array_equal(ints, [1, 2, 3])
    assert valid.all()
    decs, dvalid = native.parse_int64_fields(
        buf, np.ascontiguousarray(offs[2::3]),
        np.ascontiguousarray(lens[2::3]), 2)
    np.testing.assert_array_equal(decs, [250, 350, 450])


def test_tokenizer_ragged_returns_none():
    data = b"1,2,3\n4,5\n"
    assert native.csv_tokenize(data, 3) is None


def test_load_semantics_match_python_oracle(tmp_path):
    # review regressions: lone-CR endings, int overflow, decimal rounding,
    # garbage cells — native path must match the python path's semantics
    db = Database(str(tmp_path / "db"))
    s = db.session()

    cr = tmp_path / "cr.csv"
    cr.write_bytes(b"1,10\r2,20\r3,30\r4,40\r")
    s.execute("create table c1 (k int primary key, v int)")
    r = s.execute(f"load data infile '{cr}' into table c1 "
                  f"fields terminated by ','")
    assert r.rowcount == 4  # no silent truncation

    ov = tmp_path / "ov.csv"
    ov.write_text("1,99999999999999999999999\n")
    s.execute("create table c2 (k int primary key, v int)")
    with pytest.raises(ValueError):
        s.execute(f"load data infile '{ov}' into table c2 "
                  f"fields terminated by ','")

    rd = tmp_path / "rd.csv"
    rd.write_text("1,2.555\n2,-2.555\n")
    s.execute("create table c3 (k int primary key, v decimal(10,2))")
    s.execute(f"load data infile '{rd}' into table c3 "
              f"fields terminated by ','")
    assert s.execute("select v from c3 order by k").rows() == \
        [(2.56,), (-2.56,)]

    g = tmp_path / "g.csv"
    g.write_text("1,abc\n")
    s.execute("create table c4 (k int primary key, v int)")
    with pytest.raises(ValueError):
        s.execute(f"load data infile '{g}' into table c4 "
                  f"fields terminated by ','")
    db.close()


def test_alter_tables_typo_rejected():
    from oceanbase_tpu.sql.parser import ParseError, parse_sql

    with pytest.raises(ParseError):
        parse_sql("alter tables t add column x int")


def test_native_load_matches_python_path(tmp_path, rng):
    n = 5000
    ks = np.arange(n)
    vs = np.round(rng.uniform(0, 1000, n), 2)
    names = rng.choice(np.array(["ann", "bob, jr.", 'says "hi"', ""]), n)
    lines = ["k,v,name,d"]
    for i in range(n):
        nm = names[i]
        if "," in nm or '"' in nm:
            nm = '"' + nm.replace('"', '""') + '"'
        d = f"19{90 + int(ks[i]) % 10}-0{1 + int(ks[i]) % 9}-15"
        lines.append(f"{ks[i]},{vs[i]:.2f},{nm},{d}")
    csv_path = tmp_path / "big.csv"
    csv_path.write_text("\n".join(lines) + "\n")

    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v decimal(10,2), "
              "name varchar(40), d date)")
    r = s.execute(f"load data infile '{csv_path}' into table t "
                  f"fields terminated by ',' ignore 1 lines")
    assert r.rowcount == n
    got = s.execute("select count(*), sum(v), min(d), max(k) from t").rows()
    want_sum = round(float(np.sum(np.round(vs * 100))) / 100, 2)
    assert got[0][0] == n
    assert got[0][1] == pytest.approx(want_sum)
    assert got[0][3] == n - 1
    # spot-check a quoted name survived
    r = s.execute("select count(*) from t where name = 'bob, jr.'")
    assert r.rows()[0][0] == int((names == "bob, jr.").sum())
    # empty strings loaded as NULL
    r = s.execute("select count(*) from t where name is null")
    assert r.rows()[0][0] == int((names == "").sum())
    db.close()
