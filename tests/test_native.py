"""Native kernel tests: crc64, varint codec, log integrity.

≙ unittest/lib checksum + codec tests in the reference.
"""

import numpy as np
import pytest

from oceanbase_tpu import native


def test_native_builds():
    # the toolchain is available in this image; the native path must load
    assert native.native_available(), "native library failed to build/load"


def test_crc64_known_vector():
    # CRC-64/XZ check value for '123456789'
    assert native.crc64(b"123456789") == 0x995DC9BBDF1939FA
    assert native.crc64(b"") == 0
    # native and python fallback agree
    data = bytes(range(256)) * 3 + b"tail"
    got = native.crc64(data)
    lib, native._lib = native._lib, None
    avail, native._build_attempted = native._build_attempted, True
    try:
        import os

        so = native._SO
        native._SO = "/nonexistent.so"
        assert native.crc64(data) == got
        assert native.crc64(b"123456789") == 0x995DC9BBDF1939FA
    finally:
        native._SO = so
        native._lib = lib
        native._build_attempted = avail


def test_varint_roundtrip(rng):
    cases = [
        np.arange(1000, dtype=np.int64),
        rng.integers(-(2**62), 2**62, 500),
        np.zeros(100, dtype=np.int64),
        np.array([np.iinfo(np.int64).max, np.iinfo(np.int64).min, 0, -1, 1]),
    ]
    for arr in cases:
        buf = native.delta_varint_encode(arr)
        out = native.delta_varint_decode(buf, len(arr))
        np.testing.assert_array_equal(out, arr)
    # sorted keys compress far below 8 bytes/row
    keys = np.arange(100000, dtype=np.int64)
    assert len(native.delta_varint_encode(keys)) < 110000


def test_varint_segment_encoding(rng):
    from oceanbase_tpu.datatypes import SqlType
    from oceanbase_tpu.storage.segment import Segment

    keys = np.arange(50000, dtype=np.int64) * 3
    seg = Segment.build(1, 2, {"k": keys}, {"k": SqlType.int_()})
    enc = seg.columns["k"][0].encoding
    assert enc in ("varint", "delta")
    a, _ = seg.decode()
    np.testing.assert_array_equal(a["k"], keys)


def test_palf_log_corruption_detected(tmp_path):
    from oceanbase_tpu.palf.cluster import PalfCluster

    root = str(tmp_path)
    c = PalfCluster(3, log_root=root)
    c.elect()
    c.append([b"good1", b"good2", b"good3"])
    # a lease lapse between elect() and append() may insert an extra
    # noop: measure the actual log length instead of assuming it
    n_before = c.replicas[1].last_lsn()
    c.close()
    # corrupt the tail of replica 1's log
    import os

    path = os.path.join(root, "replica_1.log")
    with open(path, "r+b") as f:
        f.seek(-2, os.SEEK_END)
        f.write(b"\xff\xff")
    c2 = PalfCluster(3, log_root=root)
    r1 = c2.replicas[1]
    # the corrupt tail entry is dropped, earlier entries survive
    assert r1.last_lsn() == n_before - 1
    payloads = [e.payload for e in r1.entries]
    assert b"good3" not in payloads
    # the cluster still elects and catches the replica up from peers
    c2.elect()
    c2.tick()
    data = [e.payload for e in c2.replicas[1].entries
            if b"noop" not in e.payload]
    assert data == [b"good1", b"good2", b"good3"]
    c2.close()
