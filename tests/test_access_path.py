"""Access-path selection: candidate-superset prefilter correctness and
EXPLAIN surfacing (≙ optimizer access-path choice + DAS index lookup,
src/sql/optimizer/ob_join_order.h / src/sql/das/iter/ob_das_iter.h)."""

import numpy as np
import pytest

from oceanbase_tpu.server.database import Database


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def _load(db, n=20_000):
    s = db.session()
    s.execute("create table t (k int primary key, v int, grp int, "
              "name varchar(16))")
    rng = np.random.default_rng(7)
    db.engine.bulk_load("t", {
        "k": np.arange(n, dtype=np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "grp": (np.arange(n, dtype=np.int64) * 7919) % 97,
        "name": np.array([f"n{i % 513}" for i in range(n)], dtype=object),
    }, version=db.tenant().tx.gts.current())
    db.tenant().catalog.invalidate("t")
    return s


def test_pk_range_prefilter_matches_full_scan(db):
    s = _load(db)
    s.execute("set enable_index_access = 0")
    full = s.execute("select k, v from t where k between 100 and 120 "
                     "order by k").rows()
    s.execute("set enable_index_access = 1")
    fast = s.execute("select k, v from t where k between 100 and 120 "
                     "order by k").rows()
    assert fast == full and len(fast) == 21


def test_secondary_index_prefilter_matches_full_scan(db):
    s = _load(db)
    s.execute("create index ig on t (grp)")
    s.execute("set enable_index_access = 0")
    full = s.execute("select k, grp from t where grp = 13 order by k").rows()
    s.execute("set enable_index_access = 1")
    fast = s.execute("select k, grp from t where grp = 13 order by k").rows()
    assert fast == full and len(fast) > 0


def test_string_index_prefilter(db):
    s = _load(db)
    s.execute("create index inm on t (name)")
    s.execute("set enable_index_access = 0")
    full = s.execute("select k from t where name = 'n7' order by k").rows()
    s.execute("set enable_index_access = 1")
    fast = s.execute("select k from t where name = 'n7' order by k").rows()
    assert fast == full and len(fast) > 0


def test_explain_shows_access_path(db):
    s = _load(db)
    s.execute("create index ig on t (grp)")
    text = s.execute("explain select * from t where k = 5").result_text() \
        if hasattr(s.execute("explain select * from t where k = 5"),
                   "result_text") else \
        "\n".join(r[0] for r in
                  s.execute("explain select * from t where k = 5").rows())
    assert "via PRIMARY" in text
    text2 = "\n".join(r[0] for r in
                      s.execute("explain select * from t where grp = 3")
                      .rows())
    assert "via INDEX ig" in text2


def test_prefilter_sees_tx_own_writes(db):
    s = _load(db)
    s.execute("begin")
    s.execute("insert into t values (1000000, 1, 5, 'zz')")
    rows = s.execute("select v from t where k = 1000000").rows()
    assert rows == [(1,)]
    s.execute("rollback")
    assert s.execute("select v from t where k = 1000000").rows() == []


def test_update_delete_via_index_path(db):
    s = _load(db)
    s.execute("update t set v = -1 where k = 42")
    assert s.execute("select v from t where k = 42").rows() == [(-1,)]
    s.execute("delete from t where k between 10 and 12")
    assert s.execute("select count(*) from t").rows()[0][0] == 20_000 - 3
    # uncovered predicate still works (full path)
    s.execute("update t set v = -2 where v = 500")
    assert s.execute("select count(*) from t where v = -2").rows()[0][0] \
        >= 0


def test_prefilter_skipped_for_wide_ranges(db):
    """A low-selectivity range must not take the host path (estimate
    above budget) — and must stay correct either way."""
    s = _load(db)
    a = s.execute("select count(*) from t where k >= 0").rows()[0][0]
    assert a == 20_000


def test_in_list_uses_envelope(db):
    s = _load(db)
    s.execute("set enable_index_access = 0")
    full = s.execute("select k from t where k in (5, 17, 123) "
                     "order by k").rows()
    s.execute("set enable_index_access = 1")
    fast = s.execute("select k from t where k in (5, 17, 123) "
                     "order by k").rows()
    assert fast == full == [(5,), (17,), (123,)]


def test_self_join_prefilter_sound(db):
    """Review finding: per-alias ranges must not restrict the shared
    relation of a table scanned twice (self-join)."""
    s = db.session()
    s.execute("create table sj (k int primary key, v int)")
    for i in range(200):
        s.execute(f"insert into sj values ({i}, 7)")
    full = s.execute("select count(*) from sj a join sj b on a.v = b.v "
                     "where a.k = 1").rows()
    assert full == [(200,)]
