"""Regression tests for the runtime code-review findings."""

import os

import pytest

from oceanbase_tpu.server import Database


def test_empty_virtual_table_query(tmp_path):
    # finding 1: 0-row virtual tables must not produce capacity-0 relations
    db = Database(str(tmp_path / "db"))
    s = db.session()
    r = s.execute("select event from v$wait_events order by event")
    assert r.rowcount == 0
    r = s.execute("select count(*) from v$wait_events")
    assert r.rows() == [(0,)]
    db.close()


def test_dropped_tenant_stays_dropped(tmp_path):
    # finding 2: drop tenant must remove its data; no resurrection on boot
    root = str(tmp_path / "db")
    db = Database(root)
    db.session().execute("create tenant t1")
    db.session(tenant="t1").execute("create table x (a int)")
    db.session().execute("drop tenant t1")
    db.close()
    db2 = Database(root)
    assert "t1" not in db2.tenants
    db2.session().execute("create tenant t1")  # recreate works
    db2.close()


def test_ash_session_id_joins_audit(tmp_path):
    # finding 3: ASH rows and audit rows share the same session_id space
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int)")
    s._ash_state.update(active=True, sql="x", state="executing")
    db.ash.sample_once()
    s._ash_state.update(active=False)
    hist = db.ash.history(10)
    assert hist and hist[-1][1] == s.session_id
    recs = db.audit.recent(10)
    assert recs and recs[-1].session_id == s.session_id
    # close unregisters
    s.close()
    assert s.session_id not in db.ash._sessions
    db.close()


def test_virtual_table_in_insert_select_and_where(tmp_path):
    # finding 4: INSERT..SELECT and expression subqueries refresh virtuals
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table snap (name varchar(64))")
    s.execute("select 1 from v$parameters limit 1")  # warm
    s.execute("insert into snap select name from v$parameters")
    n = s.execute("select count(*) from snap").rows()[0][0]
    assert n > 20
    # expression subquery over a never-before-seen virtual table
    r = s.execute("select 1 from snap where snap.name in "
                  "(select tracepoint from v$errsim) limit 1")
    assert r.rowcount == 0  # no overlap, but it must bind and run
    db.close()


def test_boot_ignores_stray_files(tmp_path):
    # finding 6: a stray file under tenants/ must not break boot
    root = str(tmp_path / "db")
    db = Database(root)
    db.close()
    os.makedirs(os.path.join(root, "tenants"), exist_ok=True)
    with open(os.path.join(root, "tenants", "README"), "w") as f:
        f.write("not a tenant")
    db2 = Database(root)
    assert "README" not in db2.tenants
    db2.close()
