"""HYBRID_HASH skew handling: hot join keys bypass the hash exchange
(VERDICT r3 missing #6; ≙ ObSliceIdxCalc::HYBRID_HASH_{BROADCAST,RANDOM},
src/sql/engine/px/ob_slice_calc.h:73-88).
"""

import numpy as np
import pytest

from oceanbase_tpu.sql import Session


@pytest.fixture()
def skewed():
    rng = np.random.default_rng(5)
    n = 40_000
    # 80% of probe rows carry ONE key — a plain hash exchange funnels
    # them into a single destination shard
    hot = rng.random(n) < 0.8
    j = np.where(hot, 7, rng.integers(100, 5000, n))
    s = Session()
    s.catalog.load_numpy("probe", {
        "k": np.arange(n), "j": j,
        "v": rng.integers(0, 100, n)}, primary_key=["k"])
    nb = 6000
    s.catalog.load_numpy("build", {
        "bj": np.arange(nb), "w": rng.integers(0, 10, nb)},
        primary_key=["bj"])
    return s, j


def test_skewed_join_distributes_correctly(skewed):
    s, j = skewed
    sql = ("select count(*) as c, sum(v + w) as sv "
           "from probe join build on j = bj")
    serial = s.execute(sql).rows()
    s.variables["px_dop"] = 8
    try:
        dist = s.execute(sql).rows()
        assert s._last_px, "skewed join should still run on PX"
    finally:
        s.variables["px_dop"] = 0
    assert serial == dist


def test_hot_key_detection():
    import jax

    from oceanbase_tpu.expr import ir
    from oceanbase_tpu.px.dist_ops import _HOT_SENTINEL, _global_hot_keys
    from oceanbase_tpu.px.exchange import (
        default_mesh,
        shard_map_compat,
        shard_relation,
    )
    from oceanbase_tpu.vector import from_numpy

    rng = np.random.default_rng(0)
    n = 4096
    keys = np.where(rng.random(n) < 0.5, 42,
                    rng.integers(1000, 9000, n))
    keys = np.where(rng.random(n) < 0.2, 77, keys)
    rel = from_numpy({"j": keys})
    mesh = default_mesh(8)
    sharded = shard_relation(rel, mesh)

    def body(r):
        hot, _k, _m = _global_hot_keys(r, [ir.col("j")], 4, "px")
        return hot

    from jax.sharding import PartitionSpec as P

    out = jax.jit(shard_map_compat(
        body, mesh=mesh, in_specs=(P("px"),), out_specs=P("px")))(sharded)
    hot = set(np.asarray(out).reshape(8, -1)[0].tolist())
    hot.discard(_HOT_SENTINEL)
    assert 42 in hot and 77 in hot


def test_skewed_semi_and_left_joins(skewed):
    s, j = skewed
    for sql in (
        "select count(*) from probe where j in (select bj from build)",
        "select count(*), sum(w) from probe left join build on j = bj",
    ):
        serial = s.execute(sql).rows()
        s.variables["px_dop"] = 8
        try:
            dist = s.execute(sql).rows()
        finally:
            s.variables["px_dop"] = 0
        assert serial == dist, sql
