"""Workload-diagnostics plane (server/workload.py + the time model).

Covers the PR's acceptance checklist at tier-1 speed:

- host-phase time model: per-statement phase columns in gv$sql_audit,
  per-tenant accumulation in gv$time_model, phase sum reconciling with
  the measured statement wall, worst phase in EXPLAIN ANALYZE;
- snapshot persistence: crc64 round-trip, corruption (seeded via
  ``where="disk"`` fault rules, kind="workload") -> quarantine +
  CorruptionError + clean re-snapshot, write-errno faults surfacing;
- delta math vs hand-computed counter movement; merge/delta helpers;
- retention: the snapshot dir stays bounded by count and age under a
  fast-interval background loop; knob on/off hot-reload;
- ANALYZE WORKLOAD REPORT / SHOW WORKLOAD REPORT SQL faces and the
  gv$workload_* virtual tables;
- gv$ completeness: every registered virtual table is listed in SHOW
  TABLES and DESCRIBEable.
"""

import glob
import os
import time

import pytest

from oceanbase_tpu.net.faults import FaultPlane
from oceanbase_tpu.server import Database
from oceanbase_tpu.server import metrics as qmetrics
from oceanbase_tpu.server.workload import (
    WorkloadRepository,
    _delta_value,
    _merge_value,
    canonical_bytes,
)
from oceanbase_tpu.storage.integrity import CorruptionError, bytes_crc


@pytest.fixture()
def db(tmp_path):
    d = Database(str(tmp_path / "db"))
    yield d
    d.close()


def _warm(s, rows=200):
    s.execute("create table t (k int primary key, v int)")
    vals = ", ".join(f"({i}, {i % 13})" for i in range(rows))
    s.execute(f"insert into t values {vals}")
    for _ in range(3):
        s.execute("select v, count(*) from t where v < 11 group by v")
        s.execute("select sum(v) from t")


# ---------------------------------------------------------------------------
# host-phase time model
# ---------------------------------------------------------------------------


def test_sql_audit_phase_columns(db):
    s = db.session()
    _warm(s)
    r = s.execute(
        "select sql, bind_s, sidecar_build_s, lower_s, xla_compile_s,"
        " dispatch_s, merge_s, elapsed_s from gv$sql_audit")
    hits = [row for row in r.rows() if row[0].startswith("select sum")]
    assert hits
    row = dict(zip(r.names[1:], hits[-1][1:]))
    # bind (parse->plan) and dispatch (execute) always run on host
    assert row["bind_s"] > 0.0
    assert row["dispatch_s"] > 0.0
    # each phase is a sub-interval of the statement wall
    for col in ("bind_s", "sidecar_build_s", "lower_s",
                "xla_compile_s", "dispatch_s", "merge_s"):
        assert 0.0 <= row[col] <= row["elapsed_s"]


def test_time_model_accumulates_and_reconciles(db):
    s = db.session()
    _warm(s)
    tm = db.time_model.snapshot()["sys"]
    assert tm["statements"] >= 1
    phase_sum = sum(tm[p] for p in
                    ("queue_s", "bind_s", "sidecar_build_s", "lower_s",
                     "compile_s", "dispatch_s", "merge_s", "device_s"))
    # the decomposition must neither exceed the wall (phases are
    # sub-intervals; 5% timer-noise allowance) nor leave most of it
    # unexplained (the bench gates the tight 10% bound)
    assert 0.0 < phase_sum <= tm["elapsed_s"] * 1.05
    assert phase_sum >= tm["elapsed_s"] * 0.5
    rows = s.execute(
        "select tenant, phase, seconds from gv$time_model").rows()
    phases = {r[1] for r in rows if r[0] == "sys"}
    assert {"bind_s", "dispatch_s", "device_s", "elapsed_s"} <= phases


def test_explain_analyze_worst_phase(db):
    s = db.session()
    _warm(s)
    r = s.execute("explain analyze select v, count(*) from t group by v")
    assert "worst_phase=" in r.plan_text


def test_plan_cache_sidecar_columns(db):
    s = db.session()
    _warm(s)
    r = s.execute(
        "select plan_hash, sidecar_builds, sidecar_build_s"
        " from gv$plan_cache")
    assert r.rowcount >= 1
    assert all(b >= 0 for _h, b, _s in r.rows())


# ---------------------------------------------------------------------------
# merge / delta helpers
# ---------------------------------------------------------------------------


def test_merge_value_semantics():
    a = {"n": 2, "f": 1.5, "sub": {"x": 1}, "lst": [1], "s": "a",
         "flag": False}
    b = {"n": 3, "f": 0.5, "sub": {"x": 2, "y": 7}, "lst": [2],
         "flag": True, "only_b": 9}
    m = _merge_value(a, b)
    assert m["n"] == 5 and m["f"] == 2.0
    assert m["sub"] == {"x": 3, "y": 7}
    assert m["lst"] == [1, 2]
    assert m["s"] == "a" and m["flag"] is True and m["only_b"] == 9


def test_delta_value_semantics():
    frm = {"n": 10, "sub": {"x": 4}, "gone": 3}
    to = {"n": 25, "sub": {"x": 9, "new": 2}, "txt": "z", "flag": True}
    d = _delta_value(frm, to)
    # numbers subtract, missing FROM side counts as zero, the TO side's
    # keys define the delta (a counter absent in TO produces no row)
    assert d == {"n": 15, "sub": {"x": 5, "new": 2}, "txt": "z",
                 "flag": True}


# ---------------------------------------------------------------------------
# snapshots: persistence, crc, corruption quarantine
# ---------------------------------------------------------------------------


def test_snapshot_persist_and_crc_roundtrip(db):
    s = db.session()
    _warm(s)
    snap = db.workload.snapshot(cluster=False)
    assert snap["id"] in db.workload.snapshot_ids()
    loaded = db.workload.load(snap["id"])
    assert loaded["payload"] == snap["payload"]
    assert bytes_crc(canonical_bytes(loaded["payload"])) == loaded["crc"]
    # the payload spans every diagnostic surface
    for section in ("sysstat", "time_model", "plan_cache",
                    "plan_history", "wait_events", "ash", "top_sql",
                    "disk", "health"):
        assert section in snap["payload"]


def test_corrupt_snapshot_quarantined_then_resnapshot(db, tmp_path):
    s = db.session()
    _warm(s)
    fp = FaultPlane(seed=3)
    fp.disk("bitflip", kind="workload", count=1)
    db.faults = fp
    before = qmetrics.counter_value("workload.snapshot_corrupt")
    snap = db.workload.snapshot(cluster=False)  # rot fires post-write
    with pytest.raises(CorruptionError) as ei:
        db.workload.load(snap["id"])
    assert ei.value.kind == "workload"
    # quarantined, not deleted: the rotten bytes stay for forensics
    wdir = os.path.join(str(tmp_path / "db"), "workload")
    assert glob.glob(os.path.join(wdir, "*.corrupt"))
    assert snap["id"] not in db.workload.snapshot_ids()
    assert qmetrics.counter_value("workload.snapshot_corrupt") > before
    # the rule was one-shot: a re-snapshot persists clean
    snap2 = db.workload.snapshot(cluster=False)
    assert db.workload.load(snap2["id"])["id"] == snap2["id"]


def test_snapshot_write_errno_fault_surfaces(db):
    s = db.session()
    _warm(s)
    fp = FaultPlane(seed=5)
    fp.disk("enospc", kind="workload", count=1)
    db.faults = fp
    with pytest.raises(OSError):
        db.workload.snapshot(cluster=False)
    # no torn file left behind; the next snapshot succeeds
    assert not glob.glob(os.path.join(db.workload.dir or "", "*.tmp"))
    snap = db.workload.snapshot(cluster=False)
    assert db.workload.load(snap["id"])


def test_delta_math_vs_hand_computed_counters(db):
    s = db.session()
    _warm(s)
    a = db.workload.snapshot(cluster=False)
    for _ in range(4):
        s.execute("select sum(v) from t")
    b = db.workload.snapshot(cluster=False)
    d = db.workload.delta(a["id"], b["id"])
    # monotonic sections subtract exactly (series ids carry labels)
    name = next(k for k in b["payload"]["sysstat"]
                if k.startswith("sql.statements"))
    assert d["payload"]["sysstat"][name] == pytest.approx(
        b["payload"]["sysstat"][name] - a["payload"]["sysstat"].get(name, 0))
    tm_a = a["payload"]["time_model"]["sys"]
    tm_b = b["payload"]["time_model"]["sys"]
    assert d["payload"]["time_model"]["sys"]["statements"] == \
        tm_b["statements"] - tm_a["statements"]
    # point-in-time sections take the TO side verbatim
    assert d["payload"]["disk"] == b["payload"]["disk"]
    assert d["payload"]["top_sql"] == b["payload"]["top_sql"]
    assert d["span_s"] >= 0.0


def test_restart_survival_and_cross_restart_report(tmp_path):
    root = str(tmp_path / "db")
    db = Database(root)
    s = db.session()
    _warm(s)
    pre = db.workload.snapshot(cluster=False)["id"]
    db.close()

    db2 = Database(root)
    s2 = db2.session()
    s2.execute("select sum(v) from t")
    assert pre in db2.workload.snapshot_ids()
    assert db2.workload.load(pre)["id"] == pre  # crc-verified
    rep = db2.workload.build_report(from_id=pre, to_id=-1)
    assert rep["from_id"] == pre and rep["to_id"] > pre
    assert rep["rows"]
    db2.close()


# ---------------------------------------------------------------------------
# retention + background loop knobs
# ---------------------------------------------------------------------------


def test_retention_prunes_by_count(db):
    s = db.session()
    _warm(s, rows=50)
    s.execute("alter system set workload_retention_keep = 3")
    for _ in range(7):
        db.workload.snapshot(cluster=False)
    ids = db.workload.snapshot_ids()
    assert len(ids) == 3
    assert ids == sorted(ids)[-3:]  # newest survive
    files = os.listdir(db.workload.dir)
    assert len([f for f in files if f.endswith(".json")]) == 3


def test_retention_prunes_by_age(db):
    s = db.session()
    _warm(s, rows=50)
    old = db.workload.snapshot(cluster=False)
    new = db.workload.snapshot(cluster=False)
    s.execute("alter system set workload_retention_max_age_s = 60")
    stale = time.time() - 3600
    os.utime(db.workload._path(old["id"]), (stale, stale))
    db.workload.prune()
    assert db.workload.snapshot_ids() == [new["id"]]


def test_background_loop_bounded_dir_and_knob_off(db):
    s = db.session()
    _warm(s, rows=50)
    s.execute("alter system set workload_retention_keep = 2")
    s.execute("alter system set workload_snapshot_interval_s = 0.05")
    s.execute("alter system set enable_workload_repo = true")
    deadline = time.monotonic() + 10.0
    while not db.workload.snapshot_ids() and time.monotonic() < deadline:
        time.sleep(0.05)
    ids = db.workload.snapshot_ids()
    assert ids, "background loop never snapshotted"
    assert len(ids) <= 2  # retention holds under the fast loop
    # hot-reload off: the loop stops taking snapshots (the loop ticks
    # every min(interval, 1s) = 0.05s here, so 0.4s drains any round)
    s.execute("alter system set enable_workload_repo = false")
    time.sleep(0.4)
    frozen = db.workload.snapshot_ids()
    time.sleep(0.4)
    assert db.workload.snapshot_ids() == frozen


# ---------------------------------------------------------------------------
# SQL faces: ANALYZE WORKLOAD REPORT / SHOW WORKLOAD REPORT / gv$
# ---------------------------------------------------------------------------


def test_analyze_workload_report_end_to_end(db):
    s = db.session()
    _warm(s)
    r = s.execute("analyze workload report")
    assert r.names == ["section", "item", "value", "detail"]
    sections = {row[0] for row in r.rows()}
    assert {"report", "time_model", "plan_cache", "sysstat"} <= sections
    # the report took its own to-snapshot on demand (thread off)
    assert db.workload.snapshot_ids()
    # time-model lines carry the per-tenant phase split
    items = {row[1] for row in r.rows() if row[0] == "time_model"}
    assert "sys.dispatch_s" in items and "sys.elapsed_s" in items

    # explicit FROM/TO over known ids
    s.execute("select sum(v) from t")
    b = db.workload.snapshot(cluster=False)
    a_id = db.workload.snapshot_ids()[0]
    r2 = s.execute(
        f"analyze workload report from {a_id} to {b['id']}")
    hdr = next(row for row in r2.rows() if row[0] == "report")
    assert f"from={a_id}" in hdr[3] and f"to={b['id']}" in hdr[3]

    # the text tree face renders the last built report
    tree = s.execute("show workload report").rows()
    assert tree and tree[0][0].startswith("workload report ")
    assert any(line[0].strip() == "time_model" for line in tree)

    # gv$ faces agree
    gv = s.execute("select section, item from gv$workload_report")
    assert gv.rowcount == r2.rowcount
    snaps = s.execute(
        "select snapshot_id, crc64 from gv$workload_snapshot").rows()
    assert {row[0] for row in snaps} == set(db.workload.snapshot_ids())


def test_analyze_workload_report_parses():
    from oceanbase_tpu.sql import ast
    from oceanbase_tpu.sql.parser import ParseError, parse_sql

    st = parse_sql("analyze workload report")
    assert isinstance(st, ast.AnalyzeWorkloadStmt)
    assert st.from_id == -1 and st.to_id == -1
    st = parse_sql("analyze workload report from 3 to 9")
    assert (st.from_id, st.to_id) == (3, 9)
    assert parse_sql("show workload report").what == "workload_report"
    with pytest.raises(ParseError):
        parse_sql("analyze workload report from x to 2")
    with pytest.raises(ParseError):
        parse_sql("show workload")


def test_in_memory_repo_without_root(db):
    # root=None (embedded/test harnesses): snapshots live in memory,
    # same ids/load/delta/report contract, no disk
    repo = WorkloadRepository(db, root=None)
    a = repo.snapshot(cluster=False)
    b = repo.snapshot(cluster=False)
    assert repo.snapshot_ids() == [a["id"], b["id"]]
    rep = repo.build_report(a["id"], b["id"])
    assert rep["from_id"] == a["id"] and rep["to_id"] == b["id"]


# ---------------------------------------------------------------------------
# gv$ completeness
# ---------------------------------------------------------------------------


def test_every_virtual_table_listed_and_describable(db):
    s = db.session()
    registry = sorted(db.virtual_tables.names())
    assert "gv$time_model" in registry
    assert "gv$workload_snapshot" in registry
    assert "gv$workload_report" in registry
    shown = set(s.execute("show tables").arrays["table_name"])
    missing = [n for n in registry if n not in shown]
    assert not missing, f"gv$ tables absent from SHOW TABLES: {missing}"
    for name in registry:
        d = s.execute(f"describe {name}")
        assert d.rowcount >= 1, f"{name} not DESCRIBEable"
