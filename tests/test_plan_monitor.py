"""Per-operator plan monitor stats (≙ sql_plan_monitor rows)."""

from oceanbase_tpu.server import Database


def test_plan_monitor_rows(tmp_path):
    db = Database(str(tmp_path / "db"))
    s = db.session()
    s.execute("create table t (k int primary key, v int)")
    s.execute("insert into t values (1, 1), (2, 2), (3, 3)")
    s.execute("select sum(v) from t where k >= 2")
    recent = db.plan_monitor.recent(5)
    assert recent, "plan monitor should have entries"
    rec = recent[-1]
    ops = {r["op"]: r["rows"] for r in rec.op_stats}
    assert ops.get("TableScan") == 3
    assert ops.get("Filter") == 2
    assert ops.get("ScalarAgg") == 1
    # the estimate-vs-actual ledger rides every row
    assert all("est" in r and "q_error" in r for r in rec.op_stats)
    assert rec.logical_hash and rec.path == "serial"
    # surfaced through SQL too
    r = s.execute("select operator, output_rows from gv$plan_monitor "
                  "where operator = 'Filter'")
    assert (("Filter", 2) in r.rows())
    # can be turned off at runtime
    s.execute("alter system set enable_sql_plan_monitor = false")
    n_before = len(db.plan_monitor.recent(1000))
    s.execute("select count(*) from t")
    assert len(db.plan_monitor.recent(1000)) == n_before
    db.close()
