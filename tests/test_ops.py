"""Operator unit tests vs numpy/python oracles.

≙ unittest/sql/engine operator tests with the fake table scan feeding
synthetic vectors (unittest/sql/engine/ob_fake_table_scan_vec_op.h)."""

import numpy as np
import pytest

from oceanbase_tpu.exec import (
    AggSpec,
    compact,
    filter_rows,
    hash_groupby,
    join,
    limit,
    scalar_agg,
    sort_rows,
)
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector import from_numpy, to_numpy


def test_filter_and_compact(rng):
    n = 5000
    rel = from_numpy({"a": rng.integers(0, 100, n), "b": rng.integers(0, 5, n)})
    a = np.asarray(rel.columns["a"].data)
    out = filter_rows(rel, ir.col("a") < 30)
    assert int(out.count()) == int((a < 30).sum())
    c = compact(out)
    got = to_numpy(c)["a"]
    np.testing.assert_array_equal(np.sort(got), np.sort(a[a < 30]))


def test_groupby_sums(rng):
    n = 10000
    a = rng.integers(0, 7, n)
    v = rng.integers(-50, 50, n)
    rel = from_numpy({"g": a, "v": v})
    out = hash_groupby(
        rel,
        {"g": ir.col("g")},
        [
            AggSpec("s", "sum", ir.col("v")),
            AggSpec("c", "count_star"),
            AggSpec("mn", "min", ir.col("v")),
            AggSpec("mx", "max", ir.col("v")),
            AggSpec("av", "avg", ir.col("v")),
        ],
        out_capacity=64,
    )
    res = to_numpy(out)
    order = np.argsort(res["g"])
    for k in res:
        res[k] = res[k][order]
    keys = np.unique(a)
    np.testing.assert_array_equal(res["g"], keys)
    np.testing.assert_array_equal(res["s"], [v[a == k].sum() for k in keys])
    np.testing.assert_array_equal(res["c"], [(a == k).sum() for k in keys])
    np.testing.assert_array_equal(res["mn"], [v[a == k].min() for k in keys])
    np.testing.assert_array_equal(res["mx"], [v[a == k].max() for k in keys])
    np.testing.assert_allclose(res["av"], [v[a == k].mean() for k in keys])


def test_groupby_multi_key_with_nulls(rng):
    n = 2000
    g1 = rng.integers(0, 3, n)
    g2 = rng.integers(0, 4, n)
    nulls = rng.random(n) < 0.1
    v = rng.integers(0, 100, n)
    rel = from_numpy({"g1": g1, "g2": g2, "v": v},
                     valids={"g2": ~nulls})
    out = hash_groupby(rel, {"g1": ir.col("g1"), "g2": ir.col("g2")},
                       [AggSpec("c", "count_star")])
    res = to_numpy(out)
    # oracle: nulls form their own group per g1
    import collections
    oracle = collections.Counter()
    for i in range(n):
        key = (g1[i], None if nulls[i] else g2[i])
        oracle[key] += 1
    assert len(res["g1"]) == len(oracle)
    got_total = res["c"].sum()
    assert got_total == n


def test_count_distinct(rng):
    n = 3000
    g = rng.integers(0, 5, n)
    v = rng.integers(0, 20, n)
    rel = from_numpy({"g": g, "v": v})
    out = hash_groupby(rel, {"g": ir.col("g")},
                       [AggSpec("cd", "count_distinct", ir.col("v"))],
                       out_capacity=16)
    res = to_numpy(out)
    order = np.argsort(res["g"])
    np.testing.assert_array_equal(
        res["cd"][order], [len(np.unique(v[g == k])) for k in np.unique(g)]
    )


def test_scalar_agg_empty_and_nulls():
    rel = from_numpy({"x": np.array([1, 2, 3, 4])},
                     valids={"x": np.array([True, True, False, False])})
    rel = filter_rows(rel, ir.col("x") < 0)  # empty
    out = scalar_agg(rel, [AggSpec("c", "count", ir.col("x")),
                           AggSpec("s", "sum", ir.col("x")),
                           AggSpec("n", "count_star")])
    res = to_numpy(out)
    assert res["c"][0] == 0 and res["n"][0] == 0
    assert not np.asarray(out.columns["s"].valid)[0]  # SUM of empty = NULL


def test_inner_join_pk_fk(rng):
    nl, nr = 5000, 200
    fk = rng.integers(0, nr, nl)
    lval = rng.integers(0, 1000, nl)
    rval = rng.integers(0, 1000, nr)
    left = from_numpy({"fk": fk, "lv": lval})
    right = from_numpy({"pk": np.arange(nr), "rv": rval})
    out = join(left, right, [ir.col("fk")], [ir.col("pk")], how="inner",
               out_capacity=nl)
    res = to_numpy(out)
    assert len(res["fk"]) == nl
    np.testing.assert_array_equal(res["fk"], res["pk"])
    np.testing.assert_array_equal(res["rv"], rval[res["fk"]])


def test_join_duplicates_and_semi_anti(rng):
    left = from_numpy({"k": np.array([1, 2, 3, 4]), "lv": np.array([10, 20, 30, 40])})
    right = from_numpy({"rk": np.array([2, 2, 3, 9]), "rv": np.array([1, 2, 3, 4])})
    out = join(left, right, [ir.col("k")], [ir.col("rk")], how="inner",
               out_capacity=16)
    res = to_numpy(out)
    pairs = sorted(zip(res["k"].tolist(), res["rv"].tolist()))
    assert pairs == [(2, 1), (2, 2), (3, 3)]

    semi = join(left, right, [ir.col("k")], [ir.col("rk")], how="semi")
    np.testing.assert_array_equal(np.sort(to_numpy(semi)["k"]), [2, 3])

    anti = join(left, right, [ir.col("k")], [ir.col("rk")], how="anti")
    np.testing.assert_array_equal(np.sort(to_numpy(anti)["k"]), [1, 4])


def test_left_join(rng):
    left = from_numpy({"k": np.array([1, 2, 3]), "lv": np.array([10, 20, 30])})
    right = from_numpy({"rk": np.array([2, 2]), "rv": np.array([7, 8])})
    out = join(left, right, [ir.col("k")], [ir.col("rk")], how="left",
               out_capacity=8)
    res = to_numpy(out)
    assert sorted(res["k"].tolist()) == [1, 2, 2, 3]
    rv_valid = np.asarray(out.columns["rv"].valid)[
        np.nonzero(np.asarray(out.mask_or_true()))[0]]
    assert rv_valid.sum() == 2  # only the two matched rows have rv


def test_multikey_join(rng):
    n = 1000
    k1 = rng.integers(0, 10, n)
    k2 = rng.integers(0, 10, n)
    left = from_numpy({"a1": k1, "a2": k2, "lv": np.arange(n)})
    rk1 = np.repeat(np.arange(10), 10)
    rk2 = np.tile(np.arange(10), 10)
    right = from_numpy({"b1": rk1, "b2": rk2, "rv": np.arange(100)})
    out = join(left, right, [ir.col("a1"), ir.col("a2")],
               [ir.col("b1"), ir.col("b2")], how="inner", out_capacity=n)
    res = to_numpy(out)
    assert len(res["a1"]) == n  # every (k1,k2) pair exists exactly once
    np.testing.assert_array_equal(res["a1"], res["b1"])
    np.testing.assert_array_equal(res["a2"], res["b2"])
    np.testing.assert_array_equal(res["rv"], res["a1"] * 10 + res["a2"])


def test_sort_and_limit(rng):
    n = 1000
    a = rng.integers(0, 100, n)
    b = rng.integers(0, 100, n)
    rel = from_numpy({"a": a, "b": b})
    out = limit(sort_rows(rel, [ir.col("a"), ir.col("b")], [True, False]), 10)
    res = to_numpy(out)
    oracle = sorted(zip(a.tolist(), (-b).tolist()))[:10]
    got = list(zip(res["a"].tolist(), (-res["b"]).tolist()))
    assert got == oracle


def test_join_string_keys_different_dicts():
    left = from_numpy({"name": np.array(["fr", "de", "us", "cn"]),
                       "lv": np.array([1, 2, 3, 4])})
    right = from_numpy({"rname": np.array(["de", "us", "jp"]),
                        "rv": np.array([10, 20, 30])})
    out = join(left, right, [ir.col("name")], [ir.col("rname")], how="inner",
               out_capacity=8)
    res = to_numpy(out)
    pairs = sorted(zip(res["name"].tolist(), res["rv"].tolist()))
    assert pairs == [("de", 10), ("us", 20)]
