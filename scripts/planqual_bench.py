#!/usr/bin/env python
"""Plan-quality observability bench: the PR-8 standing contracts.

Four halves, one dtl_bench-style JSON line (with an embedded
``gv$sysstat`` snapshot, so bench artifacts and the metrics plane share
one schema):

1. **q-error coverage** — all 22 TPC-H queries at SF (default 0.1) on an
   in-process Database with the plan monitor on: EVERY operator row in
   the estimate-vs-actual ledger must carry an estimate (q_error >= 1).

2. **Overhead** — the TPC-H slice (q6 + q1) timed with the plan monitor
   (+ feedback + watchdog recording) OFF vs ON, alternating blocks;
   contract <= 2%.

3. **Feedback** — a seeded join underestimate (100% duplicate keys, est
   ~ max(l, r) * 1.5 vs true l * r) costs exactly ONE CapacityOverflow
   retry with feedback on (the overflow report jumps straight to a
   clearing budget) vs >= 2 on the blind 4x ladder with it off; a fresh
   session then binds straight to the observed bucket (0 retries).

4. **DTL slice skew** — a real 3-node cluster runs a filter pushdown
   whose matching rows all pk-hash into slice 0: ``gv$px_exchange``
   must show max/mean slice rows >= 3x, while a uniformly-spread key
   set stays < 1.5x.

    python scripts/planqual_bench.py                    # all halves
    PLANQUAL_SKIP_CLUSTER=1 python scripts/planqual_bench.py
    PLANQUAL_SF=0.01 python scripts/planqual_bench.py   # faster
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

SF = float(os.environ.get("PLANQUAL_SF", "0.1"))
# overhead sampling: ~1ms run-to-run drift on the slice's q1 needs many
# interleaved samples for a stable median — run this bench ALONE
REPEATS = int(os.environ.get("PLANQUAL_REPEATS", "96"))

SLICE_QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


# ---------------------------------------------------------------------------
# 1. q-error coverage over the full TPC-H suite
# ---------------------------------------------------------------------------


def bench_qerror_coverage() -> dict:
    from oceanbase_tpu.bench.tpch import TPCH_PRIMARY_KEYS, gen_tpch
    from oceanbase_tpu.bench.tpch_queries import QUERIES
    from oceanbase_tpu.server import Database

    t0 = time.monotonic()
    tables, types = gen_tpch(sf=SF)
    gen_s = time.monotonic() - t0
    root = tempfile.mkdtemp(prefix="planqual_cov_")
    try:
        db = Database(root)
        s = db.session()
        for name, arrays in tables.items():
            s.catalog.load_numpy(
                name, arrays,
                types={k: v for k, v in types.items() if k in arrays},
                primary_key=TPCH_PRIMARY_KEYS[name])
        for name in tables:
            s.execute(f"analyze table {name}")
        # the same *key secondary indexes sf_parity gives the oracle —
        # without them the CBO has no index access paths to validate
        for name, arrays in tables.items():
            for c in arrays:
                if c.endswith("key"):
                    s.execute(
                        f"create index idx_{name}_{c} on {name} ({c})")
        per_query = {}
        worst = {"q": None, "op": "", "q_error": 0.0}
        t0 = time.monotonic()
        for qnum in sorted(QUERIES):
            s.execute(QUERIES[qnum])
            rec = db.plan_monitor.recent(1)[-1]
            ops = len(rec.op_stats)
            with_est = sum(1 for r in rec.op_stats
                           if r.get("est") is not None
                           and r.get("q_error", 0.0) >= 1.0)
            qmax = max(rec.op_stats,
                       key=lambda r: r.get("q_error", 0.0))
            per_query[f"q{qnum}"] = {
                "operators": ops, "with_qerror": with_est,
                "max_q_error": round(qmax.get("q_error", 0.0), 2),
                "retries": rec.retries, "path": rec.path}
            if qmax.get("q_error", 0.0) > worst["q_error"]:
                worst = {"q": qnum, "op": qmax["op"],
                         "q_error": round(qmax["q_error"], 2)}
        run_s = time.monotonic() - t0
        all_covered = all(v["operators"] == v["with_qerror"]
                          for v in per_query.values())
        cost_model = _cost_model_validation(db)
        db.close()
        return {"sf": SF, "gen_s": round(gen_s, 1),
                "run_s": round(run_s, 1),
                "queries": len(per_query),
                "all_operators_covered": all_covered,
                "worst_misestimate": worst,
                "cost_model": cost_model,
                "per_query": per_query}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _cost_model_validation(db) -> dict:
    """CBO validation over the ``gv$plan_choice`` ledger after a full
    TPC-H run: how far the chosen plan's predicted seconds sat under
    the runner-up's (margin), and whether predicted seconds RANK the
    executed plans the same way measured device seconds do (pairwise
    concordance) — the check that pricing in measured units actually
    orders real plans, not just flop counts."""
    rows = [r for r in db.plan_choice.rows() if r["executions"] > 0]
    cbo = [r for r in rows if r["enumerated"] > 1 and r["pred_s"] > 0]
    margins = sorted(r["margin"] for r in cbo if r["runner_up_s"] > 0)
    pairs = conc = 0
    ranked = [r for r in cbo if r["device_s_mean"] > 0]
    for i in range(len(ranked)):
        for j in range(i + 1, len(ranked)):
            a, b = ranked[i], ranked[j]
            if (a["pred_s"] == b["pred_s"]
                    or a["device_s_mean"] == b["device_s_mean"]):
                continue
            pairs += 1
            conc += int((a["pred_s"] > b["pred_s"])
                        == (a["device_s_mean"] > b["device_s_mean"]))

    def med(xs):
        if not xs:
            return None
        k = len(xs) // 2
        return xs[k] if len(xs) % 2 else (xs[k - 1] + xs[k]) / 2

    mm = med(margins)
    return {"plans_recorded": len(rows),
            "plans_enumerated": len(cbo),
            "plans_with_runner_up": len(margins),
            "index_probe_plans":
                sum(1 for r in rows if r["index_probes"] > 0),
            "median_margin": round(mm, 3) if mm is not None else None,
            "ranking_pairs": pairs,
            "ranking_agreement":
                round(conc / pairs, 3) if pairs else None}


# ---------------------------------------------------------------------------
# 2. monitoring overhead on the TPC-H slice
# ---------------------------------------------------------------------------


def _gen_slice(n_rows: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _time_queries(sess, repeats: int) -> float:
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in SLICE_QUERIES.values():
            sess.execute(q)
    return time.monotonic() - t0


def bench_overhead(n_rows: int = 20000) -> dict:
    from oceanbase_tpu.server import Database

    root = tempfile.mkdtemp(prefix="planqual_ovh_")
    try:
        db = Database(root)
        s = db.session()
        cols = _gen_slice(n_rows)
        s.catalog.load_numpy("lineitem",
                             {"l_id": np.arange(n_rows), **cols},
                             primary_key=["l_id"])

        def set_monitoring(on: str):
            s.execute(f"alter system set enable_sql_plan_monitor = {on}")
            s.execute(f"alter system set enable_plan_feedback = {on}")

        # parity guard: monitoring must never change results
        set_monitoring("true")
        on_rows = {k: s.execute(q).rows()
                   for k, q in SLICE_QUERIES.items()}
        set_monitoring("false")
        off_rows = {k: s.execute(q).rows()
                    for k, q in SLICE_QUERIES.items()}
        assert on_rows == off_rows, "monitoring changed results"
        _time_queries(s, 3)  # warm the jit caches
        # tightly interleaved on/off samples (order alternating per
        # iteration), MEDIAN per mode: the slice's q1 drifts +-3% on a
        # busy 2-core box, so per-block ratios are unusable — medians
        # over many interleaved samples cancel the drift both modes see
        per_sample = 2
        samples = max(REPEATS // per_sample, 8)
        off_times, on_times = [], []
        for i in range(samples):
            order = (("false", "true") if i % 2 == 0
                     else ("true", "false"))
            for mode in order:
                set_monitoring(mode)
                dt = _time_queries(s, per_sample)
                (on_times if mode == "true" else off_times).append(dt)
        set_monitoring("true")
        db.close()

        def med(xs):
            xs = sorted(xs)
            k = len(xs) // 2
            return xs[k] if len(xs) % 2 else (xs[k - 1] + xs[k]) / 2

        off_m, on_m = med(off_times), med(on_times)
        return {"rows": n_rows,
                "repeats": samples * per_sample,
                "off_s": round(sum(off_times), 4),
                "on_s": round(sum(on_times), 4),
                "mean_overhead_pct": round(
                    (sum(on_times) - sum(off_times))
                    / sum(off_times) * 100, 2),
                "overhead_pct": round(
                    (on_m - off_m) / off_m * 100, 2)}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# 3. cardinality feedback vs the blind retry ladder
# ---------------------------------------------------------------------------


def _seed_join(s, n=100):
    s.execute("create table fa (id int primary key, k int)")
    s.execute("create table fb (id int primary key, k int)")
    s.execute("insert into fa values "
              + ",".join(f"({i},1)" for i in range(n)))
    s.execute("insert into fb values "
              + ",".join(f"({i},1)" for i in range(n)))


def bench_feedback() -> dict:
    from oceanbase_tpu.server import Database
    from oceanbase_tpu.server import metrics as qmetrics

    def retries():
        return int(qmetrics.sysstat_dict().get(
            "plan.capacity_retries", 0))

    q = "select count(*) from fa, fb where fa.k = fb.k"
    out = {}
    for mode in ("on", "off"):
        root = tempfile.mkdtemp(prefix=f"planqual_fb_{mode}_")
        try:
            db = Database(root)
            s = db.session()
            s.execute("alter system set enable_plan_feedback = "
                      + ("true" if mode == "on" else "false"))
            _seed_join(s)
            r0 = retries()
            assert s.execute(q).rows() == [(10000,)]
            first = retries() - r0
            # a FRESH session = cold plan cache; only gv$plan_feedback
            # can save it from re-riding the ladder
            s2 = db.session()
            r1 = retries()
            assert s2.execute(q).rows() == [(10000,)]
            second = retries() - r1
            out[mode] = {"first_run_retries": first,
                         "fresh_session_retries": second}
            db.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
    return out


# ---------------------------------------------------------------------------
# 4. DTL slice skew on a real 3-node cluster
# ---------------------------------------------------------------------------


def bench_skew(n_rows: int = 3000) -> dict:
    from dtl_bench import boot_cluster, wait_converged

    from oceanbase_tpu.px.dtl import slice_mask

    ids = np.arange(n_rows * 4, dtype=np.int64)
    in_part0 = slice_mask({"k": ids}, ["k"], 0, 3)
    n_match = n_rows // 3
    root = tempfile.mkdtemp(prefix="planqual_skew_")
    procs = []
    try:
        procs, clients = boot_cluster(root)
        c1 = clients[1]

        def sql(text):
            return c1.call("sql.execute", sql=text)

        def load(table, match_ids, rest_ids):
            sql(f"create table {table} (k int primary key, flag int,"
                " v int)")
            rows = [(int(k), 1, int(k) % 97) for k in match_ids] + \
                   [(int(k), 0, int(k) % 97) for k in rest_ids]
            for st in range(0, len(rows), 1000):
                vals = ", ".join(f"({k}, {f}, {v})" for k, f, v in
                                 rows[st:st + 1000])
                sql(f"insert into {table} values {vals}")

        # skewed: every row MATCHING the pushed filter pk-hashes into
        # slice 0 (the coordinator's slice); uniform: random pks
        load("skewed", ids[in_part0][:n_match],
             ids[~in_part0][:n_rows - n_match])
        rng = np.random.default_rng(5)
        uni_ids = rng.permutation(ids)[:n_rows]
        load("uniform", uni_ids[:n_match], uni_ids[n_match:])
        wait_converged(clients, "skewed", n_rows)
        wait_converged(clients, "uniform", n_rows)
        sql("alter system set dtl_min_rows = 1")

        def skew_of(table):
            r = sql(f"select v from {table} where flag = 1")
            assert len(r["arrays"]["v"]) == n_match
            ex = sql("select slice_skew, max_slice_rows,"
                     " mean_slice_rows, parts, pushdown_hit from"
                     " gv$px_exchange where mode = 'pushdown'"
                     " order by ts desc limit 1")
            a = ex["arrays"]
            assert int(a["pushdown_hit"][0]) == 1, \
                f"{table} did not push down"
            return {"slice_skew": round(float(a["slice_skew"][0]), 3),
                    "max_slice_rows": int(a["max_slice_rows"][0]),
                    "mean_slice_rows":
                        round(float(a["mean_slice_rows"][0]), 1),
                    "parts": int(a["parts"][0])}

        return {"rows": n_rows, "matching": n_match,
                "skewed": skew_of("skewed"),
                "uniform": skew_of("uniform")}
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        shutil.rmtree(root, ignore_errors=True)


def main():
    result = {"metric": "planqual_bench", "sf": SF}
    cov = bench_qerror_coverage()
    result["coverage"] = cov
    ovh = bench_overhead()
    result["overhead"] = ovh
    fb = bench_feedback()
    result["feedback"] = fb
    if os.environ.get("PLANQUAL_SKIP_CLUSTER"):
        result["skew"] = {"skipped": True}
    else:
        result["skew"] = bench_skew()

    # contracts (the gate)
    cm = cov["cost_model"]
    checks = {
        "qerror_all_operators": bool(cov["all_operators_covered"]),
        # cost-model validation: the CBO must have priced real choices
        # (enumerated plans with a runner-up) and predicted seconds must
        # agree with measured device seconds on most plan-pair rankings
        "cost_model_choices_recorded":
            cm["plans_enumerated"] >= 5
            and cm["plans_with_runner_up"] >= 1,
        "cost_model_ranking":
            cm["ranking_agreement"] is None
            or cm["ranking_agreement"] >= 0.5,
        "overhead_le_2pct": ovh["overhead_pct"] <= 2.0,
        "feedback_one_retry":
            fb["on"]["first_run_retries"] == 1
            and fb["on"]["fresh_session_retries"] == 0,
        "ladder_without_feedback":
            fb["off"]["first_run_retries"] >= 2,
    }
    if not result["skew"].get("skipped"):
        checks["skew_visible"] = (
            result["skew"]["skewed"]["slice_skew"] >= 3.0
            and result["skew"]["uniform"]["slice_skew"] < 1.5)
    result["checks"] = checks
    result["ok"] = all(checks.values())

    # bench artifacts and the metrics plane share one schema
    from oceanbase_tpu.server import metrics as qmetrics

    result["sysstat"] = qmetrics.sysstat_dict()
    print(json.dumps(result))
    if not result["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
