#!/usr/bin/env python
"""Disk-pressure bench: the disk plane's standing contract.

Three halves, one dtl_bench-style JSON line:

1. **Overhead** — a write+read workload timed with disk budgets OFF
   (all limits 0: the plane costs one monotonic read per write) vs ON
   (1 GiB limits: the interval-gated poll walks the surfaces while the
   workload runs).  Contract: <= 2% elapsed overhead.

2. **Seeded ENOSPC per surface** — one-shot errno injection on every
   durable surface (wal, slog, manifest, segment, spill, backup)
   through the REAL entry points (SQL insert/DDL, checkpoint, spilled
   query, full backup).  Contract per surface: the failure lands as the
   typed plane error (DiskFull — never a bare OSError), the retry
   succeeds once the budget is spent, and the restarted instance is
   oracle-identical (no torn artifacts).

3. **Inject -> degrade -> recover** — an unreachable log budget drops
   the tenant to read-only (after the reclaim round: aggressive
   checkpoint + WAL recycle); writes fail fast typed, reads keep
   serving, and lifting the budget auto-exits.  gv$disk used_bytes must
   track du within 5% throughout.

    python scripts/disk_bench.py            # BENCH_ROWS=4000 default
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _du(paths):
    total = 0
    for root in paths:
        if os.path.isfile(root):
            total += os.path.getsize(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    pass
    return total


def _count(s):
    return s.execute("select count(*), sum(v) from t").rows()[0]


def workload_block(s, keys, n_writes=40):
    """One timed block: n_writes rows through the admitted write path
    (the choke point the budgets gate).  Reads are NOT timed here —
    they bypass the gate by design, and their XLA recompiles at bucket
    boundaries would drown a 2% write-side signal in compile noise."""
    base = keys[0]
    vals = ", ".join(f"({base + i}, {(base + i) % 997})"
                     for i in range(n_writes))
    s.execute(f"insert into t values {vals}")
    keys[0] = base + n_writes


def _set_limits(s, lim):
    for knob in ("log_disk_limit_bytes", "data_disk_limit_bytes",
                 "spill_disk_limit_bytes"):
        s.execute(f"alter system set {knob} = {lim}")


def bench_overhead(s, keys, blocks=24):
    """Alternating off/on blocks; the verdict compares MEDIAN block
    times (a memtable flush or GC spike must not decide the gate)."""
    import statistics

    off, on = [], []
    for b in range(blocks):
        order = (False, True) if b % 2 == 0 else (True, False)
        for mode in order:
            _set_limits(s, (1 << 30) if mode else 0)
            t0 = time.monotonic()
            for _ in range(4):
                workload_block(s, keys)
            (on if mode else off).append(time.monotonic() - t0)
    _set_limits(s, 0)
    med_off = statistics.median(off)
    med_on = statistics.median(on)
    overhead = (med_on - med_off) / med_off if med_off else 0.0
    return {"off_s": round(sum(off), 3), "on_s": round(sum(on), 3),
            "median_off_s": round(med_off, 4),
            "median_on_s": round(med_on, 4),
            "overhead_pct": round(overhead * 100, 2),
            "pass": overhead <= 0.02}


def bench_surfaces(db, s, keys, tmp):
    """One-shot seeded ENOSPC per durable surface, through the real
    entry points; each must surface typed and recover on retry."""
    from oceanbase_tpu.net.faults import FaultPlane
    from oceanbase_tpu.server.backup import full_backup
    from oceanbase_tpu.server.diskmgr import DiskFull

    tenant = db.tenant("sys")
    local = tenant.wal.replicas[tenant.wal.leader_id]
    results = []

    def trial(surface, arm, fire, recover):
        plane = FaultPlane(seed=1000 + len(results))
        plane.disk("enospc", kind=surface)
        arm(plane)
        t0 = time.monotonic()
        typed = retried = False
        err = ""
        try:
            fire()
        except DiskFull:
            typed = True
        except Exception as exc:  # wrong type = torn contract
            err = f"{type(exc).__name__}: {exc}"
        if typed:
            try:
                recover()
                retried = True
            except Exception as exc:
                err = f"retry failed: {type(exc).__name__}: {exc}"
        arm(None)
        results.append({
            "surface": surface, "typed_error": typed,
            "recovered": retried, "error": err,
            "round_trip_s": round(time.monotonic() - t0, 3),
            "pass": typed and retried})

    def _ins():
        k = keys[0]
        keys[0] += 1
        s.execute(f"insert into t values ({k}, {k % 997})")

    def _arm_wal(p):
        local.faults = p

    def _arm_eng(p):
        tenant.engine.faults = p

    def _arm_db(p):
        db.faults = p

    trial("wal", _arm_wal, _ins, _ins)
    trial("slog", _arm_eng,
          lambda: s.execute("create table slog_probe (k int primary key)"),
          lambda: s.execute("create table slog_probe (k int primary key)"))
    _ins()  # memtable data so the next checkpoint flushes a segment
    trial("segment", _arm_eng, db.checkpoint, db.checkpoint)
    trial("manifest", _arm_eng, db.checkpoint, db.checkpoint)
    s.execute("alter system set sql_work_area_rows = 100")
    spill_q = "select k, v from t order by v, k"
    trial("spill", _arm_db,
          lambda: s.execute(spill_q), lambda: s.execute(spill_q))
    s.execute("alter system set sql_work_area_rows = 1000000")
    bdir = os.path.join(tmp, "backup")

    def _backup():
        shutil.rmtree(bdir, ignore_errors=True)
        full_backup(db, bdir)

    trial("backup", _arm_db, _backup, _backup)
    return {"surfaces": results,
            "pass": all(r["pass"] for r in results)}


def bench_degrade(db, s):
    """Inject (unreachable log budget) -> degrade (read-only, reads
    serve) -> recover (auto-exit), with gv$disk tracking du <= 5%."""
    from oceanbase_tpu.server.diskmgr import TenantReadOnly

    dm = db.tenant("sys").diskmgr
    out = {}
    t0 = time.monotonic()
    s.execute("alter system set log_disk_limit_bytes = 10")
    dm.poll(force=True)
    out["reclaims"] = dm.reclaims
    out["entered_readonly"] = dm.read_only
    rejected = False
    try:
        s.execute("insert into t values (99999991, 1)")
    except TenantReadOnly:
        rejected = True
    out["write_rejected_typed"] = rejected
    pre = _count(s)
    out["reads_serve_in_readonly"] = pre[0] > 0
    rows = s.execute("select surface, used_bytes, state from gv$disk"
                     " where surface = 'log'").rows()
    du = _du(dm.paths["log"])
    out["gv_disk_state"] = rows[0][2] if rows else ""
    out["gv_vs_du_pct"] = round(
        abs(rows[0][1] - du) / max(1, du) * 100, 2) if rows else 100.0
    s.execute("alter system set log_disk_limit_bytes = 0")
    dm.poll(force=True)
    out["exited_readonly"] = not dm.read_only
    recovered = False
    try:
        s.execute("insert into t values (99999991, 1)")
        recovered = True
    except Exception:
        pass
    out["writes_resume"] = recovered
    out["round_trip_s"] = round(time.monotonic() - t0, 3)
    out["pass"] = bool(
        out["entered_readonly"] and out["write_rejected_typed"]
        and out["reads_serve_in_readonly"] and out["exited_readonly"]
        and out["writes_resume"] and out["gv_disk_state"] == "readonly"
        and out["gv_vs_du_pct"] <= 5.0 and out["reclaims"] >= 1)
    return out


def main():
    from oceanbase_tpu.server import Database

    n_rows = int(os.environ.get("BENCH_ROWS", "4000"))
    tmp = tempfile.mkdtemp(prefix="diskbench_")
    out = {"metric": "disk_bench", "rows": n_rows}
    db = None
    try:
        db = Database(os.path.join(tmp, "db"))
        s = db.session()
        s.execute("create table t (k int primary key, v int)")
        for lo in range(0, n_rows, 1000):
            hi = min(lo + 1000, n_rows)
            s.execute("insert into t values " + ", ".join(
                f"({i}, {i % 997})" for i in range(lo, hi)))
        keys = [n_rows]
        workload_block(s, keys)  # warmup (plan cache, jit)

        out["overhead"] = bench_overhead(s, keys)
        out["surfaces"] = bench_surfaces(db, s, keys, tmp)
        out["degrade"] = bench_degrade(db, s)

        # gv$disk vs du with budgets armed, steady state
        s.execute("alter system set log_disk_limit_bytes = 1073741824")
        s.execute("alter system set data_disk_limit_bytes = 1073741824")
        dm = db.tenant("sys").diskmgr
        rows = s.execute("select surface, used_bytes from gv$disk").rows()
        by = {r[0]: r[1] for r in rows}
        acct = {}
        for surface in ("log", "data"):
            du = _du(dm.paths[surface])
            pct = abs(by[surface] - du) / max(1, du) * 100
            acct[surface] = {"gv_bytes": by[surface], "du_bytes": du,
                             "delta_pct": round(pct, 2)}
        acct["pass"] = all(a["delta_pct"] <= 5.0
                           for a in acct.values() if isinstance(a, dict))
        out["accounting"] = acct

        # restart after the whole gauntlet is oracle-identical
        expect = _count(s)
        db.close()
        db = Database(os.path.join(tmp, "db"))
        got = _count(db.session())
        out["restart"] = {"expect": list(expect), "got": list(got),
                          "pass": got == expect}

        out["pass"] = bool(out["overhead"]["pass"]
                           and out["surfaces"]["pass"]
                           and out["degrade"]["pass"]
                           and out["accounting"]["pass"]
                           and out["restart"]["pass"])
        from oceanbase_tpu.server import metrics as qmetrics

        out["sysstat"] = {k: v for k, v in
                          sorted(qmetrics.sysstat_dict().items())
                          if k.startswith("disk.")}
        print(json.dumps(out))
        if not out["pass"]:
            sys.exit(1)
    finally:
        if db is not None:
            try:
                db.close()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
