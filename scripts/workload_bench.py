#!/usr/bin/env python
"""Workload-diagnostics bench: the plane's standing contract, one JSON
artifact (WORKLOAD_BENCH.json).

Four lanes:

1. **Overhead** — the TPC-H slice (q6 + q1) with the workload-snapshot
   thread OFF vs ON at a fast interval; the repo must cost <= 2%
   elapsed (diagnostics that tax the workload get turned off).

2. **Time model** — the same slice's host-phase decomposition
   (gv$time_model): bind + sidecar + lower + compile + dispatch +
   merge + device must sum to within 10% of the measured statement
   wall, or the decomposition is lying about where the clock went.

3. **Restart survival** — a snapshot written before Database close is
   crc64-verified on reopen and delta-reportable against a fresh
   post-restart snapshot (the repository's whole point: before/after
   comparisons across restarts).

4. **Cluster merge** — a real 3-node cluster runs Q6 through the DTL
   exchange, then ANALYZE WORKLOAD REPORT on one node must merge all
   three peers (workload.snapshot verb) and its
   ``rpc.bytes{verb=dtl.execute}`` sysstat line must reconcile with
   the coordinator's gv$px_exchange pushdown bytes within 1%.

    python scripts/workload_bench.py
    WORKLOAD_BENCH_SKIP_CLUSTER=1 python scripts/workload_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


def _gen(n_rows: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _load(sess, cols, n_rows):
    sess.execute(
        "create table lineitem (l_id int primary key, l_quantity int,"
        " l_extendedprice int, l_discount int, l_shipdate int,"
        " l_returnflag int, l_linestatus int)")
    for s in range(0, n_rows, 2000):
        e = min(s + 2000, n_rows)
        vals = ", ".join(
            f"({i}, {cols['l_quantity'][i]}, {cols['l_extendedprice'][i]},"
            f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
            f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
            for i in range(s, e))
        sess.execute(f"insert into lineitem values {vals}")


def _time_queries(sess, repeats: int) -> float:
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in QUERIES.values():
            sess.execute(q)
    return time.monotonic() - t0


def bench_overhead_and_phases(n_rows: int, repeats: int) -> dict:
    """Lanes 1+2 on one in-process Database: snapshot-thread overhead
    and the time-model phase-sum-vs-wall reconciliation."""
    from oceanbase_tpu.server import Database

    root = tempfile.mkdtemp(prefix="workloadbench_")
    try:
        db = Database(root)
        s = db.session()
        _load(s, _gen(n_rows), n_rows)
        # parity guard: the snapshot thread must never change results
        s.execute("alter system set workload_snapshot_interval_s = 0.2")
        s.execute("alter system set enable_workload_repo = true")
        on_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        s.execute("alter system set enable_workload_repo = false")
        off_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        assert on_rows == off_rows, "workload repo changed results"
        # measure at 1s — aggressive (60× the default cadence) but not
        # the 0.2s parity-phase setting, which exists to force many
        # snapshot/prune cycles, not to model production overhead
        s.execute("alter system set workload_snapshot_interval_s = 1.0")
        _time_queries(s, 3)  # steady state before measuring
        # finely interleaved off/on rounds (one q6+q1 pair per knob
        # flip) so host drift hits both modes equally, then compare
        # 25%-trimmed means — a scheduler spike on a shared host lands
        # in one round and gets trimmed, not averaged into the verdict
        rounds = max(repeats, 24)
        samples = {"false": [], "true": []}
        for r in range(rounds):
            for mode in (("false", "true") if r % 2 == 0
                         else ("true", "false")):
                s.execute(
                    f"alter system set enable_workload_repo = {mode}")
                samples[mode].append(_time_queries(s, 1))
        s.execute("alter system set enable_workload_repo = false")

        def _trimmed(xs):
            xs = sorted(xs)
            k = len(xs) // 4
            xs = xs[k:len(xs) - k] or xs
            return sum(xs) / len(xs)

        off_s, on_s = sum(samples["false"]), sum(samples["true"])
        overhead_pct = (_trimmed(samples["true"])
                        - _trimmed(samples["false"])) \
            / _trimmed(samples["false"]) * 100.0

        # lane 2: phase sum vs measured wall over the slice itself —
        # delta of the (monotonic) tenant account around a pure query
        # loop, so the load/knob statements don't dilute the check
        tm0 = db.time_model.snapshot()["sys"]
        _time_queries(s, max(repeats // 2, 5))
        tm1 = db.time_model.snapshot()["sys"]
        tm = {k: tm1[k] - tm0[k] for k in tm1}
        phase_sum = sum(tm[p] for p in
                        ("queue_s", "bind_s", "sidecar_build_s",
                         "lower_s", "compile_s", "dispatch_s",
                         "merge_s", "device_s"))
        coverage_pct = phase_sum / max(tm["elapsed_s"], 1e-12) * 100.0
        snaps = len(db.workload.snapshot_ids())
        db.close()
        return {
            "rows": n_rows, "repeats": rounds,
            "off_s": round(off_s, 4), "on_s": round(on_s, 4),
            "overhead_pct": round(overhead_pct, 3),
            "snapshots_taken": snaps,
            "phase_sum_s": round(phase_sum, 4),
            "elapsed_s": round(tm["elapsed_s"], 4),
            "statements": int(tm["statements"]),
            "phase_coverage_pct": round(coverage_pct, 2),
            "phases_reconcile": bool(90.0 <= coverage_pct <= 110.0),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_restart(n_rows: int) -> dict:
    """Lane 3: snapshot -> close -> reopen -> crc-verified load + delta
    report across the restart."""
    from oceanbase_tpu.server import Database

    root = tempfile.mkdtemp(prefix="workloadbench_rs_")
    try:
        db = Database(root)
        s = db.session()
        _load(s, _gen(n_rows), n_rows)
        for q in QUERIES.values():
            s.execute(q)
        snap = db.workload.snapshot(cluster=False)
        pre_id = snap["id"]
        db.close()

        db2 = Database(root)
        s2 = db2.session(tenant="sys")
        for q in QUERIES.values():
            s2.execute(q)
        loaded = db2.workload.load(pre_id)  # crc-verified or raises
        rep = db2.workload.build_report(from_id=pre_id, to_id=-1)
        ok = (loaded["id"] == pre_id and rep["from_id"] == pre_id
              and rep["to_id"] > pre_id and len(rep["rows"]) > 0)
        db2.close()
        return {
            "pre_restart_id": pre_id,
            "post_restart_to_id": rep["to_id"],
            "report_rows": len(rep["rows"]),
            "crc_verified_after_restart": True,
            "delta_reportable": bool(ok),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_cluster(n_rows: int, seed: int = 7) -> dict:
    """Lane 4: 3-node merged report; its rpc.bytes{verb=dtl.execute}
    line must reconcile with gv$px_exchange within 1%."""
    from chaos_bench import boot_cluster, rows_of, wait_converged

    root = tempfile.mkdtemp(prefix="workloadbench_cl_")
    procs = {}
    try:
        procs, clients, _sn, _wc = boot_cluster(root, seed=seed)
        c1 = clients[1]

        def sql(text):
            last = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    return c1.call("sql.execute", sql=text)
                except Exception as e:  # noqa: BLE001 — retried
                    last = e
                    time.sleep(0.3)
            raise TimeoutError(f"query never succeeded: {last}")

        cols = _gen(n_rows)
        sql("create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        for s in range(0, n_rows, 1000):
            e = min(s + 1000, n_rows)
            vals = ", ".join(
                f"({i}, {cols['l_quantity'][i]},"
                f" {cols['l_extendedprice'][i]},"
                f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
                f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
                for i in range(s, e))
            sql(f"insert into lineitem values {vals}")
        wait_converged(clients, "lineitem", n_rows)
        sql("alter system set dtl_min_rows = 1")
        for _ in range(3):
            sql(QUERIES["q6"])  # pushdown traffic to reconcile

        # the merged report: one statement on the coordinator
        rep = rows_of(sql("analyze workload report"))
        by_item = {(r[0], r[1]): r[2] for r in rep}
        span_detail = next((r[3] for r in rep if r[0] == "report"), "")
        nodes = span_detail.split("nodes=")[-1].split(",") \
            if "nodes=" in span_detail else []
        rpc_dtl = float(by_item.get(
            ("sysstat", "rpc.bytes{verb=dtl.execute}"), 0.0))

        exch = rows_of(sql(
            "select bytes_shipped from gv$px_exchange"
            " where mode = 'pushdown'"))
        dtl_bytes = sum(int(r[0]) for r in exch)
        drift_pct = (abs(rpc_dtl - dtl_bytes)
                     / max(dtl_bytes, 1) * 100.0)

        # the text face renders the same report
        tree = rows_of(sql("show workload report"))
        return {
            "rows": n_rows, "nodes_merged": len(nodes),
            "report_rows": len(rep),
            "tree_lines": len(tree),
            "rpc_dtl_bytes": int(rpc_dtl),
            "px_exchange_bytes": int(dtl_bytes),
            "drift_pct": round(drift_pct, 4),
            "reconciled": bool(len(nodes) == 3 and dtl_bytes > 0
                               and drift_pct <= 1.0),
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "100000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "40"))
    out = {"metric": "workload_bench"}
    out["overhead"] = bench_overhead_and_phases(n_rows, repeats)
    out["restart"] = bench_restart(min(n_rows, 20000))
    ok = (out["overhead"]["overhead_pct"] <= 2.0
          and out["overhead"]["phases_reconcile"]
          and out["restart"]["delta_reportable"])
    if not os.environ.get("WORKLOAD_BENCH_SKIP_CLUSTER"):
        out["cluster"] = bench_cluster(
            int(os.environ.get("BENCH_CLUSTER_ROWS", "20000")))
        ok = ok and out["cluster"]["reconciled"]
    out["ok"] = bool(ok)
    with open(os.path.join(REPO, "WORKLOAD_BENCH.json"), "w") as fh:
        json.dump(out, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
