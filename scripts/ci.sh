#!/usr/bin/env bash
# Local CI gate, in the order review expects:
#   1. obcheck --ci   static contract families (trace/mask/lock/metric/
#                     time/io/cancel/rpc) vs analysis/baseline.json
#   2. poison         dynamic Static-shape policy check: poison-lane
#                     parity tests (analysis/poison.py via the fixture)
#   3. tier-1         full non-slow pytest suite
# Prints one PASS/FAIL line per stage and exits non-zero if any failed.
#
# Slow perf contracts run out-of-band, not here:
#   python scripts/workload_bench.py   # writes WORKLOAD_BENCH.json
#     (snapshot overhead <= 2%, time-model phase sum within 10% of
#      wall, crc restart survival, 3-node merged-report reconciliation)
set -u
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

names=()
results=()
overall=0

run_stage() {
    name="$1"; shift
    echo "=== $name: $*"
    if "$@"; then
        names+=("$name"); results+=("PASS")
    else
        names+=("$name"); results+=("FAIL"); overall=1
    fi
    echo
}

run_stage "obcheck" python scripts/obcheck.py --ci
run_stage "poison" python -m pytest tests/ -q -m "not slow" -k poison \
    -p no:cacheprovider
run_stage "tier-1" python -m pytest tests/ -q -m "not slow" \
    -p no:cacheprovider

echo "==== local CI summary ===="
for i in "${!names[@]}"; do
    printf '  %-8s %s\n' "${names[$i]}" "${results[$i]}"
done
if [ "$overall" -eq 0 ]; then
    echo "RESULT: PASS"
else
    echo "RESULT: FAIL"
fi
exit "$overall"
