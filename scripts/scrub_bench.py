#!/usr/bin/env python
"""Scrub bench: the data-integrity plane's standing contract.

Two halves, one dtl_bench-style JSON line:

1. **Overhead** — the TPC-H slice (q6 + q1) on the leader of a live
   3-node cluster, timed with the scrubber OFF vs ON at an aggressive
   cadence (rounds re-reading every segment file + exchanging
   cross-replica digests WHILE the queries run).  Contract: <= 2%
   elapsed overhead — continuous verification must be effectively free.

2. **Bitflip → repair round trip** — a seeded bit flip rots one
   replica's segment file on disk; one scrub round must detect it,
   quarantine the file, refetch the table from a healthy peer over the
   chunked crc-verified rebuild verbs, and re-verify digest parity.
   The round trip is timed and byte-accounted, and the slice queries on
   the mended replica must return rows IDENTICAL to an independent
   sqlite oracle — zero corrupt reads served.

    python scripts/scrub_bench.py            # BENCH_ROWS=20000 default
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import socket
import sqlite3
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _gen(n_rows, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _rows(res):
    names = res["names"]
    n = len(next(iter(res["arrays"].values()))) if names else 0
    out = []
    for r in range(n):
        row = []
        for nm in names:
            v = res.get("valids", {}).get(nm)
            if v is not None and not v[r]:
                row.append(None)
            else:
                x = res["arrays"][nm][r]
                x = x.item() if hasattr(x, "item") else x
                row.append(round(x, 9) if isinstance(x, float) else x)
        out.append(tuple(row))
    return out


def sqlite_oracle(cols, n_rows):
    """The independent truth: the same slice queries through sqlite."""
    conn = sqlite3.connect(":memory:")
    conn.execute(
        "create table lineitem (l_id integer primary key,"
        " l_quantity int, l_extendedprice int, l_discount int,"
        " l_shipdate int, l_returnflag int, l_linestatus int)")
    conn.executemany(
        "insert into lineitem values (?,?,?,?,?,?,?)",
        [(i,) + tuple(int(cols[c][i]) for c in
                      ("l_quantity", "l_extendedprice", "l_discount",
                       "l_shipdate", "l_returnflag", "l_linestatus"))
         for i in range(n_rows)])
    out = {}
    for name, q in QUERIES.items():
        rows = conn.execute(q).fetchall()
        out[name] = [tuple(round(x, 9) if isinstance(x, float) else x
                           for x in r) for r in rows]
    conn.close()
    return out


def boot_trio(root):
    from oceanbase_tpu.net.node import NodeServer

    ports = _free_ports(3)
    nodes = {}
    for i in range(1, 4):
        peers = {j: ("127.0.0.1", ports[j - 1])
                 for j in range(1, 4) if j != i}
        nodes[i] = NodeServer(i, "127.0.0.1", ports[i - 1], peers,
                              root=os.path.join(root, f"n{i}"),
                              bootstrap=(i == 1), lease_ms=1500)
    for n in nodes.values():
        n.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            nodes[1].execute("select 1")
            return nodes
        except Exception:
            time.sleep(0.3)
    raise TimeoutError("cluster never elected a leader")


def wait_converged(nodes, n_rows, timeout=120):
    deadline = time.time() + timeout
    for i in (2, 3):
        while time.time() < deadline:
            try:
                r = nodes[i].execute("select count(*) from lineitem",
                                     consistency="weak")
                if int(r["arrays"][r["names"][0]][0]) == n_rows:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        else:
            raise TimeoutError(f"node {i} never converged")


def time_queries(node, repeats):
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in QUERIES.values():
            node.execute(q)
    return time.monotonic() - t0


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "20000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "32"))
    root = tempfile.mkdtemp(prefix="scrubbench_")
    out = {"metric": "scrub_bench", "rows": n_rows}
    nodes = {}
    try:
        cols = _gen(n_rows)
        oracle = sqlite_oracle(cols, n_rows)
        nodes = boot_trio(root)
        lead = nodes[1]
        lead.execute(
            "create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        t_load = time.monotonic()
        for s in range(0, n_rows, 2000):
            e = min(s + 2000, n_rows)
            vals = ", ".join(
                f"({i}, {cols['l_quantity'][i]},"
                f" {cols['l_extendedprice'][i]},"
                f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
                f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
                for i in range(s, e))
            lead.execute(f"insert into lineitem values {vals}")
        out["load_s"] = round(time.monotonic() - t_load, 2)
        wait_converged(nodes, n_rows)
        for n in nodes.values():
            n.tenant.checkpoint()

        # parity guard + jit warmup
        for name, q in QUERIES.items():
            assert _rows(lead.execute(q)) == oracle[name], \
                f"{name} diverges from sqlite oracle pre-bench"
        time_queries(lead, 3)

        # ---- half 1: scrub-on vs scrub-off overhead ----------------
        # aggressive cadence (150x the production default of 300 s) so
        # rounds genuinely overlap the measured queries
        for n in nodes.values():
            n.config.set("scrub_interval_s", 2.0)
        off_s = on_s = 0.0
        blocks = 8
        per_block = max(repeats // blocks, 1)
        for b in range(blocks):
            order = (False, True) if b % 2 == 0 else (True, False)
            for mode in order:
                for n in nodes.values():
                    n.config.set("enable_scrub", mode)
                dt = time_queries(lead, per_block)
                if mode:
                    on_s += dt
                else:
                    off_s += dt
        for n in nodes.values():
            n.config.set("enable_scrub", True)
        scrub_rounds = sum(
            1 for r in lead.scrubber.state.rows()
            if r["phase"] == "verify")
        overhead = (on_s - off_s) / off_s if off_s else 0.0
        out["overhead"] = {
            "off_s": round(off_s, 3), "on_s": round(on_s, 3),
            "overhead_pct": round(overhead * 100, 2),
            "scrub_rounds_leader": scrub_rounds,
            "queries": per_block * blocks * 2 * len(QUERIES),
            "pass": overhead <= 0.02}

        # ---- half 2: seeded bitflip -> detect/quarantine/repair ----
        from oceanbase_tpu.net.faults import bitflip_file
        from oceanbase_tpu.storage.integrity import CorruptionError
        from oceanbase_tpu.storage.segment import Segment

        victim = nodes[3]
        seg_files = glob.glob(os.path.join(
            victim.root, "data", "segments", "lineitem_*.npz"))
        flipped = None
        for seed in range(1, 64):
            probe = seg_files[0] + ".probe"
            shutil.copyfile(seg_files[0], probe)
            bitflip_file(probe, seed=seed)
            try:
                Segment.load(probe)
            except CorruptionError:
                bitflip_file(seg_files[0], seed=seed)
                flipped = seed
            finally:
                os.remove(probe)
            if flipped:
                break
        assert flipped, "no detectable flip found"
        t0 = time.monotonic()
        s = victim.scrubber.run_once()
        repair_s = time.monotonic() - t0
        repair_rows = [r for r in victim.scrubber.state.rows()
                       if r["phase"] == "repair"]
        served = {name: _rows(victim.execute(q, consistency="weak"))
                  for name, q in QUERIES.items()}
        oracle_match = served == oracle
        for p in glob.glob(os.path.join(victim.root, "data", "segments",
                                        "lineitem_*.npz")):
            Segment.load(p)  # the mended files verify clean
        out["repair"] = {
            "seed": flipped,
            "detected": bool(s["corrupt"]),
            "repaired": s["repaired"],
            "failed": s["failed"],
            "round_trip_s": round(repair_s, 3),
            "repair_bytes": sum(r["bytes"] for r in repair_rows),
            "repair_peer": repair_rows[-1]["peer"] if repair_rows else -1,
            "oracle_match": oracle_match,
            "pass": bool(s["corrupt"] and s["repaired"] == ["lineitem"]
                         and not s["failed"] and oracle_match)}

        out["pass"] = bool(out["overhead"]["pass"]
                           and out["repair"]["pass"])
        from oceanbase_tpu.server import metrics as qmetrics

        out["sysstat"] = {k: v for k, v in
                          sorted(qmetrics.sysstat_dict().items())
                          if k.startswith("scrub.")}
        print(json.dumps(out))
        if not out["pass"]:
            sys.exit(1)
    finally:
        for n in nodes.values():
            try:
                n.stop()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
