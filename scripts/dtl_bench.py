"""DTL pushdown micro-bench: bytes-on-wire, plan pushdown vs snapshot pull.

Boots a real 3-node cluster (subprocess nodes, TCP rpc), loads a TPC-H
lineitem slice, then runs a Q6-style scan-aggregate (and a Q1-style
group-by) two ways:

- **pushdown**: the DTL exchange ships the partial plan to every node;
  only partial aggregate states return (px/dtl.py);
- **pull**: the legacy remote-read path pages the whole snapshot to the
  coordinator over ``das.scan``.

Prints ONE JSON line with both byte counts and their ratio.

    python scripts/dtl_bench.py          # BENCH_ROWS=20000 by default
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from oceanbase_tpu.net.rpc import RpcClient  # noqa: E402


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def boot_cluster(root, n=3):
    ports = _free_ports(n)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for i in range(1, n + 1):
        peers = ",".join(f"{j}=127.0.0.1:{ports[j - 1]}"
                         for j in range(1, n + 1) if j != i)
        cmd = [sys.executable, "-m", "oceanbase_tpu.net.node",
               "--node-id", str(i), "--port", str(ports[i - 1]),
               "--peers", peers, "--root", os.path.join(root, f"n{i}")]
        if i == 1:
            cmd.append("--bootstrap")
        procs.append(subprocess.Popen(cmd, env=env,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL))
    clients = {i: RpcClient("127.0.0.1", ports[i - 1], timeout_s=60.0)
               for i in range(1, n + 1)}
    deadline = time.time() + 60
    for i, cli in clients.items():
        while time.time() < deadline:
            if cli.ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(f"node {i} not ready")
    return procs, clients


def wait_converged(clients, table, n_rows, timeout=120):
    deadline = time.time() + timeout
    for i in (2, 3):
        while time.time() < deadline:
            try:
                r = clients[i].call("sql.execute",
                                    sql=f"select count(*) from {table}",
                                    consistency="weak")
                cnt = int(r["arrays"][r["names"][0]][0])
                if r["node"] == i and cnt == n_rows:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            raise TimeoutError(f"node {i} never converged")


def pull_bytes(cli, table):
    """Node 1 pulls the whole snapshot FROM node 2 over das.scan (the
    legacy remote-read path) and reports the node-to-node wire cost —
    apples-to-apples with the pushdown's node-to-node exchange bytes."""
    r = cli.call("das.pull", table=table, node_id=2)
    return r["bytes"], r["rows"]


def last_exchange(cli):
    r = cli.call("sql.execute", sql=(
        "select bytes_shipped, rows_shipped, parts, pushdown_hit,"
        " elapsed_s from gv$px_exchange where mode = 'pushdown'"
        " order by ts desc limit 1"))
    a = r["arrays"]
    return {k: v[0].item() if hasattr(v[0], "item") else v[0]
            for k, v in a.items()}


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "20000"))
    query = os.environ.get("BENCH_QUERY", "q6")
    root = tempfile.mkdtemp(prefix="dtlbench_")
    procs = []
    try:
        procs, clients = boot_cluster(root)
        c1 = clients[1]

        def sql(text):
            return c1.call("sql.execute", sql=text)

        sql("create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        rng = np.random.default_rng(1)
        qty = rng.integers(1, 50, n_rows)
        price = rng.integers(1000, 100000, n_rows)
        disc = rng.integers(0, 10, n_rows)
        ship = rng.integers(8766, 10227, n_rows)  # ~1994-1997 in days
        rf = rng.integers(0, 3, n_rows)
        ls = rng.integers(0, 2, n_rows)
        t_load = time.monotonic()
        for s in range(0, n_rows, 1000):
            e = min(s + 1000, n_rows)
            vals = ", ".join(
                f"({i}, {qty[i]}, {price[i]}, {disc[i]}, {ship[i]},"
                f" {rf[i]}, {ls[i]})" for i in range(s, e))
            sql(f"insert into lineitem values {vals}")
        t_load = time.monotonic() - t_load
        wait_converged(clients, "lineitem", n_rows)
        sql("alter system set dtl_min_rows = 1")

        if query == "q1":
            q = ("select l_returnflag, l_linestatus, sum(l_quantity),"
                 " sum(l_extendedprice), avg(l_discount), count(*)"
                 " from lineitem where l_shipdate <= 10000"
                 " group by l_returnflag, l_linestatus"
                 " order by l_returnflag, l_linestatus")
        else:
            q = ("select sum(l_extendedprice * l_discount)"
                 " from lineitem where l_shipdate >= 8766"
                 " and l_shipdate < 9131 and l_discount >= 5"
                 " and l_discount <= 7 and l_quantity < 24")
        t0 = time.monotonic()
        sql(q)
        push_s = time.monotonic() - t0
        ex = last_exchange(c1)
        assert ex["pushdown_hit"] == 1, "query did not push down"

        t0 = time.monotonic()
        pbytes, prow = pull_bytes(c1, "lineitem")
        pull_s = time.monotonic() - t0

        # bench artifacts and the metrics plane share one schema: embed
        # the coordinator's gv$sysstat snapshot (flat {series: value})
        from oceanbase_tpu.server import metrics as qmetrics

        sysstat = qmetrics.wire_to_flat(
            c1.call("metrics.scrape")["wire"])

        print(json.dumps({
            "metric": "dtl_bytes_on_wire",
            "query": query, "rows": n_rows,
            "pushdown_bytes": int(ex["bytes_shipped"]),
            "pushdown_rows_shipped": int(ex["rows_shipped"]),
            "pushdown_parts": int(ex["parts"]),
            "pushdown_s": round(push_s, 4),
            "pull_bytes": int(pbytes),
            "pull_s": round(pull_s, 4),
            "bytes_ratio": round(ex["bytes_shipped"] / max(pbytes, 1), 6),
            "load_s": round(t_load, 2),
            "sysstat": sysstat,
        }))
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
