"""SF1 TPC-H 22-query parity evidence runner (VERDICT r3 item #2).

Generates TPC-H at SF (env PARITY_SF, default 1.0), loads both the
engine and the (now indexed) SQLite oracle, runs all 22 queries through
each, diffs results, and writes SF1_PARITY.json with per-query engine
and oracle wall times plus row counts — an artifact a skeptic can check.

Usage: [PARITY_SF=1.0] python scripts/sf_parity.py
"""

from __future__ import annotations

import json
import os
import sys
import time

# force CPU: the environment pins JAX_PLATFORMS to the (possibly dead)
# axon TPU tunnel, which would wedge jax initialization
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

# the axon sitecustomize registers + pins the TPU relay backend in every
# interpreter; drop it before any backend is instantiated (as conftest does)
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from oceanbase_tpu.bench.oracle import (  # noqa: E402
    load_sqlite, rows_match, run_oracle)
from oceanbase_tpu.bench.tpch import (  # noqa: E402
    TPCH_PRIMARY_KEYS, gen_tpch)
from oceanbase_tpu.bench.tpch_queries import QUERIES  # noqa: E402
from oceanbase_tpu.server import metrics as qmetrics  # noqa: E402
from oceanbase_tpu.sql import Session  # noqa: E402

SF = float(os.environ.get("PARITY_SF", "1.0"))
OUT = os.path.join(os.path.dirname(__file__), "..",
                   os.environ.get("PARITY_OUT", "SF1_PARITY.json"))


def main():
    t0 = time.monotonic()
    print(f"generating TPC-H SF={SF} ...", flush=True)
    tables, types = gen_tpch(sf=SF)
    gen_s = time.monotonic() - t0
    print(f"  done in {gen_s:.1f}s "
          f"(lineitem={len(tables['lineitem']['l_orderkey'])} rows)",
          flush=True)

    sess = Session()
    t0 = time.monotonic()
    for name, arrays in tables.items():
        sess.catalog.load_numpy(
            name, arrays,
            types={k: v for k, v in types.items() if k in arrays},
            primary_key=TPCH_PRIMARY_KEYS[name])
    # gather stats (exact NDV + histograms) before the run — mirrors the
    # reference's DBMS_STATS gather ahead of benchmarking
    for name in tables:
        sess.execute(f"analyze table {name}")
    # mirror the oracle's indexing (bench/oracle.py): a secondary index
    # on every *key column, so the CBO's index-probe access path
    # competes on equal footing with indexed SQLite
    for name, arrays in tables.items():
        for c in arrays:
            if c.endswith("key"):
                sess.execute(
                    f"create index idx_{name}_{c} on {name} ({c})")
    load_engine_s = time.monotonic() - t0
    t0 = time.monotonic()
    conn = load_sqlite(tables, types)
    load_oracle_s = time.monotonic() - t0
    print(f"loads: engine+analyze {load_engine_s:.1f}s, "
          f"oracle {load_oracle_s:.1f}s", flush=True)

    results = {}
    n_ok = 0
    for qnum in sorted(QUERIES):
        sql = QUERIES[qnum]
        t0 = time.monotonic()
        want = run_oracle(conn, sql)
        oracle_s = time.monotonic() - t0
        # per-query device attribution: the XLA cost_analysis counters
        # (exec/plan.py) delta'd across the query — measured flops and
        # bytes-accessed the cost-based-optimizer arc prices against
        f0 = qmetrics.counter_value("plan.flops_executed")
        b0 = qmetrics.counter_value("plan.bytes_executed")
        t0 = time.monotonic()
        try:
            got = sess.execute(sql).rows()
            engine_s = time.monotonic() - t0
            ordered = "order by" in sql.lower() and qnum not in (2, 18, 21)
            ok, why = rows_match(got, want, ordered=ordered)
        except Exception as e:  # noqa: BLE001 — record, keep going
            engine_s = time.monotonic() - t0
            ok, why = False, f"{type(e).__name__}: {e}"
            got = []
        n_ok += bool(ok)
        flops = qmetrics.counter_value("plan.flops_executed") - f0
        nbytes = qmetrics.counter_value("plan.bytes_executed") - b0
        results[f"q{qnum}"] = {
            "ok": bool(ok), "rows": len(got), "oracle_rows": len(want),
            "engine_s": round(engine_s, 3), "oracle_s": round(oracle_s, 3),
            "flops": int(flops), "bytes_accessed": int(nbytes),
            **({} if ok else {"why": why[:300]})}
        print(f"Q{qnum:02d}: {'OK ' if ok else 'FAIL'} "
              f"rows={len(got)} engine={engine_s:.2f}s "
              f"oracle={oracle_s:.2f}s gflops={flops / 1e9:.2f}"
              + ("" if ok else f"  [{why[:120]}]"), flush=True)

    # resolved-backend provenance (CPU-fallback runs tag themselves)
    from oceanbase_tpu.server.backend_info import (  # noqa: E402
        last_tpu_probe, resolve_backend)

    artifact = {
        "sf": SF, "queries_ok": n_ok, "queries_total": len(QUERIES),
        "gen_s": round(gen_s, 1), "load_engine_s": round(load_engine_s, 1),
        "load_oracle_s": round(load_oracle_s, 1),
        "host": {"nproc": os.cpu_count()},
        "backend": {**resolve_backend(), "tpu_probe": last_tpu_probe()},
        "results": results,
        # bench artifacts and the metrics plane share one schema
        "sysstat": qmetrics.sysstat_dict(),
    }
    with open(OUT, "w") as fh:
        json.dump(artifact, fh, indent=1)
    print(f"wrote {OUT}: {n_ok}/{len(QUERIES)} OK", flush=True)
    return 0 if n_ok == len(QUERIES) else 1


if __name__ == "__main__":
    sys.exit(main())
