"""Compile-amortization micro-bench: XLA compiles per query over a
growing table, shape buckets on vs off.

The whole-plan compiler caches jitted executables by plan fingerprint,
but ``jax.jit`` retraces per input *shape* — so without capacity
bucketing every INSERT that changes a table's cardinality invalidates
every compiled plan that touches it.  This bench runs the canonical
OLTP-interleaved loop (INSERT a batch -> run the same SELECT) ``STEPS``
times against two fresh databases — one with ``enable_shape_buckets``
on (the default), one with it off — and reports the XLA trace counts
from ``gv$plan_cache``.

Target: O(log n) compiles with buckets vs O(n) without (>= 10x fewer on
a 100-step loop), with identical query results.

Prints ONE JSON line (same harness family as scripts/dtl_bench.py):

    python scripts/recompile_bench.py            # STEPS=100 by default
    BENCH_STEPS=30 BENCH_ROWS_PER_STEP=20 python scripts/recompile_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERY = ("select grp, sum(v), count(*), avg(v) from t"
         " group by grp order by grp")


def run_loop(root: str, steps: int, rows_per_step: int,
             buckets: bool):
    """-> (trace_count, execution_count, results, elapsed_s,
    plan_cache_rows)."""
    from oceanbase_tpu.exec import plan as ep
    from oceanbase_tpu.server import Database

    ep.reset_plan_cache_stats()
    db = Database(root)
    s = db.session()
    s.execute(f"alter system set enable_shape_buckets = "
              f"{'true' if buckets else 'false'}")
    s.execute("create table t (id int primary key, v int, grp int)")
    results = []
    t0 = time.monotonic()
    next_id = 0
    for _step in range(steps):
        vals = ", ".join(
            f"({next_id + i}, {(next_id + i) * 7 % 101}, "
            f"{(next_id + i) % 5})" for i in range(rows_per_step))
        next_id += rows_per_step
        s.execute(f"insert into t values {vals}")
        results.append(s.execute(QUERY).rows())
    elapsed = time.monotonic() - t0
    # snapshot the python-side counters BEFORE the gv$plan_cache query
    # itself executes a plan; the virtual table materializes its rows
    # from the same pre-execution snapshot, so the two must agree
    traces = sum(e.xla_traces for e in ep.plan_cache_stats())
    execs = sum(e.executions for e in ep.plan_cache_stats())
    r = s.execute("select plan_text, executions, hit_count,"
                  " xla_trace_count from gv$plan_cache"
                  " order by executions desc")
    vt_rows = r.rows()
    vt_traces = sum(int(x[3]) for x in vt_rows)
    vt_execs = sum(int(x[1]) for x in vt_rows)
    db.close()
    assert vt_traces == traces and vt_execs == execs, \
        f"gv$plan_cache mismatch: {vt_traces}/{vt_execs} " \
        f"vs {traces}/{execs}"
    return traces, execs, results, elapsed, vt_rows


def main():
    steps = int(os.environ.get("BENCH_STEPS", "100"))
    rows_per_step = int(os.environ.get("BENCH_ROWS_PER_STEP", "50"))
    root = tempfile.mkdtemp(prefix="recompile_bench_")
    try:
        ex_traces, ex_execs, ex_res, ex_s, _ = run_loop(
            os.path.join(root, "exact"), steps, rows_per_step,
            buckets=False)
        bk_traces, bk_execs, bk_res, bk_s, _ = run_loop(
            os.path.join(root, "bucketed"), steps, rows_per_step,
            buckets=True)
        match = ex_res == bk_res
        # bench artifacts and the metrics plane share one schema: embed
        # this process's gv$sysstat snapshot (plan.* compile counters
        # and the bucket policy's effect live in the same series)
        from oceanbase_tpu.server import metrics as qmetrics

        print(json.dumps({
            "metric": "recompile_amortization",
            "steps": steps,
            "rows_per_step": rows_per_step,
            "final_rows": steps * rows_per_step,
            "compiles_exact": ex_traces,
            "compiles_bucketed": bk_traces,
            "compile_ratio": round(ex_traces / max(bk_traces, 1), 2),
            "executions_exact": ex_execs,
            "executions_bucketed": bk_execs,
            "loop_s_exact": round(ex_s, 3),
            "loop_s_bucketed": round(bk_s, 3),
            "results_match": bool(match),
            "sysstat": qmetrics.sysstat_dict(),
        }))
        if not match:
            raise SystemExit("bucketed results diverge from exact")
        # the >=10x gate is defined for the 100-step acceptance loop;
        # shorter smoke runs touch fewer buckets and naturally sit lower
        if steps >= 100 and ex_traces < 10 * bk_traces:
            raise SystemExit(
                f"compile amortization below 10x: {ex_traces} exact vs "
                f"{bk_traces} bucketed")
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
