#!/usr/bin/env python
"""obcheck driver: trace-safety, mask-discipline, lock-order, and
metric-discipline analysis.

    python scripts/obcheck.py                  # full report, exit 0
    python scripts/obcheck.py --ci             # fail (exit 1) on NEW
                                               # findings vs the baseline
    python scripts/obcheck.py --ci --json      # one-line JSON summary
                                               # (dtl_bench-style)
    python scripts/obcheck.py --write-baseline # refresh the baseline

The baseline (oceanbase_tpu/analysis/baseline.json) is a multiset of
finding keys: pre-existing, audited findings land green in CI and only
new violations fail.  Audited single sites prefer an inline
``# obcheck: ok(<rule>)`` pragma over a baseline entry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The checkers are pure stdlib AST analysis; pre-registering the parent
# package skips oceanbase_tpu/__init__ (which imports jax — seconds of
# cold start the CLI never needs)
if "oceanbase_tpu" not in sys.modules:
    import types

    _pkg = types.ModuleType("oceanbase_tpu")
    _pkg.__path__ = [os.path.join(REPO, "oceanbase_tpu")]
    sys.modules["oceanbase_tpu"] = _pkg

from oceanbase_tpu.analysis import (  # noqa: E402
    core,
    diff_findings,
    load_baseline,
    load_package_files,
    run_all,
    write_baseline,
)
from oceanbase_tpu.analysis.cancel_rules import (  # noqa: E402
    check_cancel_rules,
)
from oceanbase_tpu.analysis.io_rules import check_io_rules  # noqa: E402
from oceanbase_tpu.analysis.lock_order import check_lock_order  # noqa: E402
from oceanbase_tpu.analysis.mask_discipline import (  # noqa: E402
    check_mask_discipline,
)
from oceanbase_tpu.analysis.metric_rules import (  # noqa: E402
    check_metric_rules,
)
from oceanbase_tpu.analysis.rpc_rules import check_rpc_rules  # noqa: E402
from oceanbase_tpu.analysis.time_rules import check_time_rules  # noqa: E402
from oceanbase_tpu.analysis.trace_safety import check_trace_safety  # noqa: E402

CHECKERS = {
    "trace": check_trace_safety,
    "mask": check_mask_discipline,
    "lock": check_lock_order,
    "metric": check_metric_rules,
    "time": check_time_rules,
    "io": check_io_rules,
    "cancel": check_cancel_rules,
    "rpc": check_rpc_rules,
}


def _matches(rule: str, prefix: str) -> bool:
    return rule == prefix or rule.startswith(prefix + ".")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ci", action="store_true",
                    help="diff against the baseline; exit 1 on new findings")
    ap.add_argument("--json", action="store_true",
                    help="emit a one-line JSON summary")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--root", default=REPO, help="repo root to scan")
    ap.add_argument("--baseline", default=core.BASELINE_PATH,
                    help="baseline file path")
    ap.add_argument("--rules",
                    default="trace,mask,lock,metric,time,io,cancel,rpc",
                    help="comma-separated rule families to run")
    ap.add_argument("--family", action="append", default=None,
                    metavar="PREFIX",
                    help="only run/report rules matching this prefix "
                         "(repeatable; e.g. --family io --family "
                         "rpc.missing-policy)")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    files = load_package_files(args.root)
    selected = [r.strip() for r in args.rules.split(",")
                if r.strip() in CHECKERS]
    if args.family:
        # a prefix selects its whole family to run, then narrows output
        selected = [r for r in selected
                    if any(_matches(p, r) or _matches(r, p)
                           for p in args.family)]
    if args.write_baseline and (set(selected) != set(CHECKERS)
                                or args.family):
        # a partial run must never overwrite the other families' entries
        print("obcheck: --write-baseline requires all rule families "
              "(drop --rules/--family)", file=sys.stderr)
        return 2
    checkers = [CHECKERS[r] for r in selected]
    timings: dict[str, float] = {}
    findings = run_all(files, checkers, timings=timings)
    if args.family:
        findings = [f for f in findings
                    if any(_matches(f.rule, p) for p in args.family)]
    baseline = load_baseline(args.baseline) if not args.write_baseline \
        else Counter()
    new = diff_findings(findings, baseline)

    if args.write_baseline:
        data = write_baseline(findings, args.baseline)
        print(f"baseline written: {data['total']} findings -> "
              f"{args.baseline}")
        return 0

    by_rule = Counter(f.rule for f in findings)
    if args.json:
        family_s = {fam: round(timings.get(fn.__name__, 0.0), 3)
                    for fam, fn in CHECKERS.items() if fam in selected}
        print(json.dumps({
            "metric": "obcheck",
            "files": len(files),
            "findings": len(findings),
            "new": len(new),
            "baselined": len(findings) - len(new),
            "by_rule": {k: by_rule[k] for k in sorted(by_rule)},
            "family_s": family_s,
            "duration_s": round(time.monotonic() - t0, 3),
        }))
    if not args.json or new:
        report = new if args.ci else findings
        for f in report:
            print(f.render(), file=sys.stderr if args.ci else sys.stdout)
    if not args.json and not args.ci:
        print(f"{len(findings)} findings ({len(new)} new, "
              f"{len(findings) - len(new)} baselined) across "
              f"{len(files)} files")
    if args.ci and new:
        print(f"obcheck: {len(new)} NEW finding(s); fix them, add an "
              f"audited '# obcheck: ok(<rule>)' pragma, or refresh the "
              f"baseline via --write-baseline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
