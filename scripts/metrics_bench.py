#!/usr/bin/env python
"""Metrics bench: collection overhead + cluster-scrape reconciliation.

Two halves, one JSON line (the metrics plane's standing contract):

1. **Overhead** — the TPC-H slice (q6 + q1) on an in-process Database,
   timed with metrics OFF (``enable_metrics=false``) vs ON.  Every
   statement updates the statement/plan/plan-cache series in the ON
   runs; the contract is <= 2% elapsed overhead.

2. **Scrape reconciliation** — a real 3-node cluster runs Q6 through
   the DTL exchange, then every node is scraped over the idempotent
   ``metrics.scrape`` verb and the merged per-verb ``rpc.bytes``
   counter for ``dtl.execute`` must reconcile with the coordinator's
   ``gv$px_exchange`` pushdown bytes within 1% — the cluster-wide
   counters and the exchange ring are two views of one wire.

    python scripts/metrics_bench.py                  # both halves
    METRICS_BENCH_SKIP_CLUSTER=1 python scripts/metrics_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


def _gen(n_rows: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _load(sess, cols, n_rows):
    sess.execute(
        "create table lineitem (l_id int primary key, l_quantity int,"
        " l_extendedprice int, l_discount int, l_shipdate int,"
        " l_returnflag int, l_linestatus int)")
    for s in range(0, n_rows, 2000):
        e = min(s + 2000, n_rows)
        vals = ", ".join(
            f"({i}, {cols['l_quantity'][i]}, {cols['l_extendedprice'][i]},"
            f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
            f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
            for i in range(s, e))
        sess.execute(f"insert into lineitem values {vals}")


def _time_queries(sess, repeats: int) -> float:
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in QUERIES.values():
            sess.execute(q)
    return time.monotonic() - t0


def bench_overhead(n_rows: int, repeats: int) -> dict:
    from oceanbase_tpu.server import Database
    from oceanbase_tpu.server import metrics as qmetrics

    root = tempfile.mkdtemp(prefix="metricsbench_")
    try:
        db = Database(root)
        s = db.session()
        _load(s, _gen(n_rows), n_rows)
        # parity guard: metrics must never change results
        s.execute("alter system set enable_metrics = true")
        on_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        s.execute("alter system set enable_metrics = false")
        off_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        assert on_rows == off_rows, "metrics changed results"
        # warm the jit caches so the measurement sees steady state
        _time_queries(s, 3)
        # interleave off/on blocks in ALTERNATING order so warmth and
        # drift hit both sides equally
        off_s = on_s = 0.0
        blocks = 4
        per_block = max(repeats // blocks, 1)
        for b in range(blocks):
            order = ("false", "true") if b % 2 == 0 else ("true", "false")
            for mode in order:
                s.execute(f"alter system set enable_metrics = {mode}")
                dt = _time_queries(s, per_block)
                if mode == "true":
                    on_s += dt
                else:
                    off_s += dt
        s.execute("alter system set enable_metrics = true")
        n_series = len(qmetrics.sysstat_dict())
        db.close()
        return {
            "rows": n_rows, "repeats": per_block * blocks,
            "off_s": round(off_s, 4), "on_s": round(on_s, 4),
            "overhead_pct": round((on_s - off_s) / off_s * 100.0, 3),
            "series": n_series,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_scrape(n_rows: int, seed: int = 7) -> dict:
    """3-node cluster: scrape every node, merge, reconcile the merged
    rpc.bytes{verb=dtl.execute} against gv$px_exchange pushdown bytes."""
    from chaos_bench import boot_cluster, rows_of, wait_converged

    from oceanbase_tpu.server import metrics as qmetrics

    root = tempfile.mkdtemp(prefix="metricsbench_cl_")
    procs = {}
    try:
        procs, clients, _sn, _wc = boot_cluster(root, seed=seed)
        c1 = clients[1]

        def sql(text):
            last = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    return c1.call("sql.execute", sql=text)
                except Exception as e:  # noqa: BLE001 — retried
                    last = e
                    time.sleep(0.3)
            raise TimeoutError(f"query never succeeded: {last}")

        cols = _gen(n_rows)
        sql("create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        for s in range(0, n_rows, 1000):
            e = min(s + 1000, n_rows)
            vals = ", ".join(
                f"({i}, {cols['l_quantity'][i]},"
                f" {cols['l_extendedprice'][i]},"
                f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
                f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
                for i in range(s, e))
            sql(f"insert into lineitem values {vals}")
        wait_converged(clients, "lineitem", n_rows)
        sql("alter system set dtl_min_rows = 1")
        for _ in range(3):
            sql(QUERIES["q6"])  # pushdown traffic to reconcile

        # scrape all three nodes and merge (exactly what gv$sysstat does)
        merged: dict = {"counters": [], "gauges": [], "hists": []}
        per_node = {}
        for i, cli in sorted(clients.items()):
            r = cli.call("metrics.scrape")
            flat = qmetrics.wire_to_flat(r["wire"])
            per_node[str(i)] = {
                k: v for k, v in flat.items() if k.startswith("rpc.")}
            merged = qmetrics.merge_wire(merged, r["wire"])
        flat = qmetrics.wire_to_flat(merged)
        rpc_dtl_bytes = flat.get("rpc.bytes{verb=dtl.execute}", 0)

        # the exchange ring's view of the same wire (coordinator-side)
        exch = rows_of(sql(
            "select bytes_shipped from gv$px_exchange"
            " where mode = 'pushdown'"))
        dtl_bytes = sum(int(r[0]) for r in exch)
        drift_pct = (abs(rpc_dtl_bytes - dtl_bytes)
                     / max(dtl_bytes, 1) * 100.0)

        # the SQL face must agree with the raw scrape
        sysstat = rows_of(sql(
            "select stat_name, value from gv$sysstat"
            " where stat_name = 'rpc.bytes{verb=dtl.execute}'"))
        sql_face = int(sysstat[0][1]) if sysstat else 0

        prom = clients[1].call("metrics.scrape", format="prom")
        return {
            "rows": n_rows, "nodes": len(clients),
            "series_merged": len(flat),
            "rpc_dtl_bytes": int(rpc_dtl_bytes),
            "px_exchange_bytes": int(dtl_bytes),
            "drift_pct": round(drift_pct, 4),
            "sysstat_sql_face": sql_face,
            "prom_lines": len(prom["text"].splitlines()),
            "reconciled": bool(drift_pct <= 1.0 and dtl_bytes > 0
                               and sql_face >= rpc_dtl_bytes * 0.99),
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "100000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "40"))
    out = {"metric": "metrics_bench"}
    out["overhead"] = bench_overhead(n_rows, repeats)
    if not os.environ.get("METRICS_BENCH_SKIP_CLUSTER"):
        out["scrape"] = bench_scrape(
            int(os.environ.get("BENCH_CLUSTER_ROWS", "20000")))
        out["ok"] = bool(out["scrape"]["reconciled"]
                         and out["overhead"]["overhead_pct"] <= 2.0)
    else:
        out["ok"] = out["overhead"]["overhead_pct"] <= 2.0
    print(json.dumps(out))


if __name__ == "__main__":
    main()
