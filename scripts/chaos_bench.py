#!/usr/bin/env python
"""Chaos bench: the TPC-H slice under a scripted nemesis schedule.

Boots a real 3-node cluster (subprocess nodes, TCP rpc) with fault
injection enabled, loads a lineitem slice, records the fault-free Q6 /
Q1 answers, then replays the queries under three nemesis scenarios:

  drop30      30% message loss on ``dtl.execute`` (client-side sends
              from the coordinator) — the retry/backoff policy and
              per-slice fallback must absorb it;
  partition   the PALF leader partitioned from one follower (symmetric,
              installed on both sides) — the failure detector routes
              slices away and, if leadership moves, statement routing
              follows it;
  nodekill    SIGKILL a data node while a query is in flight — the
              in-flight slice falls back to the coordinator's replica;

then recovery scenarios close the loop (PR 6):

  nodekill_restart   restart the SIGKILLed process: WAL replay + leader
                     catch-up + rejoin; the detector flips back to up,
                     DTL sends slices to it again (avoided_parts → 0),
                     a row committed right before the kill reads from
                     the restarted node, and an XA branch prepared
                     before the kill is recoverable and commits;
  wipe_rebuild       empty the node's data dir: it bootstraps from a
                     peer checkpoint + segments + WAL over the chunked
                     rebuild.fetch_* verbs and reaches parity.

and the silent-corruption scenario closes the integrity loop:

  bitflip_scrub_repair   seeded bit flips rot THREE distinct persisted
                     artifact kinds on one node — a segment file (one
                     scrub.run round must detect → quarantine → repair
                     it from a peer, gv$scrub tells the story), a WAL
                     entry (restart: entry crc64 truncates the tail,
                     leader catch-up re-ships), and the manifest
                     (restart: digest check quarantines the baseline,
                     full WAL replay + catch-up reconstruct) — after
                     which the slice must be bit-identical to an
                     independent sqlite oracle with 0 corrupt reads.

and the disk-pressure scenario closes the budget loop:

  disk_full_readonly   an unreachable log budget on the PALF leader:
                     the reclaim round (aggressive checkpoint + WAL
                     recycle) cannot satisfy it, so the tenant drops
                     to read-only — typed errors only, weak reads
                     oracle-identical — leadership moves to a peer
                     with headroom (disk.takeover), lifting the
                     budget auto-exits read-only, and an ENOSPC-failed
                     WAL append + SIGKILL on the new leader restarts
                     clean (the unwound append leaves no torn entry).

Every query must return BIT-IDENTICAL rows to the fault-free baseline
and finish inside the bench deadline (no query may ride a hung socket).
Prints ONE dtl_bench-style JSON line: per-scenario parity, p99 latency,
retry/breaker counters from gv$cluster_health.

    python scripts/chaos_bench.py            # BENCH_ROWS=20000 default
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from oceanbase_tpu.net.rpc import RpcClient  # noqa: E402

#: per-query wall bound: generous vs the dtl.execute deadline (120 s)
#: but far below the 10 min sql.execute budget — a query that rides a
#: hung socket instead of failing fast blows this
QUERY_DEADLINE_S = 60.0


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def boot_cluster(root, n=3, seed=7):
    ports = _free_ports(n)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = {}

    def write_config(i):
        # arm the admin verb + pin the nemesis seed BEFORE boot (config
        # is per-node; ALTER SYSTEM on a follower would route to the
        # leader instead of the node under test)
        node_root = os.path.join(root, f"n{i}")
        os.makedirs(node_root, exist_ok=True)
        with open(os.path.join(node_root, "config.json"), "w") as f:
            # dtl_min_rows is seeded on EVERY node (not just via ALTER
            # SYSTEM on the current leader): statement routing follows
            # leadership if it moves mid-nemesis, and the new leader
            # must keep pushing down for gv$px_exchange assertions
            json.dump({"enable_fault_injection": True,
                       "fault_seed": seed,
                       "dtl_min_rows": 1}, f)
        return node_root

    def start_node(i, bootstrap=False):
        node_root = os.path.join(root, f"n{i}")
        peers = ",".join(f"{j}=127.0.0.1:{ports[j - 1]}"
                         for j in range(1, n + 1) if j != i)
        cmd = [sys.executable, "-m", "oceanbase_tpu.net.node",
               "--node-id", str(i), "--port", str(ports[i - 1]),
               "--peers", peers, "--root", node_root]
        if bootstrap:
            cmd.append("--bootstrap")
        procs[i] = subprocess.Popen(cmd, env=env,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)

    for i in range(1, n + 1):
        write_config(i)
        start_node(i, bootstrap=(i == 1))
    clients = {i: RpcClient("127.0.0.1", ports[i - 1], timeout_s=60.0)
               for i in range(1, n + 1)}
    deadline = time.time() + 60
    for i, cli in clients.items():
        while time.time() < deadline:
            if cli.ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(f"node {i} not ready")
    return procs, clients, start_node, write_config


def rows_of(res):
    names = res["names"]
    n = len(next(iter(res["arrays"].values()))) if names else 0
    out = []
    for r in range(n):
        row = []
        for nm in names:
            v = res.get("valids", {}).get(nm)
            if v is not None and not v[r]:
                row.append(None)
            else:
                x = res["arrays"][nm][r]
                row.append(x.item() if hasattr(x, "item") else x)
        out.append(tuple(row))
    return out


def wait_converged(clients, table, n_rows, timeout=120):
    deadline = time.time() + timeout
    for i in (2, 3):
        while time.time() < deadline:
            try:
                r = clients[i].call(
                    "sql.execute",
                    sql=f"select count(*) from {table}",
                    consistency="weak")
                if r["node"] == i and \
                        int(r["arrays"][r["names"][0]][0]) == n_rows:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            raise TimeoutError(f"node {i} never converged")


def wait_detector(cli, peer, states, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            h = cli.call("cluster.health")
            st = {r["peer"]: r["state"] for r in h["peers"]}
            if st.get(peer) in states:
                return True
        except Exception:
            pass
        time.sleep(0.2)
    return False


QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


def run_queries(exec_fn, baseline, repeats):
    """-> (parity, latencies, hung) over ``repeats`` rounds of q6+q1.
    A query is HUNG when it exceeds QUERY_DEADLINE_S (it must fail fast
    or succeed inside its rpc deadlines, never sit on a dead socket)."""
    lat, parity, hung = [], True, 0
    for _ in range(repeats):
        for name, sql in QUERIES.items():
            t0 = time.monotonic()
            got = rows_of(exec_fn(sql))
            dt = time.monotonic() - t0
            lat.append(dt)
            if dt > QUERY_DEADLINE_S:
                hung += 1
            if got != baseline[name]:
                parity = False
    return parity, lat, hung


def p99(lat):
    return float(np.percentile(np.asarray(lat), 99)) if lat else 0.0


def _round_rows(rows):
    return [tuple(round(x, 9) if isinstance(x, float) else x
                  for x in r) for r in rows]


def glob_segments(node_root):
    import glob as _glob

    return _glob.glob(os.path.join(node_root, "data", "segments",
                                   "lineitem_*.npz"))


def flip_detectable(path):
    """Seeded bit flip that provably lands in covered bytes (zip
    alignment padding is don't-care; a flip there corrupts nothing)."""
    from oceanbase_tpu.net.faults import bitflip_file
    from oceanbase_tpu.storage.integrity import CorruptionError
    from oceanbase_tpu.storage.segment import Segment

    for seed in range(1, 64):
        probe = path + ".probe"
        shutil.copyfile(path, probe)
        bitflip_file(probe, seed=seed)
        try:
            Segment.load(probe)
        except CorruptionError:
            os.remove(probe)
            bitflip_file(path, seed=seed)
            return seed
        finally:
            if os.path.exists(probe):
                os.remove(probe)
    raise AssertionError("no detectable flip found")


def flip_wal_entry(path):
    """Flip a payload bit of a COMPLETE mid-log entry (rot, not a torn
    append): the boot scan must fail its crc64 and truncate there."""
    from oceanbase_tpu.palf.log import _HDR, _MAGIC

    with open(path, "rb") as f:
        buf = f.read()
    assert buf.startswith(_MAGIC)
    offs = []
    off = len(_MAGIC)
    while off + _HDR.size <= len(buf):
        _t, _l, plen, _c = _HDR.unpack_from(buf, off)
        if off + _HDR.size + plen > len(buf):
            break
        offs.append(off + _HDR.size)
        off += _HDR.size + plen
    target = offs[len(offs) * 3 // 4]  # late entry: keep a replay prefix
    with open(path, "r+b") as f:
        f.seek(target)
        b = f.read(1)
        f.seek(target)
        f.write(bytes([b[0] ^ 0x10]))


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "20000"))
    seed = int(os.environ.get("BENCH_SEED", "7"))
    root = tempfile.mkdtemp(prefix="chaosbench_")
    procs = {}
    out = {"metric": "chaos_bench", "rows": n_rows, "seed": seed,
           "query_deadline_s": QUERY_DEADLINE_S, "scenarios": {}}
    try:
        procs, clients, start_node, write_config = \
            boot_cluster(root, seed=seed)
        c1 = clients[1]

        def sql(text, node=1):
            # statement-level retry over leadership churn: the rpc layer
            # fails fast (NotLeader / deadline), the client re-routes —
            # the degradation contract, not a hang
            last = None
            deadline = time.monotonic() + QUERY_DEADLINE_S
            while time.monotonic() < deadline:
                try:
                    return clients[node].call("sql.execute", sql=text)
                except Exception as e:  # noqa: BLE001 — retried
                    last = e
                    time.sleep(0.3)
            raise TimeoutError(
                f"query never succeeded inside {QUERY_DEADLINE_S}s: "
                f"{last}")

        sql("create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        rng = np.random.default_rng(1)
        qty = rng.integers(1, 50, n_rows)
        price = rng.integers(1000, 100000, n_rows)
        disc = rng.integers(0, 10, n_rows)
        ship = rng.integers(8766, 10227, n_rows)
        rf = rng.integers(0, 3, n_rows)
        ls = rng.integers(0, 2, n_rows)
        t_load = time.monotonic()
        for s in range(0, n_rows, 1000):
            e = min(s + 1000, n_rows)
            vals = ", ".join(
                f"({i}, {qty[i]}, {price[i]}, {disc[i]}, {ship[i]},"
                f" {rf[i]}, {ls[i]})" for i in range(s, e))
            sql(f"insert into lineitem values {vals}")
        out["load_s"] = round(time.monotonic() - t_load, 2)
        wait_converged(clients, "lineitem", n_rows)
        sql("alter system set dtl_min_rows = 1")

        # ---- fault-free baseline -----------------------------------
        baseline = {}
        for name, q in QUERIES.items():
            baseline[name] = rows_of(sql(q))
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        assert parity and hung == 0
        out["scenarios"]["baseline"] = {
            "parity": parity, "p99_s": round(p99(lat), 3),
            "queries": len(lat), "hung": hung}

        # ---- scenario 1: 30% drop on dtl.execute -------------------
        c1.call("fault.inject", where="send", action="drop",
                verb="dtl.execute", prob=0.30)
        parity, lat, hung = run_queries(sql, baseline, repeats=6)
        c1.call("fault.clear")
        h = c1.call("cluster.health")
        out["scenarios"]["drop30"] = {
            "parity": parity, "p99_s": round(p99(lat), 3),
            "queries": len(lat), "hung": hung,
            "retries": sum(r["retries"] for r in h["peers"])}

        if "--trace" in sys.argv:
            # full-link tracing under faults: the fault-hit queries'
            # trees must NAME the dropped/retried verb — rpc.dtl.execute
            # spans with a retry count or error tag, and per-slice
            # fallback spans for the slices re-run locally
            spans = rows_of(sql(
                "select span_name, tags from gv$trace"))
            rpc_spans = [json.loads(t) if t else {}
                         for n, t in spans if n == "rpc.dtl.execute"]
            retried = [t for t in rpc_spans
                       if int(t.get("retries", 0)) > 0 or "error" in t]
            fallbacks = sum(
                1 for n, t in spans
                if n == "dtl.slice" and t and
                json.loads(t).get("fallback"))
            out["trace"] = {
                "rpc_dtl_spans": len(rpc_spans),
                "retried_or_failed": len(retried),
                "fallback_slices": fallbacks,
                "verb_named": bool(retried or fallbacks),
            }

        # ---- scenario 2: partition the leader from node 2 ----------
        for a, b in ((1, 2), (2, 1)):
            for where in ("send", "recv"):
                clients[a].call("fault.inject", where=where,
                                action="drop", peer=b)
        wait_detector(c1, 2, ("suspect", "down"))
        # query through node 3 — it sees both sides of the partition
        parity, lat, hung = run_queries(
            lambda q: sql(q, node=3), baseline, repeats=3)
        hp = c1.call("cluster.health")
        for i in (1, 2):
            clients[i].call("fault.clear")
        wait_detector(c1, 2, ("up",))
        out["scenarios"]["partition_leader"] = {
            "parity": parity, "p99_s": round(p99(lat), 3),
            "queries": len(lat), "hung": hung,
            "leader_view": {r["peer"]: r["state"]
                            for r in hp["peers"]}}

        # staged BEFORE the SIGKILL: a committed marker row plus a
        # prepared-but-uncommitted XA branch (both with l_shipdate
        # outside the q1/q6 windows so the parity baselines hold) —
        # the restart scenario must find the marker on the restarted
        # node and the branch recoverable (durable XA)
        marker_k, xa_k = n_rows + 1, n_rows + 2
        sql(f"insert into lineitem values ({marker_k}, 1, 1, 1,"
            f" 10200, 0, 0)")
        sql("xa start 'cb1'")
        sql(f"insert into lineitem values ({xa_k}, 1, 1, 1,"
            f" 10200, 0, 0)")
        sql("xa end 'cb1'")
        sql("xa prepare 'cb1'")

        # ---- scenario 3: kill a data node mid-query ----------------
        results = {}

        def midq():
            results["rows"] = rows_of(sql(QUERIES["q6"]))

        th = threading.Thread(target=midq)
        th.start()
        time.sleep(0.05)  # the fan-out is (likely) in flight now
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        th.join(timeout=QUERY_DEADLINE_S)
        assert not th.is_alive(), "mid-kill query hung"
        mid_parity = results.get("rows") == baseline["q6"]
        wait_detector(c1, 3, ("suspect", "down"))
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        h = c1.call("cluster.health")
        st3 = {r["peer"]: r for r in h["peers"]}[3]
        out["scenarios"]["nodekill_midquery"] = {
            "parity": bool(mid_parity and parity),
            "p99_s": round(p99(lat), 3), "queries": len(lat) + 1,
            "hung": hung, "detector_state": st3["state"],
            "breaker_opens": st3["breaker_opens"]}

        # avoided slices show up in gv$px_exchange
        ex = sql("select avoided_parts, fallback_parts from"
                 " gv$px_exchange where mode = 'pushdown'"
                 " order by ts desc limit 1")
        av, fb = rows_of(ex)[0]
        out["avoided_parts_last"] = int(av)
        out["fallback_parts_last"] = int(fb)

        # ---- scenario 4: restart the SIGKILLed node ----------------
        # restart replay + log catch-up + rejoin: the detector flips
        # down→up, DTL routes slices back (avoided_parts returns to 0),
        # the pre-kill marker row reads from the restarted node, and
        # the pre-kill prepared XA branch commits (durable XA)
        t0 = time.monotonic()
        start_node(3)
        deadline = time.time() + 60
        while time.time() < deadline:
            if clients[3].ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("restarted node 3 never came up")
        if not wait_detector(c1, 3, ("up",), timeout=30):
            raise TimeoutError("detector never flipped node 3 up")
        # only the marker is committed (the XA branch is prepared, its
        # redo invisible until commit)
        wait_converged(clients, "lineitem", n_rows + 1)
        restart_s = time.monotonic() - t0

        def weak3(q):
            return clients[3].call("sql.execute", sql=q,
                                   consistency="weak")

        m = rows_of(weak3(
            f"select l_quantity from lineitem where l_id = {marker_k}"))
        marker_ok = m == [(1,)]
        rec = clients[3].call("recovery.state")
        xa_recoverable = "cb1" in rec.get("prepared_xids", [])
        sql("xa commit 'cb1'")
        wait_converged(clients, "lineitem", n_rows + 2)
        xa_row = rows_of(weak3(
            f"select l_quantity from lineitem where l_id = {xa_k}"))
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        ex = sql("select avoided_parts, fallback_parts from"
                 " gv$px_exchange where mode = 'pushdown'"
                 " order by ts desc limit 1")
        av, fb = rows_of(ex)[0]
        out["scenarios"]["nodekill_restart"] = {
            "parity": bool(parity and marker_ok and xa_recoverable
                           and xa_row == [(1,)]),
            "p99_s": round(p99(lat), 3), "queries": len(lat),
            "hung": hung, "restart_s": round(restart_s, 2),
            "detector_state": "up", "marker_on_restarted_node": marker_ok,
            "xa_recoverable": xa_recoverable,
            "xa_committed_row": xa_row == [(1,)],
            "avoided_parts": int(av), "fallback_parts": int(fb),
            "boot_phases": sorted({e["phase"]
                                   for e in rec.get("events", [])})}

        # ---- scenario 5: wipe the node's data dir, rebuild ---------
        # zero local recovery sources: bootstrap over the chunked
        # rebuild.fetch_meta / rebuild.fetch_segments verbs from a
        # peer's checkpoint + segments + WAL, then WAL-tail catch-up
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        shutil.rmtree(os.path.join(root, "n3"), ignore_errors=True)
        write_config(3)
        t0 = time.monotonic()
        start_node(3)
        deadline = time.time() + 90
        while time.time() < deadline:
            if clients[3].ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("wiped node 3 never came up")
        if not wait_detector(c1, 3, ("up",), timeout=30):
            raise TimeoutError("detector never flipped rebuilt node up")
        wait_converged(clients, "lineitem", n_rows + 2)
        rebuild_s = time.monotonic() - t0
        rec = clients[3].call("recovery.state")
        ev = {e["phase"]: e for e in rec.get("events", [])}
        served = rows_of(weak3(QUERIES["q6"]))
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        out["scenarios"]["wipe_rebuild"] = {
            "parity": bool(parity and served == baseline["q6"]
                           and "rebuild" in ev),
            "p99_s": round(p99(lat), 3), "queries": len(lat) + 1,
            "hung": hung, "rebuild_s": round(rebuild_s, 2),
            "served_by_rebuilt_node": served == baseline["q6"],
            "rebuild_bytes": int(ev.get("rebuild", {}).get("bytes", 0)),
            "rebuild_files": int(ev.get("rebuild", {}).get("entries", 0)),
            "rebuild_peer": int(ev.get("rebuild", {}).get("peer", -1))}

        # ---- scenario 6: seeded bit rot across 3 artifact kinds ----
        # independent truth: the same slice queries through sqlite
        import sqlite3

        conn = sqlite3.connect(":memory:")
        conn.execute(
            "create table lineitem (l_id integer primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        conn.executemany(
            "insert into lineitem values (?,?,?,?,?,?,?)",
            [(i, int(qty[i]), int(price[i]), int(disc[i]),
              int(ship[i]), int(rf[i]), int(ls[i]))
             for i in range(n_rows)])
        oracle = {}
        for name, q in QUERIES.items():
            oracle[name] = [
                tuple(round(x, 9) if isinstance(x, float) else x
                      for x in r) for r in conn.execute(q).fetchall()]
        conn.close()
        assert all(_round_rows(baseline[k]) == oracle[k]
                   for k in QUERIES), \
            "fault-free baseline diverges from the sqlite oracle"

        from oceanbase_tpu.net.faults import bitflip_file
        from oceanbase_tpu.storage.integrity import CorruptionError
        from oceanbase_tpu.storage.segment import Segment

        n3 = os.path.join(root, "n3")
        t0 = time.monotonic()
        # (a) segment rot, repaired LIVE by one scrub round from a peer
        seg = sorted(glob_segments(n3))[0]
        flip_detectable(seg)
        sres = clients[3].call("scrub.run")
        seg_ok = bool(sres.get("corrupt")) and \
            sres.get("repaired") == ["lineitem"] and not sres.get("failed")
        gv = rows_of(clients[3].call(
            "sql.execute", sql="select phase, bytes, peer from gv$scrub"
            " where phase = 'repair'", consistency="weak"))
        served = {k: _round_rows(rows_of(clients[3].call(
            "sql.execute", sql=q, consistency="weak")))
            for k, q in QUERIES.items()}
        seg_parity = served == oracle
        for p in glob_segments(n3):
            Segment.load(p)  # mended files verify clean

        # (b) WAL-entry rot + (c) manifest rot, repaired at RESTART
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        flip_wal_entry(os.path.join(n3, "wal", "replica_3.log"))
        bitflip_file(os.path.join(n3, "data", "manifest.json"), seed=5)
        start_node(3)
        deadline = time.time() + 90
        while time.time() < deadline:
            if clients[3].ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("bit-rotted node 3 never came back")
        if not wait_detector(c1, 3, ("up",), timeout=30):
            raise TimeoutError("detector never flipped rotted node up")
        wait_converged(clients, "lineitem", n_rows + 2)
        rot_s = time.monotonic() - t0
        rec = clients[3].call("recovery.state")
        quar = [e for e in rec.get("events", [])
                if e["phase"] == "quarantine"]
        kinds_detected = {"segment"} if seg_ok else set()
        for e in quar:
            kinds_detected.add(
                "wal" if "wal" in e.get("note", "") else "manifest")
        served2 = {k: _round_rows(rows_of(clients[3].call(
            "sql.execute", sql=q, consistency="weak")))
            for k, q in QUERIES.items()}
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        out["scenarios"]["bitflip_scrub_repair"] = {
            "parity": bool(seg_ok and seg_parity and parity
                           and served2 == oracle
                           and kinds_detected >=
                           {"segment", "wal", "manifest"}),
            "p99_s": round(p99(lat), 3), "queries": len(lat) + 4,
            "hung": hung, "round_trip_s": round(rot_s, 2),
            "kinds_detected": sorted(kinds_detected),
            "scrub_repairs": len(gv),
            "scrub_repair_bytes": sum(int(b) for _p, b, _pe in gv),
            "scrub_repair_peer": int(gv[0][2]) if gv else -1,
            "oracle_match": served2 == oracle,
            "quarantine_events": len(quar)}

        # ---- scenario 7: overload shed — 4x load + a node kill -----
        # a statement storm far over the admission slots while a data
        # node dies mid-storm: zero hangs, failures are TYPED shed/
        # routing errors only, and every ADMITTED (successful) result
        # is bit-identical to the independent sqlite oracle
        for knob, val in (("admission_slots", 2),
                          ("admission_tenant_slots", 2),
                          ("admission_queue_limit", 2),
                          ("admission_queue_timeout_s", 1.0)):
            sql(f"alter system set {knob} = {val}")
        shed_ok_kinds = {
            # admission/deadline shed (the overload plane's contract)
            "ServerBusy", "QueryTimeout", "QueryKilled",
            "MemstoreFull",
            # routing/network faults of the concurrent node kill —
            # typed at the rpc/palf layer, retried by real clients
            "NotLeader", "NoQuorum", "DeadlineExceeded",
            "ConnPoolExhausted", "DtlLagging", "ConnectionError",
            "ConnectionResetError", "BrokenPipeError", "TimeoutError"}
        storm_results: list = []
        storm_lock = threading.Lock()

        def storm_client(k):
            # one DISTINCT server-side session per client (sessions are
            # single-statement state machines, like real wire clients)
            name = "q6" if k % 2 == 0 else "q1"
            for _ in range(3):
                t0 = time.monotonic()
                kind, rows = "ok", None
                try:
                    rows = rows_of(clients[1].call(
                        "sql.execute", sql=QUERIES[name],
                        session_id=1000 + k))
                except Exception as e:  # noqa: BLE001 — triaged
                    kind = getattr(e, "kind", type(e).__name__)
                dt = time.monotonic() - t0
                with storm_lock:
                    storm_results.append((name, kind, rows, dt))

        storm_threads = [threading.Thread(target=storm_client,
                                          args=(k,))
                         for k in range(12)]
        for t in storm_threads:
            t.start()
        time.sleep(0.3)  # the storm is in flight: kill a data node
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        for t in storm_threads:
            t.join(QUERY_DEADLINE_S * 2)
        shed_hung = sum(1 for _n, _k, _r, dt in storm_results
                        if dt > QUERY_DEADLINE_S) + \
            sum(1 for t in storm_threads if t.is_alive())
        shed_kinds: dict = {}
        for _n, k, _r, _dt in storm_results:
            shed_kinds[k] = shed_kinds.get(k, 0) + 1
        untyped = {k: v for k, v in shed_kinds.items()
                   if k != "ok" and k not in shed_ok_kinds}
        mismatches = [(n, _round_rows(r))
                      for n, k, r, _dt in storm_results
                      if k == "ok" and _round_rows(r) != oracle[n]]
        admitted_parity = not mismatches
        admitted = shed_kinds.get("ok", 0)
        for knob, val in (("admission_slots", 32),
                          ("admission_tenant_slots", 16),
                          ("admission_queue_limit", 64),
                          ("admission_queue_timeout_s", 10.0)):
            sql(f"alter system set {knob} = {val}")
        tr = rows_of(sql("select tenant, admitted, rejected, queued "
                         "from gv$tenant_resource"))
        out["scenarios"]["overload_shed"] = {
            "parity": bool(admitted_parity and shed_hung == 0
                           and not untyped and admitted > 0),
            "p99_s": round(p99([dt for *_x, dt in storm_results]), 3),
            "queries": len(storm_results), "hung": shed_hung,
            "admitted": admitted, "kinds": shed_kinds,
            "untyped_errors": untyped,
            "admitted_oracle_parity": admitted_parity,
            "parity_mismatches": len(mismatches),
            "tenant_resource": [list(r) for r in tr]}

        # ---- scenario 8: disk-full read-only + leader takeover -----
        # fill the LEADER's log budget mid-workload (disk plane,
        # server/diskmgr.py): the tenant must reclaim (aggressive
        # checkpoint + WAL recycle), then degrade to READ-ONLY —
        # typed errors only, zero hangs, weak reads stay
        # oracle-identical — hand leadership to a peer with headroom
        # (disk.takeover), auto-exit once the budget lifts, and a
        # subsequent ENOSPC-failed WAL append + SIGKILL on the new
        # leader must restart clean (the unwound append never leaves
        # a torn entry for replay to trip on)
        t0 = time.monotonic()
        start_node(3)  # dead since the overload storm
        deadline = time.time() + 60
        while time.time() < deadline:
            if clients[3].ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("node 3 never came back for scenario 8")
        if not wait_detector(c1, 3, ("up",), timeout=30):
            raise TimeoutError("detector never flipped node 3 up")
        n_now = int(rows_of(sql("select count(*) from lineitem"))[0][0])
        wait_converged(clients, "lineitem", n_now)

        def leader_id():
            for i, cli in clients.items():
                try:
                    if cli.call("node.state")["role"] == "leader":
                        return i
                except Exception:  # noqa: BLE001 — node may be down
                    pass
            return 0

        deadline = time.time() + 30
        lead = 0
        while time.time() < deadline and not lead:
            lead = leader_id()
            if not lead:
                time.sleep(0.3)
        assert lead, "no leader before the disk-full scenario"

        def gv_disk_state(i):
            r = rows_of(clients[i].call(
                "sql.execute", sql="select surface, state from gv$disk"
                " where surface = 'log'", consistency="weak"))
            return r[0][1] if r else ""

        # 16 bytes is below even the post-recycle WAL floor: the
        # reclaim round runs (and shrinks the log) but CANNOT satisfy
        # the budget, so the tenant must degrade instead of flapping;
        # config.set force-polls, so the reply tells us the outcome
        st = clients[lead].call("config.set",
                                name="log_disk_limit_bytes", value=16)
        entered_ro = bool(st.get("read_only"))
        ro_state = gv_disk_state(lead)
        ro_reads = {k: _round_rows(rows_of(clients[lead].call(
            "sql.execute", sql=q, consistency="weak")))
            for k, q in QUERIES.items()}
        ro_parity = ro_reads == oracle

        # write probes pointed AT the degraded node: every failure
        # must be a typed disk/routing error (never a hang, never a
        # bare OSError), and once leadership lands on a peer with
        # headroom the same probes succeed via forwarding — the
        # cluster keeps accepting writes with one disk full
        disk_ok_kinds = {"TenantReadOnly", "NotLeader", "NoQuorum",
                         "DeadlineExceeded", "ConnectionError",
                         "TimeoutError"}
        probe_kinds: dict = {}
        probe_hung = landed = 0
        new_lead = 0
        k0 = n_now + 100
        deadline = time.monotonic() + QUERY_DEADLINE_S
        while time.monotonic() < deadline:
            t1 = time.monotonic()
            kind = "ok"
            try:
                clients[lead].call(
                    "sql.execute",
                    sql=f"insert into lineitem values ({k0}, 1, 1, 1,"
                        f" 10200, 0, 0)")
            except Exception as e:  # noqa: BLE001 — triaged below
                kind = getattr(e, "kind", type(e).__name__)
            if time.monotonic() - t1 > QUERY_DEADLINE_S:
                probe_hung += 1
            probe_kinds[kind] = probe_kinds.get(kind, 0) + 1
            k0 += 1
            if kind == "ok":
                landed += 1
                new_lead = leader_id()
                if new_lead and new_lead != lead:
                    break
            time.sleep(0.1)
        untyped_disk = {k: v for k, v in probe_kinds.items()
                        if k != "ok" and k not in disk_ok_kinds}
        took_over = bool(new_lead and new_lead != lead)
        peer_headroom = (gv_disk_state(new_lead) == "ok"
                         if took_over else False)

        # space returns: lifting the budget auto-exits read-only at
        # the very next poll (config.set forces one)
        st2 = clients[lead].call("config.set",
                                 name="log_disk_limit_bytes", value=0)
        auto_exit = (not st2.get("read_only")
                     and gv_disk_state(lead) == "ok")
        from oceanbase_tpu.server import metrics as qmetrics

        flat = qmetrics.wire_to_flat(
            clients[lead].call("metrics.scrape")["wire"])
        reclaims = sum(int(v) for k, v in flat.items()
                       if k.startswith("disk.reclaims")
                       and isinstance(v, (int, float)))
        ro_exits = sum(int(v) for k, v in flat.items()
                       if k.startswith("disk.readonly_exits")
                       and isinstance(v, (int, float)))

        # ENOSPC-failed WAL append + SIGKILL on the (new) leader:
        # the append must fail TYPED with nothing committed, and the
        # restarted node must replay clean and reach parity
        m = new_lead if took_over else lead
        clients[m].call("config.set", name="enable_disk_faults",
                        value=True)
        clients[m].call("fault.inject", where="disk", action="enospc",
                        verb="wal", count=1)
        pre = int(rows_of(clients[m].call(
            "sql.execute", sql="select count(*) from lineitem",
            consistency="weak"))[0][0])
        enospc_kind = "ok"
        try:
            clients[m].call(
                "sql.execute",
                sql=f"insert into lineitem values ({k0}, 1, 1, 1,"
                    f" 10200, 0, 0)")
        except Exception as e:  # noqa: BLE001 — triaged
            enospc_kind = getattr(e, "kind", type(e).__name__)
        post = int(rows_of(clients[m].call(
            "sql.execute", sql="select count(*) from lineitem",
            consistency="weak"))[0][0])
        count_held = post == pre
        procs[m].send_signal(signal.SIGKILL)
        procs[m].wait(timeout=10)
        t1 = time.monotonic()
        start_node(m)
        deadline = time.time() + 90
        while time.time() < deadline:
            if clients[m].ping():
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(f"node {m} never came back from ENOSPC")
        watch = clients[min(i for i in clients if i != m)]
        if not wait_detector(watch, m, ("up",), timeout=30):
            raise TimeoutError(f"detector never flipped node {m} up")
        restart_s = time.monotonic() - t1
        sql(f"insert into lineitem values ({k0 + 1}, 1, 1, 1,"
            " 10200, 0, 0)")  # writes resume post-recovery
        cnt = int(rows_of(sql("select count(*) from lineitem"))[0][0])
        wait_converged(clients, "lineitem", cnt)
        served_m = _round_rows(rows_of(clients[m].call(
            "sql.execute", sql=QUERIES["q6"], consistency="weak")))
        parity, lat, hung = run_queries(sql, baseline, repeats=3)
        out["scenarios"]["disk_full_readonly"] = {
            "parity": bool(entered_ro and ro_state == "readonly"
                           and ro_parity and not untyped_disk
                           and landed > 0 and took_over
                           and peer_headroom and auto_exit
                           and reclaims >= 1 and ro_exits >= 1
                           and enospc_kind == "DiskFull"
                           and count_held
                           and served_m == oracle["q6"]
                           and parity and probe_hung == 0),
            "p99_s": round(p99(lat), 3),
            "queries": (len(lat) + sum(probe_kinds.values())
                        + len(QUERIES) + 1),
            "hung": hung + probe_hung,
            "old_leader": lead, "new_leader": new_lead,
            "entered_readonly": entered_ro,
            "readonly_state": ro_state,
            "readonly_reads_parity": ro_parity,
            "probe_kinds": probe_kinds,
            "untyped_errors": untyped_disk,
            "writes_landed_via_peer": landed,
            "takeover": took_over, "peer_headroom": peer_headroom,
            "auto_exit": auto_exit, "reclaims": reclaims,
            "readonly_exits": ro_exits,
            "enospc_kind": enospc_kind, "count_held": count_held,
            "restart_s": round(restart_s, 2),
            "served_by_restarted_node": served_m == oracle["q6"],
            "round_trip_s": round(time.monotonic() - t0, 2)}

        out["parity_all"] = all(s["parity"]
                                for s in out["scenarios"].values())
        out["hung_total"] = sum(s["hung"]
                                for s in out["scenarios"].values())
        # bench artifacts and the metrics plane share one schema: embed
        # the coordinator's gv$sysstat snapshot (rpc retry/deadline and
        # health transition counters tell the nemesis story in numbers)
        from oceanbase_tpu.server import metrics as qmetrics

        try:
            out["sysstat"] = qmetrics.wire_to_flat(
                c1.call("metrics.scrape")["wire"])
        except Exception as e:  # noqa: BLE001 — artifact, not gate
            out["sysstat"] = {"error": str(e)}
        print(json.dumps(out))
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
