"""Forensic TPU-tunnel probe (VERDICT r3 item #1).

Runs a layered diagnostic of the axon relay and appends everything to
TPU_PROBE_r04.log so a skeptic can see exactly why a TPU number does or
does not exist this round:

  1. env dump (axon/jax/xla vars)
  2. raw TCP probes of the relay pool IPs on the plugin's ports
  3. `jax.devices()` in a subprocess under a hard timeout, stderr captured
  4. if devices come up: a tiny matmul + device_put round-trip as smoke

Usage: python scripts/tpu_probe.py [tag]   (tag: start|mid|end)
Exit code 0 iff a real TPU device was usable.
"""

from __future__ import annotations

import datetime
import json
import os
import socket
import subprocess
import sys

LOG = os.path.join(os.path.dirname(__file__), "..", "TPU_PROBE_r04.log")

# ports the axon PJRT plugin family has used: relay control + data planes
CANDIDATE_PORTS = (8471, 8476, 8477, 8478, 8479, 9009, 9010, 50051)


def log(fh, msg):
    fh.write(msg.rstrip("\n") + "\n")
    fh.flush()
    print(msg)


def probe_sockets(fh):
    ips = os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")
    results = {}
    for ip in [i.strip() for i in ips if i.strip()]:
        for port in CANDIDATE_PORTS:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.settimeout(2.0)
            try:
                s.connect((ip, port))
                results[f"{ip}:{port}"] = "OPEN"
            except OSError as e:
                results[f"{ip}:{port}"] = f"closed ({e})"
            finally:
                s.close()
    log(fh, "socket probes: " + json.dumps(results, indent=None))
    return any(v == "OPEN" for v in results.values())


DEVICE_SNIPPET = r"""
import json, sys, time
t0 = time.time()
import jax
devs = jax.devices()
info = [{"platform": d.platform, "kind": getattr(d, "device_kind", "?"),
         "id": d.id} for d in devs]
t1 = time.time()
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).block_until_ready()
t2 = time.time()
print(json.dumps({"devices": info, "init_s": round(t1 - t0, 2),
                  "matmul_s": round(t2 - t1, 2),
                  "sum": float(y.astype(jnp.float32).sum())}))
"""


def probe_devices(fh, timeout):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the axon sitecustomize pick
    try:
        r = subprocess.run(
            [sys.executable, "-c", DEVICE_SNIPPET],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired as e:
        log(fh, f"jax.devices(): TIMEOUT after {timeout}s")
        log(fh, "partial stdout: " + (e.stdout or b"").decode("utf-8", "replace")[-2000:]
            if isinstance(e.stdout, bytes) else "partial stdout: " + str(e.stdout)[-2000:])
        log(fh, "partial stderr: " + (e.stderr or b"").decode("utf-8", "replace")[-4000:]
            if isinstance(e.stderr, bytes) else "partial stderr: " + str(e.stderr)[-4000:])
        return None
    log(fh, f"jax.devices(): exit={r.returncode}")
    if r.stdout.strip():
        log(fh, "stdout: " + r.stdout.strip()[-2000:])
    if r.stderr.strip():
        log(fh, "stderr: " + r.stderr.strip()[-4000:])
    if r.returncode == 0:
        try:
            out = json.loads(r.stdout.strip().splitlines()[-1])
            plats = {d["platform"] for d in out["devices"]}
            if plats - {"cpu"}:
                return out
        except (ValueError, KeyError):
            pass
    return None


def main():
    tag = sys.argv[1] if len(sys.argv) > 1 else "adhoc"
    timeout = int(os.environ.get("PROBE_TIMEOUT", "180"))
    with open(LOG, "a") as fh:
        log(fh, f"=== TPU probe [{tag}] {datetime.datetime.now(datetime.UTC).isoformat()} ===")
        envdump = {k: v for k, v in sorted(os.environ.items())
                   if any(s in k.lower() for s in ("axon", "jax", "xla", "tpu", "pallas"))}
        log(fh, "env: " + json.dumps(envdump))
        any_open = probe_sockets(fh)
        log(fh, f"relay reachable at TCP level: {any_open}")
        out = probe_devices(fh, timeout)
        if out is None:
            log(fh, f"VERDICT[{tag}]: TPU NOT usable this window")
            return 1
        log(fh, f"VERDICT[{tag}]: TPU usable — {json.dumps(out['devices'])}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
