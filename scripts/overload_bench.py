#!/usr/bin/env python
"""Overload bench: the serving plane under 4x offered load.

The gate behind the overload-robustness contract (ROADMAP "Standing
contracts"): a burst of heavy traffic must DEGRADE — typed rejections,
fair shares, bounded memory, prompt KILL — never hang or OOM.

Four phases against one in-process Database with TWO tenants:

  saturation   2 tenants, offered load ~4x the admission slots; every
               statement must finish OR fail typed (ServerBusy /
               QueryTimeout) inside the bench deadline — zero hangs,
               zero untyped errors;
  fairness     tenant `loud` offers 4x the statements of tenant
               `quiet` into a shared slot pool; weighted round-robin
               must keep quiet's completions >= 40% of its fair (half)
               share — i.e. >= 20% of total completions;
  kill         a seeded long (spilling) scan is KILLed mid-flight; the
               victim must unwind in under 2x the statement's measured
               checkpoint interval (checkpoints/runtime from an
               uninterrupted run of the same scan);
  write flood  concurrent writers against a small memstore budget;
               unflushed bytes must stay under memstore_limit_bytes
               (peak accounting), with ramp sleeps / typed
               MemstoreFull absorbing the flood.

All gates are count/ratio assertions — the bench host is 1-core and
scheduling-noise-bound, so absolute latencies are reported but never
asserted.  Prints ONE dtl_bench-style JSON line and refreshes
OVERLOAD_BENCH.json.

    python scripts/overload_bench.py          # BENCH_ROWS=20000 default
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: per-statement wall bound: a statement that neither finishes nor
#: fails typed inside this is a HANG (the bench's core assertion)
STMT_DEADLINE_S = 30.0
TYPED = ("ServerBusy", "QueryTimeout", "QueryKilled", "MemstoreFull")


def _closed_loop(tenant, session, make_sql, results, lock, stop,
                 deadline_s=STMT_DEADLINE_S):
    """One serving client: issue statements back-to-back until the
    window closes, recording every outcome (a rejected statement is a
    SHED outcome, not a retry loop — offered load stays offered)."""
    k = 0
    while not stop.is_set():
        t0 = time.monotonic()
        kind = "ok"
        try:
            session.execute(make_sql(k))
        except Exception as e:  # noqa: BLE001 — triaged below
            kind = type(e).__name__
        dt = time.monotonic() - t0
        with lock:
            results.append((tenant, kind, dt, dt > deadline_s))
        k += 1
        if kind != "ok":
            time.sleep(0.01)  # shed: tiny client backoff, keep offering


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "20000"))
    root = tempfile.mkdtemp(prefix="overloadbench_")
    out = {"metric": "overload_bench", "rows": n_rows,
           "stmt_deadline_s": STMT_DEADLINE_S}
    from oceanbase_tpu.server.database import Database

    db = Database(os.path.join(root, "db"))
    try:
        db.create_tenant("loud")
        db.create_tenant("quiet")
        # a small shared slot pool so 4x offered load actually queues
        # a pool small enough that EVERY statement queues: tenant
        # shares are then set by the WRR grant order, not by client
        # counts; the queue is shorter than the loud tenant's client
        # herd so the storm exercises BOTH degradation modes — queuing
        # AND typed full-queue rejection
        db.config.set("admission_slots", 2)
        db.config.set("admission_tenant_slots", 2)
        db.config.set("admission_queue_limit", 3)
        db.config.set("admission_queue_timeout_s", 4.0)

        rng = np.random.default_rng(7)
        b = rng.integers(0, 97, n_rows)
        for tname in ("loud", "quiet"):
            s = db.session(tname)
            s.execute("create table big (a int primary key, b int)")
            for lo in range(0, n_rows, 2000):
                hi = min(lo + 2000, n_rows)
                vals = ", ".join(f"({i}, {b[i]})" for i in range(lo, hi))
                s.execute(f"insert into big values {vals}")
            # warm the plan/XLA caches so the storm measures serving,
            # not first-compile
            s.execute("select sum(b), count(*) from big where b < 50")
            s.close()

        q = "select sum(b), count(*) from big where b < {}"

        # ---- phase 1+2: 4x offered load over 2 tenants -------------
        # closed-loop serving clients: loud runs 4x quiet's client
        # count against a 4-slot pool for a fixed window; offered load
        # stays ~3x the pool the whole time, so the storm measures
        # STEADY-STATE shedding and WRR share, not a one-shot burst
        results: list = []
        lock = threading.Lock()
        stop = threading.Event()
        window_s = float(os.environ.get("BENCH_WINDOW_S", "8"))
        clients = []
        for tname, count in (("loud", 8), ("quiet", 2)):
            for k in range(count):
                s = db.session(tname)
                mk = (lambda k0: lambda j: q.format(
                    20 + ((k0 * 7 + j) % 60)))(k)
                clients.append((tname, s, threading.Thread(
                    target=_closed_loop,
                    args=(tname, s, mk, results, lock, stop))))
        t0 = time.monotonic()
        for _t, _s, th in clients:
            th.start()
        time.sleep(window_s)
        stop.set()
        for _t, _s, th in clients:
            th.join(STMT_DEADLINE_S * 2)
        storm_s = time.monotonic() - t0
        for _t, s, _th in clients:
            s.close()
        hung_threads = sum(1 for _t, _s, th in clients
                           if th.is_alive())
        kinds = {}
        for _tn, kind, _dt, _hung in results:
            kinds[kind] = kinds.get(kind, 0) + 1
        hung = sum(1 for _tn, _k, _dt, h in results if h) + hung_threads
        untyped = {k: v for k, v in kinds.items()
                   if k != "ok" and k not in TYPED}
        per_tenant_ok = {"loud": 0, "quiet": 0}
        for tn, kind, _dt, _h in results:
            if kind == "ok":
                per_tenant_ok[tn] += 1
        total_ok = max(sum(per_tenant_ok.values()), 1)
        quiet_share = per_tenant_ok["quiet"] / total_ok
        # fair share for 2 equal-weight tenants = 50% of completions;
        # the gate is quiet keeping >= 40% OF THAT share (>= 20% of
        # total) while loud offers 4x the clients
        fairness_ok = quiet_share >= 0.20
        adm_rows = {r["tenant"]: r for r in db.admission.stats()}
        out["saturation"] = {
            "clients": {"loud": 8, "quiet": 2},
            "window_s": window_s,
            "offered": len(results), "storm_s": round(storm_s, 2),
            "completed": total_ok, "kinds": kinds, "hung": hung,
            "untyped_errors": untyped,
            "rejected": {t: adm_rows.get(t, {}).get("rejected", 0)
                         for t in ("loud", "quiet")},
            "queued": {t: adm_rows.get(t, {}).get("queued", 0)
                       for t in ("loud", "quiet")},
        }
        out["fairness"] = {
            "loud_completed": per_tenant_ok["loud"],
            "quiet_completed": per_tenant_ok["quiet"],
            "quiet_share": round(quiet_share, 3),
            "fair_share": 0.5, "floor": 0.20,
            "ok": fairness_ok,
        }

        # ---- phase 3: KILL a seeded long scan ----------------------
        from oceanbase_tpu.server import metrics as qmetrics

        db.config.set("admission_slots", 32)
        db.config.set("sql_work_area_rows", 512)  # spill: many chunks
        s = db.session("quiet")
        long_q = "select sum(b), count(*) from big where b < 90"
        cp0 = qmetrics.counter_value("admission.checkpoints")
        t0 = time.monotonic()
        s.execute(long_q)
        base_runtime = time.monotonic() - t0
        checkpoints = max(
            qmetrics.counter_value("admission.checkpoints") - cp0, 1)
        interval = base_runtime / checkpoints
        killer = db.session("quiet")
        res: dict = {}

        def victim():
            try:
                s.execute(long_q)
                res["kind"] = "ok"
            except Exception as e:  # noqa: BLE001 — triaged
                res["kind"] = type(e).__name__

        th = threading.Thread(target=victim)
        th.start()
        time.sleep(min(base_runtime * 0.3, 1.0))
        k0 = time.monotonic()
        killer.execute(f"kill query {s.session_id}")
        th.join(STMT_DEADLINE_S)
        kill_latency = time.monotonic() - k0
        killed_ok = (not th.is_alive()
                     and res.get("kind") == "QueryKilled")
        # ratio gate (+ a small scheduling-noise floor on the 1-core
        # host): the victim returns within 2 checkpoint intervals
        kill_bound = max(2.0 * interval, 0.5)
        out["kill"] = {
            "base_runtime_s": round(base_runtime, 3),
            "checkpoints": int(checkpoints),
            "checkpoint_interval_s": round(interval, 4),
            "kill_latency_s": round(kill_latency, 3),
            "bound_s": round(kill_bound, 3),
            "typed": res.get("kind"),
            "ok": bool(killed_ok and kill_latency <= kill_bound),
        }
        s.close()
        killer.close()

        # ---- phase 4: write flood under a small memstore budget ----
        # an old OPEN transaction pins the flush horizon, so the flood
        # cannot be silently drained by pressure flushes: the ramp and
        # the hard limit must do the bounding.  Pre-drain the earlier
        # phases' accounting, then measure THIS phase's peak.
        quiet = db.tenant("quiet")
        wsess = [db.session("quiet") for _ in range(4)]
        wsess[0].execute(
            "create table flood (a int primary key, p string)")
        pin = db.session("quiet")
        pin.execute("begin")
        pin.execute("insert into flood values (-1, 'pin')")
        db.checkpoint("quiet")  # drain load-phase memstore accounting
        quiet.throttle.reset_peak()
        sleeps0 = quiet.throttle.throttle_sleeps
        full0 = quiet.throttle.full_rejections
        limit = 200_000
        db.config.set("memstore_limit_bytes", limit)
        db.config.set("writing_throttle_trigger_pct", 50)
        db.config.set("writing_throttle_max_sleep_s", 0.002)
        payload = "z" * 200
        wres: list = []

        def writer(sess, base):
            full = 0
            okc = 0
            for i in range(250):
                try:
                    sess.execute(
                        f"insert into flood values "
                        f"({base + i}, '{payload}')")
                    okc += 1
                except Exception as e:  # noqa: BLE001 — triaged
                    if type(e).__name__ != "MemstoreFull":
                        wres.append(("untyped", type(e).__name__))
                        return
                    full += 1
                    time.sleep(0.002)
            wres.append(("done", okc, full))

        wthreads = [threading.Thread(target=writer,
                                     args=(wsess[i], i * 10000))
                    for i in range(4)]
        f0 = time.monotonic()
        for t in wthreads:
            t.start()
        peak_seen = 0
        while any(t.is_alive() for t in wthreads):
            peak_seen = max(peak_seen, quiet.throttle.used_bytes())
            time.sleep(0.01)
            if time.monotonic() - f0 > 120:
                break
        for t in wthreads:
            t.join(10)
        flood_s = time.monotonic() - f0
        thr = quiet.throttle.stats()
        untyped_w = [r for r in wres if r[0] == "untyped"]
        peak = int(max(peak_seen, thr["memstore_peak_bytes"]))
        sleeps = thr["throttle_sleeps"] - sleeps0
        fulls = thr["memstore_full_rejections"] - full0
        # recovery: the pin commits, the flush catches up, writes admit
        pin.execute("commit")
        recovered = False
        db.config.set("memstore_limit_bytes", 256 << 20)
        for _ in range(50):
            try:
                wsess[0].execute(
                    "insert into flood values (999999, 'ok')")
                recovered = True
                break
            except Exception:  # noqa: BLE001 — MemstoreFull mid-drain
                time.sleep(0.05)
        out["write_flood"] = {
            "writers": 4, "flood_s": round(flood_s, 2),
            "peak_bytes": peak, "limit_bytes": limit,
            "throttle_sleeps": int(sleeps),
            "memstore_full_rejections": int(fulls),
            "untyped_errors": [r[1] for r in untyped_w],
            "recovered_after_flush": recovered,
            "ok": bool(peak <= limit and fulls > 0 and sleeps > 0
                       and not untyped_w and recovered
                       and all(not t.is_alive() for t in wthreads)),
        }
        for w in wsess:
            w.close()
        pin.close()

        # ---- the gate ----------------------------------------------
        out["ok"] = bool(
            hung == 0 and not untyped
            and fairness_ok
            and out["kill"]["ok"]
            and out["write_flood"]["ok"])
        out["sysstat"] = {
            k: qmetrics.counter_value(k) for k in (
                "admission.admitted", "admission.queued",
                "admission.rejected", "admission.timeouts",
                "admission.kills", "admission.demotions",
                "admission.throttle_sleeps",
                "admission.memstore_full",
                "admission.px_downgrades")}
        line = json.dumps(out)
        print(line)
        with open(os.path.join(REPO, "OVERLOAD_BENCH.json"), "w") as f:
            f.write(line + "\n")
        if not out["ok"]:
            raise SystemExit(1)
    finally:
        db.close()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
