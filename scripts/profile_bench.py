#!/usr/bin/env python
"""Device-time profiling & roofline-calibration bench: the standing
contracts of the measurement plane this PR built.

Four halves, one dtl_bench-style JSON line (also written to
PROFILE_BENCH.json, with an embedded ``gv$sysstat`` snapshot so bench
artifacts and the metrics plane share one schema):

1. **Overhead** — the TPC-H slice (q6 + q1) timed with the host/device
   split (``enable_profiling``) OFF vs ON, tightly interleaved samples
   with MEDIAN per mode (the 1-core bench host schedules noisily —
   long windows + medians, never per-block ratios); contract <= 2%.

2. **Roofline accuracy** — after ONE ``ALTER SYSTEM CALIBRATE`` (full
   ladder), every TPC-H SF0.1 query's predicted device time
   ``max(flops/F, bytes/B) + calls*L`` q-errors against its measured
   ``device_s``; contract: median time-q-error <= 4x across all 22.

3. **Measured rates** — ``gv$plan_cache.achieved_gflops`` must be
   nonzero on the live backend (the split actually measured something).

4. **Deep profile** — ``PROFILE`` of a TPC-H query yields >= 1
   ``gv$device_profile`` row joined to the statement by trace_id.

    python scripts/profile_bench.py
    PROFILE_SF=0.01 PROFILE_REPEATS=24 python scripts/profile_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

SF = float(os.environ.get("PROFILE_SF", "0.1"))
# overhead sampling: see planqual_bench — run this bench ALONE; the
# 1-core host needs many interleaved samples for a stable median
REPEATS = int(os.environ.get("PROFILE_REPEATS", "96"))

SLICE_QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


# ---------------------------------------------------------------------------
# 1. host/device-split overhead on the TPC-H slice
# ---------------------------------------------------------------------------


def _gen_slice(n_rows: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _time_queries(sess, repeats: int) -> float:
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in SLICE_QUERIES.values():
            sess.execute(q)
    return time.monotonic() - t0


def bench_overhead(n_rows: int = 20000) -> dict:
    from oceanbase_tpu.server import Database

    root = tempfile.mkdtemp(prefix="profbench_ovh_")
    try:
        db = Database(root)
        s = db.session()
        cols = _gen_slice(n_rows)
        s.catalog.load_numpy("lineitem",
                             {"l_id": np.arange(n_rows), **cols},
                             primary_key=["l_id"])

        def set_profiling(on: str):
            s.execute(f"alter system set enable_profiling = {on}")

        # parity guard: the split must never change results
        set_profiling("true")
        on_rows = {k: s.execute(q).rows()
                   for k, q in SLICE_QUERIES.items()}
        set_profiling("false")
        off_rows = {k: s.execute(q).rows()
                    for k, q in SLICE_QUERIES.items()}
        assert on_rows == off_rows, "profiling changed results"
        _time_queries(s, 3)  # warm the jit caches
        # LONG windows (4 slice iterations per sample), order
        # alternating, MEDIAN per mode: the 1-core bench host's
        # scheduling noise exceeds the toggle's real cost on short
        # windows, so short-window ratios measure the scheduler
        per_sample = 4
        samples = max(REPEATS // per_sample, 8)
        off_times, on_times = [], []
        for i in range(samples):
            order = (("false", "true") if i % 2 == 0
                     else ("true", "false"))
            for mode in order:
                set_profiling(mode)
                dt = _time_queries(s, per_sample)
                (on_times if mode == "true" else off_times).append(dt)
        set_profiling("true")
        db.close()

        def med(xs):
            xs = sorted(xs)
            k = len(xs) // 2
            return xs[k] if len(xs) % 2 else (xs[k - 1] + xs[k]) / 2

        off_m, on_m = med(off_times), med(on_times)
        return {"rows": n_rows,
                "repeats": samples * per_sample,
                "off_s": round(sum(off_times), 4),
                "on_s": round(sum(on_times), 4),
                "mean_overhead_pct": round(
                    (sum(on_times) - sum(off_times))
                    / sum(off_times) * 100, 2),
                "overhead_pct": round(
                    (on_m - off_m) / off_m * 100, 2)}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# 2-4. roofline accuracy + measured rates + PROFILE over full TPC-H
# ---------------------------------------------------------------------------


def bench_roofline() -> dict:
    from oceanbase_tpu.bench.tpch import TPCH_PRIMARY_KEYS, gen_tpch
    from oceanbase_tpu.bench.tpch_queries import QUERIES
    from oceanbase_tpu.server import Database

    t0 = time.monotonic()
    tables, types = gen_tpch(sf=SF)
    gen_s = time.monotonic() - t0
    root = tempfile.mkdtemp(prefix="profbench_roof_")
    try:
        db = Database(root)
        s = db.session()
        for name, arrays in tables.items():
            s.catalog.load_numpy(
                name, arrays,
                types={k: v for k, v in types.items() if k in arrays},
                primary_key=TPCH_PRIMARY_KEYS[name])
        for name in tables:
            s.execute(f"analyze table {name}")
        # collect every execution's ledger row (no sampling gaps)
        s.execute("alter system set plan_monitor_sample_every = 1")
        # ONE full-ladder calibration prices everything that follows
        s.execute("alter system calibrate")
        units = db.cost_units
        per_query = {}
        tqs = []
        t0 = time.monotonic()
        for qnum in sorted(QUERIES):
            s.execute(QUERIES[qnum])  # warm: compile outside the timing
            s.execute(QUERIES[qnum])
            rec = db.plan_monitor.recent(1)[-1]
            per_query[f"q{qnum}"] = {
                "device_ms": round(rec.device_s * 1e3, 3),
                "pred_ms": round(rec.pred_s * 1e3, 3),
                "host_ms": round(rec.host_s * 1e3, 3),
                "time_q": round(rec.time_q, 2),
                "path": rec.path}
            if rec.time_q > 0.0:
                tqs.append(rec.time_q)
        run_s = time.monotonic() - t0
        tqs.sort()
        median_tq = (tqs[len(tqs) // 2] if len(tqs) % 2
                     else (tqs[len(tqs) // 2 - 1]
                           + tqs[len(tqs) // 2]) / 2) if tqs else 0.0

        # 3. measured rates: achieved_gflops nonzero somewhere
        vt = db.virtual_tables.plan_cache()
        gflops = vt["achieved_gflops"]
        max_gflops = float(gflops.max()) if len(gflops) else 0.0

        # 4. PROFILE a TPC-H query; join gv$device_profile by trace_id
        # (whitespace-normalized: the audit LIKE prefix probe below
        # matches within one line)
        s.execute("profile " + " ".join(QUERIES[6].split()))
        tid_rows = s.execute(
            "select trace_id from gv$sql_audit where sql like"
            " 'profile%' order by start_ts desc limit 1").rows()
        trace_id = tid_rows[0][0] if tid_rows else ""
        prof = db.device_profiles.get(trace_id) if trace_id else None
        profile_rows = len(prof.rows) if prof is not None else 0
        db.close()
        return {
            "sf": SF, "gen_s": round(gen_s, 1),
            "run_s": round(run_s, 1),
            "queries": len(per_query),
            "with_time_q": len(tqs),
            "median_time_q": round(median_tq, 2),
            "worst_time_q": round(max(tqs), 2) if tqs else 0.0,
            "calibration": {
                "preset": units.preset,
                "peak_gflops": round(units.peak_flops_s / 1e9, 2),
                "peak_gbps": round(units.peak_bytes_s / 1e9, 2),
                "launch_overhead_us": round(
                    units.launch_overhead_s * 1e6, 2),
                "probe_s": units.probe_s},
            "max_achieved_gflops": round(max_gflops, 4),
            "profile": {"trace_id": trace_id, "rows": profile_rows},
            "per_query": per_query,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main():
    result = {"metric": "profile_bench", "sf": SF}
    from oceanbase_tpu.server.backend_info import resolve_backend

    result["backend"] = resolve_backend()
    roof = bench_roofline()
    result["roofline"] = roof
    ovh = bench_overhead()
    result["overhead"] = ovh

    checks = {
        "overhead_le_2pct": ovh["overhead_pct"] <= 2.0,
        "all_queries_priced": roof["with_time_q"] == roof["queries"],
        "median_time_q_le_4x": 0.0 < roof["median_time_q"] <= 4.0,
        "achieved_gflops_nonzero": roof["max_achieved_gflops"] > 0.0,
        "profile_rows_joined": roof["profile"]["rows"] >= 1
                               and bool(roof["profile"]["trace_id"]),
    }
    result["checks"] = checks
    result["ok"] = all(checks.values())

    # bench artifacts and the metrics plane share one schema
    from oceanbase_tpu.server import metrics as qmetrics

    result["sysstat"] = qmetrics.sysstat_dict()
    line = json.dumps(result)
    print(line)
    with open(os.path.join(REPO, "PROFILE_BENCH.json"), "w") as fh:
        fh.write(line + "\n")
    if not result["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
