#!/usr/bin/env python
"""Trace bench: full-link tracing overhead + fault attribution.

Two halves, one JSON line:

1. **Overhead** — the TPC-H slice (q6 + q1) on an in-process Database,
   timed with tracing OFF (``enable_query_trace=false``) vs ON at
   ``trace_sample_rate=1.0``.  Every statement collects its full span
   tree in the ON runs; the contract is <= 2% elapsed overhead.

2. **Attribution** — a real 3-node cluster runs Q6 through the DTL
   exchange with an injected ``fault.inject`` delay on ``dtl.execute``
   toward one peer.  The query's gv$sql_audit row must join one
   gv$trace tree by trace_id whose SLOWEST span is the injected verb
   (``rpc.dtl.execute``) toward the injected peer.

    python scripts/trace_bench.py                 # both halves
    TRACE_BENCH_SKIP_CLUSTER=1 python scripts/trace_bench.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUERIES = {
    "q6": ("select sum(l_extendedprice * l_discount) from lineitem"
           " where l_shipdate >= 8766 and l_shipdate < 9131"
           " and l_discount >= 5 and l_discount <= 7"
           " and l_quantity < 24"),
    "q1": ("select l_returnflag, l_linestatus, sum(l_quantity),"
           " sum(l_extendedprice), avg(l_discount), count(*)"
           " from lineitem where l_shipdate <= 10000"
           " group by l_returnflag, l_linestatus"
           " order by l_returnflag, l_linestatus"),
}


def _gen(n_rows: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    return {
        "l_quantity": rng.integers(1, 50, n_rows),
        "l_extendedprice": rng.integers(1000, 100000, n_rows),
        "l_discount": rng.integers(0, 10, n_rows),
        "l_shipdate": rng.integers(8766, 10227, n_rows),
        "l_returnflag": rng.integers(0, 3, n_rows),
        "l_linestatus": rng.integers(0, 2, n_rows),
    }


def _load(sess, cols, n_rows):
    sess.execute(
        "create table lineitem (l_id int primary key, l_quantity int,"
        " l_extendedprice int, l_discount int, l_shipdate int,"
        " l_returnflag int, l_linestatus int)")
    for s in range(0, n_rows, 2000):
        e = min(s + 2000, n_rows)
        vals = ", ".join(
            f"({i}, {cols['l_quantity'][i]}, {cols['l_extendedprice'][i]},"
            f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
            f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
            for i in range(s, e))
        sess.execute(f"insert into lineitem values {vals}")


def _time_queries(sess, repeats: int) -> float:
    t0 = time.monotonic()
    for _ in range(repeats):
        for q in QUERIES.values():
            sess.execute(q)
    return time.monotonic() - t0


def bench_overhead(n_rows: int, repeats: int) -> dict:
    from oceanbase_tpu.server import Database

    root = tempfile.mkdtemp(prefix="tracebench_")
    try:
        db = Database(root)
        s = db.session()
        _load(s, _gen(n_rows), n_rows)
        # parity guard: tracing must never change results
        s.execute("alter system set enable_query_trace = true")
        on_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        s.execute("alter system set enable_query_trace = false")
        off_rows = {k: s.execute(q).rows() for k, q in QUERIES.items()}
        assert on_rows == off_rows, "tracing changed results"
        # warm the jit caches so the measurement sees steady state
        _time_queries(s, 3)
        # interleave off/on blocks in ALTERNATING order so warmth and
        # drift hit both sides equally
        s.execute("alter system set trace_sample_rate = 1.0")
        off_s = on_s = 0.0
        blocks = 4
        per_block = max(repeats // blocks, 1)
        for b in range(blocks):
            order = ("false", "true") if b % 2 == 0 else ("true", "false")
            for mode in order:
                s.execute(f"alter system set enable_query_trace = {mode}")
                dt = _time_queries(s, per_block)
                if mode == "true":
                    on_s += dt
                else:
                    off_s += dt
        n_spans = len(db.trace_registry.recent(100000))
        db.close()
        return {
            "rows": n_rows, "repeats": per_block * blocks,
            "off_s": round(off_s, 4), "on_s": round(on_s, 4),
            "overhead_pct": round((on_s - off_s) / off_s * 100.0, 3),
            "spans_in_ring": n_spans,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_attribution(n_rows: int, seed: int = 7) -> dict:
    """3-node cluster, delay injected on dtl.execute toward peer 2: the
    slowest span of Q6's trace must name the verb and the peer."""
    from chaos_bench import boot_cluster, rows_of, wait_converged

    root = tempfile.mkdtemp(prefix="tracebench_cl_")
    procs = {}
    try:
        procs, clients = boot_cluster(root, seed=seed)
        c1 = clients[1]

        def sql(text):
            last = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    return c1.call("sql.execute", sql=text)
                except Exception as e:  # noqa: BLE001 — retried
                    last = e
                    time.sleep(0.3)
            raise TimeoutError(f"query never succeeded: {last}")

        cols = _gen(n_rows)
        sql("create table lineitem (l_id int primary key,"
            " l_quantity int, l_extendedprice int, l_discount int,"
            " l_shipdate int, l_returnflag int, l_linestatus int)")
        for s in range(0, n_rows, 1000):
            e = min(s + 1000, n_rows)
            vals = ", ".join(
                f"({i}, {cols['l_quantity'][i]},"
                f" {cols['l_extendedprice'][i]},"
                f" {cols['l_discount'][i]}, {cols['l_shipdate'][i]},"
                f" {cols['l_returnflag'][i]}, {cols['l_linestatus'][i]})"
                for i in range(s, e))
            sql(f"insert into lineitem values {vals}")
        wait_converged(clients, "lineitem", n_rows)
        sql("alter system set dtl_min_rows = 1")
        baseline = rows_of(sql(QUERIES["q6"]))
        sql(QUERIES["q6"])  # warm the pushdown path

        delay_ms = 400.0
        c1.call("fault.inject", where="send", action="delay",
                verb="dtl.execute", peer=2, delay_ms=delay_ms)
        t0 = time.monotonic()
        faulted = rows_of(sql(QUERIES["q6"]))
        q6_s = time.monotonic() - t0
        c1.call("fault.clear")
        assert faulted == baseline, "fault changed results"

        # join the audit row to its trace by trace_id
        audit = rows_of(sql(
            "select trace_id, sql, start_ts from gv$sql_audit"))
        trace_id = next(
            tid for tid, q, _ts in sorted(audit, key=lambda r: -r[2])
            if tid and q.startswith("select sum(l_extendedprice"))
        spans = rows_of(sql(
            f"select span_name, node, elapsed_s, tags from gv$trace"
            f" where trace_id = '{trace_id}'"
            f" order by elapsed_s desc"))
        # the root/statement/execute chain contains the delay too; the
        # slowest LEAF-side span below them must be the injected verb
        chain = {"statement", "execute", "dtl.exchange", "dtl.slice"}
        slowest = next(s for s in spans if s[0] not in chain)
        tags = json.loads(slowest[3]) if slowest[3] else {}
        ok = (slowest[0] == "rpc.dtl.execute"
              and int(tags.get("peer", -1)) == 2
              and float(slowest[2]) >= delay_ms / 1000.0)
        return {
            "rows": n_rows, "delay_ms": delay_ms,
            "q6_under_fault_s": round(q6_s, 3),
            "trace_id": trace_id, "trace_spans": len(spans),
            "slowest_span": slowest[0],
            "slowest_span_tags": tags,
            "slowest_elapsed_s": round(float(slowest[2]), 3),
            "attribution_ok": bool(ok), "parity": True,
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "100000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "40"))
    out = {"metric": "trace_bench"}
    out["overhead"] = bench_overhead(n_rows, repeats)
    if not os.environ.get("TRACE_BENCH_SKIP_CLUSTER"):
        out["attribution"] = bench_attribution(
            int(os.environ.get("BENCH_CLUSTER_ROWS", "20000")))
        out["ok"] = bool(out["attribution"]["attribution_ok"]
                         and out["overhead"]["overhead_pct"] <= 2.0)
    else:
        out["ok"] = out["overhead"]["overhead_pct"] <= 2.0
    print(json.dumps(out))


if __name__ == "__main__":
    main()
