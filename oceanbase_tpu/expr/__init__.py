"""Expression engine: IR + JAX compiler.

Reference analog: the flat ObExpr array with vectorized eval
(src/sql/engine/expr/ob_expr.h:516, ObExpr::eval_vector
src/sql/engine/expr/ob_expr.cpp:1378).  Where the reference needs
CG-laid-out frames and three eval ABIs, the TPU build compiles an expression
DAG straight into a fused jax computation over whole column vectors — XLA is
the frame allocator and the fusion engine.  Null semantics ride as a second
(value, valid) lane per sub-expression.
"""

from oceanbase_tpu.expr.ir import (
    AggCall,
    Arith,
    Case,
    Cast,
    Cmp,
    ColumnRef,
    Expr,
    FuncCall,
    InList,
    IsNull,
    Like,
    Literal,
    Logic,
    Not,
    lit,
    col,
)
from oceanbase_tpu.expr.compile import eval_expr, eval_predicate

__all__ = [
    "Expr", "ColumnRef", "Literal", "Arith", "Cmp", "Logic", "Not", "InList",
    "Like", "Case", "Cast", "FuncCall", "IsNull", "AggCall",
    "lit", "col", "eval_expr", "eval_predicate",
]
