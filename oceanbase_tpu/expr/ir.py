"""Expression IR.

A small algebraic IR shared by the manual plan builder, the SQL resolver and
the executor.  Nodes are untyped at construction; types are derived at
compile/trace time from the actual relation schema (the reference does this
at resolve time via deduce_type; we fold it into compilation because the
device layout is already fixed by then).

Reference analog: ObRawExpr (src/sql/resolver/expr) on the frontend side and
ObExpr (src/sql/engine/expr/ob_expr.h:516) on the engine side — collapsed
into one IR since JAX tracing removes the need for a separate runtime form.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from oceanbase_tpu.datatypes import SqlType


class Expr:
    """Base class; nodes are immutable and hashable by identity."""

    def children(self) -> Sequence["Expr"]:
        return ()

    # sugar for building trees in tests / manual plans -------------------
    def _wrap(self, other) -> "Expr":
        return other if isinstance(other, Expr) else Literal(other)

    def __add__(self, o):
        return Arith("+", self, self._wrap(o))

    def __radd__(self, o):
        return Arith("+", self._wrap(o), self)

    def __sub__(self, o):
        return Arith("-", self, self._wrap(o))

    def __rsub__(self, o):
        return Arith("-", self._wrap(o), self)

    def __mul__(self, o):
        return Arith("*", self, self._wrap(o))

    def __rmul__(self, o):
        return Arith("*", self._wrap(o), self)

    def __truediv__(self, o):
        return Arith("/", self, self._wrap(o))

    def __mod__(self, o):
        return Arith("%", self, self._wrap(o))

    def __lt__(self, o):
        return Cmp("<", self, self._wrap(o))

    def __le__(self, o):
        return Cmp("<=", self, self._wrap(o))

    def __gt__(self, o):
        return Cmp(">", self, self._wrap(o))

    def __ge__(self, o):
        return Cmp(">=", self, self._wrap(o))

    def eq(self, o):
        return Cmp("=", self, self._wrap(o))

    def ne(self, o):
        return Cmp("!=", self, self._wrap(o))

    def and_(self, o):
        return Logic("and", [self, self._wrap(o)])

    def or_(self, o):
        return Logic("or", [self, self._wrap(o)])

    def isin(self, values):
        return InList(self, list(values))

    def like(self, pattern: str):
        return Like(self, pattern)

    def between(self, lo, hi):
        return Logic("and", [Cmp(">=", self, self._wrap(lo)),
                             Cmp("<=", self, self._wrap(hi))])

    def is_null(self):
        return IsNull(self)

    def is_not_null(self):
        return IsNull(self, negated=True)


@dataclass(eq=False)
class ColumnRef(Expr):
    name: str

    def __repr__(self):
        return f"col({self.name!r})"


@dataclass(eq=False)
class Literal(Expr):
    value: Any
    # explicit type for decimals ('0.06' -> DECIMAL scale 2), dates, etc.
    dtype: Optional[SqlType] = None

    def __repr__(self):
        return f"lit({self.value!r})"


@dataclass(eq=False)
class Arith(Expr):
    op: str  # + - * / %
    left: Expr
    right: Expr

    def children(self):
        return (self.left, self.right)


@dataclass(eq=False)
class Cmp(Expr):
    op: str  # = != < <= > >=
    left: Expr
    right: Expr

    def children(self):
        return (self.left, self.right)


@dataclass(eq=False)
class Logic(Expr):
    op: str  # and | or
    args: list

    def children(self):
        return tuple(self.args)


@dataclass(eq=False)
class Not(Expr):
    arg: Expr

    def children(self):
        return (self.arg,)


@dataclass(eq=False)
class InList(Expr):
    arg: Expr
    values: list
    negated: bool = False

    def children(self):
        return (self.arg,)


@dataclass(eq=False)
class Like(Expr):
    arg: Expr
    pattern: str
    negated: bool = False

    def children(self):
        return (self.arg,)


@dataclass(eq=False)
class IsNull(Expr):
    arg: Expr
    negated: bool = False

    def children(self):
        return (self.arg,)


@dataclass(eq=False)
class Case(Expr):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE e END."""

    whens: list  # list[(Expr cond, Expr value)]
    else_: Optional[Expr] = None

    def children(self):
        cs = []
        for c, v in self.whens:
            cs += [c, v]
        if self.else_ is not None:
            cs.append(self.else_)
        return tuple(cs)


@dataclass(eq=False)
class Cast(Expr):
    arg: Expr
    dtype: SqlType

    def children(self):
        return (self.arg,)


@dataclass(eq=False)
class FuncCall(Expr):
    """Scalar functions: extract_year/extract_month/extract_day, substring,
    abs, coalesce, upper/lower, concat (dict-level for strings)."""

    name: str
    args: list

    def children(self):
        return tuple(self.args)


@dataclass(eq=False)
class WindowCall(Expr):
    """fn() OVER (PARTITION BY ... ORDER BY ... [frame]).

    Evaluated by the Window operator (≙ src/sql/engine/window_function).
    Supported fns: row_number, rank, dense_rank, ntile, lead, lag,
    first_value, last_value, sum, count, avg, min, max.  Without an
    explicit frame, ordered window aggregates use the MySQL default:
    RANGE UNBOUNDED PRECEDING .. CURRENT ROW (peers share values).

    ``frame``: ("rows", start, end) — offsets relative to the current
    row (negative = PRECEDING, None = UNBOUNDED on that side).
    ``extra``: positional extras — lead/lag (offset, default expr),
    ntile (bucket count)."""

    fn: str
    arg: "Expr | None" = None
    partition_by: list = None
    order_by: list = None       # list[(Expr, ascending)]
    frame: tuple | None = None  # ("rows", start|None, end|None)
    extra: list = None

    def children(self):
        cs = [self.arg] if self.arg is not None else []
        cs += list(self.partition_by or [])
        cs += [e for e, _ in (self.order_by or [])]
        cs += [e for e in (self.extra or []) if isinstance(e, Expr)]
        return tuple(cs)


@dataclass(eq=False)
class AggCall(Expr):
    """Aggregate reference inside a group-by output (sum/count/min/max/avg).

    Evaluated by the aggregate operator, not by eval_expr
    (≙ src/share/aggregate IAggregate, agg_ctx.h:552)."""

    fn: str  # sum | count | min | max | avg | count_star | count_distinct
    arg: Optional[Expr] = None
    distinct: bool = False

    def children(self):
        return (self.arg,) if self.arg is not None else ()


def col(name: str) -> ColumnRef:
    return ColumnRef(name)


def lit(value, dtype: SqlType | None = None) -> Literal:
    return Literal(value, dtype)


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)
