"""Compile/evaluate expression IR over device relations.

``eval_expr(expr, rel)`` runs under jax tracing and returns a Column; the
whole expression DAG fuses into the enclosing operator's XLA computation.
This replaces the reference's three eval ABIs + frame layout
(src/sql/engine/expr/ob_expr.h:953-963, :1030-1075): XLA does buffer
placement, common-subexpression reuse and elementwise fusion that the
reference implements by hand (eval flags, frames, SIMD .ipp kernels).

Null semantics: every sub-expression yields (data, valid).  Three-valued
logic is implemented exactly for AND/OR/NOT (known-true/known-false lanes),
matching MySQL semantics the reference encodes per-expr.

String semantics: string columns are order-preserving dictionary codes; all
string predicates/functions lower to host work over the dictionary plus a
device gather/compare (see vector/column.py StringDict).
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from oceanbase_tpu.datatypes import (
    SqlType,
    TypeKind,
    add_result,
    common_numeric,
    date_to_days,
    div_result,
    mul_result,
)
from oceanbase_tpu.expr import ir
from oceanbase_tpu.vector.column import Column, Relation, StringDict

_POW10 = [10**i for i in range(38)]


def _all_valid(n):
    return jnp.ones(n, dtype=jnp.bool_)


# ---------------------------------------------------------------------------
# literal -> (host scalar, SqlType)
# ---------------------------------------------------------------------------

def literal_value(e: ir.Literal):
    v, t = e.value, e.dtype
    if t is None:
        if v is None:
            t = SqlType.null()
        elif isinstance(v, bool):
            t = SqlType.bool_()
        elif isinstance(v, int):
            t = SqlType.int_()
        elif isinstance(v, float):
            t = SqlType.double()
        elif isinstance(v, str):
            t = SqlType.string()
        else:
            raise TypeError(f"unsupported literal {v!r}")
    if t.kind == TypeKind.DATE and isinstance(v, str):
        v = date_to_days(v)
    if t.kind == TypeKind.DECIMAL and isinstance(v, str):
        # exact decimal parse: '0.06' with scale from text; trailing zeros
        # stripped so '0.0001000000' costs scale 4, not 10 (keeps products
        # inside int64 range)
        neg = v.startswith("-")
        body = v.lstrip("+-")
        if "." in body:
            ip, fp = body.split(".")
        else:
            ip, fp = body, ""
        fp = fp.rstrip("0")
        scale = len(fp)
        iv = int(ip or "0") * _POW10[scale] + int(fp or "0")
        v = -iv if neg else iv
        t = SqlType.decimal(t.precision or 15, scale)
    return v, t


def _lit_column(e: ir.Literal, n: int) -> Column:
    v, t = literal_value(e)
    if v is None:
        data = jnp.zeros(n, dtype=jnp.int64)
        return Column(data=data, valid=jnp.zeros(n, dtype=jnp.bool_), dtype=t)
    if t.kind == TypeKind.STRING:
        # a bare string literal column: single-value dictionary
        sd = StringDict(np.array([v]))
        return Column(
            data=jnp.zeros(n, dtype=jnp.int32), valid=None, dtype=t, sdict=sd
        )
    data = jnp.full(n, v, dtype=jnp.dtype(t.np_dtype))
    return Column(data=data, valid=None, dtype=t)


# ---------------------------------------------------------------------------
# numeric alignment helpers
# ---------------------------------------------------------------------------

def _to_float(c: Column, kind=TypeKind.DOUBLE) -> Column:
    dt = jnp.float64 if kind == TypeKind.DOUBLE else jnp.float32
    if c.dtype.kind == TypeKind.DECIMAL:
        data = c.data.astype(dt) / _POW10[c.dtype.scale]
    else:
        data = c.data.astype(dt)
    return Column(data=data, valid=c.valid, dtype=SqlType(kind))


def _align_pair(a: Column, b: Column) -> tuple:
    """Align two numeric/date columns to a common physical representation.

    Returns (a_data, b_data, common SqlType)."""
    ta, tb = a.dtype, b.dtype
    # date/datetime compare & arith against ints happens raw
    if ta.kind in (TypeKind.DATE, TypeKind.DATETIME) or tb.kind in (
        TypeKind.DATE,
        TypeKind.DATETIME,
    ):
        ct = ta if ta.kind in (TypeKind.DATE, TypeKind.DATETIME) else tb
        return a.data.astype(jnp.int64), b.data.astype(jnp.int64), ct
    if ta.kind == TypeKind.BOOL and tb.kind == TypeKind.BOOL:
        return a.data, b.data, ta
    ct = common_numeric(ta, tb)
    if ct.kind in (TypeKind.FLOAT, TypeKind.DOUBLE):
        return _to_float(a, ct.kind).data, _to_float(b, ct.kind).data, ct
    if ct.kind == TypeKind.DECIMAL:
        s = max(ta.scale, tb.scale)
        da = a.data.astype(jnp.int64) * _POW10[s - ta.scale]
        db = b.data.astype(jnp.int64) * _POW10[s - tb.scale]
        return da, db, SqlType(TypeKind.DECIMAL, max(ta.precision, tb.precision), s)
    return a.data.astype(jnp.int64), b.data.astype(jnp.int64), ct


def _merge_valid(a: Column, b: Column):
    if a.valid is None:
        return b.valid
    if b.valid is None:
        return a.valid
    return a.valid & b.valid


# ---------------------------------------------------------------------------
# string predicate lowering
# ---------------------------------------------------------------------------

def _string_cmp(op: str, c: Column, s: str, n: int) -> Column:
    """Compare a dict-encoded column against a string literal on codes."""
    sd = c.sdict
    assert sd is not None, "string compare on non-dict column"
    if op in ("=", "!="):
        code = sd.code_of(s)
        if code < 0:
            val = jnp.zeros(n, dtype=jnp.bool_) if op == "=" else jnp.ones(n, jnp.bool_)
        else:
            val = (c.data == code) if op == "=" else (c.data != code)
        return Column(data=val, valid=c.valid, dtype=SqlType.bool_())
    # order-preserving dict: translate to a code boundary
    lb = sd.lower_bound(s)
    exists = sd.code_of(s) >= 0
    if op == "<":
        val = c.data < lb
    elif op == "<=":
        val = c.data < (lb + 1 if exists else lb)
    elif op == ">":
        val = c.data >= (lb + 1 if exists else lb)
    elif op == ">=":
        val = c.data >= lb
    else:  # pragma: no cover
        raise ValueError(op)
    return Column(data=val, valid=c.valid, dtype=SqlType.bool_())


US_PER_DAY = 86_400_000_000


def _temporal_literal(s: str, kind: TypeKind) -> int:
    """'1994-01-01[ hh:mm:ss]' -> days (DATE) or microseconds (DATETIME)."""
    date_part = s.split(" ")[0]
    days = date_to_days(date_part)
    if kind == TypeKind.DATE:
        return days
    us = days * US_PER_DAY
    if " " in s:
        hms = s.split(" ", 1)[1].split(":")
        parts = [float(x) for x in hms] + [0.0] * (3 - len(hms))
        us += int((parts[0] * 3600 + parts[1] * 60 + parts[2]) * 1_000_000)
    return us


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# ---------------------------------------------------------------------------
# 3-valued logic lanes
# ---------------------------------------------------------------------------

def _tf(c: Column):
    v = c.valid_or_true()
    d = c.data
    if d.dtype != jnp.bool_:
        # SQL truthiness of a numeric predicate (MATCH score, 0/1 ints)
        d = d != 0
    return d & v, (~d) & v


# ---------------------------------------------------------------------------
# date decomposition (Hinnant civil-from-days, branch-free for XLA)
# ---------------------------------------------------------------------------

def civil_from_days(z):
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


# ---------------------------------------------------------------------------
# main evaluator
# ---------------------------------------------------------------------------

def eval_expr(e: ir.Expr, rel: Relation) -> Column:
    n = rel.capacity

    if isinstance(e, ir.ColumnRef):
        return rel.columns[e.name]

    if isinstance(e, ir.Literal):
        return _lit_column(e, n)

    if isinstance(e, ir.Cmp):
        return _eval_cmp(e, rel, n)

    if isinstance(e, ir.Arith):
        return _eval_arith(e, rel, n)

    if isinstance(e, ir.Logic):
        cols = [eval_expr(a, rel) for a in e.args]
        t, f = _tf(cols[0])
        for c in cols[1:]:
            t2, f2 = _tf(c)
            if e.op == "and":
                t, f = t & t2, f | f2
            else:
                t, f = t | t2, f & f2
        return Column(data=t, valid=t | f, dtype=SqlType.bool_())

    if isinstance(e, ir.Not):
        c = eval_expr(e.arg, rel)
        return Column(data=~c.data, valid=c.valid, dtype=SqlType.bool_())

    if isinstance(e, ir.IsNull):
        c = eval_expr(e.arg, rel)
        isnull = (
            jnp.zeros(n, dtype=jnp.bool_) if c.valid is None else ~c.valid
        )
        return Column(
            data=(~isnull if e.negated else isnull), valid=None,
            dtype=SqlType.bool_(),
        )

    if isinstance(e, ir.InList):
        c = eval_expr(e.arg, rel)
        if c.dtype.is_string and c.sdict is not None:
            codes = [c.sdict.code_of(_as_str(v)) for v in e.values]
            codes = [cd for cd in codes if cd >= 0]
            if not codes:
                val = jnp.zeros(n, dtype=jnp.bool_)
            else:
                val = jnp.isin(c.data, jnp.asarray(codes, dtype=c.data.dtype))
        else:
            vals = []
            for v in e.values:
                lv, lt = literal_value(v if isinstance(v, ir.Literal) else ir.Literal(v))
                if c.dtype.kind == TypeKind.DECIMAL and lt.kind in (
                    TypeKind.DECIMAL, TypeKind.INT,
                ):
                    ls = lt.scale if lt.kind == TypeKind.DECIMAL else 0
                    if ls <= c.dtype.scale:
                        lv = lv * _POW10[c.dtype.scale - ls]
                    else:
                        # literal more precise than the column: exact match
                        # only possible when the extra digits are zero
                        q, r = divmod(lv, _POW10[ls - c.dtype.scale])
                        if r != 0:
                            continue  # can never equal a column value
                        lv = q
                elif c.dtype.kind in (TypeKind.DATE, TypeKind.DATETIME) and \
                        isinstance(lv, str):
                    lv = _temporal_literal(lv, c.dtype.kind)
                vals.append(lv)
            if not vals:
                val = jnp.zeros(n, dtype=jnp.bool_)
            else:
                val = jnp.isin(c.data, jnp.asarray(vals))
        if e.negated:
            val = ~val
        return Column(data=val, valid=c.valid, dtype=SqlType.bool_())

    if isinstance(e, ir.Like):
        c = eval_expr(e.arg, rel)
        assert c.sdict is not None, "LIKE requires a dict-encoded column"
        rx = re.compile(like_to_regex(e.pattern))
        lut = jnp.asarray(c.sdict.lut(lambda s: rx.match(s) is not None))
        val = lut[jnp.clip(c.data, 0, c.sdict.size - 1)]
        if e.negated:
            val = ~val
        return Column(data=val, valid=c.valid, dtype=SqlType.bool_())

    if isinstance(e, ir.Case):
        return _eval_case(e, rel, n)

    if isinstance(e, ir.Cast):
        c = eval_expr(e.arg, rel)
        return cast_column(c, e.dtype)

    if isinstance(e, ir.FuncCall):
        return _eval_func(e, rel, n)

    raise NotImplementedError(f"eval of {type(e).__name__}")


def _as_str(v):
    if isinstance(v, ir.Literal):
        return v.value
    return v


def _eval_cmp(e: ir.Cmp, rel: Relation, n: int) -> Column:
    # string-vs-literal fast path on dictionary codes
    lc_is_str_lit = isinstance(e.left, ir.Literal) and isinstance(e.left.value, str)
    rc_is_str_lit = isinstance(e.right, ir.Literal) and isinstance(e.right.value, str)
    if rc_is_str_lit:
        lcol = eval_expr(e.left, rel)
        if lcol.dtype.is_string:
            return _string_cmp(e.op, lcol, e.right.value, n)
        if lcol.dtype.kind in (TypeKind.DATE, TypeKind.DATETIME):
            rv = _temporal_literal(e.right.value, lcol.dtype.kind)
            return _cmp_data(e.op, lcol.data.astype(jnp.int64),
                             jnp.full(n, rv, jnp.int64), lcol.valid)
    if lc_is_str_lit:
        flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
        return _eval_cmp(ir.Cmp(flipped[e.op], e.right, e.left), rel, n)

    a = eval_expr(e.left, rel)
    b = eval_expr(e.right, rel)
    if a.dtype.is_string and b.dtype.is_string:
        return _string_col_cmp(e.op, a, b)
    da, db, _ = _align_pair(a, b)
    return _cmp_data(e.op, da, db, _merge_valid(a, b))


def _cmp_data(op, da, db, valid) -> Column:
    fns = {
        "=": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
        "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal,
    }
    return Column(data=fns[op](da, db), valid=valid, dtype=SqlType.bool_())


def _string_col_cmp(op, a: Column, b: Column) -> Column:
    if a.sdict is b.sdict:
        return _cmp_data(op, a.data, b.data, _merge_valid(a, b))
    # translate a's codes into b's dictionary space (host, O(|dict|))
    assert a.sdict is not None and b.sdict is not None
    pos = np.searchsorted(b.sdict.values, a.sdict.values).astype(np.int64)
    exact = np.zeros(a.sdict.size, dtype=bool)
    inb = pos < b.sdict.size
    exact[inb] = b.sdict.values[pos[inb]] == a.sdict.values[inb]
    posm = jnp.asarray(pos)[jnp.clip(a.data, 0, a.sdict.size - 1)]
    exm = jnp.asarray(exact)[jnp.clip(a.data, 0, a.sdict.size - 1)]
    valid = _merge_valid(a, b)
    if op == "=":
        return Column(data=exm & (posm == b.data), valid=valid, dtype=SqlType.bool_())
    if op == "!=":
        return Column(data=~(exm & (posm == b.data)), valid=valid, dtype=SqlType.bool_())
    # order comparisons: a < b  <=>  rank(a in b-space) < code_b, with ties
    # broken by exact membership
    raise NotImplementedError("ordered compare across dictionaries")


def _eval_arith(e: ir.Arith, rel: Relation, n: int) -> Column:
    a = eval_expr(e.left, rel)
    b = eval_expr(e.right, rel)
    valid = _merge_valid(a, b)
    ta, tb = a.dtype, b.dtype

    # temporal arithmetic: DATE ± days, DATETIME ± days, DATE - DATE;
    # "INT + DATE" commutes, "INT - DATE" is a type error
    temporal = (TypeKind.DATE, TypeKind.DATETIME)
    if tb.kind in temporal and ta.kind == TypeKind.INT:
        if e.op == "+":
            a, b, ta, tb = b, a, tb, ta
        else:
            raise TypeError(f"cannot apply {e.op!r} to INT and {tb.kind.name}")
    if ta.kind in temporal and tb.kind == TypeKind.INT and e.op in "+-":
        d = a.data.astype(jnp.int64)
        o = b.data.astype(jnp.int64)
        if ta.kind == TypeKind.DATETIME:
            o = o * US_PER_DAY
        data = d + o if e.op == "+" else d - o
        if ta.kind == TypeKind.DATE:
            data = data.astype(jnp.int32)
        return Column(data=data, valid=valid, dtype=ta)
    if ta.kind in temporal and tb.kind in temporal and e.op == "-":
        da = a.data.astype(jnp.int64)
        db = b.data.astype(jnp.int64)
        if ta.kind == TypeKind.DATETIME or tb.kind == TypeKind.DATETIME:
            if ta.kind == TypeKind.DATE:
                da = da * US_PER_DAY
            if tb.kind == TypeKind.DATE:
                db = db * US_PER_DAY
        data = da - db
        return Column(data=data, valid=valid, dtype=SqlType.int_())
    if ta.kind in temporal or tb.kind in temporal:
        raise TypeError(
            f"unsupported arithmetic {ta.kind.name} {e.op} {tb.kind.name}"
        )

    if e.op == "/":
        ct = div_result(ta, tb)
        fa, fb = _to_float(a, ct.kind), _to_float(b, ct.kind)
        zero = fb.data == 0
        data = jnp.where(zero, jnp.nan, fa.data / jnp.where(zero, 1.0, fb.data))
        v = valid if valid is not None else _all_valid(n)
        return Column(data=data, valid=v & ~zero, dtype=ct)

    if e.op == "*":
        ct = mul_result(ta, tb)
        if ct.kind == TypeKind.DECIMAL and ct.scale > 10:
            # combined fixed-point scale would overflow int64 on large
            # aggregates: fall back to double (MySQL keeps DECIMAL(65,30)
            # via wide ints; exact wide-decimal kernels are a later round)
            fa, fb = _to_float(a, TypeKind.DOUBLE), _to_float(b, TypeKind.DOUBLE)
            return Column(data=fa.data * fb.data, valid=valid,
                          dtype=SqlType.double())
        if ct.kind == TypeKind.DECIMAL:
            data = a.data.astype(jnp.int64) * b.data.astype(jnp.int64)
            return Column(data=data, valid=valid, dtype=ct)
        da, db, c2 = _align_pair(a, b)
        return Column(data=da * db, valid=valid, dtype=c2)

    da, db, ct = _align_pair(a, b)
    if e.op == "+":
        data = da + db
    elif e.op == "-":
        data = da - db
    elif e.op == "%":
        # MySQL MOD: truncated division — result carries the dividend's sign
        zero = db == 0
        safe = jnp.where(zero, 1, db)
        data = jnp.sign(da) * jnp.remainder(jnp.abs(da), jnp.abs(safe))
        data = jnp.where(zero, 0, data)
        v = valid if valid is not None else _all_valid(n)
        return Column(data=data, valid=v & ~zero, dtype=ct)
    else:  # pragma: no cover
        raise ValueError(e.op)
    return Column(data=data, valid=valid, dtype=add_result(ta, tb))


def _unify_branches(branches: list) -> tuple[list, SqlType, "StringDict | None"]:
    """Unify CASE/COALESCE branch columns to one physical representation.

    Numerics go through common_numeric; strings are re-encoded into a
    merged (union) order-preserving dictionary; date/bool/etc require
    matching kinds.  NULLTYPE branches adopt the result type.
    """
    kinds = {b.dtype.kind for b in branches if b.dtype.kind != TypeKind.NULLTYPE}
    if not kinds:
        return branches, SqlType.null(), None
    if kinds <= {TypeKind.INT, TypeKind.DECIMAL, TypeKind.FLOAT, TypeKind.DOUBLE,
                 TypeKind.BOOL}:
        if kinds == {TypeKind.BOOL}:
            rt = SqlType.bool_()
        else:
            rt = SqlType.int_()  # BOOL branches widen to INT when mixed
            for b in branches:
                if b.dtype.kind not in (TypeKind.NULLTYPE, TypeKind.BOOL):
                    rt = common_numeric(rt, b.dtype)
        return [cast_column(b, rt) for b in branches], rt, None
    if kinds == {TypeKind.STRING}:
        dicts = [b.sdict for b in branches if b.sdict is not None]
        if all(d is dicts[0] for d in dicts):
            merged = dicts[0]
            out = branches
        else:
            allvals = np.unique(np.concatenate([d.values for d in dicts]))
            merged = StringDict(allvals)
            out = []
            for b in branches:
                if b.sdict is None:
                    out.append(b)
                    continue
                remap = np.searchsorted(allvals, b.sdict.values).astype(np.int32)
                codes = jnp.asarray(remap)[jnp.clip(b.data, 0, b.sdict.size - 1)]
                out.append(Column(codes, b.valid, SqlType.string(), merged))
        return out, SqlType.string(), merged
    if len(kinds) == 1:
        rt = next(b.dtype for b in branches if b.dtype.kind != TypeKind.NULLTYPE)
        return branches, rt, None
    raise TypeError(f"CASE branches mix incompatible types: {kinds}")


def _eval_case(e: ir.Case, rel: Relation, n: int) -> Column:
    conds = []
    vals = []
    for c, v in e.whens:
        conds.append(eval_expr(c, rel))
        vals.append(eval_expr(v, rel))
    else_c = eval_expr(e.else_, rel) if e.else_ is not None else None

    branches = vals + ([else_c] if else_c is not None else [])
    branches, rt, sdict = _unify_branches(branches)

    if else_c is not None:
        data = branches[-1].data
        valid = branches[-1].valid_or_true()
    else:
        data = jnp.zeros(n, dtype=branches[0].data.dtype)
        valid = jnp.zeros(n, dtype=jnp.bool_)
    taken = jnp.zeros(n, dtype=jnp.bool_)
    for cond, val in zip(conds, branches[: len(vals)]):
        t, _ = _tf(cond)
        sel = t & ~taken
        data = jnp.where(sel, val.data, data)
        valid = jnp.where(sel, val.valid_or_true(), valid)
        taken = taken | t
    return Column(data=data, valid=valid, dtype=rt, sdict=sdict)


def cast_column(c: Column, t: SqlType) -> Column:
    if c.dtype.kind == t.kind and c.dtype.scale == t.scale:
        return c
    if t.kind in (TypeKind.FLOAT, TypeKind.DOUBLE):
        return _to_float(c, t.kind)
    if t.kind == TypeKind.DECIMAL:
        if c.dtype.kind == TypeKind.DECIMAL:
            if t.scale >= c.dtype.scale:
                data = c.data * _POW10[t.scale - c.dtype.scale]
            else:
                data = _div_round(c.data, _POW10[c.dtype.scale - t.scale])
            return Column(data=data, valid=c.valid, dtype=t)
        if c.dtype.kind == TypeKind.INT or c.dtype.kind == TypeKind.BOOL:
            data = c.data.astype(jnp.int64) * _POW10[t.scale]
            return Column(data=data, valid=c.valid, dtype=t)
        if c.dtype.kind in (TypeKind.FLOAT, TypeKind.DOUBLE):
            data = jnp.round(c.data * _POW10[t.scale]).astype(jnp.int64)
            return Column(data=data, valid=c.valid, dtype=t)
    if t.kind == TypeKind.INT:
        if c.dtype.kind == TypeKind.DECIMAL:
            data = _div_round(c.data, _POW10[c.dtype.scale])
        else:
            data = c.data.astype(jnp.int64)
        return Column(data=data, valid=c.valid, dtype=t)
    if t.kind == TypeKind.NULLTYPE or c.dtype.kind == TypeKind.NULLTYPE:
        return Column(data=c.data, valid=c.valid, dtype=t if t.kind != TypeKind.NULLTYPE else c.dtype)
    if t.kind == TypeKind.BOOL:
        return Column(data=c.data != 0, valid=c.valid, dtype=t)
    raise NotImplementedError(f"cast {c.dtype} -> {t}")


def _div_round(x, d: int):
    """Round-half-away-from-zero integer division (MySQL decimal rounding)."""
    half = d // 2
    return jnp.where(x >= 0, (x + half) // d, -((-x + half) // d))


def days_from_civil(y, m, d):
    """Inverse of civil_from_days (Hinnant, floor-division form)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y, m):
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    base = lengths[jnp.clip(m - 1, 0, 11)]
    return jnp.where((m == 2) & leap, 29, base)


# ---------------------------------------------------------------------------
# UDF registry (≙ PL/SQL + LLVM JIT, src/pl + src/objit): user functions
# written against jax.numpy trace straight into the plan's XLA program —
# tracing IS the JIT.
# ---------------------------------------------------------------------------

_UDFS: dict[str, tuple] = {}


def register_udf(name: str, fn, result_type: "SqlType | None" = None):
    """Register fn(*jnp_arrays) -> jnp_array as a SQL scalar function.

    The function must be traceable (jax.numpy ops, no data-dependent
    python control flow); NULL handling: result is NULL where any input
    is NULL (strict functions)."""
    _UDFS[name.lower()] = (fn, result_type)


def unregister_udf(name: str):
    _UDFS.pop(name.lower(), None)


def parse_vector_text(s: str) -> np.ndarray:
    """'[0.1, 0.2, ...]' -> float32 ndarray (the vector literal format)."""
    body = s.strip()
    if body.startswith("[") and body.endswith("]"):
        body = body[1:-1]
    if not body.strip():
        return np.zeros(0, dtype=np.float32)
    return np.asarray([float(x) for x in body.split(",")],
                      dtype=np.float32)


def _eval_func(e: ir.FuncCall, rel: Relation, n: int) -> Column:
    name = e.name.lower()
    if name in _UDFS:
        fn, rt = _UDFS[name]
        cols = [eval_expr(a, rel) for a in e.args]
        data = fn(*[c.data for c in cols])
        valid = None
        for c in cols:
            valid = c.valid if valid is None else (
                valid if c.valid is None else (valid & c.valid))
        if rt is None:
            if jnp.issubdtype(data.dtype, jnp.floating):
                rt = SqlType.double()
            elif data.dtype == jnp.bool_:
                rt = SqlType.bool_()
            else:
                rt = SqlType.int_()
        return Column(jnp.asarray(data), valid, rt)
    if name == "match_against":
        # MATCH(col) AGAINST('terms'): token containment evaluated in
        # the DICTIONARY domain — one host pass over distinct values
        # builds the score LUT, then a device gather maps codes to
        # scores.  ≙ the FTS inverted index consulted per term
        # (src/storage/fts): the dictionary IS the term-space here.
        import re as _re

        c = eval_expr(e.args[0], rel)
        terms = e.args[1].value if isinstance(e.args[1], ir.Literal) \
            else ""
        qtoks = [t for t in _re.split(r"\W+", str(terms).lower()) if t]
        if c.sdict is None or not qtoks:
            return Column(jnp.zeros(n, jnp.float64), c.valid,
                          SqlType.double())

        def score(text):
            toks = set(_re.split(r"\W+", str(text).lower()))
            return float(sum(1.0 for t in qtoks if t in toks))

        lut = jnp.asarray(c.sdict.lut(score).astype(np.float64))
        data = jnp.take(lut, jnp.clip(c.data, 0, c.sdict.size - 1))
        if c.valid is not None:
            data = jnp.where(c.valid, data, 0.0)
        return Column(data, c.valid, SqlType.double())
    if name in ("l2_distance", "inner_product", "negative_inner_product",
                "cosine_distance"):
        # vector distance over a VECTOR column and a '[...]' literal /
        # second vector column (≙ the vector distance exprs feeding
        # src/share/vector_index); [n,d] x [d] -> [n] double
        def _vec_arg(x):
            if isinstance(x, ir.Literal) and isinstance(x.value, str):
                v = parse_vector_text(x.value)
                return Column(jnp.asarray(v), None,
                              SqlType.vector(len(v)))
            return eval_expr(x, rel)

        a = _vec_arg(e.args[0])
        b = _vec_arg(e.args[1])
        va, vb = a.data, b.data
        if va.ndim == 1 and vb.ndim == 2:
            a, b = b, a
            va, vb = vb, va
        va = va.astype(jnp.float32)
        vb = vb.astype(jnp.float32)
        if name == "l2_distance":
            diff = va - (vb if vb.ndim == 2 else vb[None, :])
            out = jnp.sqrt(jnp.sum(diff * diff, axis=-1)
                           .astype(jnp.float64))
        elif name == "cosine_distance":
            num = jnp.sum(va * (vb if vb.ndim == 2 else vb[None, :]),
                          axis=-1)
            na = jnp.sqrt(jnp.sum(va * va, axis=-1))
            nb = jnp.sqrt(jnp.sum(vb * vb, axis=-1))
            out = (1.0 - num / jnp.maximum(na * nb, 1e-12)) \
                .astype(jnp.float64)
        else:
            out = jnp.sum(va * (vb if vb.ndim == 2 else vb[None, :]),
                          axis=-1).astype(jnp.float64)
            if name == "negative_inner_product":
                out = -out
        return Column(out, _merge_valid(a, b), SqlType.double())
    if name in ("extract_year", "year", "extract_month", "month",
                "extract_day", "day", "quarter", "dayofyear", "dayofweek",
                "weekday"):
        c = eval_expr(e.args[0], rel)
        y, m, d = civil_from_days(c.data)
        if name in ("extract_year", "year"):
            out = y
        elif name in ("extract_month", "month"):
            out = m
        elif name in ("extract_day", "day"):
            out = d
        elif name == "quarter":
            out = (m + 2) // 3
        elif name == "dayofyear":
            out = c.data.astype(jnp.int64) - days_from_civil(
                y, jnp.ones_like(m), jnp.ones_like(d)) + 1
        elif name == "dayofweek":   # MySQL: 1 = Sunday
            out = jnp.remainder(c.data.astype(jnp.int64) + 4, 7) + 1
        else:                       # weekday: 0 = Monday
            out = jnp.remainder(c.data.astype(jnp.int64) + 3, 7)
        return Column(data=out, valid=c.valid, dtype=SqlType.int_())
    if name == "add_months":
        c = eval_expr(e.args[0], rel)
        k = eval_expr(e.args[1], rel)
        y, m, d = civil_from_days(c.data)
        total = y * 12 + (m - 1) + k.data.astype(jnp.int64)
        y2 = jnp.floor_divide(total, 12)
        m2 = total - y2 * 12 + 1
        d2 = jnp.minimum(d, _days_in_month(y2, m2))
        out = days_from_civil(y2, m2, d2).astype(jnp.int32)
        return Column(data=out, valid=_merge_valid(c, k), dtype=c.dtype)
    if name == "datediff":
        a = eval_expr(e.args[0], rel)
        b = eval_expr(e.args[1], rel)
        data = a.data.astype(jnp.int64) - b.data.astype(jnp.int64)
        return Column(data=data, valid=_merge_valid(a, b),
                      dtype=SqlType.int_())
    if name == "abs":
        c = eval_expr(e.args[0], rel)
        return c.with_data(jnp.abs(c.data))
    if name == "sign":
        c = eval_expr(e.args[0], rel)
        return Column(jnp.sign(c.data).astype(jnp.int64), c.valid,
                      SqlType.int_())
    if name in ("ceil", "ceiling", "floor"):
        c = eval_expr(e.args[0], rel)
        if c.dtype.kind == TypeKind.DECIMAL:
            s = _POW10[c.dtype.scale]
            if name == "floor":
                data = jnp.floor_divide(c.data, s)
            else:
                data = -jnp.floor_divide(-c.data, s)
            return Column(data, c.valid, SqlType.int_())
        if c.dtype.kind == TypeKind.INT:
            return c
        f = jnp.floor if name == "floor" else jnp.ceil
        return Column(f(c.data).astype(jnp.int64), c.valid, SqlType.int_())
    if name in ("round", "truncate"):
        c = eval_expr(e.args[0], rel)
        nd = 0
        if len(e.args) > 1:
            nd = e.args[1].value if isinstance(e.args[1], ir.Literal) else 0
        if c.dtype.kind == TypeKind.DECIMAL:
            target = SqlType(TypeKind.DECIMAL, c.dtype.precision,
                             max(nd, 0))
            if name == "round":
                return cast_column(c, target)
            if nd >= c.dtype.scale:
                return c
            d = _POW10[c.dtype.scale - max(nd, 0)]
            data = jnp.where(c.data >= 0, c.data // d, -((-c.data) // d))
            return Column(data, c.valid, target)
        if c.dtype.kind == TypeKind.INT:
            return c
        scale = 10.0 ** nd
        if name == "round":
            data = jnp.round(c.data * scale) / scale
        else:
            data = jnp.trunc(c.data * scale) / scale
        return Column(data, c.valid, c.dtype)
    if name in ("sqrt", "exp", "ln", "log2", "log10", "sin", "cos", "tan"):
        c = _to_float(eval_expr(e.args[0], rel), TypeKind.DOUBLE)
        fns = {"sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log,
               "log2": jnp.log2, "log10": jnp.log10, "sin": jnp.sin,
               "cos": jnp.cos, "tan": jnp.tan}
        data = fns[name](c.data)
        bad = jnp.isnan(data) | jnp.isinf(data)
        v = c.valid_or_true() & ~bad
        return Column(data, v, SqlType.double())
    if name in ("power", "pow"):
        a = _to_float(eval_expr(e.args[0], rel), TypeKind.DOUBLE)
        b = _to_float(eval_expr(e.args[1], rel), TypeKind.DOUBLE)
        data = jnp.power(a.data, b.data)
        return Column(data, _merge_valid(a, b), SqlType.double())
    if name == "mod":
        return _eval_arith(ir.Arith("%", e.args[0], e.args[1]), rel, n)
    if name in ("greatest", "least"):
        cols = [eval_expr(a, rel) for a in e.args]
        cols, rt, sdict = _unify_branches(cols)
        opf = jnp.maximum if name == "greatest" else jnp.minimum
        data = cols[0].data
        valid = cols[0].valid
        for c in cols[1:]:
            data = opf(data, c.data)
            valid = _merge_valid(Column(data, valid, rt),
                                 c)
        return Column(data, valid, rt, sdict=sdict)
    if name == "ifnull":
        return _eval_func(ir.FuncCall("coalesce", e.args), rel, n)
    if name == "nullif":
        a = eval_expr(e.args[0], rel)
        eq = _eval_cmp(ir.Cmp("=", e.args[0], e.args[1]), rel, n)
        t, _f = _tf(eq)
        v = a.valid_or_true() & ~t
        return Column(a.data, v, a.dtype, a.sdict)
    if name in ("length", "char_length", "character_length"):
        c = eval_expr(e.args[0], rel)
        assert c.sdict is not None, f"{name} requires a string column"
        lut = jnp.asarray(c.sdict.lut(len).astype("int64"))
        data = lut[jnp.clip(c.data, 0, c.sdict.size - 1)]
        return Column(data, c.valid, SqlType.int_())
    if name in ("trim", "ltrim", "rtrim", "reverse"):
        fns = {"trim": str.strip, "ltrim": str.lstrip,
               "rtrim": str.rstrip, "reverse": lambda s: s[::-1]}
        return _dict_transform(e.args[0], rel, fns[name])
    if name == "replace":
        old = e.args[1].value
        new = e.args[2].value
        return _dict_transform(e.args[0], rel,
                               lambda s: s.replace(old, new))
    if name in ("left", "right"):
        k = e.args[1].value
        if name == "left":
            return _dict_transform(e.args[0], rel, lambda s: s[:k])
        return _dict_transform(e.args[0], rel,
                               lambda s: s[-k:] if k else "")
    if name == "concat":
        return _eval_concat(e, rel, n)
    if name == "coalesce":
        cols = [eval_expr(a, rel) for a in e.args]
        cols, rt, sdict = _unify_branches(cols)
        data = cols[-1].data
        valid = cols[-1].valid_or_true()
        for c in reversed(cols[:-1]):
            v = c.valid_or_true()
            data = jnp.where(v, c.data, data)
            valid = v | valid
        return Column(data=data, valid=valid, dtype=rt, sdict=sdict)
    if name in ("substring", "substr", "upper", "lower"):
        return _dict_string_func(name, e, rel)
    if name in ("lcase", "ucase"):
        return _dict_transform(e.args[0], rel,
                               str.lower if name == "lcase" else str.upper)
    if name == "if":
        from oceanbase_tpu.expr.compile import eval_predicate as _ep

        t = _ep(e.args[0], rel)
        a = eval_expr(e.args[1], rel)
        b = eval_expr(e.args[2], rel)
        (a, b), rt, sdict = _unify_branches([a, b])
        data = jnp.where(t, a.data, b.data)
        valid = jnp.where(t, a.valid_or_true(), b.valid_or_true())
        return Column(data, valid, rt, sdict)
    if name == "isnull":
        c = eval_expr(e.args[0], rel)
        v = c.valid
        data = jnp.zeros(n, jnp.bool_) if v is None else ~v
        return Column(data, None, SqlType.bool_())
    if name in ("atan", "asin", "acos", "sinh", "cosh", "tanh", "cot",
                "degrees", "radians"):
        c = eval_expr(e.args[0], rel)
        x = c.data.astype(jnp.float64)
        out = {"atan": jnp.arctan, "asin": jnp.arcsin,
               "acos": jnp.arccos, "sinh": jnp.sinh, "cosh": jnp.cosh,
               "tanh": jnp.tanh,
               "cot": lambda v: 1.0 / jnp.tan(v),
               "degrees": jnp.degrees, "radians": jnp.radians}[name](x)
        return Column(out, c.valid, SqlType.double())
    if name == "atan2":
        a = eval_expr(e.args[0], rel)
        b = eval_expr(e.args[1], rel)
        out = jnp.arctan2(a.data.astype(jnp.float64),
                          b.data.astype(jnp.float64))
        return Column(out, _merge_valid(a, b), SqlType.double())
    if name == "pi":
        return Column(jnp.full(n, np.pi, jnp.float64), None,
                      SqlType.double())
    if name == "log":
        # log(x) = ln; log(base, x) = ln(x)/ln(base) (MySQL)
        if len(e.args) == 1:
            c = eval_expr(e.args[0], rel)
            return Column(jnp.log(c.data.astype(jnp.float64)), c.valid,
                          SqlType.double())
        b = eval_expr(e.args[0], rel)
        c = eval_expr(e.args[1], rel)
        out = jnp.log(c.data.astype(jnp.float64)) / \
            jnp.log(b.data.astype(jnp.float64))
        return Column(out, _merge_valid(b, c), SqlType.double())
    if name == "repeat" and len(e.args) == 2 and \
            isinstance(e.args[1], ir.Literal):
        k = int(e.args[1].value)
        return _dict_transform(e.args[0], rel, lambda s: s * max(k, 0))
    if name in ("lpad", "rpad"):
        k = int(e.args[1].value)
        pad = str(e.args[2].value) if len(e.args) > 2 else " "

        def _pad(s, k=k, pad=pad, left=(name == "lpad")):
            if len(s) >= k:
                return s[:k]
            fill = (pad * k)[: k - len(s)]
            return fill + s if left else s + fill

        return _dict_transform(e.args[0], rel, _pad)
    if name in ("instr", "locate", "position"):
        # instr(str, sub) / locate(sub, str): 1-based, 0 = not found
        if len(e.args) > 2:
            raise NotImplementedError(
                f"{name} with a start position is not supported")
        if name == "instr":
            col_a, sub_a = e.args[0], e.args[1]
        else:
            col_a, sub_a = e.args[1], e.args[0]
        sub = str(sub_a.value) if isinstance(sub_a, ir.Literal) else None
        if sub is None:
            raise NotImplementedError(f"{name} needs a literal needle")
        c = eval_expr(col_a, rel)
        assert c.sdict is not None, f"{name} requires a string column"
        lut = jnp.asarray(
            c.sdict.lut(lambda s: s.find(sub) + 1).astype("int64"))
        data = jnp.take(lut, jnp.clip(c.data, 0, c.sdict.size - 1))
        return Column(data, c.valid, SqlType.int_())
    if name == "ascii":
        c = eval_expr(e.args[0], rel)
        assert c.sdict is not None, "ascii requires a string column"
        lut = jnp.asarray(
            c.sdict.lut(lambda s: ord(s[0]) if s else 0).astype("int64"))
        data = jnp.take(lut, jnp.clip(c.data, 0, c.sdict.size - 1))
        return Column(data, c.valid, SqlType.int_())
    if name == "substring_index" and isinstance(e.args[1], ir.Literal) \
            and isinstance(e.args[2], ir.Literal):
        delim = str(e.args[1].value)
        cnt = int(e.args[2].value)

        def _si(s, d=delim, k=cnt):
            parts = s.split(d)
            return d.join(parts[:k]) if k >= 0 else d.join(parts[k:])

        return _dict_transform(e.args[0], rel, _si)
    if name == "concat_ws":
        sep = str(e.args[0].value) if isinstance(e.args[0], ir.Literal) \
            else None
        if sep is None:
            raise NotImplementedError("concat_ws needs a literal sep")
        if len(e.args) < 2:
            raise NotImplementedError("concat_ws needs value arguments")
        # MySQL semantics: NULL values are SKIPPED (with their
        # separator), unlike CONCAT's null propagation — fold with CASE
        out = e.args[1]
        for a in e.args[2:]:
            out = ir.Case(whens=[
                (ir.FuncCall("isnull", [out]), a),
                (ir.FuncCall("isnull", [a]), out),
            ], else_=ir.FuncCall("concat", [out, ir.Literal(sep), a]))
        out = ir.FuncCall("coalesce", [out, ir.Literal("")])
        return eval_expr(out, rel)
    if name in ("md5", "sha1", "hex"):
        import hashlib as _hl

        fns = {"md5": lambda s: _hl.md5(s.encode()).hexdigest(),
               "sha1": lambda s: _hl.sha1(s.encode()).hexdigest(),
               "hex": lambda s: s.encode().hex().upper()}
        return _dict_transform(e.args[0], rel, fns[name])
    if name in ("dayname", "monthname"):
        c = eval_expr(e.args[0], rel)
        if name == "dayname":
            names = np.array(["Monday", "Tuesday", "Wednesday",
                              "Thursday", "Friday", "Saturday",
                              "Sunday"], dtype=object)
            codes = jnp.remainder(c.data.astype(jnp.int64) + 3, 7)
        else:
            names = np.array(["January", "February", "March", "April",
                              "May", "June", "July", "August",
                              "September", "October", "November",
                              "December"], dtype=object)
            _y, m, _d = civil_from_days(c.data)
            codes = (m - 1).astype(jnp.int64)
        # StringDict values must be sorted (searchsorted code lookups)
        order = np.argsort(names.astype(str))
        remap = jnp.asarray(np.argsort(order).astype(np.int32))
        return Column(jnp.take(remap, codes).astype(jnp.int32), c.valid,
                      SqlType.string(), StringDict(names[order]))
    if name == "last_day":
        c = eval_expr(e.args[0], rel)
        y, m, d = civil_from_days(c.data)
        out = days_from_civil(y, m, _days_in_month(y, m)) \
            .astype(jnp.int32)
        return Column(out, c.valid, c.dtype)
    raise NotImplementedError(f"function {name}")


def _dict_transform(arg: ir.Expr, rel: Relation, fn) -> Column:
    """Apply a host string function through the dictionary (LUT + remap)."""
    c = eval_expr(arg, rel)
    assert c.sdict is not None, "string function requires dict column"
    mapped = c.sdict.lut(fn)
    new_values, inv = np.unique(mapped.astype(object), return_inverse=True)
    remap = jnp.asarray(inv.astype(np.int32))
    codes = remap[jnp.clip(c.data, 0, c.sdict.size - 1)]
    return Column(codes, c.valid, SqlType.string(), StringDict(new_values))


_CONCAT_DICT_LIMIT = 1 << 20


def _eval_concat(e: ir.FuncCall, rel: Relation, n: int) -> Column:
    """CONCAT over dict columns/literals.  Column x column concatenation
    materializes the code-pair product dictionary, guarded by a size cap
    (beyond it the planner should pre-aggregate — r2)."""
    cols = [eval_expr(a, rel) for a in e.args]
    out = cols[0]
    for c in cols[1:]:
        if out.sdict is None or c.sdict is None:
            raise NotImplementedError("concat requires string operands")
        if out.sdict.size * c.sdict.size > _CONCAT_DICT_LIMIT:
            raise NotImplementedError(
                "concat dictionary product too large (round-1 limit)")
        pairs = np.char.add(
            np.repeat(out.sdict.values.astype(str), c.sdict.size),
            np.tile(c.sdict.values.astype(str), out.sdict.size),
        ).astype(object)
        new_values, inv = np.unique(pairs, return_inverse=True)
        remap = jnp.asarray(inv.astype(np.int32)).reshape(
            out.sdict.size, c.sdict.size)
        codes = remap[jnp.clip(out.data, 0, out.sdict.size - 1),
                      jnp.clip(c.data, 0, c.sdict.size - 1)]
        out = Column(codes, _merge_valid(out, c), SqlType.string(),
                     StringDict(new_values))
    return out


def _dict_string_func(name: str, e: ir.FuncCall, rel: Relation) -> Column:
    """String functions as dictionary transforms (host) + device remap."""
    c = eval_expr(e.args[0], rel)
    assert c.sdict is not None, f"{name} requires dict-encoded column"
    if name in ("substring", "substr"):
        start = e.args[1].value if isinstance(e.args[1], ir.Literal) else e.args[1]
        length = None
        if len(e.args) > 2:
            length = e.args[2].value if isinstance(e.args[2], ir.Literal) else e.args[2]
        s0 = start - 1

        def f(s):
            return s[s0: s0 + length] if length is not None else s[s0:]
    elif name == "upper":
        def f(s):
            return s.upper()
    else:
        def f(s):
            return s.lower()
    mapped = c.sdict.lut(f)
    new_values, inv = np.unique(mapped, return_inverse=True)
    remap = jnp.asarray(inv.astype(np.int32))
    codes = remap[jnp.clip(c.data, 0, c.sdict.size - 1)]
    return Column(data=codes, valid=c.valid, dtype=SqlType.string(),
                  sdict=StringDict(new_values))


def eval_predicate(e: ir.Expr, rel: Relation):
    """Evaluate a WHERE predicate to a live-row bool mask (NULL -> False),
    combined with the relation's existing mask — the TPU analog of
    ObOperator filter_rows + skip accounting
    (src/sql/engine/ob_operator.cpp:1466-1560)."""
    c = eval_expr(e, rel)
    t, _ = _tf(c)
    return t & rel.mask_or_true()
