"""Immutable column segments (SSTable analog).

Reference analog: ObSSTable macro/micro blocks + column store CG files
(src/storage/blocksstable, src/storage/column_store).  A segment is the
unit the LSM produces at freeze/compaction time: per-column encoded chunks
with zone maps, optionally persisted as one .npz file, decoded column-wise
straight into the device upload path.

Layout: rows are chunked (CHUNK_ROWS ≙ micro block); each (column, chunk)
is independently encoded and zone-mapped so scans can skip chunks from
pushdown ranges (≙ blockscan + index-block skipping,
src/storage/access/ob_multiple_scan_merge.cpp:209).
"""

from __future__ import annotations

import io
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.storage.encoding import (
    EncodedColumn,
    decode_column,
    encode_column,
)

CHUNK_ROWS = 65536


@dataclass
class Segment:
    """Immutable sorted-run of rows for one tablet."""

    segment_id: int
    level: int                      # 0 = mini (L0), 1 = minor, 2 = major
    n_rows: int
    columns: dict                   # name -> list[EncodedColumn] per chunk
    types: dict                     # name -> SqlType
    # commit-version range covered (MVCC): rows in this segment are visible
    # to snapshots >= max_version
    min_version: int = 0
    max_version: int = 0

    @property
    def n_chunks(self) -> int:
        any_col = next(iter(self.columns.values()))
        return len(any_col)

    def nbytes(self) -> int:
        return sum(ec.nbytes() for chunks in self.columns.values()
                   for ec in chunks)

    # ------------------------------------------------------------------
    @staticmethod
    def build(segment_id: int, level: int, arrays: dict, types: dict,
              valids: dict | None = None, min_version=0, max_version=0,
              chunk_rows: int = CHUNK_ROWS) -> "Segment":
        n = len(next(iter(arrays.values()))) if arrays else 0
        cols: dict[str, list[EncodedColumn]] = {}
        for name, arr in arrays.items():
            valid = (valids or {}).get(name)
            chunks = []
            for s in range(0, max(n, 1), chunk_rows):
                e = min(s + chunk_rows, n)
                v = valid[s:e] if valid is not None else None
                chunks.append(encode_column(np.asarray(arr[s:e]), v))
            cols[name] = chunks
        return Segment(segment_id, level, n, cols, dict(types),
                       min_version, max_version)

    def decode(self, names=None, chunk_mask=None):
        """-> (arrays, valids) decoded host columns, optionally skipping
        chunks (zone-map pruning)."""
        names = names if names is not None else list(self.columns)
        arrays, valids = {}, {}
        for name in names:
            chunks = self.columns[name]
            parts, vparts = [], []
            has_valid = any(c.valid is not None for c in chunks)
            for i, ec in enumerate(chunks):
                if chunk_mask is not None and not chunk_mask[i]:
                    continue
                parts.append(decode_column(ec))
                if has_valid:
                    vparts.append(ec.valid if ec.valid is not None
                                  else np.ones(ec.n, dtype=bool))
            if not parts:
                dt = self.types[name].np_dtype
                arrays[name] = np.zeros(0, dtype=object
                                        if self.types[name].is_string else dt)
                valids[name] = None
                continue
            arrays[name] = np.concatenate(parts)
            valids[name] = np.concatenate(vparts) if has_valid else None
        return arrays, valids

    def prune_chunks(self, col: str, lo, hi) -> np.ndarray:
        """Zone-map chunk pruning for a range predicate on ``col``
        (≙ index-block skip, the blockscan fast path)."""
        chunks = self.columns.get(col)
        if chunks is None:
            return np.ones(self.n_chunks, dtype=bool)
        return np.array([ec.zone.may_match_range(lo, hi) for ec in chunks])

    # ------------------------------------------------------------------
    # persistence (≙ macro-block file + manifest entry)
    # ------------------------------------------------------------------
    # Integrity layout: every (column, chunk) entry carries a crc64 over
    # its encoded buffers + validity (≙ micro-block checksum), and the
    # footer carries a whole-segment digest over the meta json — which
    # transitively covers every chunk crc (≙ macro-block checksum).
    # ``load`` verifies both and raises CorruptionError instead of
    # decoding poisoned rows.
    def save(self, path: str):
        from oceanbase_tpu.storage.integrity import chunk_crc

        payload = {}
        meta = {
            "segment_id": self.segment_id, "level": self.level,
            "n_rows": self.n_rows, "min_version": self.min_version,
            "max_version": self.max_version,
            "cols": {}, "types": {},
        }
        for name, t in self.types.items():
            meta["types"][name] = [t.kind.value, t.precision, t.scale]
        for name, chunks in self.columns.items():
            meta["cols"][name] = []
            for i, ec in enumerate(chunks):
                centry = {"encoding": ec.encoding, "n": ec.n,
                          "keys": list(ec.payload),
                          "crc": chunk_crc(ec.payload, ec.valid,
                                           ec.encoding, ec.n),
                          "zone": [None if ec.zone.vmin is None else
                                   _scalar(ec.zone.vmin),
                                   None if ec.zone.vmax is None else
                                   _scalar(ec.zone.vmax),
                                   ec.zone.null_count, ec.zone.row_count]}
                for k, v in ec.payload.items():
                    payload[f"{name}/{i}/{k}"] = np.asarray(v)
                if ec.valid is not None:
                    payload[f"{name}/{i}/__valid__"] = ec.valid
                    centry["has_valid"] = True
                meta["cols"][name].append(centry)
        import json

        from oceanbase_tpu.native import crc64

        meta_json = json.dumps(meta).encode()
        payload["__meta__"] = np.frombuffer(meta_json, dtype=np.uint8)
        payload["__digest__"] = np.array([crc64(meta_json)],
                                         dtype=np.uint64)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
            # fsync BEFORE the rename: without it a crash can publish
            # the name with the bytes still in the page cache — a torn
            # current-generation segment behind an "atomic" replace
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish (≙ macro block seal)

    @staticmethod
    def load(path: str, verify: bool = True) -> "Segment":
        import json

        from oceanbase_tpu.datatypes import TypeKind
        from oceanbase_tpu.native import crc64
        from oceanbase_tpu.storage.encoding import ZoneMap
        from oceanbase_tpu.storage.integrity import (
            CorruptionError,
            chunk_crc,
        )

        try:
            with np.load(path, allow_pickle=True) as z:
                meta_json = bytes(z["__meta__"])
                meta = json.loads(meta_json.decode())
                if verify and "__digest__" in z.files:
                    if int(z["__digest__"][0]) != crc64(meta_json):
                        raise CorruptionError(
                            f"segment footer digest mismatch: {path}",
                            kind="segment", path=path)
                types = {n: SqlType(TypeKind(k), p, s)
                         for n, (k, p, s) in meta["types"].items()}
                cols = {}
                for name, centries in meta["cols"].items():
                    chunks = []
                    for i, ce in enumerate(centries):
                        payload = {k: z[f"{name}/{i}/{k}"]
                                   for k in ce["keys"]}
                        valid = None
                        if ce.get("has_valid"):
                            valid = z[f"{name}/{i}/__valid__"]
                        if verify and "crc" in ce and \
                                chunk_crc(payload, valid, ce["encoding"],
                                          ce["n"]) != ce["crc"]:
                            raise CorruptionError(
                                f"segment chunk crc mismatch: {path} "
                                f"column {name!r} chunk {i}",
                                kind="segment", path=path)
                        zn = ce["zone"]
                        chunks.append(EncodedColumn(
                            ce["encoding"], payload, valid,
                            ZoneMap(zn[0], zn[1], zn[2], zn[3]), ce["n"]))
                    cols[name] = chunks
        except CorruptionError:
            raise
        except Exception as e:
            # a flipped bit in the compressed container surfaces as a
            # zip/zlib/json/key error long before any crc check runs —
            # normalize to the ONE typed error read paths handle
            raise CorruptionError(
                f"segment unreadable: {path} ({e})",
                kind="segment", path=path) from e
        return Segment(meta["segment_id"], meta["level"], meta["n_rows"],
                       cols, types, meta["min_version"], meta["max_version"])


def sort_rows_by_keys(arrays: dict, valids: dict, key_cols: list[str]):
    """STABLY sort row arrays by the key columns (oldest-first order of
    equal keys is preserved, so position-based newest-wins dedup in
    ``snapshot_arrays`` stays correct).

    Key-sorted segments are the TPU build's primary index: each chunk's
    zone map on the key columns becomes a tight range, so point/range
    lookups decode only the chunks that can contain the key
    (≙ the index-block row scanner seeking macro/micro blocks,
    src/storage/blocksstable/index_block/ob_index_block_row_scanner.h)."""
    present = [k for k in key_cols if k in arrays]
    if not present:
        return arrays, valids
    n = len(next(iter(arrays.values()))) if arrays else 0
    if n <= 1:
        return arrays, valids
    sort_keys = []
    for k in reversed(present):  # lexsort: last key is primary
        a = arrays[k]
        sort_keys.append(a.astype("U") if a.dtype == object else a)
    order = np.lexsort(sort_keys)
    out_a = {c: a[order] for c, a in arrays.items()}
    out_v = {c: (v[order] if v is not None else None)
             for c, v in valids.items()}
    return out_a, out_v


def _scalar(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.str_, str)):
        return str(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v


def merge_segments(segment_id: int, level: int, segments: list,
                   key_cols: list[str], drop_tombstones: bool) -> Segment:
    """Compaction merge: stack rows, newest version of each key wins
    (≙ ObPartitionMerger major/minor merge,
    src/storage/compaction/ob_partition_merger.h:140).

    Segments must be given oldest-first; key_cols empty -> append-only
    merge (no dedup).  ``drop_tombstones`` must be True only when the merge
    covers EVERY level (major merge) — otherwise a tombstone may shadow a
    base row in a lower level outside the merge set and must be retained.

    The column set is the UNION across inputs: segments built from bulk
    load lack the __deleted__/__version__ bookkeeping columns that
    memtable flushes carry; missing columns fill with defaults
    (not-deleted, version = segment max_version).
    """
    if not segments:
        raise ValueError("nothing to merge")
    types: dict = {}
    for seg in segments:
        for n, t in seg.types.items():
            types.setdefault(n, t)
    all_arrays = []
    all_valids = []
    for seg in segments:
        a, v = seg.decode()
        n_rows = len(next(iter(a.values()))) if a else 0
        for n, t in types.items():
            if n not in a:
                if n == "__deleted__":
                    a[n] = np.zeros(n_rows, dtype=bool)
                elif n == "__version__":
                    a[n] = np.full(n_rows, seg.max_version, dtype=np.int64)
                else:
                    a[n] = (np.array([""] * n_rows, dtype=object)
                            if t.is_string else
                            np.zeros(n_rows, dtype=t.np_dtype))
                    v[n] = np.zeros(n_rows, dtype=bool)  # NULL-filled
        all_arrays.append(a)
        all_valids.append(v)
    names = list(types)
    stacked = {}
    stacked_valid = {}
    for n in names:
        parts = [a[n] for a in all_arrays]
        if any(p.dtype == object for p in parts):
            parts = [p.astype(object) for p in parts]
        stacked[n] = np.concatenate(parts)
        if any(v.get(n) is not None for v in all_valids):
            stacked_valid[n] = np.concatenate(
                [v[n] if v.get(n) is not None
                 else np.ones(len(a[n]), bool)
                 for v, a in zip(all_valids, all_arrays)])
    total = len(next(iter(stacked.values()))) if names else 0

    keep = np.ones(total, dtype=bool)
    if key_cols and total:
        # newest wins: iterate from the end (newest segment last)
        key_arrays = [stacked[k] for k in key_cols]
        seen: set = set()
        order = np.arange(total - 1, -1, -1)
        for idx in order:
            key = tuple(a[idx] for a in key_arrays)
            if key in seen:
                keep[idx] = False
            else:
                seen.add(key)
    if "__deleted__" in stacked and drop_tombstones:
        keep &= ~stacked["__deleted__"].astype(bool)
        del stacked["__deleted__"]
        stacked_valid.pop("__deleted__", None)
        types.pop("__deleted__", None)

    out_arrays = {n: stacked[n][keep] for n in stacked}
    out_valids = {n: v[keep] for n, v in stacked_valid.items()}
    out_arrays, out_valids = sort_rows_by_keys(out_arrays, out_valids,
                                               key_cols)
    return Segment.build(
        segment_id, level, out_arrays, types, out_valids,
        min_version=min(s.min_version for s in segments),
        max_version=max(s.max_version for s in segments),
    )
