"""StorageEngine: tables -> tablets, manifest + redo (slog analog),
checkpoint/recovery, and the catalog bridge feeding the executor.

Reference analog:
- slog + slog_ckpt (src/storage/slog, ob_server_checkpoint_slog_handler.h):
  here a JSONL redo of metadata ops + segment files named by id, with an
  atomic manifest checkpoint; boot = manifest + slog replay.
- ObLSService restart (SURVEY §3.1): ``StorageEngine.open`` reloads
  persisted segments; memtable contents are re-applied by the tx plane's
  log replay (palf WAL), not by this layer.
- direct load (src/storage/direct_load): ``bulk_load`` builds an L2
  baseline segment straight from host arrays, bypassing the memtable.

The engine also backs ``StorageCatalog`` — the Catalog implementation that
materializes device Relations from tablet snapshots with caching keyed on
(data_version, snapshot), so analytics over a quiet table hit the cached
HBM-resident columns (≙ KV cache framework serving block cache hits).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass

import numpy as np

from oceanbase_tpu.catalog import Catalog, ColumnDef, TableDef
from oceanbase_tpu.datatypes import SqlType, TypeKind
from oceanbase_tpu.storage.segment import Segment
from oceanbase_tpu.storage.tablet import Tablet

log = logging.getLogger("oceanbase_tpu.storage.engine")


@dataclass
class TableStore:
    tdef: TableDef
    tablet: Tablet  # single tablet per table in round 1; split comes with LS


# ---------------------------------------------------------------------------
# checksummed metadata files (manifest + slog) — module-level so the
# rebuild client (net/rebuild.py) can pre-verify a baseline without an
# engine instance
# ---------------------------------------------------------------------------


def load_manifest(path: str) -> dict:
    """Read + verify a checkpoint manifest.  New files are
    {"crc", "m"} with the crc over the sorted-key serialization of the
    body; legacy (pre-integrity) files load unverified."""
    from oceanbase_tpu.native import crc64
    from oceanbase_tpu.storage.integrity import CorruptionError

    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError) as e:
        raise CorruptionError(f"manifest unreadable: {path} ({e})",
                              kind="manifest", path=path) from e
    if not isinstance(d, dict):
        raise CorruptionError(f"manifest malformed: {path}",
                              kind="manifest", path=path)
    if "crc" not in d or "m" not in d:
        return d  # legacy manifest
    inner = json.dumps(d["m"], sort_keys=True)
    if crc64(inner.encode()) != d["crc"]:
        raise CorruptionError(f"manifest digest mismatch: {path}",
                              kind="manifest", path=path)
    return d["m"]


def read_slog(path: str):
    """Yield verified slog ops.  A torn FINAL line (crash mid-append) is
    tolerated and ends the scan, exactly like the WAL torn-tail scan; a
    checksum mismatch on a well-formed record is corruption and raises."""
    from oceanbase_tpu.native import crc64
    from oceanbase_tpu.storage.integrity import CorruptionError

    with open(path) as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        last = i == len(lines) - 1
        try:
            d = json.loads(line)
        except ValueError as e:
            if last and not line.endswith("\n"):
                return  # torn tail: the append never finished
            raise CorruptionError(
                f"slog record {i} unreadable: {path}",
                kind="slog", path=path) from e
        if isinstance(d, dict) and "rec" in d and "crc" in d:
            if crc64(d["rec"].encode()) != d["crc"]:
                raise CorruptionError(
                    f"slog record {i} crc mismatch: {path}",
                    kind="slog", path=path)
            yield json.loads(d["rec"])
        else:
            yield d  # legacy unwrapped record


def quarantine_file(path: str) -> str:
    """Move a corrupt artifact aside (never delete — forensics) under a
    unique .corrupt suffix, retention-capping the directory's older
    quarantines by count/age; -> the quarantine path."""
    import time

    from oceanbase_tpu.storage.integrity import prune_quarantine

    qpath = f"{path}.corrupt.{time.time_ns():x}"
    os.replace(path, qpath)
    prune_quarantine(os.path.dirname(qpath))
    return qpath


class StorageEngine:
    def __init__(self, root: str | None = None,
                 corrupt_policy: str = "raise"):
        """``corrupt_policy`` decides what boot does with a segment file
        that fails its checksum: ``"raise"`` (single node — no repair
        source, fail loudly) or ``"quarantine"`` (cluster node — move
        the file aside, boot without it, and let the scrub plane refetch
        it from a healthy peer; storage/scrub.py)."""
        self.root = root
        self.corrupt_policy = corrupt_policy
        self.tables: dict[str, TableStore] = {}
        # segments quarantined at boot or by the scrubber, pending peer
        # repair: [{"table", "segment_id", "part", "path"}]
        self.quarantined: list[dict] = []
        # scrub fast path: raw-file crc64 of fully verified segment
        # files (path -> crc); a later round that re-reads identical
        # bytes skips the decode-and-recheck
        self._verified_files: dict[str, int] = {}
        # disk-fault plane hook (net/faults.py FaultPlane or None):
        # consulted AFTER every persistence write so seeded bitflip/
        # truncate rules can target artifacts by kind
        self.faults = None
        # flush listener (tenant wiring): called AFTER freeze_and_flush
        # with (table, rows still resident in the memtables) so the
        # memstore write-backpressure accounting re-bases when a flush
        # clears pressure (server/admission.py::MemstoreThrottle)
        self.flush_listener = None
        self.meta: dict = {}  # checkpointed runtime meta (wal replay point…)
        # table -> WAL LSN of the newest TRUNCATE whose slog record this
        # engine has already applied; WAL replay must not re-apply
        # truncate barriers at/below these (they would drop direct-load
        # segments the slog restored AFTER the truncate)
        self.truncate_barriers: dict[str, int] = {}
        self._lock = threading.RLock()
        self._slog_f = None
        # segments installed in memory whose durable save (or slog
        # publish) failed typed (DiskFull/DiskIOError): memory keeps
        # serving them, and every flush/compact/checkpoint entry point
        # re-attempts the persist FIRST — a manifest must never
        # reference a segment file that does not exist on disk
        self._pending_segs: list[tuple[str, object, dict]] = []
        # multi-node hook: logical DDL ops also replicate through the
        # tenant's log stream (net/node.py wires this; followers apply
        # via _replay) — physical segment ops stay node-local
        self.ddl_wal_cb = None
        if root is not None:
            os.makedirs(os.path.join(root, "segments"), exist_ok=True)
            self._open_or_recover()

    # ------------------------------------------------------------------
    # metadata persistence (slog + checkpoint)
    # ------------------------------------------------------------------
    def _slog_path(self):
        return os.path.join(self.root, "slog.jsonl")

    def _manifest_path(self):
        return os.path.join(self.root, "manifest.json")

    def _log_meta(self, op: dict):
        from oceanbase_tpu.native import crc64

        if self.ddl_wal_cb is not None:
            self.ddl_wal_cb(op)
        if self.root is None:
            return
        if self._slog_f is None:
            self._slog_f = open(self._slog_path(), "a")
        # each record ships as {"crc", "rec"} with the crc computed over
        # the EXACT serialized op string — replay verifies before apply
        # (≙ slog entry checksums)
        rec = json.dumps(op)
        self._slog_f.flush()
        pre_off = os.path.getsize(self._slog_path())
        try:
            if self.faults is not None:
                self.faults.check_write("slog", self._slog_path())
            self._slog_f.write(json.dumps(
                {"crc": crc64(rec.encode()), "rec": rec}) + "\n")
            self._slog_f.flush()
            os.fsync(self._slog_f.fileno())
        except OSError as exc:
            # crash-safe unwind: truncate the line back so the slog
            # never carries a torn record (replay would reject it by
            # crc, but the NEXT append would land mid-line)
            self._unwind_slog(pre_off)
            from oceanbase_tpu.server.diskmgr import wrap_disk_error

            raise wrap_disk_error(exc, "slog append") from exc
        self._disk_fault("slog", self._slog_path())

    def _unwind_slog(self, pre_off: int):
        """Truncate the slog back to its pre-append offset after a
        failed write (the buffered handle is poisoned — reopen)."""
        try:
            if self._slog_f is not None:
                self._slog_f.close()
        except OSError:
            pass
        self._slog_f = None
        try:
            with open(self._slog_path(), "a") as f:
                f.truncate(pre_off)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            log.warning("slog unwind to offset %d failed", pre_off)

    def _flush_pending_locked(self):
        """Re-persist segments whose earlier save failed (disk
        pressure): save is an idempotent overwrite, so a seg whose file
        landed but whose slog record didn't simply saves again.  Raises
        typed when the disk is still failing — the caller sheds."""
        while self._pending_segs:
            name, seg, op = self._pending_segs[0]
            self._save_segment(name, seg)
            self._log_meta(op)
            self._pending_segs.pop(0)

    def _persist_segs_locked(self, name: str, segs, make_op):
        """Persist freshly minted in-memory segments; on a typed disk
        failure the unsaved remainder parks in ``_pending_segs`` (the
        next flush/compact/checkpoint re-attempts before anything else
        trusts the segment list)."""
        for i, (part, seg) in enumerate(segs):
            op = make_op(part, seg, i)
            try:
                self._save_segment(name, seg)
                self._log_meta(op)
            except Exception:
                self._pending_segs.append((name, seg, op))
                for j, (p2, s2) in enumerate(segs[i + 1:], start=i + 1):
                    self._pending_segs.append(
                        (name, s2, make_op(p2, s2, j)))
                raise

    def _disk_fault(self, kind: str, path: str):
        """Consult the disk-fault plane after a persistence write (no-op
        unless a NodeServer armed bitflip/truncate rules)."""
        if self.faults is not None:
            self.faults.act_disk(kind, path)

    def checkpoint(self):
        """Write an atomic manifest and truncate the slog
        (≙ tenant meta checkpoint advancing the slog recycle point)."""
        if self.root is None:
            return
        with self._lock:
            # a manifest must never reference a segment whose file is
            # missing (an earlier save failed under disk pressure)
            self._flush_pending_locked()
            m = {"tables": {}, "meta": self.meta}
            for name, ts in self.tables.items():
                m["tables"][name] = {
                    "columns": [[c.name, c.dtype.kind.value,
                                 c.dtype.precision, c.dtype.scale,
                                 c.nullable] for c in ts.tdef.columns],
                    "primary_key": ts.tdef.primary_key,
                    "partition": (list(ts.tdef.partition)
                                  if ts.tdef.partition else None),
                    "auto_increment": list(ts.tdef.auto_increment_cols),
                    "indexes": [[ix.name, list(ix.columns), ix.unique]
                                for ix in ts.tdef.indexes],
                    "aux_indexes": {n: {k: v for k, v in spec.items()
                                        if k != "runtime"}
                                    for n, spec in
                                    ts.tdef.aux_indexes.items()},
                    "segments": [[s.segment_id, s.level, part]
                                 for s, part in
                                 ts.tablet.segment_locations()],
                }
            from oceanbase_tpu.native import crc64

            # checkpoint digest: the manifest body travels beside a crc
            # over its canonical (sorted-key) serialization; boot
            # verifies before trusting the table/segment list
            inner = json.dumps(m, sort_keys=True)
            tmp = self._manifest_path() + ".tmp"
            try:
                if self.faults is not None:
                    self.faults.check_write("manifest",
                                            self._manifest_path())
                with open(tmp, "w") as f:
                    json.dump({"crc": crc64(inner.encode()), "m": m}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._manifest_path())
            except OSError as exc:
                # the previous manifest generation is still intact (the
                # tmp never published) — drop the partial tmp and raise
                # typed so the checkpoint caller sheds, not crashes
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                from oceanbase_tpu.server.diskmgr import wrap_disk_error

                raise wrap_disk_error(exc, "manifest checkpoint") from exc
            self._disk_fault("manifest", self._manifest_path())
            if self._slog_f:
                self._slog_f.close()
                self._slog_f = None
            # reset (not recreate) the slog: append-mode + truncate keeps
            # this an in-place recycle of an existing artifact rather
            # than an unsynced create of a new generation
            with open(self._slog_path(), "a") as f:
                f.truncate(0)

    def _open_or_recover(self):
        mpath = self._manifest_path()
        if os.path.exists(mpath):
            m = load_manifest(mpath)
            self.meta = m.get("meta", {})
            for name, t in m["tables"].items():
                cols = [ColumnDef(n, SqlType(TypeKind(k), p, s), nl)
                        for n, k, p, s, nl in t["columns"]]
                part = t.get("partition")
                tdef = TableDef(name, cols, primary_key=t["primary_key"],
                                partition=tuple(part) if part else None,
                                auto_increment_cols=t.get("auto_increment",
                                                          []))
                self._install_table(tdef, log=False)
                ts = self.tables[name]
                from oceanbase_tpu.catalog import IndexDef

                for iname, icols, iuniq in t.get("indexes", []):
                    ts.tdef.indexes.append(IndexDef(
                        iname, name, list(icols), iuniq,
                        self.index_storage_name(name, iname)))
                ts.tdef.aux_indexes.update(t.get("aux_indexes", {}))
                for entry in t["segments"]:
                    seg_id, level = entry[0], entry[1]
                    part_idx = entry[2] if len(entry) > 2 else None
                    path = self._segment_file(name, seg_id)
                    if os.path.exists(path):
                        self._load_or_quarantine(name, seg_id, part_idx,
                                                 path)
                ts.tdef.row_count = ts.tablet.row_count_estimate()
        # replay metadata ops logged after the checkpoint (each record
        # crc-verified; a torn FINAL line is a crash artifact and
        # truncates like a torn WAL tail, a bad crc anywhere is
        # corruption and raises)
        if os.path.exists(self._slog_path()):
            for op in read_slog(self._slog_path()):
                self._replay(op)

    def _load_or_quarantine(self, table: str, seg_id: int, part_idx,
                            path: str):
        """Boot-time segment load honoring ``corrupt_policy``: a file
        failing its checksum either fails the boot loudly or moves
        aside so the scrub plane can refetch it from a peer."""
        from oceanbase_tpu.storage.integrity import CorruptionError

        ts = self.tables[table]
        try:
            ts.tablet.add_segment(Segment.load(path), part_idx)
        except CorruptionError:
            if self.corrupt_policy != "quarantine":
                raise
            qpath = quarantine_file(path)
            with self._lock:  # reentrant: boot/replay callers hold it
                self.quarantined.append(
                    {"table": table, "segment_id": seg_id,
                     "part": part_idx, "path": qpath})

    def _replay(self, op: dict):
        # boot-time today, but WAL catch-up may replay on a live engine;
        # holding the (reentrant) engine lock makes either safe
        with self._lock:
            self._replay_locked(op)

    def _replay_locked(self, op: dict):
        kind = op["op"]
        if kind == "create_table":
            cols = [ColumnDef(n, SqlType(TypeKind(k), p, s), nl)
                    for n, k, p, s, nl in op["columns"]]
            part = op.get("partition")
            self._install_table(
                TableDef(op["name"], cols, primary_key=op["primary_key"],
                         partition=tuple(part) if part else None,
                         auto_increment_cols=op.get("auto_increment", [])),
                log=False)
        elif kind == "drop_table":
            self.tables.pop(op["name"], None)
        elif kind == "truncate":
            if op["table"] in self.tables:
                self.truncate_table(op["table"], log=False)
            self.truncate_barriers[op["table"]] = max(
                self.truncate_barriers.get(op["table"], 0),
                op.get("wal_lsn", 0))
        elif kind == "alter_add":
            n, k, p, s, nl = op["column"]
            if op["table"] in self.tables:
                self.alter_table(op["table"], "add_column",
                                 (n, SqlType(TypeKind(k), p, s), nl),
                                 log=False)
        elif kind == "alter_drop":
            if op["table"] in self.tables:
                try:
                    self.alter_table(op["table"], "drop_column",
                                     op["column"], log=False)
                except KeyError:
                    pass
        elif kind == "create_index":
            from oceanbase_tpu.catalog import IndexDef

            ts = self.tables.get(op["table"])
            if ts is not None and not any(ix.name == op["name"]
                                          for ix in ts.tdef.indexes):
                ts.tdef.indexes.append(IndexDef(
                    op["name"], op["table"], list(op["columns"]),
                    op["unique"],
                    self.index_storage_name(op["table"], op["name"])))
        elif kind == "drop_index":
            ts = self.tables.get(op["table"])
            if ts is not None:
                ts.tdef.indexes = [ix for ix in ts.tdef.indexes
                                   if ix.name != op["name"]]
        elif kind == "create_view":
            self.meta.setdefault("views", {})[op["name"]] = {
                "sql": op["sql"], "cols": op.get("cols", [])}
        elif kind == "drop_view":
            self.meta.get("views", {}).pop(op["name"], None)
        elif kind == "aux_index":
            ts = self.tables.get(op["table"])
            if ts is not None:
                ts.tdef.aux_indexes[op["name"]] = op["spec"]
        elif kind == "drop_aux_index":
            ts = self.tables.get(op["table"])
            if ts is not None:
                ts.tdef.aux_indexes.pop(op["name"], None)
        elif kind == "add_segment":
            ts = self.tables.get(op["table"])
            if ts is not None:
                path = self._segment_file(op["table"], op["segment_id"])
                if os.path.exists(path):
                    self._load_or_quarantine(op["table"],
                                             op["segment_id"],
                                             op.get("part"), path)
        elif kind == "replace_segments":
            ts = self.tables.get(op["table"])
            if ts is not None:
                ts.tablet.remove_segments(op["removed"])
                path = self._segment_file(op["table"], op["segment_id"])
                if os.path.exists(path):
                    self._load_or_quarantine(op["table"],
                                             op["segment_id"],
                                             op.get("part"), path)
        elif kind == "repair_segments":
            ts = self.tables.get(op["table"])
            if ts is not None:
                ts.tablet.remove_segments(op["removed"])
                for sid, _level, part in op["installed"]:
                    path = self._segment_file(op["table"], sid)
                    if os.path.exists(path):
                        self._load_or_quarantine(op["table"], sid, part,
                                                 path)

    def _segment_file(self, table: str, seg_id: int) -> str:
        return os.path.join(self.root, "segments", f"{table}_{seg_id}.npz")

    def _save_segment(self, table: str, seg) -> str:
        """Persist one segment + consult the disk-fault plane (the ONE
        place segment bytes hit disk, so bitflip rules by kind cover
        every flush/compaction/load path)."""
        path = self._segment_file(table, seg.segment_id)
        try:
            if self.faults is not None:
                self.faults.check_write("segment", path)
            seg.save(path)
        except OSError as exc:
            # seg.save stages into path+".tmp" and publishes by rename:
            # on failure the current generation (if any) is untouched —
            # clean the partial tmp and surface the typed plane error
            try:
                os.remove(path + ".tmp")
            except OSError:
                pass
            from oceanbase_tpu.server.diskmgr import wrap_disk_error

            raise wrap_disk_error(exc, f"segment flush {table}") from exc
        self._disk_fault("segment", path)
        return path

    # ------------------------------------------------------------------
    # DDL / load
    # ------------------------------------------------------------------
    def _install_table(self, tdef: TableDef, log=True):
        with self._lock:  # reentrant: callers may already hold it
            self._install_table_locked(tdef, log)

    def _install_table_locked(self, tdef: TableDef, log=True):
        types = {c.name: c.dtype for c in tdef.columns}
        columns = list(tdef.column_names)
        key_cols = list(tdef.primary_key)
        if not key_cols:
            # keyless tables get a hidden monotonically assigned rowid so
            # UPDATE/DELETE can address rows (≙ hidden pk in heap tables)
            columns.append("__rowid__")
            types["__rowid__"] = SqlType.int_()
            key_cols = ["__rowid__"]
        if tdef.partition is not None:
            from oceanbase_tpu.storage.partition import PartitionedTablet

            part_col, bounds = tdef.partition
            tablet = PartitionedTablet(len(self.tables) + 1, columns,
                                       types, key_cols, part_col,
                                       list(bounds))
        else:
            tablet = Tablet(len(self.tables) + 1, columns, types, key_cols)
        self.tables[tdef.name] = TableStore(tdef, tablet)
        if log:
            try:
                self._log_meta({
                    "op": "create_table", "name": tdef.name,
                    "columns": [[c.name, c.dtype.kind.value,
                                 c.dtype.precision,
                                 c.dtype.scale, c.nullable]
                                for c in tdef.columns],
                    "primary_key": tdef.primary_key,
                    "partition": (list(tdef.partition)
                                  if tdef.partition else None),
                    "auto_increment": list(tdef.auto_increment_cols),
                })
            except Exception:
                # unwind the in-memory install: a table that never made
                # the slog must not exist (it would vanish on restart —
                # and block a retry of the same CREATE)
                self.tables.pop(tdef.name, None)
                raise

    def create_table(self, tdef: TableDef):
        with self._lock:
            if tdef.name in self.tables:
                raise ValueError(f"table {tdef.name} exists")
            if tdef.partition is not None and tdef.primary_key and \
                    tdef.partition[0] not in tdef.primary_key:
                # MySQL/OceanBase rule: every unique key (incl. the PK)
                # must contain all partitioning columns — otherwise
                # uniqueness could only be checked across partitions
                raise ValueError(
                    "a PRIMARY KEY must include all columns in the "
                    "table's partitioning function")
            self._install_table(tdef)

    def alter_table(self, name: str, action: str, column, log=True):
        """Online schema change: ADD COLUMN (old segments serve NULLs for
        it — no rewrite) / DROP COLUMN (data ages out via compaction).
        ≙ the instant-DDL subset of ObDDLService column changes."""
        with self._lock:
            ts = self.tables[name]
            tdef = ts.tdef
            tab = ts.tablet
            tablets = getattr(tab, "partitions", [tab])
            if action == "add_column":
                cname, dtype, nullable = column
                if any(c.name == cname for c in tdef.columns):
                    raise ValueError(f"column {cname!r} exists")
                tdef.columns.append(ColumnDef(cname, dtype, nullable))
                for t in tablets:
                    t.columns.append(cname)
                    t.types[cname] = dtype
                if hasattr(tab, "part_col"):
                    tab.columns.append(cname)
                    tab.types[cname] = dtype
                if log:
                    self._log_meta({
                        "op": "alter_add", "table": name, "column":
                        [cname, dtype.kind.value, dtype.precision,
                         dtype.scale, nullable]})
            elif action == "drop_column":
                cname = column
                if cname in tdef.primary_key:
                    raise ValueError("cannot drop a primary-key column")
                for ix in tdef.indexes:
                    if cname in ix.columns:
                        raise ValueError(
                            f"cannot drop column {cname!r}: used by "
                            f"index {ix.name} (drop the index first)")
                if getattr(tab, "part_col", None) == cname:
                    raise ValueError("cannot drop the partition column")
                if not any(c.name == cname for c in tdef.columns):
                    raise KeyError(f"unknown column {cname!r}")
                tdef.columns = [c for c in tdef.columns if c.name != cname]
                for t in tablets:
                    if cname in t.columns:
                        t.columns.remove(cname)
                    t.types.pop(cname, None)
                if hasattr(tab, "part_col"):
                    if cname in tab.columns:
                        tab.columns.remove(cname)
                    tab.types.pop(cname, None)
                # purge stored values so a later ADD COLUMN of the same
                # name cannot resurrect them (no column-identity ids yet)
                for t in tablets:
                    for mt in [t.active] + t.frozen:
                        with mt._lock:
                            for head in mt._rows.values():
                                v = head
                                while v is not None:
                                    v.values.pop(cname, None)
                                    v = v.prev
                    for i, seg in enumerate(list(t.segments)):
                        if cname not in seg.columns:
                            continue
                        a, vv = seg.decode()
                        a.pop(cname, None)
                        vv.pop(cname, None)
                        stypes = {k: v for k, v in seg.types.items()
                                  if k != cname}
                        new = Segment.build(
                            seg.segment_id, seg.level, a, stypes,
                            {k: x for k, x in vv.items() if x is not None},
                            min_version=seg.min_version,
                            max_version=seg.max_version)
                        t.segments[i] = new
                        if self.root is not None:
                            self._save_segment(name, new)
                if log:
                    self._log_meta({"op": "alter_drop", "table": name,
                                    "column": cname})
            else:
                raise ValueError(action)
            for t in tablets:
                t.data_version += 1

    # ------------------------------------------------------------------
    # secondary indexes (≙ index tables, src/share/schema index DDL +
    # src/storage/ddl index build tasks)
    # ------------------------------------------------------------------
    @staticmethod
    def index_storage_name(table: str, iname: str) -> str:
        return f"__idx__{table}__{iname}"

    def create_index(self, table: str, iname: str, columns: list[str],
                     unique: bool = False, backfill_version: int = 0,
                     drain=None):
        """CREATE INDEX: install the index table (key = index columns +
        primary key columns) and backfill it from the base table's
        current snapshot as one sorted baseline segment (≙ the DDL
        service's index build scanning the base and writing the index
        SSTable, src/storage/ddl/ob_ddl_redo_log_writer.h path).

        Ordering against concurrent DML (≙ the online-DDL write fence):
        1. install the store table + IndexDef — from here every NEW
           write runs index maintenance;
        2. ``drain()`` (supplied by the session layer) waits out
           transactions live before step 1 — their earlier writes were
           never maintained and must commit/abort first;
        3. backfill from a post-drain snapshot — covers everything those
           transactions committed; entries double-written by step-1
           maintenance dedup via newest-wins on the identical entry key.
        Any failure (unique violation, drain timeout) drops the index
        again, leaving no trace."""
        from oceanbase_tpu.catalog import IndexDef

        with self._lock:
            ts = self.tables[table]
            if any(ix.name == iname for ix in ts.tdef.indexes):
                raise ValueError(f"index {iname} exists on {table}")
            for c in columns:
                ts.tdef.column(c)  # validates existence
            store = self.index_storage_name(table, iname)
            if store in self.tables:
                raise ValueError(f"index table {store} exists")
            pk = list(ts.tdef.primary_key) or ["__rowid__"]
            key_cols = list(columns) + [k for k in pk if k not in columns]
            base_types = ts.tablet.types
            cols = [ColumnDef(c, base_types[c]) for c in key_cols]
            idx = IndexDef(iname, table, list(columns), unique, store)
            itdef = TableDef(store, cols, primary_key=key_cols)
            self._install_table(itdef)
            ts.tdef.indexes.append(idx)
            self._log_meta({"op": "create_index", "table": table,
                            "name": iname, "columns": list(columns),
                            "unique": unique})
        try:
            if drain is not None:
                drain()
            with self._lock:
                arrays, valids = ts.tablet.snapshot_arrays(
                    backfill_version or 2**62)
                entry = {c: arrays[c] for c in key_cols if c in arrays}
                ev = {c: valids[c] for c in key_cols
                      if valids.get(c) is not None}
                n = len(next(iter(entry.values()))) if entry else 0
                if unique and n:
                    self._check_unique_batch(idx, entry, ev, n)
                # the backfill is a free NDV sample for the indexed
                # columns (feeds access-path cardinality estimates)
                for c in columns:
                    if c in entry and n:
                        ts.tdef.ndv[c] = max(1, len(np.unique(
                            entry[c].astype("U")
                            if entry[c].dtype == object else entry[c])))
                if n:
                    self.bulk_load(store, entry, ev or None,
                                   version=max(1, backfill_version))
        except Exception:
            self.drop_index(table, iname)
            raise
        return idx

    @staticmethod
    def _check_unique_batch(idx, entry, ev, n):
        """Reject duplicate index keys among non-NULL entries (MySQL
        semantics: rows with any NULL index column never conflict)."""
        live = np.ones(n, dtype=bool)
        for c in idx.columns:
            if ev.get(c) is not None:
                live &= ev[c]
        keys = [np.asarray(entry[c])[live].astype("U")
                if entry[c].dtype == object else entry[c][live]
                for c in idx.columns]
        if not keys or not len(keys[0]):
            return
        order = np.lexsort(keys[::-1])
        dup = np.ones(len(order), dtype=bool)
        for k in keys:
            s = k[order]
            dup[1:] &= s[1:] == s[:-1]
        dup[0] = False
        if dup.any():
            from oceanbase_tpu.tx.errors import DuplicateKey

            i = int(np.nonzero(dup)[0][0])
            vals = tuple(k[order][i] for k in keys)
            raise DuplicateKey(
                f"duplicate entry {vals} for unique index {idx.name}")

    @staticmethod
    def _check_unique_existing(ix, itab, entry, ev, n):
        """Direct-load unique enforcement against COMMITTED index rows:
        existing live entries inside the batch's value envelope are
        compared tuple-wise; a match whose pk suffix differs from every
        batch row carrying that value is a duplicate.  (The tx write
        path does its own per-row check; this covers LOAD DATA/CTAS.)"""
        if itab.row_count_estimate() == 0:
            return
        from oceanbase_tpu.storage.lookup import range_rows

        live = np.ones(n, dtype=bool)
        for c in ix.columns:
            if ev.get(c) is not None:
                live &= ev[c]
        if not live.any():
            return
        env = {}
        for c in ix.columns:
            a = entry[c][live]
            s = a.astype("U") if a.dtype == object else a
            env[c] = (a[np.argmin(s)] if a.dtype == object else s.min(),
                      a[np.argmax(s)] if a.dtype == object else s.max())
        ikey_cols = itab.key_cols
        ex, exv = range_rows(itab, env, 2**62, 0, columns=list(ikey_cols))
        m = len(next(iter(ex.values()))) if ex else 0
        if m == 0:
            return
        n_ix = len(ix.columns)
        batch_pairs = set()
        idxs = np.nonzero(live)[0]
        for i in idxs:
            val = tuple(entry[c][i] for c in ix.columns)
            pkv = tuple(entry[c][i] for c in ikey_cols[n_ix:])
            batch_pairs.add((val, pkv))
        batch_vals = {v for v, _ in batch_pairs}
        for j in range(m):
            if any(exv.get(c) is not None and not exv[c][j]
                   for c in ix.columns):
                continue  # NULL entries never conflict
            val = tuple(ex[c][j].item() if hasattr(ex[c][j], "item")
                        else ex[c][j] for c in ix.columns)
            if val not in batch_vals:
                continue
            pkv = tuple(ex[c][j].item() if hasattr(ex[c][j], "item")
                        else ex[c][j] for c in ikey_cols[n_ix:])
            if (val, pkv) not in batch_pairs:
                from oceanbase_tpu.tx.errors import DuplicateKey

                raise DuplicateKey(
                    f"duplicate entry {val} for unique index {ix.name} "
                    f"(conflicts with existing row)")

    def drop_index(self, table: str, iname: str, log=True):
        with self._lock:
            ts = self.tables[table]
            keep = [ix for ix in ts.tdef.indexes if ix.name != iname]
            if len(keep) == len(ts.tdef.indexes):
                raise KeyError(f"no index {iname} on {table}")
            dropped = next(ix for ix in ts.tdef.indexes
                           if ix.name == iname)
            ts.tdef.indexes = keep
            if log:
                self._log_meta({"op": "drop_index", "table": table,
                                "name": iname})
            # drop the storage table THROUGH drop_table so the slog also
            # records it — replay must not resurrect an orphan index
            # table that would block re-creating the index
            if dropped.storage_table in self.tables:
                self.drop_table(dropped.storage_table)

    def truncate_table(self, name: str, log=True, wal_lsn: int = 0):
        """Drop all data, keep the schema: reinstall a fresh tablet
        (segments unlinked; ≙ TRUNCATE as fast DDL, not row deletes).

        ``wal_lsn`` is the LSN of the matching WAL truncate record; it is
        persisted in the slog record so recovery can fence WAL replay
        against engine state (the two logs share one order)."""
        with self._lock:
            ts = self.tables[name]
            tdef = ts.tdef
            del self.tables[name]
            self._install_table(tdef, log=False)
            self.tables[name].tdef.row_count = 0
            if wal_lsn:
                self.truncate_barriers[name] = max(
                    self.truncate_barriers.get(name, 0), wal_lsn)
            if log:
                self._log_meta({"op": "truncate", "table": name,
                                "wal_lsn": wal_lsn})
            # secondary indexes empty together with their base table
            for ix in tdef.indexes:
                if ix.storage_table in self.tables:
                    self.truncate_table(ix.storage_table, log=log,
                                        wal_lsn=wal_lsn)

    def reset_memtables(self, name: str):
        """Discard memtable state only, keeping segments — used by WAL
        replay when a TRUNCATE barrier was already applied via the slog
        (the slog-restored post-truncate segments must survive)."""
        from oceanbase_tpu.storage.memtable import MemTable

        with self._lock:
            ts = self.tables.get(name)
            if ts is None:
                return
            tab = ts.tablet
            for t in getattr(tab, "partitions", [tab]):
                t.active = MemTable(next(t._next_mt))
                t.frozen = []
                t.data_version += 1

    def drop_table(self, name: str):
        with self._lock:
            ts = self.tables.pop(name, None)
            self._log_meta({"op": "drop_table", "name": name})
            if ts is not None:
                for ix in ts.tdef.indexes:
                    if ix.storage_table in self.tables:
                        self.drop_table(ix.storage_table)

    def bulk_load(self, name: str, arrays: dict, valids: dict | None = None,
                  version: int = 1):
        """Direct load: host arrays -> L2 baseline segment, bypassing the
        memtable (≙ src/storage/direct_load)."""
        with self._lock:
            ts = self.tables[name]
            if "__rowid__" in ts.tablet.types and "__rowid__" not in arrays:
                n = len(next(iter(arrays.values()))) if arrays else 0
                base = ts.tablet.next_rowid(n)
                arrays = dict(arrays)
                arrays["__rowid__"] = np.arange(base, base + n,
                                                dtype=np.int64)
            from oceanbase_tpu.storage.partition import PartitionedTablet

            if isinstance(ts.tablet, PartitionedTablet):
                parts = ts.tablet.split_arrays_by_partition(arrays)
                targets = [(i, pa,
                            {k: v[sel] for k, v in (valids or {}).items()
                             if v is not None})
                           for i, pa, sel in parts]
            else:
                targets = [(None, arrays, valids or {})]
            from oceanbase_tpu.storage.segment import sort_rows_by_keys

            for part_idx, pa, pv in targets:
                tab = (ts.tablet.partitions[part_idx]
                       if part_idx is not None else ts.tablet)
                if tab.key_cols != ["__rowid__"]:
                    pa, pv = sort_rows_by_keys(pa, dict(pv or {}),
                                               tab.key_cols)
                seg = Segment.build(
                    next(tab._next_seg), 2, pa, ts.tablet.types,
                    pv or None, min_version=version, max_version=version)
                ts.tablet.add_segment(seg, part_idx)
                if self.root is not None:
                    op = {"op": "add_segment", "table": name,
                          "segment_id": seg.segment_id, "part": part_idx}
                    try:
                        self._save_segment(name, seg)
                        self._log_meta(op)
                    except Exception:
                        # memory serves the loaded seg; the persist
                        # re-attempts at the next flush/checkpoint
                        self._pending_segs.append((name, seg, op))
                        raise
            ts.tdef.row_count = ts.tablet.row_count_estimate()
            # maintain secondary indexes: the loaded rows' index entries
            # load the same way (sorted baseline segment per index).
            # Unique checks here are batch-local; the tx-plane write path
            # performs the full existing-row check.
            n = len(next(iter(arrays.values()))) if arrays else 0
            for ix in ts.tdef.indexes:
                istore = self.tables[ix.storage_table]
                ikey = istore.tablet.key_cols
                entry = {}
                ev = {}
                for c in ikey:
                    if c in arrays:
                        entry[c] = arrays[c]
                        if (valids or {}).get(c) is not None:
                            ev[c] = valids[c]
                        continue
                    # a load may omit a nullable indexed column: its
                    # entries are NULL (never silently dropped — that
                    # would collapse distinct rows in the index)
                    if c in (ts.tdef.primary_key or ["__rowid__"]):
                        raise ValueError(
                            f"bulk load is missing index key column "
                            f"{c!r} for index {ix.name}")
                    t = istore.tablet.types[c]
                    entry[c] = (np.array([""] * n, dtype=object)
                                if t.is_string
                                else np.zeros(n, dtype=t.np_dtype))
                    ev[c] = np.zeros(n, dtype=bool)
                if ix.unique and n:
                    self._check_unique_batch(ix, entry, ev, n)
                    self._check_unique_existing(ix, istore.tablet,
                                                entry, ev, n)
                if n:
                    self.bulk_load(ix.storage_table, entry, ev or None,
                                   version=version)

    # ------------------------------------------------------------------
    # compaction driving (≙ tenant tablet scheduler ticks)
    # ------------------------------------------------------------------
    @staticmethod
    def _new_segs(res):
        """Normalize compact results: Segment | [(part, Segment)] | None."""
        if res is None:
            return []
        if isinstance(res, Segment):
            return [(None, res)]
        return list(res)

    def freeze_and_flush(self, name: str, snapshot: int):
        from oceanbase_tpu.server.errsim import ERRSIM

        ERRSIM.hit("storage.flush")
        with self._lock:
            self._flush_pending_locked()
            ts = self.tables[name]
            ts.tablet.freeze()
            segs = self._new_segs(ts.tablet.mini_compact(snapshot))
            if self.root is not None:
                self._persist_segs_locked(
                    name, segs,
                    lambda part, seg, _i: {
                        "op": "add_segment", "table": name,
                        "segment_id": seg.segment_id, "part": part})
            tab = ts.tablet
            remaining = sum(
                len(t.active) + sum(len(f) for f in t.frozen)
                for t in getattr(tab, "partitions", None) or [tab])
        listener = self.flush_listener
        if listener is not None:
            # outside the engine lock: the throttle takes its own lock
            listener(name, remaining)
        return segs[0][1] if segs else None

    def _compact(self, name: str, level_filter, method: str):
        with self._lock:
            self._flush_pending_locked()
            ts = self.tables[name]
            old_ids = [s.segment_id for s in ts.tablet.segments
                       if level_filter(s.level)]
            segs = self._new_segs(getattr(ts.tablet, method)())
            if segs and self.root is not None:
                # only segments ACTUALLY gone may be logged as removed — a
                # partition that declined to compact keeps its segments
                after = {s.segment_id for s in ts.tablet.segments}
                removed = [i for i in old_ids if i not in after]
                self._persist_segs_locked(
                    name, segs,
                    lambda part, seg, i: {
                        "op": "replace_segments", "table": name,
                        "segment_id": seg.segment_id, "part": part,
                        "removed": removed if i == 0 else []})
            return segs[0][1] if segs else None

    def minor_compact(self, name: str):
        return self._compact(name, lambda lv: lv == 0, "minor_compact")

    def major_compact(self, name: str):
        return self._compact(name, lambda lv: True, "major_compact")

    # ------------------------------------------------------------------
    # scrub plane hooks (storage/scrub.py drives these; ≙ the medium
    # checker re-reading macro blocks + replica checksum repair)
    # ------------------------------------------------------------------
    def scrub_verify_table(self, table: str) -> dict:
        """Re-read every persisted segment of ``table`` FROM DISK and
        verify it (the in-memory copy may be healthy while the disk
        bytes rot — exactly the failure scrub exists to catch).  A
        corrupt file is quarantined and recorded in ``quarantined``;
        the in-memory segment keeps serving until repair swaps the set,
        so no read ever sees a missing-row window.

        Cost shape: the FIRST verification of a file decodes and
        re-checks every chunk/footer crc, then caches the raw file's
        crc64; later rounds re-read the bytes (rot detection demands
        it) but only crc the raw stream — full coverage at raw-IO cost,
        which is what makes a continuous scrub cadence affordable.
        -> {"checked", "bytes", "corrupt": [segment_id, ...]}"""
        from oceanbase_tpu.native import crc64
        from oceanbase_tpu.storage.integrity import CorruptionError

        with self._lock:
            ts = self.tables.get(table)
            if ts is None or self.root is None:
                return {"checked": 0, "bytes": 0, "corrupt": []}
            locs = [(s.segment_id, part)
                    for s, part in ts.tablet.segment_locations()]
        checked, nbytes, corrupt = 0, 0, []
        for seg_id, part in locs:
            path = self._segment_file(table, seg_id)
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError:
                continue  # never persisted / already quarantined
            checked += 1
            nbytes += len(raw)
            raw_crc = crc64(raw)
            with self._lock:
                known = self._verified_files.get(path)
            if known == raw_crc:
                continue  # bytes unchanged since full verification
            try:
                Segment.load(path)  # verify=True re-checks every crc
                with self._lock:
                    self._verified_files[path] = raw_crc
            except CorruptionError:
                with self._lock:
                    self._verified_files.pop(path, None)
                    if not os.path.exists(path):
                        continue  # repaired/quarantined concurrently
                    qpath = quarantine_file(path)
                    self.quarantined.append(
                        {"table": table, "segment_id": seg_id,
                         "part": part, "path": qpath})
                corrupt.append(seg_id)
        return {"checked": checked, "bytes": nbytes, "corrupt": corrupt}

    def rewrite_segment_from_memory(self, table: str, seg_id: int) -> bool:
        """Peer-less repair: if the in-memory copy of a quarantined
        segment is still resident (boot loaded it before the disk bytes
        rotted), re-persist it.  The cluster path prefers a peer refetch
        (storage/scrub.py) — this is the single-node fallback."""
        with self._lock:
            ts = self.tables.get(table)
            if ts is None or self.root is None:
                return False
            for seg, _part in ts.tablet.segment_locations():
                if seg.segment_id == seg_id:
                    self._save_segment(table, seg)
                    self.quarantined = [
                        q for q in self.quarantined
                        if not (q["table"] == table
                                and q["segment_id"] == seg_id)]
                    return True
            return False

    def repair_table_segments(self, table: str,
                              installed: list[dict]) -> dict:
        """Swap ``table``'s whole persisted+resident segment set for a
        peer baseline already staged and VERIFIED on local disk
        (storage/scrub.py downloads + checksums before calling).

        ``installed``: [{"segment_id", "level", "part", "src"}] where
        ``src`` is the staged file path.  Installed segments are
        re-minted under FRESH local ids — peer ids live in the peer's
        id space, and reusing them here could collide with local
        history, breaking the segment-files-are-write-once invariant
        incremental backups rely on.  Crash-safe order: files land
        under their new names first, then ONE slog record publishes
        the swap, then memory swaps and replaced files are deleted —
        a crash between any two steps boots to either the old set
        (fresh files orphaned) or the new set (replay applies the
        record)."""
        with self._lock:
            ts = self.tables[table]
            tab = ts.tablet
            old_ids = [s.segment_id for s, _ in tab.segment_locations()]
            segs = []
            for ent in installed:
                seg = Segment.load(ent["src"])
                parts = getattr(tab, "partitions", None)
                alloc = (parts[0] if parts else tab)._next_seg
                seg.segment_id = next(alloc)
                self._save_segment(table, seg)
                os.remove(ent["src"])
                segs.append((seg, ent.get("part")))
            self._log_meta({
                "op": "repair_segments", "table": table,
                "removed": old_ids,
                "installed": [[s.segment_id, s.level, p]
                              for s, p in segs]})
            tab.remove_segments(old_ids)
            for s, p in segs:
                tab.add_segment(s, p)
            for sid in old_ids:
                p = self._segment_file(table, sid)
                if os.path.exists(p):
                    os.remove(p)
                self._verified_files.pop(p, None)
            ts.tdef.row_count = tab.row_count_estimate()
            self.quarantined = [q for q in self.quarantined
                                if q["table"] != table]
            return {"removed": len(old_ids), "installed": len(segs)}


class StorageCatalog(Catalog):
    """Catalog backed by the storage engine: table_data() materializes a
    snapshot Relation from the tablet LSM with device-side caching."""

    def __init__(self, engine: StorageEngine, snapshot_fn=None,
                 config=None):
        super().__init__()
        self.engine = engine
        # snapshot provider (GTS reader); default: latest
        self.snapshot_fn = snapshot_fn or (lambda: 2**62)
        # bucket-policy knobs (enable_shape_buckets & co.) read live from
        # the tenant config when one is attached; defaults otherwise
        self.config = config
        # device-relation cache: decoded HBM-resident columns behind a
        # byte-bounded LRU (≙ ObKVGlobalCache block cache,
        # src/share/cache/ob_kv_storecache.h:91)
        from oceanbase_tpu.share.kvcache import KvCache

        self._cache = KvCache(limit_bytes=2 << 30, name="relation")
        # surface engine-persisted tables in the catalog
        for name, ts in engine.tables.items():
            self._defs[name] = ts.tdef
        self._load_externals()

    # -- external tables persist with the engine root -------------------
    def _externals_path(self):
        return (os.path.join(self.engine.root, "externals.json")
                if self.engine.root else None)

    def _load_externals(self):
        p = self._externals_path()
        if not p or not os.path.exists(p):
            return
        with open(p) as f:
            for name, e in json.load(f).items():
                cols = [ColumnDef(n, SqlType(TypeKind(k), pr, sc), nl)
                        for n, k, pr, sc, nl in e["columns"]]
                self._externals[name] = {
                    "tdef": TableDef(name, cols),
                    "location": e["location"], "format": e["format"],
                    "delimiter": e["delimiter"], "skip": e["skip"],
                    "cache": None}

    def _persist_externals(self):
        p = self._externals_path()
        if not p:
            return
        out = {}
        with self._lock:
            for name, e in self._externals.items():
                out[name] = {
                    "columns": [[c.name, c.dtype.kind.value,
                                 c.dtype.precision, c.dtype.scale,
                                 c.nullable]
                                for c in e["tdef"].columns],
                    "location": e["location"], "format": e["format"],
                    "delimiter": e["delimiter"], "skip": e["skip"]}
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, p)

    def register_external(self, tdef, location, **kw):
        super().register_external(tdef, location, **kw)
        self._persist_externals()

    # -- views persist in engine meta (slog + manifest) and replicate
    # through the DDL log stream like other logical DDL -----------------
    def create_view(self, name, sql, cols=None, or_replace=False):
        with self._lock:
            if self.has_table(name) or name in self._externals:
                raise ValueError(f"table {name} already exists")
            views = self.engine.meta.setdefault("views", {})
            if name in views and not or_replace:
                raise ValueError(f"view {name} already exists")
            views[name] = {"sql": sql, "cols": list(cols or [])}
            self.schema_version += 1
        self.engine._log_meta({"op": "create_view", "name": name,
                               "sql": sql, "cols": list(cols or [])})

    def drop_view(self, name) -> bool:
        with self._lock:
            if self.engine.meta.get("views", {}).pop(name, None) is None:
                return False
            self.schema_version += 1
        self.engine._log_meta({"op": "drop_view", "name": name})
        return True

    def view_def(self, name):
        # read through to engine meta: replicated DDL applied by the
        # follower's replay service becomes visible without invalidation
        return self.engine.meta.get("views", {}).get(name)

    def view_names(self):
        return sorted(self.engine.meta.get("views", {}))

    def drop_external(self, name: str) -> bool:
        out = super().drop_external(name)
        if out:
            self._persist_externals()
        return out

    def create_table(self, tdef: TableDef, if_not_exists: bool = False):
        with self._lock:
            # view-collision check inside the locked section (same
            # check-then-act closure as Catalog.create_table)
            if self.view_def(tdef.name) is not None:
                raise ValueError(f"view {tdef.name} already exists")
            if tdef.name in self._defs or tdef.name in self._externals:
                if if_not_exists:
                    return
                raise ValueError(f"table {tdef.name} already exists")
            self.engine.create_table(tdef)
            self._defs[tdef.name] = tdef
            self.schema_version += 1

    def drop_table(self, name: str, if_exists: bool = False):
        with self._lock:
            if name not in self._defs and name not in self.engine.tables:
                if if_exists:
                    return
                raise KeyError(name)
            self.engine.drop_table(name)
            self._defs.pop(name, None)
            self._cache.invalidate(name)
            self.schema_version += 1

    # -- engine is the source of truth for defs: WAL apply on a replica
    # installs/drops tables behind the catalog's back (net/node.py) ------
    def table_def(self, name: str):
        with self._lock:
            t = self._transients.get(name)
            if t is not None:
                return t[0]
            e = self._externals.get(name)
            if e is not None:
                return e["tdef"]
            ts = self.engine.tables.get(name)
            if ts is not None:
                self._defs[name] = ts.tdef
                return ts.tdef
            self._defs.pop(name, None)
            raise KeyError(f"unknown table {name}")

    def has_table(self, name: str) -> bool:
        with self._lock:
            return name in self._transients or \
                name in self._externals or name in self.engine.tables

    def tables(self) -> list[str]:
        with self._lock:
            return sorted([n for n in self.engine.tables
                           if not n.startswith("__idx__")]
                          + list(self._externals))

    def load_numpy(self, name, arrays, types=None, primary_key=None,
                   valids=None):
        from oceanbase_tpu.vector import from_numpy

        rel = from_numpy(arrays, types=types, valids=valids)
        cols = [ColumnDef(c, rel.columns[c].dtype,
                          nullable=rel.columns[c].valid is not None)
                for c in arrays]
        tdef = TableDef(name, cols, primary_key=primary_key or [],
                        row_count=rel.capacity)
        with self._lock:
            if name not in self.engine.tables:
                self.engine.create_table(tdef)
            # store raw (pre-dict-encode) arrays; strings re-encode on read
            store_arrays = {}
            store_valids = {}
            for c in arrays:
                store_arrays[c] = np.asarray(arrays[c])
                if rel.columns[c].dtype.kind == TypeKind.DATE:
                    store_arrays[c] = store_arrays[c].astype(np.int32)
                elif rel.columns[c].dtype.kind == TypeKind.DECIMAL:
                    store_arrays[c] = store_arrays[c].astype(np.int64)
                if valids and c in valids and valids[c] is not None:
                    store_valids[c] = valids[c]
            self.engine.bulk_load(name, store_arrays, store_valids or None)
            self._defs[name] = self.engine.tables[name].tdef
            from oceanbase_tpu.catalog import sampled_ndv
            from oceanbase_tpu.datatypes import TypeKind as _TK

            for c in cols:
                col = rel.columns[c.name]
                if col.sdict is not None:
                    nd = col.sdict.size
                elif col.dtype.kind == _TK.VECTOR:
                    nd = rel.capacity
                else:
                    nd = sampled_ndv(np.asarray(arrays[c.name]),
                                     rel.capacity)
                self._defs[name].ndv[c.name] = nd
            self.schema_version += 1
            self._cache.invalidate(name)

    # -- capacity bucketing (the static-shape policy) --------------------
    def _bucket_policy(self):
        """-> (enabled, floor, growth), read live from the attached
        config so ALTER SYSTEM toggles apply to the next
        materialization."""
        from oceanbase_tpu.vector.column import (
            DEFAULT_BUCKET_FLOOR,
            DEFAULT_BUCKET_GROWTH,
        )

        cfg = self.config
        if cfg is None:
            return True, DEFAULT_BUCKET_FLOOR, DEFAULT_BUCKET_GROWTH
        try:
            return (bool(cfg["enable_shape_buckets"]),
                    int(cfg["shape_bucket_floor"]),
                    float(cfg["shape_bucket_growth"]))
        except KeyError:
            return True, DEFAULT_BUCKET_FLOOR, DEFAULT_BUCKET_GROWTH

    def _bucketed(self, rel):
        """Pad a freshly materialized relation to its capacity bucket
        (dead lanes masked) so every snapshot inside one bucket presents
        the same static shape to the compiled-plan cache."""
        from oceanbase_tpu.vector.column import bucket_capacity

        enabled, floor, growth = self._bucket_policy()
        if not enabled:
            return rel
        return rel.pad_to(bucket_capacity(rel.capacity, floor, growth))

    def table_data(self, name):
        from oceanbase_tpu.vector import from_numpy

        if name in self._externals:
            return self._external_data(name)
        with self._lock:
            t = self._transients.get(name)
            if t is not None:
                return t[1]
            ts = self.engine.tables.get(name)
            if ts is None:
                raise KeyError(f"table {name} has no data")
            ver = ts.tablet.data_version
            hit = self._cache.get(name)
            if hit is not None and hit[0] == ver:
                return hit[1]
            snap = self.snapshot_fn()
            arrays, valids = ts.tablet.snapshot_arrays(snap)
            n = len(next(iter(arrays.values()))) if arrays else 0
            if n == 0:
                # static shapes need capacity >= 1: one all-dead row
                rel = self._empty_rel(ts)
            else:
                rel = self._bucketed(from_numpy(
                    arrays,
                    types={c.name: c.dtype for c in ts.tdef.columns},
                    valids={k: v for k, v in valids.items() if v is not None},
                ))
            # only cache snapshots that cover every persisted segment —
            # a snapshot below a segment's max_version would pin a
            # partial view that later (larger) snapshots must not reuse.
            # The cached value is the bucket-padded relation, so every
            # snapshot read inside the bucket (table_data_at included)
            # reuses one HBM-resident copy AND one compiled shape.
            seg_max = max((s.max_version
                           for s, _ in ts.tablet.segment_locations()),
                          default=0)
            if snap >= seg_max:
                from oceanbase_tpu.share.kvcache import relation_bytes

                self._cache.put(name, (ver, rel),
                                nbytes=relation_bytes(rel))
            # record the LIVE row count, not the padded capacity: the
            # binder's est_rows drives join/groupby capacity budgets and
            # spill decisions, which must not drift with pad lanes
            ts.tdef.row_count = n
            return rel

    def table_data_at(self, name, snapshot: int, tx_id: int = 0):
        """Snapshot read at an explicit version (+ own-tx writes) — the
        read path active transactions use."""
        from oceanbase_tpu.vector import from_numpy

        if name in self._externals:
            return self._external_data(name)
        with self._lock:
            # last-writer-wins is fine for transients (virtual tables are
            # monotonic snapshots), but the lookup itself must be locked
            t = self._transients.get(name)
        if t is not None:
            return t[1]
        ts = self.engine.tables[name]
        if tx_id == 0 and snapshot >= ts.tablet.max_commit_version():
            # no committed version is newer than the snapshot, so the
            # latest-commit read (which caches its device relation) sees
            # identical data — reuse it instead of re-decoding.  Re-check
            # after materializing: a commit landing mid-read would make
            # the latest view newer than the snapshot.
            rel = self.table_data(name)
            if snapshot >= ts.tablet.max_commit_version():
                return rel
        arrays, valids = ts.tablet.snapshot_arrays(snapshot, tx_id)
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return self._empty_rel(ts)
        # snapshot reads pad to the SAME bucket ladder: a transaction
        # re-reading a table it is growing keeps hitting one compiled
        # shape per bucket instead of one per statement
        return self._bucketed(from_numpy(
            arrays, types={c.name: c.dtype for c in ts.tdef.columns},
            valids={k: v for k, v in valids.items() if v is not None},
        ))

    def _empty_rel(self, ts):
        import jax.numpy as jnp

        from oceanbase_tpu.vector import Relation, from_numpy

        arrays, valids2 = {}, {}
        for c in ts.tdef.columns:
            arrays[c.name] = (np.array([""], dtype=object)
                              if c.dtype.is_string else
                              np.zeros(1, dtype=c.dtype.np_dtype))
            valids2[c.name] = np.array([False])
        rel = from_numpy(arrays,
                         types={c.name: c.dtype for c in ts.tdef.columns},
                         valids=valids2)
        rel = Relation(columns=rel.columns,
                       mask=jnp.zeros(1, dtype=jnp.bool_))
        # empty tables pad to the floor bucket too: the canonical OLTP
        # birth sequence (CREATE -> first INSERTs -> SELECT) then compiles
        # once for the whole first bucket instead of once for "empty"
        # plus once for "a few rows"
        return self._bucketed(rel)

    def set_data(self, name, rel):
        raise NotImplementedError(
            "StorageCatalog data flows through the engine (DML/bulk_load)")

    def invalidate(self, name: str):
        with self._lock:
            self._cache.invalidate(name)
