"""Column encodings + zone maps for immutable segments.

Reference analog: the cs_encoding suite (src/storage/blocksstable/
cs_encoding — dict/RLE/delta/bit-packed decoders with SIMD) and
index-block zone maps (src/storage/blocksstable/index_block).

Encodings (chosen per column chunk by a simple cost rule, ≙ the
reference's encoding selector):
- PLAIN     raw numpy array
- DICT      small-cardinality values -> uint{8,16,32} codes (the global
            string dictionary already lives at the table level; this is a
            second, per-segment code compression)
- RLE       run-length (values + run lengths), good for sorted/clustered
- DELTA     monotonic-ish int sequences -> base + small deltas (bit-width
            reduced)

Decode happens column-at-a-time into dense arrays — on TPU the decode is a
gather (DICT), repeat (RLE) or cumsum (DELTA), all vectorizable; round 1
decodes on host into the device upload path, the jnp decode kernels slot
in behind the same Segment.decode() interface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class ZoneMap:
    """Per-chunk min/max/null-count (≙ index-block aggregate row)."""

    vmin: object
    vmax: object
    null_count: int
    row_count: int

    def may_match_range(self, lo, hi) -> bool:
        """Can any value in [lo, hi] exist in this chunk?"""
        if self.null_count == self.row_count:
            return False
        if lo is not None and self.vmax is not None and self.vmax < lo:
            return False
        if hi is not None and self.vmin is not None and self.vmin > hi:
            return False
        return True


@dataclass
class EncodedColumn:
    encoding: str                  # plain | dict | rle | delta
    payload: dict                  # encoding-specific numpy arrays
    valid: Optional[np.ndarray]    # bool validity or None
    zone: ZoneMap
    n: int

    def nbytes(self) -> int:
        total = 0
        for v in self.payload.values():
            if isinstance(v, np.ndarray):
                total += v.nbytes
        if self.valid is not None:
            total += self.valid.nbytes
        return total


def _zone(arr: np.ndarray, valid) -> ZoneMap:
    n = len(arr)
    nulls = 0 if valid is None else int((~valid).sum())
    live = arr[valid] if valid is not None else arr
    if n == 0 or nulls == n or len(live) == 0:
        return ZoneMap(None, None, nulls, n)
    if arr.dtype == object or arr.dtype.kind in "US":
        # numpy 2.x has no min/max ufunc loop for strings
        vals = live.tolist()
        return ZoneMap(min(vals), max(vals), nulls, n)
    return ZoneMap(live.min(), live.max(), nulls, n)


def _best_uint(maxval: int) -> np.dtype:
    if maxval < 256:
        return np.dtype(np.uint8)
    if maxval < 65536:
        return np.dtype(np.uint16)
    return np.dtype(np.uint32)


def encode_column(arr: np.ndarray, valid: np.ndarray | None) -> EncodedColumn:
    """Pick an encoding by measured size (≙ encoding selector cost rule)."""
    n = len(arr)
    zone = _zone(arr, valid)
    if n == 0 or arr.dtype == object or arr.ndim > 1:
        # object strings and [n,d] vector embeddings store plain
        return EncodedColumn("plain", {"data": arr}, valid, zone, n)

    candidates: list[tuple[int, str, dict]] = [
        (arr.nbytes, "plain", {"data": arr})
    ]

    # RLE
    if n > 1:
        change = np.empty(n, dtype=bool)
        change[0] = True
        np.not_equal(arr[1:], arr[:-1], out=change[1:])
        n_runs = int(change.sum())
        if n_runs * (arr.itemsize + 4) < arr.nbytes // 2:
            starts = np.nonzero(change)[0]
            lengths = np.diff(np.append(starts, n)).astype(np.uint32)
            candidates.append(
                (n_runs * (arr.itemsize + 4), "rle",
                 {"values": arr[starts], "lengths": lengths})
            )

    # DICT (per-segment)
    if arr.dtype.kind in "iu":
        uniq = np.unique(arr)
        if len(uniq) <= max(2, n // 4) and len(uniq) < 2**32:
            codes = np.searchsorted(uniq, arr).astype(_best_uint(len(uniq)))
            sz = uniq.nbytes + codes.nbytes
            candidates.append((sz, "dict", {"values": uniq, "codes": codes}))

    # DELTA (ints with small spread of consecutive differences)
    if arr.dtype.kind in "iu" and n > 1:
        d = np.diff(arr.astype(np.int64))
        if len(d) and d.min() >= np.iinfo(np.int32).min // 2 and \
                d.max() <= np.iinfo(np.int32).max // 2:
            spread = int(d.max() - d.min()) if len(d) else 0
            dt = (np.int8 if spread < 127 and abs(d).max() < 127 else
                  np.int16 if spread < 32000 and abs(d).max() < 32000 else
                  np.int32)
            deltas = d.astype(dt)
            sz = 8 + deltas.nbytes
            candidates.append(
                (sz, "delta", {"base": np.int64(arr[0]), "deltas": deltas})
            )

    # VARINT (delta+zigzag+LEB128 via the native codec): byte-granular,
    # often the smallest for int64 key/date columns
    if arr.dtype.kind in "iu" and arr.itemsize == 8 and n > 0:
        from oceanbase_tpu.native import delta_varint_encode

        buf = np.frombuffer(delta_varint_encode(arr), dtype=np.uint8)
        candidates.append((buf.nbytes, "varint", {"buf": buf}))

    sz, enc, payload = min(candidates, key=lambda c: c[0])
    return EncodedColumn(enc, payload, valid, zone, n)


def decode_column(ec: EncodedColumn, out_dtype=None) -> np.ndarray:
    if ec.encoding == "plain":
        data = ec.payload["data"]
    elif ec.encoding == "rle":
        data = np.repeat(ec.payload["values"], ec.payload["lengths"])
    elif ec.encoding == "dict":
        data = ec.payload["values"][ec.payload["codes"]]
    elif ec.encoding == "delta":
        base = ec.payload["base"]
        deltas = ec.payload["deltas"].astype(np.int64)
        data = np.concatenate([[0], np.cumsum(deltas)]) + base
    elif ec.encoding == "varint":
        from oceanbase_tpu.native import delta_varint_decode

        data = delta_varint_decode(ec.payload["buf"].tobytes(), ec.n)
    else:  # pragma: no cover
        raise ValueError(ec.encoding)
    if out_dtype is not None and data.dtype != out_dtype:
        data = data.astype(out_dtype)
    return data
