"""Data-integrity primitives: checksums at every persistence boundary.

Reference analog: the per-macro-block / per-micro-block checksums the
blocksstable layer verifies on every read plus the per-replica column
checksums compared at major freeze (src/storage/ob_sstable_struct.h
ObSSTableColumnChecksum* — replica checksum verification), reduced to
three primitives:

- ``CorruptionError``: the ONE typed error every read path raises when
  stored or shipped bytes fail their checksum — callers either repair
  (scrub plane, DTL slice fallback) or fail loudly; poisoned rows are
  never served.
- byte digests (crc64, the PALF log's polynomial) for physical
  artifacts: segment chunks/footers, manifests, slog records, rebuild
  transfer chunks, DTL exchange payloads.
- an order- and layout-independent **logical table digest** for
  cross-replica comparison: replicas flush memtables on their own
  schedules, so their segment FILES differ bit-for-bit while holding the
  same rows — the scrub plane compares content, not files
  (``storage/scrub.py``; ≙ replica checksum at major freeze).
"""

from __future__ import annotations

import os
import time

import numpy as np

from oceanbase_tpu.native import crc64

#: default quarantine (.corrupt) retention bounds shared by every
#: quarantining boundary (WAL dir, data/segments dir): keep the newest
#: few for forensics, never grow a directory without bound
QUARANTINE_KEEP = 4
QUARANTINE_MAX_AGE_S = 7 * 24 * 3600.0


def prune_quarantine(dirpath: str, keep: int = QUARANTINE_KEEP,
                     max_age_s: float = QUARANTINE_MAX_AGE_S):
    """Cap .corrupt quarantine files in ``dirpath`` by count AND age
    (newest first)."""
    try:
        names = sorted(
            (n for n in os.listdir(dirpath) if ".corrupt" in n),
            key=lambda n: os.path.getmtime(os.path.join(dirpath, n)),
            reverse=True)
    except OSError:
        return
    now = time.time()
    for i, n in enumerate(names):
        p = os.path.join(dirpath, n)
        try:
            if i >= keep or now - os.path.getmtime(p) > max_age_s:
                os.remove(p)
        except OSError:
            continue


class CorruptionError(RuntimeError):
    """Stored or shipped bytes failed an integrity checksum.

    Raised instead of returning poisoned rows; carries enough context
    (artifact kind + path/table) for the scrub plane to quarantine and
    repair the artifact."""

    def __init__(self, message: str, kind: str = "", path: str = ""):
        super().__init__(message)
        self.kind = kind
        self.path = path


# ---------------------------------------------------------------------------
# physical digests (crc64 over bytes)
# ---------------------------------------------------------------------------


def bytes_crc(data: bytes) -> int:
    return crc64(bytes(data))


def arrays_crc(arrays: dict, valids: dict | None = None) -> int:
    """Digest of a {name -> numpy array} payload (plus optional validity
    masks), independent of dict insertion order.  Used for DTL exchange
    replies: the fragment executor stamps its reply, the coordinator
    verifies before merging."""
    crc = 0
    for name in sorted(arrays):
        a = np.asarray(arrays[name])
        if a.dtype == object or a.dtype.kind in "US":
            body = "\x00".join("" if x is None else str(x)
                               for x in a.tolist()).encode("utf-8")
        else:
            body = np.ascontiguousarray(a).tobytes()
        crc = crc64(body, seed=crc64(name.encode(), seed=crc))
        v = (valids or {}).get(name)
        if v is not None:
            crc = crc64(np.ascontiguousarray(
                np.asarray(v, dtype=bool)).tobytes(), seed=crc)
    return crc


def chunk_crc(payload: dict, valid, encoding: str, n: int) -> int:
    """Digest of one encoded column chunk (EncodedColumn wire state):
    the encoding tag, row count, every payload buffer in key order, and
    the validity bitmap.  Computed at save time and re-computed from the
    loaded buffers at load time (storage/segment.py)."""
    crc = crc64(f"{encoding}:{n}".encode())
    for k in sorted(payload):
        v = np.asarray(payload[k])
        if v.dtype == object or v.dtype.kind in "US":
            body = "\x00".join("" if x is None else str(x)
                               for x in v.tolist()).encode("utf-8")
        else:
            body = np.ascontiguousarray(v).tobytes()
        crc = crc64(body, seed=crc64(k.encode(), seed=crc))
    if valid is not None:
        crc = crc64(np.ascontiguousarray(
            np.asarray(valid, dtype=bool)).tobytes(), seed=crc)
    return crc


# ---------------------------------------------------------------------------
# logical table digest (cross-replica scrub)
# ---------------------------------------------------------------------------


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized over uint64 (the same mixer the
    DTL slice hash uses — px/dtl.py — duplicated here so the storage
    layer never imports the execution stack)."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _col_hash(vals: np.ndarray) -> np.ndarray:
    if vals.dtype.kind in "iub":
        return _mix64(vals.astype(np.int64).astype(np.uint64))
    if vals.dtype.kind == "f":
        return _mix64(vals.astype(np.float64).view(np.uint64))
    import zlib

    return _mix64(np.fromiter(
        (zlib.crc32(str(v).encode("utf-8", "surrogatepass"))
         for v in vals), np.uint64, len(vals)))


def table_digest(arrays: dict, valids: dict | None = None) -> dict:
    """-> {"rows": n, "crc": int} — an ORDER-INDEPENDENT digest of a
    table snapshot: per-row hashes (mixing column name + value + NULL
    bit) XOR-reduced, so two replicas whose physically different
    segment layouts enumerate the same rows in different orders agree
    bit-for-bit.  NULL lanes hash by name only (their filler values are
    replica-local noise and must not contribute)."""
    n = len(next(iter(arrays.values()))) if arrays else 0
    if n == 0:
        return {"rows": 0, "crc": 0}
    h = np.zeros(n, dtype=np.uint64)
    for name in sorted(arrays):
        a = np.asarray(arrays[name])
        if a.ndim > 1:
            ch = np.zeros(n, dtype=np.uint64)
            for j in range(a.shape[1]):
                ch = _mix64(ch ^ _col_hash(a[:, j]))
        else:
            ch = _col_hash(a)
        name_h = np.uint64(crc64(name.encode()))
        v = (valids or {}).get(name)
        if v is not None:
            ch = np.where(np.asarray(v, dtype=bool), ch, np.uint64(0))
        h ^= _mix64(ch ^ name_h)
    row_h = _mix64(h)
    crc = int(np.bitwise_xor.reduce(row_h))
    return {"rows": int(n), "crc": crc}
