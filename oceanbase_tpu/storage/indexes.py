"""Transactional secondary-index maintenance.

Reference analog: the DML write path updating local index tablets in the
same transaction as the data table (src/storage/ob_dml_running_ctx +
index-table DAS write tasks; uniqueness via
src/storage/ob_rowkey_duplication_checker-style lookups).

Every index is an index TABLE whose key is (index columns + primary-key
columns).  Maintenance runs inside ``TransService.write`` BEFORE the base
row is written: the pre-image is read through the LSM (own-transaction
writes visible), stale entries are tombstoned and new entries inserted
via recursive ``svc.write`` calls — so index writes ride the same WAL
redo, participant tracking, statement rollback, and recovery replay as
any other write, for free.
"""

from __future__ import annotations

from oceanbase_tpu.storage.lookup import point_lookup, range_rows


def maintain_indexes(svc, engine, tx, table: str, tablet, key: tuple,
                     op: str, values: dict):
    """Write index-table entries matching a base-table write.

    MUST be called before the base ``tablet.write`` so the pre-image is
    still the old row.  ``values`` must carry every indexed column for
    insert/update ops (the session DML paths write full rows)."""
    ts = engine.tables.get(table)
    if ts is None or not ts.tdef.indexes:
        return
    old = point_lookup(tablet, key, tx.snapshot, tx.tx_id)
    newvals = dict(values)
    for kc, kv in zip(tablet.key_cols, key):
        if newvals.get(kc) is None:
            newvals[kc] = kv
    for ix in ts.tdef.indexes:
        istore = engine.tables.get(ix.storage_table)
        if istore is None:  # index dropped concurrently
            continue
        itab = istore.tablet
        ikey_cols = itab.key_cols
        old_ekey = (tuple(old.get(c) for c in ikey_cols)
                    if old is not None else None)
        if op == "delete":
            if old_ekey is not None:
                svc.write(tx, ix.storage_table, itab, old_ekey, "delete",
                          dict(zip(ikey_cols, old_ekey)))
            continue
        new_ekey = tuple(newvals.get(c) for c in ikey_cols)
        if old_ekey == new_ekey:
            continue  # indexed columns unchanged
        if ix.unique and all(newvals.get(c) is not None
                             for c in ix.columns):
            _check_unique(svc, tx, ix, itab, new_ekey, ikey_cols)
        if old_ekey is not None:
            svc.write(tx, ix.storage_table, itab, old_ekey, "delete",
                      dict(zip(ikey_cols, old_ekey)))
        svc.write(tx, ix.storage_table, itab, new_ekey, "insert",
                  dict(zip(ikey_cols, new_ekey)))


def _check_unique(svc, tx, ix, itab, new_ekey: tuple, ikey_cols):
    """MySQL unique-index semantics: no two live rows may share non-NULL
    values on all index columns (rows with any NULL never conflict).
    Own-transaction writes are visible to the check.

    Two layers (≙ the reference locking the index rowkey during the
    duplicate check):
    1. snapshot check — committed/own-tx live entries with the same
       index-column prefix but a different base row -> DuplicateKey;
    2. dirty check — another transaction's UNCOMMITTED entry with the
       same prefix -> WriteConflict (fail fast).  The index-table keys of
       the two writers differ in their pk suffix, so the memtable's
       write-write conflict detection alone would let both commit; this
       prefix-level check closes that race."""
    n_ix = len(ix.columns)
    prefix = new_ekey[:n_ix]
    ranges = {c: (v, v) for c, v in zip(ix.columns, prefix)}
    arrays, _valids = range_rows(itab, ranges, tx.snapshot, tx.tx_id,
                                 columns=list(ikey_cols))
    m = len(next(iter(arrays.values()))) if arrays else 0
    for i in range(m):
        ek = tuple(arrays[c][i].item()
                   if hasattr(arrays[c][i], "item") else arrays[c][i]
                   for c in ikey_cols)
        if ek[n_ix:] != new_ekey[n_ix:]:  # a different base row
            from oceanbase_tpu.tx.errors import DuplicateKey

            raise DuplicateKey(
                f"duplicate entry {prefix} for unique index {ix.name}")
    from oceanbase_tpu.storage.lookup import _base_tablets

    for t in _base_tablets(itab):
        for mt in [t.active] + t.frozen:
            with mt._lock:
                for key, head in mt._rows.items():
                    if key[:n_ix] != prefix or key == new_ekey:
                        continue
                    if head.commit_version == 0 and \
                            head.tx_id != tx.tx_id and \
                            head.op != "delete":
                        from oceanbase_tpu.tx.errors import WriteConflict

                        raise WriteConflict(
                            f"unique index {ix.name} value {prefix} "
                            f"being inserted by tx {head.tx_id}")
