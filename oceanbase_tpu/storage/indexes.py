"""Transactional secondary-index maintenance.

Reference analog: the DML write path updating local index tablets in the
same transaction as the data table (src/storage/ob_dml_running_ctx +
index-table DAS write tasks; uniqueness via
src/storage/ob_rowkey_duplication_checker-style lookups).

Every index is an index TABLE whose key is (index columns + primary-key
columns).  Maintenance runs inside ``TransService.write`` BEFORE the base
row is written: the pre-image is read through the LSM (own-transaction
writes visible), stale entries are tombstoned and new entries inserted
via recursive ``svc.write`` calls — so index writes ride the same WAL
redo, participant tracking, statement rollback, and recovery replay as
any other write, for free.
"""

from __future__ import annotations

import threading

from oceanbase_tpu.storage.lookup import point_lookup, range_rows


class IndexKeyLocks:
    """In-flight unique-index rowkey locks.

    ≙ the reference holding an index-rowkey lock across the duplicate
    check (ObRowkeyDuplicationChecker path): a writer inserting value V
    into a unique index takes the (index, V) lock before checking and
    holds it until its transaction ends, so (a) two concurrent inserters
    of V serialize (the loser fails fast with WriteConflict, matching
    this build's no-wait conflict model), and (b) the duplicate check is
    atomic with respect to commit — no window where another transaction
    commits V between our check and our commit."""

    def __init__(self):
        self._lock = threading.Lock()
        self._held: dict[tuple, int] = {}    # (index table, prefix) -> tx
        # tx -> {key: stmt_seq of FIRST acquisition} (statement rollback
        # must release only locks its statement introduced)
        self._by_tx: dict[int, dict] = {}

    def acquire(self, table: str, prefix: tuple, tx_id: int,
                stmt_seq: int = 0):
        from oceanbase_tpu.tx.errors import WriteConflict

        k = (table, prefix)
        with self._lock:
            holder = self._held.get(k)
            if holder is not None and holder != tx_id:
                raise WriteConflict(
                    f"unique index {table} value {prefix} being "
                    f"inserted by tx {holder}")
            self._held[k] = tx_id
            self._by_tx.setdefault(tx_id, {}).setdefault(k, stmt_seq)

    def release_all(self, tx_id: int):
        with self._lock:
            for k in self._by_tx.pop(tx_id, {}):
                if self._held.get(k) == tx_id:
                    del self._held[k]

    def release_stmt(self, tx_id: int, min_stmt_seq: int):
        """Release locks first acquired at stmt_seq >= min_stmt_seq (the
        rolled-back statement's acquisitions; earlier statements keep
        theirs — their index entries are still pending commit)."""
        with self._lock:
            mine = self._by_tx.get(tx_id)
            if not mine:
                return
            for k in [k for k, s in mine.items() if s >= min_stmt_seq]:
                del mine[k]
                if self._held.get(k) == tx_id:
                    del self._held[k]


def maintain_indexes(svc, engine, tx, table: str, tablet, key: tuple,
                     op: str, values: dict):
    """Write index-table entries matching a base-table write.

    MUST be called before the base ``tablet.write`` so the pre-image is
    still the old row.  ``values`` must carry every indexed column for
    insert/update ops (the session DML paths write full rows)."""
    ts = engine.tables.get(table)
    if ts is None or not ts.tdef.indexes:
        return
    old = point_lookup(tablet, key, tx.snapshot, tx.tx_id)
    newvals = dict(values)
    for kc, kv in zip(tablet.key_cols, key):
        if newvals.get(kc) is None:
            newvals[kc] = kv
    for ix in ts.tdef.indexes:
        istore = engine.tables.get(ix.storage_table)
        if istore is None:  # index dropped concurrently
            continue
        itab = istore.tablet
        ikey_cols = itab.key_cols
        old_ekey = (tuple(old.get(c) for c in ikey_cols)
                    if old is not None else None)
        if op == "delete":
            if old_ekey is not None:
                svc.write(tx, ix.storage_table, itab, old_ekey, "delete",
                          dict(zip(ikey_cols, old_ekey)))
            continue
        new_ekey = tuple(newvals.get(c) for c in ikey_cols)
        if old_ekey == new_ekey:
            continue  # indexed columns unchanged
        if ix.unique and all(newvals.get(c) is not None
                             for c in ix.columns):
            _check_unique(svc, tx, ix, itab, new_ekey, ikey_cols)
        if old_ekey is not None:
            svc.write(tx, ix.storage_table, itab, old_ekey, "delete",
                      dict(zip(ikey_cols, old_ekey)))
        svc.write(tx, ix.storage_table, itab, new_ekey, "insert",
                  dict(zip(ikey_cols, new_ekey)))


def _check_unique(svc, tx, ix, itab, new_ekey: tuple, ikey_cols):
    """MySQL unique-index semantics: no two live rows may share non-NULL
    values on all index columns (rows with any NULL never conflict).
    Own-transaction writes are visible to the check.

    Two layers (≙ the reference locking the index rowkey during the
    duplicate check):
    1. rowkey lock — the (index, value) lock serializes concurrent
       inserters of the same value; an uncommitted rival holds it, so we
       fail fast with WriteConflict instead of scanning memtables;
    2. committed check — read the index range at the LATEST committed
       state (not the transaction snapshot: an entry committed after our
       snapshot by an already-finished transaction must still conflict);
       any live entry with the same index-column prefix but a different
       base row -> DuplicateKey.  The lock from layer 1 is held until
       our transaction ends, so no rival can slip a commit in between
       this check and ours."""
    from oceanbase_tpu.storage.lookup import _INF

    n_ix = len(ix.columns)
    prefix = new_ekey[:n_ix]
    svc.index_locks.acquire(ix.storage_table, prefix, tx.tx_id,
                            stmt_seq=tx.stmt_seq)
    ranges = {c: (v, v) for c, v in zip(ix.columns, prefix)}
    # read at _INF = the latest committed state plus own-tx writes (own
    # uncommitted versions rank exactly _INF in _tablet_newest; sharing
    # the constant keeps that visibility invariant in one place)
    arrays, _valids = range_rows(itab, ranges, _INF, tx.tx_id,
                                 columns=list(ikey_cols))
    m = len(next(iter(arrays.values()))) if arrays else 0
    for i in range(m):
        ek = tuple(arrays[c][i].item()
                   if hasattr(arrays[c][i], "item") else arrays[c][i]
                   for c in ikey_cols)
        if ek[n_ix:] != new_ekey[n_ix:]:  # a different base row
            from oceanbase_tpu.tx.errors import DuplicateKey

            raise DuplicateKey(
                f"duplicate entry {prefix} for unique index {ix.name}")
