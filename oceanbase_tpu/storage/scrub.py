"""Background scrub plane: continuous local re-verification plus
cross-replica logical checksums, with automatic quarantine + repair.

Reference analog: the medium compaction checker re-reading macro blocks
against their checksums plus the replica-checksum verification at major
freeze (src/storage/ob_sstable_struct.h ObSSTableColumnChecksum — all
replicas of a tablet must agree on column checksums before a major
version is published).  Here:

1. **Local pass** — every persisted segment file is re-read FROM DISK
   and its chunk/footer crc64s verified (`StorageEngine.
   scrub_verify_table`).  The resident copy may be healthy while the
   disk bytes rot; a corrupt file quarantines (moved aside, recorded)
   while the resident segment keeps serving — no missing-row window.
2. **Cross-replica pass** — every replica hashes each table's rows at
   one common snapshot into an order/layout-independent digest
   (`integrity.table_digest`; replicas flush on their own schedules, so
   their segment FILES legitimately differ) over the idempotent
   ``scrub.checksum`` verb.  Majority wins: a local minority digest
   marks the table for repair; a split vote only reports.
3. **Repair** — a quarantined-at-boot, scrub-detected, or
   minority-mismatch table refetches a freshly checkpointed peer
   baseline over PR 6's chunked ``rebuild.fetch_meta`` /
   ``rebuild.fetch_segments`` verbs (every chunk + file crc-verified,
   staged, `Segment.load`-verified) and swaps atomically
   (`StorageEngine.repair_table_segments`), then re-verifies digest
   parity against the peer — detect → quarantine → repair → parity
   with no operator in the loop.  Single-node fallback: rewrite from
   the healthy resident copy.

Surfaces: ``gv$scrub`` rows per event, ``scrub.*`` metrics,
``scrub.verify`` trace spans.  Knobs: ``enable_scrub`` /
``scrub_interval_s`` (net/node.py runs the loop).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from collections import deque

from oceanbase_tpu.server import admission as qadmission
from oceanbase_tpu.server import metrics as qmetrics
from oceanbase_tpu.server import trace as qtrace
from oceanbase_tpu.storage.integrity import CorruptionError, table_digest

log = logging.getLogger(__name__)

MAX_EVENTS = 512
#: bounded repair attempts per table per scrub round
REPAIR_RETRIES = 2
#: quiet rounds (local digests unchanged) skip the cross-replica RPC
#: fan-out; a full vote still runs at least every this-many rounds
VOTE_EVERY = 10

qmetrics.declare("scrub.runs", "counter", "scrub rounds completed")
qmetrics.declare("scrub.segments_verified", "counter",
                 "persisted segments re-read + checksum-verified")
qmetrics.declare("scrub.bytes_verified", "counter",
                 "persisted bytes re-read by the local pass")
qmetrics.declare("scrub.corruptions", "counter",
                 "local checksum failures detected (label: kind)")
qmetrics.declare("scrub.digest_mismatches", "counter",
                 "tables where this replica's logical digest lost the "
                 "cross-replica majority vote")
qmetrics.declare("scrub.repairs", "counter",
                 "table segment-set repairs completed (label: source)")
qmetrics.declare("scrub.repair_bytes", "counter",
                 "bytes fetched from peers by scrub repairs")
qmetrics.declare("scrub.repair_failures", "counter",
                 "repair attempts that exhausted their retry budget")
qmetrics.declare("scrub.verify_s", "histogram",
                 "whole scrub-round wall time", unit="s")


class ScrubLagging(RuntimeError):
    """Replica has not applied up to the requested point — its digest
    would compare a stale row set (the caller skips it this round)."""


#: tables whose content is NODE-LOCAL by design (materialized lazily by
#: a session, never WAL-replicated) — replicas legitimately disagree on
#: them, so the cross-replica vote must not compare them
SCRUB_SKIP = {"__dual__"}


class ScrubState:
    """Bounded per-node scrub event log feeding gv$scrub."""

    def __init__(self, node_id: int = 0, max_events: int = MAX_EVENTS):
        self.node_id = node_id
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def record(self, phase: str, *, table: str = "", segments: int = 0,
               nbytes: int = 0, peer: int = -1, mismatches: int = 0,
               elapsed_s: float = 0.0, note: str = ""):
        ev = {"ts": time.time(), "node_id": self.node_id, "table": table,
              "phase": phase, "segments": int(segments),
              "bytes": int(nbytes), "peer": int(peer),
              "mismatches": int(mismatches),
              "elapsed_s": float(elapsed_s), "note": note}
        with self._lock:
            self._events.append(ev)
        return ev

    def rows(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def last(self, phase: str) -> dict | None:
        with self._lock:
            for ev in reversed(self._events):
                if ev["phase"] == phase:
                    return ev
        return None


class Scrubber:
    """One node's scrub driver (NodeServer owns it; the ``scrub.run``
    verb and the periodic loop both funnel into ``run_once``)."""

    def __init__(self, node, state: ScrubState | None = None):
        self.node = node
        self.state = state if state is not None \
            else ScrubState(node.node_id)
        # one scrub round at a time: the loop, the scrub.run verb and a
        # test driver may race — later callers skip instead of stacking
        self._run_lock = threading.Lock()
        # logical-digest cache keyed on the tablet's data_version: a
        # quiet table's digest cannot change (every commit / segment
        # swap bumps the version), so steady-state rounds skip the
        # snapshot + hash entirely.  Deliberate trade: rot reaching the
        # RESIDENT arrays without any code path bumping data_version
        # re-hashes only when the table next changes; the disk pass
        # (scrub_verify_table) re-checks files every round regardless.
        self._digest_cache: dict[str, tuple[int, dict]] = {}
        self._cache_lock = threading.Lock()
        # cross-replica vote damping: when the LOCAL digests are
        # byte-identical to the last completed vote, peers can only
        # disagree if THEY rotted — which their own rounds detect — so
        # quiet rounds skip the RPC fan-out and a full vote still runs
        # every VOTE_EVERY rounds as a backstop
        self._last_vote: dict | None = None
        self._rounds_since_vote = 0

    # ------------------------------------------------------------------
    # the scrub.checksum verb (server side — pure read, idempotent)
    # ------------------------------------------------------------------
    def checksum_handler(self, snapshot=None, applied_lsn: int = 0,
                         tables=None):
        """Per-table logical digests of the local replica at
        ``snapshot``.  ``applied_lsn`` is the coordinator's WAL apply
        point when it chose the snapshot: a replica behind it may be
        missing rows visible at the snapshot and must refuse (the
        coordinator skips it this round; a replica AHEAD is fine — the
        MVCC snapshot filter hides newer versions)."""
        node = self.node
        local_lsn = node.palf.replica.applied_lsn
        if local_lsn < int(applied_lsn):
            raise ScrubLagging(
                f"node {node.node_id} applied lsn {local_lsn} < "
                f"{applied_lsn}")
        snap = int(snapshot) if snapshot else node.tx.gts.current()
        names = (list(tables) if tables
                 else sorted(node.engine.tables))
        out = {}
        for name in names:
            if name in SCRUB_SKIP:
                continue
            ts = node.engine.tables.get(name)
            if ts is None:
                continue
            tab = ts.tablet
            ver = tab.data_version
            with self._cache_lock:
                hit = self._digest_cache.get(name)
            # cache validity: nothing changed since compute AND both
            # snapshots cover every commit — visibility is identical
            if hit is not None and hit[0] == ver \
                    and snap >= tab.max_commit_version():
                out[name] = hit[1]
                continue
            arrays, valids = tab.snapshot_arrays(snap)
            d = table_digest(arrays, valids)
            out[name] = d
            if snap >= tab.max_commit_version() \
                    and tab.data_version == ver:
                with self._cache_lock:
                    self._digest_cache[name] = (ver, d)
        return {"node_id": node.node_id, "snapshot": snap,
                "applied_lsn": local_lsn, "tables": out}

    # ------------------------------------------------------------------
    # one scrub round
    # ------------------------------------------------------------------
    def run_once(self) -> dict:
        if not self._run_lock.acquire(blocking=False):
            return {"skipped": "scrub already running"}
        try:
            return self._run_locked()
        finally:
            self._run_lock.release()

    def _run_locked(self) -> dict:
        node = self.node
        m0 = time.monotonic()
        summary = {"node_id": node.node_id, "tables": 0, "segments": 0,
                   "bytes": 0, "corrupt": [], "mismatch": [],
                   "repaired": [], "failed": [], "discarded": False}
        with qtrace.span("scrub.verify", node=node.node_id) as sp:
            need_repair: dict[str, str] = {}  # table -> reason
            # segments quarantined at boot wait for the first round
            for q in list(node.engine.quarantined):
                need_repair.setdefault(q["table"], "boot_quarantine")
            # ---- local pass: re-read + verify every persisted segment
            for name in sorted(node.engine.tables):
                r = node.engine.scrub_verify_table(name)
                summary["tables"] += 1
                summary["segments"] += r["checked"]
                summary["bytes"] += r["bytes"]
                for seg_id in r["corrupt"]:
                    summary["corrupt"].append([name, seg_id])
                    need_repair.setdefault(name, "checksum")
                    qmetrics.inc("scrub.corruptions", kind="segment")
                    self.state.record("quarantine", table=name,
                                      segments=1,
                                      note=f"segment {seg_id} checksum")
            qmetrics.inc("scrub.segments_verified", summary["segments"])
            qmetrics.inc("scrub.bytes_verified", summary["bytes"])
            # ---- cross-replica pass: logical digests, majority wins
            mism = self._cross_replica_pass(summary)
            for name in mism:
                need_repair.setdefault(name, "digest_minority")
            # ---- repair: quarantined / corrupt / minority tables
            for name, reason in sorted(need_repair.items()):
                qadmission.checkpoint()  # KILL/deadline between repairs
                ok = False
                for _attempt in range(REPAIR_RETRIES):
                    if self._repair_table(name, reason):
                        ok = True
                        break
                if ok:
                    summary["repaired"].append(name)
                else:
                    summary["failed"].append(name)
                    qmetrics.inc("scrub.repair_failures")
                    self.state.record("error", table=name,
                                      note=f"repair failed ({reason})")
            elapsed = time.monotonic() - m0
            sp.tags.update(tables=summary["tables"],
                           segments=summary["segments"],
                           corrupt=len(summary["corrupt"]),
                           repaired=len(summary["repaired"]))
            self.state.record(
                "verify", segments=summary["segments"],
                nbytes=summary["bytes"],
                mismatches=len(summary["corrupt"])
                + len(summary["mismatch"]),
                elapsed_s=elapsed,
                note=(f"tables={summary['tables']}"
                      + (" discarded" if summary["discarded"] else "")))
        qmetrics.inc("scrub.runs")
        qmetrics.observe("scrub.verify_s", elapsed)
        summary["elapsed_s"] = elapsed
        return summary

    def _cross_replica_pass(self, summary: dict) -> list[str]:
        """Compare per-table logical digests across replicas; -> tables
        where the LOCAL digest lost the majority vote."""
        node = self.node
        peers = getattr(node, "peers", None)
        if not peers:
            return []
        from oceanbase_tpu.net.rpc import RpcError

        lsn = node.palf.replica.applied_lsn
        local = self.checksum_handler()
        self._rounds_since_vote += 1
        if self._last_vote == local["tables"] and \
                self._rounds_since_vote < VOTE_EVERY:
            return []  # quiet: nothing changed since the last vote
        snap = local["snapshot"]
        votes: dict[int, dict] = {node.node_id: local["tables"]}
        health = getattr(node, "health", None)
        for pid in sorted(peers):
            qadmission.checkpoint()  # KILL/deadline between peer votes
            if health is not None and health.state(pid) != "up":
                continue
            try:
                r = peers[pid].call("scrub.checksum", snapshot=snap,
                                    applied_lsn=lsn)
                votes[pid] = r["tables"]
            except (OSError, RpcError):
                continue  # lagging or unreachable: skip this round
        if len(votes) < 2:
            return []
        if node.palf.replica.applied_lsn != lsn:
            # a commit landed mid-round: its entry postdates the lag
            # guard, so replicas could legitimately disagree on its
            # visibility — discard the round (same tear-guard as the
            # DTL exchange) instead of chasing a phantom mismatch
            summary["discarded"] = True
            return []
        self._last_vote = local["tables"]
        self._rounds_since_vote = 0
        minority: list[str] = []
        for name, mine in sorted(local["tables"].items()):
            tally: dict[tuple, int] = {}
            for tabs in votes.values():
                d = tabs.get(name)
                if d is not None:
                    key = (d["rows"], d["crc"])
                    tally[key] = tally.get(key, 0) + 1
            if not tally:
                continue
            best, n_best = max(tally.items(), key=lambda kv: kv[1])
            my_key = (mine["rows"], mine["crc"])
            if my_key == best:
                continue
            summary["mismatch"].append(name)
            if n_best * 2 > sum(tally.values()):
                # a real majority disagrees with us: we are the rot
                minority.append(name)
                qmetrics.inc("scrub.digest_mismatches")
                self.state.record(
                    "mismatch", table=name,
                    mismatches=sum(tally.values()) - n_best,
                    note=f"local={my_key} majority={best}")
            else:
                self.state.record("mismatch", table=name,
                                  note=f"split vote {tally}")
        return minority

    # ------------------------------------------------------------------
    # repair
    # ------------------------------------------------------------------
    def _repair_table(self, table: str, reason: str) -> bool:
        node = self.node
        peers = getattr(node, "peers", None) or {}
        if table in SCRUB_SKIP:
            peers = {}  # node-local content: peers are no authority
        if peers:
            try:
                return self._repair_from_peer(table, reason)
            except (OSError, CorruptionError, KeyError, ValueError) as e:
                log.warning("scrub: peer repair of %s failed: %s",
                            table, e)
                return False
        # single node: no peer to refetch from — rewrite quarantined
        # segments from their healthy resident copies when possible
        fixed = 0
        for q in [q for q in list(node.engine.quarantined)
                  if q["table"] == table]:
            if node.engine.rewrite_segment_from_memory(
                    table, q["segment_id"]):
                fixed += 1
        if fixed:
            qmetrics.inc("scrub.repairs", source="local-memory")
            self.state.record("repair", table=table, segments=fixed,
                              note="rewritten from resident copy")
        return fixed > 0 or not any(
            q["table"] == table for q in node.engine.quarantined)

    def _repair_from_peer(self, table: str, reason: str) -> bool:
        """Refetch ``table``'s baseline from a healthy peer: the peer
        checkpoints (rebuild.fetch_meta — its manifest then covers
        every version our segments could hold; any version flushed
        locally was committed, hence replicated, hence below the fresh
        checkpoint's flush horizon), its segment files stream over
        chunked crc-verified rebuild.fetch_segments into a staging dir,
        verify, swap, then digest parity re-checks the result."""
        from oceanbase_tpu.net import rebuild as _rebuild
        from oceanbase_tpu.net.rpc import RpcError
        from oceanbase_tpu.storage.engine import load_manifest

        node = self.node
        health = getattr(node, "health", None)
        t0 = time.monotonic()
        last_err: Exception | None = None
        for pid in sorted(node.peers):
            qadmission.checkpoint()  # KILL/deadline between candidates
            if health is not None and health.state(pid) != "up":
                continue
            cli = node.peers[pid]
            staging = os.path.join(node.root, ".scrub_tmp")
            try:
                with qtrace.span("scrub.repair", table=table, peer=pid):
                    # a peer that is BEHIND us would ship a baseline
                    # missing rows we hold — the post-swap parity gate
                    # below catches that and the retry budget re-runs
                    # against the next candidate
                    meta = cli.call("rebuild.fetch_meta")
                    shutil.rmtree(staging, ignore_errors=True)
                    os.makedirs(staging, exist_ok=True)
                    mpath = os.path.join(staging, "manifest.json")
                    with open(mpath, "wb") as f:
                        f.write(meta.get("manifest", b""))
                    m = load_manifest(mpath)
                    t = m.get("tables", {}).get(table)
                    if t is None:
                        last_err = KeyError(
                            f"peer {pid} has no table {table}")
                        continue
                    crcs = {f["name"]: f.get("crc")
                            for f in meta.get("files", [])}
                    nbytes = 0
                    installed = []
                    for ent in t.get("segments", []):
                        seg_id, level = int(ent[0]), int(ent[1])
                        part = ent[2] if len(ent) > 2 else None
                        rel = os.path.join(
                            "data", "segments", f"{table}_{seg_id}.npz")
                        dst = os.path.join(staging, f"{table}_{seg_id}")
                        nbytes += _rebuild.fetch_file(
                            cli, rel, dst,
                            expect_crc=crcs.get(rel))
                        # chunk/footer crcs verify inside
                        # repair_table_segments' load — no second
                        # decode here (fetch_file already checked the
                        # transfer against the whole-file digest)
                        installed.append({"segment_id": seg_id,
                                          "level": level, "part": part,
                                          "src": dst})
                    node.engine.repair_table_segments(table, installed)
                    node.catalog.invalidate(table)
                    qmetrics.inc("scrub.repairs", source="peer")
                    qmetrics.inc("scrub.repair_bytes", nbytes)
                    # parity gate: repair is only done when the mended
                    # replica agrees with the source again
                    parity = self._parity_with(pid)
                    self.state.record(
                        "repair", table=table, peer=pid,
                        segments=len(installed), nbytes=nbytes,
                        elapsed_s=time.monotonic() - t0,
                        note=f"{reason}; parity={parity}")
                    return parity
            except (OSError, RpcError, CorruptionError) as e:
                last_err = e
                continue
            finally:
                shutil.rmtree(staging, ignore_errors=True)
        if last_err is not None:
            log.warning("scrub: no peer could repair %s: %s",
                        table, last_err)
        return False

    def _parity_with(self, pid: int) -> bool:
        """Post-repair digest comparison against one peer at a fresh
        common snapshot (best-effort: unreachable peer -> False, the
        retry budget re-runs the repair)."""
        from oceanbase_tpu.net.rpc import RpcError

        node = self.node
        local = self.checksum_handler()
        try:
            r = node.peers[pid].call(
                "scrub.checksum", snapshot=local["snapshot"],
                applied_lsn=node.palf.replica.applied_lsn)
        except (OSError, RpcError):
            return False
        theirs = r["tables"]
        ok = all(theirs.get(n) == d for n, d in local["tables"].items()
                 if n in theirs)
        self.state.record("parity", peer=pid,
                          mismatches=0 if ok else 1,
                          note="ok" if ok else "post-repair divergence")
        return ok
