"""Storage engine: LSM-lite column store + MVCC memtable.

Reference analog (SURVEY §2.5/§2.6, src/storage ~1M LoC):
- encoded immutable segments ≙ SSTable macro/micro blocks with cs_encoding
  (src/storage/blocksstable, src/storage/column_store)
- zone maps / block skipping ≙ index-block aggregates + blockscan pushdown
  (src/storage/access/ob_vector_store.cpp:292 fast path)
- memtable with MVCC version chains ≙ ObMemtable (src/storage/memtable/
  ob_memtable.cpp:542 set / mvcc_write_)
- freeze -> mini / minor / major compaction ≙ ObTenantTabletScheduler DAGs
  (src/storage/compaction/ob_tenant_tablet_scheduler.h:140)
- manifest + checkpoint ≙ slog / slog_ckpt (src/storage/slog)

TPU-first split: the engine keeps encoded columns + metadata on the host,
decodes straight into device Relations (the executor's scan source), and
serves snapshot reads by stacking [base segments ; memtable delta] with a
validity mask — the "LSM merge" is a device concat + anti-join on updated
keys rather than a row-at-a-time fuse.
"""

from oceanbase_tpu.storage.encoding import (
    EncodedColumn,
    ZoneMap,
    decode_column,
    encode_column,
)

__all__ = ["EncodedColumn", "ZoneMap", "encode_column", "decode_column"]
