"""MVCC memtable: the mutable head of each tablet's LSM.

Reference analog: ObMemtable + the MVCC engine
(src/storage/memtable/ob_memtable.h:182, set at ob_memtable.cpp:542,
mvcc chains in src/storage/memtable/mvcc/ob_mvcc_engine.h).

Host-side by design (the north star keeps MVCC off-TPU): a dict keyed by
primary key holding per-key version chains, newest first.  Reads at a
snapshot version walk the chain to the first visible version; uncommitted
versions are visible only to their own transaction.  ``freeze()`` swaps
the active memtable for an immutable one that mini-compaction turns into
an L0 segment (≙ ObFreezer, src/storage/ls/ob_freezer.h:177).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class Version:
    """One MVCC version of a row (≙ ObMvccTransNode)."""

    commit_version: int          # 0 while uncommitted
    tx_id: int
    op: str                      # insert | update | delete
    values: dict                 # column -> python value (None = NULL)
    prev: Optional["Version"] = None
    stmt_seq: int = 0            # statement sequence within the tx
                                 # (savepoint granularity for stmt rollback)


class MemTable:
    """Version-chained rows for one tablet."""

    def __init__(self, mt_id: int = 0):
        self.mt_id = mt_id
        self._rows: dict[tuple, Version] = {}
        self._lock = threading.RLock()
        self.frozen = False
        self.min_version = 2**63
        self.max_version = 0

    def __len__(self):
        return len(self._rows)

    # ------------------------------------------------------------------
    # write path (called under a transaction; ≙ mvcc_write_)
    # ------------------------------------------------------------------
    def write(self, key: tuple, op: str, values: dict, tx_id: int,
              stmt_seq: int = 0, snapshot: int | None = None):
        """MVCC write.  With ``snapshot`` set, enforces snapshot-isolation
        rules: first-committer-wins (a commit newer than the writer's
        snapshot conflicts — prevents lost updates) and duplicate-key
        rejection for inserts over a visible live row."""
        with self._lock:
            if self.frozen:
                raise RuntimeError("memtable frozen")
            head = self._rows.get(key)
            from oceanbase_tpu.tx.errors import DuplicateKey, WriteConflict

            # write-write conflict: another live tx has an uncommitted head
            if head is not None and head.commit_version == 0 and \
                    head.tx_id != tx_id:
                raise WriteConflict(f"key {key} locked by tx {head.tx_id}")
            if snapshot is not None and head is not None and \
                    head.commit_version > snapshot:
                raise WriteConflict(
                    f"key {key} modified after snapshot {snapshot} "
                    f"(committed at {head.commit_version})")
            if snapshot is not None and op == "insert" and head is not None:
                vis = self.visible_version(key, snapshot, tx_id)
                if vis is not None and vis.op != "delete":
                    raise DuplicateKey(f"duplicate key {key}")
            v = Version(0, tx_id, op, dict(values), prev=head,
                        stmt_seq=stmt_seq)
            self._rows[key] = v
            return v

    def commit(self, tx_id: int, commit_version: int, keys):
        with self._lock:
            for key in keys:
                v = self._rows.get(key)
                while v is not None:
                    if v.tx_id == tx_id and v.commit_version == 0:
                        v.commit_version = commit_version
                    v = v.prev
            self.min_version = min(self.min_version, commit_version)
            self.max_version = max(self.max_version, commit_version)

    def abort(self, tx_id: int, keys, min_stmt_seq: int = 0):
        """Drop uncommitted versions of ``tx_id`` (whole-tx rollback), or
        only those with stmt_seq >= min_stmt_seq (statement-level rollback,
        ≙ the reference's savepoint rollback in the tx callback list)."""
        with self._lock:
            for key in keys:
                head = self._rows.get(key)
                while head is not None and head.commit_version == 0 and \
                        head.tx_id == tx_id and head.stmt_seq >= min_stmt_seq:
                    head = head.prev
                if head is None:
                    self._rows.pop(key, None)
                else:
                    self._rows[key] = head

    # ------------------------------------------------------------------
    # read path
    # ------------------------------------------------------------------
    def visible_version(self, key: tuple, snapshot: int,
                        tx_id: int = 0) -> Optional[Version]:
        v = self._rows.get(key)
        while v is not None:
            if v.commit_version == 0:
                if tx_id and v.tx_id == tx_id:
                    return v  # own uncommitted write
            elif v.commit_version <= snapshot:
                return v
            v = v.prev
        return None

    def snapshot_rows(self, snapshot: int, tx_id: int = 0) -> dict:
        """-> {key: Version} of all visible versions at ``snapshot``."""
        out = {}
        with self._lock:
            for key in self._rows:
                v = self.visible_version(key, snapshot, tx_id)
                if v is not None:
                    out[key] = v
        return out

    def freeze(self) -> "MemTable":
        """Make this memtable immutable; caller installs a fresh active one
        (≙ ObFreezer tablet freeze)."""
        with self._lock:
            self.frozen = True
        return self

    def to_arrays(self, columns: list, types: dict, snapshot: int):
        """Materialize ALL committed versions (<= snapshot) as host arrays
        for segment build (mini compaction input) — multi-version flush so
        live older snapshots keep reading their versions from the segment.
        Version GC happens at minor/major merge (newest-wins dedup), the
        undo-retention boundary.  Rows carry __deleted__ tombstone markers
        and per-row __version__ commit versions; per key, versions are
        emitted oldest-first so newest-wins stacking order holds."""
        with self._lock:
            chains = []
            for key in sorted(self._rows):
                vers = []
                v = self._rows[key]
                while v is not None:
                    if v.commit_version != 0 and v.commit_version <= snapshot:
                        vers.append(v)
                    v = v.prev
                vers.reverse()  # oldest first
                chains.append(vers)
        n = sum(len(vs) for vs in chains)
        arrays = {c: [] for c in columns}
        deleted = np.zeros(n, dtype=bool)
        versions = np.zeros(n, dtype=np.int64)
        valids = {c: np.ones(n, dtype=bool) for c in columns}
        i = 0
        for vers in chains:
            for v in vers:
                deleted[i] = v.op == "delete"
                versions[i] = v.commit_version
                for c in columns:
                    val = v.values.get(c)
                    if val is None:
                        valids[c][i] = False
                        arrays[c].append("" if types[c].is_string else 0)
                    else:
                        arrays[c].append(val)
                i += 1
        out = {}
        for c in columns:
            if types[c].is_string:
                out[c] = np.array(arrays[c], dtype=object)
            else:
                out[c] = np.asarray(arrays[c], dtype=types[c].np_dtype)
        out["__deleted__"] = deleted
        out["__version__"] = versions
        return out, valids

    def leftover_versions(self, snapshot: int) -> dict:
        """Version chains NOT captured by a flush at ``snapshot``:
        uncommitted versions and versions committed after the snapshot.
        The returned heads are cut below the capture boundary (older
        versions live in the flushed segment)."""
        out: dict[tuple, Version] = {}
        with self._lock:
            for key, head in self._rows.items():
                keep = []
                v = head
                while v is not None and (v.commit_version == 0 or
                                         v.commit_version > snapshot):
                    keep.append(v)
                    v = v.prev
                if keep:
                    keep[-1].prev = None  # cut: older history is flushed
                    out[key] = keep[0]
        return out
