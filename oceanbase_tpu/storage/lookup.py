"""Index-aware point and range lookups over the tablet LSM.

Reference analog: the DAS iterator stack walking index-block B+-trees to
seek micro blocks (src/sql/das/iter/ob_das_iter.h,
src/storage/blocksstable/index_block/ob_index_block_row_scanner.h).  The
TPU build's segments are key-sorted with per-chunk zone maps on the key
columns (see storage/segment.py::sort_rows_by_keys), so a lookup prunes
to the few chunks whose zone ranges cover the key and decodes only those
— a point ``get`` touches O(chunks-holding-key) rows, not the whole
segment.

All work here is host-side numpy: point/small-range operations are
latency-bound, and a device dispatch costs orders of magnitude more than
decoding one 64k-row chunk on the host.
"""

from __future__ import annotations

import numpy as np


def _base_tablets(tablet, key=None):
    """Resolve the physical tablets a key could live in."""
    parts = getattr(tablet, "partitions", None)
    if parts is None:
        return [tablet]
    if key is not None:
        t = tablet._route_key(key)
        if t is not None:
            return [t]
    return list(parts)


def _chunk_mask(seg, ranges: dict):
    """AND of per-column zone-map prunes; None -> nothing survives."""
    cm = np.ones(seg.n_chunks, dtype=bool)
    for col, (lo, hi) in ranges.items():
        cm &= seg.prune_chunks(col, lo, hi)
    if not cm.any():
        return None
    return cm


def estimate_rows_in_ranges(tablet, ranges: dict) -> int:
    """Upper bound on rows a pruned scan would decode (zone-map metadata
    only — no decode).  Feeds the access-path cost decision."""
    total = 0
    for t in _base_tablets(tablet):
        sub = {k: v for k, v in ranges.items() if k in t.key_cols}
        for seg in t.segments:
            if not sub:
                total += seg.n_rows
                continue
            cm = _chunk_mask(seg, sub)
            if cm is None:
                continue
            any_col = next(iter(seg.columns.values()))
            total += sum(any_col[i].n for i in np.nonzero(cm)[0])
        total += len(t.active) + sum(len(m) for m in t.frozen)
    return total


_INF = 2**62


def _tablet_newest(t, key: tuple, snapshot: int, tx_id: int):
    """Newest visible version of ``key`` in one physical tablet ->
    (commit_version, row-values | None-if-tombstone, found)."""
    for mt in [t.active] + t.frozen[::-1]:
        v = mt.visible_version(key, snapshot, tx_id)
        if v is not None:
            # own uncommitted writes (commit_version 0) are newest of all
            ver = v.commit_version or _INF
            row = None if v.op == "delete" else dict(v.values)
            return ver, row, True
    ranges = {kc: (kv, kv) for kc, kv in zip(t.key_cols, key)
              if kv is not None}
    best = None
    best_ver = -1
    found = False
    for seg in t.segments[::-1]:
        if seg.min_version > snapshot:
            continue
        cm = _chunk_mask(seg, ranges) if ranges else \
            np.ones(seg.n_chunks, dtype=bool)
        if cm is None:
            continue
        arrays, valids = seg.decode(chunk_mask=None if cm.all() else cm)
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            continue
        sel = np.ones(n, dtype=bool)
        for kc, kv in zip(t.key_cols, key):
            col = arrays[kc]
            vd = valids.get(kc)
            if kv is None:
                sel &= (~vd if vd is not None
                        else np.zeros(n, dtype=bool))
            else:
                sel &= col == kv
                if vd is not None:
                    sel &= vd
        if "__version__" in arrays:
            sel &= arrays["__version__"] <= snapshot
        idx = np.nonzero(sel)[0]
        if len(idx) == 0:
            continue
        vers = arrays.get("__version__")
        i = idx[-1] if vers is None else idx[np.argmax(vers[idx])]
        ver = int(vers[i]) if vers is not None else seg.max_version
        if ver > best_ver:
            best_ver = ver
            found = True
            if arrays.get("__deleted__") is not None and \
                    arrays["__deleted__"][i]:
                best = None
            else:
                row = {}
                for c in t.columns:
                    if c not in arrays:
                        continue
                    vd = valids.get(c)
                    row[c] = (None if vd is not None and not vd[i]
                              else arrays[c][i].item()
                              if hasattr(arrays[c][i], "item")
                              else arrays[c][i])
                best = row
    return best_ver, best, found


def point_lookup(tablet, key: tuple, snapshot: int, tx_id: int = 0):
    """Newest visible row for ``key`` -> values dict | None (absent or
    deleted).

    Memtables are probed newest-first (their versions are strictly newer
    than flushed segments for the same key); segments are probed with
    zone-map pruning on every key column, decoding only surviving chunks.
    When the key cannot be routed to one partition, EVERY candidate
    partition is consulted and the newest version wins — a
    partition-moving update leaves a tombstone in the old partition and a
    live row (same commit version) in the new one, and the live row must
    win the tie."""
    best_ver = -1
    best = None
    for t in _base_tablets(tablet, key):
        ver, row, found = _tablet_newest(t, key, snapshot, tx_id)
        if not found:
            continue
        if ver > best_ver or (ver == best_ver and row is not None):
            best_ver = ver
            best = row
    return best


def range_rows(tablet, ranges: dict, snapshot: int, tx_id: int = 0,
               columns=None):
    """All live rows whose key columns fall in ``ranges`` (inclusive) ->
    (arrays, valids).  Built on the pruned snapshot read, then exactly
    filtered — the result is snapshot-consistent, not a superset."""
    sub = {k: v for k, v in ranges.items()
           if k in tablet.key_cols or k == getattr(tablet, "part_col", None)}
    arrays, valids = tablet.snapshot_arrays(snapshot, tx_id, prune=sub)
    n = len(next(iter(arrays.values()))) if arrays else 0
    if n == 0:
        return arrays, valids
    sel = np.ones(n, dtype=bool)
    for col, (lo, hi) in ranges.items():
        a = arrays[col]
        vd = valids.get(col)
        if vd is not None:
            sel &= vd
        if lo is not None:
            sel &= a >= lo
        if hi is not None:
            sel &= a <= hi
    names = columns if columns is not None else list(arrays)
    return ({c: arrays[c][sel] for c in names},
            {c: (valids[c][sel] if valids.get(c) is not None else None)
             for c in names})
