"""Range-partitioned tables: multiple tablets per table.

Reference analog: partitioned tables mapping to multiple tablets hosted
by log streams (src/storage/tablet + the partition routing the DAS layer
performs).  A PartitionedTablet keeps the single-tablet interface the
rest of the engine uses (write/commit/abort/freeze/compact/snapshot) and
routes internally:

- writes route by the partition key's range (≙ PKEY slice routing)
- snapshot reads concatenate per-partition arrays (scans parallelize
  naturally — each partition is an independent granule source)
- freeze/compaction iterate partitions (≙ per-tablet merge DAGs)

Bounds are upper-exclusive split points: bounds [10, 20] makes partitions
(-inf,10), [10,20), [20,+inf).
"""

from __future__ import annotations

import bisect
import threading

import numpy as np

from oceanbase_tpu.storage.tablet import Tablet


class PartitionedTablet:
    def __init__(self, tablet_id: int, columns, types, key_cols,
                 part_col: str, bounds: list):
        if part_col not in columns:
            raise ValueError(
                f"partition column {part_col!r} is not a table column")
        if any(bounds[i] >= bounds[i + 1] for i in range(len(bounds) - 1)):
            raise ValueError("partition bounds must be strictly increasing")
        self.part_col = part_col
        self.bounds = list(bounds)
        self.columns = list(columns)
        self.types = dict(types)
        self.key_cols = list(key_cols)
        self.partitions = [
            Tablet(tablet_id * 1000 + i, columns, types, key_cols)
            for i in range(len(bounds) + 1)
        ]
        # one segment-id space across partitions (filenames stay unique;
        # add_segment bumps it past recovered ids — see SegIdAlloc)
        from oceanbase_tpu.storage.tablet import SegIdAlloc

        shared = SegIdAlloc(1)
        for p in self.partitions:
            p._next_seg = shared
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    @property
    def data_version(self) -> int:
        return sum(p.data_version for p in self.partitions)

    @property
    def active(self):
        # callers needing ALL memtables must use memtables(); this exists
        # only for interface compatibility with single-tablet code paths
        return self.partitions[0].active

    def memtables(self):
        """Every memtable across partitions, newest-first per partition."""
        out = []
        for p in self.partitions:
            out.append(p.active)
            out.extend(p.frozen[::-1])
        return out

    def max_commit_version(self) -> int:
        return max((p.max_commit_version() for p in self.partitions),
                   default=0)

    @property
    def frozen(self):
        out = []
        for p in self.partitions:
            out.extend(p.frozen)
        return out

    @property
    def segments(self):
        out = []
        for p in self.partitions:
            out.extend(p.segments)
        return out

    def _route(self, values: dict) -> Tablet:
        v = values.get(self.part_col)
        if v is None:
            return self.partitions[0]  # NULLs live in the first partition
        return self.partitions[bisect.bisect_right(self.bounds, v)]

    def _route_key(self, key: tuple) -> Tablet | None:
        """Route by key when the partition column is part of the key."""
        if self.part_col in self.key_cols:
            v = key[self.key_cols.index(self.part_col)]
            return self.partitions[bisect.bisect_right(self.bounds, v)]
        return None

    # ------------------------------------------------------------------
    def make_key(self, values: dict) -> tuple:
        return self._route(values).make_key(values)

    def next_rowid(self, n: int) -> int:
        return self.partitions[0].next_rowid(n)

    def write(self, key: tuple, op: str, values: dict, tx_id: int,
              stmt_seq: int = 0, snapshot=None):
        t = self._route_key(key) or self._route(values)
        return t.write(key, op, values, tx_id, stmt_seq, snapshot)

    def commit(self, tx_id: int, commit_version: int, keys):
        for p in self.partitions:
            p.commit(tx_id, commit_version, keys)

    def abort(self, tx_id: int, keys, min_stmt_seq: int = 0):
        for p in self.partitions:
            p.abort(tx_id, keys, min_stmt_seq)

    # ------------------------------------------------------------------
    def freeze(self):
        for p in self.partitions:
            p.freeze()

    def mini_compact(self, snapshot: int):
        """-> list[(part_idx, Segment)] of newly produced segments."""
        out = []
        for i, p in enumerate(self.partitions):
            s = p.mini_compact(snapshot)
            if s is not None:
                out.append((i, s))
        return out or None

    def minor_compact(self):
        out = []
        for i, p in enumerate(self.partitions):
            s = p.minor_compact()
            if s is not None:
                out.append((i, s))
        return out or None

    def major_compact(self):
        out = []
        for i, p in enumerate(self.partitions):
            s = p.major_compact()
            if s is not None:
                out.append((i, s))
        return out or None

    # ------------------------------------------------------------------
    def snapshot_arrays(self, snapshot: int, tx_id: int = 0, prune=None):
        live = self.partitions
        if prune and self.part_col in prune:
            lo, hi = prune[self.part_col]
            first = 0 if lo is None else \
                bisect.bisect_right(self.bounds, lo)
            last = len(self.partitions) - 1 if hi is None else \
                bisect.bisect_right(self.bounds, hi)
            live = self.partitions[first:last + 1]
        # chunk-level pruning below the partition router is only sound on
        # key columns (see Tablet.snapshot_arrays); partition-level routing
        # on part_col is sound regardless because a row's partition is
        # derived from the very value being ranged on
        sub = ({k: v for k, v in prune.items()
                if k in self.partitions[0].key_cols} or None) if prune \
            else None
        parts = [p.snapshot_arrays(snapshot, tx_id, prune=sub)
                 for p in live]
        arrays: dict = {}
        valids: dict = {}
        for c in self.columns:
            chunks = [a[c] for a, _v in parts if c in a]
            if any(x.dtype == object for x in chunks):
                chunks = [x.astype(object) for x in chunks]
            arrays[c] = np.concatenate(chunks) if chunks else \
                np.zeros(0, dtype=self.types[c].np_dtype)
            vs = [v.get(c) for _a, v in parts]
            if any(x is not None for x in vs):
                valids[c] = np.concatenate(
                    [x if x is not None
                     else np.ones(len(a[c]), dtype=bool)
                     for (a, v), x in zip(parts, vs)])
            else:
                valids[c] = None
        return arrays, valids

    def row_count_estimate(self) -> int:
        return sum(p.row_count_estimate() for p in self.partitions)

    # -- segment management hooks ----------------------------------------
    def add_segment(self, seg, part_idx=None):
        self.partitions[part_idx or 0].add_segment(seg)

    def remove_segments(self, ids):
        for p in self.partitions:
            p.remove_segments(ids)

    def segment_locations(self):
        out = []
        for i, p in enumerate(self.partitions):
            out.extend((s, i) for s in p.segments)
        return out

    def split_arrays_by_partition(self, arrays: dict):
        """Bulk-load routing: -> [(part_idx, {col -> rows})] per range."""
        col = arrays[self.part_col]
        idx = np.searchsorted(np.asarray(self.bounds), col, side="right")
        out = []
        for i in range(len(self.partitions)):
            sel = idx == i
            if sel.any():
                out.append((i, {k: v[sel] for k, v in arrays.items()}, sel))
        return out

    def route_partition_index(self, values: dict) -> int:
        """Which partition a row with these values lives in (DML uses it
        to detect partition-moving updates)."""
        return self.partitions.index(self._route(values))
