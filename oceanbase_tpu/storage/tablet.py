"""Tablet: one partition's LSM — memtables + leveled segments.

Reference analog: ObTablet (src/storage/tablet) owning memtables and an
SSTable table-store; freeze/mini/minor/major compaction driven by the
tenant scheduler (src/storage/compaction/ob_tenant_tablet_scheduler.h:140).

Read path: ``snapshot_arrays`` fuses base segments (oldest..newest,
newest-wins by primary key) with the visible memtable overlay — the TPU
build's version of ObMultipleScanMerge fusing memtable + SSTables
(src/storage/access/ob_multiple_merge.cpp:507), done column-wise on host
metadata before the device upload instead of row-at-a-time.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field

import numpy as np

from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.storage.memtable import MemTable
from oceanbase_tpu.storage.segment import Segment, merge_segments


class SegIdAlloc:
    """Monotonic segment-id allocator that can be bumped past ids seen
    on recovery/repair installs: a restarted tablet must never mint an
    id that collides with a persisted segment file (the fresh segment
    would silently overwrite the old one on disk)."""

    def __init__(self, start: int = 1):
        self.n = start

    def __next__(self) -> int:
        v = self.n
        self.n += 1
        return v

    def bump_past(self, seg_id: int):
        self.n = max(self.n, int(seg_id) + 1)


class Tablet:
    def __init__(self, tablet_id: int, columns: list[str],
                 types: dict[str, SqlType], key_cols: list[str]):
        self.tablet_id = tablet_id
        self.columns = list(columns)
        self.types = dict(types)
        self.key_cols = list(key_cols)
        self.active = MemTable(0)
        self.frozen: list[MemTable] = []
        self.segments: list[Segment] = []   # oldest first
        self._next_mt = itertools.count(1)
        self._next_seg = SegIdAlloc(1)
        self._lock = threading.RLock()
        self._auto_key = itertools.count()  # rowid for keyless tables
        self.data_version = 0               # bumps on any visible change

    # ------------------------------------------------------------------
    def make_key(self, values: dict) -> tuple:
        if self.key_cols == ["__rowid__"] and "__rowid__" not in values:
            values["__rowid__"] = self.next_rowid(1)
        return tuple(values[k] for k in self.key_cols)

    def next_rowid(self, n: int) -> int:
        """Allocate n consecutive hidden rowids (restart-safe: seeded from
        the max persisted rowid on first use)."""
        with self._lock:
            if not hasattr(self, "_rowid_base"):
                base = 0
                for seg in self.segments:
                    chunks = seg.columns.get("__rowid__")
                    if chunks:
                        for ec in chunks:
                            if ec.zone.vmax is not None:
                                base = max(base, int(ec.zone.vmax) + 1)
                # rows replayed from the WAL live only in memtables
                if self.key_cols == ["__rowid__"]:
                    for mt in [self.active] + self.frozen:
                        for key in mt._rows:
                            base = max(base, int(key[0]) + 1)
                self._rowid_base = base
            out = self._rowid_base
            self._rowid_base += n
            return out

    def write(self, key: tuple, op: str, values: dict, tx_id: int,
              stmt_seq: int = 0, snapshot: int | None = None):
        with self._lock:
            # invariant: stored values always carry their key columns
            # (callers that copied the dict before make_key would
            # otherwise persist NULL rowids that dedup collapses)
            if any(values.get(kc) is None for kc in self.key_cols):
                values = dict(values)
                for kc, kv in zip(self.key_cols, key):
                    if values.get(kc) is None:
                        values[kc] = kv
            # SI conflict checks look at frozen memtables too: the key's
            # newest version may have been frozen mid-transaction
            if snapshot is not None:
                for mt in self.frozen:
                    head = mt._rows.get(key)
                    if head is not None and head.commit_version > snapshot:
                        from oceanbase_tpu.tx.errors import WriteConflict

                        raise WriteConflict(
                            f"key {key} modified after snapshot {snapshot}")
            v = self.active.write(key, op, values, tx_id, stmt_seq,
                                  snapshot=snapshot)
            return v

    def commit(self, tx_id: int, commit_version: int, keys):
        with self._lock:
            self.active.commit(tx_id, commit_version, keys)
            for mt in self.frozen:
                mt.commit(tx_id, commit_version, keys)
            self.data_version += 1

    def abort(self, tx_id: int, keys, min_stmt_seq: int = 0):
        with self._lock:
            self.active.abort(tx_id, keys, min_stmt_seq)
            for mt in self.frozen:
                mt.abort(tx_id, keys, min_stmt_seq)

    # ------------------------------------------------------------------
    # compaction (≙ mini/minor/major merge DAGs)
    # ------------------------------------------------------------------
    def freeze(self):
        with self._lock:
            if len(self.active) == 0:
                return None
            mt = self.active.freeze()
            self.frozen.append(mt)
            self.active = MemTable(next(self._next_mt))
            return mt

    def mini_compact(self, snapshot: int):
        """Frozen memtables -> one L0 segment.

        Versions the flush snapshot cannot capture (uncommitted, or
        committed after the snapshot) are CARRIED OVER into the active
        memtable instead of being dropped — a frozen memtable may hold a
        live transaction's writes (≙ the reference's freeze waiting on
        active tx handover; we migrate instead of waiting)."""
        with self._lock:
            if not self.frozen:
                return None
            parts = []
            leftovers: list[dict] = []
            for mt in self.frozen:
                arrays, valids = mt.to_arrays(self.columns, self.types,
                                              snapshot)
                parts.append((arrays, valids, mt))
                leftovers.append(mt.leftover_versions(snapshot))
            merged_arrays, merged_valids = _stack_parts(parts, self.columns,
                                                        self.types)
            from oceanbase_tpu.storage.segment import sort_rows_by_keys

            merged_arrays, merged_valids = sort_rows_by_keys(
                merged_arrays, merged_valids, self.key_cols)
            seg = Segment.build(
                next(self._next_seg), 0, merged_arrays,
                {**self.types, "__deleted__": SqlType.bool_(),
                 "__version__": SqlType.int_()},
                merged_valids,
                min_version=min((mt.min_version for _, _, mt in parts
                                 if mt.max_version > 0), default=snapshot),
                max_version=max((mt.max_version for _, _, mt in parts),
                                default=snapshot),
            )
            self.segments.append(seg)
            self.frozen = []
            for lo in leftovers:
                self._graft_versions(lo)
            self.data_version += 1
            return seg

    def _graft_versions(self, chains: dict):
        """Attach carried-over version chains under the active memtable's
        chains (active versions are strictly newer)."""
        for key, head in chains.items():
            cur = self.active._rows.get(key)
            if cur is None:
                self.active._rows[key] = head
            else:
                tail = cur
                while tail.prev is not None:
                    tail = tail.prev
                tail.prev = head

    def minor_compact(self):
        """All L0 segments -> one L1 (≙ minor merge).  Tombstones are
        RETAINED: the rows they shadow may live in lower levels outside
        this merge."""
        with self._lock:
            l0 = [s for s in self.segments if s.level == 0]
            if len(l0) < 2:
                return None
            keep = [s for s in self.segments if s.level != 0]
            merged = merge_segments(next(self._next_seg), 1, l0,
                                    self.key_cols, drop_tombstones=False)
            # place after existing L1/L2 so order stays oldest-first
            self.segments = keep + [merged]
            self.data_version += 1
            return merged

    def major_compact(self):
        """Everything -> one L2 baseline (≙ daily major merge); the merge
        covers every level, so tombstones fall out here."""
        with self._lock:
            if not self.segments:
                return None
            merged = merge_segments(next(self._next_seg), 2, self.segments,
                                    self.key_cols, drop_tombstones=True)
            self.segments = [merged]
            self.data_version += 1
            return merged

    # ------------------------------------------------------------------
    # snapshot read
    # ------------------------------------------------------------------
    def snapshot_arrays(self, snapshot: int, tx_id: int = 0, prune=None):
        """-> (arrays, valids) visible at ``snapshot`` (plus own tx).

        ``prune``: optional {key_col: (lo, hi)} inclusive ranges used for
        zone-map chunk pruning (≙ blockscan skipping via index blocks).
        SOUNDNESS: pruning columns MUST be key columns — every version of
        a key (including tombstones) carries identical key-column values,
        so a chunk mask derived from key ranges either keeps every version
        of a key or drops every version; newest-wins dedup stays correct
        for all surviving keys.  Pruning on a non-key column could split a
        version chain and resurrect stale rows."""
        if prune:
            assert set(prune) <= set(self.key_cols), \
                "zone-map pruning is only sound on key columns"
        with self._lock:
            seg_parts = []
            for seg in self.segments:
                if seg.min_version > snapshot:
                    continue  # wholly invisible at this snapshot
                if prune:
                    cm = np.ones(seg.n_chunks, dtype=bool)
                    for pc, (lo, hi) in prune.items():
                        cm &= seg.prune_chunks(pc, lo, hi)
                    if not cm.any():
                        continue
                    a, v = seg.decode(chunk_mask=None if cm.all() else cm)
                else:
                    a, v = seg.decode()
                if seg.max_version > snapshot and "__version__" in a:
                    vis = a["__version__"] <= snapshot
                    a = {k: arr[vis] for k, arr in a.items()}
                    v = {k: (vv[vis] if vv is not None else None)
                         for k, vv in v.items()}
                seg_parts.append((a, v, None))
            mt_parts = []
            for mt in self.frozen + [self.active]:
                rows = mt.snapshot_rows(snapshot, tx_id)
                if rows:
                    a, v = _rows_to_arrays(rows, self.columns, self.types)
                    mt_parts.append((a, v, None))
        parts = seg_parts + mt_parts
        if not parts:
            return ({c: np.zeros(0, dtype=object if self.types[c].is_string
                                 else self.types[c].np_dtype)
                     for c in self.columns},
                    {c: None for c in self.columns})
        arrays, valids = _stack_parts(parts, self.columns, self.types)
        n = len(next(iter(arrays.values())))
        keep = np.ones(n, dtype=bool)
        if self.key_cols and n:
            key_arrays = [arrays[k] for k in self.key_cols]
            seen: set = set()
            for idx in range(n - 1, -1, -1):  # newest last -> wins
                key = tuple(a[idx] for a in key_arrays)
                if key in seen:
                    keep[idx] = False
                else:
                    seen.add(key)
        if "__deleted__" in arrays:
            keep &= ~arrays["__deleted__"].astype(bool)
        out_a = {c: arrays[c][keep] for c in self.columns}
        out_v = {c: (valids[c][keep] if valids.get(c) is not None else None)
                 for c in self.columns}
        return out_a, out_v

    def row_count_estimate(self) -> int:
        return sum(s.n_rows for s in self.segments) + len(self.active) + \
            sum(len(m) for m in self.frozen)

    def memtables(self):
        """Active + frozen memtables, newest-first (interface shared with
        PartitionedTablet for point-lookup/streaming paths)."""
        return [self.active] + self.frozen[::-1]

    # -- segment management hooks (shared with PartitionedTablet) --------
    def add_segment(self, seg, part_idx=None):
        # segment list + data_version guard reads through THIS tablet's
        # lock; callers under the engine lock still must not bypass it
        with self._lock:
            self.segments.append(seg)
            self._next_seg.bump_past(seg.segment_id)
            self.data_version += 1

    def remove_segments(self, ids):
        ids = set(ids)
        with self._lock:
            self.segments = [s for s in self.segments
                             if s.segment_id not in ids]
            self.data_version += 1

    def segment_locations(self):
        """-> [(Segment, partition_idx|None)] for manifest checkpoints."""
        return [(s, None) for s in self.segments]

    def max_commit_version(self) -> int:
        """Largest commit version any row in this tablet carries; a read
        at snapshot >= this sees the same data as a latest-commit read."""
        v = max((s.max_version for s in self.segments), default=0)
        for mt in [self.active] + self.frozen:
            v = max(v, mt.max_version)
        return v


def _rows_to_arrays(rows: dict, columns, types):
    n = len(rows)
    arrays = {c: [] for c in columns}
    valids = {c: np.ones(n, dtype=bool) for c in columns}
    deleted = np.zeros(n, dtype=bool)
    for i, (key, v) in enumerate(sorted(rows.items())):
        deleted[i] = v.op == "delete"
        for c in columns:
            val = v.values.get(c)
            if val is None:
                valids[c][i] = False
                arrays[c].append("" if types[c].is_string else 0)
            else:
                arrays[c].append(val)
    out = {}
    for c in columns:
        if types[c].is_string:
            out[c] = np.array(arrays[c], dtype=object)
        else:
            out[c] = np.asarray(arrays[c], dtype=types[c].np_dtype)
    out["__deleted__"] = deleted
    return out, valids


def _stack_parts(parts, columns, types):
    """Stack (arrays, valids, _) parts preserving the hidden __deleted__
    tombstone and __version__ commit-version columns.

    A part MISSING a real column (segments written before an ALTER TABLE
    ADD COLUMN) contributes NULLs for it — schema evolution without
    rewriting old segments."""
    cols = list(columns) + ["__deleted__", "__version__"]
    arrays = {}
    valids = {}
    for c in cols:
        arrs = []
        missing = []  # parallel flags: part lacked this column entirely
        for a, v, _ in parts:
            if c in a:
                arrs.append(a[c])
                missing.append(False)
            else:
                n = len(next(iter(a.values())))
                if c == "__deleted__":
                    arrs.append(np.zeros(n, dtype=bool))
                elif c == "__version__":
                    arrs.append(np.zeros(n, dtype=np.int64))
                else:
                    arrs.append(
                        np.array([""] * n, dtype=object)
                        if types[c].is_string
                        else np.zeros(n, dtype=types[c].np_dtype))
                missing.append(True)
        if any(x.dtype == object for x in arrs):
            arrs = [x.astype(object) for x in arrs]
        arrays[c] = np.concatenate(arrs) if arrs else np.zeros(0)
        if c not in ("__deleted__", "__version__"):
            vparts = []
            has = any(v.get(c) is not None for _, v, _ in parts) or \
                any(m for m in missing)
            if has:
                for (a, v, _), m, arr in zip(parts, missing, arrs):
                    n = len(arr)
                    if m:
                        vparts.append(np.zeros(n, dtype=bool))  # NULLs
                    else:
                        vv = v.get(c)
                        vparts.append(vv if vv is not None
                                      else np.ones(n, dtype=bool))
                valids[c] = np.concatenate(vparts)
            else:
                valids[c] = None
    return arrays, valids
