"""Recovery bookkeeping: what a node did to come back.

Reference analog: the HA dag-net progress the reference surfaces for
replica rebuild/migration (src/storage/high_availability/
ob_storage_ha_dag.h, __all_virtual_ls_restore_progress) — here one
bounded event log per tenant/node feeding the ``gv$recovery`` virtual
table.

Phases recorded:

- ``boot_replay``    slog/checkpoint restore + palf WAL tail replay at
                     process start (wal_start_lsn..wal_end_lsn, entry /
                     commit counters);
- ``restore_prepared`` XA branches reconstructed into PREPARE state
                     (durable XA — the branches XA RECOVER reports);
- ``rebuild``        wiped-replica bootstrap over ``rebuild.fetch_meta``
                     / ``rebuild.fetch_segments`` (peer, files, bytes);
- ``checkpoint``     periodic replay-point advance (the O(tail) bound);
- ``catchup``        live row: local apply point vs the group commit
                     point (appended by the gv$recovery provider);
- ``quarantine``     corrupt persisted artifacts moved aside (bad-magic
                     WAL files in palf/log.py — retention-capped by
                     count/age — and digest-failing manifest/slog pairs
                     in net/rebuild.py::quarantine_corrupt_baseline).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from oceanbase_tpu.server import metrics as qmetrics

MAX_EVENTS = 256

qmetrics.declare("recovery.events", "counter",
                 "recovery-plane events (label: phase)")
qmetrics.declare("recovery.entries", "counter",
                 "WAL entries replayed/shipped by recovery events")
qmetrics.declare("recovery.bytes", "counter",
                 "bytes moved by recovery events (rebuild fetches)")


class RecoveryState:
    """Bounded per-node/tenant recovery event log (thread-safe)."""

    def __init__(self, node_id: int = 0, max_events: int = MAX_EVENTS):
        self.node_id = node_id
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def record(self, phase: str, *, tenant: str = "sys", peer: int = -1,
               wal_start_lsn: int = 0, wal_end_lsn: int = 0,
               entries: int = 0, nbytes: int = 0, prepared: int = 0,
               xids: str = "", elapsed_s: float = 0.0, note: str = ""):
        ev = {"ts": time.time(), "node_id": self.node_id,
              "tenant": tenant, "phase": phase, "peer": peer,
              "wal_start_lsn": int(wal_start_lsn),
              "wal_end_lsn": int(wal_end_lsn),
              "entries": int(entries), "bytes": int(nbytes),
              "prepared": int(prepared), "xids": xids,
              "elapsed_s": float(elapsed_s), "note": note}
        with self._lock:
            self._events.append(ev)
        qmetrics.inc("recovery.events", phase=phase)
        if entries:
            qmetrics.inc("recovery.entries", int(entries), phase=phase)
        if nbytes:
            qmetrics.inc("recovery.bytes", int(nbytes), phase=phase)
        return ev

    def rows(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def last(self, phase: str) -> dict | None:
        with self._lock:
            for ev in reversed(self._events):
                if ev["phase"] == phase:
                    return ev
        return None
