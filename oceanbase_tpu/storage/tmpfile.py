"""Temp-file system: spill runs for out-of-memory operators.

Reference analog: the tmp-file layer backing sort/hash spill
(src/storage/tmp_file/ob_i_tmp_file.h, ob_tmp_file_manager.h) — page-
granular virtual files with buffered IO.  The TPU build spills COLUMN
CHUNKS instead of row pages: a run is a sequence of npz-compressed
column batches, append-ordered, read back chunk-at-a-time so peak host
memory stays at one chunk per open cursor.

Accounting is byte-based per store (≙ tenant tmp-file quota); deletion
is eager (`close_run`/`clear`) with a directory sweep on close.
"""

from __future__ import annotations

import os
import shutil
import threading
from dataclasses import dataclass, field

import numpy as np


@dataclass
class _Run:
    run_id: int
    n_chunks: int = 0
    n_rows: int = 0
    nbytes: int = 0
    meta: dict = field(default_factory=dict)  # caller stash (sort keys…)


class TempFileStore:
    """One spill directory; runs are subdirectories of chunk files."""

    def __init__(self, root: str, budget=None, faults=None,
                 label: str = ""):
        """``budget``: a server/diskmgr.DiskManager whose spill surface
        accounts every chunk this store writes (admit on append,
        release on run close) — exhaustion kills only the spilling
        statement.  ``faults``: a net/faults.FaultPlane consulted
        before each chunk write (seeded ENOSPC/EIO on kind="spill").
        ``label`` names this store in gv$disk's per-statement rows."""
        self.root = root
        self.budget = budget
        self.faults = faults
        self.label = label
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._next = 0
        self._runs: dict[int, _Run] = {}
        self.bytes_written = 0  # lifetime counter (tests/diagnostics)

    # -- write ----------------------------------------------------------
    def new_run(self, **meta) -> int:
        with self._lock:
            rid = self._next
            self._next += 1
            self._runs[rid] = _Run(rid, meta=dict(meta))
        os.makedirs(self._chunk_dir(rid), exist_ok=True)
        return rid

    def append_chunk(self, run_id: int, arrays: dict,
                     valids: dict | None = None):
        """Append one column batch to a run (written compressed)."""
        run = self._runs[run_id]
        n = len(next(iter(arrays.values()))) if arrays else 0
        payload = {}
        for k, v in arrays.items():
            v = np.asarray(v)
            payload[f"a/{k}"] = (v.astype("U") if v.dtype == object else v)
        for k, v in (valids or {}).items():
            if v is not None:
                payload[f"v/{k}"] = np.asarray(v)
        path = self._chunk_path(run_id, run.n_chunks)
        try:
            if self.faults is not None:
                self.faults.check_write("spill", path)
            with open(path + ".tmp", "wb") as f:
                np.savez_compressed(f, **payload)
        except OSError as exc:
            try:
                os.remove(path + ".tmp")
            except OSError:
                pass
            from oceanbase_tpu.server.diskmgr import wrap_disk_error

            raise wrap_disk_error(exc, "spill chunk write") from exc
        sz = os.path.getsize(path + ".tmp")
        if self.budget is not None:
            # admit BEFORE publishing: a rejected chunk leaves no file
            # behind and kills only this statement (SpillBudgetExceeded)
            try:
                self.budget.admit_spill(sz, store=self, label=self.label)
            except Exception:
                try:
                    os.remove(path + ".tmp")
                except OSError:
                    pass
                raise
        os.replace(path + ".tmp", path)
        with self._lock:
            run.n_chunks += 1
            run.n_rows += n
            run.nbytes += sz
            self.bytes_written += sz

    # -- read -----------------------------------------------------------
    def run(self, run_id: int) -> _Run:
        return self._runs[run_id]

    def read_chunks(self, run_id: int, object_strings: bool = True):
        """Yield (arrays, valids) per stored chunk, one in memory at a
        time."""
        run = self._runs[run_id]
        for i in range(run.n_chunks):
            with np.load(self._chunk_path(run_id, i),
                         allow_pickle=False) as z:
                arrays, valids = {}, {}
                for k in z.files:
                    kind, name = k.split("/", 1)
                    if kind == "a":
                        a = z[k]
                        if object_strings and a.dtype.kind in "U":
                            a = a.astype(object)
                        arrays[name] = a
                    else:
                        valids[name] = z[k]
            yield arrays, valids

    # -- lifecycle ------------------------------------------------------
    def close_run(self, run_id: int):
        run = self._runs.pop(run_id, None)
        if run is not None:
            shutil.rmtree(self._chunk_dir(run_id), ignore_errors=True)
            if self.budget is not None:
                self.budget.release_spill(store=self, nbytes=run.nbytes)

    def clear(self):
        for rid in list(self._runs):
            self.close_run(rid)
        if self.budget is not None:
            # sweep accounting residue (partial runs, failed appends)
            self.budget.release_spill(store=self)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._runs.values())

    def _chunk_dir(self, rid: int) -> str:
        return os.path.join(self.root, f"run_{rid}")

    def _chunk_path(self, rid: int, i: int) -> str:
        return os.path.join(self._chunk_dir(rid), f"c{i}.npz")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.clear()
        shutil.rmtree(self.root, ignore_errors=True)
