"""Vectorized window functions.

Reference analog: the vectorized window-function operator
(src/sql/engine/window_function, 16k LoC).  TPU-first design: one lexsort
by (partition keys, order keys), then every supported function is a
segment-scan primitive over the sorted order —

- row_number          position within partition
- rank / dense_rank   order-key-change boundaries (cummax / cumsum)
- agg OVER(part)      segment reduce broadcast back to rows
- agg OVER(part ORDER BY ...)  running prefix (cumsum/cummax/cummin) with
  RANGE-frame peer smearing: tied order keys share the frame value at the
  last peer (MySQL's default frame semantics)

Results scatter back to the original row order, so the operator composes
anywhere in the plan without disturbing downstream ops.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from oceanbase_tpu.datatypes import SqlType, TypeKind
from oceanbase_tpu.expr import ir
from oceanbase_tpu.expr.compile import cast_column, eval_expr
from oceanbase_tpu.vector.column import Column, Relation

_INT_MAX = np.iinfo(np.int64).max


def window(rel: Relation, specs: Sequence[tuple]) -> Relation:
    """specs: [(out_name, ir.WindowCall)]; returns rel + result columns."""
    out_cols = dict(rel.columns)
    for name, wc in specs:
        out_cols[name] = _one_window(rel, wc)
    return Relation(columns=out_cols, mask=rel.mask)


def _one_window(rel: Relation, wc: ir.WindowCall) -> Column:
    n = rel.capacity
    m = rel.mask_or_true()
    part_cols = [eval_expr(e, rel) for e in (wc.partition_by or [])]
    order_cols = [(eval_expr(e, rel), asc) for e, asc in (wc.order_by or [])]

    # lexsort: dead last, then partition keys, then order keys
    minor_to_major = []
    for c, asc in reversed(order_cols):
        d = c.data.astype(jnp.int64) if c.data.dtype == jnp.bool_ else c.data
        if not asc:
            d = -d if not jnp.issubdtype(d.dtype, jnp.floating) else -d
        minor_to_major.append(d)
        if c.valid is not None:
            nk = jnp.where(c.valid, 0, -1 if asc else 1).astype(jnp.int8)
            minor_to_major.append(nk)
    for c in reversed(part_cols):
        d = jnp.where(c.valid, c.data, jnp.zeros((), c.data.dtype)) \
            if c.valid is not None else c.data
        minor_to_major.append(d)
        if c.valid is not None:
            minor_to_major.append((~c.valid).astype(jnp.int8))
    minor_to_major.append((~m).astype(jnp.int8))
    order = jnp.lexsort(tuple(minor_to_major))
    inv = jnp.argsort(order)  # scatter-back permutation
    s_live = jnp.take(m, order)

    # partition boundaries in sorted order
    new_part = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                jnp.zeros(n - 1, jnp.bool_)]) if n else \
        jnp.zeros(0, jnp.bool_)
    for c in part_cols:
        d = jnp.where(c.valid, c.data, jnp.zeros((), c.data.dtype)) \
            if c.valid is not None else c.data
        sd = jnp.take(d, order)
        new_part = new_part | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), sd[1:] != sd[:-1]])
        if c.valid is not None:
            sv = jnp.take(c.valid, order)
            new_part = new_part | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), sv[1:] != sv[:-1]])
    part_id = jnp.cumsum(new_part.astype(jnp.int64)) - 1
    pos = jnp.arange(n)
    part_start = jax.ops.segment_min(pos, part_id, num_segments=n)
    start_of_row = jnp.take(part_start, part_id)
    pos_in_part = pos - start_of_row

    # order-key change boundaries ("peers" share rank / frame values)
    new_peer = new_part
    for c, _asc in order_cols:
        sd = jnp.take(c.data, order)
        new_peer = new_peer | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), sd[1:] != sd[:-1]])
        if c.valid is not None:
            sv = jnp.take(c.valid, order)
            new_peer = new_peer | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), sv[1:] != sv[:-1]])

    fn = wc.fn
    # live-row extent per partition: the lexsort puts every dead row
    # after every live row, so a partition's live rows occupy
    # [start_of_row, start_of_row + psize - 1] contiguously
    psize_t = jax.ops.segment_sum(s_live.astype(jnp.int64), part_id,
                                  num_segments=n)
    psize = jnp.take(psize_t, part_id)
    last_live = start_of_row + jnp.maximum(psize - 1, 0)

    def _lit_int(e, default):
        if e is None:
            return default
        if isinstance(e, ir.Literal) and isinstance(e.value, int):
            return int(e.value)
        raise NotImplementedError(
            f"window {fn} offset must be an integer literal")

    if fn == "ntile":
        buckets = _lit_int((wc.extra or [None])[0], None)
        if not buckets or buckets < 1:
            raise NotImplementedError("ntile needs a positive bucket count")
        q, r = psize // buckets, psize % buckets
        j = pos_in_part
        big = r * (q + 1)
        res = jnp.where(j < big, j // jnp.maximum(q + 1, 1),
                        r + (j - big) // jnp.maximum(q, 1)) + 1
        return Column(jnp.take(res, inv), rel.mask, SqlType.int_())
    if fn == "row_number":
        res = pos_in_part + 1
        return Column(jnp.take(res, inv), rel.mask, SqlType.int_())
    if fn == "rank":
        # start position of the current peer group, relative to partition
        peer_start = jnp.where(new_peer, pos, 0)
        peer_start = jax.lax.associative_scan(jnp.maximum, peer_start)
        res = peer_start - start_of_row + 1
        return Column(jnp.take(res, inv), rel.mask, SqlType.int_())
    if fn == "dense_rank":
        in_part_newpeer = (new_peer & ~new_part).astype(jnp.int64)
        cums = jnp.cumsum(in_part_newpeer)
        base = jnp.take(cums, jnp.clip(start_of_row, 0, n - 1))
        res = cums - base + 1
        return Column(jnp.take(res, inv), rel.mask, SqlType.int_())

    # window aggregates
    if fn == "count_star":
        ac = Column(jnp.ones(n, dtype=jnp.int64), None, SqlType.int_())
    else:
        assert wc.arg is not None, f"{fn} needs an argument"
        ac = eval_expr(wc.arg, rel)
        if ac.dtype.kind == TypeKind.BOOL:
            ac = cast_column(ac, SqlType.int_())
    s_data = jnp.take(ac.data, order)
    s_valid = jnp.take(ac.valid, order) if ac.valid is not None else None
    weight = s_live if s_valid is None else (s_live & s_valid)

    # ---- navigation functions (lead/lag/first_value/last_value) --------
    if fn in ("lead", "lag"):
        extra = wc.extra or []
        k = _lit_int(extra[0] if extra else None, 1)
        shift = k if fn == "lead" else -k
        tgt = pos + shift
        ok = (tgt >= start_of_row) & (tgt <= last_live) & s_live
        tgtc = jnp.clip(tgt, 0, max(n - 1, 0))
        data = jnp.take(s_data, tgtc)
        valid = ok if s_valid is None else (ok & jnp.take(s_valid, tgtc))
        if len(extra) > 1 and extra[1] is not None:
            dflt = eval_expr(extra[1], rel)
            d_s = jnp.take(cast_column(dflt, ac.dtype).data, order)
            data = jnp.where(ok, data, d_s)
            dv = jnp.take(dflt.valid, order) if dflt.valid is not None \
                else jnp.ones(n, jnp.bool_)
            valid = jnp.where(ok, valid, dv)
        return Column(jnp.take(data, inv), jnp.take(valid, inv) & m,
                      ac.dtype, sdict=ac.sdict)
    if fn in ("first_value", "last_value"):
        fr = wc.frame
        if fr is None:
            # default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW —
            # first = partition start, last = last peer of current row
            peer_id = jnp.cumsum(new_peer.astype(jnp.int64)) - 1
            last_pos = jax.ops.segment_max(pos, peer_id, num_segments=n)
            tgt = start_of_row if fn == "first_value" else \
                jnp.minimum(jnp.take(last_pos, peer_id), last_live)
        else:
            _unit, fs, fe = fr
            lo = start_of_row if fs is None else \
                jnp.maximum(pos + fs, start_of_row)
            hi = last_live if fe is None else jnp.minimum(pos + fe,
                                                          last_live)
            tgt = lo if fn == "first_value" else hi
            empty = hi < lo
            tgt = jnp.where(empty, 0, tgt)
        tgtc = jnp.clip(tgt, 0, max(n - 1, 0))
        data = jnp.take(s_data, tgtc)
        valid = s_live if s_valid is None else jnp.take(s_valid, tgtc)
        if fr is not None:
            valid = valid & ~empty
        return Column(jnp.take(data, inv), jnp.take(valid, inv) & m,
                      ac.dtype, sdict=ac.sdict)

    ordered = bool(wc.order_by)
    rt = SqlType.int_() if fn in ("count", "count_star") else \
        (SqlType.double() if fn == "avg" else ac.dtype)

    def running(x, op, identity):
        """prefix-scan within partitions (reset at partition starts)."""
        if op == "sum":
            cums = jnp.cumsum(x)
            base = jnp.take(cums, jnp.clip(start_of_row - 1, 0, n - 1))
            base = jnp.where(start_of_row == 0, 0, base)
            return cums - base
        # running min/max via associative scan with partition reset:
        # inject identity at partition starts through a segmented scan
        def seg_op(a, b):
            av, af = a
            bv, bf = b
            v = jnp.where(bf, bv, op(av, bv))
            return v, af | bf
        flags = new_part
        vals, _ = jax.lax.associative_scan(seg_op, (x, flags))
        return vals

    if wc.frame is not None and fn in ("sum", "avg", "count",
                                       "count_star", "min", "max"):
        # explicit ROWS frame: per-row [lo, hi] ranges clamped to the
        # partition's live extent; sums via prefix differences, min/max
        # via a sparse table (two overlapping power-of-2 windows) —
        # ≙ the window-function op's frame evaluation, vectorized
        _unit, fs, fe = wc.frame
        lo = start_of_row if fs is None else \
            jnp.maximum(pos + fs, start_of_row)
        hi = last_live if fe is None else jnp.minimum(pos + fe, last_live)
        empty = (hi < lo) | ~s_live
        lo_c = jnp.clip(lo, 0, max(n - 1, 0))
        hi_c = jnp.clip(hi, 0, max(n - 1, 0))

        def range_sum(vals):
            cums = jnp.cumsum(vals)
            upper = jnp.take(cums, hi_c)
            lower = jnp.where(lo_c > 0,
                              jnp.take(cums, jnp.maximum(lo_c - 1, 0)), 0)
            return jnp.where(empty, 0, upper - lower)

        cnt = range_sum(weight.astype(jnp.int64))
        if fn in ("min", "max"):
            from oceanbase_tpu.exec.ops import _agg_identity

            ident = _agg_identity(fn, s_data.dtype)
            opf = jnp.minimum if fn == "min" else jnp.maximum
            x = jnp.where(weight, s_data, ident)
            # sparse table: sp[j][i] = op over [i, i + 2^j - 1].  Levels
            # cap at log2(max frame length) when both bounds are finite —
            # a 3-row sliding frame must not materialize log2(n) copies
            if fs is not None and fe is not None:
                max_len = max(fe - fs + 1, 1)
            else:
                max_len = max(n, 2)
            levels = max(int(np.ceil(np.log2(max(max_len, 2)))) + 1, 1)
            sp = [x]
            for j in range(1, levels):
                half = 1 << (j - 1)
                shifted = jnp.concatenate(
                    [sp[-1][half:], jnp.full(min(half, n), ident,
                                             dtype=x.dtype)])[:n]
                sp.append(opf(sp[-1], shifted))
            table = jnp.stack(sp)  # (levels, n)
            ln = hi_c - lo_c + 1
            k = jnp.clip(
                jnp.floor(jnp.log2(jnp.maximum(ln, 1).astype(
                    jnp.float64))).astype(jnp.int64), 0, levels - 1)
            flat = table.reshape(-1)
            a = jnp.take(flat, k * n + lo_c)
            b = jnp.take(flat, k * n + jnp.maximum(
                hi_c - (1 << k) + 1, 0))
            run = jnp.where(empty, ident, opf(a, b))
        else:
            xs = jnp.where(weight,
                           s_data if fn in ("sum", "avg")
                           else jnp.ones(n, dtype=jnp.int64),
                           jnp.zeros((), s_data.dtype
                                     if fn in ("sum", "avg")
                                     else jnp.int64))
            run = range_sum(xs)
        ordered = False  # frame computed exactly; no peer smearing
    elif fn in ("sum", "avg", "count", "count_star"):
        x = jnp.where(weight, s_data if fn in ("sum", "avg")
                      else jnp.ones(n, dtype=jnp.int64),
                      jnp.zeros((), s_data.dtype if fn in ("sum", "avg")
                                else jnp.int64))
        if ordered:
            run = running(x, "sum", 0)
            cnt = running(weight.astype(jnp.int64), "sum", 0)
        else:
            tot = jax.ops.segment_sum(x, part_id, num_segments=n)
            run = jnp.take(tot, part_id)
            cntt = jax.ops.segment_sum(weight.astype(jnp.int64), part_id,
                                       num_segments=n)
            cnt = jnp.take(cntt, part_id)
    elif fn in ("min", "max"):
        from oceanbase_tpu.exec.ops import _agg_identity

        ident = _agg_identity(fn, s_data.dtype)
        x = jnp.where(weight, s_data, ident)
        opf = jnp.minimum if fn == "min" else jnp.maximum
        if ordered:
            run = running(x, opf, ident)
            cnt = running(weight.astype(jnp.int64), "sum", 0)
        else:
            segf = jax.ops.segment_min if fn == "min" else jax.ops.segment_max
            tot = segf(x, part_id, num_segments=n)
            run = jnp.take(tot, part_id)
            cntt = jax.ops.segment_sum(weight.astype(jnp.int64), part_id,
                                       num_segments=n)
            cnt = jnp.take(cntt, part_id)
    else:
        raise NotImplementedError(f"window function {fn}")

    if ordered:
        # RANGE frame: peers share the value at the LAST row of the peer
        # group — gather the running value from each group's last position
        peer_id = jnp.cumsum(new_peer.astype(jnp.int64)) - 1
        last_pos = jax.ops.segment_max(pos, peer_id, num_segments=n)
        lp = jnp.clip(jnp.take(last_pos, peer_id), 0, max(n - 1, 0))
        run = jnp.take(run, lp)
        cnt = jnp.take(cnt, lp)

    if fn == "avg":
        if ac.dtype.kind == TypeKind.DECIMAL:
            num = run.astype(jnp.float64) / (10 ** ac.dtype.scale)
        else:
            num = run.astype(jnp.float64)
        res = num / jnp.maximum(cnt, 1).astype(jnp.float64)
        valid = jnp.take(cnt > 0, inv)
        return Column(jnp.take(res, inv), valid, rt)
    if fn in ("count", "count_star"):
        return Column(jnp.take(cnt, inv), rel.mask, rt)
    valid = jnp.take(cnt > 0, inv)
    return Column(jnp.take(run, inv), valid, rt, sdict=ac.sdict)